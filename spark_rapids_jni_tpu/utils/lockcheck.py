"""Dynamic lock-order detector — the runtime half of ``srt-check``.

Eleven PRs grew a concurrency-heavy runtime (resident registry,
pipeline worker pool, donation barriers, spill LRU, fair-share serving
scheduler) whose deadlock freedom rests on an acquisition-order
discipline that until now lived only in reviewers' heads. This module
makes it machine-checked, the way the reference stack leans on
``compute-sanitizer``/``cuda-memcheck`` CI lanes for its CUDA-side
race discipline (see README parity table):

* modules construct their locks through :func:`make_lock` /
  :func:`make_rlock` / :func:`make_condition` instead of ``threading``
  directly (srt-check's static side has no pass for this yet; grep
  ``threading.Lock(`` stays the review rule for new modules). Each
  factory takes a **dotted name** — ``"registry.resident"``,
  ``"session.state"`` — whose first segment keys the sanctioned-order
  table below.
* under ``SPARK_RAPIDS_TPU_LOCKCHECK=on`` every acquisition records
  the per-thread held-lock set and folds edges ``held -> acquiring``
  into a global acquisition-order graph. :func:`report` finds cycles
  in that graph (potential deadlocks: an A->B and a B->A edge mean two
  threads can meet halfway) plus **immediate** inversions of
  :data:`LOCK_ORDER`, and lists locks held across device dispatch or
  blocking IO (:func:`note_blocking` hooks in ``runtime_bridge`` and
  the spill disk tier).
* the report rides the existing observability exits: a ``lockcheck``
  flight-dump section (``flight.register_exit_section``) and
  ``lock.*`` counters folded into the metrics snapshot at report time.

Gating follows the metrics/flight discipline: disabled, an acquisition
costs the raw ``threading`` primitive plus one cached generation
compare (< 5 µs asserted in tests/test_lockcheck.py); the detector's
own bookkeeping uses ONE raw (untracked) lock and never calls back
into metrics/flight on the hot path, so the telemetry planes' own raw
locks cannot recurse through it. ``metrics.py``/``flight.py``/
``log.py`` keep raw locks by design — the detector reports *through*
them, so tracking them would let a lockcheck report deadlock on the
lock it is reporting about.

Sanctioned order (ISSUE 12 satellite: codified as data, validated on
every ranked acquisition):

    registry -> session -> scheduler -> spill

i.e. code holding a ``session.*`` lock may take ``scheduler.*`` or
``spill.*`` locks but must NEVER take ``registry.*`` — that inversion
is how PRs 9–11 each nearly deadlocked the donate barrier against the
serving admission path. First segments not in the table (``pipeline``,
``buckets``, ``hbm``, ...) are unranked: they still contribute graph
edges (cycle detection covers them) but skip the rank check.
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Dict, List, Optional, Tuple

from . import config

# ---------------------------------------------------------------------------
# sanctioned acquisition order — data, not prose. Rank by the FIRST
# dotted segment of the lock name; lower rank must be acquired first.
# ---------------------------------------------------------------------------

LOCK_ORDER: Tuple[str, ...] = ("registry", "session", "scheduler", "spill")

_RANK = {seg: i for i, seg in enumerate(LOCK_ORDER)}

# detector bookkeeping lock — deliberately RAW: tracking it would
# recurse, and it is only ever taken with the gate already passed
_STATE_LOCK = threading.Lock()

# (held_name, acquired_name) -> {"count": int, "example": {...}}
_EDGES: Dict[Tuple[str, str], Dict[str, Any]] = {}
# sanctioned-order inversions, recorded at the acquiring call site
_ORDER_VIOLATIONS: List[Dict[str, Any]] = []
# locks held while entering a device dispatch / blocking-IO region
_BLOCKING_VIOLATIONS: List[Dict[str, Any]] = []
_ACQUISITIONS = 0

_MAX_VIOLATIONS = 256  # a broken loop must not grow these unbounded

_TLS = threading.local()

# gate cache on config.generation(), the metrics.py discipline
_GATE_GEN = -1
_GATE_ON = False


def _refresh_gate() -> None:
    global _GATE_GEN, _GATE_ON
    _GATE_ON = bool(config.get_flag("LOCKCHECK"))
    _GATE_GEN = config.generation()


def enabled() -> bool:
    """True when the detector is recording (cheap cached gate)."""
    if _GATE_GEN != config.generation():
        _refresh_gate()
    return _GATE_ON


def _held() -> list:
    got = getattr(_TLS, "held", None)
    if got is None:
        got = _TLS.held = []
    return got


def _site(skip: int = 3) -> str:
    """``file:line`` of the acquiring frame (best effort, first-edge
    cost only — never on the per-acquisition fast path)."""
    try:
        frames = traceback.extract_stack(limit=skip + 2)
        # walk outward past lockcheck frames to the caller
        for fr in reversed(frames):
            if "lockcheck" not in fr.filename:
                return f"{fr.filename}:{fr.lineno}"
        return "<unknown>"
    except Exception:  # srt: allow-broad-except(diagnostic provenance only; a stack-walk failure must not break the acquisition it annotates)
        return "<unknown>"


def _note_acquiring(lock: "_Tracked") -> None:
    """Order/graph bookkeeping at the acquisition ATTEMPT (before the
    raw acquire blocks — a true deadlock still leaves its edges)."""
    global _ACQUISITIONS
    held = _held()
    for entry in held:
        if entry[0] is lock:
            return  # RLock re-entry: no new edges, no rank check
    rank = _RANK.get(lock.name.split(".", 1)[0])
    with _STATE_LOCK:
        _ACQUISITIONS += 1
        for entry in held:
            other = entry[0]
            if other.name == lock.name:
                continue  # two instances of one class: not an order fact
            key = (other.name, lock.name)
            e = _EDGES.get(key)
            if e is None:
                _EDGES[key] = {"count": 1, "example": _site()}
            else:
                e["count"] += 1
            if (
                rank is not None
                and entry[1] is not None
                and entry[1] > rank
                and len(_ORDER_VIOLATIONS) < _MAX_VIOLATIONS
            ):
                _ORDER_VIOLATIONS.append({
                    "held": other.name,
                    "acquiring": lock.name,
                    "order": "->".join(LOCK_ORDER),
                    "thread": threading.current_thread().name,
                    "site": _site(),
                })


def _note_acquired(lock: "_Tracked") -> None:
    held = _held()
    for entry in held:
        if entry[0] is lock:
            entry[2] += 1
            return
    rank = _RANK.get(lock.name.split(".", 1)[0])
    held.append([lock, rank, 1])


def _note_released(lock: "_Tracked") -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is lock:
            held[i][2] -= 1
            if held[i][2] <= 0:
                del held[i]
            return


def note_blocking(kind: str) -> None:
    """Hook for device-dispatch / blocking-IO entry points: records any
    tracked lock the calling thread still holds — holding the registry
    lock across a device launch serializes every other dispatcher
    behind the chip. Costs one cached gate compare when off."""
    if not enabled():
        return
    held = _held()
    if not held:
        return
    with _STATE_LOCK:
        if len(_BLOCKING_VIOLATIONS) < _MAX_VIOLATIONS:
            _BLOCKING_VIOLATIONS.append({
                "kind": kind,
                "held": [e[0].name for e in held],
                "thread": threading.current_thread().name,
                "site": _site(),
            })


# ---------------------------------------------------------------------------
# tracked primitives
# ---------------------------------------------------------------------------


class _Tracked:
    """Shared acquire/release shim over a raw threading primitive."""

    __slots__ = ("_lock", "name")

    def __init__(self, raw, name: str):
        self._lock = raw
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if enabled():
            _note_acquiring(self)
            got = self._lock.acquire(blocking, timeout)
            if got:
                _note_acquired(self)
            return got
        return self._lock.acquire(blocking, timeout)

    def release(self) -> None:
        self._lock.release()
        if enabled():
            _note_released(self)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):  # pragma: no cover - debug aid
        return f"<{type(self).__name__} {self.name!r}>"


class TrackedLock(_Tracked):
    __slots__ = ()


class TrackedRLock(_Tracked):
    __slots__ = ()

    # threading.Condition probes these when built over an RLock; they
    # must come in matched release/acquire pairs around a wait, so the
    # held-set bookkeeping rides along
    def _release_save(self):
        state = self._lock._release_save()
        if enabled():
            # a wait fully releases the RLock regardless of depth
            held = self._held_entry()
            if held is not None:
                held[2] = 1
                _note_released(self)
        return state

    def _acquire_restore(self, state) -> None:
        self._lock._acquire_restore(state)
        if enabled():
            _note_acquired(self)

    def _is_owned(self) -> bool:
        return self._lock._is_owned()

    def _held_entry(self):
        for entry in _held():
            if entry[0] is self:
                return entry
        return None


class TrackedCondition:
    """Condition over a tracked lock: waits release the held-set entry
    (the raw wait releases the raw lock) and re-add it on wake, so a
    waiter never looks like it holds the lock across the block."""

    __slots__ = ("_cond", "_owner")

    def __init__(self, owner: _Tracked):
        self._owner = owner
        if isinstance(owner, TrackedRLock):
            # Condition drives the tracked RLock directly through the
            # _release_save/_acquire_restore shims above
            self._cond = threading.Condition(owner)
        else:
            self._cond = threading.Condition(owner._lock)

    def __enter__(self):
        self._owner.__enter__()
        return self

    def __exit__(self, *exc):
        return self._owner.__exit__(*exc)

    def wait(self, timeout: Optional[float] = None):
        track = enabled() and not isinstance(self._owner, TrackedRLock)
        if track:
            _note_released(self._owner)
        try:
            return self._cond.wait(timeout)
        finally:
            if track:
                _note_acquired(self._owner)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # re-implemented over self.wait so the held-set bookkeeping
        # wraps every underlying wait slice
        import time as _time

        end = None if timeout is None else _time.monotonic() + timeout
        result = predicate()
        while not result:
            if end is not None:
                left = end - _time.monotonic()
                if left <= 0:
                    break
                self.wait(left)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


def make_lock(name: str) -> TrackedLock:
    """A named, order-tracked ``threading.Lock``."""
    return TrackedLock(threading.Lock(), name)


def make_rlock(name: str) -> TrackedRLock:
    """A named, order-tracked ``threading.RLock``."""
    return TrackedRLock(threading.RLock(), name)


def make_condition(lock: _Tracked) -> TrackedCondition:
    """A ``threading.Condition`` sharing a tracked lock."""
    if not isinstance(lock, _Tracked):
        raise TypeError(
            f"make_condition needs a lockcheck-tracked lock, got "
            f"{type(lock).__name__}"
        )
    return TrackedCondition(lock)


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------


def _find_cycles(edges) -> List[List[str]]:
    """Elementary cycles in the (small) name graph: for each strongly
    connected component with more than one node (or a self-edge), one
    witness cycle via DFS. Deterministic: nodes walked sorted."""
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    for v in graph.values():
        v.sort()
    cycles: List[List[str]] = []
    seen_keys = set()
    for start in sorted(graph):
        # DFS from start looking for a path back to start
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        visited = set()
        while stack:
            node, path = stack.pop()
            for nxt in graph.get(node, ()):
                if nxt == start and len(path) > 1:
                    key = frozenset(path)
                    if key not in seen_keys:
                        seen_keys.add(key)
                        cycles.append(path + [start])
                elif nxt not in visited and nxt != start:
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))
    return cycles


def report() -> dict:
    """One JSON-able report: the acquisition-order graph, cycles found
    in it, sanctioned-order inversions, and locks held across blocking
    regions. Also folds ``lock.*`` counters into the metrics registry
    (report time, never the acquisition path)."""
    with _STATE_LOCK:
        edges = {
            f"{a}->{b}": dict(v) for (a, b), v in sorted(_EDGES.items())
        }
        edge_keys = list(_EDGES.keys())
        order_violations = [dict(v) for v in _ORDER_VIOLATIONS]
        blocking = [dict(v) for v in _BLOCKING_VIOLATIONS]
        acquisitions = _ACQUISITIONS
    cycles = _find_cycles(edge_keys)
    doc = {
        "enabled": enabled(),
        "order": list(LOCK_ORDER),
        "acquisitions": acquisitions,
        "edges": edges,
        "cycles": [" -> ".join(c) for c in cycles],
        "order_violations": order_violations,
        "held_across_blocking": blocking,
    }
    from . import metrics  # late: metrics imports nothing from here

    metrics.counter_add("lock.acquisitions", 0)  # ensure the row exists
    metrics.gauge_set("lock.tracked_edges", len(edges))
    if cycles:
        metrics.counter_add("lock.cycles", len(cycles))
    if order_violations:
        metrics.counter_add("lock.order_violations", len(order_violations))
    if blocking:
        metrics.counter_add("lock.held_across_blocking", len(blocking))
    return doc


def assert_clean(strict_blocking: bool = False) -> dict:
    """Raise AssertionError on any cycle or sanctioned-order inversion;
    returns the report when clean (test/CI helper). Held-across-
    blocking findings are informational by default — some are
    intentional (the repage path reads disk under the registry lock by
    design, so the table can't be freed mid-load) — pass
    ``strict_blocking=True`` to fail on them too."""
    doc = report()
    keys = ["cycles", "order_violations"]
    if strict_blocking:
        keys.append("held_across_blocking")
    problems = {k: doc[k] for k in keys if doc[k]}
    if problems:
        raise AssertionError(f"lockcheck found problems: {problems}")
    return doc


def summary_line() -> str:
    """The one-line findings summary CI prints."""
    doc = report()
    return (
        f"lockcheck: {doc['acquisitions']} acquisitions, "
        f"{len(doc['edges'])} order edges, {len(doc['cycles'])} cycles, "
        f"{len(doc['order_violations'])} order violations, "
        f"{len(doc['held_across_blocking'])} held-across-blocking"
    )


def reset() -> None:
    """Drop every recorded edge/violation (test isolation). Held sets
    are per-thread state and drain as their locks release."""
    global _ACQUISITIONS
    with _STATE_LOCK:
        _EDGES.clear()
        _ORDER_VIOLATIONS.clear()
        _BLOCKING_VIOLATIONS.clear()
        _ACQUISITIONS = 0


def _exit_section() -> dict:
    if not enabled():
        return {"enabled": False}
    return report()


# ride the flight dump: a crashed run's last act includes the lock
# graph (the postmortem that explains a hang-to-SIGKILL)
from . import flight as _flight  # noqa: E402  (import cycle: none — flight imports only config)

_flight.register_exit_section("lockcheck", _exit_section)
