"""Row ⇄ columnar transpose: the reference's core Spark-specific kernel.

Re-implements, byte-for-byte, the packed row format of
``spark-rapids-jni`` (spec: RowConversion.java:43-102; layout computation:
row_conversion.cu:432-456) as compiled XLA computations:

* Each fixed-width column is placed at ``align_offset(cursor, width)``.
* Validity is 1 bit per column, bytes **appended** after the last column
  value, 1 byte per 8 columns, LSB-first (row_conversion.cu:448-453).
* The row is padded to a 64-bit multiple so consecutive rows stay aligned
  (row_conversion.cu:454-455).
* A single packed output caps at INT_MAX bytes, so tables split into
  batches of ``(INT_MAX / row_size) / 32 * 32`` rows — multiples of 32 so
  validity words never straddle batches (row_conversion.cu:476-479).
* Only fixed-width types are supported, mirroring the reference's gate
  (row_conversion.cu:514-516 / :572-574).

TPU-first design
----------------
The CUDA implementation tiles through 48 KB shared memory with warp
ballots and byte atomics (row_conversion.cu:48-304). None of that
translates: on TPU the whole transpose is expressed as a fused gather of
byte-cast column buffers into an ``(n, row_size)`` uint8 matrix —
``lax.bitcast_convert_type`` + static-slice writes — which XLA fuses into
a single HBM-bandwidth-bound kernel; a Pallas kernel variant
(kernels/row_transpose.py) tiles it explicitly through VMEM for large
row counts. Validity bit packing is a vectorized (n, 8)·(powers of two)
matmul instead of warp ballots (SURVEY.md §7 hard part 3).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dt
from .column import Column, Table

INT_MAX = 2**31 - 1


def align_offset(offset: int, alignment: int) -> int:
    """Round ``offset`` up to ``alignment`` (row_conversion.cu:417-419)."""
    return (offset + alignment - 1) & ~(alignment - 1)


@dataclasses.dataclass(frozen=True)
class RowLayout:
    """Byte layout of one packed row for a fixed-width schema.

    Mirrors ``compute_fixed_width_layout`` (row_conversion.cu:432-456).
    """

    dtypes: tuple[dt.DType, ...]
    column_offsets: tuple[int, ...]
    column_widths: tuple[int, ...]
    validity_offset: int
    validity_bytes: int
    row_size: int


def compute_fixed_width_layout(dtypes: Sequence[dt.DType]) -> RowLayout:
    dtypes = tuple(dtypes)
    if not dtypes:
        raise TypeError("row format requires at least one column")
    for d in dtypes:
        if not d.is_fixed_width:
            raise TypeError(
                f"only fixed-width types supported in row format, got {d!r}"
            )
    offsets, widths = [], []
    cursor = 0
    for d in dtypes:
        w = d.itemsize
        cursor = align_offset(cursor, w)
        offsets.append(cursor)
        widths.append(w)
        cursor += w
    validity_offset = cursor
    validity_bytes = (len(dtypes) + 7) // 8
    cursor += validity_bytes
    # Pad to 64-bit multiple so rows stay aligned back to back
    # (row_conversion.cu:454-455).
    row_size = align_offset(cursor, 8)
    return RowLayout(
        dtypes=dtypes,
        column_offsets=tuple(offsets),
        column_widths=tuple(widths),
        validity_offset=validity_offset,
        validity_bytes=validity_bytes,
        row_size=row_size,
    )


def max_rows_per_batch(row_size: int) -> int:
    """2 GB split granularity (row_conversion.cu:476-479)."""
    if row_size * 32 > INT_MAX:
        raise ValueError("row size too large: 32 rows exceed INT_MAX bytes")
    return (INT_MAX // row_size) // 32 * 32


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(eq=False)
class PackedRows:
    """One batch of packed rows: an (n, row_size) uint8 device matrix.

    This is the LIST<INT8> column of the reference flattened: the offsets
    child is implicit (an arithmetic sequence 0, row_size, 2*row_size, …,
    exactly what cudf::detail::sequence builds at row_conversion.cu:389-390),
    so we don't materialize it on device; ``offsets()`` reconstructs it for
    interop/JNI export.
    """

    data: jax.Array  # (n, row_size) uint8
    layout: RowLayout

    def tree_flatten(self):
        return (self.data,), self.layout

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(data=children[0], layout=aux)

    @property
    def row_count(self) -> int:
        return int(self.data.shape[0])

    @property
    def row_size(self) -> int:
        return int(self.data.shape[1])

    def offsets(self) -> np.ndarray:
        """int32 offsets of the LIST<INT8> representation.

        Raises if the batch exceeds INT_MAX bytes (possible via the
        ``split=False`` / ``batch_rows`` escape hatches) — the reference
        enforces the same cap with an assert (row_conversion.cu:384-386).
        """
        n = self.row_count
        total = n * self.row_size
        if total > INT_MAX:
            raise ValueError(
                f"batch of {total} bytes exceeds INT_MAX; re-pack with "
                "to_rows(split=True)"
            )
        return (np.arange(n + 1, dtype=np.int64) * self.row_size).astype(
            np.int32
        )

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.data)


# ---------------------------------------------------------------------------
# columnar -> rows
# ---------------------------------------------------------------------------

def _column_bytes(col: Column) -> jax.Array:
    """(n, width) uint8 little-endian view of a fixed-width column."""
    data = col.data
    if col.dtype.is_boolean:
        # BOOL8 is one byte in the row format.
        return data.astype(jnp.uint8)[:, None]
    if col.dtype.id == dt.TypeId.DECIMAL128:
        # (n, 2) u64 limbs [lo, hi] -> 16 little-endian bytes
        b = jax.lax.bitcast_convert_type(data, jnp.uint8)  # (n, 2, 8)
        return b.reshape(b.shape[0], 16)
    b = jax.lax.bitcast_convert_type(data, jnp.uint8)
    if b.ndim == 1:  # 1-byte dtypes keep their shape
        b = b[:, None]
    return b


_BIT_WEIGHTS = np.array([1, 2, 4, 8, 16, 32, 64, 128], dtype=np.uint8)


def _pack_validity_bytes(valid: jax.Array, num_cols: int) -> jax.Array:
    """(n, num_cols) bool -> (n, vbytes) uint8, LSB-first within each byte.

    The vectorized-masked-reduction replacement for the reference's warp
    ballots / byte atomics (row_conversion.cu:158-165, :255-272).
    """
    n = valid.shape[0]
    vbytes = (num_cols + 7) // 8
    padded = jnp.zeros((n, vbytes * 8), dtype=jnp.uint8)
    padded = padded.at[:, :num_cols].set(valid.astype(jnp.uint8))
    groups = padded.reshape(n, vbytes, 8)
    weights = jnp.asarray(_BIT_WEIGHTS)
    return jnp.sum(groups * weights[None, None, :], axis=-1, dtype=jnp.uint8)


def _unpack_validity_bytes(vb: jax.Array, num_cols: int) -> jax.Array:
    """(n, vbytes) uint8 -> (n, num_cols) bool, LSB-first."""
    n = vb.shape[0]
    weights = jnp.asarray(_BIT_WEIGHTS)
    bits = (vb[:, :, None] & weights[None, None, :]) != 0
    # explicit dims: reshape(n, -1) is uninferable for zero-row batches
    return bits.reshape(n, vb.shape[1] * 8)[:, :num_cols]


def _pack_batch(columns: Sequence[Column], layout: RowLayout) -> jax.Array:
    """Jittable core: pack equal-length columns into (n, row_size) uint8."""
    n = columns[0].data.shape[0]
    out = jnp.zeros((n, layout.row_size), dtype=jnp.uint8)
    for col, off, w in zip(
        columns, layout.column_offsets, layout.column_widths
    ):
        out = out.at[:, off : off + w].set(_column_bytes(col))
    valid = jnp.stack(
        [
            c.validity
            if c.validity is not None
            else jnp.ones((n,), dtype=jnp.bool_)
            for c in columns
        ],
        axis=1,
    )
    vb = _pack_validity_bytes(valid, len(columns))
    out = out.at[
        :, layout.validity_offset : layout.validity_offset + layout.validity_bytes
    ].set(vb)
    return out


_pack_batch_jit = jax.jit(_pack_batch, static_argnames="layout")


def _pack_batch_pallas(columns: Sequence[Column], layout: RowLayout):
    from .kernels import row_transpose as rt

    n = columns[0].data.shape[0]
    col_bytes = tuple(_column_bytes(c) for c in columns)
    valid = jnp.stack(
        [
            c.validity
            if c.validity is not None
            else jnp.ones((n,), dtype=jnp.bool_)
            for c in columns
        ],
        axis=1,
    ).astype(jnp.uint8)
    from . import kernels

    return rt.pack_rows_pallas(
        col_bytes, valid, layout, interpret=kernels.default_interpret()
    )


def to_rows(
    table: Table,
    split: bool = True,
    batch_rows: Optional[int] = None,
    backend: str = "xla",
) -> list[PackedRows]:
    """Columnar -> packed rows (``convert_to_rows``, row_conversion.cu:458-517).

    Returns one ``PackedRows`` per 2 GB batch, mirroring the reference's
    ``ColumnVector[]`` return (RowConversion.java:104-111). ``batch_rows``
    overrides the INT_MAX-derived split size (testing / memory tuning); it
    is clamped to a multiple of 32 like the reference.

    ``backend`` selects the device code path: ``"xla"`` (default — one
    fused gather XLA compiles itself) or ``"pallas"`` (the explicit
    VMEM-tiled kernel, kernels/row_transpose.py). Both emit identical
    bytes; the round-trip tests cross-check them.
    """
    if backend not in ("xla", "pallas"):
        raise ValueError(f"unknown backend {backend!r}")
    layout = compute_fixed_width_layout(table.dtypes())
    n = table.row_count
    if batch_rows is not None:
        batch = max(batch_rows // 32 * 32, 32)
    elif split:
        batch = max_rows_per_batch(layout.row_size)
    else:
        batch = max(n, 1)
    out = []
    start = 0
    while True:
        stop = min(start + batch, n)
        cols = [
            Column(
                c.data[start:stop],
                c.dtype,
                None if c.validity is None else c.validity[start:stop],
            )
            for c in table.columns
        ]
        data = (
            _pack_batch_pallas(cols, layout)
            if backend == "pallas"
            else _pack_batch_jit(cols, layout)
        )
        out.append(PackedRows(data, layout))
        start = stop
        if start >= n:
            break
    return out


# ---------------------------------------------------------------------------
# rows -> columnar
# ---------------------------------------------------------------------------

def column_bytes_to_storage(raw: jax.Array, d) -> jax.Array:
    """(n, width) little-endian bytes -> storage-dtype values. The single
    definition both backends decode through (XLA `_unpack_batch` here and
    the Pallas kernel boundary in kernels/row_transpose.py) so the
    storage-dtype rules can never diverge."""
    if d.is_boolean:
        return raw[:, 0] != 0
    if d.id == dt.TypeId.DECIMAL128:
        # 16 little-endian bytes -> (n, 2) u64 limbs [lo, hi]
        n = raw.shape[0]
        return jax.lax.bitcast_convert_type(
            raw.reshape(n, 2, 8), jnp.uint64
        )
    target = np.dtype(d.storage_dtype)
    if target.itemsize == 1:
        return jax.lax.bitcast_convert_type(raw[:, 0], target)
    return jax.lax.bitcast_convert_type(raw, target)


def _unpack_batch(
    data: jax.Array, layout: RowLayout
) -> tuple[list[jax.Array], jax.Array]:
    """Jittable core: (n, row_size) uint8 -> per-column data + validity."""
    cols = []
    for d, off, w in zip(
        layout.dtypes, layout.column_offsets, layout.column_widths
    ):
        cols.append(column_bytes_to_storage(data[:, off : off + w], d))
    vb = data[
        :, layout.validity_offset : layout.validity_offset + layout.validity_bytes
    ]
    valid = _unpack_validity_bytes(vb, len(layout.dtypes))
    return cols, valid


_unpack_batch_jit = jax.jit(_unpack_batch, static_argnames="layout")


def _unpack_batch_pallas(data: jax.Array, layout: RowLayout):
    from . import kernels
    from .kernels import row_transpose as rt

    raw_cols, valid = rt.unpack_rows_pallas(
        data, layout, interpret=kernels.default_interpret()
    )
    cols = [
        rt.column_bytes_to_storage(raw, d)
        for raw, d in zip(raw_cols, layout.dtypes)
    ]
    return cols, valid != 0


def from_rows(
    packed: Sequence[PackedRows] | PackedRows,
    dtypes: Optional[Sequence[dt.DType]] = None,
    names: Optional[Sequence[str]] = None,
    backend: str = "xla",
) -> Table:
    """Packed rows -> columnar (``convert_from_rows``, row_conversion.cu:519-575).

    ``dtypes`` is the schema the caller asserts — the (type id, scale) wire
    arrays of the reference JNI (RowConversionJni.cpp:56-61). Defaults to the
    layout's recorded schema. ``backend`` as in :func:`to_rows`.
    """
    if backend not in ("xla", "pallas"):
        raise ValueError(f"unknown backend {backend!r}")
    if isinstance(packed, PackedRows):
        packed = [packed]
    if not packed:
        raise ValueError("no row batches")
    layout = packed[0].layout
    if dtypes is not None:
        want = compute_fixed_width_layout(dtypes)
        if want.row_size != layout.row_size or want.column_offsets != layout.column_offsets:
            raise ValueError(
                "schema layout does not match the packed row size "
                f"({want.row_size} != {layout.row_size})"
            )
        layout = want

    unpack = (
        _unpack_batch_pallas if backend == "pallas" else _unpack_batch_jit
    )
    parts = [unpack(p.data, layout) for p in packed]
    # Preserve the validity=None invariant for null-free columns so
    # downstream ops keep their no-nulls fast path. One batched (num_cols,)
    # reduction + a single host transfer, not a sync per column.
    all_valid = np.asarray(
        jnp.all(
            jnp.concatenate([p[1] for p in parts], axis=0)
            if len(parts) > 1
            else parts[0][1],
            axis=0,
        )
    )
    columns = []
    for i, d in enumerate(layout.dtypes):
        data = jnp.concatenate([p[0][i] for p in parts]) if len(parts) > 1 else parts[0][0][i]
        valid = jnp.concatenate([p[1][:, i] for p in parts]) if len(parts) > 1 else parts[0][1][:, i]
        columns.append(
            Column(data=data, dtype=d, validity=None if all_valid[i] else valid)
        )
    return Table(columns, names)


def packed_rows_from_numpy(
    data: np.ndarray, dtypes: Sequence[dt.DType]
) -> PackedRows:
    """Wrap host row bytes (n, row_size) as a device PackedRows batch."""
    layout = compute_fixed_width_layout(dtypes)
    data = np.asarray(data, dtype=np.uint8)
    if data.ndim == 1:
        if data.size % layout.row_size:
            raise ValueError("flat row buffer not a multiple of row_size")
        data = data.reshape(-1, layout.row_size)
    if data.shape[1] != layout.row_size:
        raise ValueError(
            f"row width {data.shape[1]} != layout row_size {layout.row_size}"
        )
    return PackedRows(jnp.asarray(data), layout)


def to_rows_list(
    table: Table, split: bool = True, backend: str = "xla"
) -> Column:
    """Packed rows as a true LIST<UINT8> column — the reference's output
    type (offsets sequence + INT8 child assembled via make_lists_column,
    row_conversion.cu:389-406). Fixed row width means every list has
    length ``row_size``; the padded-matrix LIST layout holds the batch
    concatenation directly."""
    batches = to_rows(table, split=split, backend=backend)
    data = (
        jnp.concatenate([b.data for b in batches])
        if len(batches) > 1
        else batches[0].data
    )
    n = data.shape[0]
    lengths = jnp.full((n,), data.shape[1], jnp.int32)
    return Column(data, dt.DType(dt.TypeId.LIST), None, lengths)


def from_rows_list(
    col: Column,
    dtypes: Sequence[dt.DType],
    names: Optional[Sequence[str]] = None,
    backend: str = "xla",
) -> Table:
    """Inverse of :func:`to_rows_list`: LIST<UINT8/INT8> column of packed
    rows -> columnar table (convert_from_rows takes a lists_column_view,
    RowConversionJni.cpp:54-55)."""
    if col.dtype.id != dt.TypeId.LIST:
        raise TypeError("from_rows_list expects a LIST column")
    layout = compute_fixed_width_layout(dtypes)
    if col.data.ndim != 2 or col.data.shape[1] != layout.row_size:
        raise ValueError(
            f"packed list width {col.data.shape[1:]} != row size "
            f"{layout.row_size}"
        )
    # every list must be exactly one full row and non-null: a ragged or
    # nullable input whose PAD happens to equal row_size would otherwise
    # silently decode zero padding as row bytes (the reference gates the
    # same way: child must be a dense INT8 list, row_conversion.cu:524-528)
    if col.validity is not None and not bool(jnp.all(col.validity)):
        raise ValueError("packed-rows list column must have no nulls")
    if not bool(jnp.all(col.lengths == layout.row_size)):
        raise ValueError(
            f"every packed row must be exactly {layout.row_size} bytes"
        )
    pr = PackedRows(col.data.astype(jnp.uint8), layout)
    return from_rows(pr, dtypes, names, backend=backend)
