"""Avro read (host decode -> HBM upload).

The reference's capability surface includes Avro ingest: cudf ships an
Avro reader exposed through the Java API the artifact packages
(``Table.readAvro``/``AvroOptions`` in the vendored cudf test tree;
the reference's own test deps pull ``parquet-avro``,
/root/reference/pom.xml:118-123). cudf's reader supports primitive
types only — the same scope here.

No Avro library exists in the pinned environment, so this is a minimal
self-contained Object Container File codec: header/schema parse, zigzag
varint decode, ``null`` and ``deflate`` codecs (zlib is in the stdlib).
Record fields may be Avro primitives (boolean/int/long/float/double/
string/bytes) or the nullable union ``["null", <primitive>]``; anything
else raises. Decoded columns upload once, with the same projection +
device-filter pushdown as the other readers. A matching writer rounds
trips tables for tests and interop (cudf has no Avro writer; this one
exists for the test tier, SURVEY.md §4).
"""

from __future__ import annotations

import io as _io
import json
import os
import struct as _struct
import zlib
from typing import Optional, Sequence

import numpy as np

from ..column import Column, Table
from ..utils.tracing import trace_range
from . import predicates as preds

from .. import dtype as dt

_MAGIC = b"Obj\x01"

_PRIMITIVES = {"boolean", "int", "long", "float", "double", "string", "bytes"}

_AVRO_TO_DTYPE = {
    "boolean": dt.BOOL8,
    "int": dt.INT32,
    "long": dt.INT64,
    "float": dt.FLOAT32,
    "double": dt.FLOAT64,
    "string": dt.STRING,
    "bytes": dt.STRING,
}


# ---------------------------------------------------------------------------
# varint / zigzag primitives
# ---------------------------------------------------------------------------


def _read_long(buf: _io.BytesIO) -> int:
    """Zigzag varint (Avro int/long share the encoding)."""
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        v = b[0]
        acc |= (v & 0x7F) << shift
        if not (v & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _write_long(out: bytearray, v: int) -> None:
    z = (v << 1) ^ (v >> 63) if v < 0 else (v << 1)
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            break


def _read_bytes(buf: _io.BytesIO) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise EOFError("truncated bytes")
    return data


# ---------------------------------------------------------------------------
# schema handling
# ---------------------------------------------------------------------------


def _field_plan(field: dict) -> tuple[str, str, int]:
    """(name, primitive type, null-branch index) for one record field
    (-1 = not nullable); raises on unsupported shapes (the cudf Avro
    reader's primitive-only scope). Unions may spell the null branch in
    either position — the wire index follows the declaration order."""
    name = field["name"]
    t = field["type"]
    null_branch = -1
    if isinstance(t, list):
        branches = [b for b in t if b != "null"]
        if len(branches) != 1 or len(t) > 2:
            raise TypeError(
                f"avro field {name!r}: only two-branch null unions "
                f"are supported, got {t}"
            )
        if "null" in t:
            null_branch = t.index("null")
        t = branches[0]
    if isinstance(t, dict):
        t = t.get("type", t)
    if t not in _PRIMITIVES:
        raise TypeError(
            f"avro field {name!r}: unsupported type {t!r} (primitive "
            "types only, matching the cudf Avro reader scope)"
        )
    return name, t, null_branch


def _parse_schema(meta: dict) -> list[tuple[str, str, bool]]:
    schema = json.loads(meta[b"avro.schema"].decode())
    if isinstance(schema, dict) and schema.get("type") == "record":
        return [_field_plan(f) for f in schema.get("fields", [])]
    raise TypeError("avro: top-level schema must be a record")


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------


def _read_header(f) -> tuple[dict, bytes, _io.BytesIO]:
    if f.read(4) != _MAGIC:
        raise ValueError("not an Avro object container file")
    buf = _io.BytesIO(f.read())
    meta = {}
    while True:
        count = _read_long(buf)
        if count == 0:
            break
        if count < 0:  # block with a byte size prefix
            _read_long(buf)
            count = -count
        for _ in range(count):
            k = _read_bytes(buf)
            meta[k] = _read_bytes(buf)
    sync = buf.read(16)
    return meta, sync, buf


def _decode_value(buf: _io.BytesIO, typ: str):
    if typ == "boolean":
        return buf.read(1)[0] != 0
    if typ in ("int", "long"):
        return _read_long(buf)
    if typ == "float":
        return _struct.unpack("<f", buf.read(4))[0]
    if typ == "double":
        return _struct.unpack("<d", buf.read(8))[0]
    # string / bytes
    raw = _read_bytes(buf)
    return raw.decode("utf-8", "surrogateescape") if typ == "string" else raw


def read_avro(
    path,
    columns: Optional[Sequence[str]] = None,
    filters=None,
    pad_widths: Optional[dict] = None,
) -> Table:
    """Avro container file -> device Table (projection + device filter)."""
    from ..interop import table_from_arrow  # noqa: F401  (parity import)
    from .parquet import _apply_exact_filter

    predicate = preds.from_dnf(filters) if filters is not None else None
    with trace_range("io.avro.parse"), open(path, "rb") as f:
        meta, sync, buf = _read_header(f)
        plan = _parse_schema(meta)
        codec = meta.get(b"avro.codec", b"null").decode()
        if codec not in ("null", "deflate"):
            raise ValueError(f"avro codec {codec!r} not supported")
        values: dict[str, list] = {name: [] for name, _, _ in plan}
        while True:
            try:
                nrecords = _read_long(buf)
            except EOFError:
                break
            nbytes = _read_long(buf)
            block = buf.read(nbytes)
            if len(block) != nbytes:
                raise EOFError("truncated avro block")
            if codec == "deflate":
                block = zlib.decompress(block, -15)
            bbuf = _io.BytesIO(block)
            for _ in range(nrecords):
                for name, typ, null_branch in plan:
                    if null_branch >= 0:
                        branch = _read_long(bbuf)
                        if branch == null_branch:
                            values[name].append(None)
                            continue
                    values[name].append(_decode_value(bbuf, typ))
            if buf.read(16) != sync:
                raise ValueError("avro sync-marker mismatch")

    want, read_cols = preds.projection_columns(
        predicate, columns, list(values.keys())
    )
    # restrict to the decode set BEFORE padding/upload (like read_json),
    # and pin dtypes from the Avro schema — value-based inference would
    # widen float->float64 and type 0-row files arbitrarily
    dtypes = {
        name: _AVRO_TO_DTYPE[typ]
        for name, typ, _ in plan
        if name in read_cols
    }
    dev = Table.from_pydict(
        {k: values[k] for k in read_cols},
        dtypes=dtypes,
        pad_widths=pad_widths,
    )
    if predicate is not None:
        with trace_range("io.avro.filter"):
            dev = _apply_exact_filter(dev, predicate, want)
    return dev


# ---------------------------------------------------------------------------
# writer (test/interop convenience; cudf ships no Avro writer)
# ---------------------------------------------------------------------------

_AVRO_TYPE = {
    "int64": "long", "int32": "int", "int16": "int", "int8": "int",
    "uint8": "int", "uint16": "int", "uint32": "long",
    "float64": "double", "float32": "float", "bool": "boolean",
}


def write_avro(table: Table, path, codec: str = "null") -> None:
    """Device Table -> Avro container file (primitive columns)."""
    if codec not in ("null", "deflate"):
        raise ValueError(f"avro codec {codec!r} not supported")
    names = (
        list(table.names)
        if table.names is not None
        else [f"c{i}" for i in range(len(table.columns))]
    )
    plan = []
    pylists = []
    for name, col in zip(names, table.columns):
        vals = col.to_pylist()
        if col.dtype.is_string:
            typ = "string"
        else:
            np_name = np.dtype(col.to_numpy().dtype).name
            typ = _AVRO_TYPE.get(np_name)
            if typ is None:
                raise TypeError(
                    f"avro writer: unsupported column dtype {col.dtype}"
                )
        nullable = any(v is None for v in vals)
        plan.append((name, typ, nullable))
        pylists.append(vals)

    schema = {
        "type": "record",
        "name": "spark_rapids_tpu",
        "fields": [
            {"name": n, "type": (["null", t] if nullable else t)}
            for n, t, nullable in plan
        ],
    }
    body = bytearray()
    n_rows = table.row_count
    for i in range(n_rows):
        for (name, typ, nullable), vals in zip(plan, pylists):
            v = vals[i]
            if nullable:
                _write_long(body, 0 if v is None else 1)
                if v is None:
                    continue
            if typ == "boolean":
                body.append(1 if v else 0)
            elif typ in ("int", "long"):
                _write_long(body, int(v))
            elif typ == "float":
                body += _struct.pack("<f", float(v))
            elif typ == "double":
                body += _struct.pack("<d", float(v))
            else:
                raw = (
                    v.encode("utf-8", "surrogateescape")
                    if isinstance(v, str)
                    else bytes(v)
                )
                _write_long(body, len(raw))
                body += raw
    payload = bytes(body)
    if codec == "deflate":
        comp = zlib.compressobj(wbits=-15)
        payload = comp.compress(payload) + comp.flush()

    sync = os.urandom(16)
    out = bytearray(_MAGIC)
    meta = {
        b"avro.schema": json.dumps(schema).encode(),
        b"avro.codec": codec.encode(),
    }
    _write_long(out, len(meta))
    for k, v in meta.items():
        _write_long(out, len(k))
        out += k
        _write_long(out, len(v))
        out += v
    _write_long(out, 0)
    out += sync
    if n_rows:
        _write_long(out, n_rows)
        _write_long(out, len(payload))
        out += payload
        out += sync
    with open(path, "wb") as f:
        f.write(bytes(out))
