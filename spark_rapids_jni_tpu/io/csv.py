"""CSV read/write (host parse -> HBM upload).

Parity with the CSV surface of the cudf Java API the reference ships
(``Table.readCSV``/``writeCSVToFile`` in the vendored cudf test tree,
SURVEY.md §2.3 relational-ops row). Parsing runs on host via Arrow's
multithreaded CSV reader; typed columns then upload once.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..column import Table
from ..utils.tracing import trace_range
from . import predicates as preds

try:
    import pyarrow as pa
    import pyarrow.csv as pa_csv
except ImportError:  # pragma: no cover
    pa = pa_csv = None


def _require():
    if pa_csv is None:  # pragma: no cover
        raise ImportError("pyarrow.csv not available")


def read_csv(
    path,
    columns: Optional[Sequence[str]] = None,
    filters=None,
    delimiter: str = ",",
    header: bool = True,
    column_names: Optional[Sequence[str]] = None,
    dtypes: Optional[dict] = None,
    pad_widths: Optional[dict] = None,
) -> Table:
    """CSV file -> device Table (optional projection + device filter)."""
    _require()
    from ..interop import table_from_arrow
    from .parquet import _apply_exact_filter

    predicate = preds.from_dnf(filters) if filters is not None else None
    read_opts = pa_csv.ReadOptions(
        column_names=list(column_names) if column_names else None,
        autogenerate_column_names=not header and column_names is None,
        # with explicit names, the file's header line (if any) is data to
        # pyarrow — skip it ourselves
        skip_rows=1 if (header and column_names) else 0,
    )
    parse_opts = pa_csv.ParseOptions(delimiter=delimiter)
    # With an explicit projection the decode set is known up front, so
    # unused columns skip type conversion entirely; without one the column
    # list is only known after the read.
    if columns is not None:
        want, read_cols = preds.projection_columns(
            predicate, columns, columns
        )
    else:
        want = read_cols = None
    convert_opts = pa_csv.ConvertOptions(
        column_types={k: v for k, v in (dtypes or {}).items()},
        include_columns=read_cols,
    )
    with trace_range("io.csv.parse"):
        atbl = pa_csv.read_csv(
            path,
            read_options=read_opts,
            parse_options=parse_opts,
            convert_options=convert_opts,
        )
    if want is None:
        want, read_cols = preds.projection_columns(
            predicate, None, atbl.column_names
        )
        atbl = atbl.select(read_cols)
    with trace_range("io.csv.upload"):
        dev = table_from_arrow(atbl, pad_widths=pad_widths)
    if predicate is not None:
        with trace_range("io.csv.filter"):
            dev = _apply_exact_filter(dev, predicate, want)
    return dev


def write_csv(table: Table, path, delimiter: str = ",", header: bool = True) -> None:
    """Device Table -> CSV file."""
    _require()
    from ..interop import table_to_arrow

    with trace_range("io.csv.write"):
        atbl = table_to_arrow(table)
        pa_csv.write_csv(
            atbl,
            path,
            write_options=pa_csv.WriteOptions(
                include_header=header, delimiter=delimiter
            ),
        )


def scan_csv(
    path,
    columns: Optional[Sequence[str]] = None,
    filters=None,
    delimiter: str = ",",
    header: bool = True,
    block_size: int = 1 << 22,
    dtypes: Optional[dict] = None,
    pad_widths: Optional[dict] = None,
    prefetch: int = 0,
):
    """Stream a CSV file as device Table batches (Arrow incremental
    reader, one batch per ~``block_size`` bytes). ``prefetch=N`` parses
    and uploads ahead on a background thread like scan_parquet.

    ``dtypes`` pins column types up front — the incremental reader infers
    types from the FIRST block only and aborts on later drift, so pin any
    column whose early rows underdetermine its type (e.g. ints followed by
    floats past ``block_size``)."""
    _require()
    from .parquet import _prefetch_iter

    if prefetch > 0:
        return _prefetch_iter(
            scan_csv(path, columns, filters, delimiter, header,
                     block_size, dtypes, pad_widths, prefetch=0),
            prefetch,
        )
    return _scan_csv_serial(
        path, columns, filters, delimiter, header, block_size, dtypes,
        pad_widths,
    )


def _scan_csv_serial(
    path, columns, filters, delimiter, header, block_size, dtypes,
    pad_widths,
):
    from ..interop import table_from_arrow
    from .parquet import _apply_exact_filter

    predicate = preds.from_dnf(filters) if filters is not None else None
    read_opts = pa_csv.ReadOptions(
        autogenerate_column_names=not header, block_size=block_size
    )
    parse_opts = pa_csv.ParseOptions(delimiter=delimiter)
    # with an explicit projection the convert set is known up front, so
    # unprojected columns skip host type conversion entirely (same
    # pushdown read_csv does); without one it's known after block 1
    want = read_cols = None
    if columns is not None:
        want, read_cols = preds.projection_columns(
            predicate, columns, columns
        )
    convert_opts = pa_csv.ConvertOptions(
        column_types={k: v for k, v in (dtypes or {}).items()},
        include_columns=read_cols,
    )
    with pa_csv.open_csv(
        path,
        read_options=read_opts,
        parse_options=parse_opts,
        convert_options=convert_opts,
    ) as reader:
        while True:
            with trace_range("io.csv.parse"):
                try:
                    batch = reader.read_next_batch()
                except StopIteration:
                    break
                atbl = pa.Table.from_batches([batch])
            if want is None:
                want, read_cols = preds.projection_columns(
                    predicate, None, atbl.column_names
                )
            with trace_range("io.csv.upload"):
                dev = table_from_arrow(
                    atbl.select(read_cols), pad_widths=pad_widths
                )
            if predicate is not None:
                with trace_range("io.csv.filter"):
                    dev = _apply_exact_filter(dev, predicate, want)
            yield dev
