"""Scan predicates: host-side stats pruning + device-side residual filter.

A predicate is a DNF tree (OR of ANDs of leaf comparisons), the same shape
pyarrow/Spark push down to Parquet readers. Two evaluators:

* ``maybe_matches(stats)`` — conservative host check against per-row-group
  (or per-stripe) min/max/null statistics: may a row in this group satisfy
  the predicate? False ⇒ the group is skipped before decode (the pushdown
  the reference gets from cudf's Parquet reader).
* ``evaluate(table)`` — exact device evaluation producing a BOOL8 mask
  Column via the binaryop library, with Spark null semantics (null
  comparisons are null ⇒ row dropped by WHERE).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import numpy as np

from .. import dtype as dt
from ..column import Column, Table

_LEAF_OPS = {"==", "!=", "<", "<=", ">", ">=", "in", "not in", "is_null", "is_not_null"}

_BINOP_NAME = {"==": "eq", "!=": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}


@dataclasses.dataclass(frozen=True)
class ColumnStats:
    """Per-group statistics as found in a Parquet footer / ORC stripe."""

    min: Any = None
    max: Any = None
    null_count: Optional[int] = None
    num_values: Optional[int] = None

    @property
    def has_nulls(self) -> Optional[bool]:
        if self.null_count is None:
            return None
        return self.null_count > 0

    @property
    def all_null(self) -> Optional[bool]:
        if self.null_count is None or self.num_values is None:
            return None
        return self.null_count >= self.num_values


class Predicate:
    """Base class; build with ``col("x") > 3``, ``and_``/``or_``."""

    def maybe_matches(self, stats: dict) -> bool:
        raise NotImplementedError

    def evaluate(self, table: Table) -> Column:
        raise NotImplementedError

    def columns(self) -> set:
        raise NotImplementedError

    def __and__(self, other: "Predicate") -> "Predicate":
        return And([self, other])

    def __or__(self, other: "Predicate") -> "Predicate":
        return Or([self, other])


def _literal_column(value, n: int, like: Column) -> Column:
    """Broadcast a Python literal to an n-row column of a compatible dtype."""
    if like.dtype.is_string:
        if isinstance(value, str):
            value = value.encode("utf-8", "surrogateescape")
        return Column.from_strings([value] * n, pad_width=max(len(value), 1))
    if like.dtype.is_decimal:
        # Literal given in *scaled* units (a plain number): convert to the
        # column's unscaled representation.
        unscaled = int(round(float(value) * 10 ** (-like.dtype.scale)))
        host = np.full((n,), unscaled, dtype=np.dtype(like.dtype.device_dtype))
        return Column.from_numpy(host, dtype=like.dtype)
    if like.dtype.is_timestamp or like.dtype.is_duration:
        host = np.full(
            (n,), int(value), dtype=np.dtype(f"i{like.dtype.itemsize}")
        )
        return Column.from_numpy(host, dtype=like.dtype)
    host = np.full((n,), value, dtype=np.dtype(like.dtype.device_dtype))
    return Column.from_numpy(host, dtype=like.dtype)


@dataclasses.dataclass(frozen=True)
class Leaf(Predicate):
    name: str
    op: str
    value: Any = None

    def __post_init__(self):
        # normalize pyarrow/SQL spellings
        aliases = {"=": "==", "<>": "!="}
        if self.op in aliases:
            object.__setattr__(self, "op", aliases[self.op])
        if self.op not in _LEAF_OPS:
            raise ValueError(f"unknown predicate op {self.op!r}")

    def columns(self) -> set:
        return {self.name}

    # -- host pruning ----------------------------------------------------
    def maybe_matches(self, stats: dict) -> bool:
        st = stats.get(self.name)
        if st is None:
            return True  # no stats -> cannot prune
        if self.op == "is_null":
            return st.has_nulls is not False
        if self.op == "is_not_null":
            return st.all_null is not True
        lo, hi = st.min, st.max
        if lo is None or hi is None:
            return True
        v = self.value
        try:
            if self.op == "==":
                return lo <= v <= hi
            if self.op == "!=":
                return not (lo == v == hi)
            if self.op == "<":
                return lo < v
            if self.op == "<=":
                return lo <= v
            if self.op == ">":
                return hi > v
            if self.op == ">=":
                return hi >= v
            if self.op == "in":
                return any(lo <= x <= hi for x in v)
            if self.op == "not in":
                return not any(lo == x == hi for x in v)
        except TypeError:
            return True  # incomparable literal vs stats -> keep the group
        return True

    # -- device residual -------------------------------------------------
    def evaluate(self, table: Table) -> Column:
        from ..ops import binaryop, unaryop

        c = table[self.name]
        if self.op == "is_null":
            return unaryop.is_null(c)
        if self.op == "is_not_null":
            return unaryop.is_not_null(c)
        if self.op in ("in", "not in"):
            acc = None
            for v in self.value:
                lit = _literal_column(v, c.row_count, c)
                term = binaryop.binary_op("eq", c, lit)
                acc = term if acc is None else binaryop.binary_op("or", acc, term)
            if acc is None:
                # SQL semantics for the empty list: x IN () is false,
                # x NOT IN () is true — both null for null x.
                import jax.numpy as jnp

                if self.op == "not in":
                    return unaryop.is_not_null(c)
                return Column(
                    jnp.zeros((c.row_count,), dtype=jnp.bool_), dt.BOOL8, None
                )
            if self.op == "not in":
                return unaryop.unary_op("not", acc)
            return acc
        lit = _literal_column(self.value, c.row_count, c)
        return binaryop.binary_op(_BINOP_NAME[self.op], c, lit)


@dataclasses.dataclass(frozen=True)
class And(Predicate):
    children: Sequence[Predicate]

    def __post_init__(self):
        object.__setattr__(self, "children", tuple(self.children))

    def columns(self) -> set:
        return set().union(*(c.columns() for c in self.children))

    def maybe_matches(self, stats: dict) -> bool:
        return all(c.maybe_matches(stats) for c in self.children)

    def evaluate(self, table: Table) -> Column:
        from ..ops import binaryop

        out = self.children[0].evaluate(table)
        for c in self.children[1:]:
            out = binaryop.binary_op("and", out, c.evaluate(table))
        return out


@dataclasses.dataclass(frozen=True)
class Or(Predicate):
    children: Sequence[Predicate]

    def __post_init__(self):
        object.__setattr__(self, "children", tuple(self.children))

    def columns(self) -> set:
        return set().union(*(c.columns() for c in self.children))

    def maybe_matches(self, stats: dict) -> bool:
        return any(c.maybe_matches(stats) for c in self.children)

    def evaluate(self, table: Table) -> Column:
        from ..ops import binaryop

        out = self.children[0].evaluate(table)
        for c in self.children[1:]:
            out = binaryop.binary_op("or", out, c.evaluate(table))
        return out


class _ColBuilder:
    """``col("x") > 3`` sugar for building Leaf predicates."""

    def __init__(self, name: str):
        self._name = name

    def __eq__(self, other):  # type: ignore[override]
        return Leaf(self._name, "==", other)

    def __ne__(self, other):  # type: ignore[override]
        return Leaf(self._name, "!=", other)

    def __lt__(self, other):
        return Leaf(self._name, "<", other)

    def __le__(self, other):
        return Leaf(self._name, "<=", other)

    def __gt__(self, other):
        return Leaf(self._name, ">", other)

    def __ge__(self, other):
        return Leaf(self._name, ">=", other)

    def isin(self, values):
        return Leaf(self._name, "in", tuple(values))

    def not_in(self, values):
        return Leaf(self._name, "not in", tuple(values))

    def is_null(self):
        return Leaf(self._name, "is_null")

    def is_not_null(self):
        return Leaf(self._name, "is_not_null")

    __hash__ = None  # builders are not hashable (== builds a Leaf)


def col(name: str) -> _ColBuilder:
    return _ColBuilder(name)


def and_(*preds: Predicate) -> Predicate:
    return And(preds) if len(preds) > 1 else preds[0]


def or_(*preds: Predicate) -> Predicate:
    return Or(preds) if len(preds) > 1 else preds[0]


def projection_columns(
    predicate: Optional[Predicate], columns, all_names
) -> tuple[list, list]:
    """(wanted output columns, columns to actually decode).

    The decode set adds the predicate's columns so the residual filter can
    evaluate; they are dropped again after filtering (Spark does the same
    for pushed-down scan filters).
    """
    want = list(columns) if columns is not None else list(all_names)
    read_cols = want
    if predicate is not None:
        extra = [c for c in sorted(predicate.columns()) if c not in want]
        read_cols = want + extra
    return want, read_cols


def from_dnf(filters) -> Predicate:
    """pyarrow-style DNF list(s) of (col, op, value) -> Predicate tree."""
    if isinstance(filters, Predicate):
        return filters
    if filters and isinstance(filters[0], tuple):
        filters = [filters]
    conjunctions = []
    for conj in filters:
        leaves = [Leaf(name, op, value) for (name, op, value) in conj]
        conjunctions.append(And(leaves) if len(leaves) > 1 else leaves[0])
    return Or(conjunctions) if len(conjunctions) > 1 else conjunctions[0]
