"""ORC scan/write (host Arrow decode -> HBM; stripe-granular streaming).

Capability parity with the ORC half of the reference's columnar I/O
surface (SURVEY.md §2.3 "Compressed columnar file I/O"; the cudf Java test
tree the reference runs covers ORC round trips). The host decoder
(pyarrow.orc) does not expose per-stripe statistics to Python, so pruning
is file-granular only; exact predicate filtering still runs on device,
which keeps results identical to the Parquet path.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

from ..column import Table
from ..utils.tracing import trace_range
from . import predicates as preds

try:
    import pyarrow as pa
    import pyarrow.orc as pa_orc
except ImportError:  # pragma: no cover
    pa = pa_orc = None


def _require():
    if pa_orc is None:  # pragma: no cover
        raise ImportError("pyarrow.orc not available")


def scan_orc(
    path,
    columns: Optional[Sequence[str]] = None,
    filters=None,
    pad_widths: Optional[dict] = None,
    exact_filter: bool = True,
    prefetch: int = 0,
) -> Iterator[Table]:
    """Stream an ORC file stripe-by-stripe as device Tables.

    ``prefetch=N`` decodes/uploads up to N stripes ahead on a background
    thread (same overlap machinery as scan_parquet)."""
    _require()
    from ..interop import table_from_arrow
    from .parquet import _apply_exact_filter, _prefetch_iter

    if prefetch > 0:
        return _prefetch_iter(
            scan_orc(path, columns, filters, pad_widths, exact_filter,
                     prefetch=0),
            prefetch,
        )
    return _scan_orc_serial(
        path, columns, filters, pad_widths, exact_filter
    )


def _scan_orc_serial(
    path, columns, filters, pad_widths, exact_filter
) -> Iterator[Table]:
    from ..interop import table_from_arrow
    from .parquet import _apply_exact_filter

    predicate = preds.from_dnf(filters) if filters is not None else None
    f = pa_orc.ORCFile(path)
    want, read_cols = preds.projection_columns(
        predicate, columns, f.schema.names
    )
    for i in range(f.nstripes):
        with trace_range("io.orc.decode"):
            batch = f.read_stripe(i, columns=read_cols)
            atbl = pa.Table.from_batches([batch])
        with trace_range("io.orc.upload"):
            dev = table_from_arrow(atbl, pad_widths=pad_widths)
        if predicate is not None and exact_filter:
            with trace_range("io.orc.filter"):
                dev = _apply_exact_filter(dev, predicate, want)
        yield dev


def read_orc(
    path,
    columns: Optional[Sequence[str]] = None,
    filters=None,
    pad_widths: Optional[dict] = None,
    exact_filter: bool = True,
) -> Table:
    """Eager ORC read -> one device Table."""
    _require()
    from ..interop import table_from_arrow
    from .parquet import _apply_exact_filter

    predicate = preds.from_dnf(filters) if filters is not None else None
    f = pa_orc.ORCFile(path)
    want, read_cols = preds.projection_columns(
        predicate, columns, f.schema.names
    )
    with trace_range("io.orc.decode"):
        atbl = f.read(columns=read_cols)
    with trace_range("io.orc.upload"):
        dev = table_from_arrow(atbl, pad_widths=pad_widths)
    if predicate is not None and exact_filter:
        with trace_range("io.orc.filter"):
            dev = _apply_exact_filter(dev, predicate, want)
    return dev


def write_orc(table: Table, path, compression: str = "zstd") -> None:
    """Device Table -> ORC file."""
    _require()
    from ..interop import table_to_arrow

    with trace_range("io.orc.write"):
        atbl = table_to_arrow(table)
        pa_orc.write_table(atbl, path, compression=compression)
