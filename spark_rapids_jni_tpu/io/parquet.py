"""Parquet scan/write: host decode -> async HBM upload -> device filter.

The reference's Parquet path is libcudf's GPU decoder fed by nvcomp
(SURVEY.md §2.3 row "Compressed columnar file I/O"); its pushdown happens
inside cudf's reader. The TPU-native shape decodes on host (Arrow) and
pushes three things down *before* any byte reaches HBM:

1. column projection (only requested + predicate columns are decoded),
2. row-group pruning against footer min/max/null statistics
   (predicates.Leaf.maybe_matches), and
3. exact residual filtering on device over the uploaded batch
   (predicates.Predicate.evaluate + ops.filter), where Spark's null
   semantics are applied by the columnar op library.

``scan_parquet`` streams row-group batches (the unit the reference's 2 GB
batching discipline maps to, row_conversion.cu:505-511); ``read_parquet``
is the eager single-table form.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence, Union

from ..column import Table
from ..utils.tracing import trace_range
from . import predicates as preds
from .predicates import ColumnStats, Predicate

try:  # pyarrow is optional (environment contract — no new installs)
    import pyarrow as pa
    import pyarrow.parquet as pq
except ImportError:  # pragma: no cover
    pa = pq = None


def _require():
    if pq is None:  # pragma: no cover
        raise ImportError("pyarrow.parquet not available")


def _normalize_paths(path) -> list:
    import os

    def one(p):
        # fspath: pathlib.Path must behave exactly like str (the
        # device_decode gate checks isinstance(p, str))
        try:
            return os.fspath(p)
        except TypeError:
            return p  # file-like objects pass through

    if isinstance(path, (list, tuple)):
        return [one(p) for p in path]
    return [one(path)]


def _row_group_stats(meta, rg_index: int, names: Sequence[str]) -> dict:
    """Footer statistics for one row group, keyed by column name."""
    out = {}
    rg = meta.row_group(rg_index)
    want = set(names)
    for ci in range(rg.num_columns):
        colmeta = rg.column(ci)
        name = colmeta.path_in_schema
        if name not in want:
            continue
        st = colmeta.statistics
        if st is None:
            continue
        try:
            lo = st.min if st.has_min_max else None
            hi = st.max if st.has_min_max else None
        except (ValueError, TypeError):  # undecodable physical stats
            lo = hi = None
        out[name] = ColumnStats(
            min=lo,
            max=hi,
            null_count=st.null_count if st.has_null_count else None,
            num_values=colmeta.num_values,
        )
    return out


def parquet_metadata(path) -> dict:
    """Schema + per-row-group stats (host only, reads just the footer)."""
    _require()
    pf = pq.ParquetFile(path)
    names = pf.schema_arrow.names
    return {
        "num_rows": pf.metadata.num_rows,
        "num_row_groups": pf.metadata.num_row_groups,
        "columns": names,
        "row_groups": [
            {
                "num_rows": pf.metadata.row_group(i).num_rows,
                "stats": _row_group_stats(pf.metadata, i, names),
            }
            for i in range(pf.metadata.num_row_groups)
        ],
    }


def _apply_exact_filter(table: Table, predicate: Predicate, keep_names) -> Table:
    from ..ops.filter import filter_table

    mask = predicate.evaluate(table)
    out = filter_table(table, mask)
    if keep_names is not None and list(out.names) != list(keep_names):
        out = out.select(list(keep_names))
    return out


def _prefetch_iter(gen: Iterator, depth: int) -> Iterator:
    """Run ``gen`` on a daemon thread ``depth`` items ahead of the
    consumer — the decode/compute overlap the reference gets from
    nvcomp+GDS feeding the GPU decoder asynchronously (SURVEY.md §2.3
    file-I/O row). Arrow's decode and XLA's host->device upload both
    release the GIL, so row group k+1 decodes while the consumer
    computes on k even on one core. Producer exceptions re-raise at the
    consumption point."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
    sentinel = object()
    failure: list = []
    stop = threading.Event()

    def worker():
        try:
            for item in gen:
                # bounded put that observes shutdown: an early-exiting
                # consumer (LIMIT, exception) must not leave this thread
                # blocked forever pinning decoded device batches
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    break
        # srt: allow-broad-except(captured verbatim and re-raised on the consumer side — a relocation, not a swallow)
        except BaseException as e:
            failure.append(e)
        finally:
            gen.close()
            # the sentinel must actually land (a dropped sentinel leaves
            # the consumer blocked on q.get() forever); the same bounded
            # put as above so a stopped consumer doesn't pin this thread
            while not stop.is_set():
                try:
                    q.put(sentinel, timeout=0.1)
                    break
                except queue.Full:
                    continue

    threading.Thread(target=worker, daemon=True).start()
    try:
        while True:
            item = q.get()
            if item is sentinel:
                if failure:
                    raise failure[0]
                return
            yield item
    finally:
        stop.set()
        try:  # unblock a producer waiting on a full queue
            while True:
                q.get_nowait()
        except queue.Empty:
            pass


def scan_parquet(
    path,
    columns: Optional[Sequence[str]] = None,
    filters=None,
    pad_widths: Optional[dict] = None,
    row_groups_per_batch: int = 1,
    exact_filter: bool = True,
    prefetch: int = 0,
    device_decode: bool = False,
) -> Iterator[Table]:
    """Stream a Parquet file (or list of files) as device Table batches.

    Each batch covers ``row_groups_per_batch`` surviving row groups.
    ``filters`` is a Predicate (``col("x") > 3``) or pyarrow-style DNF
    list of (name, op, value) tuples. ``prefetch=N`` decodes and uploads
    up to N batches ahead on a background thread, overlapping host
    decode with device compute (round-3 VERDICT item 10).

    ``device_decode=True`` moves page decode onto the device for
    fixed-width PLAIN/dictionary columns (io/parquet_device.py): the
    host parses headers and uploads the still-encoded page bytes, the
    chip does the O(n) expansion — the libcudf+nvcomp role. Columns the
    device path can't take fall back to Arrow transparently.
    """
    _require()
    if prefetch > 0:
        return _prefetch_iter(
            scan_parquet(
                path, columns, filters, pad_widths,
                row_groups_per_batch, exact_filter, prefetch=0,
                device_decode=device_decode,
            ),
            prefetch,
        )
    return _scan_parquet_serial(
        path, columns, filters, pad_widths, row_groups_per_batch,
        exact_filter, device_decode,
    )


def _device_decode_batch(path, pf, row_groups, read_cols, pad_widths):
    """Device-decode one batch; Arrow-decode only what the device path
    refuses, preserving the requested column order."""
    from ..interop import table_from_arrow
    from . import parquet_device as pdev

    per_rg = []
    for rg in row_groups:
        decoded, fallback = pdev.decode_row_group(path, pf, rg, read_cols)
        if fallback:
            atbl = pf.read_row_groups([rg], columns=fallback)
            host = table_from_arrow(atbl, pad_widths=pad_widths)
            for name, col in zip(host.names, host.columns):
                decoded[name] = col
        per_rg.append(
            Table([decoded[n] for n in read_cols], list(read_cols))
        )
    if len(per_rg) == 1:
        return per_rg[0]
    from ..ops.copying import concatenate

    return concatenate(per_rg)


def _scan_parquet_serial(
    path, columns, filters, pad_widths, row_groups_per_batch,
    exact_filter, device_decode=False,
) -> Iterator[Table]:
    predicate = preds.from_dnf(filters) if filters is not None else None
    for p in _normalize_paths(path):
        pf = pq.ParquetFile(p)
        want, read_cols = preds.projection_columns(
            predicate, columns, pf.schema_arrow.names
        )
        stats_names = (
            sorted(predicate.columns()) if predicate is not None else []
        )

        surviving = []
        for rg in range(pf.metadata.num_row_groups):
            if predicate is not None:
                stats = _row_group_stats(pf.metadata, rg, stats_names)
                if not predicate.maybe_matches(stats):
                    continue
            surviving.append(rg)

        for i in range(0, len(surviving), max(row_groups_per_batch, 1)):
            batch = surviving[i : i + max(row_groups_per_batch, 1)]
            if device_decode and isinstance(p, str):
                with trace_range("io.parquet.device_decode"):
                    dev = _device_decode_batch(
                        p, pf, batch, read_cols, pad_widths
                    )
            else:
                with trace_range("io.parquet.decode"):
                    atbl = pf.read_row_groups(batch, columns=read_cols)
                with trace_range("io.parquet.upload"):
                    from ..interop import table_from_arrow

                    dev = table_from_arrow(atbl, pad_widths=pad_widths)
            if predicate is not None and exact_filter:
                with trace_range("io.parquet.filter"):
                    dev = _apply_exact_filter(dev, predicate, want)
            yield dev


def read_parquet(
    path,
    columns: Optional[Sequence[str]] = None,
    filters=None,
    pad_widths: Optional[dict] = None,
    exact_filter: bool = True,
) -> Table:
    """Eager read: prune row groups, decode once, upload, filter on device."""
    _require()
    predicate = preds.from_dnf(filters) if filters is not None else None
    tables = []
    for p in _normalize_paths(path):
        pf = pq.ParquetFile(p)
        want, read_cols = preds.projection_columns(
            predicate, columns, pf.schema_arrow.names
        )
        if predicate is not None:
            stats_names = sorted(predicate.columns())
            surviving = [
                rg
                for rg in range(pf.metadata.num_row_groups)
                if predicate.maybe_matches(
                    _row_group_stats(pf.metadata, rg, stats_names)
                )
            ]
        else:
            surviving = list(range(pf.metadata.num_row_groups))
        with trace_range("io.parquet.decode"):
            atbl = pf.read_row_groups(surviving, columns=read_cols)
        tables.append(atbl)

    merged = tables[0] if len(tables) == 1 else pa.concat_tables(tables)
    with trace_range("io.parquet.upload"):
        from ..interop import table_from_arrow

        dev = table_from_arrow(merged, pad_widths=pad_widths)
    if predicate is not None and exact_filter:
        with trace_range("io.parquet.filter"):
            want = list(columns) if columns is not None else None
            dev = _apply_exact_filter(
                dev, predicate, want if want is not None else dev.names
            )
    return dev


def write_parquet(
    table: Table,
    path,
    compression: str = "snappy",
    row_group_size: Optional[int] = None,
) -> None:
    """Device Table -> Parquet file (host readback + Arrow writer)."""
    _require()
    from ..interop import table_to_arrow

    with trace_range("io.parquet.write"):
        atbl = table_to_arrow(table)
        pq.write_table(
            atbl, path, compression=compression, row_group_size=row_group_size
        )
