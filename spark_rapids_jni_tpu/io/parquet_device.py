"""Device-side Parquet page decode (PLAIN + RLE/dictionary, fixed width).

The reference decodes compressed pages ON the GPU (libcudf reader fed by
nvcomp, reference CMakeLists.txt:91, USE_GDS pom.xml:84); round 3 left
all decode on the host Arrow path, which Amdahl-caps the scan pipeline
at ~2x however much compute/decode overlap prefetch buys (r3 VERDICT
missing item 3). This module moves the O(n) decode work to the device:

  host    reads the RAW column-chunk bytes, parses page headers (a
          minimal Thrift compact-protocol reader — pyarrow exposes no
          page-level API), host-decompresses the codec (the nvcomp
          role; snappy/zstd via pyarrow.Codec), and parses RLE run
          HEADERS only — O(#runs), not O(values).
  upload  the still-ENCODED payload bytes: dictionary-encoded pages are
          typically several times smaller than decoded columns, so the
          host->HBM link (the tunnel here, PCIe in the reference's
          world) moves less data than the Arrow path uploads.
  device  everything O(n): definition levels -> validity + compaction
          gathers, bit-field extraction of dictionary indices
          (searchsorted over the run table + byte gathers + shifts),
          dictionary gathers, PLAIN byte reinterpretation.

Scope (the VERDICT item-4 contract): fixed-width physical types
(INT32/INT64/FLOAT/DOUBLE — including DECIMAL and DATE logical types
stored on them), PLAIN and RLE_DICTIONARY/PLAIN_DICTIONARY encodings,
v1 data pages, flat schemas. Everything else falls back to the host
Arrow path per column (io/parquet.py), so ``scan_parquet(...,
device_decode=True)`` is always correct and only faster where it can
be.
"""

from __future__ import annotations

import dataclasses
import struct as _struct
from typing import Optional

import numpy as np

from .. import dtype as dt
from ..column import Column

# parquet-format enums (format/Encodings.md)
_PAGE_DATA = 0
_PAGE_INDEX = 1
_PAGE_DICT = 2
_PAGE_DATA_V2 = 3
_ENC_PLAIN = 0
_ENC_PLAIN_DICT = 2
_ENC_RLE = 3
_ENC_RLE_DICT = 8

_PHYS_WIDTH = {  # parquet physical type id -> byte width
    1: 4,   # INT32
    2: 8,   # INT64
    4: 4,   # FLOAT
    5: 8,   # DOUBLE
}
_PHYS_NP = {1: np.int32, 2: np.int64, 4: np.float32, 5: np.float64}


# ---------------------------------------------------------------------------
# host: Thrift compact-protocol PageHeader reader
# ---------------------------------------------------------------------------


class _Compact:
    """Just enough of Thrift compact protocol to walk PageHeader structs
    (parquet-format.thrift): varints, zigzag, generic field skipping."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self.byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def skip(self, ftype: int) -> None:
        if ftype in (1, 2):       # bool true/false: value in the type
            return
        if ftype == 3:            # byte
            self.pos += 1
        elif ftype in (4, 5, 6):  # i16/i32/i64
            self.varint()
        elif ftype == 7:          # double
            self.pos += 8
        elif ftype == 8:          # binary
            # NOTE: not `self.pos += self.varint()` — augmented
            # assignment loads the old pos BEFORE varint() advances it,
            # silently landing one byte short per length byte
            n = self.varint()
            self.pos += n
        elif ftype in (9, 10):    # list/set
            head = self.byte()
            size = head >> 4
            etype = head & 0x0F
            if size == 15:
                size = self.varint()
            for _ in range(size):
                self.skip(etype)
        elif ftype == 12:         # struct
            self.struct_skip()
        else:  # pragma: no cover - map etc. don't appear in PageHeader
            raise ValueError(f"unsupported thrift compact type {ftype}")

    def struct_skip(self) -> None:
        last = 0
        while True:
            head = self.byte()
            if head == 0:
                return
            delta = head >> 4
            ftype = head & 0x0F
            last = last + delta if delta else self.zigzag()
            self.skip(ftype)

    def struct_fields(self) -> dict:
        """Parse one struct into {field_id: value} with i-types decoded,
        sub-structs recursed, everything else skipped."""
        out = {}
        last = 0
        while True:
            head = self.byte()
            if head == 0:
                return out
            delta = head >> 4
            ftype = head & 0x0F
            fid = last + delta if delta else self.zigzag()
            last = fid
            if ftype == 1:
                out[fid] = True
            elif ftype == 2:
                out[fid] = False
            elif ftype in (4, 5, 6):
                out[fid] = self.zigzag()
            elif ftype == 12:
                out[fid] = self.struct_fields()
            else:
                self.skip(ftype)


@dataclasses.dataclass
class _Page:
    kind: int
    num_values: int
    encoding: int
    def_encoding: int
    payload: bytes  # decompressed


def _decompress(codec: str, buf: bytes, uncompressed_size: int) -> bytes:
    if codec in ("UNCOMPRESSED", None):
        return buf
    import pyarrow as pa

    return (
        pa.Codec(codec.lower())
        .decompress(buf, decompressed_size=uncompressed_size)
        .to_pybytes()
    )


def read_chunk_pages(f, colmeta) -> list[_Page]:
    """Walk one column chunk's raw bytes into decompressed pages."""
    offsets = [colmeta.data_page_offset]
    # truthiness also rejects 0: no page can start at the PAR1 magic,
    # and some writers surface "no dictionary" as 0 rather than None
    if colmeta.dictionary_page_offset:
        offsets.append(colmeta.dictionary_page_offset)
    start = min(offsets)
    f.seek(start)
    raw = f.read(colmeta.total_compressed_size)
    codec = colmeta.compression
    pages = []
    pos = 0
    while pos < len(raw):
        rd = _Compact(raw, pos)
        hdr = rd.struct_fields()
        pos = rd.pos
        comp_size = hdr[3]
        unc_size = hdr[2]
        payload = _decompress(codec, raw[pos : pos + comp_size], unc_size)
        pos += comp_size
        kind = hdr[1]
        if kind == _PAGE_DICT:
            sub = hdr.get(7, {})
            pages.append(_Page(kind, sub.get(1, 0), sub.get(2, 0), 0, payload))
        elif kind == _PAGE_DATA:
            sub = hdr.get(5, {})
            pages.append(
                _Page(kind, sub.get(1, 0), sub.get(2, 0), sub.get(3, 0),
                      payload)
            )
        else:
            # v2/index pages: whole chunk falls back to Arrow
            raise _Unsupported(f"page type {kind}")
    return pages


class _Unsupported(Exception):
    """Column can't take the device path; caller falls back to Arrow."""


# ---------------------------------------------------------------------------
# host: RLE/bit-packed hybrid run-header parse — O(#runs)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _RunTable:
    out_start: np.ndarray   # (R,) int32 first output index of each run
    is_packed: np.ndarray   # (R,) bool
    value: np.ndarray       # (R,) int32 repeated value (RLE runs)
    bit_base: np.ndarray    # (R,) int64 payload bit offset (packed runs)
    consumed: int           # payload bytes consumed


def parse_rle_runs(buf: bytes, bit_width: int, num_values: int) -> _RunTable:
    pos = 0
    out = 0
    starts, packed, values, bases = [], [], [], []
    vbytes = (bit_width + 7) // 8
    while out < num_values:
        if pos >= len(buf):
            raise _Unsupported("RLE stream truncated")
        rd = _Compact(buf, pos)
        header = rd.varint()
        pos = rd.pos
        if header & 1:
            groups = header >> 1
            starts.append(out)
            packed.append(True)
            values.append(0)
            bases.append(pos * 8)
            pos += groups * bit_width
            out += groups * 8
        else:
            count = header >> 1
            if count == 0:
                raise _Unsupported("zero-length RLE run")
            v = int.from_bytes(buf[pos : pos + vbytes], "little")
            starts.append(out)
            packed.append(False)
            values.append(v)
            bases.append(0)
            pos += vbytes
            out += count
    return _RunTable(
        np.asarray(starts, np.int32),
        np.asarray(packed, np.bool_),
        np.asarray(values, np.int32),
        np.asarray(bases, np.int64),
        pos,
    )


# ---------------------------------------------------------------------------
# device: O(n) decode kernels (pure jnp; everything jit-friendly)
# ---------------------------------------------------------------------------


def _pow2(x: int) -> int:
    return 1 << max(0, (max(x, 1) - 1).bit_length())


import functools


@functools.lru_cache(maxsize=512)
def _expand_runs_fn(bit_width: int, n_cap: int):
    """Jitted hybrid-run expansion at a pow2 capacity. Shapes are
    bucketed (runs, payload bytes and output all pad to pow2) so pages
    of a big file reuse a handful of compiled executables instead of
    recompiling per page — without this, per-page compile time dwarfed
    the decode itself on the first measurement."""
    import jax
    import jax.numpy as jnp

    def fn(out_start, is_packed, value, bit_base, packed_bytes):
        pos = jnp.arange(n_cap, dtype=jnp.int32)
        r = jnp.clip(
            jnp.searchsorted(out_start, pos, side="right") - 1,
            0,
            out_start.shape[0] - 1,
        )
        in_run = pos - out_start[r]
        bit = bit_base[r] + in_run.astype(jnp.int64) * bit_width
        byte = (bit >> 3).astype(jnp.int32)
        shift = (bit & 7).astype(jnp.uint32)
        m = packed_bytes.shape[0]

        def at(k):
            return packed_bytes[
                jnp.clip(byte + k, 0, m - 1)
            ].astype(jnp.uint32)

        word = at(0) | (at(1) << 8) | (at(2) << 16) | (at(3) << 24)
        mask = jnp.uint32((1 << bit_width) - 1)
        extracted = ((word >> shift) & mask).astype(jnp.int32)
        return jnp.where(is_packed[r], extracted, value[r])

    return jax.jit(fn)


_RUN_SENTINEL = np.int32(2**31 - 1)  # padding runs sort past any pos


def _device_expand_runs(
    runs: _RunTable, packed_bytes, bit_width: int, n: int
):
    """(n,) int32 values of an RLE/bit-packed hybrid stream. One
    searchsorted over the run table per output plus a 4-byte gather and
    shift/mask for packed runs — the vectorized TPU replacement for the
    sequential run walk a CPU/GPU decoder does per thread block."""
    import jax.numpy as jnp

    if bit_width > 24:
        # 4-byte window can't always cover a >24-bit field crossing a
        # byte boundary
        raise _Unsupported(f"bit width {bit_width} > 24")

    r_cap = _pow2(len(runs.out_start))
    b_cap = _pow2(packed_bytes.shape[0] + 4)

    def pad(a, cap, fill=0):
        out = np.full((cap,), fill, a.dtype)
        out[: len(a)] = a
        return jnp.asarray(out)

    out = _expand_runs_fn(bit_width, _pow2(n))(
        pad(runs.out_start, r_cap, _RUN_SENTINEL),
        pad(runs.is_packed, r_cap),
        pad(runs.value, r_cap),
        pad(runs.bit_base, r_cap),
        jnp.pad(packed_bytes, (0, b_cap - packed_bytes.shape[0])),
    )
    return out[:n]


def _defined_count(runs: _RunTable, buf: bytes, n: int) -> int:
    """Host-side exact count of def-level==1 values — O(#runs) plus a
    popcount over the packed sections (1 bit/value). Needed because a
    dictionary page's index stream holds only the DEFINED values: asking
    the run parser for all n raises 'truncated' on every nullable dict
    page (r4 review finding)."""
    total = 0
    starts = runs.out_start
    for i in range(len(starts)):
        start = int(starts[i])
        end = int(starts[i + 1]) if i + 1 < len(starts) else n
        end = min(end, n)
        run_len = max(0, end - start)
        if run_len == 0:
            continue
        if runs.is_packed[i]:
            base = int(runs.bit_base[i]) // 8
            nbytes = (run_len + 7) // 8
            bits = np.unpackbits(
                np.frombuffer(buf[base : base + nbytes], np.uint8),
                bitorder="little",
            )[:run_len]
            total += int(bits.sum())
        elif int(runs.value[i]) == 1:
            total += run_len
    return total


def _device_defined(def_runs, def_bytes, n: int):
    """Definition levels (flat schema: max level 1) -> (n,) bool."""
    if def_runs is None:
        import jax.numpy as jnp

        return jnp.ones((n,), jnp.bool_)
    levels = _device_expand_runs(def_runs, def_bytes, 1, n)
    return levels == 1


@functools.lru_cache(maxsize=256)
def _plain_fn(width: int, kind: str, cap_bytes: int):
    """Jitted PLAIN recombine at a pow2 byte capacity (shape-bucketed
    like _expand_runs_fn)."""
    import jax
    import jax.numpy as jnp

    def fn(values_u8):
        mat = values_u8.reshape(-1, width)

        def combine(cols, utype, shift_t):
            out = cols[:, 0].astype(utype)
            for k in range(1, cols.shape[1]):
                out = out | (cols[:, k].astype(utype) << shift_t(8 * k))
            return out

        if width == 4:
            out = combine(mat, jnp.uint32, jnp.uint32)
            target = jnp.int32 if kind == "i" else jnp.float32
            return jax.lax.bitcast_convert_type(out, target)
        out = combine(mat, jnp.uint64, jnp.uint64)
        if kind == "i":
            return jax.lax.bitcast_convert_type(out, jnp.int64)
        # FLOAT64 columns STORE the uint64 bit pattern (dtype.py: the
        # f64 emulation envelope) — the combined word IS the storage
        return out

    return jax.jit(fn)


def _device_plain(values_u8, width: int, np_dtype, n_defined_cap: int):
    """PLAIN page payload -> typed (n,) array: little-endian byte
    columns recombined with shifts, then one bitcast (elementwise VPU
    work; no data-dependent anything)."""
    import jax.numpy as jnp

    usable = (values_u8.shape[0] // width) * width
    n = min(n_defined_cap, usable // width)
    cap_bytes = max(_pow2(values_u8.shape[0]), width)
    padded = jnp.pad(values_u8, (0, cap_bytes - values_u8.shape[0]))
    kind = "i" if np_dtype in (np.int32, np.int64) else "f"
    out = _plain_fn(width, kind, cap_bytes)(padded)
    return out[:n]


# ---------------------------------------------------------------------------
# column assembly
# ---------------------------------------------------------------------------


def _decode_data_page(
    page: _Page, width: int, np_dtype, nullable: bool, dict_vals
):
    """One v1 data page -> (values (n,), defined (n,) bool)."""
    import jax.numpy as jnp

    n = page.num_values
    buf = page.payload
    pos = 0
    def_runs = None
    def_bytes = None
    if nullable:
        if page.def_encoding != _ENC_RLE:
            raise _Unsupported("non-RLE definition levels")
        (dl,) = _struct.unpack_from("<i", buf, pos)
        pos += 4
        raw_def = buf[pos : pos + dl]
        def_runs = parse_rle_runs(raw_def, 1, n)
        def_bytes = jnp.asarray(np.frombuffer(raw_def, np.uint8))
        pos += dl
    defined = _device_defined(def_runs, def_bytes, n)
    # the dense value stream stores DEFINED values only
    n_dense = n if def_runs is None else _defined_count(def_runs, raw_def, n)

    if page.encoding == _ENC_PLAIN:
        vals_dense = _device_plain(
            jnp.asarray(np.frombuffer(buf[pos:], np.uint8)), width,
            np_dtype, max(n_dense, 1),
        )
    elif page.encoding in (_ENC_PLAIN_DICT, _ENC_RLE_DICT):
        if dict_vals is None:
            raise _Unsupported("dictionary page missing")
        bw = buf[pos]
        pos += 1
        if bw == 0:
            idx_dense = jnp.zeros((max(n_dense, 1),), jnp.int32)
        else:
            runs = parse_rle_runs(buf[pos:], bw, max(n_dense, 1))
            packed = jnp.asarray(
                np.frombuffer(buf[pos : pos + runs.consumed], np.uint8)
            )
            idx_dense = _device_expand_runs(runs, packed, bw, max(n_dense, 1))
        idx_dense = jnp.clip(idx_dense, 0, dict_vals.shape[0] - 1)
        vals_dense = dict_vals[idx_dense]
    else:
        raise _Unsupported(f"encoding {page.encoding}")

    if not nullable:
        return vals_dense[:n], defined

    # dense stream holds DEFINED rows only: row i reads slot
    # cumsum(defined)-1, null rows read garbage and are masked
    slot = jnp.cumsum(defined.astype(jnp.int32)) - 1
    cap = vals_dense.shape[0]
    vals = vals_dense[jnp.clip(slot, 0, max(cap - 1, 0))]
    zero = jnp.zeros((), vals.dtype)
    return jnp.where(defined, vals, zero), defined


def decode_column_chunk(
    f, colmeta, field_dtype: dt.DType, nullable: bool
) -> Column:
    """One row group x one column -> device Column, or _Unsupported.

    ``nullable`` is the SCHEMA field's nullability: pyarrow writes
    definition levels for every optional field, nulls present or not."""
    import jax.numpy as jnp

    phys = colmeta.physical_type
    phys_id = {"INT32": 1, "INT64": 2, "FLOAT": 4, "DOUBLE": 5}.get(phys)
    if phys_id is None:
        raise _Unsupported(f"physical type {phys}")
    width = _PHYS_WIDTH[phys_id]
    np_dtype = _PHYS_NP[phys_id]
    pages = read_chunk_pages(f, colmeta)
    dict_vals = None
    parts = []
    masks = []
    for p in pages:
        if p.kind == _PAGE_DICT:
            if p.encoding not in (_ENC_PLAIN, _ENC_PLAIN_DICT):
                raise _Unsupported("non-PLAIN dictionary page")
            dict_vals = _device_plain(
                jnp.asarray(np.frombuffer(p.payload, np.uint8)), width,
                np_dtype, p.num_values,
            )
        else:
            vals, defined = _decode_data_page(
                p, width, np_dtype, nullable, dict_vals
            )
            parts.append(vals)
            masks.append(defined)
    if not parts:
        raise _Unsupported("no data pages")
    vals = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    validity = None
    if nullable:
        validity = (
            masks[0] if len(masks) == 1 else jnp.concatenate(masks)
        )
    data = vals
    storage = np.dtype(field_dtype.storage_dtype)
    if storage != vals.dtype:
        # logical narrowing (e.g. decimal64 stored as parquet INT32)
        data = vals.astype(storage)
    return Column(data, field_dtype, validity)


def decode_row_group(path: str, pf, rg: int, columns) -> tuple[dict, list]:
    """Try the device path for every requested column of one row group.

    Returns (decoded {name: Column}, fallback [names]) — the caller
    reads fallback columns through Arrow and reassembles in order."""
    from ..interop import _arrow_type_to_dtype as dtype_from_arrow

    schema = pf.schema_arrow
    rgmeta = pf.metadata.row_group(rg)
    name_to_ci = {
        rgmeta.column(ci).path_in_schema: ci
        for ci in range(rgmeta.num_columns)
    }
    decoded = {}
    fallback = []
    with open(path, "rb") as f:
        for name in columns:
            ci = name_to_ci.get(name)
            if ci is None:
                fallback.append(name)
                continue
            try:
                field = schema.field(name)
                fdt = dtype_from_arrow(field.type)
                decoded[name] = decode_column_chunk(
                    f, rgmeta.column(ci), fdt, field.nullable
                )
            # srt: allow-broad-except(transparent per-column fallback to the Arrow decoder — never a crashed scan)
            except Exception:
                # the contract is transparent per-column fallback:
                # truncated chunks (IndexError), short payloads
                # (struct.error), codec mismatches (ArrowInvalid) and
                # the typed _Unsupported all mean "Arrow decodes this
                # one" — never a crashed scan
                fallback.append(name)
    return decoded, fallback
