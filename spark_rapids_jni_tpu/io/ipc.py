"""Arrow IPC (Feather v2 / stream) read & write.

Arrow is the wire format of the whole framework (SURVEY.md §2.3 "Arrow
interop": the reference builds static Arrow into libcudf,
CUDF_USE_ARROW_STATIC=ON at build-libcudf.xml:41). IPC files are the
spill/exchange format between host processes — e.g. a Spark executor
handing batches to the TPU runtime out-of-process.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..column import Table
from ..utils.tracing import trace_range

try:
    import pyarrow as pa
    import pyarrow.ipc as pa_ipc
except ImportError:  # pragma: no cover
    pa = pa_ipc = None


def _require():
    if pa_ipc is None:  # pragma: no cover
        raise ImportError("pyarrow.ipc not available")


def read_arrow_ipc(
    path,
    columns: Optional[Sequence[str]] = None,
    pad_widths: Optional[dict] = None,
) -> Table:
    _require()
    from ..interop import table_from_arrow

    with trace_range("io.ipc.read"):
        with pa_ipc.open_file(path) as reader:
            atbl = reader.read_all()
    if columns is not None:
        atbl = atbl.select(list(columns))
    with trace_range("io.ipc.upload"):
        return table_from_arrow(atbl, pad_widths=pad_widths)


def write_arrow_ipc(table: Table, path) -> None:
    _require()
    from ..interop import table_to_arrow

    with trace_range("io.ipc.write"):
        atbl = table_to_arrow(table)
        with pa_ipc.new_file(path, atbl.schema) as writer:
            writer.write_table(atbl)
