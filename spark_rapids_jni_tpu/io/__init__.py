"""Columnar file I/O: the TPU-native analog of cudf's io layer.

The reference artifact ships compressed columnar file decode (Parquet/
ORC/CSV/JSON/Avro/Arrow-IPC here) via libcudf + nvcomp + optional GPUDirect Storage (SURVEY.md §2.3:
nvcomp include CMakeLists.txt:91, USE_GDS pom.xml:84; parquet-avro +
hadoop-common test deps pom.xml:112-123 feed the cudf Java I/O tests).

TPU-first shape (SURVEY.md §7 Phase 4): *host* decode (Arrow readers —
the nvcomp analog is Arrow's codec layer) feeding **async HBM uploads**,
with two-level predicate pushdown:

1. coarse: row-group/stripe pruning against file-footer statistics on the
   host (no decode, no upload for pruned groups), and
2. exact: residual predicate evaluated **on device** over the uploaded
   batch with the columnar op library (filter.py), where the TPU is fast.

Later rounds can move fixed-width/dictionary page decode itself into
Pallas; the interface here (scan -> Table batches) is already shaped for
that swap.
"""

from .predicates import Predicate, and_, or_, col  # noqa: F401
from .parquet import (  # noqa: F401
    read_parquet,
    scan_parquet,
    write_parquet,
    parquet_metadata,
)
from .orc import read_orc, scan_orc, write_orc  # noqa: F401
from .csv import read_csv, scan_csv, write_csv  # noqa: F401
from .ipc import read_arrow_ipc, write_arrow_ipc  # noqa: F401
from .json import read_json, scan_json, write_json  # noqa: F401
from .avro import read_avro, write_avro  # noqa: F401

__all__ = [
    "Predicate",
    "and_",
    "or_",
    "col",
    "read_parquet",
    "scan_parquet",
    "write_parquet",
    "parquet_metadata",
    "read_orc",
    "scan_orc",
    "write_orc",
    "read_csv",
    "scan_csv",
    "write_csv",
    "read_arrow_ipc",
    "write_arrow_ipc",
    "read_json",
    "scan_json",
    "write_json",
    "read_avro",
    "write_avro",
]
