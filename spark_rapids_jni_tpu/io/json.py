"""Line-delimited JSON read/write (host parse -> HBM upload).

Parity with the JSON surface of the cudf Java API the reference ships
(``Table.readJSON`` / ``JSONOptions`` in the vendored cudf test tree,
SURVEY.md §2.3 relational-ops row; cudf reads JSON-lines records).
Parsing runs on host via Arrow's multithreaded JSON reader; typed
columns then upload once, with the same two-level predicate pushdown and
background-prefetch streaming as the Parquet/ORC/CSV scanners.
"""

from __future__ import annotations

import io as _io
from typing import Optional, Sequence

from ..column import Table
from ..utils.tracing import trace_range
from . import predicates as preds

try:
    import pyarrow as pa
    import pyarrow.json as pa_json
except ImportError:  # pragma: no cover
    pa = pa_json = None


def _require():
    if pa_json is None:  # pragma: no cover
        raise ImportError("pyarrow.json not available")


def read_json(
    path,
    columns: Optional[Sequence[str]] = None,
    filters=None,
    dtypes: Optional[dict] = None,
    pad_widths: Optional[dict] = None,
) -> Table:
    """JSON-lines file -> device Table (projection + device filter).

    ``dtypes`` maps column name -> pyarrow type to pin parse types
    (Arrow's ``explicit_schema``); unlisted columns stay inferred."""
    _require()
    from ..interop import table_from_arrow
    from .parquet import _apply_exact_filter

    predicate = preds.from_dnf(filters) if filters is not None else None
    parse_opts = None
    if dtypes:
        parse_opts = pa_json.ParseOptions(
            explicit_schema=pa.schema(list(dtypes.items())),
            unexpected_field_behavior="infer",
        )
    with trace_range("io.json.parse"):
        atbl = pa_json.read_json(path, parse_options=parse_opts)
    want, read_cols = preds.projection_columns(
        predicate, columns, atbl.column_names
    )
    atbl = atbl.select(read_cols)
    with trace_range("io.json.upload"):
        dev = table_from_arrow(atbl, pad_widths=pad_widths)
    if predicate is not None:
        with trace_range("io.json.filter"):
            dev = _apply_exact_filter(dev, predicate, want)
    return dev


def write_json(table: Table, path) -> None:
    """Device Table -> JSON-lines file (the cudf writeJSON shape).

    Non-finite floats (NaN/Inf) become JSON null — strict JSON has no
    token for them and Arrow's reader (so our own read_json) rejects the
    Python-extension spelling."""
    import json as _json
    import math

    def _clean(v):
        if isinstance(v, float) and not math.isfinite(v):
            return None
        return v

    with trace_range("io.json.write"):
        names = (
            list(table.names)
            if table.names is not None
            else [f"c{i}" for i in range(len(table.columns))]
        )
        rows = zip(*(c.to_pylist() for c in table.columns))
        with open(path, "w") as f:
            for row in rows:
                f.write(
                    _json.dumps(
                        {n: _clean(v) for n, v in zip(names, row)},
                        allow_nan=False,
                    )
                )
                f.write("\n")


def scan_json(
    path,
    columns: Optional[Sequence[str]] = None,
    filters=None,
    block_rows: int = 1 << 16,
    dtypes: Optional[dict] = None,
    pad_widths: Optional[dict] = None,
    prefetch: int = 0,
):
    """Stream a JSON-lines file as device Table batches of ~``block_rows``
    records. Arrow's JSON reader has no incremental mode, so the scanner
    chunks the file on line boundaries and parses each chunk
    independently — types pinned via ``dtypes`` stay consistent across
    chunks (pin any column whose early records underdetermine its type).
    ``prefetch=N`` parses and uploads ahead on a background thread."""
    _require()
    from .parquet import _prefetch_iter

    if prefetch > 0:
        return _prefetch_iter(
            scan_json(path, columns, filters, block_rows, dtypes,
                      pad_widths, prefetch=0),
            prefetch,
        )
    return _scan_json_serial(
        path, columns, filters, block_rows, dtypes, pad_widths
    )


def _scan_json_serial(
    path, columns, filters, block_rows, dtypes, pad_widths
):
    from ..interop import table_from_arrow
    from .parquet import _apply_exact_filter

    predicate = preds.from_dnf(filters) if filters is not None else None
    parse_opts = None
    if dtypes:
        parse_opts = pa_json.ParseOptions(
            explicit_schema=pa.schema(list(dtypes.items())),
            unexpected_field_behavior="infer",
        )
    # with an explicit projection the read set is known before chunk 1
    # (a projected column may be entirely absent from early chunks)
    want = read_cols = None
    if columns is not None:
        want, read_cols = preds.projection_columns(
            predicate, columns, columns
        )
    _seen_schema = None
    with open(path, "rb") as f:
        eof = False
        while not eof:
            with trace_range("io.json.parse"):
                lines = []
                for _ in range(block_rows):
                    line = f.readline()
                    if not line:
                        eof = True
                        break
                    if line.strip():
                        lines.append(line)
                if not lines:
                    continue  # blank-only block is not EOF
                atbl = pa_json.read_json(
                    _io.BytesIO(b"".join(lines)), parse_options=parse_opts
                )
            if want is None:
                want, read_cols = preds.projection_columns(
                    predicate, columns, atbl.column_names
                )
            # JSON key sets drift across chunks (sparse keys are normal);
            # whole-file read_json null-fills, so the scanner must too.
            # A column absent from this chunk needs a type for its null
            # fill: dtypes-pinned ones use the pin, others the first
            # chunk's schema (kept below); a column never seen at all
            # raises with advice to pin it.
            missing = [c for c in read_cols if c not in atbl.column_names]
            if missing:
                fills = []
                for c in missing:
                    typ = None
                    if dtypes and c in dtypes:
                        typ = dtypes[c]
                    elif _seen_schema is not None and c in _seen_schema.names:
                        typ = _seen_schema.field(c).type
                    if typ is None:
                        raise ValueError(
                            f"scan_json: column {c!r} missing from a "
                            "chunk and its type is unknown — pin it via "
                            "dtypes="
                        )
                    fills.append(pa.nulls(len(atbl), type=typ))
                atbl = pa.table(
                    list(atbl.columns) + fills,
                    names=list(atbl.column_names) + missing,
                )
            if _seen_schema is None:
                _seen_schema = atbl.schema
            with trace_range("io.json.upload"):
                dev = table_from_arrow(
                    atbl.select(read_cols), pad_widths=pad_widths
                )
            if predicate is not None:
                with trace_range("io.json.filter"):
                    dev = _apply_exact_filter(dev, predicate, want)
            yield dev
