"""Column / Table: the device-resident columnar substrate.

This is the TPU-native analog of cudf's ``column``/``table`` and the Java
``ai.rapids.cudf.ColumnVector``/``Table`` that the reference binds to
(reference: RowConversion.java:19-22 imports; ownership model
RowConversionJni.cpp:31-37).

TPU-first design decisions
--------------------------
* A column is a pair of ``jax.Array`` buffers in HBM: a data buffer and an
  optional *boolean* validity mask. Arrow packs validity as 1 bit/value; on
  TPU a bool vector is the fusable representation (XLA lowers selects/masks
  on it directly), so bits are packed/unpacked only at host-interop and
  row-format boundaries (``rows.py``, ``interop.py``).
* ``Column`` and ``Table`` are registered pytrees: they flow through ``jit``,
  ``shard_map`` and collectives like any other JAX value. This replaces the
  reference's opaque ``long`` native handles (RowConversionJni.cpp:31) —
  under XLA, the compiler owns buffer lifetime via donation, so the
  handle-registry role is only needed at the foreign-language boundary
  (see ``src/`` native runtime).
* Strings use a padded byte-matrix layout: ``data`` is ``(n, pad_width)``
  uint8 and ``lengths`` is ``(n,)`` int32. Static shapes keep XLA happy; the
  pad width is a per-column compile-time constant (chosen at ingest).
* Row counts are static Python ints (shape metadata), but *logical* row
  counts after data-dependent ops (filter/join/groupby) can be device
  scalars with padded buffers — see ``ops/`` two-phase patterns mirroring the
  reference's two-phase 2GB batching (row_conversion.cu:505-511).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dt


def storage_host_view(arr: np.ndarray, dtype: dt.DType) -> np.ndarray:
    """Host-side half of the storage-encoding rule: the FLOAT64
    bit-view (device storage is the uint64 bit pattern — see
    DType.storage_dtype). Shared by encode_storage and the wire
    layer's batched-upload staging (runtime_bridge)."""
    if dtype.id == dt.TypeId.FLOAT64:
        return np.ascontiguousarray(arr, dtype=np.float64).view(np.uint64)
    return arr


def x64_downgrade_error(got, want, what: str = "types") -> TypeError:
    """The x64-downgrade guard's error, one wording per upload site:
    jax_enable_x64 off (SPARK_RAPIDS_TPU_DISABLE_X64=1) makes jnp
    silently downgrade 64-bit dtypes, which would corrupt data while
    the DType metadata still claims 64 bits."""
    suffix = {
        "types": (
            "64-bit types require jax_enable_x64 (unset "
            "SPARK_RAPIDS_TPU_DISABLE_X64)"
        ),
        "LIST children": "64-bit LIST children require jax_enable_x64",
        "children": "64-bit children require jax_enable_x64",
    }[what]
    return TypeError(f"device buffer dtype {got} != {want}; {suffix}")


def encode_storage(arr: np.ndarray, dtype: dt.DType) -> jax.Array:
    """Upload a host array as a column storage buffer.

    Single place for the FLOAT64 bit-view rule (storage_host_view) and
    the x64-downgrade guard (x64_downgrade_error), shared by
    Column.from_numpy, interop, and the wire layer.
    """
    arr = storage_host_view(arr, dtype)
    dev = jnp.asarray(arr, dtype=dtype.storage_dtype)
    if dev.dtype != np.dtype(dtype.storage_dtype):
        raise x64_downgrade_error(dev.dtype, dtype.storage_dtype)
    return dev


# LIST child types whose storage dtype maps back to the declared type
# unambiguously (see Column.list_child_dtype)
_LIST_CHILD_IDS = frozenset({
    dt.TypeId.INT8, dt.TypeId.INT16, dt.TypeId.INT32, dt.TypeId.INT64,
    dt.TypeId.UINT8, dt.TypeId.UINT16, dt.TypeId.UINT32, dt.TypeId.UINT64,
    dt.TypeId.FLOAT32, dt.TypeId.BOOL8,
})


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(eq=False)
class Column:
    """One column: HBM data buffer + optional validity mask (+ string lengths).

    Invariants:
      * fixed-width: ``data.shape == (n,)`` with ``data.dtype ==
        dtype.storage_dtype`` (FLOAT64 stores IEEE-754 bits as uint64 —
        see DType.storage_dtype for why).
      * string: ``data.shape == (n, pad)`` uint8, ``lengths.shape == (n,)``
        int32, bytes past ``lengths[i]`` are zero.
      * ``validity`` is None (no nulls) or ``(n,)`` bool, True = valid —
        matching Arrow/cudf polarity.
    """

    data: jax.Array
    dtype: dt.DType
    validity: Optional[jax.Array] = None
    lengths: Optional[jax.Array] = None

    # --- pytree protocol -------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.validity, self.lengths), self.dtype

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, validity, lengths = children
        return cls(data=data, dtype=aux, validity=validity, lengths=lengths)

    # --- basic properties -------------------------------------------------
    @property
    def row_count(self) -> int:
        return int(self.data.shape[0])

    def __len__(self) -> int:
        return self.row_count

    @property
    def has_validity(self) -> bool:
        return self.validity is not None

    def null_count(self) -> int:
        """Number of nulls (host sync)."""
        if self.validity is None:
            return 0
        return int(self.row_count - jnp.count_nonzero(self.validity))

    @property
    def pad_width(self) -> int:
        if not self.dtype.is_string:
            raise TypeError("pad_width only applies to STRING columns")
        return int(self.data.shape[1])

    # --- construction -----------------------------------------------------
    @staticmethod
    def from_numpy(
        arr: np.ndarray,
        validity: Optional[np.ndarray] = None,
        dtype: Optional[dt.DType] = None,
    ) -> "Column":
        """Build a fixed-width column from host data (uploads to device).

        ``dtype`` overrides inference — required for decimals (pass e.g.
        ``dt.decimal32(-3)`` with an int32 array of unscaled values, the
        representation the reference round-trips in RowConversionTest.java:37-38).
        """
        arr = np.asarray(arr)
        if dtype is not None and dtype.id == dt.TypeId.DECIMAL128:
            if arr.ndim != 2 or arr.shape[1] != 2:
                raise ValueError(
                    "DECIMAL128 expects (n, 2) uint64 limbs [lo, hi]"
                )
        elif arr.ndim != 1:
            raise ValueError("expected 1-D host array")
        if dtype is None:
            dtype = dt.from_numpy_dtype(arr.dtype)
        if arr.dtype.kind in "Mm":
            arr = arr.view(np.dtype(f"i{arr.dtype.itemsize}"))
        dev = encode_storage(arr, dtype)
        valid = None
        if validity is not None:
            valid = jnp.asarray(np.asarray(validity, dtype=np.bool_))
            if valid.shape != dev.shape[:1]:
                raise ValueError("validity shape mismatch")
        return Column(data=dev, dtype=dtype, validity=valid)

    @staticmethod
    def from_list_of_lists(
        values: Sequence, child_dtype: Optional[dt.DType] = None,
        pad_width: Optional[int] = None,
    ) -> "Column":
        """Build a LIST column (fixed-width child) from Python lists.

        Device layout mirrors STRING (SURVEY.md §7 hard part 2 — padding
        instead of offsets under XLA static shapes): ``data`` is an
        (n, pad) matrix of child storage values, ``lengths`` the per-row
        element counts; the child dtype is carried by the data buffer's
        dtype. This is the LIST<INT8> shape of the reference's packed-row
        output (row_conversion.cu:389-406).
        """
        child = child_dtype or dt.UINT8
        if child.id not in _LIST_CHILD_IDS:
            # the child type is reconstructed from the buffer dtype
            # (list_child_dtype), so only types whose storage dtype maps
            # back unambiguously are supported — FLOAT64 (bit-view
            # storage), temporals and decimals would silently change
            # type on a round trip
            raise TypeError(
                f"LIST child {child} not supported (MVP children: "
                "int8..64, uint8..64, float32, bool)"
            )
        n = len(values)
        max_len = max(
            (len(v) for v in values if v is not None), default=0
        )
        if pad_width is not None and max_len > pad_width:
            raise ValueError(
                f"list length {max_len} exceeds pad width {pad_width}"
            )
        pad = pad_width if pad_width is not None else max(max_len, 1)
        npdt = np.dtype(child.storage_dtype)
        mat = np.zeros((n, pad), dtype=npdt)
        lens = np.zeros((n,), dtype=np.int32)
        valid = np.ones((n,), dtype=np.bool_)
        for i, v in enumerate(values):
            if v is None:
                valid[i] = False
                continue
            items = list(v)
            if any(x is None for x in items):
                # Arrow allows element-level nulls; the padded-matrix
                # layout has no child validity yet — refuse rather than
                # coerce (NaN for floats, TypeError deep in numpy for
                # ints)
                raise TypeError(
                    "null elements inside lists are not supported "
                    f"(row {i})"
                )
            arr = np.asarray(items, dtype=npdt)
            mat[i, : len(arr)] = arr
            lens[i] = len(arr)
        dev = jnp.asarray(mat)
        if dev.dtype != npdt:
            raise x64_downgrade_error(dev.dtype, npdt, "children")
        return Column(
            data=dev,
            dtype=dt.DType(dt.TypeId.LIST),
            validity=None if valid.all() else jnp.asarray(valid),
            lengths=jnp.asarray(lens),
        )

    @property
    def list_child_dtype(self) -> dt.DType:
        """Child element dtype of a LIST column, reconstructed from the
        data buffer's dtype — faithful exactly for the child set
        from_list_of_lists accepts (which is why it restricts one)."""
        if self.dtype.id != dt.TypeId.LIST:
            raise TypeError("not a LIST column")
        return dt.from_numpy_dtype(np.dtype(self.data.dtype))

    @staticmethod
    def from_decimal128(
        values: Sequence[Optional[int]], scale: int = 0
    ) -> "Column":
        """Build a DECIMAL128 column from Python ints (unscaled values;
        None = null). Device layout: (n, 2) uint64 limbs [lo, hi]."""
        from .ops.int128 import from_py_ints

        limbs = from_py_ints(values)
        valid = np.array([v is not None for v in values], dtype=np.bool_)
        return Column.from_numpy(
            limbs,
            validity=None if valid.all() else valid,
            dtype=dt.DType(dt.TypeId.DECIMAL128, scale),
        )

    @staticmethod
    def from_strings(
        values: Sequence[Optional[Union[str, bytes]]],
        pad_width: Optional[int] = None,
    ) -> "Column":
        """Build a STRING column (padded byte-matrix device layout)."""
        # surrogateescape keeps arbitrary binary payloads lossless through the
        # str representation (Arrow binary arrays also land here).
        raw = [
            v.encode("utf-8", "surrogateescape") if isinstance(v, str) else v
            for v in values
        ]
        n = len(raw)
        max_len = max((len(v) for v in raw if v is not None), default=0)
        pad = pad_width if pad_width is not None else max(max_len, 1)
        if max_len > pad:
            raise ValueError(f"string of length {max_len} exceeds pad width {pad}")
        mat = np.zeros((n, pad), dtype=np.uint8)
        lens = np.zeros((n,), dtype=np.int32)
        valid = np.ones((n,), dtype=np.bool_)
        for i, v in enumerate(raw):
            if v is None:
                valid[i] = False
                continue
            mat[i, : len(v)] = np.frombuffer(v, dtype=np.uint8)
            lens[i] = len(v)
        return Column(
            data=jnp.asarray(mat),
            dtype=dt.STRING,
            validity=None if valid.all() else jnp.asarray(valid),
            lengths=jnp.asarray(lens),
        )

    # --- host readback ------------------------------------------------------
    def to_numpy(self) -> np.ndarray:
        """Raw data buffer on host (nulls have unspecified payload)."""
        arr = np.asarray(self.data)
        if self.dtype.id == dt.TypeId.FLOAT64:
            return arr.view(np.float64)
        if self.dtype.is_timestamp or self.dtype.is_duration:
            unit = {
                dt.TypeId.TIMESTAMP_DAYS: "D",
                dt.TypeId.TIMESTAMP_SECONDS: "s",
                dt.TypeId.TIMESTAMP_MILLISECONDS: "ms",
                dt.TypeId.TIMESTAMP_MICROSECONDS: "us",
                dt.TypeId.TIMESTAMP_NANOSECONDS: "ns",
                dt.TypeId.DURATION_DAYS: "D",
                dt.TypeId.DURATION_SECONDS: "s",
                dt.TypeId.DURATION_MILLISECONDS: "ms",
                dt.TypeId.DURATION_MICROSECONDS: "us",
                dt.TypeId.DURATION_NANOSECONDS: "ns",
            }[self.dtype.id]
            kind = "M" if self.dtype.is_timestamp else "m"
            # numpy datetime64/timedelta64 are 8-byte regardless of unit;
            # widen our int32 day counts before the view.
            return arr.astype(np.int64).view(np.dtype(f"{kind}8[{unit}]"))
        return arr

    def validity_to_numpy(self) -> np.ndarray:
        if self.validity is None:
            return np.ones((self.row_count,), dtype=np.bool_)
        return np.asarray(self.validity)

    def to_pylist(self) -> list:
        """Python values with None for nulls (testing convenience)."""
        valid = self.validity_to_numpy()
        if self.dtype.is_string:
            mat = np.asarray(self.data)
            lens = np.asarray(self.lengths)
            return [
                bytes(mat[i, : lens[i]]).decode("utf-8", "surrogateescape")
                if valid[i]
                else None
                for i in range(self.row_count)
            ]
        if self.dtype.id == dt.TypeId.DECIMAL128:
            from .ops.int128 import to_py_ints

            ints = to_py_ints(np.asarray(self.data))
            return [
                ints[i] if valid[i] else None
                for i in range(self.row_count)
            ]
        if self.dtype.id == dt.TypeId.LIST:
            mat = np.asarray(self.data)
            lens = np.asarray(self.lengths)
            return [
                mat[i, : lens[i]].tolist() if valid[i] else None
                for i in range(self.row_count)
            ]
        arr = self.to_numpy()
        out = []
        for i in range(self.row_count):
            if not valid[i]:
                out.append(None)
            elif self.dtype.is_decimal:
                out.append(int(arr[i]))
            else:
                out.append(arr[i].item())
        return out

    # --- misc ----------------------------------------------------------------
    def with_validity(self, validity: Optional[jax.Array]) -> "Column":
        return dataclasses.replace(self, validity=validity)

    def merged_validity(self, *others: "Column") -> Optional[jax.Array]:
        """AND of this column's validity with others' (null-propagation)."""
        masks = [c.validity for c in (self, *others) if c.validity is not None]
        if not masks:
            return None
        out = masks[0]
        for m in masks[1:]:
            out = jnp.logical_and(out, m)
        return out


@jax.tree_util.register_pytree_node_class
class Table:
    """An ordered collection of equal-length columns, optionally named.

    The analog of ``cudf::table`` / ``ai.rapids.cudf.Table``
    (reference: RowConversion.java:104 takes a Table; the JNI side views it
    as a ``cudf::table_view`` at RowConversionJni.cpp:31).

    ``logical_rows`` supports the shape-bucket plane (utils/buckets.py):
    a table padded to a row-count bucket keeps its buffers at the bucket
    size (``row_count``) while carrying the number of REAL rows here.
    None means exact (every row is real). Rows past ``logical_rows`` are
    garbage; only the bucketed dispatch layer may consume padded tables
    (it masks them with ``row_valid`` occupancy), everything else goes
    through ``buckets.unpad_table`` first.
    """

    def __init__(
        self,
        columns: Sequence[Column],
        names: Optional[Sequence[str]] = None,
        logical_rows: Optional[int] = None,
    ):
        columns = tuple(columns)
        if columns:
            n = columns[0].row_count
            for c in columns[1:]:
                if c.row_count != n:
                    raise ValueError("column length mismatch")
        if names is not None:
            names = tuple(names)
            if len(names) != len(columns):
                raise ValueError("names/columns length mismatch")
        if logical_rows is not None:
            logical_rows = int(logical_rows)
            physical = columns[0].row_count if columns else 0
            if not 0 <= logical_rows <= physical:
                raise ValueError(
                    f"logical_rows {logical_rows} out of range for "
                    f"{physical} physical rows"
                )
        self.columns = columns
        self.names = names
        self.logical_rows = logical_rows

    # --- pytree protocol -------------------------------------------------
    def tree_flatten(self):
        return self.columns, (self.names, self.logical_rows)

    @classmethod
    def tree_unflatten(cls, aux, children):
        obj = cls.__new__(cls)
        obj.columns = tuple(children)
        obj.names, obj.logical_rows = aux
        return obj

    # --- accessors ---------------------------------------------------------
    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def row_count(self) -> int:
        return self.columns[0].row_count if self.columns else 0

    @property
    def logical_row_count(self) -> int:
        """Real rows: ``logical_rows`` when padded, else ``row_count``."""
        if self.logical_rows is not None:
            return self.logical_rows
        return self.row_count

    @property
    def is_padded(self) -> bool:
        return self.logical_rows is not None

    def column(self, key: Union[int, str]) -> Column:
        if isinstance(key, str):
            if self.names is None:
                raise KeyError("table has no column names")
            key = self.names.index(key)
        return self.columns[key]

    def __getitem__(self, key) -> Column:
        return self.column(key)

    def dtypes(self) -> tuple[dt.DType, ...]:
        return tuple(c.dtype for c in self.columns)

    def schema_wire(self) -> tuple[list[int], list[int]]:
        """(type ids, scales) — the JNI wire arrays of the reference
        (RowConversion.java:116-122)."""
        ids, scales = [], []
        for c in self.columns:
            i, s = c.dtype.to_wire()
            ids.append(i)
            scales.append(s)
        return ids, scales

    def select(self, keys: Sequence[Union[int, str]]) -> "Table":
        cols = [self.column(k) for k in keys]
        names = None
        if self.names is not None:
            names = [
                k if isinstance(k, str) else self.names[k] for k in keys
            ]
        return Table(cols, names)

    @staticmethod
    def from_pydict(
        data: dict,
        dtypes: Optional[dict] = None,
        pad_widths: Optional[dict] = None,
    ) -> "Table":
        """Host-side convenience constructor (numpy arrays or string
        lists). ``pad_widths`` maps string column name -> pad width, like
        the io readers."""
        cols, names = [], []
        for name, values in data.items():
            want = (dtypes or {}).get(name)
            pad = (pad_widths or {}).get(name)
            if want is not None and want.is_string:
                cols.append(Column.from_strings(values, pad_width=pad))
            elif (
                isinstance(values, (list, tuple))
                and values
                and isinstance(values[0], (str, bytes, type(None)))
                and any(isinstance(v, (str, bytes)) for v in values)
            ):
                cols.append(Column.from_strings(values, pad_width=pad))
            else:
                arr = np.asarray(values)
                if arr.dtype == object:
                    mask = np.array([v is not None for v in values])
                    present = [v for v in values if v is not None]
                    if present and all(isinstance(v, bool) for v in present):
                        filled = np.array(
                            [bool(v) for v in values], dtype=np.bool_
                        )
                    else:
                        filled = np.array(
                            [v if v is not None else 0 for v in values]
                        )
                    cols.append(Column.from_numpy(filled, mask, want))
                else:
                    cols.append(Column.from_numpy(arr, dtype=want))
            names.append(name)
        return Table(cols, names)

    def to_pydict(self) -> dict:
        if self.names is None:
            names = [f"c{i}" for i in range(self.num_columns)]
        else:
            names = list(self.names)
        return {n: c.to_pylist() for n, c in zip(names, self.columns)}

    def __repr__(self) -> str:
        parts = []
        for i, c in enumerate(self.columns):
            name = self.names[i] if self.names else f"c{i}"
            parts.append(f"{name}: {c.dtype!r}[{c.row_count}]")
        pad = (
            f", logical_rows={self.logical_rows}"
            if self.logical_rows is not None
            else ""
        )
        return f"Table({', '.join(parts)}{pad})"
