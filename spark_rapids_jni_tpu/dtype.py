"""Columnar type system for the TPU-native Spark RAPIDS backend.

Plays the role of cudf's ``data_type`` / the Java ``ai.rapids.cudf.DType``:
a *type id* plus a decimal *scale*, which is exactly the wire format the
reference marshals across the JNI boundary as two parallel int arrays
(reference: spark-rapids-jni/src/main/cpp/src/RowConversionJni.cpp:56-61 and
spark-rapids-jni/src/main/java/com/nvidia/spark/rapids/jni/RowConversion.java:113-124).

TPU-first design notes
----------------------
* Fixed-width types map 1:1 onto jnp dtypes; decimals are *unscaled integers*
  (int32/int64) carried with a scale, the same representation cudf uses.
* BOOL8 is one byte in the packed row format (reference row format spec,
  RowConversion.java:43-102) but lives as ``jnp.bool_`` on device so XLA can
  fuse mask arithmetic; width bookkeeping here is about the *row wire format*.
* TIMESTAMP_*/DURATION_* are int32/int64 ticks — no special device type.
* STRING has no fixed width; string columns use a padded byte-matrix device
  layout (see ``column.py``) and are rejected by the row transpose, matching
  the reference's fixed-width-only gate (row_conversion.cu:514-516).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import jax.numpy as jnp
import numpy as np


class TypeId(enum.IntEnum):
    """Stable numeric type ids.

    Values mirror the native ids of the cudf 22.04 type enum that the
    reference pins (pom.xml:88) and ships across JNI as
    ``DType.getTypeId().getNativeId()`` (RowConversion.java:119).
    """

    EMPTY = 0
    INT8 = 1
    INT16 = 2
    INT32 = 3
    INT64 = 4
    UINT8 = 5
    UINT16 = 6
    UINT32 = 7
    UINT64 = 8
    FLOAT32 = 9
    FLOAT64 = 10
    BOOL8 = 11
    TIMESTAMP_DAYS = 12
    TIMESTAMP_SECONDS = 13
    TIMESTAMP_MILLISECONDS = 14
    TIMESTAMP_MICROSECONDS = 15
    TIMESTAMP_NANOSECONDS = 16
    DURATION_DAYS = 17
    DURATION_SECONDS = 18
    DURATION_MILLISECONDS = 19
    DURATION_MICROSECONDS = 20
    DURATION_NANOSECONDS = 21
    DICTIONARY32 = 22
    STRING = 23
    LIST = 24
    DECIMAL32 = 25
    DECIMAL64 = 26
    DECIMAL128 = 27
    STRUCT = 28


# Row-format width in bytes for fixed-width types (the packed-row layout uses
# these widths; reference layout computation row_conversion.cu:432-456).
_WIDTHS = {
    TypeId.INT8: 1,
    TypeId.INT16: 2,
    TypeId.INT32: 4,
    TypeId.INT64: 8,
    TypeId.UINT8: 1,
    TypeId.UINT16: 2,
    TypeId.UINT32: 4,
    TypeId.UINT64: 8,
    TypeId.FLOAT32: 4,
    TypeId.FLOAT64: 8,
    TypeId.BOOL8: 1,
    TypeId.TIMESTAMP_DAYS: 4,
    TypeId.TIMESTAMP_SECONDS: 8,
    TypeId.TIMESTAMP_MILLISECONDS: 8,
    TypeId.TIMESTAMP_MICROSECONDS: 8,
    TypeId.TIMESTAMP_NANOSECONDS: 8,
    TypeId.DURATION_DAYS: 4,
    TypeId.DURATION_SECONDS: 8,
    TypeId.DURATION_MILLISECONDS: 8,
    TypeId.DURATION_MICROSECONDS: 8,
    TypeId.DURATION_NANOSECONDS: 8,
    TypeId.DICTIONARY32: 4,
    TypeId.DECIMAL32: 4,
    TypeId.DECIMAL64: 8,
    TypeId.DECIMAL128: 16,
}

# Device (jnp) storage dtype for each fixed-width type id. Bool is stored as
# jnp.bool_ on device; row packing widens it to one byte.
_DEVICE_DTYPES = {
    TypeId.INT8: jnp.int8,
    TypeId.INT16: jnp.int16,
    TypeId.INT32: jnp.int32,
    TypeId.INT64: jnp.int64,
    TypeId.UINT8: jnp.uint8,
    TypeId.UINT16: jnp.uint16,
    TypeId.UINT32: jnp.uint32,
    TypeId.UINT64: jnp.uint64,
    TypeId.FLOAT32: jnp.float32,
    TypeId.FLOAT64: jnp.float64,
    TypeId.BOOL8: jnp.bool_,
    TypeId.TIMESTAMP_DAYS: jnp.int32,
    TypeId.TIMESTAMP_SECONDS: jnp.int64,
    TypeId.TIMESTAMP_MILLISECONDS: jnp.int64,
    TypeId.TIMESTAMP_MICROSECONDS: jnp.int64,
    TypeId.TIMESTAMP_NANOSECONDS: jnp.int64,
    TypeId.DURATION_DAYS: jnp.int32,
    TypeId.DURATION_SECONDS: jnp.int64,
    TypeId.DURATION_MILLISECONDS: jnp.int64,
    TypeId.DURATION_MICROSECONDS: jnp.int64,
    TypeId.DURATION_NANOSECONDS: jnp.int64,
    TypeId.DICTIONARY32: jnp.int32,
    TypeId.DECIMAL32: jnp.int32,
    TypeId.DECIMAL64: jnp.int64,
}

_SIGNED_INT_IDS = frozenset(
    {TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64}
)
_UNSIGNED_INT_IDS = frozenset(
    {TypeId.UINT8, TypeId.UINT16, TypeId.UINT32, TypeId.UINT64}
)
_FLOAT_IDS = frozenset({TypeId.FLOAT32, TypeId.FLOAT64})
_DECIMAL_IDS = frozenset({TypeId.DECIMAL32, TypeId.DECIMAL64, TypeId.DECIMAL128})
_TIMESTAMP_IDS = frozenset(
    {
        TypeId.TIMESTAMP_DAYS,
        TypeId.TIMESTAMP_SECONDS,
        TypeId.TIMESTAMP_MILLISECONDS,
        TypeId.TIMESTAMP_MICROSECONDS,
        TypeId.TIMESTAMP_NANOSECONDS,
    }
)
_DURATION_IDS = frozenset(
    {
        TypeId.DURATION_DAYS,
        TypeId.DURATION_SECONDS,
        TypeId.DURATION_MILLISECONDS,
        TypeId.DURATION_MICROSECONDS,
        TypeId.DURATION_NANOSECONDS,
    }
)


@dataclasses.dataclass(frozen=True)
class DType:
    """A columnar data type: (type id, decimal scale).

    ``scale`` is only meaningful for DECIMAL32/64/128 and uses cudf's
    convention: the stored integer x represents ``x * 10**scale`` (so the
    reference test's decimal32 with scale -3 stores milli-units;
    RowConversionTest.java:37-38).
    """

    id: TypeId
    scale: int = 0

    def __post_init__(self):
        if self.scale != 0 and self.id not in _DECIMAL_IDS:
            raise ValueError(f"non-zero scale on non-decimal type {self.id!r}")

    # --- classification -------------------------------------------------
    @property
    def is_fixed_width(self) -> bool:
        return self.id in _WIDTHS

    @property
    def is_decimal(self) -> bool:
        return self.id in _DECIMAL_IDS

    @property
    def is_integer(self) -> bool:
        return self.id in _SIGNED_INT_IDS or self.id in _UNSIGNED_INT_IDS

    @property
    def is_floating(self) -> bool:
        return self.id in _FLOAT_IDS

    @property
    def is_numeric(self) -> bool:
        return self.is_integer or self.is_floating or self.is_decimal

    @property
    def is_boolean(self) -> bool:
        return self.id == TypeId.BOOL8

    @property
    def is_timestamp(self) -> bool:
        return self.id in _TIMESTAMP_IDS

    @property
    def is_duration(self) -> bool:
        return self.id in _DURATION_IDS

    @property
    def is_string(self) -> bool:
        return self.id == TypeId.STRING

    # --- widths and device mapping -------------------------------------
    @property
    def itemsize(self) -> int:
        """Width in bytes in the packed row format (cudf ``size_of``)."""
        try:
            return _WIDTHS[self.id]
        except KeyError:
            raise TypeError(f"{self.id!r} is not fixed-width") from None

    @property
    def device_dtype(self):
        """The *logical* jnp dtype of this column's values."""
        if self.id == TypeId.DECIMAL128:
            raise TypeError(
                "DECIMAL128 has no scalar device dtype: columns are "
                "(n, 2) uint64 little-endian limb buffers (ops/int128.py)"
            )
        try:
            return _DEVICE_DTYPES[self.id]
        except KeyError:
            raise TypeError(f"{self.id!r} has no device dtype") from None

    @property
    def storage_dtype(self):
        """The jnp dtype of the HBM buffer backing this column.

        Equal to ``device_dtype`` except FLOAT64: TPU's f64 is a
        double-float emulation with an f32 exponent range and ~48-bit
        mantissa — ordinary doubles (1.1, 0.1, 1e300) do not even survive
        an HBM upload round trip. A SQL engine cannot corrupt every DOUBLE
        at ingest, so FLOAT64 columns store the IEEE-754 bit pattern as
        uint64 (exact on every backend); compute ops decode to the device
        float envelope on demand (ops/compute.py) and sorts/comparisons use
        the order-preserving bit trick instead of decoding.
        """
        if self.id == TypeId.FLOAT64:
            return jnp.uint64
        if self.id == TypeId.DECIMAL128:
            # (n, 2) little-endian u64 limbs [lo, hi]; TPU has no native
            # int128, so 128-bit values are limb vectors (ops/int128.py)
            return jnp.uint64
        return self.device_dtype

    # --- wire format ----------------------------------------------------
    def to_wire(self) -> tuple[int, int]:
        """(native type id, scale) — the JNI int-array pair of the reference."""
        return int(self.id), int(self.scale)

    @staticmethod
    def from_wire(type_id: int, scale: int = 0) -> "DType":
        return DType(TypeId(type_id), scale)

    def __repr__(self) -> str:
        if self.is_decimal:
            return f"DType({self.id.name}, scale={self.scale})"
        return f"DType({self.id.name})"


# Convenience singletons (the ai.rapids.cudf.DType static instances analog).
INT8 = DType(TypeId.INT8)
INT16 = DType(TypeId.INT16)
INT32 = DType(TypeId.INT32)
INT64 = DType(TypeId.INT64)
UINT8 = DType(TypeId.UINT8)
UINT16 = DType(TypeId.UINT16)
UINT32 = DType(TypeId.UINT32)
UINT64 = DType(TypeId.UINT64)
FLOAT32 = DType(TypeId.FLOAT32)
FLOAT64 = DType(TypeId.FLOAT64)
BOOL8 = DType(TypeId.BOOL8)
STRING = DType(TypeId.STRING)
TIMESTAMP_DAYS = DType(TypeId.TIMESTAMP_DAYS)
TIMESTAMP_SECONDS = DType(TypeId.TIMESTAMP_SECONDS)
TIMESTAMP_MILLISECONDS = DType(TypeId.TIMESTAMP_MILLISECONDS)
TIMESTAMP_MICROSECONDS = DType(TypeId.TIMESTAMP_MICROSECONDS)
TIMESTAMP_NANOSECONDS = DType(TypeId.TIMESTAMP_NANOSECONDS)
DURATION_DAYS = DType(TypeId.DURATION_DAYS)
DURATION_SECONDS = DType(TypeId.DURATION_SECONDS)
DURATION_MILLISECONDS = DType(TypeId.DURATION_MILLISECONDS)
DURATION_MICROSECONDS = DType(TypeId.DURATION_MICROSECONDS)
DURATION_NANOSECONDS = DType(TypeId.DURATION_NANOSECONDS)


def decimal32(scale: int) -> DType:
    return DType(TypeId.DECIMAL32, scale)


def decimal64(scale: int) -> DType:
    return DType(TypeId.DECIMAL64, scale)


def decimal128(scale: int) -> DType:
    return DType(TypeId.DECIMAL128, scale)


_NP_TO_TYPEID = {
    np.dtype(np.int8): TypeId.INT8,
    np.dtype(np.int16): TypeId.INT16,
    np.dtype(np.int32): TypeId.INT32,
    np.dtype(np.int64): TypeId.INT64,
    np.dtype(np.uint8): TypeId.UINT8,
    np.dtype(np.uint16): TypeId.UINT16,
    np.dtype(np.uint32): TypeId.UINT32,
    np.dtype(np.uint64): TypeId.UINT64,
    np.dtype(np.float32): TypeId.FLOAT32,
    np.dtype(np.float64): TypeId.FLOAT64,
    np.dtype(np.bool_): TypeId.BOOL8,
}


def from_numpy_dtype(np_dtype, scale: int = 0) -> DType:
    """Infer a DType from a numpy/jnp dtype (non-decimal, non-temporal)."""
    np_dtype = np.dtype(np_dtype)
    if np_dtype.kind == "M":  # datetime64
        unit = np.datetime_data(np_dtype)[0]
        return {
            "D": TIMESTAMP_DAYS,
            "s": TIMESTAMP_SECONDS,
            "ms": TIMESTAMP_MILLISECONDS,
            "us": TIMESTAMP_MICROSECONDS,
            "ns": TIMESTAMP_NANOSECONDS,
        }[unit]
    if np_dtype.kind == "m":  # timedelta64
        unit = np.datetime_data(np_dtype)[0]
        return {
            "D": DURATION_DAYS,
            "s": DURATION_SECONDS,
            "ms": DURATION_MILLISECONDS,
            "us": DURATION_MICROSECONDS,
            "ns": DURATION_NANOSECONDS,
        }[unit]
    try:
        return DType(_NP_TO_TYPEID[np_dtype], scale)
    except KeyError:
        raise TypeError(f"unsupported numpy dtype {np_dtype}") from None


def common_numeric_dtype(a: DType, b: DType) -> DType:
    """Binary-op type promotion following numpy/cudf rules for plain numerics."""
    if a.is_decimal or b.is_decimal:
        # Decimal promotion: widest storage, max precision semantics are the
        # caller's job; binary ops rescale explicitly (ops/binaryop.py).
        wid = max(a.itemsize, b.itemsize)
        scale = min(a.scale if a.is_decimal else 0, b.scale if b.is_decimal else 0)
        return DType(TypeId.DECIMAL64 if wid >= 8 else TypeId.DECIMAL32, scale)
    out = np.promote_types(
        np.dtype(a.device_dtype), np.dtype(b.device_dtype)
    )
    return from_numpy_dtype(out)
