"""Plan-time static analyzer — the ``GpuOverrides`` tagging pass analog.

The reference plugin decides *at plan time* which operators can run on the
accelerator and why (GpuOverrides.scala: every Expr/Exec gets a tag with a
human-readable willNotWorkOnGpu reason before any kernel launches). This
module is that pass for the TPU dispatch plane: it walks a plan's JSON op
list against an input schema signature — before any upload, compile, or
scheduler admission — and produces a tagged report:

* per-op inferred output schema/dtypes (a rule table covering every op key
  the ``runtime_bridge`` dispatch plane accepts; SRT008 enforces that the
  two registries can never drift),
* a support tier per op — ``fusable`` (can ride inside a traced fused
  segment, plan.op_fusable), ``per-op`` (bucketed per-op runner coverage,
  bucketed.is_bucketable), ``exact-only`` (eager exact dispatch only), or
  ``unsupported`` (statically known to raise) — each with a reason,
* predicted fusion segmentation that must agree exactly with
  ``plan.segment_plan`` (cross-checked by test so the two cannot drift),
* a static cost/footprint bound (rows-in bound x row widths -> per-segment
  HBM bytes) that serving admission and the spill preflight can consult.

The analyzer is deliberately *permissive*: it rejects only what is
statically certain to raise in the dispatch plane (unknown op, malformed
spec, out-of-range column, dtype combo the kernels refuse). Anything
data-dependent — a regex that never matches, a sample larger than the
filtered row count — passes and keeps its runtime error surface. When the
input schema is unknown (resident tables still materializing), the walk
degrades to structural validation and schema inference reports ``None``.

Error strings mirror the dispatch plane's own messages wherever a runtime
equivalent exists (e.g. ``unknown table op {name!r}``) so callers matching
on substrings see the same text whether a plan dies statically or at
dispatch.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from . import dtype as dt

__all__ = [
    "ColType",
    "PlanCheckError",
    "schema_from_wire",
    "schema_of_table",
    "predict_segments",
    "analyze",
    "check_plan",
    "render_report",
]


# ---------------------------------------------------------------------------
# schema signatures
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ColType:
    """Static column signature: type id + decimal scale + LIST child id.

    The wire-protocol analog of a cudf ``data_type``: for LIST columns the
    wire scale slot carries the child's type id (runtime_bridge
    ``_host_column_from_wire``), which this class splits back out so rules
    can reason about element types.
    """

    id: dt.TypeId
    scale: int = 0
    child: Optional[dt.TypeId] = None

    @property
    def is_fixed_width(self) -> bool:
        return self.id in dt._WIDTHS

    @property
    def is_string(self) -> bool:
        return self.id == dt.TypeId.STRING

    @property
    def is_list(self) -> bool:
        return self.id == dt.TypeId.LIST

    @property
    def is_decimal(self) -> bool:
        return self.id in dt._DECIMAL_IDS

    @property
    def is_integer(self) -> bool:
        return self.id in dt._SIGNED_INT_IDS or self.id in dt._UNSIGNED_INT_IDS

    @property
    def is_floating(self) -> bool:
        return self.id in dt._FLOAT_IDS

    @property
    def is_boolean(self) -> bool:
        return self.id == dt.TypeId.BOOL8

    def pretty(self) -> str:
        if self.is_list:
            child = self.child.name if self.child is not None else "?"
            return f"LIST<{child}>"
        if self.is_decimal and self.scale:
            return f"{self.id.name}(scale={self.scale})"
        return self.id.name

    def to_json(self) -> dict:
        return {
            "type_id": int(self.id),
            "scale": int(self.scale),
            "child": int(self.child) if self.child is not None else None,
            "pretty": self.pretty(),
        }


def schema_from_wire(
    type_ids: Sequence[int], scales: Sequence[int]
) -> List[ColType]:
    """Schema signature from the JNI-style parallel (type_ids, scales)
    arrays. LIST reuses the scale slot for the child type id, exactly as
    the wire decoder does."""
    out: List[ColType] = []
    for tid, scale in zip(type_ids, scales):
        tid = dt.TypeId(int(tid))
        if tid == dt.TypeId.LIST:
            out.append(ColType(tid, 0, dt.TypeId(int(scale))))
        else:
            out.append(ColType(tid, int(scale)))
    return out


def schema_of_table(table) -> List[ColType]:
    """Schema signature of a live Table (for the resident-plan entry)."""
    out: List[ColType] = []
    for col in table.columns:
        d = col.dtype
        if d.id == dt.TypeId.LIST:
            out.append(ColType(d.id, 0, col.list_child_dtype.id))
        else:
            out.append(ColType(d.id, int(d.scale)))
    return out


class PlanCheckError(ValueError):
    """A plan that statically cannot run. Subclasses ValueError so
    pre-existing callers matching the dispatch plane's error class (and
    the serving ``bad_request`` mapping) keep working; carries the op
    index, op name, reason, and the full tagged report."""

    def __init__(self, index: int, op_name, reason: str, plan_report=None):
        self.index = index
        self.op_name = op_name
        self.reason = reason
        self.plan_report = plan_report
        super().__init__(f"plancheck: op[{index}] {op_name!r}: {reason}")


class _Reject(Exception):
    """Internal: a rule refused the op; .reason is the message."""

    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(reason)


# ---------------------------------------------------------------------------
# shared helpers for the rule table
# ---------------------------------------------------------------------------

# nominal per-row byte widths for the variable-width layouts, used only by
# the footprint *estimate* (never by support decisions): strings are a
# padded byte matrix plus a length vector, lists a child run plus lengths.
_STRING_EST_BYTES = 20
_LIST_EST_ELEMS = 8


def _col_width(col: ColType) -> int:
    w = dt._WIDTHS.get(col.id)
    if w is not None:
        return w
    if col.is_string:
        return _STRING_EST_BYTES
    if col.is_list:
        cw = dt._WIDTHS.get(col.child, 8) if col.child is not None else 8
        return cw * _LIST_EST_ELEMS + 4
    return 8


def _row_width(schema: Optional[List[ColType]]) -> Optional[int]:
    if schema is None:
        return None
    return sum(_col_width(c) for c in schema)


def _col_index(op: dict, key: str, schema, *, what: str) -> Optional[int]:
    """Resolve an op's column reference. Integer indices are range-checked
    against the schema when known; string names would need a named table —
    wire tables are unnamed, so names only resolve when the caller passed
    them. Returns None when the reference cannot be checked statically."""
    if key not in op:
        raise _Reject(f"missing required field {key!r}")
    ref = op[key]
    if isinstance(ref, bool) or not isinstance(ref, int):
        raise _Reject(
            f"{what} must be an integer column index, got {ref!r}"
        )
    if schema is not None and not (0 <= ref < len(schema)):
        raise _Reject(
            f"{what} index {ref} out of range for "
            f"{len(schema)}-column input"
        )
    return ref


def _key_ref(ref, schema, names, *, what: str) -> Optional[int]:
    """Resolve a sort/groupby/join/distinct key that the runtime routes
    through ``_resolve_col`` (int index or string name)."""
    if isinstance(ref, bool):
        raise _Reject(f"{what} must be a column index or name, got {ref!r}")
    if isinstance(ref, int):
        if schema is not None and not (0 <= ref < len(schema)):
            raise _Reject(
                f"{what} index {ref} out of range for "
                f"{len(schema)}-column input"
            )
        return ref
    if isinstance(ref, str):
        if schema is None:
            return None
        if not names:
            # mirrors ops/join._resolve_col on a name-less table
            raise _Reject(f"column name {ref!r} on an unnamed table")
        if ref not in names:
            raise _Reject(f"unknown column name {ref!r}")
        return list(names).index(ref)
    raise _Reject(f"{what} must be a column index or name, got {ref!r}")


def _cast_ok(src: ColType, to: ColType) -> Optional[str]:
    """None when the cast is statically supported; else the reason the
    kernel would refuse it. Mirrors ops/strings.cast and ops/cast.cast."""
    to_d = f"DType({to.id.name}" + (f", scale={to.scale})" if to.is_decimal else ")")
    src_d = f"DType({src.id.name}" + (
        f", scale={src.scale})" if src.is_decimal else ")"
    )
    if src.is_string or to.id == dt.TypeId.STRING:
        # strings.cast path (checked first in the dispatch plane)
        if src.is_string:
            ok = (
                to.is_boolean
                or to.is_integer
                or to.is_floating
                or to.is_decimal
                or to.id == dt.TypeId.STRING
            )
            return None if ok else f"cast STRING -> {to_d} not supported"
        ok = (
            src.is_boolean
            or src.is_integer
            or src.is_decimal
            or src.is_floating
        )
        return None if ok else f"cast {src_d} -> STRING not supported"
    if src.id == to.id and src.scale == to.scale:
        return None
    if to.id == dt.TypeId.DECIMAL128:
        if src.is_decimal or src.is_integer:
            return None
        return f"cannot cast {src_d} to DECIMAL128"
    if src.id == dt.TypeId.DECIMAL128:
        if to.is_decimal or to.is_floating or to.is_integer or to.is_boolean:
            return None
        return f"cannot cast DECIMAL128 to {to_d}"
    if not src.is_fixed_width or not to.is_fixed_width:
        return f"cast {src_d} -> {to_d} not supported"
    return None


# agg output-dtype rules mirroring ops/groupby.py; raises _Reject for
# combos the kernel refuses.
def _agg_out(agg: str, col: ColType) -> ColType:
    i64 = ColType(dt.TypeId.INT64)
    f64 = ColType(dt.TypeId.FLOAT64)
    if agg == "count":
        return i64
    if col.is_string and agg != "count":
        # string byte-matrix aggregation is not meaningful; only count is
        # statically safe (the kernels would mangle bytes shape-wise)
        raise _Reject(f"aggregation {agg!r} not supported on STRING values")
    if col.is_list:
        raise _Reject(f"aggregation {agg!r} not supported on LIST values")
    if agg == "nunique":
        if col.id == dt.TypeId.DECIMAL128:
            raise _Reject("nunique not supported for DECIMAL128")
        return i64
    if agg in ("first", "last", "min", "max"):
        return col
    if agg in ("collect_list", "collect_set"):
        from .column import _LIST_CHILD_IDS

        if col.id not in _LIST_CHILD_IDS:
            raise _Reject(
                f"{agg} not supported for DType({col.id.name}) (LIST "
                "children are int8..64, uint8..64, float32, bool)"
            )
        return ColType(dt.TypeId.LIST, 0, col.id)
    if agg == "sum":
        if col.is_floating:
            return f64
        if col.id in (dt.TypeId.DECIMAL32, dt.TypeId.DECIMAL64):
            return ColType(dt.TypeId.DECIMAL64, col.scale)
        if col.id == dt.TypeId.DECIMAL128:
            return ColType(dt.TypeId.DECIMAL128, col.scale)
        return i64
    if agg in ("mean", "variance", "std"):
        return f64
    raise _Reject(f"unknown aggregation {agg!r}")


# ---------------------------------------------------------------------------
# per-op inference rules
#
# Each rule takes (op, state) where state carries the flowing schema and
# row bound plus the rest-table queue, validates what is statically
# checkable, and returns (out_schema | None, out_names | None,
# rows_bound | None). A rule raises _Reject when the op statically cannot
# run. The key set of _RULES is the SRT008 parity anchor: it must equal
# runtime_bridge.DISPATCH_OPS.
# ---------------------------------------------------------------------------


class _State:
    def __init__(self, schema, names, rows, rest):
        self.schema = schema  # Optional[List[ColType]]
        self.names = names  # Optional[Sequence[str]]
        self.rows = rows  # Optional[int]
        # rest entries: (schema | None, rows | None); consumed exactly
        # like plan._take_rest
        self.orig_rest: List[Tuple] = list(rest)
        self.queue: List[Tuple] = list(rest)

    def take_rest(self, op: dict) -> List[Tuple]:
        idxs = op.get("rest")
        if idxs is not None:
            try:
                picked = [self.orig_rest[int(i)] for i in idxs]
            except (IndexError, TypeError, ValueError):
                raise _Reject(
                    f"'rest' indices {idxs!r} out of range for "
                    f"{len(self.orig_rest)} extra tables"
                ) from None
            return picked
        name = op.get("op")
        if name in ("join", "cross_join"):
            return [self.queue.pop(0)] if self.queue else []
        if name == "concat":
            out = list(self.queue)
            self.queue.clear()
            return out
        return []


def _r_cast(op, st):
    ci = _col_index(op, "column", st.schema, what="cast column")
    if "type_id" not in op:
        raise _Reject("missing required field 'type_id'")
    try:
        target_id = dt.TypeId(int(op["type_id"]))
    except (ValueError, TypeError):
        raise _Reject(f"unknown type_id {op.get('type_id')!r}") from None
    scale = op.get("scale", 0)
    if not isinstance(scale, int) or isinstance(scale, bool):
        raise _Reject(f"cast scale must be an integer, got {scale!r}")
    if scale != 0 and target_id not in dt._DECIMAL_IDS:
        # mirrors DType.__post_init__
        raise _Reject(f"non-zero scale on non-decimal type {target_id!r}")
    target = ColType(target_id, scale)
    if st.schema is None:
        return None, None, st.rows
    src = st.schema[ci]
    why = _cast_ok(src, target)
    if why is not None:
        raise _Reject(why)
    out = list(st.schema)
    out[ci] = target
    return out, st.names, st.rows


def _r_filter(op, st):
    mi = _col_index(op, "mask", st.schema, what="filter mask")
    if st.schema is None:
        return None, None, st.rows
    if not st.schema[mi].is_boolean:
        # mirrors ops/filter.filter_table's gate
        raise _Reject(
            f"filter mask must be BOOL8, got {st.schema[mi].pretty()}"
        )
    out = [c for i, c in enumerate(st.schema) if i != mi]
    if not out:
        raise _Reject("filter would leave a zero-column table")
    return out, None, st.rows  # names dropped, rows <= input


def _r_rlike(op, st):
    ci = _col_index(op, "column", st.schema, what="rlike column")
    pat = op.get("pattern")
    if not isinstance(pat, str):
        raise _Reject(f"rlike pattern must be a string, got {pat!r}")
    if st.schema is None:
        return None, None, st.rows
    if not st.schema[ci].is_string:
        # mirrors ops/strings._require_string
        raise _Reject(
            f"rlike expected a STRING column, got {st.schema[ci].pretty()}"
        )
    return list(st.schema), st.names, st.rows  # rows <= input


def _r_sort_by(op, st):
    keys = op.get("keys")
    if not isinstance(keys, list) or not keys:
        raise _Reject("sort_by needs a non-empty 'keys' list")
    for k in keys:
        if not isinstance(k, dict) or "column" not in k:
            raise _Reject(f"sort_by key must be {{'column': ...}}, got {k!r}")
        _key_ref(k["column"], st.schema, st.names, what="sort_by key")
    if st.schema is None:
        return None, None, st.rows
    return list(st.schema), st.names, st.rows


def _r_distinct(op, st):
    keys = op.get("keys")
    if keys is not None:
        if not isinstance(keys, list):
            raise _Reject(f"distinct 'keys' must be a list, got {keys!r}")
        for k in keys:
            _key_ref(k, st.schema, st.names, what="distinct key")
    if st.schema is None:
        return None, None, st.rows
    return list(st.schema), st.names, st.rows  # rows <= input


def _r_slice(op, st):
    start = op.get("start", 0)
    stop = op.get("stop")
    try:
        start_i = int(start)
        stop_i = None if stop is None else int(stop)
    except (TypeError, ValueError):
        raise _Reject(
            f"slice bounds must be integers, got start={start!r} "
            f"stop={stop!r}"
        ) from None
    if start_i < 0 or (stop_i is not None and stop_i < 0):
        # mirrors ops/copying.slice_rows
        raise _Reject(
            "slice: negative bounds not supported "
            f"(start={start_i}, stop={stop_i})"
        )
    rows = st.rows
    if rows is not None:
        hi = rows if stop_i is None else min(stop_i, rows)
        rows = max(0, hi - min(start_i, rows))
    elif stop_i is not None:
        rows = max(0, stop_i - start_i)
    if st.schema is None:
        return None, None, rows
    return list(st.schema), st.names, rows


def _r_groupby(op, st):
    by = op.get("by")
    if not isinstance(by, list) or not by:
        raise _Reject("groupby needs a non-empty 'by' list")
    aggs = op.get("aggs")
    if not isinstance(aggs, list):
        raise _Reject("groupby needs an 'aggs' list")
    key_idx = [
        _key_ref(b, st.schema, st.names, what="groupby 'by' column")
        for b in by
    ]
    agg_specs = []
    for a in aggs:
        if not isinstance(a, dict) or "column" not in a or "agg" not in a:
            raise _Reject(
                f"groupby agg must be {{'column': ..., 'agg': ...}}, "
                f"got {a!r}"
            )
        agg = a["agg"]
        if agg not in _KNOWN_AGGS:
            raise _Reject(f"unknown aggregation {a!r}")
        ci = _key_ref(
            a["column"], st.schema, st.names, what="groupby agg column"
        )
        agg_specs.append((ci, agg))
    if st.schema is None:
        return None, None, st.rows
    out = [st.schema[i] for i in key_idx]
    for ci, agg in agg_specs:
        out.append(_agg_out(agg, st.schema[ci]))
    return out, None, st.rows  # groups <= rows; output names dropped


_KNOWN_AGGS = frozenset(
    {
        "sum",
        "count",
        "min",
        "max",
        "mean",
        "variance",
        "std",
        "collect_list",
        "collect_set",
        "nunique",
        "first",
        "last",
    }
)

_JOIN_HOWS = frozenset({"inner", "left", "right", "full", "semi", "anti"})


def _r_join(op, st):
    how = op.get("how", "inner")
    if how not in _JOIN_HOWS:
        raise _Reject(f"unknown join how={how!r}")
    rest = st.take_rest(op)
    if not rest:
        raise _Reject("join needs two input tables")
    on = op.get("on")
    if on is None:
        raise _Reject("missing required field 'on'")
    on = on if isinstance(on, list) else [on]
    left_idx = [
        _key_ref(c, st.schema, st.names, what="join 'on' column") for c in on
    ]
    r_schema, r_rows = rest[0]
    right_idx = None
    if r_schema is not None:
        right_idx = []
        for c in on:
            if isinstance(c, int) and not isinstance(c, bool):
                if not (0 <= c < len(r_schema)):
                    raise _Reject(
                        f"join 'on' index {c} out of range for "
                        f"{len(r_schema)}-column right table"
                    )
                right_idx.append(c)
            else:
                right_idx = None
                break
    if (
        how in ("right", "full")
        and st.schema is not None
        and r_schema is not None
        and right_idx is not None
        and None not in left_idx
    ):
        for li, ri in zip(left_idx, right_idx):
            lt, rt = st.schema[li], r_schema[ri]
            if (lt.id, lt.scale) != (rt.id, rt.scale):
                # mirrors ops/join's outer-join gate
                raise _Reject(
                    "outer-join key dtypes differ: "
                    f"{lt.pretty()} vs {rt.pretty()}"
                )
    rows = None
    if st.rows is not None and r_rows is not None:
        if how in ("semi", "anti"):
            rows = st.rows
        else:
            rows = st.rows * r_rows  # upper bound without key stats
    if how in ("semi", "anti"):
        return (
            (list(st.schema) if st.schema is not None else None),
            st.names,
            rows,
        )
    if st.schema is None or r_schema is None or right_idx is None:
        return None, None, rows
    # USING semantics: left columns + right columns minus right join keys
    out = list(st.schema)
    out.extend(c for i, c in enumerate(r_schema) if i not in set(right_idx))
    return out, None, rows


def _r_cross_join(op, st):
    rest = st.take_rest(op)
    if not rest:
        raise _Reject("cross_join needs two input tables")
    r_schema, r_rows = rest[0]
    rows = None
    if st.rows is not None and r_rows is not None:
        rows = st.rows * r_rows
    if st.schema is None or r_schema is None:
        return None, None, rows
    return list(st.schema) + list(r_schema), None, rows


def _r_concat(op, st):
    rest = st.take_rest(op)
    rows = st.rows
    out = list(st.schema) if st.schema is not None else None
    for r_schema, r_rows in rest:
        rows = rows + r_rows if (rows is not None and r_rows is not None) else None
        if out is None or r_schema is None:
            out = None
            continue
        if len(r_schema) != len(out):
            raise _Reject(
                "concatenate: column counts differ "
                f"({len(out)} vs {len(r_schema)})"
            )
        for a, b in zip(out, r_schema):
            if (a.id, a.scale, a.child) != (b.id, b.scale, b.child):
                raise _Reject(
                    f"concatenate dtype mismatch: {a.pretty()} vs "
                    f"{b.pretty()}"
                )
    return out, (st.names if out is not None else None), rows


def _r_explode(op, st):
    ci = _col_index(op, "column", st.schema, what="explode column")
    if st.schema is None:
        return None, None, None  # output rows are data-dependent
    col = st.schema[ci]
    if not col.is_list:
        # mirrors ops/lists._require_list
        raise _Reject(
            f"explode expected a LIST column, got {col.pretty()}"
        )
    out = list(st.schema)
    child = col.child if col.child is not None else dt.TypeId.INT64
    out[ci] = ColType(child)
    return out, st.names, None  # rows unbounded statically


def _r_repeat(op, st):
    count = op.get("count")
    if isinstance(count, bool) or not isinstance(count, int):
        raise _Reject(f"repeat count must be an integer, got {count!r}")
    if count < 0:
        # mirrors ops/copying.repeat
        raise _Reject("repeat: count must be non-negative")
    rows = st.rows * count if st.rows is not None else None
    if st.schema is None:
        return None, None, rows
    return list(st.schema), st.names, rows


def _r_sample(op, st):
    n = op.get("n")
    if isinstance(n, bool) or not isinstance(n, int):
        raise _Reject(f"sample n must be an integer, got {n!r}")
    if n < 0:
        raise _Reject(f"sample n must be non-negative, got {n}")
    # n > rows without replacement is a *runtime* error: upstream filters
    # make the live row count data-dependent, so it stays dynamic.
    if st.schema is None:
        return None, None, n
    return list(st.schema), st.names, n


def _r_to_rows(op, st):
    if st.schema is None:
        return None, None, st.rows
    if not st.schema:
        raise _Reject("row format requires at least one column")
    for c in st.schema:
        if not c.is_fixed_width:
            # mirrors rows.compute_fixed_width_layout
            raise _Reject(
                "only fixed-width types supported in row format "
                f"(got {c.pretty()})"
            )
    return [ColType(dt.TypeId.LIST, 0, dt.TypeId.UINT8)], None, st.rows


def _r_from_rows(op, st):
    tids = op.get("type_ids")
    scales = op.get("scales")
    if not isinstance(tids, list) or not isinstance(scales, list):
        raise _Reject("from_rows needs 'type_ids' and 'scales' lists")
    if len(tids) != len(scales):
        raise _Reject(
            f"from_rows type_ids/scales length mismatch "
            f"({len(tids)} vs {len(scales)})"
        )
    if not tids:
        raise _Reject("row format requires at least one column")
    out: List[ColType] = []
    for t, s in zip(tids, scales):
        try:
            tid = dt.TypeId(int(t))
        except (ValueError, TypeError):
            raise _Reject(f"unknown type_id {t!r} in from_rows") from None
        if tid not in dt._WIDTHS:
            raise _Reject(
                "only fixed-width types supported in row format "
                f"(got {tid.name})"
            )
        if s != 0 and tid not in dt._DECIMAL_IDS:
            raise _Reject(f"non-zero scale on non-decimal type {tid!r}")
        out.append(ColType(tid, int(s)))
    if st.schema is not None:
        first = st.schema[0] if st.schema else None
        if first is not None and not (
            first.is_list or first.id == dt.TypeId.UINT8
        ):
            raise _Reject(
                "from_rows input must be a LIST<UINT8> row column or a "
                f"flat UINT8 buffer, got {first.pretty()}"
            )
        if first is not None and not first.is_list and "num_rows" not in op:
            raise _Reject(
                "from_rows on a flat UINT8 buffer needs 'num_rows'"
            )
    rows = st.rows
    if "num_rows" in op:
        nr = op["num_rows"]
        if isinstance(nr, bool) or not isinstance(nr, int) or nr < 0:
            raise _Reject(f"from_rows num_rows must be a non-negative "
                          f"integer, got {nr!r}")
        rows = nr
    return out, None, rows


def _r_partition(op, st):
    kind = op.get("kind", "hash")
    if kind not in ("hash", "range"):
        raise _Reject(f"unknown partition kind {kind!r}")
    num = op.get("num")
    if isinstance(num, bool) or not isinstance(num, int):
        raise _Reject(f"partition num must be an integer, got {num!r}")
    if num < 1:
        raise _Reject(f"partition num must be >= 1, got {num}")
    keys = op.get("keys", [])
    if not isinstance(keys, list):
        raise _Reject(f"partition 'keys' must be a list, got {keys!r}")
    if kind == "range" and not keys:
        raise _Reject("partition kind='range' needs a non-empty 'keys' list")
    for k in keys:
        _key_ref(k, st.schema, st.names, what="partition key")
    # pure row redistribution: schema and total rows pass through
    # unchanged — only the row ORDER (exact path) / placement (mesh
    # path) moves, which is why it can sit on a segment boundary.
    if st.schema is None:
        return None, None, st.rows
    return list(st.schema), st.names, st.rows


# The rule table — the plancheck side of the SRT008 registry-parity pair.
# Keys must equal runtime_bridge.DISPATCH_OPS (enforced statically by
# srt_check pass SRT008 and dynamically by tests/test_plancheck.py).
_RULES = {
    "join": _r_join,
    "concat": _r_concat,
    "groupby": _r_groupby,
    "sort_by": _r_sort_by,
    "filter": _r_filter,
    "distinct": _r_distinct,
    "cast": _r_cast,
    "explode": _r_explode,
    "rlike": _r_rlike,
    "cross_join": _r_cross_join,
    "slice": _r_slice,
    "repeat": _r_repeat,
    "sample": _r_sample,
    "partition": _r_partition,
    "to_rows": _r_to_rows,
    "from_rows": _r_from_rows,
}


# ---------------------------------------------------------------------------
# support tiers (the GpuOverrides tag)
# ---------------------------------------------------------------------------

# ops the per-op bucketed runners cover (bucketed._RUNNERS); parity is
# asserted dynamically by tests/test_plancheck.py
_BUCKETED_OPS = frozenset(
    {"cast", "filter", "sort_by", "groupby", "distinct", "rlike", "join"}
)
_BUCKETED_JOIN_HOWS = frozenset({"inner", "left", "semi", "anti"})
_COLLECT_AGGS = frozenset({"collect_list", "collect_set"})


def _op_fusable(op: dict) -> bool:
    """Mirror of plan.op_fusable — kept local so the analyzer stays
    import-light; parity with the runtime is cross-checked by test."""
    if not isinstance(op, dict):
        return False
    name = op.get("op")
    if name in ("cast", "filter", "rlike", "distinct", "sort_by"):
        return True
    if name == "slice":
        try:
            start = int(op.get("start", 0))
            stop = op.get("stop")
            return start >= 0 and (stop is None or int(stop) >= 0)
        except (TypeError, ValueError):
            return False
    if name == "groupby":
        return not any(
            a.get("agg") in _COLLECT_AGGS
            for a in op.get("aggs", ())
            if isinstance(a, dict)
        )
    return False


def _tier(op: dict) -> Tuple[str, str]:
    """(tier, reason) for a well-formed op — GpuOverrides-style tag."""
    name = op.get("op")
    if _op_fusable(op):
        if name == "groupby":
            return (
                "fusable",
                "rides a fused segment tail-only: the groupby closes "
                "its run (plan.segment_plan)",
            )
        if name == "slice":
            return (
                "fusable",
                "non-negative static bounds ride inside a fused segment",
            )
        return "fusable", "single-table row-local op: rides fused segments"
    if name in _BUCKETED_OPS:
        if name == "join":
            how = op.get("how", "inner")
            if how in _BUCKETED_JOIN_HOWS:
                return (
                    "per-op",
                    f"join how={how!r} has a bucketed per-op runner",
                )
            return (
                "exact-only",
                f"join how={how!r} needs the exact path (outer-side "
                "row expansion defeats bucket padding)",
            )
        if name == "groupby":
            return (
                "exact-only",
                "collect_list/collect_set needs a data-dependent list "
                "capacity pre-pass only the exact path owns",
            )
        if name == "slice":
            return (
                "exact-only",
                "non-static or negative slice bounds fall back to the "
                "exact path (where negative bounds raise)",
            )
    if name == "slice":
        return (
            "exact-only",
            "non-static slice bounds fall back to the exact path",
        )
    _EXACT_REASONS = {
        "concat": "multi-table op: exact path only",
        "join": "multi-table op: exact path only",
        "cross_join": "multi-table op with n*m row expansion: exact only",
        "explode": "data-dependent output rows: exact path only",
        "repeat": "row-multiplying op: exact path only",
        "sample": "data-dependent gather: exact path only",
        "partition": "exchange boundary: exact path reorders in place; "
                     "the mesh path (planmesh) runs a counts-sized "
                     "all-to-all here and fuses the chains either side",
        "to_rows": "row-format transpose: exact path only",
        "from_rows": "row-format transpose: exact path only",
    }
    if name in _RULES:
        return "exact-only", _EXACT_REASONS.get(
            name, "no fused or bucketed runner: exact path only"
        )
    return "unsupported", f"unknown table op {name!r}"


# ---------------------------------------------------------------------------
# kernel tier (kernels/registry.py) — static eligibility tags
# ---------------------------------------------------------------------------

# the static halves of the registry's applicability predicates. Keys
# must equal kernels.registry.KERNEL_NAMES — the SRT012 parity pair
# (enforced statically by srt_check pass SRT012 and dynamically by
# tests/test_kernel_tier.py). The tag is ADDITIVE to the support tier:
# a kernel-tagged op keeps its fusable/per-op/exact-only tier and may
# still decline at runtime on facts plancheck cannot see (nullability,
# bucket ladder, duplicate build keys) — the tag means "structurally
# eligible", never "will launch".

_KERNEL_JOIN_HOWS = frozenset({"inner", "semi", "anti"})
_KERNEL_AGG_OPS = frozenset({"sum", "count", "min", "max"})


def _kernel_key_reason(ct: Optional[ColType]) -> Optional[str]:
    """Static half of registry._order_word_reason: why this column can
    never be a single-u64-order-word kernel key (None = maybe; the
    nullable-key decline is a runtime fact)."""
    if ct is None:
        return None
    if ct.is_string:
        return "string key (multi-word order key)"
    if ct.id == dt.TypeId.DECIMAL128:
        return "DECIMAL128 key (two-word order key)"
    if ct.id in (dt.TypeId.LIST, dt.TypeId.STRUCT):
        return f"{ct.id.name} key"
    return None


def _kernel_col(ref, schema, names) -> Tuple[Optional[ColType], bool]:
    """(coltype, resolvable): resolve a key ref without raising.
    Unknown schema answers (None, True) — permissive, like the rest of
    the analyzer."""
    try:
        idx = _key_ref(ref, schema, names, what="kernel key")
    except _Reject:
        return None, False
    if idx is None or schema is None:
        return None, True
    return schema[idx], True


def _peek_rest(op: dict, st) -> Optional[List[Tuple]]:
    """The (schema, rows) pairs take_rest WOULD hand this op, without
    consuming them (the kernel tag runs before the rule does)."""
    idxs = op.get("rest")
    if idxs is not None:
        try:
            return [st.orig_rest[int(i)] for i in idxs]
        except (IndexError, TypeError, ValueError):
            return None
    return [st.queue[0]] if st.queue else []


def _k_packed_sort(op: dict, st) -> Optional[str]:
    ks = op.get("keys")
    if not isinstance(ks, list) or len(ks) != 1 \
            or not isinstance(ks[0], dict):
        return "multi-key sort (one packed word per network)"
    ct, ok = _kernel_col(ks[0].get("column"), st.schema, st.names)
    if not ok:
        return "unresolvable sort key column"
    return _kernel_key_reason(ct)


def _k_hash_join(op: dict, st) -> Optional[str]:
    how = op.get("how", "inner")
    if how not in _KERNEL_JOIN_HOWS:
        return f"join how={how!r} (left/outer build on exact machinery)"
    on = op.get("on")
    if not isinstance(on, list) or len(on) != 1:
        return "multi-column join key"
    rest = _peek_rest(op, st)
    if not rest:
        return "missing build-side table"
    lct, lok = _kernel_col(on[0], st.schema, st.names)
    rct, rok = _kernel_col(on[0], rest[0][0], None)
    if not (lok and rok):
        return "unresolvable join key column"
    for side, ct in (("probe", lct), ("build", rct)):
        r = _kernel_key_reason(ct)
        if r is not None:
            return f"{side} side: {r}"
    return None


def _k_hash_groupby(op: dict, st) -> Optional[str]:
    by = op.get("by")
    if not isinstance(by, list) or len(by) != 1:
        return "multi-column group key"
    aggs = op.get("aggs")
    if not isinstance(aggs, list) or not aggs:
        return "no aggregations"
    for a in aggs:
        if not isinstance(a, dict):
            return "malformed aggregation spec"
        if a.get("agg") not in _KERNEL_AGG_OPS:
            return f"non-decomposable agg {a.get('agg')!r}"
    ct, ok = _kernel_col(by[0], st.schema, st.names)
    if not ok:
        return "unresolvable group key column"
    r = _kernel_key_reason(ct)
    if r is not None:
        return r
    for a in aggs:
        vct, vok = _kernel_col(a.get("column"), st.schema, st.names)
        if not vok:
            return "unresolvable aggregation column"
        if vct is not None and (
            vct.is_string or vct.is_decimal or vct.is_floating
            or vct.is_list
        ):
            return (
                f"{vct.id.name} aggregation value (order-sensitive or "
                "multi-word)"
            )
    return None


def _k_row_pack(op: dict, st) -> Optional[str]:
    if st.schema is not None:
        for ct in st.schema:
            if not ct.is_fixed_width:
                return (
                    f"{ct.id.name} column has no fixed-width row slot"
                )
    return None


def _k_row_unpack(op: dict, st) -> Optional[str]:
    if st.schema is not None and st.schema:
        first = st.schema[0]
        if not first.is_list:
            return "legacy flat row buffer (host decode path)"
    for tid in op.get("type_ids") or ():
        try:
            if dt.TypeId(int(tid)) not in dt._WIDTHS:
                return "non-fixed-width target schema"
        except (TypeError, ValueError):
            return "non-fixed-width target schema"
    return None


# kernel name -> (covered op name, static eligibility rule). The keys
# are the SRT012 anchor; the op coverage must mirror the registry's
# KernelSpec.ops tuples.
_KERNEL_RULES = {
    "packed_sort": ("sort_by", _k_packed_sort),
    "hash_build_probe": ("join", _k_hash_join),
    "hash_groupby": ("groupby", _k_hash_groupby),
    "row_pack": ("to_rows", _k_row_pack),
    "row_unpack": ("from_rows", _k_row_unpack),
}

_KERNELS_BY_OP: Dict[str, List[str]] = {}
for _kname, (_opname, _) in _KERNEL_RULES.items():
    _KERNELS_BY_OP.setdefault(_opname, []).append(_kname)
for _v in _KERNELS_BY_OP.values():
    _v.sort()


def _kernel_tag(op: dict, st) -> Optional[str]:
    """The kernel-tier tag for one op against the INPUT schema state:
    the registered kernel name when the op is statically eligible, else
    None. Never raises — malformed specs answer None and the op rule
    reports the real rejection."""
    for kname in _KERNELS_BY_OP.get(op.get("op"), ()):
        _, krule = _KERNEL_RULES[kname]
        try:
            if krule(op, st) is None:
                return kname
        # srt: allow-broad-except(the tag is advisory; a rule surprise degrades to untagged and the op rule reports the real rejection)
        except Exception:
            return None
    return None


def predict_segments(ops: Sequence[dict]) -> List[Tuple[str, List[int]]]:
    """Predicted fusion segmentation as ``[(kind, [op indices])]`` —
    must agree exactly with ``plan.segment_plan`` (cross-checked by
    test so the two can never drift)."""
    segs: List[Tuple[str, List[int]]] = []
    cur: List[int] = []

    def flush():
        nonlocal cur
        if not cur:
            return
        if len(cur) >= 2:
            segs.append(("fused", cur))
        else:
            segs.extend(("exact", [i]) for i in cur)
        cur = []

    for i, op in enumerate(ops):
        if _op_fusable(op):
            cur.append(i)
            if op.get("op") == "groupby":
                flush()
        else:
            flush()
            segs.append(("exact", [i]))
    flush()
    return segs


# ---------------------------------------------------------------------------
# the analyzer
# ---------------------------------------------------------------------------


def analyze(
    ops,
    schema: Optional[Sequence[ColType]] = None,
    rows: Optional[int] = None,
    rest: Sequence[Tuple[Optional[Sequence[ColType]], Optional[int]]] = (),
    names: Optional[Sequence[str]] = None,
) -> dict:
    """Walk a plan statically and return the tagged report (never raises
    for plan content — malformed ops become ``unsupported`` entries with
    ``ok: False``). ``schema=None`` degrades to structural validation.

    ``rest`` carries the extra input tables as ``(schema, rows)`` pairs,
    consumed exactly like ``plan._take_rest``.
    """
    report: dict = {
        "ok": True,
        "rows_in": rows,
        "ops": [],
        "kernel_ops": [],
        "segments": [],
        "est_hbm_peak_bytes": None,
    }
    if not isinstance(ops, list):
        report["ok"] = False
        report["ops"].append(
            {
                "index": 0,
                "op": None,
                "tier": "unsupported",
                "reason": "plan must be a JSON list of op objects, got "
                + type(ops).__name__,
                "out_schema": None,
                "rows_bound": None,
            }
        )
        return report

    st = _State(list(schema) if schema is not None else None, names, rows, rest)
    op_rows: List[Optional[int]] = []
    op_widths: List[Tuple[Optional[int], Optional[int]]] = []
    for i, op in enumerate(ops):
        entry = {
            "index": i,
            "op": None,
            "tier": None,
            "reason": None,
            "kernel": None,
            "out_schema": None,
            "rows_bound": None,
        }
        if not isinstance(op, dict) or "op" not in op:
            entry["tier"] = "unsupported"
            entry["reason"] = f"plan entries must be op objects, got {op!r}"
            report["ok"] = False
            report["ops"].append(entry)
            op_rows.append(None)
            op_widths.append((None, None))
            # schema unknowable downstream of a malformed entry
            st.schema, st.names, st.rows = None, None, None
            continue
        name = op.get("op")
        entry["op"] = name
        tier, reason = _tier(op)
        entry["tier"], entry["reason"] = tier, reason
        # kernel tag against the INPUT state — before the rule advances
        # st past this op (the runtime predicate sees the same input)
        entry["kernel"] = _kernel_tag(op, st)
        rule = _RULES.get(name)
        if rule is None:
            report["ok"] = False
            report["ops"].append(entry)
            op_rows.append(None)
            op_widths.append((None, None))
            st.schema, st.names, st.rows = None, None, None
            continue
        width_in = _row_width(st.schema)
        try:
            out_schema, out_names, out_rows = rule(op, st)
        except _Reject as e:
            entry["tier"] = "unsupported"
            entry["reason"] = e.reason
            entry["kernel"] = None
            report["ok"] = False
            report["ops"].append(entry)
            op_rows.append(None)
            op_widths.append((width_in, None))
            st.schema, st.names, st.rows = None, None, None
            continue
        entry["out_schema"] = (
            [c.to_json() for c in out_schema]
            if out_schema is not None
            else None
        )
        entry["rows_bound"] = out_rows
        report["ops"].append(entry)
        op_rows.append(out_rows)
        op_widths.append((width_in, _row_width(out_schema)))
        st.schema, st.names, st.rows = out_schema, out_names, out_rows

    report["kernel_ops"] = [
        e["index"] for e in report["ops"] if e.get("kernel")
    ]
    report["out_schema"] = report["ops"][-1]["out_schema"] if report["ops"] else (
        [c.to_json() for c in schema] if schema is not None else None
    )
    report["rows_out_bound"] = op_rows[-1] if op_rows else rows

    # segmentation + footprint: per-op working set ~ rows_in*width_in +
    # rows_out*width_out; segment bound = max over its ops; plan peak =
    # max over segments. None propagates (variable-width/unbounded ops).
    segs = predict_segments(ops)
    peak: Optional[int] = None
    rows_before: List[Optional[int]] = [rows] + op_rows[:-1] if ops else []
    for kind, idxs in segs:
        seg_bytes: Optional[int] = 0
        seg_rows: Optional[int] = None
        for i in idxs:
            win, wout = op_widths[i]
            rin, rout = rows_before[i], op_rows[i]
            seg_rows = rout
            if None in (win, rin):
                op_bytes = None
            else:
                op_bytes = rin * win
                if wout is not None and rout is not None:
                    op_bytes += rout * wout
            if op_bytes is None:
                seg_bytes = None
            elif seg_bytes is not None:
                seg_bytes = max(seg_bytes, op_bytes)
        report["segments"].append(
            {
                "kind": kind,
                "ops": list(idxs),
                "rows_bound": seg_rows,
                "est_hbm_bytes": seg_bytes,
            }
        )
        if seg_bytes is not None:
            peak = seg_bytes if peak is None else max(peak, seg_bytes)
    report["est_hbm_peak_bytes"] = peak
    return report


def check_plan(
    ops,
    schema: Optional[Sequence[ColType]] = None,
    rows: Optional[int] = None,
    rest: Sequence[Tuple[Optional[Sequence[ColType]], Optional[int]]] = (),
    names: Optional[Sequence[str]] = None,
) -> dict:
    """``analyze`` + fail-fast: raises :class:`PlanCheckError` naming the
    first statically-invalid op (index, name, reason, full report
    attached) — before any upload, compile, or scheduler admission.
    Returns the report when the plan tags clean."""
    report = analyze(ops, schema=schema, rows=rows, rest=rest, names=names)
    if not report["ok"]:
        for entry in report["ops"]:
            if entry["tier"] == "unsupported":
                raise PlanCheckError(
                    entry["index"], entry["op"], entry["reason"], report
                )
        raise PlanCheckError(0, None, "plan failed static analysis", report)
    return report


# ---------------------------------------------------------------------------
# rendering (tools/explain.py --static)
# ---------------------------------------------------------------------------

_TIER_GLYPH = {
    "fusable": "*",
    "per-op": "+",
    "exact-only": "=",
    "unsupported": "!",
}


def render_report(report: dict) -> str:
    """Human-readable tagged plan, GpuOverrides-style: one line per op
    with tier glyph, inferred output schema, and reason; then the
    predicted segmentation and the static footprint bound."""
    lines: List[str] = []
    ok = report.get("ok", False)
    lines.append(f"plancheck: {'clean' if ok else 'REJECTED'}")
    rows_in = report.get("rows_in")
    if rows_in is not None:
        lines.append(f"rows in: {rows_in}")
    for e in report.get("ops", []):
        glyph = _TIER_GLYPH.get(e.get("tier"), "?")
        schema = e.get("out_schema")
        if schema is None:
            sch = "?"
        else:
            sch = "[" + ", ".join(c["pretty"] for c in schema) + "]"
        rb = e.get("rows_bound")
        rows_s = f" rows<={rb}" if rb is not None else ""
        kern = e.get("kernel")
        kern_s = f" ~kernel:{kern}" if kern else ""
        lines.append(
            f"  {glyph} op[{e['index']}] {e.get('op')!s:<10} "
            f"{e.get('tier') or '?':<11} -> {sch}{rows_s}{kern_s}"
        )
        lines.append(f"      {e.get('reason')}")
    segs = report.get("segments", [])
    if segs:
        parts = []
        for s in segs:
            idxs = ",".join(str(i) for i in s["ops"])
            b = s.get("est_hbm_bytes")
            b_s = f" ~{b}B" if b is not None else ""
            parts.append(f"{s['kind']}[{idxs}]{b_s}")
        lines.append("segments: " + " | ".join(parts))
    peak = report.get("est_hbm_peak_bytes")
    lines.append(
        "est HBM peak: " + (f"{peak} bytes" if peak is not None else "unbounded/unknown")
    )
    return "\n".join(lines)
