"""Order-preserving key normalization — the backbone of sort/groupby/join.

Every fixed-width column maps to one (or more, for strings) uint64 "order
key" arrays whose unsigned order equals the column's logical order. All
comparison-based ops (sort, merge join, groupby segmentation) then operate
on uniform u64 vectors, which XLA sorts/compares efficiently on TPU —
replacing cudf's per-type comparator template dispatch with a single
normalization pass.

Encodings:
* signed ints / timestamps / durations / decimals: value XOR sign-flip
  (two's complement order -> unsigned order).
* unsigned ints / bool: widen.
* FLOAT32/FLOAT64: the classic IEEE total-order trick on the *stored bit
  pattern* (negative values invert all bits, positives set the sign bit).
  NaN (canonical 0x7FF8...) maps above +inf, matching Spark/cudf's
  "NaN is largest" ordering — and doubles never need decoding, so this is
  exact on TPU regardless of the f64 emulation envelope.
* STRING: pad/8 big-endian u64 words of the padded byte matrix plus the
  length as a final tiebreaker word (memcmp order on '\0'-padded equal
  words == lexicographic byte order).

Nulls are handled by callers as an extra leading key (see sort.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column

_SIGN64 = np.uint64(1) << np.uint64(63)
_SIGN32 = np.uint32(1) << np.uint32(31)


def _float_bits_order(bits: jax.Array, width: int) -> jax.Array:
    """IEEE bits -> order-preserving unsigned key (same width)."""
    if width == 64:
        sign = (bits >> jnp.uint64(63)) != 0
        return jnp.where(sign, ~bits, bits | _SIGN64)
    sign = (bits >> jnp.uint32(31)) != 0
    return jnp.where(sign, ~bits, bits | _SIGN32)


def column_order_keys(col: Column) -> list[jax.Array]:
    """uint64 key array(s) whose unsigned order == the column's order."""
    d = col.dtype
    data = col.data
    if d.is_string:
        return _string_order_keys(col)
    if d.id == dt.TypeId.DECIMAL128:
        # (n, 2) u64 limbs: sign-flipped hi word then lo word — the
        # 128-bit instance of the signed sign-flip rule below
        from .int128 import order_key_words

        return order_key_words(data)
    if d.id == dt.TypeId.FLOAT64:
        return [_float_bits_order(data, 64)]
    if d.id == dt.TypeId.FLOAT32:
        bits = jax.lax.bitcast_convert_type(data, jnp.uint32)
        return [_float_bits_order(bits, 32).astype(jnp.uint64)]
    if d.is_boolean:
        return [data.astype(jnp.uint64)]
    np_dt = np.dtype(d.storage_dtype)
    if np_dt.kind == "u":
        return [data.astype(jnp.uint64)]
    # signed (ints, decimals, timestamps, durations): flip the sign bit
    # after widening so two's-complement order becomes unsigned order.
    widened = data.astype(jnp.int64).astype(jnp.uint64)
    return [widened ^ _SIGN64]


def _string_order_keys(col: Column) -> list[jax.Array]:
    mat = col.data  # (n, pad) uint8, zero-padded past length
    n, pad = mat.shape
    words = []
    for w in range((pad + 7) // 8):
        acc = jnp.zeros((n,), dtype=jnp.uint64)
        for b in range(8):
            i = w * 8 + b
            byte = (
                mat[:, i].astype(jnp.uint64)
                if i < pad
                else jnp.zeros((n,), dtype=jnp.uint64)
            )
            acc = (acc << jnp.uint64(8)) | byte  # big-endian => memcmp order
        words.append(acc)
    # length tiebreaker: "a" < "a\0" can't happen (pad bytes are zero and
    # shorter strings compare smaller on the zero word), but "a" vs "a" with
    # embedded NULs needs the explicit length word.
    words.append(col.lengths.astype(jnp.uint64))
    return words


def table_order_keys(cols: list[Column]) -> list[jax.Array]:
    out = []
    for c in cols:
        out.extend(column_order_keys(c))
    return out


def composite_compare_le(
    a_keys: list[jax.Array], a_idx, b_keys: list[jax.Array], b_idx
) -> jax.Array:
    """Lexicographic (a[a_idx] <= b[b_idx]) over parallel u64 key lists."""
    lt = jnp.zeros(jnp.shape(a_idx), dtype=jnp.bool_)
    eq = jnp.ones(jnp.shape(a_idx), dtype=jnp.bool_)
    for ak, bk in zip(a_keys, b_keys):
        av = ak[a_idx]
        bv = bk[b_idx]
        lt = lt | (eq & (av < bv))
        eq = eq & (av == bv)
    return lt | eq


def rows_equal(
    a_keys: list[jax.Array], a_idx, b_keys: list[jax.Array], b_idx
) -> jax.Array:
    eq = jnp.ones(jnp.shape(a_idx), dtype=jnp.bool_)
    for ak, bk in zip(a_keys, b_keys):
        eq = eq & (ak[a_idx] == bk[b_idx])
    return eq


@jax.jit
def _minmax_jit(kw):
    return jnp.min(kw), jnp.max(kw)


def minmax_host(kw):
    """Host (int, int) min/max of a key-order word — the eager range
    probe every packed-key router shares."""
    lo, hi = _minmax_jit(kw)
    return int(lo), int(hi)


def fold_fields(rels, field_bits):
    """Pack parallel relative-key u64 arrays as bit fields of ONE word
    (first field in the high bits): lexicographic order of the tuple ==
    numeric order of the composite. Callers validate that each rel fits
    its declared width — the shared primitive of the packed
    groupby/join/sort formulations."""
    out = jnp.zeros(rels[0].shape, jnp.uint64)
    for r, b in zip(rels, field_bits):
        out = (out << jnp.uint64(b)) | r
    return out


def peel_fields(word, field_bits):
    """Inverse of :func:`fold_fields`: the per-key relative fields."""
    shift = 0
    fields = []
    for b in reversed(field_bits):
        fields.append(
            (word >> jnp.uint64(shift))
            & ((jnp.uint64(1) << jnp.uint64(b)) - jnp.uint64(1))
        )
        shift += b
    fields.reverse()
    return fields
