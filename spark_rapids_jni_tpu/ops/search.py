"""Search ops (cudf ``lower_bound`` / ``upper_bound`` / ``contains``).

Capability-surface rows of SURVEY.md §2.3 (the vendored cudf Java suite
covers Table.lowerBound/upperBound and ColumnVector.contains). Rows
reduce to the shared uint64 order-key space of ops/keys.py and the
bounds run through the same vectorized multi-word binary search the
join uses — one code path for every fixed-width and string type instead
of cudf's per-type comparator dispatch.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from .. import dtype as dt
from ..column import Column, Table
from .join import _lex_searchsorted
from .keys import table_order_keys


def _key_words(table: Table, keys: Sequence | None) -> list[jax.Array]:
    cols = (
        [table.column(k) for k in keys]
        if keys is not None
        else list(table.columns)
    )
    return table_order_keys(cols)


def lower_bound(
    haystack: Table, needles: Table, keys: Sequence | None = None
) -> Column:
    """First insertion index of each needle row into the sorted haystack
    (cudf ``lower_bound``). ``haystack`` must already be sorted ascending
    on ``keys`` (defaults: positionally, all columns)."""
    return _bound(haystack, needles, keys, "left")


def upper_bound(
    haystack: Table, needles: Table, keys: Sequence | None = None
) -> Column:
    """One-past-last insertion index (cudf ``upper_bound``)."""
    return _bound(haystack, needles, keys, "right")


def _bound(haystack: Table, needles: Table, keys, side: str) -> Column:
    hwords = _key_words(haystack, keys)
    nwords = _key_words(
        needles, keys if keys is not None and _names_apply(needles, keys) else None
    )
    if len(hwords) != len(nwords):
        raise ValueError("lower/upper_bound: key schemas differ")
    out = _lex_searchsorted(hwords, nwords, side)
    return Column(out.astype(jnp.int32), dt.INT32, None)


def _names_apply(table: Table, keys) -> bool:
    try:
        for k in keys:
            table.column(k)
        return True
    except (KeyError, IndexError, ValueError):
        return False


def _sorted_words(words: list[jax.Array]) -> list[jax.Array]:
    """Sort rows of a multi-word key set lexicographically."""
    # lexsort: last key is primary
    perm = jnp.lexsort(tuple(reversed(words)))
    return [w[perm] for w in words]


def contains_column(
    haystack: Column, needles: Column
) -> Column:
    """BOOL8 column: is each needle value present in haystack (cudf
    ``contains``, the IN-list expression). Null needles stay null; null
    haystack entries never match."""
    if haystack.dtype != needles.dtype:
        raise TypeError(
            f"contains: dtype mismatch {haystack.dtype} vs {needles.dtype}"
        )
    hwords = table_order_keys([haystack])
    nwords = table_order_keys([needles])
    if haystack.validity is not None:
        # exile null rows to a key needles can only match if they also
        # carry the max key AND are valid — handled by the equality scan
        # below over hi>lo ranges of *valid* rows only
        mask = haystack.validity
        hwords = [
            jnp.where(mask, w, jnp.uint64(0xFFFFFFFFFFFFFFFF)) for w in hwords
        ]
    sw = _sorted_words(hwords)
    lo = _lex_searchsorted(sw, nwords, "left")
    hi = _lex_searchsorted(sw, nwords, "right")
    found = hi > lo
    if haystack.validity is not None:
        # a needle equal to the exile key could false-positive against
        # nulled slots; cap the range at the count of valid rows
        n_valid = jnp.sum(haystack.validity).astype(jnp.int32)
        found = jnp.logical_and(found, lo < n_valid)
    return Column(found, dt.BOOL8, needles.validity)
