"""Two-level (chunked) group-by — the high-throughput path.

The single-pass design in ops/groupby.py pays one variadic stable sort
over ALL rows: at 100M rows that is ~log2(1e8)^2 ≈ 700 compare passes
over ~2 GB resident in HBM, which measured at ~0.2% of v5e HBM peak
(round-3 bench) — a design ceiling, not a tuning problem.

This module replaces the one giant sort with the classic two-level
aggregation, shaped for the TPU memory hierarchy:

  phase 1  rows reshaped to (C, T) chunks; the EXISTING capped groupby
           runs per-chunk under ``jax.vmap`` — C independent T-row
           sorts batched by XLA instead of one n-row sort. Small sorts
           cut the bitonic pass count quadratically (log2(T)^2 vs
           log2(n)^2) and fit VMEM (~16 MB/core) so passes stop
           round-tripping HBM.
  phase 2  the C×S chunk partials (at most `chunk_segments` groups per
           chunk) concatenate into one small table that a single capped
           groupby combines: sums of sums, min of mins, etc.

Exactness: every aggregate here is algebraically decomposable —
integer/decimal sums are associative mod 2^64/2^128, counts/min/max/
first/last trivially so (chunk-major row order preserves first/last
semantics); float sums re-associate, like any parallel reduction.
``variance``/``nunique``/``collect_*`` are NOT decomposable and stay on
the single-pass path (the eager router checks).

Capacity: a chunk holding more than ``chunk_segments`` distinct keys
would silently truncate, so the jittable API returns the max per-chunk
group count for the caller to check; the eager wrapper probes one chunk
to size the capacity, verifies after the fact, and falls back to the
exact single-pass path when cardinality is too high for chunking to
win.

Reference parity: cudf's groupby hash-aggregates per thread block then
merges across blocks — same two-level shape, re-expressed as batched
sorts + segment reductions because TPU has no device-wide atomic hash
tables (SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .. import dtype as dt
from ..column import Column, Table
from . import compute
from .groupby import GroupbyAgg, groupby_aggregate_capped

# aggregations with an exact two-level decomposition
DECOMPOSABLE_OPS = {"sum", "count", "min", "max", "mean", "first", "last"}

# phase-1 partial op + phase-2 combine op per user-facing op
_COMBINE = {
    "sum": "sum",
    "count": "sum",
    "min": "min",
    "max": "max",
    "first": "first",
    "last": "last",
}


def _ceil_pow2(x: int) -> int:
    return 1 << max(0, (x - 1).bit_length())


def _pad_chunks(table: Table, chunk_rows: int):
    """(chunked table with (C, T) leaves, (C, T) occupancy mask)."""
    n = table.row_count
    c = -(-n // chunk_rows)
    padded = c * chunk_rows

    def pad_reshape(x):
        if x is None:
            return None
        pad_width = [(0, padded - n)] + [(0, 0)] * (x.ndim - 1)
        y = jnp.pad(x, pad_width)
        return y.reshape((c, chunk_rows) + x.shape[1:])

    cols = [
        Column(
            pad_reshape(col.data),
            col.dtype,
            pad_reshape(col.validity),
            pad_reshape(col.lengths),
        )
        for col in table.columns
    ]
    occ = (
        jnp.arange(padded, dtype=jnp.int32).reshape(c, chunk_rows) < n
    )
    return Table(cols, table.names), occ


def _phase1_plan(table: Table, by, aggs: Sequence[GroupbyAgg]):
    """The partial aggregations phase 1 must compute: every requested
    decomposable op, plus a count per mean column (mean = Σsum/Σcount).
    Returns (phase-1 agg list, per-user-agg plan entries)."""
    p1: list[GroupbyAgg] = []
    names: dict = {}

    def need(column, op) -> str:
        key = (id(table.column(column)), op)
        if key not in names:
            nm = f"__p1_{len(p1)}_{op}"
            names[key] = nm
            p1.append(GroupbyAgg(column, op, name=nm))
        return names[key]

    plan = []
    for a in aggs:
        if a.op not in DECOMPOSABLE_OPS:
            raise ValueError(
                f"{a.op} has no two-level decomposition; route through "
                "the single-pass groupby"
            )
        if a.op == "mean":
            if table.column(a.column).dtype.id == dt.TypeId.DECIMAL128:
                raise ValueError(
                    "DECIMAL128 mean is not chunkable (needs the 128-bit "
                    "limb decode); use the single-pass groupby"
                )
            plan.append(
                ("mean", a, need(a.column, "sum"), need(a.column, "count"))
            )
        else:
            plan.append((a.op, a, need(a.column, a.op), None))
    return p1, plan


def groupby_aggregate_capped_chunked(
    table: Table,
    by: Sequence[Union[int, str]],
    aggs: Sequence[GroupbyAgg],
    num_segments: int,
    chunk_rows: int = 1 << 18,
    chunk_segments: int = 1 << 14,
) -> tuple[Table, jax.Array, jax.Array]:
    """Jittable two-level groupby.

    Returns ``(padded result of num_segments rows, total group count,
    max per-chunk group count)``. The result is EXACT iff the last
    value is <= ``chunk_segments`` — a chunk with more distinct keys
    than that would have truncated groups, so callers must check (the
    eager wrapper does; bench asserts it).
    """
    # must mirror groupby_aggregate_capped's output naming exactly
    # (unnamed tables name keys by POSITION, f"key{i}", not column index)
    key_names = [
        c
        if isinstance(c, str)
        else (table.names[c] if table.names else f"key{i}")
        for i, c in enumerate(by)
    ]
    p1_aggs, plan = _phase1_plan(table, by, aggs)

    chunked, occ = _pad_chunks(table, chunk_rows)
    c = occ.shape[0]

    def one_chunk(tbl, rv):
        return groupby_aggregate_capped(
            tbl, by, p1_aggs, num_segments=chunk_segments, row_valid=rv
        )
    partial, chunk_groups = jax.vmap(one_chunk)(chunked, occ)

    # flatten (C, S, ...) partials to one (C*S, ...) table; chunk-major
    # order keeps first/last semantics (earlier chunks = earlier rows)
    flat_cols = jax.tree.map(
        lambda x: x.reshape((c * chunk_segments,) + x.shape[2:]), partial
    )
    seg_iota = jnp.arange(chunk_segments, dtype=jnp.int32)[None, :]
    p2_valid = (seg_iota < chunk_groups[:, None]).reshape(-1)

    # phase 2: combine partials with one small capped groupby
    p2_aggs = []
    for i, a in enumerate(p1_aggs):
        p2_aggs.append(
            GroupbyAgg(a.name, _COMBINE[a.op], name=f"__p2_{i}")
        )
    combined, num_groups = groupby_aggregate_capped(
        flat_cols, key_names, p2_aggs, num_segments=num_segments,
        row_valid=p2_valid,
    )

    # assemble the user-facing schema (same as the single-pass capped API)
    out_cols = list(combined.columns[: len(by)])
    out_names = list(combined.names[: len(by)])
    p2_of = {f"__p2_{i}": combined.column(f"__p2_{i}") for i in range(len(p1_aggs))}
    p1_name_to_p2 = {
        a.name: p2_of[f"__p2_{i}"] for i, a in enumerate(p1_aggs)
    }
    for op, a, main_name, count_name in plan:
        colref = a.column
        base = (
            colref
            if isinstance(colref, str)
            else (table.names[colref] if table.names else f"c{colref}")
        )
        out_name = a.name or f"{a.op}_{base}"
        if op == "mean":
            total = p1_name_to_p2[main_name]
            cnt = p1_name_to_p2[count_name]
            n_valid = compute.values(cnt)
            mean = compute.values(total).astype(jnp.float64) / jnp.maximum(
                n_valid, 1
            )
            src_dtype = table.column(colref).dtype
            if src_dtype.is_decimal and src_dtype.id != dt.TypeId.DECIMAL128:
                mean = mean * (10.0 ** src_dtype.scale)
            has = jnp.logical_and(compute.valid_mask(cnt), n_valid > 0)
            out_cols.append(compute.from_values(mean, dt.FLOAT64, has))
        else:
            out_cols.append(p1_name_to_p2[main_name])
        out_names.append(out_name)
    return (
        Table(out_cols, out_names),
        num_groups,
        jnp.max(chunk_groups),
    )


def chunked_groupby_supported(table: Table, aggs: Sequence[GroupbyAgg]) -> bool:
    for a in aggs:
        if a.op not in DECOMPOSABLE_OPS:
            return False
        if (
            a.op == "mean"
            and table.column(a.column).dtype.id == dt.TypeId.DECIMAL128
        ):
            # dec128 mean needs the 128-bit->f64 decode of the summed
            # limbs (int128.to_float64); only the single-pass path has it
            return False
    return True


def groupby_aggregate_chunked(
    table: Table,
    by: Sequence[Union[int, str]],
    aggs: Sequence[GroupbyAgg],
    chunk_rows: int = 1 << 18,
    chunk_segments: Optional[int] = None,
) -> Optional[Table]:
    """Eager two-level groupby with exact output size, or ``None`` when
    chunking cannot win (cardinality too high — caller should use the
    single-pass path).

    Capacity protocol (the two-phase sizing discipline of the *_capped
    APIs, applied to cardinality instead of byte counts):
      1. probe chunk 0 at full capacity for its exact group count;
      2. size ``chunk_segments`` with 4x headroom, run all chunks;
      3. the returned max per-chunk count PROVES sufficiency; one
         doubling retry on overflow, else fall back.
    """
    from .copying import slice_rows

    n = table.row_count
    if n <= chunk_rows:
        return None
    if not chunked_groupby_supported(table, aggs):
        return None

    if chunk_segments is None:
        probe = slice_rows(table, 0, chunk_rows)
        _, g0 = groupby_aggregate_capped(
            probe, by, [GroupbyAgg(by[0], "count")],
            num_segments=chunk_rows,
        )
        g0 = int(g0)
        if g0 > chunk_rows // 4:
            return None  # near-distinct keys: chunking only adds passes
        chunk_segments = min(chunk_rows, _ceil_pow2(4 * g0 + 64))

    c = -(-n // chunk_rows)
    for _ in range(2):
        cap = min(c * chunk_segments, n)
        out, num_groups, max_chunk = _jit_capped_chunked(
            table, tuple(by), tuple(aggs), cap, chunk_rows, chunk_segments
        )
        if int(max_chunk) <= chunk_segments:
            g = int(num_groups)
            cols = [
                Column(
                    col.data[:g],
                    col.dtype,
                    None if col.validity is None else col.validity[:g],
                    None if col.lengths is None else col.lengths[:g],
                )
                for col in out.columns
            ]
            return Table(cols, out.names)
        if chunk_segments >= chunk_rows:
            break
        chunk_segments = min(chunk_rows, _ceil_pow2(int(max_chunk)))
    return None


def _jit_capped_chunked(table, by, aggs, num_segments, chunk_rows, chunk_segments):
    """One jitted dispatch for the whole two-level pipeline (compile
    cache keyed by the static args via jit's weak cache)."""
    fn = _capped_chunked_fn(by, aggs, num_segments, chunk_rows, chunk_segments)
    return fn(table)


@functools.lru_cache(maxsize=256)
def _capped_chunked_fn(by, aggs, num_segments, chunk_rows, chunk_segments):
    def fn(tbl):
        return groupby_aggregate_capped_chunked(
            tbl, list(by), list(aggs), num_segments,
            chunk_rows, chunk_segments,
        )

    return jax.jit(fn)
