"""Cumulative scans (cudf ``scan``: SUM/MIN/MAX/PRODUCT, inclusive or
exclusive, null-excluding).

Capability-surface row of SURVEY.md §2.3 (cudf Java suite covers
ColumnVector.scan). Null policy matches cudf EXCLUDE: null rows emit
null and do not contribute; the running aggregate carries past them —
expressed as a masked identity substitution before one ``associative_scan``,
which XLA lowers to a log-depth TPU scan.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..column import Column
from . import compute

_OPS = {
    "sum": jnp.add,
    "product": jnp.multiply,
    "min": jnp.minimum,
    "max": jnp.maximum,
}


def _identity_for(agg: str, dtype) -> object:
    if agg == "sum":
        return 0
    if agg == "product":
        return 1
    if jnp.issubdtype(dtype, jnp.bool_):
        return agg == "min"  # min identity True, max identity False
    info_fn = jnp.finfo if jnp.issubdtype(dtype, jnp.floating) else jnp.iinfo
    if agg == "min":
        return info_fn(dtype).max
    return info_fn(dtype).min


def scan(col: Column, agg: str, inclusive: bool = True) -> Column:
    """Running aggregate down the column. Output dtype == input dtype
    (cudf scan contract); null rows are excluded and stay null."""
    if agg not in _OPS:
        raise ValueError(f"unknown scan aggregation {agg!r}")
    vals = compute.values(col)
    ident = jnp.asarray(_identity_for(agg, vals.dtype), vals.dtype)
    if col.validity is not None:
        vals = jnp.where(col.validity, vals, ident)
    out = lax.associative_scan(_OPS[agg], vals)
    if not inclusive:
        # exclusive scan: shift right, seed with identity
        out = jnp.concatenate([ident[None], out[:-1]])
    return compute.from_values(out, col.dtype, col.validity)
