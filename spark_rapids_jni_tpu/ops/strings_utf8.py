"""UTF-8-aware string ops: char-level length/substring, case mapping.

Round-4 VERDICT item 9: the base string ops (ops/strings.py) are
byte/ASCII-level — correct for the bytes they see, but Spark's
``length``/``substring``/``upper`` count CHARACTERS and case-map the
whole Basic Multilingual Plane (cudf's string kernels are UTF-8 aware).
This module adds the UTF-8 tier over the same (n, pad) byte-matrix
representation, in the division of labor the engine uses everywhere:

  host    builds lookup tables once per process (a 64K-entry BMP case
          table from Python's own Unicode database — the analog of the
          host-compiled DFA in ops/regex.py),
  device  runs only fixed-shape vectorized passes: classify lead bytes,
          assemble codepoints with shifts/ors, gather through the
          table, re-emit bytes; a per-row cummax forward-fill gives
          every continuation byte its character's mapped codepoint.

Scope, stated where it binds (and pinned in tests):
* case mapping covers 1:1 mappings whose UTF-8 byte length is
  preserved — ASCII, Latin-1/Extended, Greek, Cyrillic, full-width
  forms. Length-CHANGING mappings (German ß -> SS, U+0130 dotted I)
  and supplementary-plane (4-byte) characters pass through unchanged;
  cudf shares the 1:1 restriction for its device kernels.
* inputs are assumed valid UTF-8 (what Spark hands the backend);
  malformed bytes pass through byte-identically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column
from .strings import _require_string, _shift_left


def _in_str(col: Column):
    n, pad = col.data.shape
    j = jnp.arange(pad)[None, :]
    return j < col.lengths[:, None]


def _is_char_start(col: Column):
    """Bytes that begin a character: everything but 0b10xxxxxx."""
    return ((col.data & 0xC0) != 0x80) & _in_str(col)


def char_length(col: Column) -> Column:
    """Character count (Spark ``length``; cudf ``count_characters``)."""
    _require_string(col)
    n = jnp.sum(_is_char_start(col), axis=1).astype(jnp.int32)
    return Column(n, dt.INT32, col.validity)


def utf8_substring(
    col: Column, start: int, length: int | None = None
) -> Column:
    """Character-indexed substring (0-based start; negative counts from
    the end, Python/Spark style). Continuation bytes travel with their
    character, so the kept byte range is contiguous and lands in one
    ``_shift_left`` pass."""
    _require_string(col)
    is_start = _is_char_start(col)
    in_str = _in_str(col)
    # char index of every byte (continuation bytes inherit theirs)
    char_idx = jnp.cumsum(is_start.astype(jnp.int32), axis=1) - 1
    total = jnp.sum(is_start, axis=1).astype(jnp.int32)
    if start < 0:
        s = jnp.maximum(total + start, 0)[:, None]
    else:
        s = jnp.full_like(total, start)[:, None]
    keep = in_str & (char_idx >= s)
    if length is not None:
        keep = keep & (char_idx < s + length)
    any_keep = jnp.any(keep, axis=1)
    first = jnp.where(any_keep, jnp.argmax(keep, axis=1), 0).astype(
        jnp.int32
    )
    new_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    return _shift_left(col, first, new_len)


@functools.lru_cache(maxsize=4)
def _bmp_case_table(upper: bool) -> np.ndarray:
    """(65536,) uint32: cp -> case-mapped cp, restricted to 1:1
    mappings that keep the UTF-8 byte length (so the device pass never
    reflows bytes). Built once from Python's Unicode tables."""

    def u8len(cp: int) -> int:
        if cp < 0x80:
            return 1
        if cp < 0x800:
            return 2
        return 3

    table = np.arange(0x10000, dtype=np.uint32)
    for cp in range(0x10000):
        if 0xD800 <= cp <= 0xDFFF:
            continue  # surrogates: not characters
        c = chr(cp)
        m = c.upper() if upper else c.lower()
        if len(m) == 1:
            mcp = ord(m)
            if mcp < 0x10000 and u8len(mcp) == u8len(cp):
                table[cp] = mcp
    return table


def _case_map_utf8(col: Column, upper: bool) -> Column:
    _require_string(col)
    mat = col.data.astype(jnp.int32)
    n, pad = mat.shape
    j = jnp.arange(pad)[None, :]
    in_str = _in_str(col)
    b = jnp.where(in_str, mat, 0)

    is1 = (b < 0x80) & in_str
    is2 = (b & 0xE0) == 0xC0
    is3 = (b & 0xF0) == 0xE0
    is4 = (b & 0xF8) == 0xF0
    is_start = is1 | is2 | is3 | is4

    def nxt(k):
        rolled = jnp.roll(b, -k, axis=1)
        # bytes rolled in from the row start are out of range anyway
        return jnp.where(j + k < pad, rolled, 0) & 0x3F

    cp = jnp.where(
        is1,
        b,
        jnp.where(
            is2,
            ((b & 0x1F) << 6) | nxt(1),
            ((b & 0x0F) << 12) | (nxt(1) << 6) | nxt(2),
        ),
    )
    table = jnp.asarray(_bmp_case_table(upper).astype(np.int32))
    mapped = table[jnp.clip(cp, 0, 0xFFFF)]

    # forward-fill each byte with its character's start position, then
    # gather that start's mapped codepoint + length class
    start_pos = jax.lax.cummax(
        jnp.where(is_start, j, -1), axis=1
    )
    safe = jnp.clip(start_pos, 0, pad - 1)
    my_mapped = jnp.take_along_axis(mapped, safe, axis=1)
    my_len = jnp.take_along_axis(
        jnp.where(is1, 1, jnp.where(is2, 2, jnp.where(is3, 3, 4))),
        safe,
        axis=1,
    )
    k = j - safe  # byte offset within the character

    out = jnp.where(
        my_len == 1,
        my_mapped,
        jnp.where(
            my_len == 2,
            jnp.where(
                k == 0,
                0xC0 | (my_mapped >> 6),
                0x80 | (my_mapped & 0x3F),
            ),
            jnp.where(
                my_len == 3,
                jnp.where(
                    k == 0,
                    0xE0 | (my_mapped >> 12),
                    jnp.where(
                        k == 1,
                        0x80 | ((my_mapped >> 6) & 0x3F),
                        0x80 | (my_mapped & 0x3F),
                    ),
                ),
                b,  # 4-byte chars pass through
            ),
        ),
    )
    # malformed leads (start_pos == -1 prefix) and padding keep original
    out = jnp.where((start_pos >= 0) & in_str, out, mat)
    return Column(
        out.astype(jnp.uint8), dt.STRING, col.validity, col.lengths
    )


def utf8_upper(col: Column) -> Column:
    """UTF-8 uppercase (cudf ``strings::to_upper`` device scope)."""
    return _case_map_utf8(col, True)


def utf8_lower(col: Column) -> Column:
    """UTF-8 lowercase."""
    return _case_map_utf8(col, False)
