"""Datetime field extraction and arithmetic (cudf ``datetime`` ops).

Capability-surface row of SURVEY.md §2.3: the vendored cudf Java suite
covers extract year/month/day/hour/minute/second/weekday, last-day-of-
month and day-of-year over TIMESTAMP_* columns. Timestamps store int64
ticks since the Unix epoch in the column's unit (TIMESTAMP_DAYS: int32
days). All field math is branch-free integer arithmetic (the civil-
calendar algorithms), so everything jits and vectorizes on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import dtype as dt
from ..column import Column

_TICKS_PER_DAY = {
    dt.TypeId.TIMESTAMP_DAYS: 1,
    dt.TypeId.TIMESTAMP_SECONDS: 86_400,
    dt.TypeId.TIMESTAMP_MILLISECONDS: 86_400_000,
    dt.TypeId.TIMESTAMP_MICROSECONDS: 86_400_000_000,
    dt.TypeId.TIMESTAMP_NANOSECONDS: 86_400_000_000_000,
}

_TICKS_PER_SECOND = {
    dt.TypeId.TIMESTAMP_SECONDS: 1,
    dt.TypeId.TIMESTAMP_MILLISECONDS: 1_000,
    dt.TypeId.TIMESTAMP_MICROSECONDS: 1_000_000,
    dt.TypeId.TIMESTAMP_NANOSECONDS: 1_000_000_000,
}


def _require_timestamp(col: Column):
    if col.dtype.id not in _TICKS_PER_DAY:
        raise TypeError(f"expected a timestamp column, got {col.dtype}")


def _days_and_seconds(col: Column):
    """(days since epoch, seconds within day) — floor semantics so
    pre-1970 instants land in the correct civil day."""
    ticks = col.data.astype(jnp.int64)
    per_day = _TICKS_PER_DAY[col.dtype.id]
    days = ticks // per_day
    if col.dtype.id == dt.TypeId.TIMESTAMP_DAYS:
        return days, jnp.zeros_like(days)
    per_sec = _TICKS_PER_SECOND[col.dtype.id]
    secs = (ticks - days * per_day) // per_sec
    return days, secs


def _civil_from_days(days):
    """days since 1970-01-01 -> (year, month, day), proleptic Gregorian.

    The classic branch-free era/day-of-era decomposition (public-domain
    civil-calendar math), expressed in int64 lax arithmetic.
    """
    z = days + 719_468
    era = jnp.where(z >= 0, z, z - 146_096) // 146_097
    doe = z - era * 146_097  # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36_524 - doe // 146_096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)  # [0, 365]
    mp = (5 * doy + 2) // 153  # [0, 11], March-based
    d = doy - (153 * mp + 2) // 5 + 1  # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)  # [1, 12]
    year = jnp.where(m <= 2, y + 1, y)
    return year, m, d


def _field(col: Column, fn) -> Column:
    _require_timestamp(col)
    days, secs = _days_and_seconds(col)
    out = fn(days, secs).astype(jnp.int16)
    return Column(out, dt.INT16, col.validity)


def year(col: Column) -> Column:
    return _field(col, lambda d, s: _civil_from_days(d)[0])


def month(col: Column) -> Column:
    return _field(col, lambda d, s: _civil_from_days(d)[1])


def day(col: Column) -> Column:
    return _field(col, lambda d, s: _civil_from_days(d)[2])


def hour(col: Column) -> Column:
    return _field(col, lambda d, s: s // 3600)


def minute(col: Column) -> Column:
    return _field(col, lambda d, s: (s // 60) % 60)


def second(col: Column) -> Column:
    return _field(col, lambda d, s: s % 60)


def weekday(col: Column) -> Column:
    """ISO day-of-week: Monday=1 .. Sunday=7 (cudf convention)."""
    # 1970-01-01 was a Thursday (ISO 4)
    return _field(col, lambda d, s: ((d + 3) % 7) + 1)


def day_of_year(col: Column) -> Column:
    def f(days, secs):
        y, m, d = _civil_from_days(days)
        jan1 = _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        return days - jan1 + 1

    return _field(col, f)


def _days_from_civil(y, m, d):
    """(year, month, day) -> days since epoch; inverse of
    _civil_from_days."""
    y_adj = jnp.where(m <= 2, y - 1, y)
    era = jnp.where(y_adj >= 0, y_adj, y_adj - 399) // 400
    yoe = y_adj - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146_097 + doe - 719_468


def last_day_of_month(col: Column) -> Column:
    """TIMESTAMP_DAYS column of each instant's month-end date."""
    _require_timestamp(col)
    days, _ = _days_and_seconds(col)
    y, m, _d = _civil_from_days(days)
    ny = jnp.where(m == 12, y + 1, y)
    nm = jnp.where(m == 12, jnp.ones_like(m), m + 1)
    first_next = _days_from_civil(ny, nm, jnp.ones_like(nm))
    out = (first_next - 1).astype(jnp.int32)
    return Column(out, dt.TIMESTAMP_DAYS, col.validity)


def add_calendrical_months(col: Column, months: Column | int) -> Column:
    """Shift by calendar months, clamping the day to the target month's
    length (cudf add_calendrical_months / Spark add_months)."""
    _require_timestamp(col)
    days, secs = _days_and_seconds(col)
    delta = months.data if isinstance(months, Column) else months
    y, m, d = _civil_from_days(days)
    total = y * 12 + (m - 1) + delta
    ny = total // 12
    nm = total % 12 + 1
    # clamp day to the length of the target month
    ny2 = jnp.where(nm == 12, ny + 1, ny)
    nm2 = jnp.where(nm == 12, jnp.ones_like(nm), nm + 1)
    month_len = _days_from_civil(ny2, nm2, jnp.ones_like(nm)) - _days_from_civil(
        ny, nm, jnp.ones_like(nm)
    )
    nd = jnp.minimum(d, month_len)
    out_days = _days_from_civil(ny, nm, nd)
    per_day = _TICKS_PER_DAY[col.dtype.id]
    ticks = out_days * per_day + (col.data.astype(jnp.int64) - days * per_day)
    out = ticks.astype(col.dtype.storage_dtype)
    valid = col.validity
    if isinstance(months, Column) and months.validity is not None:
        valid = (
            months.validity
            if valid is None
            else jnp.logical_and(valid, months.validity)
        )
    return Column(out, col.dtype, valid)


def quarter(col: Column) -> Column:
    """Quarter 1-4 (Spark ``quarter`` / cudf ``extract_quarter``)."""
    return _field(
        col,
        lambda days, secs: (_civil_from_days(days)[1] - 1) // 3 + 1,
    )


def truncate(col: Column, unit: str) -> Column:
    """Round the timestamp DOWN to the unit boundary (Spark
    ``date_trunc`` / cudf ``floor_temporal``). Units: year, quarter,
    month, week (ISO Monday), day, hour, minute, second. Result keeps
    the input timestamp type."""
    _require_timestamp(col)
    days, secs = _days_and_seconds(col)
    unit = unit.lower()
    if unit in ("year", "quarter", "month"):
        y, m, _ = _civil_from_days(days)
        if unit == "year":
            m_out = jnp.ones_like(m)
        elif unit == "quarter":
            m_out = ((m - 1) // 3) * 3 + 1
        else:
            m_out = m
        new_days = _days_from_civil(y, m_out, jnp.ones_like(m))
        new_secs = jnp.zeros_like(secs)
    elif unit == "week":
        # ISO week starts Monday; 1970-01-01 was a Thursday (weekday 3
        # with Monday=0)
        dow = (days + 3) % 7
        new_days = days - dow
        new_secs = jnp.zeros_like(secs)
    elif unit == "day":
        new_days, new_secs = days, jnp.zeros_like(secs)
    elif unit in ("hour", "minute", "second"):
        step = {"hour": 3600, "minute": 60, "second": 1}[unit]
        new_days = days
        new_secs = (secs // step) * step
    else:
        raise ValueError(f"date_trunc: unknown unit {unit!r}")
    per_day = _TICKS_PER_DAY[col.dtype.id]
    if col.dtype.id == dt.TypeId.TIMESTAMP_DAYS:
        ticks = new_days
    else:
        per_sec = _TICKS_PER_SECOND[col.dtype.id]
        ticks = new_days * per_day + new_secs * per_sec
    return Column(
        ticks.astype(col.data.dtype), col.dtype, col.validity
    )


def _subsecond_ticks(col: Column):
    """Ticks past the whole second (floor semantics), in the column's
    own resolution; zero for second/day resolutions."""
    _require_timestamp(col)
    per_sec = _TICKS_PER_SECOND.get(col.dtype.id, 1)
    if per_sec == 1:
        return jnp.zeros(col.data.shape, jnp.int64)
    ticks = col.data.astype(jnp.int64)
    secs = ticks // per_sec
    return ticks - secs * per_sec


def millisecond_fraction(col: Column) -> Column:
    """Milliseconds past the second, 0-999 (cudf
    ``extract_millisecond_fraction``)."""
    per_sec = _TICKS_PER_SECOND.get(col.dtype.id, 1)
    # sub-second ticks are already zero below millisecond resolution,
    # so the unconditional formula covers every unit
    out = _subsecond_ticks(col) * 1_000 // max(per_sec, 1_000)
    return Column(out.astype(jnp.int16), dt.INT16, col.validity)


def microsecond_fraction(col: Column) -> Column:
    """Microseconds within the millisecond, 0-999 (cudf
    ``extract_microsecond_fraction``)."""
    _require_timestamp(col)
    per_sec = _TICKS_PER_SECOND.get(col.dtype.id, 1)
    if per_sec < 1_000_000:
        out = jnp.zeros(col.data.shape, jnp.int16)
        return Column(out, dt.INT16, col.validity)
    us = _subsecond_ticks(col) * 1_000_000 // per_sec
    return Column(
        (us % 1_000).astype(jnp.int16), dt.INT16, col.validity
    )


def nanosecond_fraction(col: Column) -> Column:
    """Nanoseconds within the microsecond, 0-999 (cudf
    ``extract_nanosecond_fraction``)."""
    if col.dtype.id != dt.TypeId.TIMESTAMP_NANOSECONDS:
        _require_timestamp(col)
        return Column(
            jnp.zeros(col.data.shape, jnp.int16), dt.INT16, col.validity
        )
    ns = _subsecond_ticks(col)
    return Column(
        (ns % 1_000).astype(jnp.int16), dt.INT16, col.validity
    )


def day_of_week_sunday(col: Column) -> Column:
    """Spark ``dayofweek``: 1=Sunday .. 7=Saturday (vs ``weekday``'s
    ISO 1=Monday .. 7=Sunday)."""
    # 1970-01-01 was a Thursday: Sunday-based index 5 (Sun=1)
    return _field(col, lambda d, s: ((d + 4) % 7) + 1)
