"""Regular-expression ops over string columns: host-compiled DFA,
device-executed as table gathers under ``lax.scan``.

cudf ships a full regex engine in CUDA (``contains_re``, ``matches_re``,
``extract``, ``replace_re`` — part of the string surface exercised by the
vendored Java suite, SURVEY.md §2.3 string-ops row; Spark plans reach it
through ``rlike`` / ``regexp_extract`` / ``regexp_replace``). A
backtracking engine is hostile to XLA — per-row data-dependent control
flow — so the TPU design moves ALL regex analysis to the host and leaves
the device a branch-free automaton:

  host:   pattern → Thompson NFA → byte equivalence classes → dense
          (states × classes) DFA transition table (numpy, cached)
  device: ``lax.scan`` over the pad dimension of the (n, pad) string
          matrix; each step is one gather into the transition table —
          identical cost for every row, no data-dependent shapes.

Span queries (extract/replace) track one DFA instance per start offset:
the carry is an (n, pad) state matrix and every scan step advances all
starts at once, so the whole leftmost-longest span table costs ``pad``
steps of vectorized work instead of a per-row backtracking loop.

Supported syntax (byte-level, ASCII-oriented — a documented subset):
literals, ``.``, escapes (``\\n \\t \\r \\f \\v \\xHH`` + escaped
specials), ``[...]`` classes with ranges and negation, ``\\d \\D \\w
\\W \\s \\S``, alternation ``|``, groups ``(...)`` / ``(?:...)``,
quantifiers ``* + ? {m} {m,} {m,n}``, anchors ``^`` / ``$`` at the
pattern ends. Match semantics are leftmost-longest (POSIX), which agrees
with Java/Spark for the patterns plans generate; divergent corners
(e.g. ``(a|ab)`` alternation order) are pinned in tests.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import dtype as dt
from ..column import Column
from .strings import _require_string, _shift_left

_MAX_DFA_STATES = 1024
_MAX_COUNTED_REPEAT = 64


class UnsupportedPatternError(ValueError):
    """Pattern outside the engine's documented subset (or beyond its
    DFA-size budget). Typed so a Spark layer can catch it and fall back
    to CPU evaluation instead of failing the query — the posture cudf
    takes for its unsupported regex corners. Subclasses ValueError so
    existing raise-on-unsupported callers keep working."""

_DIGIT = frozenset(range(ord("0"), ord("9") + 1))
_WORD = frozenset(
    set(_DIGIT)
    | set(range(ord("a"), ord("z") + 1))
    | set(range(ord("A"), ord("Z") + 1))
    | {ord("_")}
)
_SPACE = frozenset(b" \t\n\r\f\v")
_ALL = frozenset(range(256))
_DOT = _ALL - {ord("\n")}
_SPECIALS = set("\\^$.|?*+()[]{}")


# ---------------------------------------------------------------------------
# AST: ('lit', charset) | ('cat', [nodes]) | ('alt', [nodes])
#      ('star'|'plus'|'opt', node) | ('group', node, index)
# ---------------------------------------------------------------------------


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.ngroups = 0

    def _peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def _take(self):
        c = self.p[self.i]
        self.i += 1
        return c

    def _error(self, msg):
        raise UnsupportedPatternError(
            f"regex: {msg} at position {self.i} in {self.p!r}"
        )

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            self._error(f"unexpected {self._peek()!r}")
        return node

    def _alt(self):
        branches = [self._cat()]
        while self._peek() == "|":
            self._take()
            branches.append(self._cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _cat(self):
        items = []
        while self._peek() not in (None, "|", ")"):
            items.append(self._repeat())
        if len(items) == 1:
            return items[0]
        return ("cat", items)

    def _repeat(self):
        node = self._atom()
        while True:
            c = self._peek()
            if c == "*":
                self._take()
                node = ("star", node)
            elif c == "+":
                self._take()
                node = ("plus", node)
            elif c == "?":
                self._take()
                node = ("opt", node)
            elif c == "{":
                node = self._counted(node)
            else:
                return node

    def _counted(self, node):
        self._take()  # '{'
        spec = ""
        while self._peek() not in (None, "}"):
            spec += self._take()
        if self._peek() != "}":
            self._error("unterminated {m,n}")
        self._take()
        parts = spec.split(",")
        try:
            lo = int(parts[0])
            if len(parts) == 1:
                hi = lo
            elif parts[1] == "":
                hi = None
            else:
                hi = int(parts[1])
        except ValueError:
            self._error(f"bad counted repeat {{{spec}}}")
        if hi is not None and (hi < lo or hi > _MAX_COUNTED_REPEAT):
            self._error(f"counted repeat bound must be <= {_MAX_COUNTED_REPEAT}")
        if lo > _MAX_COUNTED_REPEAT:
            self._error(f"counted repeat bound must be <= {_MAX_COUNTED_REPEAT}")
        items = [node] * lo
        if hi is None:
            items.append(("star", node))
        else:
            items.extend([("opt", node)] * (hi - lo))
        return ("cat", items)

    def _atom(self):
        c = self._peek()
        if c is None:
            self._error("expected atom")
        if c == "(":
            self._take()
            capturing = True
            if self.p[self.i : self.i + 2] == "?:":
                self.i += 2
                capturing = False
            inner = self._alt()
            if self._peek() != ")":
                self._error("unterminated group")
            self._take()
            if capturing:
                self.ngroups += 1
                return ("group", inner, self.ngroups)
            return inner
        if c == "[":
            return ("lit", self._char_class())
        if c == ".":
            self._take()
            return ("lit", _DOT)
        if c == "\\":
            return ("lit", self._escape())
        if c in "^$":
            self._error(f"anchor {c!r} only supported at the pattern ends")
        if c in "*+?{":
            self._error(f"quantifier {c!r} with nothing to repeat")
        self._take()
        return ("lit", frozenset({ord(c)}))

    def _escape(self) -> frozenset:
        self._take()  # backslash
        c = self._peek()
        if c is None:
            self._error("trailing backslash")
        self._take()
        simple = {"n": 10, "t": 9, "r": 13, "f": 12, "v": 11, "0": 0}
        if c in simple:
            return frozenset({simple[c]})
        if c == "x":
            hh = self.p[self.i : self.i + 2]
            if len(hh) != 2:
                self._error("bad \\xHH escape")
            self.i += 2
            return frozenset({int(hh, 16)})
        classes = {
            "d": _DIGIT, "D": _ALL - _DIGIT,
            "w": _WORD, "W": _ALL - _WORD,
            "s": _SPACE, "S": _ALL - _SPACE,
        }
        if c in classes:
            return classes[c]
        if c in _SPECIALS or not c.isalnum():
            return frozenset({ord(c)})
        self._error(f"unsupported escape \\{c}")

    def _char_class(self) -> frozenset:
        self._take()  # '['
        negate = False
        if self._peek() == "^":
            negate = True
            self._take()
        members: set = set()
        while True:
            c = self._peek()
            if c is None:
                self._error("unterminated character class")
            if c == "]":
                self._take()
                break
            if c == "\\":
                sub = self._escape()
                if len(sub) > 1:  # \d etc. — no range allowed off it
                    members |= sub
                    continue
                lo = next(iter(sub))
            else:
                self._take()
                lo = ord(c)
            if self._peek() == "-" and self.p[self.i + 1 : self.i + 2] not in (
                "", "]",
            ):
                self._take()  # '-'
                c2 = self._take()
                if c2 == "\\":
                    self.i -= 1
                    sub2 = self._escape()
                    if len(sub2) > 1:
                        self._error("bad range endpoint")
                    hi = next(iter(sub2))
                else:
                    hi = ord(c2)
                if hi < lo:
                    self._error("reversed character-class range")
                members |= set(range(lo, hi + 1))
            else:
                members.add(lo)
        return frozenset(_ALL - members if negate else members)


def _split_top_level(pattern: str) -> list[str]:
    """Split on ``|`` at nesting depth 0 (host-side, respecting escapes,
    groups and character classes) — how Java scopes anchors: in
    ``^a|b`` the ``^`` binds only the first branch."""
    branches = []
    depth = 0
    in_class = False
    cur = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "\\" and i + 1 < len(pattern):
            cur.append(pattern[i : i + 2])
            i += 2
            continue
        if in_class:
            if c == "]":
                in_class = False
        elif c == "[":
            in_class = True
        elif c == "(":
            depth += 1
        elif c == ")":
            depth = max(0, depth - 1)
        elif c == "|" and depth == 0:
            branches.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    branches.append("".join(cur))
    return branches


def _branch_anchored(branch: str) -> bool:
    if branch.startswith("^"):
        return True
    if branch.endswith("$"):
        nbs = len(branch[:-1]) - len(branch[:-1].rstrip("\\"))
        return nbs % 2 == 0
    return False


def _strip_anchors(pattern: str):
    """Peel ``^``/``$`` off the pattern ends (the only positions the
    subset supports; elsewhere the parser errors out)."""
    anchored_start = pattern.startswith("^")
    if anchored_start:
        pattern = pattern[1:]
    # '$' anchors only when not escaped: count trailing backslashes
    anchored_end = False
    if pattern.endswith("$"):
        nbs = len(pattern[:-1]) - len(pattern[:-1].rstrip("\\"))
        if nbs % 2 == 0:
            anchored_end = True
            pattern = pattern[:-1]
    return pattern, anchored_start, anchored_end


# ---------------------------------------------------------------------------
# Thompson NFA + subset construction over byte equivalence classes
# ---------------------------------------------------------------------------


class _NFA:
    def __init__(self):
        self.nstates = 0
        self.eps: list[set] = []
        self.trans: list[tuple[int, frozenset, int]] = []

    def state(self) -> int:
        self.eps.append(set())
        self.nstates += 1
        return self.nstates - 1

    def add(self, src, charset, dst):
        self.trans.append((src, charset, dst))

    def compile(self, node) -> tuple[int, int]:
        """Thompson fragment: returns (start, accept)."""
        kind = node[0]
        if kind == "lit":
            s, a = self.state(), self.state()
            self.add(s, node[1], a)
            return s, a
        if kind == "cat":
            if not node[1]:
                s = self.state()
                return s, s
            frags = [self.compile(ch) for ch in node[1]]
            for (_, a), (s2, _) in zip(frags, frags[1:]):
                self.eps[a].add(s2)
            return frags[0][0], frags[-1][1]
        if kind == "alt":
            s, a = self.state(), self.state()
            for branch in node[1]:
                bs, ba = self.compile(branch)
                self.eps[s].add(bs)
                self.eps[ba].add(a)
            return s, a
        if kind == "star":
            s, a = self.state(), self.state()
            bs, ba = self.compile(node[1])
            self.eps[s] |= {bs, a}
            self.eps[ba] |= {bs, a}
            return s, a
        if kind == "plus":
            bs, ba = self.compile(node[1])
            s, a = self.state(), self.state()
            self.eps[s].add(bs)
            self.eps[ba] |= {bs, a}
            return s, a
        if kind == "opt":
            s, a = self.state(), self.state()
            bs, ba = self.compile(node[1])
            self.eps[s] |= {bs, a}
            self.eps[ba].add(a)
            return s, a
        if kind == "group":
            return self.compile(node[1])
        raise AssertionError(f"unknown AST node {kind}")

    def closure(self, states: frozenset) -> frozenset:
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in self.eps[s]:
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)


@dataclasses.dataclass(frozen=True)
class CompiledRegex:
    """Dense DFA tables (host numpy; uploaded as constants at trace time)."""

    class_map: np.ndarray  # (256,) int32: byte -> equivalence class
    trans: np.ndarray      # (S, C) int32: state × class -> state
    accepting: np.ndarray  # (S,) bool
    anchored_start: bool
    anchored_end: bool
    # capture-group geometry for extract (None when not applicable)
    prefix_len: int | None = None
    suffix_len: int | None = None

    @property
    def num_classes(self) -> int:
        return self.trans.shape[1]


def _byte_classes(nfa: _NFA) -> np.ndarray:
    """Partition bytes by the set of NFA edges they can drive — the DFA
    only needs one column per class, keeping the table small."""
    sig = {}
    class_map = np.zeros(256, dtype=np.int32)
    for b in range(256):
        key = frozenset(
            i for i, (_, cs, _) in enumerate(nfa.trans) if b in cs
        )
        if key not in sig:
            sig[key] = len(sig)
        class_map[b] = sig[key]
    return class_map


def _determinize(nfa: _NFA, start: int, accept: int, class_map) -> tuple:
    nclasses = int(class_map.max()) + 1
    rep_byte = [int(np.argmax(class_map == c)) for c in range(nclasses)]
    start_set = nfa.closure(frozenset({start}))
    ids = {start_set: 0}
    rows = []
    work = [start_set]
    while work:
        cur = work.pop(0)
        row = []
        for c in range(nclasses):
            b = rep_byte[c]
            moved = frozenset(
                d for (s, cs, d) in nfa.trans if s in cur and b in cs
            )
            nxt = nfa.closure(moved) if moved else frozenset()
            if nxt not in ids:
                if len(ids) >= _MAX_DFA_STATES:
                    raise UnsupportedPatternError(
                        f"regex too complex: DFA exceeds {_MAX_DFA_STATES} states"
                    )
                ids[nxt] = len(ids)
                work.append(nxt)
            row.append(ids[nxt])
        rows.append(row)
    trans = np.asarray(rows, dtype=np.int32)
    accepting = np.zeros(len(ids), dtype=bool)
    for sset, i in ids.items():
        accepting[i] = accept in sset
    return trans, accepting


def _node_len_range(node) -> tuple[int, float]:
    kind = node[0]
    if kind == "lit":
        return 1, 1
    if kind == "cat":
        lo = hi = 0
        for ch in node[1]:
            l, h = _node_len_range(ch)
            lo, hi = lo + l, hi + h
        return lo, hi
    if kind == "alt":
        ranges = [_node_len_range(b) for b in node[1]]
        return min(r[0] for r in ranges), max(r[1] for r in ranges)
    if kind == "star":
        return 0, float("inf")
    if kind == "plus":
        return _node_len_range(node[1])[0], float("inf")
    if kind == "opt":
        return 0, _node_len_range(node[1])[1]
    if kind == "group":
        return _node_len_range(node[1])
    raise AssertionError(kind)


def _group_geometry(node):
    """For extract: the pattern must be a concatenation containing exactly
    one capture group, with fixed-length prefix and suffix around it (the
    shape of practical ``regexp_extract`` patterns like ``id=(\\d+)``).
    Returns (prefix_len, suffix_len) or raises."""
    items = node[1] if node[0] == "cat" else [node]
    gidx = [i for i, it in enumerate(items) if it[0] == "group"]
    if len(gidx) != 1:
        raise UnsupportedPatternError(
            "extract_re: pattern must contain exactly one capture group"
        )
    g = gidx[0]
    pre_lo, pre_hi = _node_len_range(("cat", items[:g]))
    suf_lo, suf_hi = _node_len_range(("cat", items[g + 1 :]))
    if pre_lo != pre_hi or suf_lo != suf_hi:
        raise UnsupportedPatternError(
            "extract_re: text before/after the capture group must have a "
            "fixed match length (use {m} instead of open quantifiers there)"
        )
    return int(pre_lo), int(suf_lo)


@functools.lru_cache(maxsize=256)
def compile_re(
    pattern: str, *, search_prefix: bool = False, with_group: bool = False
) -> CompiledRegex:
    """Compile to DFA tables. ``search_prefix`` prepends an implicit
    ``.*`` (any byte, including newline) for find-anywhere semantics;
    ``with_group`` additionally computes extract geometry."""
    body, anch_s, anch_e = _strip_anchors(pattern)
    parser = _Parser(body)
    ast = parser.parse()
    if (anch_s or anch_e) and ast[0] == "alt":
        # '^a|b' must NOT become '^(a|b)': Java/Spark scope anchors to
        # one branch (ADVICE r3). contains_re/matches_re split branches
        # before reaching here; span ops (extract/replace) surface the
        # typed error instead of silently changing match semantics.
        raise UnsupportedPatternError(
            "anchor over a top-level alternation: in Java the anchor "
            "binds one branch, which the single-DFA span engine cannot "
            "express — split the pattern into per-branch calls"
        )
    pre = suf = None
    if with_group:
        pre, suf = _group_geometry(ast)
    if search_prefix and not anch_s:
        ast = ("cat", [("star", ("lit", _ALL)), ast])
    nfa = _NFA()
    start, accept = nfa.compile(ast)
    class_map = _byte_classes(nfa)
    trans, accepting = _determinize(nfa, start, accept, class_map)
    return CompiledRegex(
        class_map=class_map,
        trans=trans,
        accepting=accepting,
        anchored_start=anch_s,
        anchored_end=anch_e,
        prefix_len=pre,
        suffix_len=suf,
    )


# ---------------------------------------------------------------------------
# Device execution
# ---------------------------------------------------------------------------


def _dfa_tables(rx: CompiledRegex):
    return (
        jnp.asarray(rx.class_map),
        jnp.asarray(rx.trans.reshape(-1)),
        jnp.asarray(rx.accepting),
        rx.trans.shape[1],
    )


def contains_re(col: Column, pattern: str) -> Column:
    """True where the pattern matches anywhere in the string — Spark
    ``rlike`` / cudf ``strings::contains_re``. One DFA state per row,
    ``pad`` scan steps of one gather each.

    Anchored top-level alternations (``^a|b``, ``a$|^b``) evaluate one
    DFA per branch and OR the results — the anchor binds its own branch
    only, matching Java (``re.search('^a|b', 'zb')`` is True)."""
    _require_string(col)
    branches = _split_top_level(pattern)
    if len(branches) > 1 and any(_branch_anchored(b) for b in branches):
        out = contains_re(col, branches[0])
        for b in branches[1:]:
            nxt = contains_re(col, b)
            out = Column(out.data | nxt.data, dt.BOOL8, col.validity)
        return out
    rx = compile_re(pattern, search_prefix=True)
    cmap, tflat, acc, C = _dfa_tables(rx)
    n, pad = col.data.shape
    lens = col.lengths

    def step(carry, x):
        state, found = carry
        j, byte_col = x
        nxt = tflat[state * C + cmap[byte_col]]
        live = j < lens
        state = jnp.where(live, nxt, state)
        found = found | (acc[state] & live)
        return (state, found), None

    state0 = jnp.zeros((n,), jnp.int32)
    found0 = jnp.broadcast_to(acc[0], (n,))
    (state, found), _ = lax.scan(
        step, (state0, found0), (jnp.arange(pad), col.data.T)
    )
    if rx.anchored_end:
        # the match must end exactly at the string end: only the final
        # state (after consuming all len bytes) counts
        found = acc[state]
    return Column(found, dt.BOOL8, col.validity)


def matches_re(col: Column, pattern: str) -> Column:
    """Anchored full-string match — cudf ``strings::matches_re`` (Java
    ``String.matches``): the whole string must match the pattern. A
    top-level alternation full-matches if ANY branch full-matches
    (``"a".matches("^a|b")`` is True in Java), so each branch gets its
    own ``^...$`` wrap rather than one ambiguous concatenation."""
    _require_string(col)
    branches = _split_top_level(pattern)
    if len(branches) > 1:
        out = matches_re(col, branches[0])
        for b in branches[1:]:
            nxt = matches_re(col, b)
            out = Column(out.data | nxt.data, dt.BOOL8, col.validity)
        return out
    body, _, _ = _strip_anchors(pattern)
    return contains_re(col, "^" + body + "$")


def rlike(col: Column, pattern: str) -> Column:
    """Spark SQL ``rlike`` alias of :func:`contains_re`."""
    return contains_re(col, pattern)


def _span_table(col: Column, rx: CompiledRegex):
    """best_end[i, s] = largest e with a match over bytes [s, e) of row i
    (leftmost-longest span table), or -1. Carry is an (n, pad) state
    matrix: every scan step advances ALL start offsets at once."""
    cmap, tflat, acc, C = _dfa_tables(rx)
    n, pad = col.data.shape
    lens = col.lengths
    starts = jnp.arange(pad)[None, :]

    # empty-width matches: pattern accepts at the start offset itself
    empty_ok = jnp.broadcast_to(acc[0], (n, pad)) & (starts <= lens[:, None])
    if rx.anchored_end:
        empty_ok = empty_ok & (starts == lens[:, None])
    best0 = jnp.where(empty_ok, starts, -1).astype(jnp.int32)
    if rx.anchored_start:
        best0 = jnp.where(starts == 0, best0, -1)

    def step(carry, x):
        states, best = carry
        j, byte_col = x
        cls = cmap[byte_col]  # (n,)
        nxt = tflat[states * C + cls[:, None]]
        live = (starts <= j) & (j < lens[:, None])
        states = jnp.where(live, nxt, states)
        hit = live & acc[states]
        if rx.anchored_end:
            hit = hit & (j + 1 == lens[:, None])
        best = jnp.where(hit, (j + 1).astype(jnp.int32), best)
        return (states, best), None

    states0 = jnp.zeros((n, pad), jnp.int32)
    (_, best), _ = lax.scan(
        step, (states0, best0), (jnp.arange(pad), col.data.T)
    )
    if rx.anchored_start:
        best = jnp.where(starts == 0, best, -1)
    return best


def find_re(col: Column, pattern: str) -> Column:
    """Byte offset of the leftmost match, -1 when absent (cudf
    ``strings::find_re``)."""
    _require_string(col)
    rx = compile_re(pattern)
    best = _span_table(col, rx)
    has = jnp.any(best >= 0, axis=1)
    pos = jnp.argmax(best >= 0, axis=1).astype(jnp.int32)
    return Column(jnp.where(has, pos, -1), dt.INT32, col.validity)


def extract_re(col: Column, pattern: str) -> Column:
    """Contents of the single capture group at the leftmost-longest match
    (cudf ``strings::extract``; Spark ``regexp_extract(s, p, 1)``). Rows
    with no match are null (the cudf convention). The group must sit
    between fixed-length prefix/suffix regexes — the shape of practical
    extract patterns; open-ended context raises."""
    _require_string(col)
    rx = compile_re(pattern, with_group=True)
    best = _span_table(col, rx)
    n, pad = col.data.shape
    has = jnp.any(best >= 0, axis=1)
    s_star = jnp.argmax(best >= 0, axis=1).astype(jnp.int32)
    e_star = jnp.take_along_axis(best, s_star[:, None], axis=1)[:, 0]
    gs = s_star + rx.prefix_len
    glen = jnp.maximum(e_star - rx.suffix_len - gs, 0)
    glen = jnp.where(has, glen, 0).astype(jnp.int32)
    out = _shift_left(col, gs.astype(jnp.int32), glen)
    validity = has if col.validity is None else (col.validity & has)
    return Column(out.data, dt.STRING, validity, out.lengths)


def replace_re(col: Column, pattern: str, repl: str | bytes) -> Column:
    """Replace every non-overlapping leftmost-longest match with the
    literal ``repl`` (cudf ``strings::replace_re``; Spark
    ``regexp_replace`` sans backreferences). Empty-width matches are
    skipped. Eager (cudf call model): the output pad width comes from the
    realized lengths, which costs one device sync."""
    _require_string(col)
    if isinstance(repl, str):
        repl = repl.encode("utf-8", "surrogateescape")
    m = len(repl)
    rx = compile_re(pattern)
    best = _span_table(col, rx)
    n, pad = col.data.shape
    lens = col.lengths

    # greedy leftmost non-overlapping selection: walk starts ascending;
    # in_match[t] falls out of the same carry (cursor > t ⟺ t inside a
    # selected span)
    def select(carry, x):
        cursor = carry
        s, ends_col = x
        can = (s >= cursor) & (ends_col > s)
        cursor = jnp.where(can, ends_col, cursor)
        return cursor, (can, cursor > s)

    _, (is_start_T, in_match_T) = lax.scan(
        select,
        jnp.zeros((n,), jnp.int32),
        (jnp.arange(pad), best.T),
    )
    is_start = is_start_T.T  # (n, pad)
    in_match = in_match_T.T
    j = jnp.arange(pad)[None, :]
    in_str = j < lens[:, None]
    copied = in_str & ~in_match
    starts_i32 = is_start.astype(jnp.int32)
    copied_i32 = copied.astype(jnp.int32)
    starts_before = jnp.cumsum(starts_i32, axis=1) - starts_i32
    copied_before = jnp.cumsum(copied_i32, axis=1) - copied_i32
    out_pos = copied_before + m * starts_before

    n_matches = jnp.sum(starts_i32, axis=1)
    dropped = jnp.sum((in_match & in_str).astype(jnp.int32), axis=1)
    new_len = (lens - dropped + m * n_matches).astype(jnp.int32)

    if n == 0:
        return Column(col.data, dt.STRING, col.validity, col.lengths)
    pad_out = max(int(np.asarray(jnp.max(new_len))), 1)  # eager sync
    rows = jnp.arange(n)[:, None]
    dump = pad_out  # out-of-range scatter target, sliced off below
    out = jnp.zeros((n, pad_out + 1), jnp.uint8)
    idx = jnp.where(copied, jnp.minimum(out_pos, dump), dump)
    out = out.at[rows, idx].set(jnp.where(copied, col.data, 0))
    for k in range(m):
        idx_k = jnp.where(is_start, jnp.minimum(out_pos + k, dump), dump)
        out = out.at[rows, idx_k].set(
            jnp.where(is_start, jnp.uint8(repl[k]), 0)
        )
    data = out[:, :pad_out]
    data = jnp.where(jnp.arange(pad_out)[None, :] < new_len[:, None], data, 0)
    return Column(data.astype(jnp.uint8), dt.STRING, col.validity, new_len)


def count_re(col: Column, pattern: str) -> Column:
    """Number of non-overlapping matches per row (cudf
    ``strings::count_re``). Empty-width matches are not counted."""
    _require_string(col)
    rx = compile_re(pattern)
    best = _span_table(col, rx)
    n, pad = col.data.shape

    def select(cursor, x):
        s, ends_col = x
        can = (s >= cursor) & (ends_col > s)
        cursor = jnp.where(can, ends_col, cursor)
        return cursor, can

    _, is_start_T = lax.scan(
        select, jnp.zeros((n,), jnp.int32), (jnp.arange(pad), best.T)
    )
    counts = jnp.sum(is_start_T.astype(jnp.int32), axis=0)
    return Column(counts, dt.INT32, col.validity)
