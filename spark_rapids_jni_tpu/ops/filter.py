"""Filter (cudf ``apply_boolean_mask``) in the two-phase discipline.

XLA needs static shapes, so a data-dependent filter comes in two forms
(SURVEY.md §7 hard part 5 — generalizing the reference's two-phase 2 GB
batching at row_conversion.cu:505-511):

* ``filter_table`` — eager: host-sync the surviving count, return an
  exactly-sized table (the cudf/JNI call model).
* ``filter_table_capped`` — jittable: caller supplies a static capacity;
  returns a padded table + device row count. Selected rows are compacted
  to the front with a stable cumsum+gather (no scatter conflicts — the
  TPU-friendly replacement for CUDA stream compaction).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..column import Column, Table
from . import compute
from .gather import gather_table


def _selection_mask(mask: Column) -> jax.Array:
    """Spark WHERE keeps rows where the predicate is TRUE (not null)."""
    if not mask.dtype.is_boolean:
        raise TypeError("filter mask must be BOOL8")
    keep = mask.data
    if mask.validity is not None:
        keep = jnp.logical_and(keep, mask.validity)
    return keep


def _compaction_indices(keep: jax.Array, capacity: int):
    """Stable indices of kept rows, padded to ``capacity``."""
    n = keep.shape[0]
    # positions[i] = output slot of row i (exclusive cumsum of keep)
    slots = jnp.cumsum(keep) - keep.astype(jnp.int32)
    count = jnp.sum(keep).astype(jnp.int32)
    # inverse permutation via scatter of row ids into their slots
    idx = jnp.zeros((capacity,), dtype=jnp.int32)
    row_ids = jnp.arange(n, dtype=jnp.int32)
    idx = idx.at[jnp.where(keep, slots, capacity)].set(row_ids, mode="drop")
    return idx, count


def filter_table_capped(
    table: Table, mask: Column, capacity: int
) -> tuple[Table, jax.Array]:
    """Jittable filter: (padded table of ``capacity`` rows, device count).

    Rows past the count are clones of kept rows (garbage but in-bounds);
    consumers must respect the count.
    """
    keep = _selection_mask(mask)
    idx, count = _compaction_indices(keep, capacity)
    return gather_table(table, idx), count


def filter_table(table: Table, mask: Column) -> Table:
    """Eager filter with exact output size (one host sync for the count)."""
    keep = _selection_mask(mask)
    count = int(jnp.sum(keep))
    if count == table.row_count:
        return table
    idx, _ = _compaction_indices(keep, max(count, 1))
    out = gather_table(table, idx[:count] if count else idx[:0])
    return out
