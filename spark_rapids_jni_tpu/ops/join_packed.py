"""Packed-key batched inner join — the narrow-key fast path.

The batched join (ops/join.py inner_join_batched) sorts the build side
over (occupancy word, key order word) with a separate permutation iota
riding the sort, then probes with a hand-rolled multi-word lexicographic
binary search. When the integer-family keys' combined VALUE RANGES fit
in ``64 - log2(build_rows)`` bits — every dictionary-coded, date, or
sequential-id join key, alone or composed (the q64 shape joins on
(item_sk, ticket_number): two narrow fields) — the same trick as the
packed groupby (ops/groupby_packed.py) collapses all of it into one
u64 word::

    build:  sorted = lax.sort( (key - kmin) << bits | build_iota )   # ONE array
    perm:   sorted & mask                                            # free
    probe:  lo = searchsorted(sorted, rel_q << bits,        'left')
            hi = searchsorted(sorted, rel_q << bits | mask, 'right')

What this buys over the general path:

* the build sort carries ONE u64 operand instead of two u64 words plus
  an int32 iota (8 B/row vs 20) — and the permutation needs no gather,
  it is the low bits of the sorted word;
* the probe is ``jnp.searchsorted`` over one word (XLA's native binary
  search) instead of the fori-loop lexicographic search over word lists;
* probe keys below/above the build range wrap or clamp harmlessly:
  ``rel`` is computed against the GLOBAL min of both sides and the fit
  check covers the global span, so every query is in-range by
  construction and unmatched keys get ``lo == hi`` (count 0).

Expansion and output assembly reuse the shared machinery (``_expand`` /
``_join_output``), so semantics — row order, schema, null handling — are
identical to ``inner_join_batched``; this module only changes how the
match ranges are found. Eligibility is decided EAGERLY (one min/max
reduction per side); ineligible shapes return ``None`` and callers fall
back to the general batched path. The fused-graph XLA fault fence is
irrelevant here: every graph this module builds is (sort-one-word) or
(searchsorted + expand), both known-safe shapes.

Reference parity: cudf's mixed/hash join specializations pick cheaper
kernels for simple key types (hash_join.cu type dispatch); this is the
sort-based machine's version of the same specialization.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .keys import minmax_host as _minmax
from ..column import Table
from . import keys as keys_mod
from .groupby_packed import _key_supported
from .join import _join_output


def packed_join_supported(
    left: Table, right: Table, on: Sequence, right_on: Sequence
) -> bool:
    """Every key pair integer-family and no-null on both sides —
    multi-key pairs pack as composite bit fields (the q64 shape joins
    on (item_sk, ticket_number))."""
    if not on or len(on) != len(right_on):
        return False
    return all(
        _key_supported(left.column(lk)) and _key_supported(right.column(rk))
        for lk, rk in zip(on, right_on)
    )


def _composite(kws, kmins, field_bits):
    """Composite relative word over parallel key-word arrays (shared
    kmins across both join sides; fields validated by the caller)."""
    return keys_mod.fold_fields(
        [kw - kmin for kw, kmin in zip(kws, kmins)], field_bits
    )


@functools.lru_cache(maxsize=64)
def _build_fn(bits: int, field_bits: tuple):
    mask = jnp.uint64((1 << bits) - 1)

    def fn(kws_r, kmins):
        m = kws_r[0].shape[0]
        rel = _composite(kws_r, kmins, field_bits)
        iota = jnp.arange(m, dtype=jnp.uint64)
        (sorted_packed,) = jax.lax.sort(
            ((rel << jnp.uint64(bits)) | iota,), num_keys=1
        )
        # permutation extracted ONCE here (the low bits), not per probe
        # chunk — matching the general path's prep/materialize split
        return sorted_packed, (sorted_packed & mask).astype(jnp.int32)

    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _probe_fn(bits: int, field_bits: tuple):
    mask = jnp.uint64((1 << bits) - 1)

    def fn(sorted_packed, kws_chunk, kmins):
        base = _composite(kws_chunk, kmins, field_bits) << jnp.uint64(
            bits
        )
        lo = jnp.searchsorted(
            sorted_packed, base, side="left"
        ).astype(jnp.int32)
        hi = jnp.searchsorted(
            sorted_packed, base | mask, side="right"
        ).astype(jnp.int32)
        counts = hi - lo
        return lo, counts, jnp.sum(counts)

    return jax.jit(fn)


def inner_join_batched_packed(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    right_on: Optional[Sequence[Union[int, str]]] = None,
    probe_rows: Optional[int] = None,
) -> Optional[Table]:
    """Eager batched inner join via the packed formulation, or ``None``
    when the shape is ineligible / the key span does not fit (callers
    fall back to :func:`ops.join.inner_join_batched`).

    ``probe_rows`` defaults to the live fault fence
    (``join.FUSED_PROBE_MAX_ROWS``) so tuning the fence moves this path
    with it. Oversized chunk outputs re-split like the general batched
    path (heavy-hitter keys must not materialize an HBM-breaking padded
    output in one graph)."""
    from collections import deque

    from ..utils import hbm
    from . import join as join_mod
    from .copying import concatenate, slice_rows

    right_on = right_on or on
    if probe_rows is not None and probe_rows <= 0:
        # a config error, not an eligibility decision (same eager
        # validation as inner_join_batches)
        raise ValueError(f"probe_rows must be positive, got {probe_rows}")
    if not packed_join_supported(left, right, on, right_on):
        return None
    n, m = left.row_count, right.row_count
    if n == 0 or m == 0:
        return None
    bits = max(1, (m - 1).bit_length())
    kws_l = [
        keys_mod.column_order_keys(left.column(k))[0] for k in on
    ]
    kws_r = [
        keys_mod.column_order_keys(right.column(k))[0] for k in right_on
    ]
    kmins = []
    field_bits = []
    for kl, kr in zip(kws_l, kws_r):
        lo_l, hi_l = _minmax(kl)
        lo_r, hi_r = _minmax(kr)
        kmin = min(lo_l, lo_r)
        kmins.append(kmin)
        field_bits.append(
            max(1, (max(hi_l, hi_r) - kmin).bit_length())
        )
    if sum(field_bits) + bits > 64:
        # no sentinel here (unlike the groupby's padding slot): the
        # full 64 bits are usable
        return None
    field_bits = tuple(field_bits)
    kmins_dev = jnp.asarray(kmins, dtype=jnp.uint64)
    if probe_rows is None:
        # HBM-budget chunk sizing with THIS path's resident set — the
        # general plan models a 20 B/build-row word+perm set, but the
        # packed build holds one u64 + an int32 perm (12 B/row); sized
        # here, AFTER eligibility, so ineligible joins neither pay the
        # plan nor double-warn on fallback
        budget = hbm.budget_bytes()
        nk = len(on)
        fixed = (
            hbm.table_bytes(left) + hbm.table_bytes(right)
            + 12 * m          # packed build word + int32 perm
            + 8 * nk * (n + m)  # both sides' key-word arrays, live
        )
        out_row = hbm.row_bytes(left) + hbm.row_bytes(right)
        per_probe_row = hbm.row_bytes(left) + 8 + 2 * out_row
        avail = budget - fixed
        if avail <= 0:
            import warnings

            warnings.warn(
                "join inputs exceed the HBM budget before any probe "
                f"chunk ({fixed} fixed vs {budget} budget); expect "
                "allocator pressure. Raise SPARK_RAPIDS_TPU_HBM_"
                "BUDGET_GB if the chip really has more.",
                stacklevel=2,
            )
        probe_rows = min(
            join_mod.FUSED_PROBE_MAX_ROWS,
            max(1024, avail // max(per_probe_row, 1)),
        )

    sorted_packed, perm_r = _build_fn(bits, field_bits)(
        tuple(kws_r), kmins_dev
    )
    probe = _probe_fn(bits, field_bits)
    out_row_bytes = hbm.row_bytes(left) + hbm.row_bytes(right)
    chunk_out_budget = max(
        probe_rows * 2 * out_row_bytes, join_mod.MIN_CHUNK_OUT_BYTES
    )
    pieces = []
    spans = deque(
        (s, min(s + probe_rows, n)) for s in range(0, n, probe_rows)
    )
    while spans:
        start, stop = spans.popleft()
        lo, counts, total_dev = probe(
            sorted_packed,
            tuple(kw[start:stop] for kw in kws_l),
            kmins_dev,
        )
        total = int(total_dev)
        if total == 0:
            continue
        cap = max(32, 1 << (total - 1).bit_length())
        if cap * out_row_bytes > chunk_out_budget and stop - start > 1024:
            mid = (start + stop) // 2
            spans.appendleft((mid, stop))
            spans.appendleft((start, mid))
            continue
        chunk = slice_rows(left, start, stop)
        padded = join_mod._batched_materialize_fn(tuple(right_on), cap)(
            perm_r, lo, counts, chunk, right
        )
        pieces.append(slice_rows(padded, 0, total))
    if not pieces:
        # zero matches: the empty joined schema, built directly
        z = jnp.zeros((0,), jnp.int32)
        return _join_output(
            slice_rows(left, 0, 0), right, list(right_on), z, z, None,
            None,
        )
    return concatenate(pieces) if len(pieces) > 1 else pieces[0]


