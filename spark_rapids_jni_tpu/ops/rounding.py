"""Rounding (cudf ``round``: HALF_UP / HALF_EVEN) for numeric and
decimal columns.

Capability-surface row of SURVEY.md §2.3 (the vendored cudf Java test
suite exercises ``Table.round``/``ColumnVector.round``). Decimal columns
round on the unscaled integer representation — exact, no float detour —
matching Spark's Decimal semantics; floats scale/round/unscale in f64.
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import dtype as dt
from ..column import Column
from . import compute

HALF_UP = "half_up"
HALF_EVEN = "half_even"


def _round_half_up(vals, scale):
    # half away from zero (Spark/cudf HALF_UP), not floor(x+0.5)
    scaled = vals * scale
    return jnp.where(
        scaled >= 0, jnp.floor(scaled + 0.5), jnp.ceil(scaled - 0.5)
    ) / scale


def _round_half_even(vals, scale):
    # jnp.round implements banker's rounding
    return jnp.round(vals * scale) / scale


def _round_unscaled(unscaled, shift, how):
    """Round integer ``unscaled`` to a multiple of 10**shift (shift>0),
    exactly, in integer arithmetic."""
    p = jnp.asarray(10, unscaled.dtype) ** shift
    q = unscaled // p  # floor division
    r = unscaled - q * p  # remainder in [0, p)
    if how == HALF_UP:
        # half away from zero. r is the floor-division remainder, so for
        # negatives the tie (2r == p) must stay at q (the more-negative
        # floor) while positives move up.
        up = jnp.where(unscaled >= 0, r * 2 >= p, r * 2 > p)
    else:
        tie = r * 2 == p
        up = jnp.where(tie, q % 2 != 0, r * 2 > p)
    return (q + up.astype(unscaled.dtype)) * p


def round_column(
    col: Column, decimal_places: int = 0, how: str = HALF_UP
) -> Column:
    """Round to ``decimal_places`` (negative = powers of ten left of the
    point). Output dtype: unchanged for floats/ints; decimals keep their
    scale (cudf round keeps the column type, adjusting only values)."""
    if how not in (HALF_UP, HALF_EVEN):
        raise ValueError(f"unknown rounding mode {how!r}")
    d = col.dtype
    if d.is_decimal:
        # value = unscaled * 10^scale; rounding at decimal_places means
        # zeroing digits below 10^(-decimal_places)
        shift = -decimal_places - d.scale
        if shift <= 0:
            return col  # already coarser than requested
        out = _round_unscaled(col.data, shift, how)
        return Column(out, d, col.validity)
    if d.is_floating:
        vals = compute.values(col)
        scale = 10.0 ** decimal_places
        fn = _round_half_up if how == HALF_UP else _round_half_even
        return compute.from_values(fn(vals, scale), d, col.validity)
    if d.is_integer:
        if decimal_places >= 0:
            return col
        shift = -decimal_places
        out = _round_unscaled(col.data, shift, how)
        return Column(out, d, col.validity)
    raise TypeError(f"round: unsupported dtype {d}")
