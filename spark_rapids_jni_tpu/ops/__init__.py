"""Columnar operator library — the capability surface of the cudf pin.

Each module is the TPU-native equivalent of a cudf kernel family the
reference artifact ships (SURVEY.md §2.3 table): ops lower to XLA where
jnp can express them and to Pallas kernels (kernels/) where it can't.
Data-dependent result sizes (filter/join/groupby) come in two flavors,
mirroring the reference's two-phase 2GB batching discipline
(row_conversion.cu:505-511):

* eager APIs that host-sync the exact size (the cudf/JNI call model), and
* ``*_capped`` jittable variants with caller-fixed capacity + a device
  row count, for whole-query fusion under jit/shard_map.

Two scale disciplines sit above the per-op level (round 4):

* ``*_chunked`` / ``*_batches`` forms split giant inputs into
  VMEM-/fault-sized pieces automatically (groupby_chunked.py, the
  join's chunk-probed paths) — the batching the reference applies at
  INT_MAX bytes, applied at TPU limits; and
* the HBM footprint planner (utils/hbm.py) sizes those pieces from a
  per-chip budget instead of constants.
"""

from . import compute, keys
from .binaryop import binary_op, add, sub, mul, div, eq, ne, lt, le, gt, ge
from .unaryop import unary_op, is_null, is_not_null
from .cast import cast
from .reductions import reduce as reduce_column
from .reductions import arg_extreme, extreme_by
from .filter import filter_table, filter_table_capped
from .gather import gather_table, gather_column
from .sort import sort_table, argsort_table, SortKey, is_sorted, merge_sorted
from .hashing import murmur3_column, murmur3_table
from .groupby import groupby_aggregate, GroupbyAgg
from .groupby_chunked import (
    groupby_aggregate_chunked,
    groupby_aggregate_capped_chunked,
)
from .join import (
    inner_join,
    inner_join_batched,
    inner_join_batches,
    left_join,
    left_join_capped,
    left_join_count,
    membership_mask,
    right_join,
    full_join,
    semi_join,
    anti_join,
)
from .partition import hash_partition, round_robin_partition
from .rounding import round_column
from . import datetime, replace, rounding
from .copying import (
    concatenate,
    concatenate_columns,
    interleave_columns,
    copy_if_else,
    sequence,
    cross_join,
    repeat,
    scatter,
    slice_rows,
    split,
    sample,
)
from .replace import (
    replace_nulls,
    replace_nulls_policy,
    nans_to_nulls,
    find_and_replace,
    clamp,
)
from .search import lower_bound, upper_bound, contains_column
from .scan import scan
from .compaction import distinct, distinct_capped, distinct_count, drop_nulls
from . import window
from .window import (
    rolling_aggregate,
    grouped_rolling_aggregate,
    grouped_range_rolling_aggregate,
    lead,
    lag,
    row_number,
    rank,
    dense_rank,
    percent_rank,
    ntile,
)
from .quantiles import quantile
from . import lists, regex
from .lists import (
    count_elements,
    explode,
    split_explode,
    explode_outer,
    explode_position,
    extract_list_element,
    list_contains,
)
from .regex import (
    contains_re,
    matches_re,
    rlike,
    find_re,
    extract_re,
    replace_re,
    count_re,
)

__all__ = [
    "compute",
    "keys",
    "binary_op",
    "add",
    "sub",
    "mul",
    "div",
    "eq",
    "ne",
    "lt",
    "le",
    "gt",
    "ge",
    "unary_op",
    "is_null",
    "is_not_null",
    "cast",
    "reduce_column",
    "arg_extreme",
    "extreme_by",
    "filter_table",
    "filter_table_capped",
    "gather_table",
    "gather_column",
    "sort_table",
    "argsort_table",
    "SortKey",
    "is_sorted",
    "merge_sorted",
    "murmur3_column",
    "murmur3_table",
    "groupby_aggregate",
    "groupby_aggregate_chunked",
    "groupby_aggregate_capped_chunked",
    "inner_join_batches",
    "GroupbyAgg",
    "inner_join",
    "inner_join_batched",
    "left_join",
    "left_join_capped",
    "left_join_count",
    "membership_mask",
    "right_join",
    "full_join",
    "semi_join",
    "anti_join",
    "hash_partition",
    "round_robin_partition",
    "round_column",
    "datetime",
    "concatenate",
    "concatenate_columns",
    "interleave_columns",
    "copy_if_else",
    "sequence",
    "cross_join",
    "repeat",
    "scatter",
    "slice_rows",
    "split",
    "sample",
    "replace_nulls",
    "replace_nulls_policy",
    "nans_to_nulls",
    "find_and_replace",
    "clamp",
    "lower_bound",
    "upper_bound",
    "contains_column",
    "scan",
    "distinct",
    "distinct_capped",
    "distinct_count",
    "drop_nulls",
    "window",
    "rolling_aggregate",
    "grouped_rolling_aggregate",
    "grouped_range_rolling_aggregate",
    "lead",
    "lag",
    "row_number",
    "rank",
    "dense_rank",
    "percent_rank",
    "ntile",
    "quantile",
    "lists",
    "count_elements",
    "explode",
    "split_explode",
    "explode_outer",
    "explode_position",
    "extract_list_element",
    "list_contains",
    "regex",
    "contains_re",
    "matches_re",
    "rlike",
    "find_re",
    "extract_re",
    "replace_re",
    "count_re",
]
