"""String operations over the padded byte-matrix layout.

cudf strings are (offsets, chars) variable-width columns; under XLA's
static-shape regime strings live as an (n, pad) uint8 matrix + lengths
(SURVEY.md §7 hard part 2 — padding instead of offsets). All ops below are
plain vectorized byte arithmetic, so they fuse like any other elementwise
op; pad width is a compile-time constant per column.

ASCII-oriented where case matters (upper/lower), byte-exact elsewhere —
matching Spark's behavior for ASCII data; full UTF-8 case mapping is a
later phase.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column
from . import compute
from . import keys as keys_mod


def _require_string(col: Column):
    if not col.dtype.is_string:
        raise TypeError("expected a STRING column")


def length(col: Column) -> Column:
    """Byte length (Spark ``length`` counts chars; equal for ASCII)."""
    _require_string(col)
    return Column(col.lengths.astype(jnp.int32), dt.INT32, col.validity)


def _case_map(col: Column, to_upper: bool) -> Column:
    _require_string(col)
    mat = col.data
    if to_upper:
        shift = ((mat >= ord("a")) & (mat <= ord("z"))).astype(jnp.uint8) * 32
        out = mat - shift
    else:
        shift = ((mat >= ord("A")) & (mat <= ord("Z"))).astype(jnp.uint8) * 32
        out = mat + shift
    return Column(out, dt.STRING, col.validity, col.lengths)


def upper(col: Column) -> Column:
    return _case_map(col, True)


def lower(col: Column) -> Column:
    return _case_map(col, False)


def _literal_bytes(pat: str | bytes) -> np.ndarray:
    if isinstance(pat, str):
        pat = pat.encode("utf-8", "surrogateescape")
    return np.frombuffer(pat, dtype=np.uint8)


def _window_matches(col: Column, pat: np.ndarray) -> list[jax.Array]:
    """match[start] = (n,) bool: the literal ``pat`` occurs at byte
    ``start`` fully inside the string. The one sliding-window scan that
    contains/find/replace all build on — static pad width makes it a
    fixed unrolled compare."""
    m = len(pat)
    n, pad = col.data.shape
    patv = jnp.asarray(pat)
    out = []
    for start in range(pad - m + 1):
        window_eq = jnp.all(
            col.data[:, start : start + m] == patv[None, :], axis=1
        )
        out.append(window_eq & (col.lengths >= start + m))
    return out


def contains(col: Column, pattern: str | bytes) -> Column:
    """Literal substring search (Spark ``contains``)."""
    _require_string(col)
    pat = _literal_bytes(pattern)
    n, pad = col.data.shape
    if len(pat) == 0:
        return Column(jnp.ones((n,), jnp.bool_), dt.BOOL8, col.validity)
    if len(pat) > pad:
        return Column(jnp.zeros((n,), jnp.bool_), dt.BOOL8, col.validity)
    found = jnp.zeros((n,), dtype=jnp.bool_)
    for hit in _window_matches(col, pat):
        found = found | hit
    return Column(found, dt.BOOL8, col.validity)


def starts_with(col: Column, pattern: str | bytes) -> Column:
    _require_string(col)
    pat = _literal_bytes(pattern)
    m = len(pat)
    n, pad = col.data.shape
    if m == 0:
        return Column(jnp.ones((n,), jnp.bool_), dt.BOOL8, col.validity)
    if m > pad:
        return Column(jnp.zeros((n,), jnp.bool_), dt.BOOL8, col.validity)
    ok = jnp.all(col.data[:, :m] == jnp.asarray(pat)[None, :], axis=1) & (
        col.lengths >= m
    )
    return Column(ok, dt.BOOL8, col.validity)


def ends_with(col: Column, pattern: str | bytes) -> Column:
    _require_string(col)
    pat = _literal_bytes(pattern)
    m = len(pat)
    n, pad = col.data.shape
    if m == 0:
        return Column(jnp.ones((n,), jnp.bool_), dt.BOOL8, col.validity)
    if m > pad:
        return Column(jnp.zeros((n,), jnp.bool_), dt.BOOL8, col.validity)
    # gather the tail window [len-m, len) per row
    starts = jnp.clip(col.lengths - m, 0, pad - m)
    idx = starts[:, None] + jnp.arange(m)[None, :]
    tail = jnp.take_along_axis(col.data, idx, axis=1)
    ok = jnp.all(tail == jnp.asarray(pat)[None, :], axis=1) & (col.lengths >= m)
    return Column(ok, dt.BOOL8, col.validity)


def substring(col: Column, start: int, slice_len: int) -> Column:
    """0-based substring with fixed start/length (Spark ``substring``)."""
    _require_string(col)
    n, pad = col.data.shape
    out_pad = max(min(slice_len, pad), 1)
    shifted = jnp.roll(col.data, -start, axis=1)
    out = shifted[:, :out_pad]
    # zero bytes past the new length
    new_len = jnp.clip(col.lengths - start, 0, slice_len)
    mask = jnp.arange(out_pad)[None, :] < new_len[:, None]
    out = jnp.where(mask, out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, new_len.astype(jnp.int32))


def concat(a: Column, b: Column) -> Column:
    """Rowwise concatenation (Spark ``concat``: null if either null)."""
    _require_string(a)
    _require_string(b)
    n, pad_a = a.data.shape
    _, pad_b = b.data.shape
    out_pad = pad_a + pad_b
    out = jnp.zeros((n, out_pad), dtype=jnp.uint8)
    out = out.at[:, :pad_a].set(a.data)
    # place b at offset len(a) via gather: out[i, j] = b[i, j - len_a[i]]
    j = jnp.arange(out_pad)[None, :]
    src = j - a.lengths[:, None]
    valid_src = (src >= 0) & (src < pad_b)
    b_g = jnp.take_along_axis(
        b.data, jnp.clip(src, 0, pad_b - 1), axis=1
    )
    out = jnp.where(valid_src & (j >= a.lengths[:, None]), b_g, out).astype(
        jnp.uint8
    )
    new_len = a.lengths + b.lengths
    # zero past length (b's pad garbage)
    out = jnp.where(j < new_len[:, None], out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, compute.merge_validity(a, b), new_len)


def repad(col: Column, pad: int) -> Column:
    """Return the column with a different pad width (>= max length)."""
    _require_string(col)
    n, old = col.data.shape
    if pad == old:
        return col
    if pad > old:
        out = jnp.zeros((n, pad), dtype=jnp.uint8).at[:, :old].set(col.data)
    else:
        out = jnp.where(
            jnp.arange(pad)[None, :] < col.lengths[:, None], col.data[:, :pad], 0
        ).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, col.lengths)


def binary_op(op: str, a: Column, b: Column) -> Column:
    """String comparisons dispatch through order keys (memcmp order)."""
    _require_string(a)
    _require_string(b)
    common = max(a.data.shape[1], b.data.shape[1])
    a = repad(a, common)
    b = repad(b, common)
    aw = keys_mod.column_order_keys(a)
    bw = keys_mod.column_order_keys(b)
    eq_w = jnp.ones((a.data.shape[0],), dtype=jnp.bool_)
    lt_w = jnp.zeros((a.data.shape[0],), dtype=jnp.bool_)
    for x, y in zip(aw, bw):
        lt_w = lt_w | (eq_w & (x < y))
        eq_w = eq_w & (x == y)
    valid = compute.merge_validity(a, b)
    table = {
        "eq": eq_w,
        "ne": ~eq_w,
        "lt": lt_w,
        "le": lt_w | eq_w,
        "gt": ~(lt_w | eq_w),
        "ge": ~lt_w,
    }
    if op == "add":  # Spark || / concat
        return concat(a, b)
    if op not in table:
        raise TypeError(f"binary op {op!r} not supported for strings")
    return Column(table[op], dt.BOOL8, valid)


def cast(col: Column, to: dt.DType) -> Column:
    raise NotImplementedError(
        "string casts land with the format/parse phase"
    )


def _shift_left(col: Column, shift: jax.Array, new_len: jax.Array) -> Column:
    """Row-wise left shift by a per-row amount, zeroing past new_len."""
    n, pad = col.data.shape
    j = jnp.arange(pad)[None, :]
    src = jnp.clip(j + shift[:, None], 0, pad - 1)
    out = jnp.take_along_axis(col.data, src, axis=1)
    out = jnp.where(j < new_len[:, None], out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, new_len.astype(jnp.int32))


def _strip_counts(col: Column, chars: bytes, from_left: bool):
    """Count of strip-set bytes at the left (or right) edge of each row."""
    n, pad = col.data.shape
    in_set = jnp.zeros((n, pad), dtype=jnp.bool_)
    for ch in chars:
        in_set = in_set | (col.data == ch)
    j = jnp.arange(pad)[None, :]
    in_str = j < col.lengths[:, None]
    if from_left:
        # leading run length: first position that is in-string and not
        # in the strip set
        boundary = in_str & ~in_set
        has = jnp.any(boundary, axis=1)
        first = jnp.argmax(boundary, axis=1)
        return jnp.where(has, first, col.lengths)
    # trailing run: scan from the right
    boundary = in_str & ~in_set
    has = jnp.any(boundary, axis=1)
    last = pad - 1 - jnp.argmax(boundary[:, ::-1], axis=1)
    return jnp.where(has, col.lengths - last - 1, col.lengths)


def strip(col: Column, chars: str | bytes = b" ") -> Column:
    """Trim the byte set from both ends. Default trims only the space
    byte — Spark ``trim`` semantics (pass explicit chars for python-str
    whitespace stripping)."""
    _require_string(col)
    cset = chars.encode() if isinstance(chars, str) else bytes(chars)
    left = _strip_counts(col, cset, True)
    right = _strip_counts(col, cset, False)
    new_len = jnp.maximum(col.lengths - left - right, 0)
    return _shift_left(col, left, new_len)


def lstrip(col: Column, chars: str | bytes = b" ") -> Column:
    """Spark ``ltrim`` (space-only default)."""
    _require_string(col)
    cset = chars.encode() if isinstance(chars, str) else bytes(chars)
    left = _strip_counts(col, cset, True)
    return _shift_left(col, left, col.lengths - left)


def rstrip(col: Column, chars: str | bytes = b" ") -> Column:
    """Spark ``rtrim`` (space-only default)."""
    _require_string(col)
    cset = chars.encode() if isinstance(chars, str) else bytes(chars)
    right = _strip_counts(col, cset, False)
    new_len = col.lengths - right
    return _shift_left(col, jnp.zeros_like(col.lengths), new_len)


def find(col: Column, pattern: str | bytes) -> Column:
    """First byte index of the literal pattern, -1 if absent (Spark
    ``instr`` is this + 1)."""
    _require_string(col)
    pat = _literal_bytes(pattern)
    m = len(pat)
    n, pad_w = col.data.shape
    if m == 0:
        return Column(jnp.zeros((n,), jnp.int32), dt.INT32, col.validity)
    pos = jnp.full((n,), -1, dtype=jnp.int32)
    if m <= pad_w:
        matches = _window_matches(col, pat)
        for start in range(len(matches) - 1, -1, -1):  # right-to-left keeps first
            pos = jnp.where(matches[start], start, pos)
    return Column(pos, dt.INT32, col.validity)


def pad(col: Column, width: int, side: str = "right", fill: str = " ") -> Column:
    """Spark ``lpad``/``rpad``: result is EXACTLY ``width`` bytes — padded
    with the (possibly multi-byte) ``fill`` pattern when shorter,
    truncated to the leading ``width`` bytes when longer."""
    _require_string(col)
    fill_b = _literal_bytes(fill)
    if len(fill_b) == 0:
        raise ValueError("pad: fill must be non-empty")
    if side not in ("left", "right"):
        raise ValueError("side must be 'left' or 'right'")
    n, old = col.data.shape
    c = repad(col, max(old, width))
    out_pad = c.data.shape[1]
    j = jnp.arange(out_pad)[None, :]
    fillv = jnp.asarray(fill_b)
    m = len(fill_b)
    s_len = jnp.minimum(c.lengths, width)  # truncation bound
    if side == "right":
        fill_idx = (j - c.lengths[:, None]) % m
        data = jnp.where(
            j < s_len[:, None], c.data, fillv[fill_idx]
        )
    else:
        shift = jnp.maximum(width - c.lengths, 0)
        src = jnp.clip(j - shift[:, None], 0, out_pad - 1)
        moved = jnp.take_along_axis(c.data, src, axis=1)
        data = jnp.where(j < shift[:, None], fillv[j % m], moved)
    new_len = jnp.full((n,), width, jnp.int32)
    data = jnp.where(j < new_len[:, None], data, 0)
    out = Column(
        data.astype(jnp.uint8), dt.STRING, c.validity, new_len
    )
    return repad(out, max(width, 1))


def replace(col: Column, old: str | bytes, new: str | bytes) -> Column:
    """Literal, non-overlapping, leftmost-first replacement (Spark
    ``replace``). Equal-width substitutions stay fully on device; width-
    changing substitutions rebuild the column (eager host path, the cudf
    call model)."""
    _require_string(col)
    old_b = _literal_bytes(old)
    new_b = _literal_bytes(new)
    m = len(old_b)
    if m == 0:
        return col
    n, pad_w = col.data.shape
    if len(new_b) == m and m <= pad_w:
        # device path: greedy non-overlapping match selection, then an
        # unrolled masked substitution of one rolled pattern row
        match = _window_matches(col, old_b)
        base_row = jnp.zeros((pad_w,), jnp.uint8).at[:m].set(
            jnp.asarray(new_b)
        )
        j = jnp.arange(pad_w)[None, :]
        data = col.data
        next_free = jnp.zeros((n,), jnp.int32)
        for start in range(pad_w - m + 1):
            sel = match[start] & (next_free <= start)
            in_window = (j >= start) & (j < start + m)
            data = jnp.where(
                sel[:, None] & in_window,
                jnp.roll(base_row, start)[None, :],
                data,
            )
            next_free = jnp.where(sel, start + m, next_free)
        return Column(data.astype(jnp.uint8), dt.STRING, col.validity, col.lengths)
    # host path for width-changing substitutions
    out = [
        None if v is None else v.replace(
            old if isinstance(old, str) else old.decode("utf-8", "surrogateescape"),
            new if isinstance(new, str) else new.decode("utf-8", "surrogateescape"),
        )
        for v in col.to_pylist()
    ]
    return Column.from_strings(out)


def split_get(col: Column, delimiter: str | bytes, index: int) -> Column:
    """k-th field after splitting on a single-byte delimiter (Spark
    ``split_part`` with 0-based index); empty string when out of range."""
    _require_string(col)
    d = _literal_bytes(delimiter)
    if len(d) != 1:
        raise ValueError("split_get: single-byte delimiter only")
    n, pad_w = col.data.shape
    j = jnp.arange(pad_w)[None, :]
    in_str = j < col.lengths[:, None]
    is_delim = (col.data == d[0]) & in_str
    # field id of each byte = number of delimiters before it
    field = jnp.cumsum(is_delim.astype(jnp.int32), axis=1) - is_delim.astype(
        jnp.int32
    )
    keep = in_str & ~is_delim & (field == index)
    tok_len = jnp.sum(keep, axis=1)
    # start = first kept position (or 0)
    has = jnp.any(keep, axis=1)
    start = jnp.where(has, jnp.argmax(keep, axis=1), 0)
    return _shift_left(
        Column(col.data, dt.STRING, col.validity, col.lengths),
        start.astype(jnp.int32),
        tok_len.astype(jnp.int32),
    )


def reverse(col: Column) -> Column:
    """Byte-wise reversal (Spark ``reverse``; char-exact for ASCII)."""
    _require_string(col)
    n, pad_w = col.data.shape
    j = jnp.arange(pad_w)[None, :]
    src = jnp.clip(col.lengths[:, None] - 1 - j, 0, pad_w - 1)
    out = jnp.take_along_axis(col.data, src, axis=1)
    out = jnp.where(j < col.lengths[:, None], out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, col.lengths)
