"""String operations over the padded byte-matrix layout.

cudf strings are (offsets, chars) variable-width columns; under XLA's
static-shape regime strings live as an (n, pad) uint8 matrix + lengths
(SURVEY.md §7 hard part 2 — padding instead of offsets). All ops below are
plain vectorized byte arithmetic, so they fuse like any other elementwise
op; pad width is a compile-time constant per column.

ASCII-oriented where case matters (upper/lower), byte-exact elsewhere —
matching Spark's behavior for ASCII data; full UTF-8 case mapping is a
later phase.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column
from . import compute
from . import keys as keys_mod


def _require_string(col: Column):
    if not col.dtype.is_string:
        raise TypeError("expected a STRING column")


def length(col: Column) -> Column:
    """Byte length (Spark ``length`` counts chars; equal for ASCII)."""
    _require_string(col)
    return Column(col.lengths.astype(jnp.int32), dt.INT32, col.validity)


def _case_map(col: Column, to_upper: bool) -> Column:
    _require_string(col)
    mat = col.data
    if to_upper:
        shift = ((mat >= ord("a")) & (mat <= ord("z"))).astype(jnp.uint8) * 32
        out = mat - shift
    else:
        shift = ((mat >= ord("A")) & (mat <= ord("Z"))).astype(jnp.uint8) * 32
        out = mat + shift
    return Column(out, dt.STRING, col.validity, col.lengths)


def upper(col: Column) -> Column:
    return _case_map(col, True)


def lower(col: Column) -> Column:
    return _case_map(col, False)


def _literal_bytes(pat: str | bytes) -> np.ndarray:
    if isinstance(pat, str):
        pat = pat.encode("utf-8", "surrogateescape")
    return np.frombuffer(pat, dtype=np.uint8)


def _match_ends(col: Column, pat: np.ndarray) -> jax.Array:
    """(n, pad) bool: the literal ``pat`` (1..64 bytes) ends at byte j
    (occupying [j-m+1, j]) fully inside the string.

    Shift-or (bitap) under one ``lax.scan`` over the pad dimension: the
    carry is one uint64 running-match bitset per row and each step is a
    256-entry table gather + two bitops — O(n·pad) total work and O(1)
    graph size, replacing the unrolled per-start window compares that
    emitted O(pad) slices of O(m) compares each (round-3 VERDICT: at
    pad 128-256 those were huge HLO graphs and compile times)."""
    from jax import lax

    m = len(pat)
    n, pad = col.data.shape
    table = np.zeros(256, dtype=np.uint64)
    for i, b in enumerate(pat):
        table[int(b)] |= np.uint64(1) << np.uint64(i)
    tab = jnp.asarray(table)
    hit_bit = jnp.uint64(1) << jnp.uint64(m - 1)

    def step(state, byte_col):
        state = ((state << jnp.uint64(1)) | jnp.uint64(1)) & tab[byte_col]
        return state, (state & hit_bit) != 0

    _, hits = lax.scan(step, jnp.zeros((n,), jnp.uint64), col.data.T)
    ends = hits.T  # (n, pad)
    # zero pad bytes could fake-extend a match past the string end (a
    # pattern containing NUL), so bound ends by the real length
    j = jnp.arange(pad)[None, :]
    return ends & (j < col.lengths[:, None])


_BITAP_MAX = 64  # one uint64 bitset per row


def _window_matches(col: Column, pat: np.ndarray) -> list[jax.Array]:
    """match[start] = (n,) bool: the literal ``pat`` occurs at byte
    ``start`` fully inside the string. Patterns up to 64 bytes ride the
    shift-or scan (one pass); longer ones fall back to unrolled window
    compares."""
    m = len(pat)
    n, pad = col.data.shape
    if 1 <= m <= _BITAP_MAX:
        ends = _match_ends(col, pat)
        return [ends[:, s + m - 1] for s in range(pad - m + 1)]
    patv = jnp.asarray(pat)
    out = []
    for start in range(pad - m + 1):
        window_eq = jnp.all(
            col.data[:, start : start + m] == patv[None, :], axis=1
        )
        out.append(window_eq & (col.lengths >= start + m))
    return out


def contains(col: Column, pattern: str | bytes) -> Column:
    """Literal substring search (Spark ``contains``)."""
    _require_string(col)
    pat = _literal_bytes(pattern)
    n, pad = col.data.shape
    if len(pat) == 0:
        return Column(jnp.ones((n,), jnp.bool_), dt.BOOL8, col.validity)
    if len(pat) > pad:
        return Column(jnp.zeros((n,), jnp.bool_), dt.BOOL8, col.validity)
    if len(pat) <= _BITAP_MAX:
        found = jnp.any(_match_ends(col, pat), axis=1)
        return Column(found, dt.BOOL8, col.validity)
    found = jnp.zeros((n,), dtype=jnp.bool_)
    for hit in _window_matches(col, pat):
        found = found | hit
    return Column(found, dt.BOOL8, col.validity)


def starts_with(col: Column, pattern: str | bytes) -> Column:
    _require_string(col)
    pat = _literal_bytes(pattern)
    m = len(pat)
    n, pad = col.data.shape
    if m == 0:
        return Column(jnp.ones((n,), jnp.bool_), dt.BOOL8, col.validity)
    if m > pad:
        return Column(jnp.zeros((n,), jnp.bool_), dt.BOOL8, col.validity)
    ok = jnp.all(col.data[:, :m] == jnp.asarray(pat)[None, :], axis=1) & (
        col.lengths >= m
    )
    return Column(ok, dt.BOOL8, col.validity)


def ends_with(col: Column, pattern: str | bytes) -> Column:
    _require_string(col)
    pat = _literal_bytes(pattern)
    m = len(pat)
    n, pad = col.data.shape
    if m == 0:
        return Column(jnp.ones((n,), jnp.bool_), dt.BOOL8, col.validity)
    if m > pad:
        return Column(jnp.zeros((n,), jnp.bool_), dt.BOOL8, col.validity)
    # gather the tail window [len-m, len) per row
    starts = jnp.clip(col.lengths - m, 0, pad - m)
    idx = starts[:, None] + jnp.arange(m)[None, :]
    tail = jnp.take_along_axis(col.data, idx, axis=1)
    ok = jnp.all(tail == jnp.asarray(pat)[None, :], axis=1) & (col.lengths >= m)
    return Column(ok, dt.BOOL8, col.validity)


def substring(col: Column, start: int, slice_len: int) -> Column:
    """0-based substring with fixed start/length (Spark ``substring``)."""
    _require_string(col)
    n, pad = col.data.shape
    out_pad = max(min(slice_len, pad), 1)
    shifted = jnp.roll(col.data, -start, axis=1)
    out = shifted[:, :out_pad]
    # zero bytes past the new length
    new_len = jnp.clip(col.lengths - start, 0, slice_len)
    mask = jnp.arange(out_pad)[None, :] < new_len[:, None]
    out = jnp.where(mask, out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, new_len.astype(jnp.int32))


def concat(a: Column, b: Column) -> Column:
    """Rowwise concatenation (Spark ``concat``: null if either null)."""
    _require_string(a)
    _require_string(b)
    n, pad_a = a.data.shape
    _, pad_b = b.data.shape
    out_pad = pad_a + pad_b
    out = jnp.zeros((n, out_pad), dtype=jnp.uint8)
    out = out.at[:, :pad_a].set(a.data)
    # place b at offset len(a) via gather: out[i, j] = b[i, j - len_a[i]]
    j = jnp.arange(out_pad)[None, :]
    src = j - a.lengths[:, None]
    valid_src = (src >= 0) & (src < pad_b)
    b_g = jnp.take_along_axis(
        b.data, jnp.clip(src, 0, pad_b - 1), axis=1
    )
    out = jnp.where(valid_src & (j >= a.lengths[:, None]), b_g, out).astype(
        jnp.uint8
    )
    new_len = a.lengths + b.lengths
    # zero past length (b's pad garbage)
    out = jnp.where(j < new_len[:, None], out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, compute.merge_validity(a, b), new_len)


def repad(col: Column, pad: int) -> Column:
    """Return the column with a different pad width (>= max length)."""
    _require_string(col)
    n, old = col.data.shape
    if pad == old:
        return col
    if pad > old:
        out = jnp.zeros((n, pad), dtype=jnp.uint8).at[:, :old].set(col.data)
    else:
        out = jnp.where(
            jnp.arange(pad)[None, :] < col.lengths[:, None], col.data[:, :pad], 0
        ).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, col.lengths)


def binary_op(op: str, a: Column, b: Column) -> Column:
    """String comparisons dispatch through order keys (memcmp order)."""
    _require_string(a)
    _require_string(b)
    common = max(a.data.shape[1], b.data.shape[1])
    a = repad(a, common)
    b = repad(b, common)
    aw = keys_mod.column_order_keys(a)
    bw = keys_mod.column_order_keys(b)
    eq_w = jnp.ones((a.data.shape[0],), dtype=jnp.bool_)
    lt_w = jnp.zeros((a.data.shape[0],), dtype=jnp.bool_)
    for x, y in zip(aw, bw):
        lt_w = lt_w | (eq_w & (x < y))
        eq_w = eq_w & (x == y)
    valid = compute.merge_validity(a, b)
    table = {
        "eq": eq_w,
        "ne": ~eq_w,
        "lt": lt_w,
        "le": lt_w | eq_w,
        "gt": ~(lt_w | eq_w),
        "ge": ~lt_w,
    }
    if op == "add":  # Spark || / concat
        return concat(a, b)
    if op not in table:
        raise TypeError(f"binary op {op!r} not supported for strings")
    return Column(table[op], dt.BOOL8, valid)


def cast(col: Column, to: dt.DType) -> Column:
    """Spark CAST between STRING and other types (round-3 VERDICT item 8).

    string -> int/float/bool/decimal parse fully on device (vectorized
    byte arithmetic over the padded matrix, floats through the
    Eisel-Lemire core; unparseable rows become null, the Spark
    non-ANSI contract). EVERY format direction is device-resident too:
    ints/bools via the digit matrix, floats via the vectorized Ryu
    core (ops/ryu.py), decimals of all widths and scales via the
    u64/base-10^9 digit extraction. ``_format_host`` remains only as
    the test oracle.
    """
    if col.dtype.is_string and to.is_string:
        return col
    if col.dtype.is_string:
        if to.is_boolean:
            return _parse_bool(col)
        if to.is_integer:
            return _parse_int(col, to)
        if to.is_floating:
            return _parse_float(col, to)
        if to.is_decimal:
            return _parse_decimal(col, to)
        raise TypeError(f"cast STRING -> {to} not supported")
    if to.is_string:
        if col.dtype.is_boolean:
            return _format_bool(col)
        if col.dtype.is_integer:
            return _format_int(col)
        if col.dtype.is_decimal:
            # every decimal formats on device: DECIMAL32/64 through
            # the u64 digit matrix, DECIMAL128 through the base-10^9
            # limb long division; any scale (negative inserts the
            # point, positive appends zeros)
            return _format_decimal(col)
        if col.dtype.id in (dt.TypeId.FLOAT32, dt.TypeId.FLOAT64):
            # device Ryu (ops/ryu.py): shortest round-trip digits +
            # Java Double.toString placement, no host round-trip
            return _format_float(col)
        raise TypeError(f"cast {col.dtype} -> STRING not supported")
    raise TypeError(f"not a string cast: {col.dtype} -> {to}")


_WS = b" \t\r\n\x0b\x0c"


def _parse_parts(col: Column):
    """Shared scanner: whitespace-trimmed sign/digits/dot/exponent
    decomposition of every row. Returns a dict of (n,)/(n, pad) arrays
    consumed by the typed parsers."""
    c = strip(col, _WS)
    mat, lens = c.data, c.lengths
    n, pad = mat.shape
    j = jnp.arange(pad)[None, :]
    in_str = j < lens[:, None]
    first = mat[:, 0]
    neg = (first == ord("-")) & (lens > 0)
    has_sign = neg | ((first == ord("+")) & (lens > 0))
    start = has_sign.astype(jnp.int32)
    isdigit = (mat >= ord("0")) & (mat <= ord("9")) & in_str
    isdot = (mat == ord(".")) & in_str
    is_e = ((mat == ord("e")) | (mat == ord("E"))) & in_str
    ndots = jnp.sum(isdot, axis=1)
    nes = jnp.sum(is_e, axis=1)
    dotpos = jnp.where(ndots > 0, jnp.argmax(isdot, axis=1), lens)
    epos = jnp.where(nes > 0, jnp.argmax(is_e, axis=1), lens)
    return {
        "col": c, "mat": mat, "lens": lens, "j": j, "in_str": in_str,
        "neg": neg, "start": start, "isdigit": isdigit,
        "isdot": isdot, "is_e": is_e, "ndots": ndots, "nes": nes,
        "dotpos": dotpos, "epos": epos, "pad": pad, "n": n,
    }


def _weighted_int(digits_mask, mat, max_digits=18):
    """Value of the masked digit run as int64 (digits read left to
    right; (n,) overflow flag).

    Overflow counts SIGNIFICANT digits (after leading zeros — leading
    zeros contribute 0 to the value, so their out-of-clip weights are
    harmless). 19 significant digits are accepted when the int64 sum
    did not wrap (result >= 10^18); the INT64_MIN magnitude is the one
    representable 19-digit value this rejects (conservatively null)."""
    cum = jnp.cumsum(digits_mask.astype(jnp.int32), axis=1)
    total = cum[:, -1:]
    rank = total - cum  # digits to the right of this one, within the run
    w = jnp.where(
        digits_mask,
        10 ** jnp.clip(rank, 0, max_digits).astype(jnp.int64),
        0,
    )
    dig = (mat - ord("0")).astype(jnp.int64)
    val = jnp.sum(jnp.where(digits_mask, dig * w, 0), axis=1)
    nonzero = digits_mask & (mat != ord("0"))
    lead_zero = digits_mask & (
        jnp.cumsum(nonzero.astype(jnp.int32), axis=1) == 0
    )
    sig = total[:, 0] - jnp.sum(lead_zero, axis=1)
    if max_digits >= 18:
        overflow = (sig > 19) | ((sig == 19) & (val < 10**18))
    else:
        overflow = sig > max_digits
    return val, total[:, 0], overflow


def _int_syntax_ok(p, int_mask, frac_mask):
    """Bytes after the sign must be digits or one dot (frac digits
    allowed and truncated, the Spark '3.7' -> 3 behavior)."""
    body = p["in_str"] & (p["j"] >= p["start"][:, None])
    ok_bytes = jnp.all(
        ~body | p["isdigit"] | p["isdot"], axis=1
    )
    some_digit = jnp.any(int_mask | frac_mask, axis=1)
    return (
        ok_bytes
        & (p["ndots"] <= 1)
        & (p["lens"] > p["start"])
        & some_digit
    )


def _parse_int(col: Column, to: dt.DType) -> Column:
    p = _parse_parts(col)
    int_mask = (
        p["isdigit"]
        & (p["j"] >= p["start"][:, None])
        & (p["j"] < p["dotpos"][:, None])
    )
    frac_mask = p["isdigit"] & (p["j"] > p["dotpos"][:, None])
    val, _, overflow = _weighted_int(int_mask, p["mat"])
    ok = _int_syntax_ok(p, int_mask, frac_mask) & ~overflow & (p["nes"] == 0)
    signed = jnp.where(p["neg"], -val, val)
    info = np.iinfo(np.dtype(to.storage_dtype))
    in_range = (signed >= info.min) & (signed <= info.max)
    ok = ok & in_range
    valid = ok if col.validity is None else jnp.logical_and(col.validity, ok)
    return compute.from_values(
        jnp.where(ok, signed, 0).astype(to.storage_dtype), to, valid
    )


_SPECIALS = {
    b"nan": np.nan, b"inf": np.inf, b"infinity": np.inf,
    b"+inf": np.inf, b"+infinity": np.inf,
    b"-inf": -np.inf, b"-infinity": -np.inf,
}


def _literal_eq(low: Column, lit: bytes) -> jax.Array:
    """(n,) rows equal to the literal; ``low`` must ALREADY be
    lowercased (callers hoist the one case-mapping pass out of their
    literal loops)."""
    m = len(lit)
    n, pad = low.data.shape
    if m > pad:
        return jnp.zeros((n,), jnp.bool_)
    eq = jnp.all(
        low.data[:, :m] == jnp.asarray(np.frombuffer(lit, np.uint8))[None, :],
        axis=1,
    )
    return eq & (low.lengths == m)


def _parse_float(col: Column, to: dt.DType) -> Column:
    p = _parse_parts(col)
    c = p["col"]
    # mantissa digits left of the exponent marker
    int_mask = (
        p["isdigit"]
        & (p["j"] >= p["start"][:, None])
        & (p["j"] < p["dotpos"][:, None])
        & (p["j"] < p["epos"][:, None])
    )
    frac_mask = (
        p["isdigit"]
        & (p["j"] > p["dotpos"][:, None])
        & (p["j"] < p["epos"][:, None])
    )
    mant_mask = int_mask | frac_mask
    # mantissa as one EXACT u64: ranked digit weights over the top-19
    # window (a u64 holds 19 full decimal digits); digits below the
    # window shift into the decimal exponent instead. >19 significant
    # digits therefore truncate — the same corner every fast-path
    # parser (fast_float, Go/Rust strconv) resolves only via a big-int
    # slow path, <=1 ulp here.
    cum = jnp.cumsum(mant_mask.astype(jnp.int32), axis=1)
    total = cum[:, -1]
    rank = total[:, None] - cum  # digit's power of ten within mantissa
    dig_u = (p["mat"] - ord("0")).astype(jnp.uint64)
    # leading mantissa zeros carry no information but would eat window
    # slots ("0.00<17 digits>" has 20 mantissa characters): the window
    # covers the top 19 SIGNIFICANT digits
    nz_seen = jnp.cumsum(
        (mant_mask & (dig_u != 0)).astype(jnp.int32), axis=1
    )
    lead = jnp.sum(mant_mask & (nz_seen == 0), axis=1)
    hi_cut = total - lead  # first significant rank (exclusive bound)
    lo_cut = jnp.maximum(hi_cut - 19, 0)
    in_window = (
        mant_mask
        & (rank < hi_cut[:, None])
        & (rank >= lo_cut[:, None])
    )
    w_rank = jnp.clip(rank - lo_cut[:, None], 0, 18).astype(jnp.uint64)
    ten_pows = jnp.asarray(
        [np.uint64(10) ** np.uint64(k) for k in range(19)]
    )
    mant_w = jnp.sum(
        jnp.where(in_window, dig_u * ten_pows[w_rank], jnp.uint64(0)),
        axis=1,
    )
    window = lo_cut  # digits dropped below the window shift the exponent
    n_frac = jnp.sum(frac_mask, axis=1)
    # exponent: optional sign then digits after e/E
    e_start = p["epos"] + 1
    e_first = jnp.take_along_axis(
        p["mat"], jnp.clip(e_start, 0, p["pad"] - 1)[:, None], axis=1
    )[:, 0]
    e_neg = (e_first == ord("-")) & (e_start < p["lens"])
    e_sign = e_neg | ((e_first == ord("+")) & (e_start < p["lens"]))
    e_digits = p["isdigit"] & (
        p["j"] >= (e_start + e_sign.astype(jnp.int32))[:, None]
    )
    e_val, e_count, _ = _weighted_int(e_digits, p["mat"], max_digits=3)
    has_e = p["nes"] > 0
    exp = jnp.where(has_e, jnp.where(e_neg, -e_val, e_val), 0)
    # correctly-rounded binary conversion (Eisel-Lemire, ops/ryu.py)
    from .ryu import decimal_to_bits

    q10 = (exp - n_frac + window).astype(jnp.int32)
    is64 = to.id == dt.TypeId.FLOAT64
    bits = decimal_to_bits(mant_w, q10, bits64=is64)
    # sign applied on the BIT pattern, and float32 never routed through
    # float64 arithmetic: XLA's CPU backend flushes f32 subnormals to
    # zero in conversions, which would zero correctly-parsed values
    # near 1e-39 (caught by the format->parse bit-exactness drive)
    if is64:
        bits = bits | (p["neg"].astype(jnp.uint64) << jnp.uint64(63))
        value = jax.lax.bitcast_convert_type(bits, jnp.float64)
    else:
        b32 = bits.astype(jnp.uint32) | (
            p["neg"].astype(jnp.uint32) << jnp.uint32(31)
        )
        value = jax.lax.bitcast_convert_type(b32, jnp.float32)

    # syntax: mantissa bytes are digits/dot; exponent is signed digits
    body = p["in_str"] & (p["j"] >= p["start"][:, None]) & (
        p["j"] < p["epos"][:, None]
    )
    ok_mant = jnp.all(~body | p["isdigit"] | p["isdot"], axis=1)
    e_body = p["in_str"] & (
        p["j"] >= (e_start + e_sign.astype(jnp.int32))[:, None]
    )
    ok_exp = jnp.all(~e_body | p["isdigit"], axis=1) & (
        ~has_e | (e_count > 0)
    )
    some_digit = jnp.sum(mant_mask, axis=1) > 0
    ok = (
        ok_mant & ok_exp & some_digit & (p["ndots"] <= 1)
        & (p["nes"] <= 1)
        # a dot, if present, must precede the exponent marker
        & ((p["ndots"] == 0) | (p["dotpos"] <= p["epos"]))
    )

    # special literals override syntax (one case-map pass, not per lit)
    low = lower(c)
    for lit, sval in _SPECIALS.items():
        hit = _literal_eq(low, lit)
        value = jnp.where(hit, sval, value)
        ok = ok | hit
    valid = ok if col.validity is None else jnp.logical_and(col.validity, ok)
    return compute.from_values(
        jnp.where(ok, value, 0.0), to, valid
    )


_TRUE = (b"t", b"true", b"y", b"yes", b"1")
_FALSE = (b"f", b"false", b"n", b"no", b"0")


def _parse_bool(col: Column) -> Column:
    c = strip(col, _WS)
    low = lower(c)
    is_true = jnp.zeros((c.data.shape[0],), jnp.bool_)
    is_false = jnp.zeros((c.data.shape[0],), jnp.bool_)
    for lit in _TRUE:
        is_true = is_true | _literal_eq(low, lit)
    for lit in _FALSE:
        is_false = is_false | _literal_eq(low, lit)
    ok = is_true | is_false
    valid = ok if col.validity is None else jnp.logical_and(col.validity, ok)
    return Column(is_true, dt.BOOL8, valid)


def _decimal_parts(p, k: int, drop_int: int = 0):
    """Shared STRING->decimal decomposition: kept-digit masks and the
    significant-KEPT-integer-digit count, for both accumulator widths.

    ``drop_int`` (a positive target scale) excludes the last that many
    INTEGER digits from the kept window — fixed_point truncation
    toward zero done by never accumulating the dropped digits, so a
    wide string whose post-truncation value fits is representable and
    the accumulator cannot wrap on digits that would be divided away.
    Returns ``(int_keep, frac_keep, frac_mask, sig_int, int_mask)``."""
    int_mask = (
        p["isdigit"]
        & (p["j"] >= p["start"][:, None])
        & (p["j"] < p["dotpos"][:, None])
    )
    if drop_int > 0:
        cum_int = jnp.cumsum(int_mask.astype(jnp.int32), axis=1)
        total_int = cum_int[:, -1:]
        int_rank = total_int - cum_int  # digits after this one
        int_keep = int_mask & (int_rank >= drop_int)
    else:
        int_keep = int_mask
    frac_keep = (
        p["isdigit"]
        & (p["j"] > p["dotpos"][:, None])
        & (p["j"] <= (p["dotpos"] + k)[:, None])
    )
    frac_mask = p["isdigit"] & (p["j"] > p["dotpos"][:, None])
    nonzero = int_keep & (p["mat"] != ord("0"))
    lead = int_keep & (
        jnp.cumsum(nonzero.astype(jnp.int32), axis=1) == 0
    )
    sig_int = jnp.sum(int_keep, axis=1) - jnp.sum(lead, axis=1)
    return int_keep, frac_keep, frac_mask, sig_int, int_mask


def _parse_decimal128(col: Column, to: dt.DType) -> Column:
    """STRING -> DECIMAL128: exact 128-bit integer arithmetic.

    Masked Horner over the byte matrix: per character, the running
    (lo, hi) limb pair multiplies by ten and adds the digit wherever
    the position is a kept mantissa digit (integer digits, then the
    first ``-scale`` fractional digits); missing fractional places
    fill with a trailing power-of-ten multiply. Up to 38 significant
    digits (Spark's DECIMAL(38) bound, < 2^127), beyond -> null."""
    from . import int128

    p = _parse_parts(col)
    k = max(-to.scale, 0)
    int_keep, frac_keep, frac_mask, sig_int, int_mask = _decimal_parts(
        p, k, drop_int=max(to.scale, 0)
    )
    kept = int_keep | frac_keep
    dig = (p["mat"] - ord("0")).astype(jnp.uint64)
    n = p["mat"].shape[0]

    def horner(carry, xs):
        lo, hi = carry
        keep_j, dig_j = xs
        tlo, thi = int128.mul_u64(lo, hi, jnp.uint64(10))
        nlo = tlo + dig_j
        nhi = thi + (nlo < tlo).astype(jnp.uint64)
        return (
            jnp.where(keep_j, nlo, lo),
            jnp.where(keep_j, nhi, hi),
        ), None

    (lo, hi), _ = jax.lax.scan(
        horner,
        (jnp.zeros(n, jnp.uint64), jnp.zeros(n, jnp.uint64)),
        (kept.T, dig.T),
    )
    # fill the missing fractional places with trailing zeros: one or
    # two u64 power-of-ten multiplies (10^t, t <= 38 splits as <=19+19)
    n_frac = jnp.sum(frac_keep, axis=1)
    fill = jnp.clip(k - n_frac, 0, 38)
    p10 = jnp.asarray(
        [np.uint64(10) ** np.uint64(t) for t in range(20)]
    )
    m1 = p10[jnp.minimum(fill, 19)]
    m2 = p10[jnp.clip(fill - 19, 0, 19)]
    lo, hi = int128.mul_u64(lo, hi, m1)
    lo, hi = int128.mul_u64(lo, hi, m2)

    # representability: significant integer digits + k <= 38
    representable = (sig_int + k) <= 38
    ok = (
        _int_syntax_ok(p, int_mask, frac_mask)
        & (p["nes"] == 0)
        & representable
    )
    nlo, nhi = int128.negate(lo, hi)
    lo = jnp.where(p["neg"], nlo, lo)
    hi = jnp.where(p["neg"], nhi, hi)
    limbs = jnp.stack(
        [jnp.where(ok, lo, 0), jnp.where(ok, hi, 0)], axis=1
    )
    valid = ok if col.validity is None else jnp.logical_and(
        col.validity, ok
    )
    return Column(limbs, to, valid)


def _parse_decimal(col: Column, to: dt.DType) -> Column:
    """STRING -> DECIMAL32/64: exact integer arithmetic. The unscaled
    result is int_part * 10^-scale plus the first -scale fractional
    digits (excess fractional digits truncate, cudf fixed_point)."""
    if to.id == dt.TypeId.DECIMAL128:
        return _parse_decimal128(col, to)
    p = _parse_parts(col)
    k = max(-to.scale, 0)
    int_keep, frac_keep, frac_mask, sig_int, int_mask = _decimal_parts(
        p, k, drop_int=max(to.scale, 0)
    )
    int_val, _, int_over = _weighted_int(int_keep, p["mat"])
    # frac digits weighted to exactly k places (missing digits = 0)
    cum = jnp.cumsum(frac_keep.astype(jnp.int32), axis=1)
    pos = jnp.where(frac_keep, cum, 0)  # 1-based frac position
    w = jnp.where(
        frac_keep, 10 ** jnp.clip(k - pos, 0, 18).astype(jnp.int64), 0
    )
    dig = (p["mat"] - ord("0")).astype(jnp.int64)
    frac_val = jnp.sum(jnp.where(frac_keep, dig * w, 0), axis=1)
    unscaled = int_val * (10 ** min(k, 18)) + frac_val
    # representability: integer digits (after leading zeros) + the k
    # fractional places must fit the 18-digit exact window, and the
    # scaled value must fit the target storage — otherwise NULL, never
    # a wrapped value marked valid
    representable = (sig_int + k) <= 18
    info = np.iinfo(np.dtype(to.storage_dtype))
    signed = jnp.where(p["neg"], -unscaled, unscaled)
    in_range = (signed >= info.min) & (signed <= info.max)
    ok = (
        _int_syntax_ok(p, int_mask, frac_mask)
        & ~int_over
        & (p["nes"] == 0)
        & representable
        & in_range
    )
    valid = ok if col.validity is None else jnp.logical_and(col.validity, ok)
    return compute.from_values(
        jnp.where(ok, signed, 0).astype(to.storage_dtype), to, valid
    )


def _format_bool(col: Column) -> Column:
    n = col.data.shape[0]
    t = np.frombuffer(b"true\x00", np.uint8)
    f = np.frombuffer(b"false", np.uint8)
    data = jnp.where(
        col.data[:, None], jnp.asarray(t)[None, :], jnp.asarray(f)[None, :]
    ).astype(jnp.uint8)
    lens = jnp.where(col.data, 4, 5).astype(jnp.int32)
    return Column(data, dt.STRING, col.validity, lens)


def _digit_matrix(mag, K):
    """(digits least-significant-first (n, K+1) u8, digit count (n,))
    of a u64 magnitude vector — the shared core of every decimal
    formatter in this module."""
    pows = jnp.asarray(
        [np.uint64(10) ** np.uint64(k) for k in range(K + 1)]
    )
    digs = ((mag[:, None] // pows[None, :]) % jnp.uint64(10)).astype(
        jnp.uint8
    )
    ndig = jnp.maximum(
        jnp.sum((mag[:, None] >= pows[None, :]).astype(jnp.int32), axis=1),
        1,
    )
    return digs, ndig


def _format_int(col: Column) -> Column:
    """INT -> STRING fully on device: extract up to 19 decimal digits,
    suppress leading zeros, prepend the sign."""
    v = compute.values(col).astype(jnp.int64)
    n = v.shape[0]
    neg = v < 0
    # magnitude in uint64 (covers INT64_MIN, whose negation overflows i64)
    mag = jnp.where(neg, (~v.astype(jnp.uint64)) + jnp.uint64(1),
                    v.astype(jnp.uint64))
    K = 19
    digs, ndig = _digit_matrix(mag, K)
    lens = ndig + neg.astype(jnp.int32)
    width = K + 2
    j = jnp.arange(width)[None, :]
    # output byte j: '-' at 0 when negative, else digit (ndig-1-(j-neg))
    digit_idx = jnp.clip(
        ndig[:, None] - 1 - (j - neg.astype(jnp.int32)[:, None]), 0, K
    )
    chars = jnp.take_along_axis(digs, digit_idx, axis=1) + ord("0")
    out = jnp.where(
        neg[:, None] & (j == 0), ord("-"), chars
    )
    out = jnp.where(j < lens[:, None], out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, lens.astype(jnp.int32))


def _digit_matrix128(lo, hi):
    """(digits least-significant-first (n, 40) u8, digit count (n,)) of
    a 128-bit magnitude in (lo, hi) u64 limbs — five base-10^9 chunks
    via constant long division, then the u64 digit extraction per
    chunk (a u128 holds at most 39 decimal digits)."""
    from .int128 import divmod_u32_rem

    chunks = []
    for _ in range(4):
        lo, hi, r = divmod_u32_rem(lo, hi, 10 ** 9)
        chunks.append(r)
    chunks.append(lo)  # top chunk: < 10^3 after four divisions
    pows9 = jnp.asarray(
        [np.uint64(10) ** np.uint64(k) for k in range(9)]
    )
    digs = jnp.concatenate(
        [
            ((c[:, None] // pows9[None, :]) % jnp.uint64(10)).astype(
                jnp.uint8
            )
            for c in chunks
        ],
        axis=1,
    )  # (n, 45) lsf; only the first 40 can be nonzero
    digs = digs[:, :40]
    nz = digs != 0
    highest = 39 - jnp.argmax(nz[:, ::-1], axis=1)  # top nonzero index
    ndig = jnp.where(jnp.any(nz, axis=1), highest + 1, 1)
    return digs, ndig.astype(jnp.int32)


def _format_decimal(col: Column) -> Column:
    """DECIMAL32/64/128 -> STRING fully on device, any scale: the
    digit extraction plus a decimal point inserted ``-scale`` digits
    from the right (integer part zero-padded to at least one digit) —
    byte-identical to the host formatter's
    ``str(abs(u)).rjust(-s+1, '0')[: s] + '.' + [s:]`` shape. A
    positive scale appends ``scale`` zeros (value = unscaled * 10^s)
    with no point."""
    s = col.dtype.scale
    d = -s
    if s == 0 and col.dtype.id != dt.TypeId.DECIMAL128:
        return _format_int(col)  # the generic path below also handles
        # d == 0, but the int formatter's narrower matrix is cheaper
    if col.dtype.id == dt.TypeId.DECIMAL128:
        from .int128 import negate as _negate128

        limbs = col.data
        lo = limbs[:, 0]
        hi = limbs[:, 1]
        neg = (hi >> jnp.uint64(63)) != 0
        nlo, nhi = _negate128(lo, hi)
        mlo = jnp.where(neg, nlo, lo)
        mhi = jnp.where(neg, nhi, hi)
        digs, ndig = _digit_matrix128(mlo, mhi)
        K = 39
    else:
        v = compute.values(col).astype(jnp.int64)
        neg = v < 0
        mag = jnp.where(
            neg, (~v.astype(jnp.uint64)) + jnp.uint64(1),
            v.astype(jnp.uint64),
        )
        K = 19
        digs, ndig = _digit_matrix(mag, K)
    if s > 0:
        # trailing zeros, no point: magnitude digits then s zeros
        lens = neg.astype(jnp.int32) + ndig + s
        width = K + 1 + 1 + max(s, 0)
        j = jnp.arange(width)[None, :]
        p = j - neg.astype(jnp.int32)[:, None]
        digit_idx = jnp.clip(ndig[:, None] - 1 - p, 0, K)
        in_digits = (p >= 0) & (p < ndig[:, None])
        chars = jnp.where(
            in_digits,
            jnp.take_along_axis(digs, digit_idx, axis=1),
            0,
        ) + ord("0")
        out = jnp.where(neg[:, None] & (j == 0), ord("-"), chars)
        out = jnp.where(j < lens[:, None], out, 0).astype(jnp.uint8)
        return Column(
            out, dt.STRING, col.validity, lens.astype(jnp.int32)
        )
    int_digits = jnp.maximum(ndig - d, 1)
    lens = neg.astype(jnp.int32) + int_digits + (1 + d if d else 0)
    width = K + 3 + max(d - K, 0)  # "0." + d fraction digits worst case
    j = jnp.arange(width)[None, :]
    p = j - neg.astype(jnp.int32)[:, None]  # position after the sign
    point_at = int_digits[:, None]
    # digit index (10^k, least-significant-first) per output position:
    # integer part counts down from int_digits-1+d; fraction part from
    # d-1 after the point
    int_idx = int_digits[:, None] - 1 - p + d
    frac_idx = d - 1 - (p - point_at - 1)
    digit_idx = jnp.clip(
        jnp.where(p < point_at, int_idx, frac_idx), 0, K
    )
    in_digits = jnp.where(p < point_at, int_idx, frac_idx) <= K
    chars = jnp.where(
        in_digits, jnp.take_along_axis(digs, digit_idx, axis=1), 0
    ) + ord("0")
    out = jnp.where((p == point_at) & (d > 0), ord("."), chars)
    out = jnp.where(
        neg[:, None] & (j == 0), ord("-"), out
    )
    out = jnp.where(j < lens[:, None], out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, lens.astype(jnp.int32))


def _format_float(col: Column) -> Column:
    """FLOAT32/64 -> STRING fully on device.

    Digits come from the vectorized Ryu core (ops/ryu.py: shortest
    round-trip significand, exactly libcudf's ftos_converter contract);
    this function applies the Java ``Double.toString`` placement rules
    the host fallback implemented: plain decimal when the normalized
    exponent is in [-3, 7) (always at least one fractional digit, so
    integral values read "4.0"), scientific ``d.fracEexp`` otherwise,
    "NaN" / "Infinity" / "-Infinity" / signed zero verbatim."""
    from .ryu import shortest_decimal32, shortest_decimal64

    v = compute.values(col)
    if col.dtype.id == dt.TypeId.FLOAT64:
        bits = jax.lax.bitcast_convert_type(v, jnp.uint64)
        sign, digits, exp10, is_zero, is_inf, is_nan = (
            shortest_decimal64(bits)
        )
        K = 17  # max shortest-significand digits
        width = 26  # sign + d + point + 16 frac + E + sign + 3 exp
    else:
        bits = jax.lax.bitcast_convert_type(
            v.astype(jnp.float32), jnp.uint32
        )
        sign, digits, exp10, is_zero, is_inf, is_nan = (
            shortest_decimal32(bits)
        )
        K = 9
        width = 18
    digs, olen = _digit_matrix(digits, K)
    sci_exp = olen - 1 + exp10
    plain = (sci_exp >= -3) & (sci_exp < 7)

    neg = sign & ~is_nan
    o = neg.astype(jnp.int32)
    # integer-part digit count (plain): sciExp+1 real digits, padded
    # with zeros when the digits run out (E >= 0); sciExp < 0 prints
    # the single forced '0'
    int_len = jnp.where(plain & (sci_exp >= 0), sci_exp + 1, 1)
    lead_zeros = jnp.where(
        plain & (sci_exp < 0), -sci_exp - 1, 0
    )  # zeros after "0."
    frac_digits = jnp.where(
        plain,
        jnp.where(
            sci_exp >= 0,
            jnp.maximum(olen - (sci_exp + 1), 1),
            lead_zeros + olen,
        ),
        jnp.maximum(olen - 1, 1),
    )
    point_at = o + int_len

    # exponent suffix (scientific only): E[-]ddd, no leading zeros
    eabs = jnp.abs(sci_exp)
    e_ndig = jnp.where(eabs >= 100, 3, jnp.where(eabs >= 10, 2, 1))
    e_neg = (sci_exp < 0).astype(jnp.int32)
    suffix_len = jnp.where(plain, 0, 1 + e_neg + e_ndig)

    lens = o + int_len + 1 + frac_digits + suffix_len
    lens = jnp.where(is_nan, 3, lens)
    lens = jnp.where(is_inf, 8 + o, lens)
    lens = jnp.where(is_zero, 3 + o, lens)

    j = jnp.arange(width)[None, :]
    p = j - o[:, None]  # position after the sign

    # ---- mantissa digit index per position ---------------------------
    # most-significant-first index i -> ls index olen-1-i
    int_i = p  # i for integer positions (plain, sciExp >= 0)
    frac_start = point_at[:, None] + 1
    frac_k = j - frac_start  # 0-based fraction position
    plain_pos_i = jnp.where(
        j < point_at[:, None], int_i, int_len[:, None] + frac_k
    )
    # sciExp < 0 plain: '0' . zeros digits
    planb_digit = frac_k - lead_zeros[:, None]  # index into digits
    sci_i = jnp.where(j < point_at[:, None], 0, 1 + frac_k)

    ms_i = jnp.where(
        plain[:, None],
        jnp.where(
            (sci_exp >= 0)[:, None], plain_pos_i,
            jnp.where(j < point_at[:, None], K, planb_digit),
        ),
        sci_i,
    )  # index K = forced zero sentinel
    in_digits = (ms_i >= 0) & (ms_i < olen[:, None])
    ls_idx = jnp.clip(olen[:, None] - 1 - ms_i, 0, K)
    digit_chars = jnp.where(
        in_digits,
        jnp.take_along_axis(digs, ls_idx, axis=1),
        0,
    ) + ord("0")

    out = jnp.where(j == point_at[:, None], ord("."), digit_chars)

    # ---- scientific suffix ------------------------------------------
    e_at = point_at + 1 + frac_digits  # position of 'E'
    out = jnp.where(
        ~plain[:, None] & (j == e_at[:, None]), ord("E"), out
    )
    out = jnp.where(
        ~plain[:, None] & (e_neg == 1)[:, None]
        & (j == (e_at + 1)[:, None]),
        ord("-"),
        out,
    )
    e_digit_ms = j - (e_at + 1 + e_neg)[:, None]  # 0-based ms index
    e_pows = jnp.asarray([1, 10, 100, 1000], dtype=jnp.int32)
    e_ls = jnp.clip(e_ndig[:, None] - 1 - e_digit_ms, 0, 3)
    e_chars = (
        (eabs[:, None] // jnp.take(e_pows, e_ls)) % 10
    ).astype(jnp.uint8) + ord("0")
    in_exp = (e_digit_ms >= 0) & (e_digit_ms < e_ndig[:, None])
    out = jnp.where(~plain[:, None] & in_exp, e_chars, out)

    # ---- sign + specials --------------------------------------------
    out = jnp.where(neg[:, None] & (j == 0), ord("-"), out)
    nan_s = jnp.asarray(
        np.frombuffer(b"NaN".ljust(width, b"\0"), dtype=np.uint8)
    )
    inf_s = jnp.asarray(
        np.frombuffer(b"Infinity".ljust(width, b"\0"), dtype=np.uint8)
    )
    zero_s = jnp.asarray(
        np.frombuffer(b"0.0".ljust(width, b"\0"), dtype=np.uint8)
    )
    out = jnp.where(is_nan[:, None], nan_s[None, :], out)
    shifted_inf = jnp.where(
        (j - o[:, None] >= 0) & (j - o[:, None] < 8),
        inf_s[jnp.clip(j - o[:, None], 0, width - 1)],
        0,
    )
    out = jnp.where(is_inf[:, None], shifted_inf, out)
    shifted_zero = jnp.where(
        (j - o[:, None] >= 0) & (j - o[:, None] < 3),
        zero_s[jnp.clip(j - o[:, None], 0, width - 1)],
        0,
    )
    out = jnp.where(is_zero[:, None], shifted_zero, out)
    out = jnp.where(
        (is_inf | is_zero)[:, None] & neg[:, None] & (j == 0),
        ord("-"),
        out,
    )
    out = jnp.where(j < lens[:, None], out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, lens.astype(jnp.int32))


def _format_host(col: Column) -> Column:
    """Float/decimal -> string via a host pass (Java Double.toString
    style for floats: plain decimal in [1e-3, 1e7), else scientific)."""
    vals = col.to_pylist()
    out = []
    for v in vals:
        if v is None:
            out.append(None)
        elif col.dtype.is_decimal:
            s = col.dtype.scale
            sign = "-" if v < 0 else ""
            if s > 0:  # value = unscaled * 10^s: appended zeros
                out.append(sign + str(abs(v)) + "0" * s)
            else:
                digits = str(abs(v)).rjust(max(1, -s + 1), "0")
                out.append(
                    sign
                    + (digits if s == 0 else digits[:s] + "." + digits[s:])
                )
        elif v != v:  # NaN
            out.append("NaN")
        elif v in (float("inf"), float("-inf")):
            out.append("Infinity" if v > 0 else "-Infinity")
        elif v == int(v) and 1e-3 <= abs(v) < 1e7 or v == 0.0:
            out.append(f"{v:.1f}")
        elif 1e-3 <= abs(v) < 1e7:
            out.append(repr(float(v)))
        else:
            # shortest round-trip mantissa from repr (Python repr IS
            # shortest; the old %.{p}e scan missed it on exact-halfway
            # mantissas like 2^-24, where round-half-even truncation
            # skips the nearer 16-digit form), re-laid-out in the Java
            # Double.toString scientific shape (5.0E-4)
            s = repr(abs(float(v)))
            if "e" in s:
                m, e = s.split("e")
                e10 = int(e)
            else:
                m, e10 = s, 0
            ip, _, fp = m.partition(".")
            raw = ip + fp
            digs = raw.lstrip("0").rstrip("0") or "0"
            # decimal exponent of the last KEPT digit
            stripped_right = len(raw) - len(raw.rstrip("0"))
            exp10 = e10 - len(fp) + stripped_right
            sci_exp = len(digs) - 1 + exp10
            mant = digs[0] + "." + (digs[1:] or "0")
            sign = "-" if v < 0 else ""
            out.append(f"{sign}{mant}E{sci_exp}")
    res = Column.from_strings(out)
    valid = res.validity
    if col.validity is not None:
        valid = col.validity if valid is None else jnp.logical_and(
            valid, col.validity
        )
    return Column(res.data, dt.STRING, valid, res.lengths)


# ---------------------------------------------------------------------------
# dictionary encoding (round-3 VERDICT item 8): joins/groupbys on string
# keys hash int codes instead of pad-width byte matrices
# ---------------------------------------------------------------------------

def _dictionary_codes(col: Column):
    """Jittable half of dictionary encoding: (codes int32 in row order,
    perm, seg, num_uniq device scalar). Sort-based (no device hash
    table): one stable sort of the order-key words, boundary scan for
    ids, scatter-free inverse permutation via a second sort on the
    carried iota. Codes are ORDER-PRESERVING: code order == key order,
    so they can replace the key words in any comparison-based op."""
    from .groupby import _segment_ids

    perm, seg, num_uniq, _ = _segment_ids([col])
    # codes in original row order: sort (perm -> seg) pairs back by perm
    iota_sorted, codes = jax.lax.sort((perm, seg), num_keys=1)
    del iota_sorted
    return codes.astype(jnp.int32), perm, seg, num_uniq


def dictionary_encode(col: Column):
    """(codes INT32 column, uniques STRING column): codes index into the
    sorted unique values (eager: host-syncs the unique count)."""
    _require_string(col)
    from .gather import gather_table
    from ..column import Table

    codes, perm, seg, num_uniq = _dictionary_codes(col)
    n = col.data.shape[0]
    g = int(num_uniq)
    starts = jnp.searchsorted(
        seg, jnp.arange(g, dtype=seg.dtype), side="left"
    )
    first_rows = perm[jnp.clip(starts, 0, max(n - 1, 0))]
    uniques = gather_table(Table([col]), first_rows).columns[0]
    return Column(codes, dt.INT32, col.validity), uniques


def encode_join_keys(left: Column, right: Column):
    """Encode two string key columns against ONE shared dictionary so
    equality (and ORDER) of codes == equality/order of strings across
    the tables; the int32 codes then drive the join instead of the
    pad/8+1 u64 words per compare. Fully jittable (no host sync), so
    the capped join APIs can use it under jit — how string join keys
    become cheap by default (round-4 VERDICT item 5)."""
    _require_string(left)
    _require_string(right)
    common = max(left.data.shape[1], right.data.shape[1])
    both = Column(
        jnp.concatenate([repad(left, common).data,
                         repad(right, common).data]),
        dt.STRING,
        None,
        jnp.concatenate([left.lengths, right.lengths]),
    )
    codes, _, _, _ = _dictionary_codes(both)
    nl = left.data.shape[0]
    return (
        Column(codes[:nl], dt.INT32, left.validity),
        Column(codes[nl:], dt.INT32, right.validity),
    )


def _shift_left(col: Column, shift: jax.Array, new_len: jax.Array) -> Column:
    """Row-wise left shift by a per-row amount, zeroing past new_len."""
    n, pad = col.data.shape
    j = jnp.arange(pad)[None, :]
    src = jnp.clip(j + shift[:, None], 0, pad - 1)
    out = jnp.take_along_axis(col.data, src, axis=1)
    out = jnp.where(j < new_len[:, None], out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, new_len.astype(jnp.int32))


def _strip_counts(col: Column, chars: bytes, from_left: bool):
    """Count of strip-set bytes at the left (or right) edge of each row."""
    n, pad = col.data.shape
    in_set = jnp.zeros((n, pad), dtype=jnp.bool_)
    for ch in chars:
        in_set = in_set | (col.data == ch)
    j = jnp.arange(pad)[None, :]
    in_str = j < col.lengths[:, None]
    if from_left:
        # leading run length: first position that is in-string and not
        # in the strip set
        boundary = in_str & ~in_set
        has = jnp.any(boundary, axis=1)
        first = jnp.argmax(boundary, axis=1)
        return jnp.where(has, first, col.lengths)
    # trailing run: scan from the right
    boundary = in_str & ~in_set
    has = jnp.any(boundary, axis=1)
    last = pad - 1 - jnp.argmax(boundary[:, ::-1], axis=1)
    return jnp.where(has, col.lengths - last - 1, col.lengths)


def strip(col: Column, chars: str | bytes = b" ") -> Column:
    """Trim the byte set from both ends. Default trims only the space
    byte — Spark ``trim`` semantics (pass explicit chars for python-str
    whitespace stripping)."""
    _require_string(col)
    cset = chars.encode() if isinstance(chars, str) else bytes(chars)
    left = _strip_counts(col, cset, True)
    right = _strip_counts(col, cset, False)
    new_len = jnp.maximum(col.lengths - left - right, 0)
    return _shift_left(col, left, new_len)


def lstrip(col: Column, chars: str | bytes = b" ") -> Column:
    """Spark ``ltrim`` (space-only default)."""
    _require_string(col)
    cset = chars.encode() if isinstance(chars, str) else bytes(chars)
    left = _strip_counts(col, cset, True)
    return _shift_left(col, left, col.lengths - left)


def rstrip(col: Column, chars: str | bytes = b" ") -> Column:
    """Spark ``rtrim`` (space-only default)."""
    _require_string(col)
    cset = chars.encode() if isinstance(chars, str) else bytes(chars)
    right = _strip_counts(col, cset, False)
    new_len = col.lengths - right
    return _shift_left(col, jnp.zeros_like(col.lengths), new_len)


def find(col: Column, pattern: str | bytes) -> Column:
    """First byte index of the literal pattern, -1 if absent (Spark
    ``instr`` is this + 1)."""
    _require_string(col)
    pat = _literal_bytes(pattern)
    m = len(pat)
    n, pad_w = col.data.shape
    if m == 0:
        return Column(jnp.zeros((n,), jnp.int32), dt.INT32, col.validity)
    pos = jnp.full((n,), -1, dtype=jnp.int32)
    if m <= min(pad_w, _BITAP_MAX):
        ends = _match_ends(col, pat)
        has = jnp.any(ends, axis=1)
        first_end = jnp.argmax(ends, axis=1).astype(jnp.int32)
        pos = jnp.where(has, first_end - (m - 1), pos)
    elif m <= pad_w:
        matches = _window_matches(col, pat)
        for start in range(len(matches) - 1, -1, -1):  # right-to-left keeps first
            pos = jnp.where(matches[start], start, pos)
    return Column(pos, dt.INT32, col.validity)


def pad(col: Column, width: int, side: str = "right", fill: str = " ") -> Column:
    """Spark ``lpad``/``rpad``: result is EXACTLY ``width`` bytes — padded
    with the (possibly multi-byte) ``fill`` pattern when shorter,
    truncated to the leading ``width`` bytes when longer."""
    _require_string(col)
    fill_b = _literal_bytes(fill)
    if len(fill_b) == 0:
        raise ValueError("pad: fill must be non-empty")
    if side not in ("left", "right"):
        raise ValueError("side must be 'left' or 'right'")
    n, old = col.data.shape
    c = repad(col, max(old, width))
    out_pad = c.data.shape[1]
    j = jnp.arange(out_pad)[None, :]
    fillv = jnp.asarray(fill_b)
    m = len(fill_b)
    s_len = jnp.minimum(c.lengths, width)  # truncation bound
    if side == "right":
        fill_idx = (j - c.lengths[:, None]) % m
        data = jnp.where(
            j < s_len[:, None], c.data, fillv[fill_idx]
        )
    else:
        shift = jnp.maximum(width - c.lengths, 0)
        src = jnp.clip(j - shift[:, None], 0, out_pad - 1)
        moved = jnp.take_along_axis(c.data, src, axis=1)
        data = jnp.where(j < shift[:, None], fillv[j % m], moved)
    new_len = jnp.full((n,), width, jnp.int32)
    data = jnp.where(j < new_len[:, None], data, 0)
    out = Column(
        data.astype(jnp.uint8), dt.STRING, c.validity, new_len
    )
    return repad(out, max(width, 1))


def replace(col: Column, old: str | bytes, new: str | bytes) -> Column:
    """Literal, non-overlapping, leftmost-first replacement (Spark
    ``replace``). Equal-width substitutions stay fully on device; width-
    changing substitutions rebuild the column (eager host path, the cudf
    call model)."""
    _require_string(col)
    old_b = _literal_bytes(old)
    new_b = _literal_bytes(new)
    m = len(old_b)
    if m == 0:
        return col
    n, pad_w = col.data.shape
    if len(new_b) == m and m <= pad_w and m <= _BITAP_MAX:
        # device path: greedy non-overlapping match selection as ONE
        # lax.scan over start offsets (O(1) graph; the carry holds the
        # data matrix and each row's next free position)
        from jax import lax

        ends = _match_ends(col, old_b)
        match_start = ends[:, m - 1 :]  # (n, pad-m+1), col s = start s
        base_row = jnp.zeros((pad_w,), jnp.uint8).at[:m].set(
            jnp.asarray(new_b)
        )
        j = jnp.arange(pad_w)[None, :]

        def step(carry, x):
            data, next_free = carry
            s, ms = x
            sel = ms & (next_free <= s)
            in_window = (j >= s) & (j < s + m)
            data = jnp.where(
                sel[:, None] & in_window,
                jnp.roll(base_row, s)[None, :],
                data,
            )
            next_free = jnp.where(sel, s + m, next_free)
            return (data, next_free), None

        (data, _), _ = lax.scan(
            step,
            (col.data, jnp.zeros((n,), jnp.int32)),
            (jnp.arange(pad_w - m + 1, dtype=jnp.int32), match_start.T),
        )
        return Column(data.astype(jnp.uint8), dt.STRING, col.validity, col.lengths)
    if len(new_b) == m and m <= pad_w:
        # unrolled fallback for patterns past the 64-byte bitap bitset
        match = _window_matches(col, old_b)
        base_row = jnp.zeros((pad_w,), jnp.uint8).at[:m].set(
            jnp.asarray(new_b)
        )
        j = jnp.arange(pad_w)[None, :]
        data = col.data
        next_free = jnp.zeros((n,), jnp.int32)
        for start in range(pad_w - m + 1):
            sel = match[start] & (next_free <= start)
            in_window = (j >= start) & (j < start + m)
            data = jnp.where(
                sel[:, None] & in_window,
                jnp.roll(base_row, start)[None, :],
                data,
            )
            next_free = jnp.where(sel, start + m, next_free)
        return Column(data.astype(jnp.uint8), dt.STRING, col.validity, col.lengths)
    # host path for width-changing substitutions
    out = [
        None if v is None else v.replace(
            old if isinstance(old, str) else old.decode("utf-8", "surrogateescape"),
            new if isinstance(new, str) else new.decode("utf-8", "surrogateescape"),
        )
        for v in col.to_pylist()
    ]
    return Column.from_strings(out)


def _extract_token(
    data, lengths, validity, delim_byte: int, token_index
) -> Column:
    """The k-th delimiter-separated token of each row (shared by
    split_get and lists.split_explode): ``token_index`` may be a scalar
    or a per-row array. Out-of-range tokens are empty strings."""
    pad_w = data.shape[1]
    j = jnp.arange(pad_w)[None, :]
    in_str = j < lengths[:, None]
    is_delim = (data == delim_byte) & in_str
    # field id of each byte = number of delimiters before it
    field = jnp.cumsum(is_delim.astype(jnp.int32), axis=1) - is_delim.astype(
        jnp.int32
    )
    idx = (
        token_index
        if jnp.ndim(token_index) == 0
        else token_index[:, None]
    )
    keep = in_str & ~is_delim & (field == idx)
    tok_len = jnp.sum(keep, axis=1)
    has = jnp.any(keep, axis=1)
    start = jnp.where(has, jnp.argmax(keep, axis=1), 0)
    return _shift_left(
        Column(data, dt.STRING, validity, lengths),
        start.astype(jnp.int32),
        tok_len.astype(jnp.int32),
    )


def split_get(col: Column, delimiter: str | bytes, index: int) -> Column:
    """k-th field after splitting on a single-byte delimiter (Spark
    ``split_part`` with 0-based index); empty string when out of range."""
    _require_string(col)
    d = _literal_bytes(delimiter)
    if len(d) != 1:
        raise ValueError("split_get: single-byte delimiter only")
    return _extract_token(
        col.data, col.lengths, col.validity, int(d[0]), index
    )


def reverse(col: Column) -> Column:
    """Byte-wise reversal (Spark ``reverse``; char-exact for ASCII)."""
    _require_string(col)
    n, pad_w = col.data.shape
    j = jnp.arange(pad_w)[None, :]
    src = jnp.clip(col.lengths[:, None] - 1 - j, 0, pad_w - 1)
    out = jnp.take_along_axis(col.data, src, axis=1)
    out = jnp.where(j < col.lengths[:, None], out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, col.lengths)


# ---------------------------------------------------------------------------
# character-class predicates (cudf all_characters_of_type: isAlpha/isDigit/
# isAlphaNumeric/isSpace/isUpper/isLower in the Java API)
# ---------------------------------------------------------------------------


def _char_class_pred(col: Column, in_class) -> Column:
    """True where every byte of the (non-empty) string is in the class —
    cudf's all-characters-of-type semantics (empty strings are False,
    matching cudf/Python)."""
    _require_string(col)
    n, pad_w = col.data.shape
    j = jnp.arange(pad_w)[None, :]
    in_str = j < col.lengths[:, None]
    ok = jnp.all(~in_str | in_class(col.data), axis=1) & (col.lengths > 0)
    return Column(ok, dt.BOOL8, col.validity)


def is_digit(col: Column) -> Column:
    return _char_class_pred(
        col, lambda m: (m >= ord("0")) & (m <= ord("9"))
    )


def is_alpha(col: Column) -> Column:
    return _char_class_pred(
        col,
        lambda m: ((m >= ord("a")) & (m <= ord("z")))
        | ((m >= ord("A")) & (m <= ord("Z"))),
    )


def is_alnum(col: Column) -> Column:
    return _char_class_pred(
        col,
        lambda m: ((m >= ord("a")) & (m <= ord("z")))
        | ((m >= ord("A")) & (m <= ord("Z")))
        | ((m >= ord("0")) & (m <= ord("9"))),
    )


def is_space(col: Column) -> Column:
    return _char_class_pred(
        col,
        lambda m: (m == ord(" ")) | ((m >= 9) & (m <= 13)),
    )


def is_upper(col: Column) -> Column:
    """No lowercase letters and at least one uppercase (cudf isUpper)."""
    _require_string(col)
    n, pad_w = col.data.shape
    j = jnp.arange(pad_w)[None, :]
    in_str = j < col.lengths[:, None]
    m = col.data
    lower_b = (m >= ord("a")) & (m <= ord("z")) & in_str
    upper_b = (m >= ord("A")) & (m <= ord("Z")) & in_str
    ok = ~jnp.any(lower_b, axis=1) & jnp.any(upper_b, axis=1)
    return Column(ok, dt.BOOL8, col.validity)


def is_lower(col: Column) -> Column:
    """No uppercase letters and at least one lowercase (cudf isLower)."""
    _require_string(col)
    n, pad_w = col.data.shape
    j = jnp.arange(pad_w)[None, :]
    in_str = j < col.lengths[:, None]
    m = col.data
    lower_b = (m >= ord("a")) & (m <= ord("z")) & in_str
    upper_b = (m >= ord("A")) & (m <= ord("Z")) & in_str
    ok = ~jnp.any(upper_b, axis=1) & jnp.any(lower_b, axis=1)
    return Column(ok, dt.BOOL8, col.validity)


def zfill(col: Column, width: int) -> Column:
    """Left-pad with '0' to ``width`` bytes, inserting after a leading
    +/- sign (cudf ``zfill`` / Python ``str.zfill``). Strings already
    ``width`` or longer are unchanged."""
    _require_string(col)
    n, old = col.data.shape
    out_pad = max(old, width)
    c = repad(col, out_pad)
    j = jnp.arange(out_pad)[None, :]
    first = c.data[:, 0]
    has_sign = ((first == ord("+")) | (first == ord("-"))) & (
        c.lengths > 0
    )
    fill = jnp.maximum(width - c.lengths, 0)
    new_len = jnp.maximum(c.lengths, width)
    # body (past the sign) shifts right by fill; zeros in between
    shift = fill[:, None]
    sign_ofs = has_sign.astype(jnp.int32)[:, None]
    src = jnp.clip(j - shift, 0, out_pad - 1)
    moved = jnp.take_along_axis(c.data, src, axis=1)
    zero_zone = (j >= sign_ofs) & (j < sign_ofs + shift)
    data = jnp.where(zero_zone, jnp.uint8(ord("0")), moved)
    # sign byte stays at position 0
    data = data.at[:, 0].set(
        jnp.where(has_sign, first, data[:, 0]).astype(jnp.uint8)
    )
    data = jnp.where(j < new_len[:, None], data, 0).astype(jnp.uint8)
    return Column(data, dt.STRING, col.validity, new_len.astype(jnp.int32))


def capitalize(col: Column) -> Column:
    """First byte uppercased, the rest lowercased (cudf ``capitalize``)."""
    _require_string(col)
    lowered = lower(col).data
    first = lowered[:, 0]
    is_l = (first >= ord("a")) & (first <= ord("z"))
    data = lowered.at[:, 0].set(
        jnp.where(is_l, first - 32, first).astype(jnp.uint8)
    )
    return Column(data, dt.STRING, col.validity, col.lengths)


def title(col: Column) -> Column:
    """Uppercase every letter that follows a non-letter (cudf
    ``title``)."""
    _require_string(col)
    n, pad_w = col.data.shape
    lowered = lower(col).data
    is_letter = ((lowered >= ord("a")) & (lowered <= ord("z"))) | (
        (lowered >= ord("A")) & (lowered <= ord("Z"))
    )
    prev_letter = jnp.concatenate(
        [jnp.zeros((n, 1), jnp.bool_), is_letter[:, :-1]], axis=1
    )
    start = is_letter & ~prev_letter
    low_l = (lowered >= ord("a")) & (lowered <= ord("z"))
    data = jnp.where(start & low_l, lowered - 32, lowered)
    return Column(
        data.astype(jnp.uint8), dt.STRING, col.validity, col.lengths
    )


# ---------------------------------------------------------------------------
# URL encode/decode (cudf Java urlEncode/urlDecode)
# ---------------------------------------------------------------------------

_HEX_UPPER = np.frombuffer(b"0123456789ABCDEF", dtype=np.uint8)


def _hex_val(m):
    """Per-byte hex digit value (garbage for non-hex bytes)."""
    dig = m - ord("0")
    upper_l = m - ord("A") + 10
    lower_l = m - ord("a") + 10
    out = jnp.where((m >= ord("a")) & (m <= ord("f")), lower_l, dig)
    return jnp.where((m >= ord("A")) & (m <= ord("F")), upper_l, out)


def _compact_bytes(values, keep):
    """Left-compact the kept bytes of each row via the cumsum-positioned
    dump-column scatter (shared by url_decode and translate): returns
    ((n, pad) uint8 data zero-padded past the new lengths, (n,) int32
    lengths)."""
    n, pad_w = values.shape
    out_pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    new_len = jnp.sum(keep.astype(jnp.int32), axis=1).astype(jnp.int32)
    rows = jnp.arange(n)[:, None]
    idx = jnp.where(keep, out_pos, pad_w)
    out = jnp.zeros((n, pad_w + 1), jnp.uint8)
    out = out.at[rows, idx].set(
        jnp.where(keep, values, 0).astype(jnp.uint8)
    )
    data = jnp.where(
        jnp.arange(pad_w)[None, :] < new_len[:, None], out[:, :pad_w], 0
    )
    return data.astype(jnp.uint8), new_len


def url_decode(col: Column) -> Column:
    """Percent-decoding: ``%XX`` -> byte, ``+`` -> space (cudf
    ``url_decode`` / java.net.URLDecoder). Malformed escapes pass
    through literally."""
    _require_string(col)
    n, pad_w = col.data.shape
    j = jnp.arange(pad_w)[None, :]
    in_str = j < col.lengths[:, None]
    m = col.data
    is_hex = (
        ((m >= ord("0")) & (m <= ord("9")))
        | ((m >= ord("A")) & (m <= ord("F")))
        | ((m >= ord("a")) & (m <= ord("f")))
    )
    hex1 = jnp.concatenate(
        [is_hex[:, 1:], jnp.zeros((n, 1), jnp.bool_)], axis=1
    )
    hex2 = jnp.concatenate(
        [is_hex[:, 2:], jnp.zeros((n, 2), jnp.bool_)], axis=1
    )
    len_ok = (j + 2) < col.lengths[:, None]
    esc_start = (m == ord("%")) & hex1 & hex2 & len_ok & in_str
    v1 = _hex_val(jnp.roll(m, -1, axis=1))
    v2 = _hex_val(jnp.roll(m, -2, axis=1))
    decoded = (v1 * 16 + v2).astype(jnp.uint8)
    # a byte is a tail if one of the two previous bytes starts an escape
    tail1 = jnp.concatenate(
        [jnp.zeros((n, 1), jnp.bool_), esc_start[:, :-1]], axis=1
    )
    tail2 = jnp.concatenate(
        [jnp.zeros((n, 2), jnp.bool_), esc_start[:, :-2]], axis=1
    )
    emits = in_str & ~tail1 & ~tail2
    out_val = jnp.where(
        esc_start, decoded,
        jnp.where(m == ord("+"), jnp.uint8(ord(" ")), m),
    )
    data, new_len = _compact_bytes(out_val, emits)
    return Column(data, dt.STRING, col.validity, new_len)


def url_encode(col: Column) -> Column:
    """Percent-encoding: unreserved bytes (alnum, ``-_.~``) pass, space
    -> ``%20``, everything else -> ``%XX`` uppercase hex (cudf
    ``url_encode`` semantics). Eager: output pad width comes from the
    realized lengths (one device sync, the cudf call model)."""
    _require_string(col)
    n, pad_w = col.data.shape
    j = jnp.arange(pad_w)[None, :]
    in_str = j < col.lengths[:, None]
    m = col.data
    unreserved = (
        ((m >= ord("a")) & (m <= ord("z")))
        | ((m >= ord("A")) & (m <= ord("Z")))
        | ((m >= ord("0")) & (m <= ord("9")))
        | (m == ord("-")) | (m == ord("_"))
        | (m == ord(".")) | (m == ord("~"))
    )
    widths = jnp.where(in_str, jnp.where(unreserved, 1, 3), 0)
    ends = jnp.cumsum(widths, axis=1)
    starts = ends - widths
    new_len = ends[:, -1].astype(jnp.int32)
    if n == 0:
        return Column(col.data, dt.STRING, col.validity, col.lengths)
    pad_out = max(int(np.asarray(jnp.max(new_len))), 1)  # eager sync
    hexv = jnp.asarray(_HEX_UPPER)
    rows = jnp.arange(n)[:, None]
    dump = pad_out
    out = jnp.zeros((n, pad_out + 1), jnp.uint8)
    # byte 0: the literal or '%'
    b0 = jnp.where(unreserved, m, jnp.uint8(ord("%")))
    idx0 = jnp.where(in_str, jnp.minimum(starts, dump), dump)
    out = out.at[rows, idx0].set(jnp.where(in_str, b0, 0))
    # bytes 1-2: hex digits for escaped bytes
    esc = in_str & ~unreserved
    hi = hexv[(m >> 4).astype(jnp.int32)]
    lo_d = hexv[(m & 0xF).astype(jnp.int32)]
    idx1 = jnp.where(esc, jnp.minimum(starts + 1, dump), dump)
    out = out.at[rows, idx1].set(jnp.where(esc, hi, 0))
    idx2 = jnp.where(esc, jnp.minimum(starts + 2, dump), dump)
    out = out.at[rows, idx2].set(jnp.where(esc, lo_d, 0))
    data = out[:, :pad_out]
    data = jnp.where(
        jnp.arange(pad_out)[None, :] < new_len[:, None], data, 0
    )
    return Column(data.astype(jnp.uint8), dt.STRING, col.validity, new_len)


def concat_ws(sep: str | bytes, *cols: Column) -> Column:
    """Separator-joined rowwise concatenation (Spark ``concat_ws``):
    null inputs are SKIPPED (not propagated — unlike ``concat``) and
    the result is never null — rows where every input is null yield
    the empty string."""
    if not cols:
        raise ValueError("concat_ws needs at least one column")
    for c in cols:
        _require_string(c)
    sep_b = _literal_bytes(sep)
    n = cols[0].data.shape[0]
    sep_pad = max(len(sep_b), 1)
    sep_col = Column(
        jnp.broadcast_to(
            jnp.zeros((sep_pad,), jnp.uint8).at[: len(sep_b)].set(
                jnp.asarray(sep_b)
            ),
            (n, sep_pad),
        ),
        dt.STRING,
        None,
        jnp.full((n,), len(sep_b), jnp.int32),
    )

    out = None
    started = jnp.zeros((n,), jnp.bool_)  # any non-null piece emitted yet
    for c in cols:
        have = compute.valid_mask(c)
        lens = jnp.where(have, c.lengths, 0)
        data = c.data
        if c.validity is not None:
            # re-zero bytes past the nulled-to-0 lengths: null rows may
            # carry real bytes under their mask, and the string
            # invariant (column.py: bytes past lengths[i] are zero) is
            # load-bearing for order keys and equality. (concat()
            # re-zeroes its own output, so this matters on the
            # single-column direct-return path.)
            data = jnp.where(
                jnp.arange(c.data.shape[1])[None, :] < lens[:, None],
                c.data,
                0,
            ).astype(jnp.uint8)
        piece = Column(data, dt.STRING, None, lens)
        if out is None:
            out = piece
            started = have
            continue
        # separator only between emitted pieces
        use_sep = started & have
        sepc = Column(
            sep_col.data, dt.STRING, None,
            jnp.where(use_sep, sep_col.lengths, 0),
        )
        out = concat(concat(out, sepc), piece)
        started = started | have
    return Column(out.data, dt.STRING, None, out.lengths)


def substring_column(col: Column, starts: Column, lengths: Column) -> Column:
    """Per-row substring with 0-based start and length COLUMNS (the
    dynamic form of ``substring``; cudf ``slice_strings`` with column
    offsets). Out-of-range starts clamp; null starts/lengths propagate."""
    _require_string(col)
    n, pad_w = col.data.shape
    s = jnp.clip(starts.data.astype(jnp.int32), 0, None)
    s = jnp.minimum(s, col.lengths)
    want = jnp.clip(lengths.data.astype(jnp.int32), 0, None)
    new_len = jnp.minimum(want, col.lengths - s)
    out = _shift_left(col, s, new_len)
    valid = compute.merge_validity(col, starts, lengths)
    return Column(out.data, dt.STRING, valid, out.lengths)


def translate(col: Column, from_chars: str | bytes,
              to_chars: str | bytes) -> Column:
    """Per-byte mapping (Spark ``translate``): byte ``from_chars[i]``
    becomes ``to_chars[i]``; positions of ``from_chars`` beyond
    ``len(to_chars)`` are DELETED. One 256-entry LUT gather does the
    mapping; deletions compact with the cumsum-positioned scatter the
    url codec uses."""
    _require_string(col)
    for name, s in (("from_chars", from_chars), ("to_chars", to_chars)):
        if isinstance(s, str) and not s.isascii():
            raise ValueError(
                f"translate: {name} must be ASCII (byte-level op; "
                "multi-byte UTF-8 chars would corrupt unrelated bytes)"
            )
    f = _literal_bytes(from_chars)
    t = _literal_bytes(to_chars)
    lut = np.arange(256, dtype=np.int32)  # identity; -1 = delete
    seen: set = set()
    for i, ch in enumerate(f):
        if ch in seen:
            continue  # first occurrence wins (Spark/Oracle TRANSLATE)
        seen.add(ch)
        lut[ch] = t[i] if i < len(t) else -1
    lut_d = jnp.asarray(lut)
    n, pad_w = col.data.shape
    j = jnp.arange(pad_w)[None, :]
    in_str = j < col.lengths[:, None]
    mapped = lut_d[col.data]  # uint8 indexes the 256-entry LUT directly
    if not (lut < 0).any():
        data = jnp.where(in_str, mapped, 0).astype(jnp.uint8)
        return Column(data, dt.STRING, col.validity, col.lengths)
    keep = in_str & (mapped >= 0)
    data, new_len = _compact_bytes(mapped, keep)
    return Column(data, dt.STRING, col.validity, new_len)
