"""String operations over the padded byte-matrix layout.

cudf strings are (offsets, chars) variable-width columns; under XLA's
static-shape regime strings live as an (n, pad) uint8 matrix + lengths
(SURVEY.md §7 hard part 2 — padding instead of offsets). All ops below are
plain vectorized byte arithmetic, so they fuse like any other elementwise
op; pad width is a compile-time constant per column.

ASCII-oriented where case matters (upper/lower), byte-exact elsewhere —
matching Spark's behavior for ASCII data; full UTF-8 case mapping is a
later phase.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column
from . import compute
from . import keys as keys_mod


def _require_string(col: Column):
    if not col.dtype.is_string:
        raise TypeError("expected a STRING column")


def length(col: Column) -> Column:
    """Byte length (Spark ``length`` counts chars; equal for ASCII)."""
    _require_string(col)
    return Column(col.lengths.astype(jnp.int32), dt.INT32, col.validity)


def _case_map(col: Column, to_upper: bool) -> Column:
    _require_string(col)
    mat = col.data
    if to_upper:
        shift = ((mat >= ord("a")) & (mat <= ord("z"))).astype(jnp.uint8) * 32
        out = mat - shift
    else:
        shift = ((mat >= ord("A")) & (mat <= ord("Z"))).astype(jnp.uint8) * 32
        out = mat + shift
    return Column(out, dt.STRING, col.validity, col.lengths)


def upper(col: Column) -> Column:
    return _case_map(col, True)


def lower(col: Column) -> Column:
    return _case_map(col, False)


def _literal_bytes(pat: str | bytes) -> np.ndarray:
    if isinstance(pat, str):
        pat = pat.encode("utf-8", "surrogateescape")
    return np.frombuffer(pat, dtype=np.uint8)


def contains(col: Column, pattern: str | bytes) -> Column:
    """Literal substring search (Spark ``contains``), via a sliding
    window compare — static pad width makes this a fixed unrolled scan."""
    _require_string(col)
    pat = _literal_bytes(pattern)
    m = len(pat)
    n, pad = col.data.shape
    if m == 0:
        return Column(jnp.ones((n,), jnp.bool_), dt.BOOL8, col.validity)
    if m > pad:
        return Column(jnp.zeros((n,), jnp.bool_), dt.BOOL8, col.validity)
    mat = col.data
    patv = jnp.asarray(pat)
    found = jnp.zeros((n,), dtype=jnp.bool_)
    for start in range(pad - m + 1):
        window_eq = jnp.all(mat[:, start : start + m] == patv[None, :], axis=1)
        in_len = col.lengths >= start + m
        found = found | (window_eq & in_len)
    return Column(found, dt.BOOL8, col.validity)


def starts_with(col: Column, pattern: str | bytes) -> Column:
    _require_string(col)
    pat = _literal_bytes(pattern)
    m = len(pat)
    n, pad = col.data.shape
    if m == 0:
        return Column(jnp.ones((n,), jnp.bool_), dt.BOOL8, col.validity)
    if m > pad:
        return Column(jnp.zeros((n,), jnp.bool_), dt.BOOL8, col.validity)
    ok = jnp.all(col.data[:, :m] == jnp.asarray(pat)[None, :], axis=1) & (
        col.lengths >= m
    )
    return Column(ok, dt.BOOL8, col.validity)


def ends_with(col: Column, pattern: str | bytes) -> Column:
    _require_string(col)
    pat = _literal_bytes(pattern)
    m = len(pat)
    n, pad = col.data.shape
    if m == 0:
        return Column(jnp.ones((n,), jnp.bool_), dt.BOOL8, col.validity)
    if m > pad:
        return Column(jnp.zeros((n,), jnp.bool_), dt.BOOL8, col.validity)
    # gather the tail window [len-m, len) per row
    starts = jnp.clip(col.lengths - m, 0, pad - m)
    idx = starts[:, None] + jnp.arange(m)[None, :]
    tail = jnp.take_along_axis(col.data, idx, axis=1)
    ok = jnp.all(tail == jnp.asarray(pat)[None, :], axis=1) & (col.lengths >= m)
    return Column(ok, dt.BOOL8, col.validity)


def substring(col: Column, start: int, slice_len: int) -> Column:
    """0-based substring with fixed start/length (Spark ``substring``)."""
    _require_string(col)
    n, pad = col.data.shape
    out_pad = max(min(slice_len, pad), 1)
    shifted = jnp.roll(col.data, -start, axis=1)
    out = shifted[:, :out_pad]
    # zero bytes past the new length
    new_len = jnp.clip(col.lengths - start, 0, slice_len)
    mask = jnp.arange(out_pad)[None, :] < new_len[:, None]
    out = jnp.where(mask, out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, new_len.astype(jnp.int32))


def concat(a: Column, b: Column) -> Column:
    """Rowwise concatenation (Spark ``concat``: null if either null)."""
    _require_string(a)
    _require_string(b)
    n, pad_a = a.data.shape
    _, pad_b = b.data.shape
    out_pad = pad_a + pad_b
    out = jnp.zeros((n, out_pad), dtype=jnp.uint8)
    out = out.at[:, :pad_a].set(a.data)
    # place b at offset len(a) via gather: out[i, j] = b[i, j - len_a[i]]
    j = jnp.arange(out_pad)[None, :]
    src = j - a.lengths[:, None]
    valid_src = (src >= 0) & (src < pad_b)
    b_g = jnp.take_along_axis(
        b.data, jnp.clip(src, 0, pad_b - 1), axis=1
    )
    out = jnp.where(valid_src & (j >= a.lengths[:, None]), b_g, out).astype(
        jnp.uint8
    )
    new_len = a.lengths + b.lengths
    # zero past length (b's pad garbage)
    out = jnp.where(j < new_len[:, None], out, 0).astype(jnp.uint8)
    return Column(out, dt.STRING, compute.merge_validity(a, b), new_len)


def repad(col: Column, pad: int) -> Column:
    """Return the column with a different pad width (>= max length)."""
    _require_string(col)
    n, old = col.data.shape
    if pad == old:
        return col
    if pad > old:
        out = jnp.zeros((n, pad), dtype=jnp.uint8).at[:, :old].set(col.data)
    else:
        out = jnp.where(
            jnp.arange(pad)[None, :] < col.lengths[:, None], col.data[:, :pad], 0
        ).astype(jnp.uint8)
    return Column(out, dt.STRING, col.validity, col.lengths)


def binary_op(op: str, a: Column, b: Column) -> Column:
    """String comparisons dispatch through order keys (memcmp order)."""
    _require_string(a)
    _require_string(b)
    common = max(a.data.shape[1], b.data.shape[1])
    a = repad(a, common)
    b = repad(b, common)
    aw = keys_mod.column_order_keys(a)
    bw = keys_mod.column_order_keys(b)
    eq_w = jnp.ones((a.data.shape[0],), dtype=jnp.bool_)
    lt_w = jnp.zeros((a.data.shape[0],), dtype=jnp.bool_)
    for x, y in zip(aw, bw):
        lt_w = lt_w | (eq_w & (x < y))
        eq_w = eq_w & (x == y)
    valid = compute.merge_validity(a, b)
    table = {
        "eq": eq_w,
        "ne": ~eq_w,
        "lt": lt_w,
        "le": lt_w | eq_w,
        "gt": ~(lt_w | eq_w),
        "ge": ~lt_w,
    }
    if op == "add":  # Spark || / concat
        return concat(a, b)
    if op not in table:
        raise TypeError(f"binary op {op!r} not supported for strings")
    return Column(table[op], dt.BOOL8, valid)


def cast(col: Column, to: dt.DType) -> Column:
    raise NotImplementedError(
        "string casts land with the format/parse phase"
    )
