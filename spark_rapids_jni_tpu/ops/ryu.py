"""Vectorized Ryu: shortest round-trip decimal digits for f64/f32.

The float->string cast surface needs, per element, the shortest decimal
``digits x 10^exp10`` that parses back to the exact same float — the
problem GPU libcudf solves with a device Ryu port (its
``ftos_converter`` inside strings/convert) and the reference inherits
through cudf's cast surface. A TPU has no per-thread scalar loops, so
this is Ryu re-expressed as fixed-shape u64 vector arithmetic:

* the 128-bit ``(5^q)`` / ``(2^k / 5^q)`` factor tables are generated
  at import time from exact Python bigints (no transcribed magic
  tables — the bit counts are the published invariants);
* the 64x128->shifted-64 multiplies decompose into 32-bit limbs (every
  32x32 product is exact in u64);
* the data-dependent digit-trimming loops become fixed-trip masked
  ``fori_loop``s (<= 17 digits for f64, <= 9 for f32), shared by both
  cores (:func:`_trim_loop`).

Returns digits + decimal exponent + special-value masks; the string
assembly (Java ``Double.toString`` placement rules: plain decimal for
1e-3 <= |v| < 1e7, scientific otherwise) lives with the other
formatters in ``ops/strings``.

Reference parity: cudf ``cpp/src/strings/convert/convert_floats.cu``
(ftos_converter's shortest-significand contract); algorithm: Ulf
Adams, "Ryu: fast float-to-string conversion", PLDI 2018.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# tables (exact bigint generation, split into u64 limbs)
# ---------------------------------------------------------------------------

_D_POW5_INV_BITCOUNT = 125
_D_POW5_BITCOUNT = 125
_F_POW5_INV_BITCOUNT = 59
_F_POW5_BITCOUNT = 61


def _pow5bits(e):
    """ceil(log2(5^e)) + 1-ish bound used by Ryu: exact for
    0 <= e <= 3528. Works on Python ints (table generation) and traced
    int32 arrays (per-element j/k shifts) alike."""
    return ((e * 1217359) >> 19) + 1


@functools.lru_cache(maxsize=1)
def _double_tables():
    inv_lo, inv_hi = [], []
    for q in range(342):
        pow5 = 5 ** q
        j = _pow5bits(q) - 1 + _D_POW5_INV_BITCOUNT
        inv = (1 << j) // pow5 + 1
        inv_lo.append(inv & 0xFFFFFFFFFFFFFFFF)
        inv_hi.append(inv >> 64)
    sp_lo, sp_hi = [], []
    for i in range(326):
        pow5 = 5 ** i
        shift = _pow5bits(i) - _D_POW5_BITCOUNT
        v = pow5 >> shift if shift >= 0 else pow5 << -shift
        sp_lo.append(v & 0xFFFFFFFFFFFFFFFF)
        sp_hi.append(v >> 64)
    u = lambda a: np.array(a, dtype=np.uint64)  # numpy: safe to cache
    return u(inv_lo), u(inv_hi), u(sp_lo), u(sp_hi)


@functools.lru_cache(maxsize=1)
def _float_tables():
    inv = []
    for q in range(31):
        pow5 = 5 ** q
        j = _pow5bits(q) - 1 + _F_POW5_INV_BITCOUNT
        inv.append((1 << j) // pow5 + 1)
    sp = []
    for i in range(48):
        pow5 = 5 ** i
        shift = _pow5bits(i) - _F_POW5_BITCOUNT
        sp.append(pow5 >> shift if shift >= 0 else pow5 << -shift)
    u = lambda a: np.array(a, dtype=np.uint64)  # numpy: safe to cache
    return u(inv), u(sp)


# ---------------------------------------------------------------------------
# u64 limb arithmetic
# ---------------------------------------------------------------------------

_MASK32 = jnp.uint64(0xFFFFFFFF)


def _umul128(a, b):
    """(hi, lo) of the exact 128-bit product of two u64 vectors."""
    a_lo = a & _MASK32
    a_hi = a >> jnp.uint64(32)
    b_lo = b & _MASK32
    b_hi = b >> jnp.uint64(32)
    ll = a_lo * b_lo
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    mid = (ll >> jnp.uint64(32)) + (lh & _MASK32) + (hl & _MASK32)
    lo = (mid << jnp.uint64(32)) | (ll & _MASK32)
    hi = (
        hh
        + (lh >> jnp.uint64(32))
        + (hl >> jnp.uint64(32))
        + (mid >> jnp.uint64(32))
    )
    return hi, lo


def _shiftright128(hi, lo, dist):
    """(hi:lo) >> dist for 0 < dist < 64 (vector dist)."""
    return (hi << (jnp.uint64(64) - dist)) | (lo >> dist)


def _mulshift64(m, factor_hi, factor_lo, j):
    """(m * (factor_hi:factor_lo)) >> j, with 64 < j < 128 and the
    result guaranteed to fit u64 (Ryu's invariant)."""
    b0_hi, _ = _umul128(m, factor_lo)
    b2_hi, b2_lo = _umul128(m, factor_hi)
    # sum = b2 + (b0 >> 64): 128-bit add, carry into the high word
    s_lo = b2_lo + b0_hi
    carry = (s_lo < b2_lo).astype(jnp.uint64)
    s_hi = b2_hi + carry
    return _shiftright128(s_hi, s_lo, j - jnp.uint64(64))


def _mulshift32(m, factor, shift):
    """(m * factor) >> shift with m < 2^27, factor < 2^61, 32 < shift:
    the f2s decomposition — both partial products fit u64 exactly."""
    f_lo = factor & _MASK32
    f_hi = factor >> jnp.uint64(32)
    return (((m * f_lo) >> jnp.uint64(32)) + m * f_hi) >> (
        shift - jnp.uint64(32)
    )


_POW5_U64 = np.array([5 ** k for k in range(23)], dtype=np.uint64)


def _pow5_factor_ge(value, p, max_iter=None):
    """True where 5^p divides value (value != 0; p <= 22, inside the
    callers' q-window guards). One gather + one mod — 5^22 fits u64,
    so no loop is needed."""
    del max_iter  # kept for call-site symmetry; the table covers p
    t = jnp.asarray(_POW5_U64)[jnp.clip(p, 0, 22)]
    return value % t == 0


def _multiple_of_pow2(value, p):
    one = jnp.uint64(1)
    return (value & ((one << p.astype(jnp.uint64)) - one)) == 0


def _log10_pow2(e):  # e in [0, 1650)
    return (e * 78913) >> 18


def _log10_pow5(e):  # e in [0, 2620)
    return (e * 732923) >> 20


def _trim_loop(vr, vp, vm, last0, vr_tz, vm_tz, trips):
    """The Ryu digit-removal loops as fixed-trip masked fori_loops.

    First loop removes digits while ``vp/10 > vm/10`` (tracking the
    last removed vr digit and both trailing-zero flags); the second
    continues while vm ends in 0, applied only where ``vm_tz`` held
    (the reference's acceptBounds path). Shared by both cores —
    ``trips`` bounds the digit count (22 for f64, 11 for f32).

    Returns ``(vr, vm, removed, last, vr_tz, vm_tz)`` — vm is the
    TRIMMED lower bound: the final ``vr == vm`` round-up decision must
    compare like with like (comparing against the pre-trim vm breaks
    the boundary round-up on e.g. 2^-24)."""
    ten = jnp.uint64(10)

    def trim_main(_, state):
        vr_, vp_, vm_, removed, last, vr_tz_, vm_tz_ = state
        vp_d = vp_ // ten
        vm_d = vm_ // ten
        go = vp_d > vm_d
        vr_d = vr_ // ten
        vr_rem = (vr_ - ten * vr_d).astype(jnp.int32)
        vm_rem0 = vm_ - ten * vm_d == 0
        return (
            jnp.where(go, vr_d, vr_),
            jnp.where(go, vp_d, vp_),
            jnp.where(go, vm_d, vm_),
            removed + go.astype(jnp.int32),
            jnp.where(go, vr_rem, last),
            jnp.where(go, vr_tz_ & (last == 0), vr_tz_),
            jnp.where(go, vm_tz_ & vm_rem0, vm_tz_),
        )

    state = (
        vr, vp, vm,
        jnp.zeros(vr.shape, jnp.int32),
        last0,
        vr_tz, vm_tz,
    )
    vr, vp, vm, removed, last, vr_tz, vm_tz = jax.lax.fori_loop(
        0, trips, trim_main, state
    )

    def trim_vm_zeros(_, state):
        vr_, vp_, vm_, removed, last, vr_tz_ = state
        vm_d = vm_ // ten
        go = (vm_ - ten * vm_d == 0) & (vm_ != 0)
        vr_d = vr_ // ten
        vr_rem = (vr_ - ten * vr_d).astype(jnp.int32)
        return (
            jnp.where(go, vr_d, vr_),
            jnp.where(go, vp_ // ten, vp_),
            jnp.where(go, vm_d, vm_),
            removed + go.astype(jnp.int32),
            jnp.where(go, vr_rem, last),
            jnp.where(go, vr_tz_ & (last == 0), vr_tz_),
        )

    state2 = (vr, vp, vm, removed, last, vr_tz)
    vr2, _, vm2, removed2, last2, vr_tz2 = jax.lax.fori_loop(
        0, trips, trim_vm_zeros, state2
    )
    vr = jnp.where(vm_tz, vr2, vr)
    vm = jnp.where(vm_tz, vm2, vm)
    removed = jnp.where(vm_tz, removed2, removed)
    last = jnp.where(vm_tz, last2, last)
    vr_tz = jnp.where(vm_tz, vr_tz2, vr_tz)
    return vr, vm, removed, last, vr_tz, vm_tz


# ---------------------------------------------------------------------------
# f64 core
# ---------------------------------------------------------------------------


def shortest_decimal64(bits):
    """Ryu d2d over a u64 bit-pattern vector.

    Returns ``(sign, digits, exp10, is_zero, is_inf, is_nan)`` where
    for finite nonzero values ``value = ±digits * 10^exp10`` is the
    shortest, correctly-rounded representation (digits has no trailing
    zeros)."""
    bits = bits.astype(jnp.uint64)
    one = jnp.uint64(1)
    mant_mask = (one << jnp.uint64(52)) - one
    ieee_m = bits & mant_mask
    ieee_e = ((bits >> jnp.uint64(52)) & jnp.uint64(0x7FF)).astype(
        jnp.int32
    )
    sign = (bits >> jnp.uint64(63)) != 0
    is_zero = (ieee_e == 0) & (ieee_m == 0)
    is_inf = (ieee_e == 0x7FF) & (ieee_m == 0)
    is_nan = (ieee_e == 0x7FF) & (ieee_m != 0)

    subnormal = ieee_e == 0
    e2 = jnp.where(subnormal, 1, ieee_e) - 1023 - 52 - 2
    m2 = jnp.where(
        subnormal, ieee_m, ieee_m | (one << jnp.uint64(52))
    )
    even = (m2 & one) == 0
    accept = even

    mv = jnp.uint64(4) * m2
    # mm = mv - 1 - mm_shift
    mm_shift = ((ieee_m != 0) | (ieee_e <= 1)).astype(jnp.uint64)

    inv_lo, inv_hi, sp_lo, sp_hi = (
        jnp.asarray(t) for t in _double_tables()
    )

    # ---- e2 >= 0 branch -------------------------------------------------
    e2c = jnp.maximum(e2, 0)
    q_pos = _log10_pow2(e2c) - (e2c > 3).astype(jnp.int32)
    k_pos = _D_POW5_INV_BITCOUNT + _pow5bits(q_pos) - 1
    j_pos = (-e2c + q_pos + k_pos).astype(jnp.uint64)
    qp_idx = jnp.clip(q_pos, 0, 341)
    fp_hi = inv_hi[qp_idx]
    fp_lo = inv_lo[qp_idx]

    # ---- e2 < 0 branch --------------------------------------------------
    e2n = jnp.maximum(-e2, 0)
    q_neg = _log10_pow5(e2n) - (e2n > 1).astype(jnp.int32)
    i_neg = jnp.clip(e2n - q_neg, 0, 325)
    k_neg = _pow5bits(i_neg) - _D_POW5_BITCOUNT
    j_neg = (q_neg - k_neg).astype(jnp.uint64)
    fn_hi = sp_hi[i_neg]
    fn_lo = sp_lo[i_neg]

    pos = e2 >= 0
    f_hi = jnp.where(pos, fp_hi, fn_hi)
    f_lo = jnp.where(pos, fp_lo, fn_lo)
    j = jnp.where(pos, j_pos, j_neg)
    q = jnp.where(pos, q_pos, q_neg)
    e10 = jnp.where(pos, q_pos, q_neg + e2)

    mp = mv + jnp.uint64(2)
    mm = mv - one - mm_shift
    vr = _mulshift64(mv, f_hi, f_lo, j)
    vp = _mulshift64(mp, f_hi, f_lo, j)
    vm = _mulshift64(mm, f_hi, f_lo, j)

    # trailing-zero bookkeeping
    vr_tz = jnp.zeros(bits.shape, jnp.bool_)
    vm_tz = jnp.zeros(bits.shape, jnp.bool_)
    vp_adj = jnp.zeros(bits.shape, jnp.bool_)

    # e2 >= 0, q <= 21 cases
    small_q = pos & (q <= 21)
    mv_mod5 = (mv - jnp.uint64(5) * (mv // jnp.uint64(5))) == 0
    vr_tz = jnp.where(
        small_q & mv_mod5, _pow5_factor_ge(mv, q, 23), vr_tz
    )
    vm_tz = jnp.where(
        small_q & ~mv_mod5 & accept, _pow5_factor_ge(mm, q, 23), vm_tz
    )
    vp_adj = jnp.where(
        small_q & ~mv_mod5 & ~accept, _pow5_factor_ge(mp, q, 23), vp_adj
    )

    # e2 < 0 cases
    neg_q1 = ~pos & (q <= 1)
    vr_tz = jnp.where(neg_q1, True, vr_tz)
    vm_tz = jnp.where(neg_q1 & accept, mm_shift == one, vm_tz)
    vp_adj = jnp.where(neg_q1 & ~accept, True, vp_adj)
    neg_q63 = ~pos & (q > 1) & (q < 63)
    vr_tz = jnp.where(
        neg_q63, _multiple_of_pow2(mv, q - 1), vr_tz
    )

    vp = vp - vp_adj.astype(jnp.uint64)

    vr, vm, removed, last, vr_tz, vm_tz = _trim_loop(
        vr, vp, vm, jnp.zeros(bits.shape, jnp.int32), vr_tz, vm_tz, 22
    )

    # round-to-even on the exact halfway remainder
    half_even = vr_tz & (last == 5) & ((vr & one) == 0)
    last = jnp.where(half_even, jnp.int32(4), last)
    round_up = ((vr == vm) & (~accept | ~vm_tz)) | (last >= 5)
    digits = vr + round_up.astype(jnp.uint64)
    exp10 = e10 + removed
    return sign, digits, exp10, is_zero, is_inf, is_nan


# ---------------------------------------------------------------------------
# f32 core
# ---------------------------------------------------------------------------


def shortest_decimal32(bits):
    """Ryu f2f over a u32 bit-pattern vector; same contract as
    :func:`shortest_decimal64` (digits fit 9 decimal digits)."""
    bits = bits.astype(jnp.uint32)
    one = jnp.uint64(1)
    ieee_m = (bits & jnp.uint32((1 << 23) - 1)).astype(jnp.uint64)
    ieee_e = ((bits >> jnp.uint32(23)) & jnp.uint32(0xFF)).astype(
        jnp.int32
    )
    sign = (bits >> jnp.uint32(31)) != 0
    is_zero = (ieee_e == 0) & (ieee_m == 0)
    is_inf = (ieee_e == 0xFF) & (ieee_m == 0)
    is_nan = (ieee_e == 0xFF) & (ieee_m != 0)

    subnormal = ieee_e == 0
    e2 = jnp.where(subnormal, 1, ieee_e) - 127 - 23 - 2
    m2 = jnp.where(subnormal, ieee_m, ieee_m | (one << jnp.uint64(23)))
    even = (m2 & one) == 0
    accept = even

    mv = jnp.uint64(4) * m2
    mm_shift = ((ieee_m != 0) | (ieee_e <= 1)).astype(jnp.uint64)
    mp = mv + jnp.uint64(2)
    mm = mv - one - mm_shift

    inv, sp = (jnp.asarray(t) for t in _float_tables())

    # ---- e2 >= 0 -------------------------------------------------------
    e2c = jnp.maximum(e2, 0)
    q_pos = _log10_pow2(e2c)
    k_pos = _F_POW5_INV_BITCOUNT + _pow5bits(q_pos) - 1
    j_pos = (-e2c + q_pos + k_pos).astype(jnp.uint64)
    qp_idx = jnp.clip(q_pos, 0, 30)
    f_pos = inv[qp_idx]
    # one-digit-lower recompute for the no-trim rounding case
    qm1 = jnp.clip(q_pos - 1, 0, 30)
    k_pos1 = _F_POW5_INV_BITCOUNT + _pow5bits(qm1) - 1
    j_pos1 = (-e2c + (q_pos - 1) + k_pos1).astype(jnp.uint64)
    f_pos1 = inv[qm1]

    # ---- e2 < 0 --------------------------------------------------------
    e2n = jnp.maximum(-e2, 0)
    q_neg = _log10_pow5(e2n)
    i_neg = jnp.clip(e2n - q_neg, 0, 47)
    k_neg = _pow5bits(i_neg) - _F_POW5_BITCOUNT
    j_neg = (q_neg - k_neg).astype(jnp.uint64)
    f_neg = sp[i_neg]
    i1 = jnp.clip(i_neg + 1, 0, 47)
    j_neg1 = (
        q_neg - 1 - (_pow5bits(i1) - _F_POW5_BITCOUNT)
    ).astype(jnp.uint64)
    f_neg1 = sp[i1]

    pos = e2 >= 0
    factor = jnp.where(pos, f_pos, f_neg)
    j = jnp.where(pos, j_pos, j_neg)
    q = jnp.where(pos, q_pos, q_neg)
    e10 = jnp.where(pos, q_pos, q_neg + e2)
    factor1 = jnp.where(pos, f_pos1, f_neg1)
    j1 = jnp.where(pos, j_pos1, j_neg1)

    vr = _mulshift32(mv, factor, j)
    vp = _mulshift32(mp, factor, j)
    vm = _mulshift32(mm, factor, j)

    ten = jnp.uint64(10)
    # f2s precomputes lastRemovedDigit one scale down when the trim
    # loop will not run (q != 0 and (vp-1)/10 <= vm/10)
    pre_last = (_mulshift32(mv, factor1, j1) % ten).astype(jnp.int32)
    need_pre = (q != 0) & ((vp - one) // ten <= vm // ten)
    last0 = jnp.where(need_pre, pre_last, 0)

    vr_tz = jnp.zeros(bits.shape, jnp.bool_)
    vm_tz = jnp.zeros(bits.shape, jnp.bool_)
    vp_adj = jnp.zeros(bits.shape, jnp.bool_)

    small_q = pos & (q <= 9)
    mv_mod5 = (mv % jnp.uint64(5)) == 0
    vr_tz = jnp.where(
        small_q & mv_mod5, _pow5_factor_ge(mv, q, 11), vr_tz
    )
    vm_tz = jnp.where(
        small_q & ~mv_mod5 & accept, _pow5_factor_ge(mm, q, 11), vm_tz
    )
    vp_adj = jnp.where(
        small_q & ~mv_mod5 & ~accept, _pow5_factor_ge(mp, q, 11), vp_adj
    )

    neg_q1 = ~pos & (q <= 1)
    vr_tz = jnp.where(neg_q1, True, vr_tz)
    vm_tz = jnp.where(neg_q1 & accept, mm_shift == one, vm_tz)
    vp_adj = jnp.where(neg_q1 & ~accept, True, vp_adj)
    neg_q31 = ~pos & (q > 1) & (q < 31)
    vr_tz = jnp.where(neg_q31, _multiple_of_pow2(mv, q - 1), vr_tz)

    vp = vp - vp_adj.astype(jnp.uint64)

    vr, vm, removed, last, vr_tz, vm_tz = _trim_loop(
        vr, vp, vm, last0, vr_tz, vm_tz, 11
    )

    half_even = vr_tz & (last == 5) & ((vr & one) == 0)
    last = jnp.where(half_even, jnp.int32(4), last)
    round_up = ((vr == vm) & (~accept | ~vm_tz)) | (last >= 5)
    digits = vr + round_up.astype(jnp.uint64)
    exp10 = e10 + removed
    return sign, digits, exp10, is_zero, is_inf, is_nan


# ---------------------------------------------------------------------------
# Eisel-Lemire: correctly-rounded decimal -> binary (the parse inverse)
# ---------------------------------------------------------------------------
#
# The string->float cast needs w x 10^q rounded correctly to f64/f32.
# This is the Eisel-Lemire fast path (Lemire, "Number parsing at a
# gigabyte per second", SP&E 2021; the algorithm under fast_float and
# Go/Rust strconv) vectorized the same way as the Ryu core above: one
# 128-bit truncated power-of-five table (exact bigint generation), the
# 64x64->128 product in 32-bit limbs, and branch-free mask selection.
# For w <= 19 digits the 128-bit product provably suffices (paper
# Thm. 1 + the explicit round-to-even window), so no slow path exists
# on this route; callers truncate longer mantissas to their top 19
# digits (documented <=1-ulp corner shared with every fast parser
# before its big-int fallback).

_EL_SMALLEST_Q = -342
_EL_LARGEST_Q = 308


@functools.lru_cache(maxsize=1)
def _el_pow5_tables():
    his, los = [], []
    for q in range(_EL_SMALLEST_Q, _EL_LARGEST_Q + 1):
        if q >= 0:
            v = 5 ** q
            b = v.bit_length()
            v = v << (128 - b) if b <= 128 else v >> (b - 128)
        else:
            p = 5 ** (-q)
            b = p.bit_length() + 127
            v = (1 << b) // p + 1
        assert v.bit_length() == 128
        his.append((v >> 64) & 0xFFFFFFFFFFFFFFFF)
        los.append(v & 0xFFFFFFFFFFFFFFFF)
    u = lambda a: np.array(a, dtype=np.uint64)  # numpy: safe to cache
    return u(his), u(los)


def _clz64(w):
    """Count leading zeros of a u64 vector (w != 0)."""
    n = jnp.zeros(w.shape, jnp.uint64)
    x = w
    for shift in (32, 16, 8, 4, 2, 1):
        s = jnp.uint64(shift)
        top_empty = (x >> (jnp.uint64(64) - s)) == 0  # top s bits clear
        n = n + jnp.where(top_empty, s, jnp.uint64(0))
        x = jnp.where(top_empty, x << s, x)
    return n


def decimal_to_bits(w, q, bits64=True):
    """w x 10^q correctly rounded to an IEEE bit pattern (positive).

    ``w`` u64 (non-zero mantissa; callers handle w == 0), ``q`` i32
    decimal exponent. Returns u64 bit patterns (f64) or u32-valued u64
    (f32), with overflow -> +inf bits and underflow -> +0 bits."""
    w = w.astype(jnp.uint64)
    q = q.astype(jnp.int32)
    one = jnp.uint64(1)

    if bits64:
        expl_bits, prec_shift = 52, jnp.uint64(9)  # 64 - (52 + 3)
        min_exp = -1023
        tie_lo, tie_hi = -4, 23
        inf_exp = 0x7FF
    else:
        expl_bits, prec_shift = 23, jnp.uint64(38)  # 64 - (23 + 3)
        min_exp = -127
        tie_lo, tie_hi = -17, 10
        inf_exp = 0xFF

    qc = jnp.clip(q, _EL_SMALLEST_Q, _EL_LARGEST_Q)
    t_hi, t_lo = (jnp.asarray(t) for t in _el_pow5_tables())
    f_hi = t_hi[qc - _EL_SMALLEST_Q]
    f_lo = t_lo[qc - _EL_SMALLEST_Q]

    lz = _clz64(jnp.where(w == 0, one, w))
    wn = w << lz

    hi, lo = _umul128(wn, f_hi)
    # refine with the low table word when the top bits are ambiguous
    prec_mask = (one << prec_shift) - one
    need2 = (hi & prec_mask) == prec_mask
    hi2, _ = _umul128(wn, f_lo)
    lo_r = lo + hi2
    carry = (lo_r < lo).astype(jnp.uint64)
    hi = jnp.where(need2, hi + carry, hi)
    lo = jnp.where(need2, lo_r, lo)

    upperbit = hi >> jnp.uint64(63)
    m = hi >> (upperbit + prec_shift)
    # power(q) = floor(q * log2(10)) + 63
    pow_q = ((217706 * q) >> 16) + 63
    power2 = (
        pow_q + upperbit.astype(jnp.int32) - lz.astype(jnp.int32)
        - min_exp
    )

    # ---- subnormal path ---------------------------------------------
    sub_shift = jnp.clip(1 - power2, 0, 63).astype(jnp.uint64)
    m_sub = m >> sub_shift
    m_sub = (m_sub + (m_sub & one)) >> one
    sub_pow = (m_sub >> jnp.uint64(expl_bits)).astype(jnp.int32)
    sub_bits = m_sub | (
        sub_pow.astype(jnp.uint64) << jnp.uint64(expl_bits)
    )
    underflow = (1 - power2) >= 64

    # ---- normal path ------------------------------------------------
    # round-ties-to-even window: the product can be exactly halfway
    # only for q in [tie_lo, tie_hi]; detect and clear the round bit
    tie = (
        (lo <= one)
        & (q >= tie_lo)
        & (q <= tie_hi)
        & ((m & jnp.uint64(3)) == one)
        & ((m << (upperbit + prec_shift)) == hi)
    )
    m_n = jnp.where(tie, m & ~one, m)
    m_n = (m_n + (m_n & one)) >> one
    ovf = m_n >= (one << jnp.uint64(expl_bits + 1))
    m_n = jnp.where(ovf, one << jnp.uint64(expl_bits), m_n)
    power2 = power2 + ovf.astype(jnp.int32)
    m_n = m_n & ~(one << jnp.uint64(expl_bits))
    norm_bits = (
        power2.astype(jnp.uint64) << jnp.uint64(expl_bits)
    ) | m_n

    bits = jnp.where(power2 <= 0, sub_bits, norm_bits)
    bits = jnp.where(underflow & (power2 <= 0), jnp.uint64(0), bits)
    inf_bits = jnp.uint64(inf_exp) << jnp.uint64(expl_bits)
    bits = jnp.where(power2 >= inf_exp, inf_bits, bits)
    # range clamps on q (beyond the table the value saturates)
    bits = jnp.where(q > _EL_LARGEST_Q, inf_bits, bits)
    bits = jnp.where(q < _EL_SMALLEST_Q, jnp.uint64(0), bits)
    bits = jnp.where(w == 0, jnp.uint64(0), bits)
    return bits
