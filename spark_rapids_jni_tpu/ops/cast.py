"""Type casts (cudf ``cast``): numeric <-> numeric, bool, decimal, temporal."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column
from . import compute


def cast(col: Column, to: dt.DType) -> Column:
    """Spark CAST semantics (non-ANSI: overflow wraps, no exceptions)."""
    if col.dtype == to:
        return col
    if col.dtype.is_string or to.is_string:
        from . import strings

        return strings.cast(col, to)

    if col.dtype.id == dt.TypeId.DECIMAL128 or to.id == dt.TypeId.DECIMAL128:
        return _cast_decimal128(col, to)

    vals = compute.values(col)

    if col.dtype.is_decimal and to.is_decimal:
        res = _rescale(vals.astype(jnp.int64), col.dtype.scale, to.scale)
        return compute.from_values(res, to, col.validity)
    if col.dtype.is_decimal:
        # decimal -> numeric: real value = unscaled * 10^scale
        scaled = vals.astype(jnp.float64) * (10.0 ** col.dtype.scale)
        if to.is_floating:
            return compute.from_values(scaled, to, col.validity)
        return compute.from_values(
            _rescale(vals.astype(jnp.int64), col.dtype.scale, 0), to, col.validity
        )
    if to.is_decimal:
        if col.dtype.is_floating:
            unscaled = jnp.rint(vals * (10.0 ** -to.scale)).astype(jnp.int64)
        else:
            unscaled = _rescale(vals.astype(jnp.int64), 0, to.scale)
        return compute.from_values(unscaled, to, col.validity)

    if to.is_boolean:
        return Column(vals != 0, dt.BOOL8, col.validity)

    return compute.from_values(vals, to, col.validity)


def _rescale(vals, from_scale: int, to_scale: int):
    """Decimal rescale: one shared implementation (truncation toward
    zero on narrowing) lives in binaryop._rescale_decimal."""
    from .binaryop import _rescale_decimal

    return _rescale_decimal(vals, from_scale, to_scale)


def _cast_decimal128(col: Column, to: dt.DType) -> Column:
    """Casts touching DECIMAL128 (two-u64-limb columns, ops/int128.py):
    widen from any decimal/integer, rescale within decimal128, narrow to
    decimal64/32 (wrapping like Spark non-ANSI), or approximate to
    float."""
    from . import int128

    if to.id == dt.TypeId.DECIMAL128:
        if col.dtype.id == dt.TypeId.DECIMAL128:
            lo, hi = int128.rescale(
                col.data[:, 0], col.data[:, 1], col.dtype.scale, to.scale
            )
        elif col.dtype.is_decimal or col.dtype.is_integer:
            lo, hi = int128.from_signed_int(col.data)
            lo, hi = int128.rescale(lo, hi, col.dtype.scale, to.scale)
        else:
            raise TypeError(f"cannot cast {col.dtype} to DECIMAL128")
        return Column(
            jnp.stack([lo, hi], axis=1), to, col.validity
        )

    # from DECIMAL128
    lo, hi = col.data[:, 0], col.data[:, 1]
    if to.is_decimal:
        lo, hi = int128.rescale(lo, hi, col.dtype.scale, to.scale)
        return compute.from_values(lo.astype(jnp.int64), to, col.validity)
    if to.is_floating:
        scaled = int128.to_float64(lo, hi) * (10.0 ** col.dtype.scale)
        return compute.from_values(scaled, to, col.validity)
    if to.is_integer or to.is_boolean:
        lo, hi = int128.rescale(lo, hi, col.dtype.scale, 0)
        v = lo.astype(jnp.int64)
        if to.is_boolean:
            return Column((lo != 0) | (hi != 0), dt.BOOL8, col.validity)
        return compute.from_values(v, to, col.validity)
    raise TypeError(f"cannot cast DECIMAL128 to {to}")
