"""Type casts (cudf ``cast``): numeric <-> numeric, bool, decimal, temporal."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column
from . import compute


def cast(col: Column, to: dt.DType) -> Column:
    """Spark CAST semantics (non-ANSI: overflow wraps, no exceptions)."""
    if col.dtype == to:
        return col
    if col.dtype.is_string or to.is_string:
        from . import strings

        return strings.cast(col, to)

    vals = compute.values(col)

    if col.dtype.is_decimal and to.is_decimal:
        res = _rescale(vals.astype(jnp.int64), col.dtype.scale, to.scale)
        return compute.from_values(res, to, col.validity)
    if col.dtype.is_decimal:
        # decimal -> numeric: real value = unscaled * 10^scale
        scaled = vals.astype(jnp.float64) * (10.0 ** col.dtype.scale)
        if to.is_floating:
            return compute.from_values(scaled, to, col.validity)
        return compute.from_values(
            _rescale(vals.astype(jnp.int64), col.dtype.scale, 0), to, col.validity
        )
    if to.is_decimal:
        if col.dtype.is_floating:
            unscaled = jnp.rint(vals * (10.0 ** -to.scale)).astype(jnp.int64)
        else:
            unscaled = _rescale(vals.astype(jnp.int64), 0, to.scale)
        return compute.from_values(unscaled, to, col.validity)

    if to.is_boolean:
        return Column(vals != 0, dt.BOOL8, col.validity)

    return compute.from_values(vals, to, col.validity)


def _rescale(vals, from_scale: int, to_scale: int):
    if from_scale == to_scale:
        return vals
    if to_scale < from_scale:
        return vals * (10 ** (from_scale - to_scale))
    return vals // (10 ** (to_scale - from_scale))
