"""Equi-joins (cudf ``inner_join``/``left_join``/semi/anti), sort-merge.

Design (SURVEY.md §7 hard parts 1 & 5): no device hash tables — the build
side is sorted once by normalized keys (ops/keys.py) and the probe side
binary-searches lower/upper bounds lexicographically over the u64 key
words (log2(m) rounds of gathers, fully vectorized over probe rows).
Output cardinality is data-dependent, so materialization is two-phase:
count matches on device, size the output (host sync in the eager API, a
static capacity in the ``*_capped`` jittable variants), then expand with
``jnp.repeat(..., total_repeat_length=...)`` — the XLA-static equivalent
of the reference's two-phase batching (row_conversion.cu:505-511).

Nulls: null join keys never match (Spark inner-join semantics); left joins
still emit their left rows with a null right side.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..column import Column, Table
from . import compute
from . import keys as keys_mod
from .gather import gather_table

# The fused single-shot join graph (key normalization + lexsort +
# lex-searchsorted in one compiled region) reproducibly kills the TPU
# worker at >= 32M rows with 64-bit keys (tools/xla_join_fault_repro.py;
# every sub-graph passes in isolation at the same sizes — an XLA
# codegen/runtime fault, not OOM). 16M passes. Above this threshold the
# eager join APIs route themselves through the chunk-probed path so no
# public join API can crash the worker at any size — the reference's own
# discipline of never letting callers choose safety (its 2 GB batch
# splits are automatic, row_conversion.cu:476-479,505-511).
# Module-level so tests can lower it to pin the routing.
#
# MIN_CHUNK_OUT_BYTES floors the batched join's per-chunk output budget
# (module-level so the skew re-split path is testable at small scale).
#
# Scope of the fence: it removes the XLA codegen fault by keeping every
# compiled probe graph at or below this row count. The OUTER joins'
# materialization (expand + gathers over the full pair count) still runs
# single-shot, so a pathological fan-out can exhaust HBM — that sizing
# concern belongs to the memory planner (utils/hbm.py), not this fence.
FUSED_PROBE_MAX_ROWS = 16_000_000
MIN_CHUNK_OUT_BYTES = 64 << 20


def _on_accelerator() -> bool:
    """CPU runs the fused graph fine (and tests rely on it); only real
    accelerator backends need the fault fence."""
    return jax.default_backend() != "cpu"


def _is_tracing(table: Table) -> bool:
    return isinstance(table.columns[0].data, jax.core.Tracer)


def _needs_chunked_probe(left: Table, right: Table) -> bool:
    """True when the eager API must avoid the fused single-shot graph.

    Under jit (tracers) the fence cannot host-sync, so the caller keeps
    the fused graph — jittable ``*_capped`` users (e.g. shard_map
    per-device shards) stay below the threshold by construction."""
    if _is_tracing(left) or _is_tracing(right):
        return False
    if not _on_accelerator():
        return False
    return (
        max(left.row_count, right.row_count) > FUSED_PROBE_MAX_ROWS
    )


def _key_words(cols: Sequence[Column]) -> tuple[list[jax.Array], jax.Array]:
    """(order-key words with null payloads zeroed, all-valid mask)."""
    words: list[jax.Array] = []
    n = cols[0].data.shape[0]
    valid = jnp.ones((n,), dtype=jnp.bool_)
    for c in cols:
        if c.validity is not None:
            valid = valid & c.validity
    for c in cols:
        for w in keys_mod.column_order_keys(c):
            words.append(jnp.where(valid, w, jnp.uint64(0)))
    return words, valid


def _lex_searchsorted(
    sorted_words: list[jax.Array], query_words: list[jax.Array], side: str
) -> jax.Array:
    """Vectorized multi-word binary search (lower/upper bound)."""
    m = sorted_words[0].shape[0]
    nq = query_words[0].shape[0]
    lo = jnp.zeros((nq,), dtype=jnp.int32)
    hi = jnp.full((nq,), m, dtype=jnp.int32)
    steps = max(1, int(np.ceil(np.log2(m + 1)))) if m > 0 else 1

    def body(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) // 2
        safe_mid = jnp.clip(mid, 0, max(m - 1, 0))
        # go_right: sorted[mid] < q (lower bound) or <= q (upper bound)
        lt = jnp.zeros((nq,), dtype=jnp.bool_)
        eq = jnp.ones((nq,), dtype=jnp.bool_)
        for sw, qw in zip(sorted_words, query_words):
            sv = sw[safe_mid]
            lt = lt | (eq & (sv < qw))
            eq = eq & (sv == qw)
        go_right = lt | eq if side == "right" else lt
        active = lo < hi
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, steps, body, (lo, hi))
    return lo


def _equalize_string_key_pads(left, right, left_on, right_on):
    """Repad string KEY columns to one common width across both sides.

    The chunk-probed path compares each side's order words positionally
    (`zip` in _lex_searchsorted); string columns emit pad/8+1 words, so
    DIFFERENT pads would silently truncate the comparison to the
    narrower side's words and drop matches (caught by
    tests/test_join_routing.py::test_batched_string_join_mismatched_pads
    — batched string joins returned 0 rows). Repadding is free
    semantically: pad bytes are zero and lengths are unchanged."""
    lcols = [left.column(c) for c in left_on]
    rcols = [right.column(c) for c in right_on]
    if not any(
        lc.dtype.is_string or rc.dtype.is_string
        for lc, rc in zip(lcols, rcols)
    ):
        return left, right
    from .strings import repad

    left_cols = list(left.columns)
    right_cols = list(right.columns)
    for lc, rc, lref, rref in zip(lcols, rcols, left_on, right_on):
        if not (lc.dtype.is_string or rc.dtype.is_string):
            continue
        if not (lc.dtype.is_string and rc.dtype.is_string):
            # same rejection as _maybe_encode_string_keys: a silent skip
            # here would let _lex_searchsorted's positional zip truncate
            # the word comparison and return wrong matches
            raise TypeError("join key dtypes differ: STRING vs non-STRING")
        common = max(lc.data.shape[1], rc.data.shape[1])
        li = _resolve_col(left, lref)
        ri = _resolve_col(right, rref)
        if lc.data.shape[1] != common:
            left_cols[li] = repad(lc, common)
        if rc.data.shape[1] != common:
            right_cols[ri] = repad(rc, common)
    return (
        Table(left_cols, left.names),
        Table(right_cols, right.names),
    )


def _maybe_encode_string_keys(lcols, rcols):
    """Auto dictionary-encode string join keys (VERDICT r4 item 5): a
    pad-128 string key costs 17 u64 words per compare; one shared-
    dictionary encode (jittable, ops/strings.py) reduces every later
    sort/search compare to ONE int32 code with identical order and
    equality. Only the fused path encodes — the chunk-probed big-table
    path would need a 2n-row encode sort upfront, the very graph shape
    the fence exists to avoid."""
    if not any(c.dtype.is_string for c in lcols + rcols):
        return lcols, rcols
    from .strings import encode_join_keys

    lcols, rcols = list(lcols), list(rcols)
    for i, (lc, rc) in enumerate(zip(lcols, rcols)):
        if lc.dtype.is_string or rc.dtype.is_string:
            if not (lc.dtype.is_string and rc.dtype.is_string):
                raise TypeError(
                    "join key dtypes differ: STRING vs non-STRING"
                )
            lcols[i], rcols[i] = encode_join_keys(lc, rc)
    return lcols, rcols


def _prepare_build(
    right: Table,
    right_on: Sequence[Union[int, str]],
    right_valid: Optional[jax.Array] = None,
    rcols: Optional[Sequence[Column]] = None,
):
    """Sort the build side once: (perm_r, sorted key words). Invalid
    rows sink to the front on the leading validity word (0 < 1), outside
    the range any valid probe (lead word 1) can reach — reusable across
    any number of probe batches."""
    if rcols is None:
        rcols = [right.column(c) for c in right_on]
    rwords, rvalid = _key_words(rcols)
    if right_valid is not None:
        rvalid = rvalid & right_valid
    rsort_words = [rvalid.astype(jnp.uint64)] + rwords
    perm_r = jnp.lexsort(rsort_words[::-1])
    sorted_words = [w[perm_r] for w in rsort_words]
    return perm_r, sorted_words


def _probe_build(
    sorted_words,
    left: Table,
    left_on: Sequence[Union[int, str]],
    left_valid: Optional[jax.Array] = None,
    lcols: Optional[Sequence[Column]] = None,
):
    """Binary-search the prepared build side: (lo, counts, lvalid)."""
    if lcols is None:
        lcols = [left.column(c) for c in left_on]
    lwords, lvalid = _key_words(lcols)
    if left_valid is not None:
        lvalid = lvalid & left_valid
    qwords = [jnp.ones_like(lvalid, dtype=jnp.uint64)] + lwords
    lo = _lex_searchsorted(sorted_words, qwords, "left")
    hi = _lex_searchsorted(sorted_words, qwords, "right")
    counts = jnp.where(lvalid, hi - lo, 0)
    return lo, counts, lvalid


def _match_ranges(
    left: Table,
    right: Table,
    left_on: Sequence[Union[int, str]],
    right_on: Sequence[Union[int, str]],
    left_valid: Optional[jax.Array] = None,
    right_valid: Optional[jax.Array] = None,
):
    """Per-left-row [lo, hi) match range into the sorted right side.

    ``left_valid``/``right_valid`` exclude rows entirely (shuffle-padding
    occupancy) — excluded rows behave like null keys and never match:
    invalid left rows get their counts zeroed, and invalid right rows sort
    ahead of every valid row on the leading validity word (0 < 1), outside
    the range any valid query (probing with lead word 1) can reach.

    String join keys are dictionary-encoded to int32 codes first (one
    shared dictionary, order-preserving) so every sort/search compare
    touches one word instead of pad/8+1.
    """
    lcols = [left.column(c) for c in left_on]
    rcols = [right.column(c) for c in right_on]
    lcols, rcols = _maybe_encode_string_keys(lcols, rcols)
    perm_r, sorted_words = _prepare_build(
        right, right_on, right_valid, rcols=rcols
    )
    lo, counts, lvalid = _probe_build(
        sorted_words, left, left_on, left_valid, lcols=lcols
    )
    return perm_r, lo, counts, lvalid


@functools.lru_cache(maxsize=64)
def _chunk_ranges_fn(on: tuple, with_valid: bool):
    """Jitted per-chunk probe: (lo, counts, lvalid, chunk total). The
    single probe wrapper every chunked caller shares (one jit cache):
    ``_match_ranges_safe`` uses the full triple, ``inner_join_batched``
    the count sum — returning both costs two extra scalars."""
    def fn(sw, chunk, chunk_valid=None):
        lo, counts, lvalid = _probe_build(
            list(sw), chunk, list(on), chunk_valid
        )
        return lo, counts, lvalid, jnp.sum(counts)

    if with_valid:
        return jax.jit(fn)
    return jax.jit(lambda sw, chunk: fn(sw, chunk))


@functools.lru_cache(maxsize=64)
def _batched_prep_valid_fn(right_on: tuple):
    return jax.jit(
        lambda r, rv: _prepare_build(r, list(right_on), rv)
    )


def _match_ranges_safe(
    left: Table,
    right: Table,
    left_on: Sequence[Union[int, str]],
    right_on: Sequence[Union[int, str]],
    left_valid: Optional[jax.Array] = None,
    right_valid: Optional[jax.Array] = None,
):
    """Eager ``_match_ranges`` that never builds the faulting fused
    graph: build side sorted in its own jit, probe side searched in
    ``FUSED_PROBE_MAX_ROWS`` chunks (each a known-safe graph), results
    concatenated. Drop-in for the eager outer joins and count APIs;
    occupancy masks ride along (sliced per probe chunk)."""
    if not _needs_chunked_probe(left, right):
        return _match_ranges(
            left, right, left_on, right_on, left_valid, right_valid
        )
    from .copying import slice_rows

    left, right = _equalize_string_key_pads(
        left, right, left_on, right_on
    )
    if right_valid is not None:
        perm_r, sorted_words = _batched_prep_valid_fn(tuple(right_on))(
            right, right_valid
        )
    else:
        perm_r, sorted_words = _batched_prep_fn(tuple(right_on))(right)
    sorted_words = tuple(sorted_words)
    probe = _chunk_ranges_fn(tuple(left_on), left_valid is not None)
    n = left.row_count
    step = FUSED_PROBE_MAX_ROWS
    los, counts, lvalids = [], [], []
    for start in range(0, n, step):
        stop = min(start + step, n)
        chunk = slice_rows(left, start, stop)
        if left_valid is not None:
            lo_c, cnt_c, lv_c, _ = probe(
                sorted_words, chunk, left_valid[start:stop]
            )
        else:
            lo_c, cnt_c, lv_c, _ = probe(sorted_words, chunk)
        los.append(lo_c)
        counts.append(cnt_c)
        lvalids.append(lv_c)
    return (
        perm_r,
        jnp.concatenate(los),
        jnp.concatenate(counts),
        jnp.concatenate(lvalids),
    )


def _expand(
    perm_r, lo, counts, total: int, left_outer: bool, emit=None
):
    """Materialize (left_idx, right_idx, right_valid) pair arrays.

    ``emit`` overrides the per-left-row output count (used by the capped
    left join to skip shuffle-padding rows entirely)."""
    n_left = counts.shape[0]
    if emit is None:
        emit = jnp.maximum(counts, 1) if left_outer else counts
    start = jnp.cumsum(emit) - emit
    left_idx = jnp.repeat(
        jnp.arange(n_left, dtype=jnp.int32), emit, total_repeat_length=total
    )
    k = jnp.arange(total, dtype=jnp.int32) - start[left_idx]
    matched = k < counts[left_idx]
    r_sorted_pos = jnp.clip(lo[left_idx] + k, 0, max(perm_r.shape[0] - 1, 0))
    right_idx = perm_r[r_sorted_pos]
    # pairs beyond the emitted total (possible when total is a capacity)
    in_range = jnp.arange(total, dtype=jnp.int32) < jnp.sum(emit)
    return left_idx, right_idx, matched & in_range, in_range


def _join_output(
    left: Table,
    right: Table,
    right_on: Sequence[Union[int, str]],
    left_idx,
    right_idx,
    matched,
    row_valid,
) -> Table:
    """left columns + right columns (minus its join keys, like Spark USING)."""
    drop = set()
    for c in right_on:
        if isinstance(c, str):
            if right.names is not None:
                drop.add(right.names.index(c))
        else:
            drop.add(c)
    lcols = gather_table(left, left_idx, None).columns
    out_cols = list(lcols)
    out_names = list(left.names) if left.names else [f"l{i}" for i in range(left.num_columns)]
    for j, c in enumerate(right.columns):
        if j in drop:
            continue
        g = gather_table(Table([c]), right_idx, matched).columns[0]
        out_cols.append(g)
        out_names.append(
            right.names[j] if right.names else f"r{j}"
        )
    return Table(out_cols, out_names)


def inner_join_from_ranges(
    left: Table,
    right: Table,
    right_on: Sequence[Union[int, str]],
    perm_r,
    lo,
    counts,
    capacity: int,
) -> tuple[Table, jax.Array]:
    """Materialize a capped inner join from ALREADY-COMPUTED match
    ranges (a prior _prepare_build + _probe_build pass) — the
    share-the-probe half of two-phase sizing. Jittable; pairs past the
    count are padding (nulled)."""
    left_idx, right_idx, matched, in_range = _expand(
        perm_r, lo, counts, capacity, left_outer=False
    )
    out = _join_output(
        left, right, right_on, left_idx, right_idx, matched, in_range
    )
    cols = [
        Column(
            c.data,
            c.dtype,
            in_range
            if c.validity is None
            else jnp.logical_and(c.validity, in_range),
            c.lengths,
        )
        for c in out.columns
    ]
    return Table(cols, out.names), jnp.sum(counts)


def inner_join_capped(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    capacity: int,
    right_on: Optional[Sequence[Union[int, str]]] = None,
    left_valid: Optional[jax.Array] = None,
    right_valid: Optional[jax.Array] = None,
) -> tuple[Table, jax.Array]:
    """Jittable inner join with static output capacity; returns (padded
    table, device match count). Pairs past the count are padding."""
    right_on = right_on or on
    perm_r, lo, counts, _ = _match_ranges(
        left, right, on, right_on, left_valid, right_valid
    )
    return inner_join_from_ranges(
        left, right, right_on, perm_r, lo, counts, capacity
    )


def _left_emit(counts, left_valid):
    """Per-left-row output count of a LEFT OUTER join — the single
    definition both sizing phases share: null-KEY rows match nothing
    (counts already zeroed by _match_ranges) but still emit their one
    left-outer row; only shuffle-PADDING rows (left_valid False) emit
    nothing."""
    occ = (
        left_valid
        if left_valid is not None
        else jnp.ones(counts.shape, jnp.bool_)
    )
    return jnp.where(occ, jnp.maximum(counts, 1), 0)


def left_join_capped(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    capacity: int,
    right_on: Optional[Sequence[Union[int, str]]] = None,
    left_valid: Optional[jax.Array] = None,
    right_valid: Optional[jax.Array] = None,
) -> tuple[Table, jax.Array]:
    """Jittable LEFT OUTER join with static output capacity; returns
    (padded table, device row count). Every valid left row emits at
    least once (null right side when unmatched); shuffle-padding rows
    (``left_valid`` False) emit nothing."""
    right_on = right_on or on
    perm_r, lo, counts, _ = _match_ranges(
        left, right, on, right_on, left_valid, right_valid
    )
    emit = _left_emit(counts, left_valid)
    left_idx, right_idx, matched, in_range = _expand(
        perm_r, lo, counts, capacity, left_outer=True, emit=emit
    )
    out = _join_output(
        left, right, right_on, left_idx, right_idx,
        jnp.logical_and(matched, in_range), in_range,
    )
    cols = [
        Column(
            c.data,
            c.dtype,
            in_range
            if c.validity is None
            else jnp.logical_and(c.validity, in_range),
            c.lengths,
        )
        for c in out.columns
    ]
    return Table(cols, out.names), jnp.sum(emit)


def left_join_count(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    right_on: Optional[Sequence[Union[int, str]]] = None,
    left_valid: Optional[jax.Array] = None,
    right_valid: Optional[jax.Array] = None,
) -> jax.Array:
    """Jittable LEFT OUTER output-row count (phase 1 of two-phase
    sizing): matches plus one per unmatched occupied left row (null-key
    rows count; shuffle-padding rows don't)."""
    right_on = right_on or on
    _, _, counts, _ = _match_ranges_safe(
        left, right, on, right_on, left_valid, right_valid
    )
    return jnp.sum(_left_emit(counts, left_valid))


def membership_mask(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    right_on: Optional[Sequence[Union[int, str]]] = None,
    left_valid: Optional[jax.Array] = None,
    right_valid: Optional[jax.Array] = None,
) -> jax.Array:
    """Jittable per-left-row bool: has at least one match in right
    (the SEMI/ANTI join predicate; fixed shape, shard_map-friendly)."""
    right_on = right_on or on
    # eager big-table calls take the fault-fenced chunked probe; under
    # jit (tracers) _match_ranges_safe falls through to the fused graph
    _, _, counts, lvalid = _match_ranges_safe(
        left, right, on, right_on, left_valid, right_valid
    )
    return jnp.logical_and(lvalid, counts > 0)


def inner_join_count(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    right_on: Optional[Sequence[Union[int, str]]] = None,
    left_valid: Optional[jax.Array] = None,
    right_valid: Optional[jax.Array] = None,
) -> jax.Array:
    """Jittable match count — phase 1 of the two-phase output sizing
    (the generalization of row_conversion.cu:505-511): count on device,
    host-sync once, then materialize with a static capacity."""
    right_on = right_on or on
    _, _, counts, _ = _match_ranges_safe(
        left, right, on, right_on, left_valid, right_valid
    )
    return jnp.sum(counts)


def inner_join(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    right_on: Optional[Sequence[Union[int, str]]] = None,
) -> Table:
    """Eager inner equi-join (host-syncs the match count).

    Above ``FUSED_PROBE_MAX_ROWS`` on an accelerator backend this routes
    itself through :func:`inner_join_batched` — the fused single-shot
    graph faults the TPU worker at >= 32M rows (see module constant)."""
    right_on = right_on or on
    if _needs_chunked_probe(left, right):
        return inner_join_batched(left, right, on, right_on)
    perm_r, lo, counts, _ = _match_ranges(left, right, on, right_on)
    total = int(jnp.sum(counts))
    if total == 0:
        left_idx = jnp.zeros((0,), jnp.int32)
        right_idx = jnp.zeros((0,), jnp.int32)
        return _join_output(
            left, right, right_on, left_idx, right_idx,
            jnp.zeros((0,), jnp.bool_), jnp.zeros((0,), jnp.bool_),
        )
    left_idx, right_idx, matched, _ = _expand(
        perm_r, lo, counts, total, left_outer=False
    )
    return _join_output(left, right, right_on, left_idx, right_idx, None, None)


@functools.lru_cache(maxsize=64)
def _batched_prep_fn(right_on: tuple):
    return jax.jit(lambda r: _prepare_build(r, list(right_on)))


@functools.lru_cache(maxsize=256)
def _batched_materialize_fn(right_on: tuple, cap: int):
    def fn(perm_r, lo, counts, chunk, r):
        left_idx, right_idx, _, _ = _expand(
            perm_r, lo, counts, cap, left_outer=False
        )
        # no matched/row_valid masks: rows past the chunk total are
        # sliced away by the caller, and passing masks here would hang
        # an all-True validity on right columns that the single-shot
        # inner_join leaves as None (schema parity)
        return _join_output(
            chunk, r, list(right_on), left_idx, right_idx, None, None
        )

    return jax.jit(fn)


def inner_join_batched(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    right_on: Optional[Sequence[Union[int, str]]] = None,
    probe_rows: Optional[int] = None,
) -> Table:
    """Eager inner join, probe side processed in ``probe_rows`` batches
    (default: ``FUSED_PROBE_MAX_ROWS``, resolved at call time so tuning
    the fence threshold shrinks the batched chunks with it).

    The single-shot join at 100M×100M rows needs both sides, the sorted
    build words, AND the expanded output resident at once — past the HBM
    of one chip (observed: the v5e worker dies). This is the reference's
    own batching discipline (2 GB splits, row_conversion.cu:505-511)
    applied to the probe side: the build side is sorted ONCE and every
    probe batch binary-searches it, materializing only its own slice of
    the output. Equal batch shapes reuse one compiled executable."""
    from .copying import concatenate, slice_rows

    right_on = right_on or on
    pieces = list(
        inner_join_batches(left, right, on, right_on, probe_rows)
    )
    if not pieces:
        # empty output with the exact join schema — no build-side sort
        z = jnp.zeros((0,), jnp.int32)
        return _join_output(
            slice_rows(left, 0, 0), right, right_on, z, z,
            jnp.zeros((0,), jnp.bool_), jnp.zeros((0,), jnp.bool_),
        )
    return concatenate(pieces) if len(pieces) > 1 else pieces[0]


def inner_join_batches(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    right_on: Optional[Sequence[Union[int, str]]] = None,
    probe_rows: Optional[int] = None,
):
    """Streaming inner join: yields one result Table per probe chunk
    instead of concatenating them — the Spark operator model (plans
    consume ``Iterator[ColumnarBatch]``), and the bounded-memory output
    path: at no point is more than one chunk's output resident beyond
    what the consumer retains, so a join whose FULL output exceeds HBM
    can still stream through a downstream aggregation.

    Same safety properties as :func:`inner_join_batched` (fault-fenced
    probe sizes, HBM-planned chunks, skew re-splitting).

    Argument validation and the HBM-budget warning fire HERE, at call
    time — not on first iteration of the returned generator — so a
    caller that builds the iterator and defers consumption still gets
    errors at the faulty call site."""
    right_on = right_on or on
    out_row_bytes = None
    if probe_rows is None:
        # size the chunk from the HBM budget (round-4 VERDICT item 7:
        # capped/batched APIs plan memory instead of fixed constants),
        # bounded by the codegen-fault fence
        from ..utils import hbm

        plan = hbm.join_plan(left, right, on, right_on)
        if not plan["fits"]:
            # the fixed resident set (both tables + build words) alone
            # exceeds the budget: no probe size can save it. Proceed at
            # minimum chunks but say so — the reserve fraction is
            # conservative, so this is a warning, not a refusal.
            import warnings

            warnings.warn(
                "join inputs exceed the HBM budget before any probe "
                f"chunk ({plan['fixed_bytes']} fixed vs "
                f"{plan['budget_bytes']} budget); expect allocator "
                "pressure. Raise SPARK_RAPIDS_TPU_HBM_BUDGET_GB if the "
                "chip really has more.",
                stacklevel=2,
            )
        probe_rows = min(FUSED_PROBE_MAX_ROWS, plan["probe_rows"])
        out_row_bytes = plan["output_row_bytes"]
    if probe_rows <= 0:
        raise ValueError(f"probe_rows must be positive, got {probe_rows}")
    # key-dtype validation is also eager (raises TypeError on mixed
    # STRING/non-STRING pairs before any work is enqueued)
    left, right = _equalize_string_key_pads(left, right, on, right_on)
    return _inner_join_batches_gen(
        left, right, on, right_on, probe_rows, out_row_bytes
    )


def _inner_join_batches_gen(
    left, right, on, right_on, probe_rows, out_row_bytes
):
    from collections import deque

    from .copying import slice_rows

    n = left.row_count
    if n == 0 or right.row_count == 0:
        return
    # two jitted stages per chunk (NOT eager op-by-op: each eager
    # dispatch pays a full host<->device round trip — ~100s at 32M over
    # the tunnel). The jitted helpers are cached at module level keyed
    # by the key columns / capacity bucket, so compile caches hit
    # across chunks, repetitions, AND separate calls.
    on_key = tuple(on)
    ron_key = tuple(right_on)
    perm_r, sorted_words = _batched_prep_fn(ron_key)(right)
    sorted_words = tuple(sorted_words)
    probe = _chunk_ranges_fn(on_key, False)
    if out_row_bytes is None:
        from ..utils import hbm

        out_row_bytes = hbm.row_bytes(left) + hbm.row_bytes(right)
    chunk_out_budget = max(
        probe_rows * 2 * out_row_bytes, MIN_CHUNK_OUT_BYTES
    )
    spans = deque(
        (s, min(s + probe_rows, n)) for s in range(0, n, probe_rows)
    )
    while spans:
        start, stop = spans.popleft()
        chunk = slice_rows(left, start, stop)
        lo, counts, _, total_dev = probe(sorted_words, chunk)
        total = int(total_dev)
        if total == 0:
            continue
        cap = max(32, 1 << (total - 1).bit_length())
        if cap * out_row_bytes > chunk_out_budget and stop - start > 1024:
            mid = (start + stop) // 2
            spans.appendleft((mid, stop))
            spans.appendleft((start, mid))
            continue
        padded = _batched_materialize_fn(ron_key, cap)(
            perm_r, lo, counts, chunk, right
        )
        yield slice_rows(padded, 0, total)


def left_join(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    right_on: Optional[Sequence[Union[int, str]]] = None,
) -> Table:
    """Eager left outer equi-join (fault-fenced: chunked probe above
    ``FUSED_PROBE_MAX_ROWS`` on accelerator backends)."""
    right_on = right_on or on
    perm_r, lo, counts, _ = _match_ranges_safe(left, right, on, right_on)
    total = int(jnp.sum(jnp.maximum(counts, 1)))
    left_idx, right_idx, matched, _ = _expand(
        perm_r, lo, counts, total, left_outer=True
    )
    return _join_output(left, right, right_on, left_idx, right_idx, matched, None)


def semi_join(left, right, on, right_on=None) -> Table:
    """Rows of ``left`` with at least one match (LEFT SEMI)."""
    from .filter import filter_table
    from .. import dtype as dt

    has = membership_mask(left, right, on, right_on)
    return filter_table(left, Column(has, dt.BOOL8, None))


def anti_join(left, right, on, right_on=None) -> Table:
    """Rows of ``left`` with no match (LEFT ANTI)."""
    from .filter import filter_table
    from .. import dtype as dt

    has = membership_mask(left, right, on, right_on)
    return filter_table(left, Column(jnp.logical_not(has), dt.BOOL8, None))


# ---------------------------------------------------------------------------
# full / right outer joins (round 3: VERDICT item 7)
# ---------------------------------------------------------------------------

def _resolve_col(table: Table, c: Union[int, str]) -> int:
    if isinstance(c, str):
        if table.names is None:
            raise ValueError(f"column name {c!r} on an unnamed table")
        return table.names.index(c)
    return c


def _coalesce_key(
    lc: Column, rc: Column, left_idx, right_idx, left_ok, right_ok
) -> Column:
    """Output key column under USING semantics: ``coalesce(l.k, r.k)`` —
    left's key for pair / left-unmatched rows, right's for
    right-unmatched rows (where no left row exists)."""
    if lc.dtype != rc.dtype:
        raise TypeError(
            f"outer-join key dtypes differ: {lc.dtype} vs {rc.dtype}"
        )
    lg = gather_table(Table([lc]), left_idx).columns[0]
    rg = gather_table(Table([rc]), right_idx).columns[0]
    m = left_ok.reshape(left_ok.shape + (1,) * (lg.data.ndim - 1))
    data = jnp.where(m, lg.data, rg.data)
    lval = jnp.logical_and(compute.valid_mask(lg), left_ok)
    rval = jnp.logical_and(compute.valid_mask(rg), right_ok)
    valid = jnp.where(left_ok, lval, rval)
    lengths = None
    if lg.lengths is not None or rg.lengths is not None:
        ll = lg.lengths if lg.lengths is not None else jnp.zeros_like(right_idx)
        rl = rg.lengths if rg.lengths is not None else jnp.zeros_like(right_idx)
        lengths = jnp.where(left_ok, ll, rl)
    return Column(data, lc.dtype, valid, lengths)


def _outer_output(
    left: Table,
    right: Table,
    left_on: Sequence[Union[int, str]],
    right_on: Sequence[Union[int, str]],
    left_idx,
    right_idx,
    left_ok,
    right_ok,
) -> Table:
    """Unified outer-join materialization: key columns coalesced, left
    non-keys masked by ``left_ok``, right non-keys (minus its join keys,
    like Spark USING) masked by ``right_ok``."""
    lkeys = [_resolve_col(left, c) for c in left_on]
    rkeys = [_resolve_col(right, c) for c in right_on]
    rkey_of = dict(zip(lkeys, rkeys))
    out_cols: list[Column] = []
    out_names: list[str] = []
    lnames = (
        list(left.names)
        if left.names
        else [f"l{i}" for i in range(left.num_columns)]
    )
    for j, c in enumerate(left.columns):
        if j in rkey_of:
            out_cols.append(
                _coalesce_key(
                    c, right.columns[rkey_of[j]],
                    left_idx, right_idx, left_ok, right_ok,
                )
            )
        else:
            out_cols.append(
                gather_table(Table([c]), left_idx, left_ok).columns[0]
            )
        out_names.append(lnames[j])
    for j, c in enumerate(right.columns):
        if j in rkeys:
            continue
        out_cols.append(
            gather_table(Table([c]), right_idx, right_ok).columns[0]
        )
        out_names.append(right.names[j] if right.names else f"r{j}")
    return Table(out_cols, out_names)


def _unmatched_right(left, right, on, right_on):
    """Bool mask over right rows with NO match in left (probe reversed).
    Null/invalid right keys never match, so they are unmatched — exactly
    the rows a FULL/RIGHT OUTER join must still emit."""
    _, _, counts, _ = _match_ranges_safe(right, left, right_on, on)
    return counts == 0


def right_join(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    right_on: Optional[Sequence[Union[int, str]]] = None,
) -> Table:
    """Eager RIGHT OUTER equi-join: inner pairs + unmatched right rows
    with a null left side (keys coalesced from the right)."""
    right_on = right_on or on
    perm_r, lo, counts, _ = _match_ranges_safe(left, right, on, right_on)
    total_in = int(jnp.sum(counts))
    run = _unmatched_right(left, right, on, right_on)
    n_run = int(jnp.sum(run))
    left_idx, right_idx, matched, _ = _expand(
        perm_r, lo, counts, total_in, left_outer=False
    )
    run_idx = jnp.nonzero(run, size=n_run)[0].astype(jnp.int32)
    left_idx = jnp.concatenate(
        [left_idx, jnp.zeros((n_run,), jnp.int32)]
    )
    right_idx = jnp.concatenate([right_idx, run_idx])
    left_ok = jnp.concatenate(
        [jnp.ones((total_in,), jnp.bool_), jnp.zeros((n_run,), jnp.bool_)]
    )
    right_ok = jnp.concatenate(
        [jnp.ones((total_in,), jnp.bool_), jnp.ones((n_run,), jnp.bool_)]
    )
    return _outer_output(
        left, right, on, right_on, left_idx, right_idx, left_ok, right_ok
    )


def full_join(
    left: Table,
    right: Table,
    on: Sequence[Union[int, str]],
    right_on: Optional[Sequence[Union[int, str]]] = None,
) -> Table:
    """Eager FULL OUTER equi-join: inner pairs + unmatched left rows
    (null right side) + unmatched right rows (null left side)."""
    right_on = right_on or on
    perm_r, lo, counts, _ = _match_ranges_safe(left, right, on, right_on)
    total_pairs = int(jnp.sum(jnp.maximum(counts, 1)))  # inner + left-unmatched
    run = _unmatched_right(left, right, on, right_on)
    n_run = int(jnp.sum(run))
    left_idx, right_idx, matched, _ = _expand(
        perm_r, lo, counts, total_pairs, left_outer=True
    )
    run_idx = jnp.nonzero(run, size=n_run)[0].astype(jnp.int32)
    left_idx = jnp.concatenate(
        [left_idx, jnp.zeros((n_run,), jnp.int32)]
    )
    right_idx = jnp.concatenate([right_idx, run_idx])
    left_ok = jnp.concatenate(
        [jnp.ones((total_pairs,), jnp.bool_), jnp.zeros((n_run,), jnp.bool_)]
    )
    right_ok = jnp.concatenate(
        [matched, jnp.ones((n_run,), jnp.bool_)]
    )
    return _outer_output(
        left, right, on, right_on, left_idx, right_idx, left_ok, right_ok
    )
