"""Packed-key two-level group-by — the narrow-key fast path.

The chunked design (groupby_chunked.py) still pays a WIDE variadic sort
per chunk: occupancy word + key order word + iota + row_valid + every
value column — 29 B/row on the headline shape (one int64 key, one int64
value). But when the key's VALUE RANGE fits in ``64 - log2(chunk_rows)``
bits — 10k-key aggregations, dictionary codes, date keys, virtually
every Spark GROUP BY that isn't keyed on a hash — the entire sort key
collapses into ONE u64 word::

    packed = (order_key(k) - kmin) << iota_bits  |  row_iota
    padding rows -> 0xFFFF...F (sorts last, one garbage segment)

which buys, per sort pass (and a bitonic sort makes O(log^2) of them):

* 16 B/row of operands instead of 29 — ~1.8x less sort traffic;
* ties are IMPOSSIBLE (the embedded iota is unique), so sorted order
  within a key group is exactly original row order: stability is free,
  first/last are just segment ends, and no separate iota operand rides;
* the occupancy word, the boundary scan over a second word, and the
  row_valid payload all vanish.

Both levels use the same trick (phase 2 packs the C x S chunk partials
with the same global kmin), and both run as a batched ``lax.sort`` over
a (C, T) layout — no vmap, XLA sees one fused static-shape graph.

Eligibility is STATIC (caller-checked, raised here): one key column of
an integer-family dtype (ints / bool / timestamps / durations /
decimal32/64 — everything whose order key is an XOR-sign-flip or a
widen, so it inverts exactly), no nulls on keys or values, decomposable
aggs. Whether the RANGE fits is data-dependent: the eager router
measures min/max first (one cheap reduction); the jittable API also
returns a traced ``overflow`` flag so a mis-sized direct call is
detected, never silently wrong — the same exactness protocol as the
chunked API's ``max_chunk`` contract.

Reference parity: this is the role of cudf's hash-based groupby
specializations for simple keys (single-pass hash aggregation) —
re-expressed for a machine with no device-wide atomic hash tables, where
the classical sort-based answer gets its constant factor back by making
the sort key as narrow as the data allows.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .. import dtype as dt
from ..column import Column, Table
from . import compute
from . import keys as keys_mod
from .keys import minmax_host as _minmax
from .groupby import GroupbyAgg
from .groupby_chunked import DECOMPOSABLE_OPS

_U64_MAX = jnp.uint64(0xFFFFFFFFFFFFFFFF)
_SIGN64 = jnp.uint64(1) << jnp.uint64(63)


def _key_supported(col: Column) -> bool:
    d = col.dtype
    if col.validity is not None:
        return False
    if d.id in (dt.TypeId.FLOAT32, dt.TypeId.FLOAT64):
        return False  # order key inverts, but ranges are meaningless
    if d.is_string or d.id in (dt.TypeId.LIST, dt.TypeId.STRUCT):
        return False
    if d.id == dt.TypeId.DECIMAL128:
        return False  # two-word key
    return True


def _unkey(word: jax.Array, d) -> jax.Array:
    """Invert column_order_keys for the integer family: the sign-flip
    XOR is an involution; unsigned/bool just widened."""
    storage = jnp.dtype(d.storage_dtype)
    if storage.kind == "i":  # signed ints, timestamps, durations, decimals
        return (word ^ _SIGN64).astype(jnp.int64).astype(storage)
    return word.astype(storage)  # unsigned / bool widen


def packed_groupby_supported(
    table: Table, by: Sequence, aggs: Sequence[GroupbyAgg]
) -> bool:
    """Static eligibility (range fitting is checked separately).
    Multi-key shapes are eligible when EVERY key is integer-family and
    no-null — the composite word packs them as bit fields (TPC-DS q64
    groups by (brand, state, year): three narrow fields)."""
    if not by:
        return False
    if not all(_key_supported(table.column(k)) for k in by):
        return False
    for a in aggs:
        if a.op not in DECOMPOSABLE_OPS:
            return False
        c = table.column(a.column)
        if c.validity is not None or c.dtype.is_string:
            return False
        if c.dtype.id == dt.TypeId.DECIMAL128:
            return False
    return True


def _plan(table: Table, aggs: Sequence[GroupbyAgg]):
    """Deduplicated partial ops via the chunked path's shared planner
    (one dedup/mean-decomposition policy for both two-level designs),
    re-indexed positionally for this module's parallel-list plumbing."""
    from .groupby_chunked import _phase1_plan

    p1, plan_named = _phase1_plan(table, (), aggs)
    idx = {a.name: i for i, a in enumerate(p1)}
    parts = [(a.name, a.op, a.column) for a in p1]
    plan = [
        (op, a, idx[main], idx[cnt] if cnt is not None else None)
        for (op, a, main, cnt) in plan_named
    ]
    return parts, plan


def _segment_reduce(op, vals, seg, starts, ends):
    """One partial aggregation over a row-sorted flat layout."""
    from .groupby import _sorted_segment_extreme, _sorted_segment_sum

    n = vals.shape[0]
    if op == "sum":
        acc = vals.astype(
            jnp.float64
            if jnp.issubdtype(vals.dtype, jnp.floating)
            else jnp.int64
        )
        return _sorted_segment_sum(acc, starts, ends)
    if op == "count":
        # no nulls on the packed path + padding confined to the trailing
        # garbage segment: count is just the segment length
        return (ends - starts).astype(jnp.int64)
    if op in ("min", "max"):
        return _sorted_segment_extreme(vals, seg, ends, op == "min")
    if op == "first":
        return vals[jnp.clip(starts, 0, max(n - 1, 0))]
    if op == "last":
        return vals[jnp.clip(ends - 1, 0, max(n - 1, 0))]
    raise ValueError(op)


def groupby_aggregate_packed_chunked(
    table: Table,
    by: Sequence[Union[int, str]],
    aggs: Sequence[GroupbyAgg],
    num_segments: int,
    chunk_rows: int = 1 << 18,
    chunk_segments: int = 1 << 14,
    field_bits: Optional[tuple] = None,
    engine: str = "lax",
) -> tuple[Table, jax.Array, jax.Array, jax.Array]:
    """Jittable packed two-level groupby.

    Returns ``(padded result of num_segments rows, num_groups,
    max_per_chunk_groups, overflow)``. EXACT iff ``overflow`` is False
    (key range fit both packing levels) and ``max_per_chunk_groups <=
    chunk_segments`` — callers must check both (the eager router does).

    ``field_bits`` (STATIC, one entry per key column) packs multiple
    narrow keys as bit fields of one composite word, lexicographic key
    order == numeric composite order. Required for multi-key shapes
    (the eager router measures spans and supplies it); the single-key
    default packs the one key into the whole word above the iota.

    ``engine`` selects the phase-1 chunk-sort backend:

    * ``"lax"`` — batched variadic ``lax.sort`` carrying the value
      columns as sort payloads (the original formulation);
    * ``"pallas"`` — the VMEM bitonic network (kernels/bitonic_sort)
      sorting the packed WORD ONLY with the (hi, lo) u64 form; values
      follow by a per-chunk gather of the embedded-iota permutation.
    * ``"pallas32"`` — same, but the words ride the single-word u32
      network. Whether they FIT u32 is data-dependent
      (``key-range << iota_bits`` strictly below the all-ones
      sentinel), so the fit rides the traced ``overflow`` flag: a
      mis-sized call is detected, never silently wrong. Callers pick
      this arm when ``chunk_segments << chunk_rows`` is comfortably
      inside 32 bits.

    Both Pallas engines need ``chunk_rows`` a power of two and a
    multiple of 128.
    """
    key_names, key_cols = _validate_and_names(table, by, aggs, field_bits)
    n = table.row_count
    c = -(-n // chunk_rows)
    padded = c * chunk_rows
    iota_bits = max(1, (chunk_rows - 1).bit_length())
    p2_rows = c * (chunk_segments + 1)  # +1: per-chunk garbage slot
    iota_bits2 = max(1, (p2_rows - 1).bit_length())

    # one fewer bit than either level leaves: both sentinels stay
    # strictly above every packed word (single-key path mirrors the
    # original fit1/fit2 pair; multi-key validates static widths)
    allowed = (
        64 - max(iota_bits, iota_bits2)
        if field_bits is None
        else 63 - max(iota_bits, iota_bits2)
    )
    rel, kmins, overflow = _composite_rel(key_cols, field_bits, allowed)

    parts, plan = _plan(table, aggs)
    vals_in = [
        compute.values(table.column(colref)) for (_, _, colref) in parts
    ]

    # ---- phase 1: batched (C, T) packed sort + flat segment reduce ----
    iota = jnp.arange(chunk_rows, dtype=jnp.uint64)
    packed = (rel << jnp.uint64(iota_bits))
    packed = jnp.pad(packed, (0, padded - n), constant_values=0)
    packed = packed.reshape(c, chunk_rows) | iota[None, :]
    occ2d = (
        jnp.arange(padded, dtype=jnp.int32).reshape(c, chunk_rows)
        < n
    )
    packed = jnp.where(occ2d, packed, _U64_MAX)

    ops_2d = tuple(
        jnp.pad(v, [(0, padded - n)] + [(0, 0)] * (v.ndim - 1)).reshape(
            (c, chunk_rows) + v.shape[1:]
        )
        for v in vals_in
    )
    if engine == "lax":
        sorted_all = jax.lax.sort((packed,) + ops_2d, num_keys=1)
        spacked = sorted_all[0]
        svals = sorted_all[1:]
    elif engine in ("pallas", "pallas32"):
        u32 = engine == "pallas32"
        if u32:
            # the narrowed word drops the high half: exact iff every
            # real word fits STRICTLY below the all-ones u32 — the
            # sentinel must stay above every real word (the module
            # invariant). The top word is rel_max << iota_bits | iota,
            # which can reach 0xFFFFFFFF exactly when rel_max ==
            # 2^(32 - iota_bits) - 1, so that rel value is reserved
            # too (conservatively: flagging rides the exactness
            # protocol, the router just falls back). Checked on rel
            # (XLA CSEs the max with _composite_rel's own range
            # reduction) rather than re-reducing the (C, T) words.
            if iota_bits >= 32:
                overflow = jnp.asarray(True)
            else:
                fit_line = (jnp.uint64(1) << jnp.uint64(
                    32 - iota_bits
                )) - jnp.uint64(1)
                overflow = overflow | (jnp.max(rel) >= fit_line)
        spacked, perm = _pallas_word_sort(
            packed, iota_bits, chunk_rows, u32
        )
        svals = tuple(
            jnp.take_along_axis(v2d, perm, axis=1) for v2d in ops_2d
        )
    else:
        raise ValueError(f"unknown packed-groupby engine {engine!r}")

    skey = spacked >> jnp.uint64(iota_bits)  # (C, T) relative key words
    boundary = jnp.concatenate(
        [
            jnp.ones((c, 1), jnp.bool_),
            skey[:, 1:] != skey[:, :-1],
        ],
        axis=1,
    )
    local_seg = jnp.cumsum(boundary.astype(jnp.int32), axis=1) - 1
    # group count per chunk = local segment of its LAST REAL row + 1
    # (real rows sort before the sentinel; padding forms one garbage
    # trailing segment per padded chunk)
    real_per_chunk = jnp.sum(occ2d, axis=1)
    last_real = jnp.clip(real_per_chunk - 1, 0, chunk_rows - 1)
    chunk_groups = jnp.where(
        real_per_chunk > 0,
        jnp.take_along_axis(local_seg, last_real[:, None], axis=1)[:, 0]
        + 1,
        0,
    )
    max_chunk = jnp.max(chunk_groups)

    # per-chunk stride is S+1: slot S is a DEDICATED garbage slot, so a
    # padded chunk whose real groups fill all S slots (max_chunk == S,
    # still documented-exact) cannot have its padding clamped into the
    # last real segment
    stride = chunk_segments + 1
    seg_flat = (
        jnp.arange(c, dtype=jnp.int32)[:, None] * stride
        + jnp.minimum(local_seg, chunk_segments)
    ).reshape(-1)
    from .groupby import _segment_bounds

    starts, ends = _segment_bounds(seg_flat, c * stride)
    # a partial slot is REAL iff its local id is below its chunk's
    # group count (slot S never is: chunk_groups <= S when exact)
    sids = jnp.arange(c * stride, dtype=jnp.int32)
    p2_valid = (sids % stride) < chunk_groups[sids // stride]
    ends = jnp.where(p2_valid, ends, starts)

    skey_flat = skey.reshape(-1)
    part_key = skey_flat[jnp.clip(starts, 0, padded - 1)]  # relative words
    partials = [
        _segment_reduce(op, sv.reshape(-1), seg_flat, starts, ends)
        for ((_, op, _), sv) in zip(parts, svals)
    ]

    # ---- phase 2: pack the C*S partials the same way ------------------
    iota2 = jnp.arange(p2_rows, dtype=jnp.uint64)
    packed2 = (part_key << jnp.uint64(iota_bits2)) | iota2
    packed2 = jnp.where(p2_valid, packed2, _U64_MAX)
    sorted2 = jax.lax.sort(
        (packed2,) + tuple(partials) + (p2_valid,), num_keys=1
    )
    sp2 = sorted2[0]
    sparts = sorted2[1:-1]
    svalid2 = sorted2[-1]

    skey2 = sp2 >> jnp.uint64(iota_bits2)
    boundary2 = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), skey2[1:] != skey2[:-1]]
    )
    seg2 = jnp.cumsum(boundary2.astype(jnp.int32)) - 1
    num_groups = jnp.max(jnp.where(svalid2, seg2 + 1, 0))
    starts2, ends2 = _segment_bounds(seg2, num_segments)
    valid_out = jnp.arange(num_segments, dtype=jnp.int32) < num_groups
    ends2 = jnp.where(valid_out, ends2, starts2)

    _COMBINE2 = {
        "sum": "sum",
        "count": "sum",
        "min": "min",
        "max": "max",
        "first": "first",
        "last": "last",
    }
    finals = [
        _segment_reduce(
            _COMBINE2[op], sp, seg2, starts2, ends2
        )
        for ((_, op, _), sp) in zip(parts, sparts)
    ]

    # reconstruct the key column(s) from the segment-start order word
    key_rel = skey2[jnp.clip(starts2, 0, p2_rows - 1)]
    out_cols = _reconstruct_keys(key_rel, key_cols, kmins, field_bits)
    out_names = list(key_names)
    out_cols, out_names = _assemble_output(
        table, plan, finals, valid_out, out_cols, out_names
    )
    return (
        Table(out_cols, out_names),
        num_groups,
        max_chunk,
        overflow,
    )


def _pallas_word_sort(packed, iota_bits: int, chunk_rows: int, u32: bool):
    """Sort the (C, T) packed u64 words with the VMEM bitonic network,
    key only, and return ``(sorted_words, perm)`` where perm is each
    row's embedded-iota source index — the permutation the caller
    applies to value columns by gather.

    ``u32=True`` runs the single-word u32 network on the narrowed
    words (the ``"pallas32"`` engine); the caller is responsible for
    OR-ing the ``rel < 2^(32 - iota_bits)`` fit into its traced
    overflow flag, so a mis-sized call is detected, never silently
    wrong. The all-ones sentinel padding word narrows to all-ones, and
    its perm bits clip inside [0, T), so padding rows gather garbage
    that lands in the trailing garbage segment — same contract as
    riding the variadic sort."""
    from ..kernels.bitonic_sort import batched_sort_u32, batched_sort_u64

    mask = jnp.uint64((1 << iota_bits) - 1)
    if u32:
        s32 = batched_sort_u32(packed.astype(jnp.uint32))[0]
        spacked = jnp.where(
            s32 == ~jnp.uint32(0), _U64_MAX, s32.astype(jnp.uint64)
        )
    else:
        spacked = batched_sort_u64(packed)[0]
    # perm needs no clamp: chunk_rows is a power of two, so the iota
    # mask already bounds it to [0, T) — including the all-ones
    # sentinel, whose masked bits gather discarded garbage
    perm = (spacked & mask).astype(jnp.int32)
    return spacked, perm


def _validate_and_names(table, by, aggs, field_bits):
    """Shared preamble of both packed kernels: eligibility, field_bits
    arity, output key names, resolved key columns."""
    if not packed_groupby_supported(table, by, aggs):
        raise ValueError(
            "packed groupby: no-null integer-family keys and no-null "
            "decomposable value columns required"
        )
    if field_bits is None and len(by) != 1:
        raise ValueError("multi-key packed groupby needs field_bits")
    if field_bits is not None and len(field_bits) != len(by):
        raise ValueError("field_bits must have one entry per key")
    key_names = [
        c
        if isinstance(c, str)
        else (table.names[c] if table.names else f"key{i}")
        for i, c in enumerate(by)
    ]
    key_cols = [table.column(k) for k in by]
    return key_names, key_cols


def _slice_groups(out: Table, g: int) -> Table:
    """The capped result trimmed to its exact group count."""
    return Table(
        [
            Column(
                col.data[:g],
                col.dtype,
                None if col.validity is None else col.validity[:g],
                None if col.lengths is None else col.lengths[:g],
            )
            for col in out.columns
        ],
        out.names,
    )


def _composite_rel(key_cols, field_bits, allowed_bits: int):
    """(rel composite u64 (n,), kmins, overflow): the relative key word
    shared by the chunked and flat paths. ``allowed_bits`` is how many
    high bits the packing level(s) leave for the key fields; the traced
    overflow flag trips when data exceeds the declared widths."""
    if field_bits is None:
        kw = keys_mod.column_order_keys(key_cols[0])[0]
        kmin = jnp.min(kw)
        rel = kw - kmin
        overflow = jnp.max(rel) >= (
            (jnp.uint64(1) << jnp.uint64(allowed_bits)) - jnp.uint64(1)
        )
        return rel, [kmin], overflow
    if sum(field_bits) > allowed_bits:
        raise ValueError(
            f"field_bits {field_bits} exceed the {allowed_bits} bits "
            "this packing leaves; the router must decline this shape"
        )
    rels = []
    overflow = jnp.zeros((), jnp.bool_)
    kmins = []
    for kc, b in zip(key_cols, field_bits):
        kwi = keys_mod.column_order_keys(kc)[0]
        kmini = jnp.min(kwi)
        kmins.append(kmini)
        reli = kwi - kmini
        overflow = jnp.logical_or(
            overflow,
            jnp.max(reli) >= (jnp.uint64(1) << jnp.uint64(b)),
        )
        rels.append(reli)
    return keys_mod.fold_fields(rels, field_bits), kmins, overflow


def _reconstruct_keys(key_rel, key_cols, kmins, field_bits):
    """Key column(s) from the composite relative word at segment starts."""
    out = []
    if field_bits is None:
        out.append(
            Column(_unkey(key_rel + kmins[0], key_cols[0].dtype),
                   key_cols[0].dtype, None)
        )
        return out
    fields = keys_mod.peel_fields(key_rel, field_bits)
    for kc, kmini, f in zip(key_cols, kmins, fields):
        out.append(Column(_unkey(f + kmini, kc.dtype), kc.dtype, None))
    return out


def _assemble_output(table, plan, finals, valid_out, out_cols, out_names):
    """User-facing agg columns, schema-identical to the single-pass
    path (count INT64, float sums FLOAT64, min/max/first/last keep the
    source dtype via from_values re-encoding)."""
    for op, a, main_i, count_i in plan:
        colref = a.column
        base = (
            colref
            if isinstance(colref, str)
            else (table.names[colref] if table.names else f"c{colref}")
        )
        out_name = a.name or f"{a.op}_{base}"
        src = table.column(colref)
        if op == "mean":
            total = finals[main_i]
            cnt = finals[count_i]
            mean = total.astype(jnp.float64) / jnp.maximum(cnt, 1)
            if src.dtype.is_decimal:
                mean = mean * (10.0 ** src.dtype.scale)
            out_cols.append(
                compute.from_values(mean, dt.FLOAT64, valid_out)
            )
        elif op == "count":
            out_cols.append(Column(finals[main_i], dt.INT64, None))
        elif op == "sum":
            v = finals[main_i]
            if src.dtype.is_floating:
                out_cols.append(compute.from_values(v, dt.FLOAT64, None))
            elif src.dtype.is_decimal:
                out_cols.append(
                    Column(
                        v,
                        dt.DType(dt.TypeId.DECIMAL64, src.dtype.scale),
                        None,
                    )
                )
            else:
                out_cols.append(Column(v, dt.INT64, None))
        else:
            out_cols.append(
                compute.from_values(finals[main_i], src.dtype, None)
            )
        out_names.append(out_name)
    return out_cols, out_names


def groupby_aggregate_packed(
    table: Table,
    by: Sequence[Union[int, str]],
    aggs: Sequence[GroupbyAgg],
    chunk_rows: int = 1 << 18,
    chunk_segments: Optional[int] = None,
) -> Optional[Table]:
    """Eager packed groupby with exact output size, or None when the
    shape is ineligible (caller falls back to chunked / single-pass).

    Range fitting is decided EAGERLY from one min/max reduction (two
    8-byte fetches), so the jitted graph never needs a fallback branch;
    the traced overflow flag is still asserted as a belt."""
    n = table.row_count
    if n <= chunk_rows:
        return None
    if not packed_groupby_supported(table, by, aggs):
        return None
    spans = []
    for k in by:
        kw = keys_mod.column_order_keys(table.column(k))[0]
        lo, hi = _minmax(kw)
        spans.append(int(hi) - int(lo))
    c = -(-n // chunk_rows)
    iota_bits = max(1, (chunk_rows - 1).bit_length())
    if len(by) == 1:
        field_bits = None
        span_bits = max(1, spans[0].bit_length())
    else:
        field_bits = tuple(
            max(1, sp.bit_length()) for sp in spans
        )
        span_bits = sum(field_bits)
    # cardinality proxy: the product of spans caps distinct keys
    span_card = 1
    for sp in spans:
        span_card *= sp + 1
        if span_card > n:
            span_card = n
            break
    if chunk_segments is None:
        # worst-case distinct keys per chunk bounded by the span product
        guess = min(
            chunk_rows, 1 << max(6, (span_card - 1).bit_length())
        )
        chunk_segments = min(guess, 1 << 14)
    iota_bits2 = max(1, (c * (chunk_segments + 1) - 1).bit_length())
    if field_bits is None:
        limit = (1 << (64 - max(iota_bits, iota_bits2))) - 1
        if spans[0] >= limit:
            return None
    else:
        if span_bits + max(iota_bits, iota_bits2) > 63:
            return None
    if span_card > chunk_segments * 4 and span_card > chunk_rows:
        # keys too spread for per-chunk dedup to win — but the FLAT
        # packed sort (one narrow word over the whole column) still
        # strictly beats the general single-pass sort's operand width
        flat_iota = max(1, (n - 1).bit_length())
        flat_allowed = (
            64 - flat_iota if field_bits is None else 63 - flat_iota
        )
        if field_bits is None:
            if spans[0] >= (1 << flat_allowed) - 1:
                return None
        elif span_bits > flat_allowed:
            return None
        # quantize the capacity knob: a raw data-dependent span_card
        # would force one XLA recompile per observed key range
        flat_cap = min(n, 1 << max(6, (span_card - 1).bit_length()))
        out, ng, ov = _packed_flat_fn(
            tuple(by), tuple(aggs), flat_cap, field_bits
        )(table)
        assert not bool(ov), "flat packed groupby overflow"
        return _slice_groups(out, int(ng))

    for _ in range(2):
        out, num_groups, max_chunk, overflow = _jit_packed(
            table, tuple(by), tuple(aggs),
            min(c * chunk_segments, n), chunk_rows, chunk_segments,
            field_bits,
        )
        assert not bool(overflow), "packed groupby range overflow"
        if int(max_chunk) <= chunk_segments:
            return _slice_groups(out, int(num_groups))
        if chunk_segments >= chunk_rows:
            break
        chunk_segments = min(
            chunk_rows, 1 << int(max_chunk - 1).bit_length()
        )
    return None


@functools.lru_cache(maxsize=256)
def _packed_fn(by, aggs, num_segments, chunk_rows, chunk_segments,
               field_bits):
    def fn(tbl):
        return groupby_aggregate_packed_chunked(
            tbl, list(by), list(aggs), num_segments, chunk_rows,
            chunk_segments, field_bits,
        )

    return jax.jit(fn)


def _jit_packed(table, by, aggs, num_segments, chunk_rows, chunk_segments,
                field_bits=None):
    return _packed_fn(
        by, aggs, num_segments, chunk_rows, chunk_segments, field_bits
    )(table)


def groupby_aggregate_packed_flat(
    table: Table,
    by: Sequence[Union[int, str]],
    aggs: Sequence[GroupbyAgg],
    num_segments: int,
    field_bits: Optional[tuple] = None,
    values_via: str = "sort",
) -> tuple[Table, jax.Array, jax.Array]:
    """Jittable SINGLE-LEVEL packed groupby — the high-cardinality arm.

    When distinct keys rival the chunk size, per-chunk dedup buys
    nothing and the two-level design only adds a combine pass; but the
    packed sort is still strictly narrower than the general single-pass
    sort (one u64 vs key words + iota + occupancy). This variant is that
    single sort: pack, sort once over the whole column, segment-reduce.

    ``values_via`` routes the value columns to sorted order: ``"sort"``
    carries them as lax.sort payloads (each payload rides every one of
    the network's O(log^2 n) passes); ``"gather"`` sorts the packed
    word ALONE and applies the embedded-iota permutation with one
    gather per value column (one extra O(n) pass each, no per-pass
    cost). Which wins is a measured on-chip A/B (bench
    ``groupby*_flat*`` rungs).

    Returns ``(padded result of num_segments rows, num_groups,
    overflow)`` — EXACT iff ``overflow`` is False (key fields fit AND
    num_groups <= num_segments; both folded into the flag)."""
    key_names, key_cols = _validate_and_names(table, by, aggs, field_bits)
    n = table.row_count
    iota_bits = max(1, (n - 1).bit_length())
    allowed = (
        64 - iota_bits if field_bits is None else 63 - iota_bits
    )
    rel, kmins, overflow = _composite_rel(key_cols, field_bits, allowed)

    parts, plan = _plan(table, aggs)
    vals_in = [
        compute.values(table.column(colref)) for (_, _, colref) in parts
    ]
    packed = (rel << jnp.uint64(iota_bits)) | jnp.arange(
        n, dtype=jnp.uint64
    )
    if values_via == "sort":
        sorted_all = jax.lax.sort((packed,) + tuple(vals_in), num_keys=1)
        skey = sorted_all[0] >> jnp.uint64(iota_bits)
        svals = sorted_all[1:]
    elif values_via == "gather":
        sword = jax.lax.sort((packed,), num_keys=1)[0]
        skey = sword >> jnp.uint64(iota_bits)
        perm = (sword & jnp.uint64((1 << iota_bits) - 1)).astype(
            jnp.int32
        )
        svals = tuple(jnp.take(v, perm, axis=0) for v in vals_in)
    else:
        raise ValueError(f"unknown values_via {values_via!r}")

    boundary = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), skey[1:] != skey[:-1]]
    )
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    num_groups = seg[-1] + 1
    overflow = jnp.logical_or(overflow, num_groups > num_segments)

    from .groupby import _segment_bounds

    starts, ends = _segment_bounds(seg, num_segments)
    valid_out = jnp.arange(num_segments, dtype=jnp.int32) < num_groups
    ends = jnp.where(valid_out, ends, starts)
    finals = [
        _segment_reduce(op, sv, seg, starts, ends)
        for ((_, op, _), sv) in zip(parts, svals)
    ]
    key_rel = skey[jnp.clip(starts, 0, n - 1)]
    out_cols = _reconstruct_keys(key_rel, key_cols, kmins, field_bits)
    out_names = list(key_names)
    out_cols, out_names = _assemble_output(
        table, plan, finals, valid_out, out_cols, out_names
    )
    return Table(out_cols, out_names), num_groups, overflow


@functools.lru_cache(maxsize=256)
def _packed_flat_fn(by, aggs, num_segments, field_bits):
    def fn(tbl):
        return groupby_aggregate_packed_flat(
            tbl, list(by), list(aggs), num_segments, field_bits
        )

    return jax.jit(fn)
