"""Partitioning (cudf ``hash_partition``/``round_robin_partition``).

This is the device half of shuffle exchange: assign each row a partition,
reorder rows so partitions are contiguous, and report per-partition
counts. The exchange itself (the UCX/NCCL shuffle manager the GPU stack
gets from the spark-rapids plugin — absent in the reference repo, see
SURVEY.md §2.5) lives in parallel/shuffle.py as ICI all-to-all collectives.

``hash_partition`` uses Spark's ``Pmod(Murmur3Hash(keys), n)`` so rows
land on the same partition ids a Spark cluster would compute.

Everything here is static-shaped, hence fully jittable with no capacity
tricks: reordering is a stable sort by partition id and counts are a
``bincount``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .. import dtype as dt
from ..column import Column, Table
from .gather import gather_table
from .hashing import murmur3_table


def partition_ids_hash(
    table: Table,
    columns: Optional[Sequence[Union[int, str]]],
    num_partitions: int,
) -> jax.Array:
    """Spark HashPartitioning ids: pmod(murmur3(keys), n) (non-negative)."""
    h = murmur3_table(table, columns).data.astype(jnp.int32)
    return jnp.mod(jnp.mod(h, num_partitions) + num_partitions, num_partitions)


def _reorder_by_parts(
    table: Table, part: jax.Array, num_partitions: int
) -> tuple[Table, jax.Array]:
    order = jnp.argsort(part, stable=True)
    counts = jnp.bincount(part, length=num_partitions).astype(jnp.int32)
    return gather_table(table, order.astype(jnp.int32)), counts


def hash_partition(
    table: Table,
    columns: Optional[Sequence[Union[int, str]]],
    num_partitions: int,
) -> tuple[Table, jax.Array]:
    """(rows reordered partition-contiguously, per-partition counts)."""
    part = partition_ids_hash(table, columns, num_partitions)
    return _reorder_by_parts(table, part, num_partitions)


def round_robin_partition(
    table: Table, num_partitions: int, start_partition: int = 0
) -> tuple[Table, jax.Array]:
    n = table.row_count
    part = jnp.mod(
        jnp.arange(n, dtype=jnp.int32) + start_partition, num_partitions
    )
    return _reorder_by_parts(table, part, num_partitions)
