"""Partitioning (cudf ``hash_partition``/``round_robin_partition``).

This is the device half of shuffle exchange: assign each row a partition,
reorder rows so partitions are contiguous, and report per-partition
counts. The exchange itself (the UCX/NCCL shuffle manager the GPU stack
gets from the spark-rapids plugin — absent in the reference repo, see
SURVEY.md §2.5) lives in parallel/shuffle.py as ICI all-to-all collectives.

``hash_partition`` uses Spark's ``Pmod(Murmur3Hash(keys), n)`` so rows
land on the same partition ids a Spark cluster would compute.

Everything here is static-shaped, hence fully jittable with no capacity
tricks: reordering is a stable sort by partition id and counts are a
``bincount``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .. import dtype as dt
from ..column import Column, Table
from .gather import gather_table
from .hashing import murmur3_table


def partition_ids_hash(
    table: Table,
    columns: Optional[Sequence[Union[int, str]]],
    num_partitions: int,
) -> jax.Array:
    """Spark HashPartitioning ids: pmod(murmur3(keys), n) (non-negative)."""
    h = murmur3_table(table, columns).data.astype(jnp.int32)
    return jnp.mod(jnp.mod(h, num_partitions) + num_partitions, num_partitions)


def _reorder_by_parts(
    table: Table, part: jax.Array, num_partitions: int
) -> tuple[Table, jax.Array]:
    order = jnp.argsort(part, stable=True)
    counts = jnp.bincount(part, length=num_partitions).astype(jnp.int32)
    return gather_table(table, order.astype(jnp.int32)), counts


def hash_partition(
    table: Table,
    columns: Optional[Sequence[Union[int, str]]],
    num_partitions: int,
) -> tuple[Table, jax.Array]:
    """(rows reordered partition-contiguously, per-partition counts)."""
    part = partition_ids_hash(table, columns, num_partitions)
    return _reorder_by_parts(table, part, num_partitions)


def round_robin_partition(
    table: Table, num_partitions: int, start_partition: int = 0
) -> tuple[Table, jax.Array]:
    n = table.row_count
    part = jnp.mod(
        jnp.arange(n, dtype=jnp.int32) + start_partition, num_partitions
    )
    return _reorder_by_parts(table, part, num_partitions)


def range_splitters(
    table: Table,
    columns: Sequence[Union[int, str]],
    num_partitions: int,
    sample_size: int = 8192,
) -> list[jax.Array]:
    """P-1 range splitters from a deterministic host-side key sample.

    Spark's RangePartitioning boundary computation: sample the sort-key
    order words at a fixed stride, lexsort the sample, and cut it into
    ``num_partitions`` equal runs. Deterministic given the table, so the
    exact path and every mesh replica compute identical boundaries —
    the byte-parity anchor for range partition as a plan op.
    """
    import numpy as np

    from .sort import SortKey, _key_words

    keys = [SortKey(c) for c in columns]
    words = []
    for k in keys:
        words.extend(_key_words(table.column(k.column), k))
    n = table.row_count
    stride = max(n // max(sample_size, 1), 1)
    # srt: allow-host-sync(range-partition sampling: the splitter sample is a deliberate host step)
    samp = [np.asarray(w[::stride]) for w in words]
    order = np.lexsort(samp[::-1])
    m = order.shape[0]
    cut = [order[(i * m) // num_partitions] for i in range(1, num_partitions)]
    return [jnp.asarray(np.stack([s[c] for c in cut])) for s in samp]


def partition_ids_range(
    table: Table,
    columns: Sequence[Union[int, str]],
    splitters: Sequence[jax.Array],
) -> jax.Array:
    """Range-partition ids from precomputed splitters (jittable).

    partition id = number of splitters <= key, lexicographically over
    the key order words — mirrors distributed_sort's dest computation
    so a range ``partition`` plan op and TotalOrderSort agree.
    """
    from .sort import SortKey, _key_words

    keys = [SortKey(c) for c in columns]
    words = []
    for k in keys:
        words.extend(_key_words(table.column(k.column), k))
    n = table.row_count
    nsplit = 0 if not splitters else int(splitters[0].shape[0])
    dest = jnp.zeros((n,), jnp.int32)
    for i in range(nsplit):
        le = jnp.zeros((n,), jnp.bool_)
        eq = jnp.ones((n,), jnp.bool_)
        for w, sp in zip(words, splitters):
            sv = sp[i]
            le = le | (eq & (sv < w))
            eq = eq & (sv == w)
        dest = dest + (le | eq).astype(jnp.int32)
    return dest


def range_partition(
    table: Table,
    columns: Sequence[Union[int, str]],
    num_partitions: int,
    sample_size: int = 8192,
) -> tuple[Table, jax.Array]:
    """(rows reordered partition-contiguously, per-partition counts)."""
    splitters = range_splitters(table, columns, num_partitions, sample_size)
    part = partition_ids_range(table, columns, splitters)
    return _reorder_by_parts(table, part, num_partitions)
