"""Copy-family ops (cudf ``concatenate`` / ``interleave_columns`` /
``copy_if_else`` / ``sequence``).

Capability-surface rows of SURVEY.md §2.3: column factories and
table-assembly utilities the vendored cudf Java suite exercises. All
shapes here are static functions of the inputs, so every op jits.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column, Table
from . import compute


def concatenate_columns(cols: Sequence[Column]) -> Column:
    """Vertical concatenation of same-dtype columns."""
    if not cols:
        raise ValueError("concatenate needs at least one column")
    d = cols[0].dtype
    for c in cols[1:]:
        if c.dtype != d:
            raise TypeError(f"concatenate dtype mismatch: {d} vs {c.dtype}")
    lengths = None
    if d.is_string:
        # strings carry a (n,) lengths vector beside the padded matrix;
        # repad to the widest so row widths agree before concatenating
        from .strings import repad

        width = max(c.data.shape[1] for c in cols)
        cols = [repad(c, width) for c in cols]
        data = jnp.concatenate([c.data for c in cols], axis=0)
        lengths = jnp.concatenate([c.lengths for c in cols])
    else:
        data = jnp.concatenate([c.data for c in cols], axis=0)
    if any(c.validity is not None for c in cols):
        valid = jnp.concatenate([compute.valid_mask(c) for c in cols])
    else:
        valid = None
    return Column(data, d, valid, lengths)


def concatenate(tables: Sequence[Table]) -> Table:
    """Vertical concatenation of same-schema tables (cudf
    ``Table.concatenate``)."""
    if not tables:
        raise ValueError("concatenate needs at least one table")
    first = tables[0]
    for t in tables[1:]:
        if t.num_columns != first.num_columns:
            raise ValueError("concatenate: column counts differ")
    out = [
        concatenate_columns([t.columns[i] for t in tables])
        for i in range(first.num_columns)
    ]
    return Table(out, list(first.names))


def interleave_columns(table: Table) -> Column:
    """Row-major interleave of same-dtype columns into one column
    (cudf ``interleave_columns``): output row i*ncols+j = col j row i."""
    d = table.columns[0].dtype
    for c in table.columns[1:]:
        if c.dtype != d:
            raise TypeError("interleave_columns needs uniform dtype")
    if d.is_string:
        raise TypeError("interleave_columns: fixed-width only")
    data = jnp.stack([c.data for c in table.columns], axis=1).reshape(-1)
    if any(c.validity is not None for c in table.columns):
        valid = jnp.stack(
            [compute.valid_mask(c) for c in table.columns], axis=1
        ).reshape(-1)
    else:
        valid = None
    return Column(data, d, valid)


def copy_if_else(
    mask: Column, lhs: Union[Column, object], rhs: Union[Column, object]
) -> Column:
    """Per-row select: mask TRUE -> lhs, else rhs (cudf ``copy_if_else``).
    Null mask rows select rhs (Spark CASE WHEN semantics). Scalars are
    broadcast."""
    if not mask.dtype.is_boolean:
        raise TypeError("copy_if_else mask must be BOOL8")
    pred = mask.data
    if mask.validity is not None:
        pred = jnp.logical_and(pred, mask.validity)
    n = len(mask)

    def as_column(x, like: Column | None):
        if isinstance(x, Column):
            return x
        if like is None:
            raise TypeError("copy_if_else: both sides scalar is ambiguous")
        vals = jnp.full((n,), x)
        return compute.from_values(vals, like.dtype, None)

    lhs_col = as_column(lhs, rhs if isinstance(rhs, Column) else None)
    rhs_col = as_column(rhs, lhs_col)
    if lhs_col.dtype != rhs_col.dtype:
        raise TypeError(
            f"copy_if_else dtype mismatch: {lhs_col.dtype} vs {rhs_col.dtype}"
        )
    lengths = None
    if lhs_col.dtype.is_string:
        if lhs_col.data.shape[1] != rhs_col.data.shape[1]:
            from .strings import repad

            width = max(lhs_col.data.shape[1], rhs_col.data.shape[1])
            lhs_col, rhs_col = repad(lhs_col, width), repad(rhs_col, width)
        data = jnp.where(pred[:, None], lhs_col.data, rhs_col.data)
        lengths = jnp.where(pred, lhs_col.lengths, rhs_col.lengths)
    else:
        data = jnp.where(pred, lhs_col.data, rhs_col.data)
    if lhs_col.validity is None and rhs_col.validity is None:
        valid = None
    else:
        valid = jnp.where(
            pred, compute.valid_mask(lhs_col), compute.valid_mask(rhs_col)
        )
    return Column(data, lhs_col.dtype, valid, lengths)


def sequence(n: int, start=0, step=1, dtype: dt.DType = dt.INT32) -> Column:
    """Arithmetic sequence column (cudf ``sequence``; the offsets builder
    of the reference's row conversion, row_conversion.cu:389-390)."""
    vals = start + step * jnp.arange(n, dtype=jnp.int64)
    return compute.from_values(vals, dtype, None)


def cross_join(left: Table, right: Table) -> Table:
    """Cartesian product (cudf ``cross_join`` / Java ``Table.crossJoin``):
    every left row paired with every right row, left-major order. Output
    size is the static product, so the op jits."""
    from .gather import gather_table

    nl, nr = left.row_count, right.row_count
    li = jnp.repeat(
        jnp.arange(nl, dtype=jnp.int32), nr, total_repeat_length=nl * nr
    )
    ri = jnp.tile(jnp.arange(nr, dtype=jnp.int32), nl)
    lg = gather_table(left, li)
    rg = gather_table(right, ri)
    lnames = list(left.names) if left.names else [
        f"l{i}" for i in range(left.num_columns)
    ]
    rnames = list(right.names) if right.names else [
        f"r{i}" for i in range(right.num_columns)
    ]
    return Table(list(lg.columns) + list(rg.columns), lnames + rnames)


def scatter(source: Table, indices, target: Table) -> Table:
    """Rows of ``source`` written into ``target`` at ``indices`` (cudf
    ``scatter``): out[indices[i]] = source[i], other rows unchanged.
    Schemas must match; which duplicate index wins is unspecified (as in
    cudf — JAX documents conflicting ``.at[].set`` updates as
    implementation-defined order)."""
    if source.num_columns != target.num_columns:
        raise ValueError("scatter: column counts differ")
    idx = jnp.asarray(indices).astype(jnp.int32)
    out_cols = []
    for s, t in zip(source.columns, target.columns):
        if s.dtype != t.dtype:
            raise TypeError(
                f"scatter dtype mismatch: {s.dtype} vs {t.dtype}"
            )
        if s.dtype.is_string and s.data.shape[1] != t.data.shape[1]:
            from .strings import repad

            width = max(s.data.shape[1], t.data.shape[1])
            s, t = repad(s, width), repad(t, width)
        data = t.data.at[idx].set(s.data)
        valid = None
        if s.validity is not None or t.validity is not None:
            valid = compute.valid_mask(t).at[idx].set(
                compute.valid_mask(s)
            )
        lengths = t.lengths
        if t.lengths is not None:
            lengths = t.lengths.at[idx].set(s.lengths)
        out_cols.append(Column(data, t.dtype, valid, lengths))
    return Table(out_cols, target.names)


def slice_rows(table: Table, start: int, stop: int) -> Table:
    """Zero-copy row range [start, stop) of every column (cudf
    ``slice``). The single place the per-Column data/validity/lengths
    slicing lives — chunked joins, split, and empty-schema fast paths
    all use it."""
    return Table(
        [
            Column(
                c.data[start:stop],
                c.dtype,
                None if c.validity is None else c.validity[start:stop],
                None if c.lengths is None else c.lengths[start:stop],
            )
            for c in table.columns
        ],
        table.names,
    )


def split(table: Table, splits: Sequence[int]) -> list[Table]:
    """Partition rows at the given boundaries (cudf ``Table.split`` /
    ``contiguous_split``, the mechanism behind the reference's 2 GB
    batching): ``splits=[s1, s2]`` yields [0,s1), [s1,s2), [s2,n)."""
    n = table.row_count
    bounds = [0] + [int(s) for s in splits] + [n]
    for a, b in zip(bounds, bounds[1:]):
        if not (0 <= a <= b <= n):
            raise ValueError(f"split: bad boundaries {splits}")
    return [slice_rows(table, a, b) for a, b in zip(bounds, bounds[1:])]


def repeat(table: Table, counts) -> Table:
    """Each row i replicated ``counts[i]`` times, in order (cudf
    ``Table.repeat``). A scalar count repeats every row that many times
    (jittable: static output size); a per-row count vector is eager
    (host-syncs the total, the cudf call model)."""
    from .gather import gather_table

    n = table.row_count
    if np.isscalar(counts):
        k = int(counts)
        if k < 0:
            raise ValueError("repeat: count must be non-negative")
        idx = jnp.repeat(
            jnp.arange(n, dtype=jnp.int32), k, total_repeat_length=n * k
        )
        return gather_table(table, idx)
    c = np.asarray(counts)
    if c.shape != (n,):
        raise ValueError(f"repeat: counts shape {c.shape} != ({n},)")
    if (c < 0).any():
        raise ValueError("repeat: counts must be non-negative")
    idx = jnp.asarray(np.repeat(np.arange(n, dtype=np.int32), c))
    return gather_table(table, idx)


def sample(table: Table, n: int, seed: int = 0,
           replacement: bool = False) -> Table:
    """Random row sample (cudf ``Table.sample``), jax PRNG keyed by
    ``seed`` — deterministic for a given seed like cudf's."""
    import jax

    from .gather import gather_table

    rows = table.row_count
    key = jax.random.PRNGKey(seed)
    if replacement:
        if rows == 0 and n > 0:
            raise ValueError("sample with replacement from an empty table")
        idx = jax.random.randint(key, (n,), 0, max(rows, 1))
    else:
        if n > rows:
            raise ValueError(f"sample of {n} from {rows} rows")
        idx = jax.random.permutation(key, rows)[:n]
    return gather_table(table, idx.astype(jnp.int32))
