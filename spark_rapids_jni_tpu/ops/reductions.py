"""Column reductions (cudf ``reduce``): null-skipping Spark aggregates.

Results are returned as 1-element Columns (so null results — e.g. SUM over
an all-null column — are representable, matching Spark).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column
from . import compute

_REDUCTIONS = {
    "sum", "min", "max", "mean", "count", "any", "all", "product",
    "variance", "std",
}


def reduce(col: Column, op: str) -> Column:
    """Null-skipping reduction to a 1-row column."""
    if op not in _REDUCTIONS:
        raise ValueError(f"unknown reduction {op!r}")
    valid = compute.valid_mask(col)
    n_valid = jnp.sum(valid)

    if op == "count":
        return Column(n_valid.astype(jnp.int64)[None], dt.INT64, None)

    if op in ("any", "all"):
        if not col.dtype.is_boolean:
            raise TypeError(f"{op} requires BOOL8")
        masked = col.data & valid
        if op == "any":
            out = jnp.any(masked)
        else:
            out = jnp.all(jnp.where(valid, col.data, True))
        return Column(out[None], dt.BOOL8, (n_valid > 0)[None])

    vals = compute.values(col)
    has_result = (n_valid > 0)[None]

    if op == "sum" or op == "mean":
        acc_dtype = (
            jnp.float64
            if col.dtype.is_floating
            else jnp.int64
        )
        total = jnp.sum(jnp.where(valid, vals, 0).astype(acc_dtype))
        if op == "mean":
            mean = total.astype(jnp.float64) / jnp.maximum(n_valid, 1)
            if col.dtype.is_decimal:
                mean = mean * (10.0 ** col.dtype.scale)
            return compute.from_values(mean[None], dt.FLOAT64, has_result)
        if col.dtype.is_floating:
            return compute.from_values(total[None], dt.FLOAT64, has_result)
        if col.dtype.is_decimal:
            # Spark widens decimal SUM; keep scale, widen storage to 64-bit.
            out_dt = dt.DType(dt.TypeId.DECIMAL64, col.dtype.scale)
            return compute.from_values(total[None], out_dt, has_result)
        return compute.from_values(total[None], dt.INT64, has_result)

    if op in ("variance", "std"):
        # sample variance (ddof=1), cudf/Spark default; null when fewer
        # than 2 valid rows
        fvals = vals.astype(jnp.float64)
        if col.dtype.is_decimal:
            fvals = fvals * (10.0 ** col.dtype.scale)
        m = jnp.sum(jnp.where(valid, fvals, 0)) / jnp.maximum(n_valid, 1)
        sq = jnp.sum(jnp.where(valid, (fvals - m) ** 2, 0))
        var = sq / jnp.maximum(n_valid - 1, 1)
        out = jnp.sqrt(var) if op == "std" else var
        return compute.from_values(out[None], dt.FLOAT64, (n_valid > 1)[None])

    if op == "product":
        acc = jnp.where(valid, vals, 1)
        total = jnp.prod(acc.astype(jnp.float64 if col.dtype.is_floating else jnp.int64))
        out_dt = dt.FLOAT64 if col.dtype.is_floating else dt.INT64
        return compute.from_values(total[None], out_dt, has_result)

    # min / max with +-inf / int extremes as masked sentinels
    if col.dtype.is_floating:
        sentinel = jnp.inf if op == "min" else -jnp.inf
    elif col.dtype.is_boolean:
        sentinel = op == "min"
    else:
        info = np.iinfo(np.dtype(col.dtype.storage_dtype))
        sentinel = info.max if op == "min" else info.min
    if vals.shape[0] == 0:
        # jnp.min/max have no identity and raise on 0 rows; an empty
        # reduction is simply null (has_result is already False)
        out = jnp.asarray(sentinel, vals.dtype)
    else:
        masked = jnp.where(valid, vals, jnp.asarray(sentinel, vals.dtype))
        out = jnp.min(masked) if op == "min" else jnp.max(masked)
    return compute.from_values(out[None], col.dtype, has_result)


def arg_extreme(col: Column, op: str) -> Column:
    """Row index of the min/max valid value (``argmin``/``argmax``;
    the index half of Spark's ``min_by``/``max_by``). 1-row INT64
    column; null when every value is null. Ties resolve to the
    earliest row (Spark semantics).

    Two passes over u64 order keys, not a sentinel argmin: a sentinel
    collides with legitimate extreme values (INT64_MAX, +/-inf) and
    would return a NULL row's index on the tie. Pass 1 takes the min
    masked key; pass 2 picks the earliest VALID row holding it —
    collision-free even when nulls share the masked key value."""
    from . import keys as keys_mod

    if op not in ("argmin", "argmax"):
        raise ValueError(f"arg_extreme: unknown op {op!r}")
    words = keys_mod.column_order_keys(col)
    if len(words) != 1:
        raise TypeError(
            f"arg_extreme: unsupported by-column type {col.dtype} "
            "(single-word order keys only)"
        )
    valid = compute.valid_mask(col)
    key = words[0] if op == "argmin" else ~words[0]
    masked = jnp.where(valid, key, jnp.uint64(0xFFFFFFFFFFFFFFFF))
    m = jnp.min(masked)
    hit = jnp.logical_and(valid, masked == m)
    idx = jnp.argmax(hit).astype(jnp.int64)
    has = jnp.any(valid)
    return Column(idx[None], dt.INT64, has[None])


def extreme_by(value_col: Column, by_col: Column, op: str) -> Column:
    """Spark ``min_by``/``max_by``: the value of ``value_col`` at the
    row where ``by_col`` is minimal/maximal. 1-row column of
    ``value_col``'s type."""
    from .gather import gather_column

    if op not in ("min_by", "max_by"):
        raise ValueError(f"extreme_by: unknown op {op!r}")
    which = "argmin" if op == "min_by" else "argmax"
    idx = arg_extreme(by_col, which)
    out = gather_column(
        value_col, idx.data.astype(jnp.int32), index_valid=idx.validity
    )
    return out
