"""Replace-family ops (cudf ``replace_nulls`` / ``nans_to_nulls`` /
``find_and_replace`` / ``clamp``).

Capability-surface rows of SURVEY.md §2.3. The fill policies re-express
cudf's scan-based implementations as ``jnp`` cumulative maxima over row
indices — O(n) segmented-propagation without serial loops, which is the
TPU-friendly formulation.
"""

from __future__ import annotations

from typing import Union

import jax.numpy as jnp
from jax import lax

from .. import dtype as dt
from ..column import Column
from . import compute

PRECEDING = "preceding"  # carry last valid value forward
FOLLOWING = "following"  # carry next valid value backward


def replace_nulls(col: Column, value) -> Column:
    """Nulls -> ``value`` (scalar or same-dtype column); result keeps
    nulls only where a replacement column is itself null. Strings route
    through copy_if_else (which handles the 2-D byte matrix + lengths)."""
    if col.validity is None:
        return col
    if col.dtype.is_string:
        from .copying import copy_if_else

        if not isinstance(value, Column):
            value = Column.from_strings([value] * len(col))
        mask = Column(col.validity, dt.BOOL8, None)
        return copy_if_else(mask, col, value)
    if isinstance(value, Column):
        if value.dtype != col.dtype:
            raise TypeError("replace_nulls: replacement dtype mismatch")
        data = jnp.where(col.validity, col.data, value.data)
        valid = jnp.where(
            col.validity, True, compute.valid_mask(value)
        )
        return Column(data, col.dtype, valid)
    fill = compute.encode_values(jnp.full((1,), value), col.dtype)[0]
    data = jnp.where(col.validity, col.data, fill)
    return Column(data, col.dtype, None)


def replace_nulls_policy(col: Column, policy: str) -> Column:
    """Directional fill: PRECEDING = last-observation-carried-forward,
    FOLLOWING = next-observation-carried-backward. Leading (resp.
    trailing) nulls stay null."""
    if col.validity is None:
        return col
    if col.dtype.is_string:
        raise TypeError("replace_nulls_policy: fixed-width only")
    n = len(col)
    idx = jnp.arange(n, dtype=jnp.int32)
    if policy == PRECEDING:
        # source[i] = largest valid row index <= i
        src = lax.cummax(jnp.where(col.validity, idx, -1))
        valid = src >= 0
    elif policy == FOLLOWING:
        # source[i] = smallest valid row index >= i (cummax on the
        # reversed, negated index)
        src = jnp.where(col.validity, idx, n)
        src = n - 1 - lax.cummax((n - 1 - src)[::-1])[::-1]
        valid = src <= n - 1
    else:
        raise ValueError(f"unknown fill policy {policy!r}")
    data = jnp.take(col.data, jnp.clip(src, 0, n - 1), axis=0)
    return Column(data, col.dtype, valid)


def nans_to_nulls(col: Column) -> Column:
    """Float NaN payloads become nulls (cudf ``nans_to_nulls``)."""
    if not col.dtype.is_floating:
        return col
    not_nan = jnp.logical_not(jnp.isnan(compute.values(col)))
    valid = (
        not_nan
        if col.validity is None
        else jnp.logical_and(col.validity, not_nan)
    )
    return Column(col.data, col.dtype, valid)


def find_and_replace(col: Column, old_values, new_values) -> Column:
    """Value substitution table (cudf ``find_and_replace_all``): each row
    equal to old_values[k] becomes new_values[k]."""
    if len(old_values) != len(new_values):
        raise ValueError("find_and_replace: length mismatch")
    vals = compute.values(col)
    out = vals
    for old, new in zip(old_values, new_values):
        out = jnp.where(vals == old, jnp.asarray(new, out.dtype), out)
    return compute.from_values(out, col.dtype, col.validity)


def clamp(
    col: Column,
    lo: Union[int, float, None] = None,
    hi: Union[int, float, None] = None,
) -> Column:
    """Clamp values into [lo, hi] (cudf ``clamp``); None bound = open."""
    vals = compute.values(col)
    if lo is not None:
        vals = jnp.maximum(vals, jnp.asarray(lo, vals.dtype))
    if hi is not None:
        vals = jnp.minimum(vals, jnp.asarray(hi, vals.dtype))
    return compute.from_values(vals, col.dtype, col.validity)
