"""Sort / order-by (cudf ``sorted_order`` + ``gather``).

Comparator dispatch is replaced by key normalization (ops/keys.py): every
key column becomes u64 order keys, descending inverts the key, and null
ordering is an extra leading key word per column — then one stable
``jnp.lexsort`` does the rest (XLA's sort is bitonic on TPU, an efficient
fit; no per-type comparators anywhere).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..column import Column, Table
from . import keys as keys_mod
from .gather import gather_table

_U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)  # numpy scalar: no backend init at import


@dataclasses.dataclass(frozen=True)
class SortKey:
    """One ORDER BY term: column (by name/index), direction, null placement.

    ``nulls_first=None`` picks Spark's default: nulls first when ascending,
    nulls last when descending.
    """

    column: Union[int, str]
    ascending: bool = True
    nulls_first: Optional[bool] = None

    @property
    def resolved_nulls_first(self) -> bool:
        if self.nulls_first is None:
            return self.ascending
        return self.nulls_first


def _key_words(col: Column, key: SortKey) -> list[jax.Array]:
    words = keys_mod.column_order_keys(col)
    if not key.ascending:
        words = [~w for w in words]
    if col.validity is not None:
        # Leading null-placement word: 0 sorts before 1, so nulls get 0 when
        # they go first and 1 when they go last.
        if key.resolved_nulls_first:
            null_word = jnp.where(col.validity, jnp.uint64(1), jnp.uint64(0))
        else:
            null_word = jnp.where(col.validity, jnp.uint64(0), jnp.uint64(1))
        words = [null_word] + words
    return words


def argsort_table(
    table: Table, sort_keys: Sequence[Union[SortKey, str, int]]
) -> jax.Array:
    """Stable row permutation ordering ``table`` by ``sort_keys``."""
    sort_keys = [
        k if isinstance(k, SortKey) else SortKey(k) for k in sort_keys
    ]
    words: list[jax.Array] = []
    for k in sort_keys:
        words.extend(_key_words(table.column(k.column), k))
    # lexsort: last key is primary -> reverse
    return jnp.lexsort(words[::-1])


def sort_table(
    table: Table,
    sort_keys: Sequence[Union[SortKey, str, int]],
    payload: Optional[Table] = None,
) -> Table:
    """ORDER BY: returns the table (or ``payload``) reordered."""
    perm = argsort_table(table, sort_keys)
    return gather_table(payload if payload is not None else table, perm)


def is_sorted(
    table: Table, sort_keys: Sequence[Union[SortKey, str, int]]
) -> jax.Array:
    """Device bool: rows already ordered by ``sort_keys`` (cudf
    ``is_sorted``). Nulls follow each key's resolved placement."""
    sort_keys = [
        k if isinstance(k, SortKey) else SortKey(k) for k in sort_keys
    ]
    words: list[jax.Array] = []
    for k in sort_keys:
        words.extend(_key_words(table.column(k.column), k))
    n = words[0].shape[0]
    if n <= 1:
        return jnp.asarray(True)
    # adjacent-pair lexicographic compare: prev <= next
    eq = jnp.ones((n - 1,), dtype=jnp.bool_)
    ok = jnp.zeros((n - 1,), dtype=jnp.bool_)
    for w in words:
        a, b = w[:-1], w[1:]
        ok = ok | (eq & (a < b))
        eq = eq & (a == b)
    return jnp.all(ok | eq)


def merge_sorted(
    tables: Sequence[Table],
    sort_keys: Sequence[Union[SortKey, str, int]],
) -> Table:
    """K-way merge of individually sorted tables into one sorted table
    (cudf ``cudf::merge`` / Java ``Table.merge``).

    TPU-first design note: a streaming k-way merge is data-dependent
    control flow per output row — hostile to XLA. Concatenate + one
    stable lexsort over normalized u64 key words runs entirely on the
    MXU-adjacent sort network at HBM bandwidth and is how the op lowers
    here; stability preserves the order of equal keys across inputs in
    table order (matching cudf's stable merge)."""
    from .copying import concatenate

    if not tables:
        raise ValueError("merge_sorted: need at least one table")
    whole = concatenate(tables)
    return sort_table(whole, sort_keys)
