"""Sort / order-by (cudf ``sorted_order`` + ``gather``).

Comparator dispatch is replaced by key normalization (ops/keys.py): every
key column becomes u64 order keys, descending inverts the key, and null
ordering is an extra leading key word per column — then one stable
``jnp.lexsort`` does the rest (XLA's sort is bitonic on TPU, an efficient
fit; no per-type comparators anywhere).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..column import Column, Table
from . import keys as keys_mod
from .gather import gather_table

_U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)  # numpy scalar: no backend init at import


@dataclasses.dataclass(frozen=True)
class SortKey:
    """One ORDER BY term: column (by name/index), direction, null placement.

    ``nulls_first=None`` picks Spark's default: nulls first when ascending,
    nulls last when descending.
    """

    column: Union[int, str]
    ascending: bool = True
    nulls_first: Optional[bool] = None

    @property
    def resolved_nulls_first(self) -> bool:
        if self.nulls_first is None:
            return self.ascending
        return self.nulls_first


def _key_words(col: Column, key: SortKey) -> list[jax.Array]:
    words = keys_mod.column_order_keys(col)
    if not key.ascending:
        words = [~w for w in words]
    if col.validity is not None:
        # Leading null-placement word: 0 sorts before 1, so nulls get 0 when
        # they go first and 1 when they go last.
        if key.resolved_nulls_first:
            null_word = jnp.where(col.validity, jnp.uint64(1), jnp.uint64(0))
        else:
            null_word = jnp.where(col.validity, jnp.uint64(0), jnp.uint64(1))
        words = [null_word] + words
    return words


def argsort_table(
    table: Table, sort_keys: Sequence[Union[SortKey, str, int]]
) -> jax.Array:
    """Stable row permutation ordering ``table`` by ``sort_keys``."""
    sort_keys = [
        k if isinstance(k, SortKey) else SortKey(k) for k in sort_keys
    ]
    words: list[jax.Array] = []
    for k in sort_keys:
        words.extend(_key_words(table.column(k.column), k))
    # lexsort: last key is primary -> reverse
    return jnp.lexsort(words[::-1])


def sort_table(
    table: Table,
    sort_keys: Sequence[Union[SortKey, str, int]],
    payload: Optional[Table] = None,
) -> Table:
    """ORDER BY: returns the table (or ``payload``) reordered."""
    perm = argsort_table(table, sort_keys)
    return gather_table(payload if payload is not None else table, perm)
