"""Sort / order-by (cudf ``sorted_order`` + ``gather``).

Comparator dispatch is replaced by key normalization (ops/keys.py): every
key column becomes u64 order keys, descending inverts the key, and null
ordering is an extra leading key word per column — then one stable
``jnp.lexsort`` does the rest (XLA's sort is bitonic on TPU, an efficient
fit; no per-type comparators anywhere).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..column import Column, Table
from . import keys as keys_mod
from .gather import gather_table

_U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)  # numpy scalar: no backend init at import


@dataclasses.dataclass(frozen=True)
class SortKey:
    """One ORDER BY term: column (by name/index), direction, null placement.

    ``nulls_first=None`` picks Spark's default: nulls first when ascending,
    nulls last when descending.
    """

    column: Union[int, str]
    ascending: bool = True
    nulls_first: Optional[bool] = None

    @property
    def resolved_nulls_first(self) -> bool:
        if self.nulls_first is None:
            return self.ascending
        return self.nulls_first


def _key_words(col: Column, key: SortKey) -> list[jax.Array]:
    words = keys_mod.column_order_keys(col)
    if not key.ascending:
        words = [~w for w in words]
    if col.validity is not None:
        # All nulls are EQUAL under ORDER BY: zero their key words so
        # masked garbage cannot order the null block — ties must fall
        # through to the next sort key / stability (caught by the sort
        # fuzz: null-primary rows were ordered by their hidden values)
        words = [
            jnp.where(col.validity, w, jnp.uint64(0)) for w in words
        ]
        # Leading null-placement word: 0 sorts before 1, so nulls get 0 when
        # they go first and 1 when they go last.
        if key.resolved_nulls_first:
            null_word = jnp.where(col.validity, jnp.uint64(1), jnp.uint64(0))
        else:
            null_word = jnp.where(col.validity, jnp.uint64(0), jnp.uint64(1))
        words = [null_word] + words
    return words


def _table_key_words(
    table: Table, sort_keys: Sequence[Union[SortKey, str, int]]
) -> list[jax.Array]:
    """Normalize the key spec and flatten every key column to its u64
    order words — the single front end argsort/sort/is_sorted share."""
    keys = [k if isinstance(k, SortKey) else SortKey(k) for k in sort_keys]
    words: list[jax.Array] = []
    for k in keys:
        words.extend(_key_words(table.column(k.column), k))
    return words


def _occupancy_word(row_valid: jax.Array) -> jax.Array:
    """Leading sort word that sinks unoccupied rows (shape-bucket
    padding, utils/buckets.py) to the END regardless of key direction
    or null placement: real rows get 0, padding rows 1."""
    return jnp.where(row_valid, jnp.uint64(0), jnp.uint64(1))


def argsort_table(
    table: Table,
    sort_keys: Sequence[Union[SortKey, str, int]],
    row_valid: Optional[jax.Array] = None,
) -> jax.Array:
    """Stable row permutation ordering ``table`` by ``sort_keys``.
    ``row_valid`` rows sort first (in key order); padding rows sink to
    the end."""
    words = _table_key_words(table, sort_keys)
    if row_valid is not None:
        words = [_occupancy_word(row_valid)] + words
    # lexsort: last key is primary -> reverse
    return jnp.lexsort(words[::-1])


def sort_table(
    table: Table,
    sort_keys: Sequence[Union[SortKey, str, int]],
    payload: Optional[Table] = None,
    row_valid: Optional[jax.Array] = None,
) -> Table:
    """ORDER BY: returns the table (or ``payload``) reordered.

    Every 1-D buffer (fixed-width data, validity, lengths) rides the
    ONE variadic stable ``lax.sort`` as a non-key operand — on TPU this
    is far cheaper than argsort + per-column random gathers (measured:
    the gather formulation ran a 100M-row 2-column sort at 5.7s; random
    gathers dominate). Matrix-shaped buffers (strings, DECIMAL128,
    LIST), whose shape can't join the variadic sort, gather through the
    permutation that rides along as an iota operand.

    ``row_valid`` (shape-bucket occupancy) adds one leading key word so
    padding rows land AFTER every real row; real rows keep the exact
    order of the unpadded sort (stability included)."""
    words = _table_key_words(table, sort_keys)
    if row_valid is not None:
        words = [_occupancy_word(row_valid)] + words
    target = payload if payload is not None else table
    n = target.row_count
    iota = jnp.arange(n, dtype=jnp.int32)
    operands: list[jax.Array] = list(words) + [iota]
    plan: list[tuple[int, str]] = []
    for ci, c in enumerate(target.columns):
        if c.data.ndim == 1:
            plan.append((ci, "data"))
            operands.append(c.data)
        if c.validity is not None:
            plan.append((ci, "validity"))
            operands.append(c.validity)
        if c.lengths is not None:
            plan.append((ci, "lengths"))
            operands.append(c.lengths)
    out = jax.lax.sort(
        tuple(operands), num_keys=len(words), is_stable=True
    )
    perm = out[len(words)]
    sorted_extras = out[len(words) + 1 :]
    by_col: dict = {}
    for (ci, attr), arr in zip(plan, sorted_extras):
        by_col.setdefault(ci, {})[attr] = arr
    cols = []
    for ci, c in enumerate(target.columns):
        got = by_col.get(ci, {})
        data = got.get("data")
        if data is None:  # matrix layout: one gather through the perm
            data = c.data[perm]
        cols.append(
            Column(
                data,
                c.dtype,
                got.get("validity") if c.validity is not None else None,
                got.get("lengths") if c.lengths is not None else None,
            )
        )
    return Table(cols, target.names)


def is_sorted(
    table: Table, sort_keys: Sequence[Union[SortKey, str, int]]
) -> jax.Array:
    """Device bool: rows already ordered by ``sort_keys`` (cudf
    ``is_sorted``). Nulls follow each key's resolved placement."""
    words = _table_key_words(table, sort_keys)
    n = words[0].shape[0]
    if n <= 1:
        return jnp.asarray(True)
    # adjacent-pair lexicographic compare: prev <= next
    eq = jnp.ones((n - 1,), dtype=jnp.bool_)
    ok = jnp.zeros((n - 1,), dtype=jnp.bool_)
    for w in words:
        a, b = w[:-1], w[1:]
        ok = ok | (eq & (a < b))
        eq = eq & (a == b)
    return jnp.all(ok | eq)


def merge_sorted(
    tables: Sequence[Table],
    sort_keys: Sequence[Union[SortKey, str, int]],
) -> Table:
    """K-way merge of individually sorted tables into one sorted table
    (cudf ``cudf::merge`` / Java ``Table.merge``).

    TPU-first design note: a streaming k-way merge is data-dependent
    control flow per output row — hostile to XLA. Concatenate + one
    stable lexsort over normalized u64 key words runs entirely on the
    MXU-adjacent sort network at HBM bandwidth and is how the op lowers
    here; stability preserves the order of equal keys across inputs in
    table order (matching cudf's stable merge)."""
    from .copying import concatenate

    if not tables:
        raise ValueError("merge_sorted: need at least one table")
    whole = concatenate(tables)
    return sort_table(whole, sort_keys)
