"""Spark-compatible Murmur3 hashing (vectorized uint32 arithmetic).

Bit-for-bit the algorithm of Spark's ``Murmur3Hash`` expression /
``Murmur3_x86_32.hashInt/hashLong/hashUnsafeBytes`` with seed 42 — the
hash the RAPIDS Accelerator uses for ``HashPartitioning``, so partition
placement matches a CPU/GPU Spark cluster exactly:

* int-family (incl. bool, dates) widen to int32 and use hashInt,
* longs/timestamps/decimal64 use hashLong, decimal32 hashes its unscaled
  int via hashLong like Spark's Decimal (precision<=18) path,
* float/double hash their IEEE bits (with -0.0 normalized to 0.0),
* strings hash 4-byte little-endian blocks then each trailing byte
  sign-extended individually (Spark's nonstandard tail),
* null values leave the running hash unchanged,
* multi-column hashing chains: h = hash(col_i, seed=h).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column, Table
from . import compute

_C1 = np.uint32(0xCC9E2D51)  # numpy scalar: no backend init at import
_C2 = np.uint32(0x1B873593)
_M5 = np.uint32(0xE6546B64)

DEFAULT_SEED = 42


def _rotl(x, r):
    return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))


def _mix_k1(k1):
    k1 = k1 * _C1
    k1 = _rotl(k1, 15)
    return k1 * _C2


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13)
    return h1 * np.uint32(5) + _M5


def _fmix(h1, length):
    h1 = h1 ^ jnp.uint32(length)
    h1 = h1 ^ (h1 >> jnp.uint32(16))
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> jnp.uint32(13))
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> jnp.uint32(16))


def _hash_int(v_i32: jax.Array, seed: jax.Array) -> jax.Array:
    return _fmix(_mix_h1(seed, _mix_k1(v_i32.astype(jnp.uint32))), 4)


def _hash_long(v_u64: jax.Array, seed: jax.Array) -> jax.Array:
    low = v_u64.astype(jnp.uint32)
    high = (v_u64 >> jnp.uint64(32)).astype(jnp.uint32)
    h1 = _mix_h1(seed, _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _fmix(h1, 8)


def _hash_string(col: Column, seed: jax.Array) -> jax.Array:
    mat = col.data  # (n, pad) uint8
    lens = col.lengths.astype(jnp.int32)
    n, pad = mat.shape
    h1 = seed
    # 4-byte little-endian blocks, processed while fully inside the length
    for w in range(pad // 4 + (1 if pad % 4 else 0)):
        word = jnp.zeros((n,), dtype=jnp.uint32)
        for b in range(4):
            i = w * 4 + b
            byte = (
                mat[:, i].astype(jnp.uint32)
                if i < pad
                else jnp.zeros((n,), jnp.uint32)
            )
            word = word | (byte << jnp.uint32(8 * b))
        in_block = lens >= (w + 1) * 4
        h1 = jnp.where(in_block, _mix_h1(h1, _mix_k1(word)), h1)
    # Spark's tail: each remaining byte sign-extended to int, full mix each
    for i in range(pad):
        is_tail = (i >= (lens // 4) * 4) & (i < lens)
        byte_signed = mat[:, i].astype(jnp.int8).astype(jnp.int32)
        h1 = jnp.where(
            is_tail, _mix_h1(h1, _mix_k1(byte_signed.astype(jnp.uint32))), h1
        )
    return _fmix(h1, lens.astype(jnp.uint32))


def _column_hash(col: Column, seed: jax.Array) -> jax.Array:
    """Running hash update for one column (nulls leave seed unchanged)."""
    d = col.dtype
    if d.is_string:
        h = _hash_string(col, seed)
    elif d.id in (
        dt.TypeId.INT8,
        dt.TypeId.INT16,
        dt.TypeId.INT32,
        dt.TypeId.UINT8,
        dt.TypeId.UINT16,
        dt.TypeId.UINT32,
        dt.TypeId.TIMESTAMP_DAYS,
        dt.TypeId.DURATION_DAYS,
        dt.TypeId.DICTIONARY32,
    ):
        h = _hash_int(col.data.astype(jnp.int32), seed)
    elif d.is_boolean:
        h = _hash_int(col.data.astype(jnp.int32), seed)
    elif d.id == dt.TypeId.FLOAT32:
        bits = jax.lax.bitcast_convert_type(
            jnp.where(col.data == 0, jnp.float32(0), col.data), jnp.uint32
        )
        h = _hash_int(bits, seed)
    elif d.id == dt.TypeId.FLOAT64:
        # storage is already IEEE bits; normalize -0.0 like Spark
        neg_zero = jnp.uint64(0x8000000000000000)
        bits = jnp.where(col.data == neg_zero, jnp.uint64(0), col.data)
        h = _hash_long(bits, seed)
    else:
        # int64-family: longs, 64-bit timestamps/durations, decimals
        # (Spark hashes the unscaled long for precision <= 18)
        h = _hash_long(col.data.astype(jnp.int64).astype(jnp.uint64), seed)
    if col.validity is not None:
        h = jnp.where(col.validity, h, seed)
    return h


def murmur3_column(col: Column, seed: int = DEFAULT_SEED) -> Column:
    """Per-row Spark murmur3 of one column -> INT32 column (never null)."""
    seed_v = jnp.full(col.data.shape[:1], seed, dtype=jnp.uint32)
    return Column(_column_hash(col, seed_v).astype(jnp.int32), dt.INT32, None)


def murmur3_table(
    table: Table, columns=None, seed: int = DEFAULT_SEED
) -> Column:
    """Spark multi-column hash: h chains through columns left to right.

    On a real TPU this dispatches to the fused Pallas kernel
    (kernels/hashing.py — one VMEM pass over all key columns, measured
    ~4.4x the fused-XLA chain on v5e); elsewhere, and for string keys,
    it runs the XLA path below. Both are bit-identical.
    """
    from .. import kernels
    from ..kernels import hashing as khash

    cols = (
        [table.column(c) for c in columns]
        if columns is not None
        else list(table.columns)
    )
    if cols and kernels.on_tpu() and khash.supports(cols):
        return khash.murmur3_table_fused(table, columns, seed)
    h = jnp.full((table.row_count,), seed, dtype=jnp.uint32)
    for c in cols:
        h = _column_hash(c, h)
    return Column(h.astype(jnp.int32), dt.INT32, None)
