"""Packed-key ORDER BY — the narrow-key fast path for sort_table.

The payload sort (ops/sort.py sort_table) carries key order words + an
iota + every 1-D buffer through one variadic stable sort. With a single
integer-family no-null key whose span fits ``64 - log2(n)`` bits (date
keys, dictionary codes, ids), the key word, the iota AND the key
column's own payload all collapse into one u64::

    packed = (rel_key << bits) | row_iota      # rel = kw-kmin (asc)
                                               #       kmax-kw (desc)

so a 2-column ORDER BY moves 16 B/row of sort operands instead of 24 —
and the sorted key column is RECONSTRUCTED from the word's high bits
(the order-key transform inverts exactly for the integer family),
while the permutation for matrix-shaped buffers (strings, DECIMAL128)
is the word's low bits. Stability is structural: embedded iotas make
ties impossible, so ``is_stable`` costs nothing.

Descending rides the same machinery with ``rel = kmax - kw`` (an exact
order-reversing shift within the same span), not a second code path.

Eligibility is eager (one min/max); ineligible shapes return ``None``
and callers fall back to :func:`ops.sort.sort_table` — this is an A/B
arm, not a routing change.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ..column import Column, Table
from .groupby_packed import _key_supported, _unkey
from .keys import column_order_keys
from .sort import SortKey


@functools.lru_cache(maxsize=64)
def _packed_sort_fn(bits: int, ascending: bool, key_ci: int):
    mask = jnp.uint64((1 << bits) - 1)

    def fn(table: Table, kbase):
        kcol = table.columns[key_ci]
        kw = column_order_keys(kcol)[0]
        rel = (kw - kbase) if ascending else (kbase - kw)
        n = kw.shape[0]
        iota = jnp.arange(n, dtype=jnp.uint64)
        packed = (rel << jnp.uint64(bits)) | iota

        operands: list[jax.Array] = [packed]
        plan: list[tuple[int, str]] = []
        for ci, c in enumerate(table.columns):
            if c.data.ndim == 1 and ci != key_ci:
                plan.append((ci, "data"))
                operands.append(c.data)
            if c.validity is not None:
                plan.append((ci, "validity"))
                operands.append(c.validity)
            if c.lengths is not None:
                plan.append((ci, "lengths"))
                operands.append(c.lengths)
        out = jax.lax.sort(tuple(operands), num_keys=1)
        packed_s = out[0]
        perm = (packed_s & mask).astype(jnp.int32)
        rel_s = packed_s >> jnp.uint64(bits)
        kw_sorted = (kbase + rel_s) if ascending else (kbase - rel_s)

        by_col: dict = {}
        for (ci, attr), arr in zip(plan, out[1:]):
            by_col.setdefault(ci, {})[attr] = arr
        cols = []
        for ci, c in enumerate(table.columns):
            got = by_col.get(ci, {})
            if ci == key_ci:
                data = _unkey(kw_sorted, c.dtype)
            else:
                data = got.get("data")
                if data is None:  # matrix layout: gather through perm
                    data = c.data[perm]
            cols.append(
                Column(
                    data,
                    c.dtype,
                    got.get("validity") if c.validity is not None else None,
                    got.get("lengths") if c.lengths is not None else None,
                )
            )
        return Table(cols, table.names)

    return jax.jit(fn)


def sort_table_packed(
    table: Table,
    sort_keys: Sequence[Union[SortKey, str, int]],
) -> Optional[Table]:
    """Eager packed ORDER BY, or ``None`` when ineligible (multi-key,
    nulls, non-integer key, span too wide) — fall back to sort_table."""
    from .groupby_packed import _minmax

    if len(sort_keys) != 1:
        return None
    k = sort_keys[0]
    k = k if isinstance(k, SortKey) else SortKey(k)
    kcol = table.column(k.column)
    if not _key_supported(kcol):
        return None
    n = table.row_count
    if n == 0:
        return None
    key_ci = next(
        i for i, c in enumerate(table.columns) if c is kcol
    )
    bits = max(1, (n - 1).bit_length())
    kw = column_order_keys(kcol)[0]
    lo, hi = _minmax(kw)
    if hi - lo >= (1 << (64 - bits)) - 1:
        return None
    kbase = jnp.uint64(lo if k.ascending else hi)
    return _packed_sort_fn(bits, bool(k.ascending), key_ci)(table, kbase)
