"""Packed-key ORDER BY — the narrow-key fast path for sort_table.

The payload sort (ops/sort.py sort_table) carries key order words + an
iota + every 1-D buffer through one variadic stable sort. With
integer-family no-null keys whose combined spans fit ``64 - log2(n)``
bits (date keys, dictionary codes, ids — alone or composed), the key
words, the iota AND the key columns' own payloads all collapse into one
u64::

    packed = (rel_1 << b_2 | rel_2 | ...) << iota_bits  |  row_iota

where each field is ``kw_i - kmin_i`` for an ascending key and
``kmax_i - kw_i`` for a descending one — so MIXED directions
(``ORDER BY a ASC, b DESC``) ride the same machinery, each field's
direction folded into its own rel. A 2-column single-key ORDER BY moves
16 B/row of sort operands instead of 24; every sorted key column is
RECONSTRUCTED from its bit field (the order-key transform inverts
exactly for the integer family), and the permutation for matrix-shaped
buffers (strings, DECIMAL128) is the word's low bits. Stability is
structural: embedded iotas make ties impossible.

Eligibility is eager (one min/max per key); ineligible shapes return
``None`` and callers fall back to :func:`ops.sort.sort_table` — this is
an A/B arm, not a routing change.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ..column import Column, Table
from .groupby_packed import _key_supported, _unkey
from .keys import column_order_keys, fold_fields, peel_fields
from .keys import minmax_host as _minmax
from .sort import SortKey


@functools.lru_cache(maxsize=64)
def _packed_sort_fn(
    bits: int, directions: tuple, field_bits: tuple, key_cis: tuple,
    values_via: str = "sort",
):
    mask = jnp.uint64((1 << bits) - 1)

    def fn(table: Table, kbases):
        n = table.row_count
        rels = []
        for i, (ci, asc) in enumerate(zip(key_cis, directions)):
            kw = column_order_keys(table.columns[ci])[0]
            rels.append((kw - kbases[i]) if asc else (kbases[i] - kw))
        rel = fold_fields(rels, field_bits)
        iota = jnp.arange(n, dtype=jnp.uint64)
        packed = (rel << jnp.uint64(bits)) | iota

        operands: list[jax.Array] = [packed]
        plan: list[tuple[int, str]] = []
        key_set = set(key_cis)
        for ci, c in enumerate(table.columns):
            if c.data.ndim == 1 and ci not in key_set:
                plan.append((ci, "data"))
                operands.append(c.data)
            if c.validity is not None:
                plan.append((ci, "validity"))
                operands.append(c.validity)
            if c.lengths is not None:
                plan.append((ci, "lengths"))
                operands.append(c.lengths)
        if values_via == "sort":
            out = jax.lax.sort(tuple(operands), num_keys=1)
            packed_s = out[0]
            perm = (packed_s & mask).astype(jnp.int32)
            payload_s = list(out[1:])
        elif values_via == "gather":
            # word-only sort; every payload follows by one O(n)
            # gather through the embedded-iota permutation
            packed_s = jax.lax.sort((packed,), num_keys=1)[0]
            perm = (packed_s & mask).astype(jnp.int32)
            payload_s = [
                jnp.take(arr, perm, axis=0) for arr in operands[1:]
            ]
        else:
            raise ValueError(f"unknown values_via {values_via!r}")
        rel_s = packed_s >> jnp.uint64(bits)

        # peel the sorted key fields back off (last key in low bits)
        peeled = peel_fields(rel_s, field_bits)
        fields = {
            ci: (f, asc)
            for ci, asc, f in zip(key_cis, directions, peeled)
        }

        by_col: dict = {}
        for (ci, attr), arr in zip(plan, payload_s):
            by_col.setdefault(ci, {})[attr] = arr
        cols = []
        for ci, c in enumerate(table.columns):
            got = by_col.get(ci, {})
            if ci in fields:
                f, asc = fields[ci]
                i = key_cis.index(ci)
                kw_sorted = (kbases[i] + f) if asc else (kbases[i] - f)
                data = _unkey(kw_sorted, c.dtype)
            else:
                data = got.get("data")
                if data is None:  # matrix layout: gather through perm
                    data = c.data[perm]
            cols.append(
                Column(
                    data,
                    c.dtype,
                    got.get("validity") if c.validity is not None else None,
                    got.get("lengths") if c.lengths is not None else None,
                )
            )
        return Table(cols, table.names)

    return jax.jit(fn)


def sort_table_packed(
    table: Table,
    sort_keys: Sequence[Union[SortKey, str, int]],
    values_via: str = "sort",
) -> Optional[Table]:
    """Eager packed ORDER BY, or ``None`` when ineligible (nulls,
    non-integer keys, duplicate key columns, combined span too wide) —
    fall back to sort_table."""
    if not sort_keys:
        return None
    keys = [
        k if isinstance(k, SortKey) else SortKey(k) for k in sort_keys
    ]
    n = table.row_count
    if n == 0:
        return None
    key_cis = []
    for k in keys:
        kcol = table.column(k.column)
        if not _key_supported(kcol):
            return None
        ci = next(i for i, c in enumerate(table.columns) if c is kcol)
        key_cis.append(ci)
    if len(set(key_cis)) != len(key_cis):
        return None  # duplicate key column: field peeling is ambiguous
    bits = max(1, (n - 1).bit_length())
    kbases = []
    field_bits = []
    for k, ci in zip(keys, key_cis):
        kw = column_order_keys(table.columns[ci])[0]
        lo, hi = _minmax(kw)
        field_bits.append(max(1, (hi - lo).bit_length()))
        kbases.append(lo if k.ascending else hi)
    if sum(field_bits) + bits > 64:
        # no sentinel word: the full 64 bits are usable
        return None
    fn = _packed_sort_fn(
        bits,
        tuple(bool(k.ascending) for k in keys),
        tuple(field_bits),
        tuple(key_cis),
        values_via,
    )
    return fn(table, jnp.asarray(kbases, dtype=jnp.uint64))
