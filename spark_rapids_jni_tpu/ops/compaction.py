"""Stream compaction (cudf ``distinct`` / ``unique`` / ``distinct_count``).

Capability-surface row of SURVEY.md §2.3. Distinct is sort-based — the
canonical TPU formulation (SURVEY.md §7 hard part 1: no device-wide
hash-table atomics; sorting by the uniform u64 order keys replaces
cuco's insert-and-test): sort rows by key words, keep each run head.
Follows the library's two-phase shape discipline: ``distinct`` host-syncs
the count (cudf call model), ``distinct_capped`` stays jittable with a
caller capacity, ``distinct_count`` is a jittable scalar.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .. import dtype as dt
from ..column import Column, Table
from .filter import filter_table, filter_table_capped
from .keys import column_order_keys


def _first_of_run_mask(
    table: Table,
    keys: Optional[Sequence],
    row_valid: Optional[jax.Array] = None,
) -> Column:
    """BOOL8 mask keeping the first occurrence of each distinct key row
    (order-preserving: the kept row is the earliest original row).

    ``row_valid`` (shape-bucket occupancy, utils/buckets.py) excludes
    padding rows entirely: they join no real row's run (an extra
    occupancy word splits them off) and the mask is False for them."""
    cols = (
        [table.column(k) for k in keys] if keys is not None else list(table.columns)
    )
    # cudf distinct treats nulls as equal to each other: zero a null
    # row's data words (whatever bytes sit under a null must not split
    # the null group) and add a validity word to separate null from a
    # genuine zero key
    words: list[jax.Array] = []
    for c in cols:
        cwords = column_order_keys(c)
        if c.validity is not None:
            cwords = [jnp.where(c.validity, w, jnp.uint64(0)) for w in cwords]
            cwords.append(c.validity.astype(jnp.uint64))
        words.extend(cwords)
    if row_valid is not None:
        words.append(row_valid.astype(jnp.uint64))
    n = table.row_count
    perm = jnp.lexsort(tuple(reversed([*words, jnp.arange(n, dtype=jnp.uint64)])))
    sorted_words = [w[perm] for w in words]
    neq_prev = jnp.zeros((n,), dtype=jnp.bool_)
    for w in sorted_words:
        neq_prev = jnp.logical_or(
            neq_prev, jnp.concatenate([jnp.ones((1,), jnp.bool_), w[1:] != w[:-1]])
        )
    # head of each run in sorted order; stable tiebreaker (arange above)
    # makes the head the smallest original index
    keep_sorted = neq_prev
    keep = jnp.zeros((n,), dtype=jnp.bool_).at[perm].set(keep_sorted)
    if row_valid is not None:
        keep = jnp.logical_and(keep, row_valid)
    return Column(keep, dt.BOOL8, None)


def distinct(table: Table, keys: Optional[Sequence] = None) -> Table:
    """First occurrence of every distinct key row (eager; host-syncs the
    result size, the cudf/JNI call model)."""
    return filter_table(table, _first_of_run_mask(table, keys))


def distinct_capped(
    table: Table,
    keys: Optional[Sequence] = None,
    capacity: Optional[int] = None,
    row_valid: Optional[jax.Array] = None,
) -> tuple[Table, jax.Array]:
    """Jittable distinct: padded result + device count. ``row_valid``
    excludes rows entirely (shape-bucket padding occupancy)."""
    cap = capacity if capacity is not None else table.row_count
    return filter_table_capped(
        table, _first_of_run_mask(table, keys, row_valid), cap
    )


def distinct_count(
    obj: Union[Table, Column], keys: Optional[Sequence] = None
) -> jax.Array:
    """Number of distinct rows/values (jittable scalar; cudf
    ``distinct_count``). Nulls count as one group, matching
    NULL_POLICY.INCLUDE."""
    table = obj if isinstance(obj, Table) else Table([obj], ["c"])
    mask = _first_of_run_mask(table, keys)
    return jnp.sum(mask.data).astype(jnp.int32)


def drop_nulls(
    table: Table,
    keys: Optional[Sequence[Union[int, str]]] = None,
    keep_threshold: Optional[int] = None,
) -> Table:
    """Rows where the key columns are non-null (cudf ``drop_nulls`` /
    Spark ``dropna``). By default every key column must be valid;
    ``keep_threshold`` keeps rows with at least that many valid key
    values (cudf's threshold semantics)."""
    from . import compute

    cols = (
        [table.column(k) for k in keys]
        if keys is not None
        else list(table.columns)
    )
    if keep_threshold is None:
        merged = compute.merge_validity(*cols)
        if merged is None:
            return table  # no key column carries nulls
        keep = merged
    else:
        n_valid = jnp.zeros((table.row_count,), jnp.int32)
        for c in cols:
            n_valid = n_valid + compute.valid_mask(c).astype(jnp.int32)
        keep = n_valid >= keep_threshold
    return filter_table(table, Column(keep, dt.BOOL8, None))
