"""Gather / take (cudf ``gather``): row selection by index, the workhorse
behind sort, join and filter materialization."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..column import Column, Table


def gather_column(
    col: Column,
    indices: jax.Array,
    index_valid: Optional[jax.Array] = None,
) -> Column:
    """col[indices]; rows where ``index_valid`` is False become null
    (the out-of-bounds-policy=NULLIFY mode of cudf gather, which is how
    left joins materialize their non-matching rows)."""
    data = jnp.take(col.data, indices, axis=0, mode="clip")
    lengths = (
        None
        if col.lengths is None
        else jnp.take(col.lengths, indices, mode="clip")
    )
    valid = None
    if col.validity is not None:
        valid = jnp.take(col.validity, indices, mode="clip")
    if index_valid is not None:
        valid = index_valid if valid is None else jnp.logical_and(valid, index_valid)
    return Column(data, col.dtype, valid, lengths)


def gather_table(
    table: Table,
    indices: jax.Array,
    index_valid: Optional[jax.Array] = None,
) -> Table:
    return Table(
        [gather_column(c, indices, index_valid) for c in table.columns],
        table.names,
    )
