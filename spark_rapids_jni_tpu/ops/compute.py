"""Compute-representation helpers: storage buffers <-> arithmetic values.

The storage layer keeps FLOAT64 as uint64 bit patterns (DType.storage_dtype).
Ops call ``values()`` to get an arithmetic view (decode on TPU, bitcast on
CPU) and ``from_values()`` to build result columns, re-encoding doubles.
Everything here is jit-traceable.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column
from ..utils import ieee754


def values(col: Column) -> jax.Array:
    """The arithmetic view of a column's data (FLOAT64 bits -> f64)."""
    if col.dtype.id == dt.TypeId.FLOAT64:
        return ieee754.bits_to_float(col.data)
    return col.data


def encode_values(vals: jax.Array, dtype: dt.DType) -> jax.Array:
    """Arithmetic values -> storage buffer for ``dtype``."""
    if dtype.id == dt.TypeId.FLOAT64:
        return ieee754.float_to_bits(vals.astype(jnp.float64))
    return vals.astype(dtype.storage_dtype)


def from_values(
    vals: jax.Array, dtype: dt.DType, validity: Optional[jax.Array]
) -> Column:
    return Column(encode_values(vals, dtype), dtype, validity)


def valid_mask(col: Column) -> jax.Array:
    """(n,) bool validity, materialized (all-True when validity is None)."""
    if col.validity is None:
        return jnp.ones(col.data.shape[:1], dtype=jnp.bool_)
    return col.validity


def merge_validity(*cols: Column) -> Optional[jax.Array]:
    """AND of the validities present (null-propagation); None if all absent."""
    masks = [c.validity for c in cols if c.validity is not None]
    if not masks:
        return None
    out = masks[0]
    for m in masks[1:]:
        out = jnp.logical_and(out, m)
    return out
