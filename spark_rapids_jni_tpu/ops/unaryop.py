"""Null-aware unary operators (cudf ``unary_op`` family + null predicates)."""

from __future__ import annotations

import jax.numpy as jnp

from .. import dtype as dt
from ..column import Column
from . import compute

_FLOAT_ONLY = {
    "sqrt",
    "cbrt",
    "exp",
    "log",
    "sin",
    "cos",
    "tan",
    "arcsin",
    "arccos",
    "arctan",
    "sinh",
    "cosh",
    "tanh",
    "rint",
}

_FNS = {
    "abs": jnp.abs,
    "neg": lambda v: -v,
    "sqrt": jnp.sqrt,
    "cbrt": jnp.cbrt,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "rint": jnp.rint,
    "bitnot": lambda v: ~v,
    "not": jnp.logical_not,
}


def unary_op(op: str, col: Column) -> Column:
    if op == "not":
        if not col.dtype.is_boolean:
            raise TypeError("'not' requires BOOL8")
        return Column(jnp.logical_not(col.data), dt.BOOL8, col.validity)
    try:
        fn = _FNS[op]
    except KeyError:
        raise ValueError(f"unknown unary op {op!r}") from None

    vals = compute.values(col)
    out_dtype = col.dtype
    if op in _FLOAT_ONLY:
        if not col.dtype.is_floating:
            vals = vals.astype(jnp.float64)
            out_dtype = dt.FLOAT64
    if op in ("floor", "ceil", "rint") and not col.dtype.is_floating:
        return Column(col.data, col.dtype, col.validity)  # integral: no-op
    if op in ("abs", "neg") and col.dtype.is_decimal:
        return compute.from_values(fn(vals), col.dtype, col.validity)
    return compute.from_values(fn(vals), out_dtype, col.validity)


def is_null(col: Column) -> Column:
    """Spark ``IS NULL`` — never itself null."""
    if col.validity is None:
        return Column(jnp.zeros(len(col), dtype=jnp.bool_), dt.BOOL8, None)
    return Column(jnp.logical_not(col.validity), dt.BOOL8, None)


def is_not_null(col: Column) -> Column:
    if col.validity is None:
        return Column(jnp.ones(len(col), dtype=jnp.bool_), dt.BOOL8, None)
    return Column(col.validity, dt.BOOL8, None)


def is_nan(col: Column) -> Column:
    if not col.dtype.is_floating:
        raise TypeError("is_nan requires a float column")
    return Column(jnp.isnan(compute.values(col)), dt.BOOL8, col.validity)
