"""Null-aware binary operators (the cudf ``binaryop`` family).

Semantics follow Spark SQL's non-ANSI mode, which is what the RAPIDS
Accelerator implements on GPU:
* any null operand -> null result (plus ``null_safe_eq``, Spark's <=>),
* integer/decimal division or modulo by zero -> null,
* float division by zero -> IEEE inf/NaN,
* decimal add/sub rescale to the finer scale; decimal mul adds scales;
  decimal div rescales the dividend first (cudf's fixed-point behavior).

Everything is jit-traceable; FLOAT64 goes through the compute view
(ops/compute.py) so storage stays bit-exact.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column
from . import compute

_CMP_OPS = {"eq", "ne", "lt", "le", "gt", "ge", "null_safe_eq"}
_LOGICAL_OPS = {"and", "or"}
_ARITH_OPS = {
    "add",
    "sub",
    "mul",
    "div",
    "true_div",
    "floor_div",
    "mod",
    "pmod",
    "pow",
    "bitand",
    "bitor",
    "bitxor",
    "shiftleft",
    "shiftright",
    "shiftright_unsigned",
}


def _promote(a: Column, b: Column) -> dt.DType:
    if a.dtype.is_decimal or b.dtype.is_decimal:
        da, db = a.dtype, b.dtype
        # Spark promotes an integer operand to decimal(scale 0), so
        # qty * price works without an explicit cast; floats still
        # require one (the result type would silently stop being exact)
        if not da.is_decimal:
            if not da.is_integer:
                raise TypeError(
                    "decimal/float binary ops require explicit cast"
                )
            da = dt.DType(
                dt.TypeId.DECIMAL64 if da.itemsize >= 8 else dt.TypeId.DECIMAL32
            )
        if not db.is_decimal:
            if not db.is_integer:
                raise TypeError(
                    "decimal/float binary ops require explicit cast"
                )
            db = dt.DType(
                dt.TypeId.DECIMAL64 if db.itemsize >= 8 else dt.TypeId.DECIMAL32
            )
        wid = max(da.itemsize, db.itemsize)
        scale = min(da.scale, db.scale)
        return dt.DType(
            dt.TypeId.DECIMAL64 if wid >= 8 else dt.TypeId.DECIMAL32, scale
        )
    return dt.common_numeric_dtype(a.dtype, b.dtype)


def _rescale_decimal(vals: jax.Array, from_scale: int, to_scale: int) -> jax.Array:
    if from_scale == to_scale:
        return vals
    if to_scale < from_scale:
        return vals * (10 ** (from_scale - to_scale))
    # narrowing truncates toward zero (cudf fixed_point / int128.rescale
    # convention; // would floor negatives: -3.75 at scale -1 is -3.7)
    return jax.lax.div(vals, jnp.asarray(10 ** (to_scale - from_scale),
                                         vals.dtype))


def binary_op(op: str, a: Column, b: Column) -> Column:
    """Elementwise ``a <op> b`` with Spark null semantics."""
    if a.dtype.is_string or b.dtype.is_string:
        from . import strings

        return strings.binary_op(op, a, b)

    if (
        a.dtype.id == dt.TypeId.DECIMAL128
        or b.dtype.id == dt.TypeId.DECIMAL128
    ):
        return _binary_op_decimal128(op, a, b)

    valid = compute.merge_validity(a, b)

    if op in _LOGICAL_OPS:
        return _logical(op, a, b)

    av, bv = compute.values(a), compute.values(b)

    if op in _CMP_OPS:
        if a.dtype.is_decimal or b.dtype.is_decimal:
            scale = min(a.dtype.scale, b.dtype.scale)
            av = _rescale_decimal(av.astype(jnp.int64), a.dtype.scale, scale)
            bv = _rescale_decimal(bv.astype(jnp.int64), b.dtype.scale, scale)
        out = {
            "eq": lambda: av == bv,
            "ne": lambda: av != bv,
            "lt": lambda: av < bv,
            "le": lambda: av <= bv,
            "gt": lambda: av > bv,
            "ge": lambda: av >= bv,
            "null_safe_eq": lambda: av == bv,
        }[op]()
        if op == "null_safe_eq":
            # Spark's <=>: null <=> null is True, null <=> x is False.
            va, vb = compute.valid_mask(a), compute.valid_mask(b)
            out = jnp.where(
                va & vb, out, jnp.logical_and(~va, ~vb)
            )
            return Column(out, dt.BOOL8, None)
        return Column(out, dt.BOOL8, valid)

    if op not in _ARITH_OPS:
        raise ValueError(f"unknown binary op {op!r}")

    out_dtype = _promote(a, b)

    if out_dtype.is_decimal:
        av = _rescale_decimal(av.astype(jnp.int64), a.dtype.scale, out_dtype.scale)
        bv = _rescale_decimal(bv.astype(jnp.int64), b.dtype.scale, out_dtype.scale)
        if op == "add":
            res = av + bv
        elif op == "sub":
            res = av - bv
        elif op == "mul":
            # product of unscaled values carries scale(a)+scale(b); bring it
            # back to the output scale (cudf fixed_point multiply).
            res = _rescale_decimal(
                compute.values(a).astype(jnp.int64)
                * compute.values(b).astype(jnp.int64),
                a.dtype.scale + b.dtype.scale,
                out_dtype.scale,
            )
        elif op in ("div", "true_div"):
            # quotient AT THE OUTPUT SCALE: rescale the dividend by
            # 10^(scale_a - scale_b - scale_out) before the truncated
            # divide (review catch: dividing two same-scale unscaled
            # values yields a scale-0 quotient, which was mislabeled
            # as scale_out — 7.50/2.00 read as 0.03). Truncation is
            # toward zero (cudf fixed_point / Java), via lax.div.
            e = a.dtype.scale - b.dtype.scale - out_dtype.scale
            av_raw = compute.values(a).astype(jnp.int64)
            bv_raw = compute.values(b).astype(jnp.int64)
            num = av_raw * (10 ** e) if e >= 0 else av_raw
            den = bv_raw if e >= 0 else bv_raw * (10 ** (-e))
            zero = bv_raw == 0
            res = jax.lax.div(num, jnp.where(zero, 1, den))
            valid = (
                ~zero if valid is None else jnp.logical_and(valid, ~zero)
            )
        else:
            raise TypeError(f"decimal op {op!r} not supported")
        return compute.from_values(res, out_dtype, valid)

    want = np.dtype(out_dtype.device_dtype)
    av = av.astype(want)
    bv = bv.astype(want)
    is_float = out_dtype.is_floating

    if op == "add":
        res = av + bv
    elif op == "sub":
        res = av - bv
    elif op == "mul":
        res = av * bv
    elif op in ("div", "true_div"):
        if is_float:
            res = av / bv  # IEEE inf/NaN on zero divide
        else:
            # Spark IntegralDivide / Java: truncation toward zero, the
            # same convention as mod (lax.rem) so a == b*div + mod
            # holds for mixed signs; jnp's // floors (-7 div 2 must be
            # -3, not -4) — caught by the binaryop fuzz
            zero = bv == 0
            res = jnp.where(
                zero, 0, jax.lax.div(av, jnp.where(zero, 1, bv))
            )
            valid = ~zero if valid is None else jnp.logical_and(valid, ~zero)
    elif op == "floor_div":
        if is_float:
            res = jnp.floor(av / bv)
        else:
            zero = bv == 0
            res = jnp.where(zero, 0, av // jnp.where(zero, 1, bv))
            valid = ~zero if valid is None else jnp.logical_and(valid, ~zero)
    elif op == "mod":
        # Spark % / cudf MOD: C/Java-style — result carries the
        # DIVIDEND's sign (jnp.mod is Python-style and would differ for
        # mixed signs: -7 % 3 is -1 in Spark, 2 in Python)
        if is_float:
            res = jnp.fmod(av, bv)
        else:
            zero = bv == 0
            res = jnp.where(
                zero, 0, jax.lax.rem(av, jnp.where(zero, 1, bv))
            )
            valid = ~zero if valid is None else jnp.logical_and(valid, ~zero)
    elif op == "pmod":
        # Spark Pmod: r = a % n (Java %); negative remainders are
        # corrected to (r + n) % n, non-negative ones returned as-is
        # (so pmod(7, -3) = 1, pmod(-7, 3) = 2, pmod(-7, -3) = -1)
        if is_float:
            m = jnp.fmod(av, bv)
            res = jnp.where(m < 0, jnp.fmod(m + bv, bv), m)
        else:
            zero = bv == 0
            safe = jnp.where(zero, 1, bv)
            m = jax.lax.rem(av, safe)
            res = jnp.where(
                zero, 0,
                jnp.where(m < 0, jax.lax.rem(m + safe, safe), m),
            )
            valid = ~zero if valid is None else jnp.logical_and(valid, ~zero)
    elif op == "pow":
        res = jnp.power(av, bv)
    elif op == "bitand":
        res = av & bv
    elif op == "bitor":
        res = av | bv
    elif op == "bitxor":
        res = av ^ bv
    elif op in ("shiftleft", "shiftright", "shiftright_unsigned"):
        # Java/Spark shift semantics: the amount is masked to
        # (bit width - 1), so x << 64 == x for int64 (XLA's behavior
        # for amounts >= width is implementation-defined)
        width = np.dtype(str(av.dtype)).itemsize * 8
        shift = (bv & (width - 1)).astype(av.dtype)
        if op == "shiftleft":
            res = av << shift
        elif op == "shiftright":
            res = av >> shift
        else:
            # logical shift: reinterpret at the SAME width as unsigned
            # so the vacated high bits fill with zeros for any int width
            kind = np.dtype(str(av.dtype))
            if kind.kind == "i":
                u = np.dtype(f"uint{width}")
                shifted = (
                    jax.lax.bitcast_convert_type(av, u) >> shift.astype(u)
                )
                res = jax.lax.bitcast_convert_type(shifted, kind)
            else:
                res = av >> shift
    else:  # pragma: no cover
        raise AssertionError(op)

    return compute.from_values(res, out_dtype, valid)


def _logical(op: str, a: Column, b: Column) -> Column:
    """Spark three-valued logic for AND/OR."""
    if not (a.dtype.is_boolean and b.dtype.is_boolean):
        raise TypeError("logical ops require BOOL8 columns")
    av, bv = a.data, b.data
    va, vb = compute.valid_mask(a), compute.valid_mask(b)
    ta = av & va  # definitely true
    tb = bv & vb
    fa = (~av) & va  # definitely false
    fb = (~bv) & vb
    if op == "and":
        out = ta & tb
        known = (fa | fb) | (va & vb)  # false wins over null
    else:
        out = ta | tb
        known = (ta | tb) | (va & vb)  # true wins over null
    return Column(out, dt.BOOL8, None if (a.validity is None and b.validity is None) else known)


# Convenience wrappers
def add(a, b):
    return binary_op("add", a, b)


def sub(a, b):
    return binary_op("sub", a, b)


def mul(a, b):
    return binary_op("mul", a, b)


def div(a, b):
    return binary_op("div", a, b)


def eq(a, b):
    return binary_op("eq", a, b)


def ne(a, b):
    return binary_op("ne", a, b)


def lt(a, b):
    return binary_op("lt", a, b)


def le(a, b):
    return binary_op("le", a, b)


def gt(a, b):
    return binary_op("gt", a, b)


def ge(a, b):
    return binary_op("ge", a, b)


def _limbs_at_scale(col: Column, to_scale: int):
    """A column's values as (lo, hi) u64 limbs rescaled to ``to_scale``.
    Rescaling to the smaller (more negative) scale multiplies, so the
    common-scale alignment below is exact."""
    from . import int128

    if col.dtype.id == dt.TypeId.DECIMAL128:
        lo, hi = col.data[:, 0], col.data[:, 1]
        return int128.rescale(lo, hi, col.dtype.scale, to_scale)
    if col.dtype.is_decimal or col.dtype.is_integer:
        lo, hi = int128.from_signed_int(col.data)
        return int128.rescale(lo, hi, col.dtype.scale, to_scale)
    raise TypeError(
        f"decimal128 binary ops require decimal/integer operands, "
        f"got {col.dtype}"
    )


def _binary_op_decimal128(op: str, a: Column, b: Column) -> Column:
    """DECIMAL128 arithmetic/comparisons over two-u64-limb vectors
    (ops/int128.py). add/sub/neg-style ops and every comparison; mul/div
    between two 128-bit operands is not yet supported (raise, never
    silently truncate)."""
    import jax.numpy as jnp

    from . import int128

    valid = compute.merge_validity(a, b)
    scale = min(
        a.dtype.scale if a.dtype.is_decimal else 0,
        b.dtype.scale if b.dtype.is_decimal else 0,
    )
    al, ah = _limbs_at_scale(a, scale)
    bl, bh = _limbs_at_scale(b, scale)

    if op in _CMP_OPS:
        is_eq = int128.eq(al, ah, bl, bh)
        is_lt = int128.lt_signed(al, ah, bl, bh)
        out = {
            "eq": lambda: is_eq,
            "ne": lambda: ~is_eq,
            "lt": lambda: is_lt,
            "le": lambda: is_lt | is_eq,
            "gt": lambda: ~(is_lt | is_eq),
            "ge": lambda: ~is_lt,
            "null_safe_eq": lambda: is_eq,
        }[op]()
        if op == "null_safe_eq":
            va, vb = compute.valid_mask(a), compute.valid_mask(b)
            out = jnp.where(va & vb, out, jnp.logical_and(~va, ~vb))
            return Column(out, dt.BOOL8, None)
        return Column(out, dt.BOOL8, valid)

    if op == "add":
        lo, hi = int128.add(al, ah, bl, bh)
    elif op == "sub":
        lo, hi = int128.sub(al, ah, bl, bh)
    else:
        raise TypeError(f"decimal128 op {op!r} not supported")
    data = jnp.stack([lo, hi], axis=1)
    return Column(data, dt.DType(dt.TypeId.DECIMAL128, scale), valid)
