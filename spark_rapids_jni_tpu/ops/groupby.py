"""Group-by aggregation (cudf ``groupby``), sort-based.

TPU has no device-wide atomic hash-table idiom (SURVEY.md §7 hard part 1),
so aggregation is sort-based: normalize keys (ops/keys.py) -> stable
lexsort -> segment boundaries -> XLA segment reductions (which lower to
sorted scatter-adds, efficient on TPU). Null keys form their own group,
like Spark/cudf.

Two forms (see ops/__init__ docstring): ``groupby_aggregate`` host-syncs
the group count; ``groupby_aggregate_capped`` is fully jittable with
``num_segments`` as the static capacity.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column, Table
from . import compute
from . import keys as keys_mod
from .gather import gather_table

_AGG_OPS = {"sum", "count", "min", "max", "mean", "variance", "std"}


@dataclasses.dataclass(frozen=True)
class GroupbyAgg:
    """One aggregation: (value column, op, output name)."""

    column: Union[int, str]
    op: str
    name: Optional[str] = None

    def __post_init__(self):
        if self.op not in _AGG_OPS:
            raise ValueError(f"unknown aggregation {self.op!r}")


def _segment_ids(
    key_cols: Sequence[Column], row_valid: Optional[jax.Array] = None
):
    """(perm, seg_ids, num_groups_device): stable sort + boundary scan.

    ``row_valid`` excludes rows entirely (shuffle-padding occupancy): the
    leading occupancy word sorts them behind every real row, where their
    garbage keys may split into any number of trailing segments; the group
    count is therefore the highest segment id holding a valid row.
    """
    words: list[jax.Array] = []
    if row_valid is not None:
        # invalid rows last: word 0 for valid, 1 for padding
        words.append(jnp.where(row_valid, jnp.uint64(0), jnp.uint64(1)))
    for c in key_cols:
        if c.validity is not None:
            # null key rows group together: validity is a key word and null
            # payloads must not split the group
            words.append(c.validity.astype(jnp.uint64))
            words.extend(
                jnp.where(c.validity, w, jnp.uint64(0))
                for w in keys_mod.column_order_keys(c)
            )
        else:
            words.extend(keys_mod.column_order_keys(c))
    perm = jnp.lexsort(words[::-1])
    sorted_words = [w[perm] for w in words]
    boundary = jnp.zeros(perm.shape, dtype=jnp.bool_).at[0].set(True)
    for w in sorted_words:
        boundary = boundary | jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), w[1:] != w[:-1]]
        )
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    if row_valid is not None:
        # Padding rows sort behind every real row (leading occupancy word)
        # but can form any number of trailing garbage segments — the real
        # group count is the highest segment id holding a valid row.
        num_groups = jnp.max(
            jnp.where(row_valid[perm], seg + 1, 0)
        )
    else:
        num_groups = seg[-1] + 1
    return perm, seg, num_groups


def _aggregate_segment(
    col: Column,
    op: str,
    perm,
    seg,
    num_segments: int,
    row_valid: Optional[jax.Array] = None,
) -> Column:
    vals = compute.values(col)[perm]
    valid = compute.valid_mask(col)[perm]
    if row_valid is not None:
        valid = jnp.logical_and(valid, row_valid[perm])
    n_valid = jax.ops.segment_sum(
        valid.astype(jnp.int64), seg, num_segments=num_segments
    )
    has = n_valid > 0

    if op == "count":
        return Column(n_valid, dt.INT64, None)

    if op in ("sum", "mean"):
        acc_dtype = jnp.float64 if col.dtype.is_floating else jnp.int64
        total = jax.ops.segment_sum(
            jnp.where(valid, vals, 0).astype(acc_dtype),
            seg,
            num_segments=num_segments,
        )
        if op == "mean":
            mean = total.astype(jnp.float64) / jnp.maximum(n_valid, 1)
            if col.dtype.is_decimal:
                mean = mean * (10.0 ** col.dtype.scale)
            return compute.from_values(mean, dt.FLOAT64, has)
        if col.dtype.is_floating:
            return compute.from_values(total, dt.FLOAT64, has)
        if col.dtype.is_decimal:
            return compute.from_values(
                total, dt.DType(dt.TypeId.DECIMAL64, col.dtype.scale), has
            )
        return compute.from_values(total, dt.INT64, has)

    if op in ("variance", "std"):
        # two-pass: segment mean, gather back to rows, segment-sum of
        # squared deviations (the mean-subtracting formula; the naive
        # E[x^2]-E[x]^2 shortcut catastrophically cancels for
        # large-magnitude values). Sample variance, ddof=1; groups with
        # fewer than 2 valid rows are null.
        fvals = vals.astype(jnp.float64)
        if col.dtype.is_decimal:
            fvals = fvals * (10.0 ** col.dtype.scale)
        nf = n_valid.astype(jnp.float64)
        s1 = jax.ops.segment_sum(
            jnp.where(valid, fvals, 0.0), seg, num_segments=num_segments
        )
        mean = s1 / jnp.maximum(nf, 1)
        dev = fvals - mean[seg]
        sq = jax.ops.segment_sum(
            jnp.where(valid, dev * dev, 0.0), seg, num_segments=num_segments
        )
        var = sq / jnp.maximum(nf - 1, 1)
        out = jnp.sqrt(var) if op == "std" else var
        return compute.from_values(out, dt.FLOAT64, n_valid > 1)

    # min / max via masked sentinels
    if col.dtype.is_floating:
        sentinel = np.inf if op == "min" else -np.inf
    elif col.dtype.is_boolean:
        sentinel = op == "min"
    else:
        info = np.iinfo(np.dtype(col.dtype.storage_dtype))
        sentinel = info.max if op == "min" else info.min
    masked = jnp.where(valid, vals, jnp.asarray(sentinel, vals.dtype))
    fn = jax.ops.segment_min if op == "min" else jax.ops.segment_max
    out = fn(masked, seg, num_segments=num_segments)
    return compute.from_values(out, col.dtype, has)


def groupby_aggregate_capped(
    table: Table,
    by: Sequence[Union[int, str]],
    aggs: Sequence[GroupbyAgg],
    num_segments: int,
    row_valid: Optional[jax.Array] = None,
) -> tuple[Table, jax.Array]:
    """Jittable groupby: (padded result of ``num_segments`` rows, count).

    Padding rows have null keys/values (validity False past the count).
    ``row_valid`` excludes rows (e.g. shuffle-padding occupancy).
    """
    key_cols = [table.column(c) for c in by]
    perm, seg, num_groups = _segment_ids(key_cols, row_valid)

    # representative (first) sorted row of each segment -> key values
    n = table.row_count
    first_pos = jax.ops.segment_min(
        jnp.arange(n, dtype=jnp.int32), seg, num_segments=num_segments
    )
    in_range = jnp.arange(num_segments, dtype=jnp.int32) < num_groups
    first_rows = perm[jnp.clip(first_pos, 0, n - 1)]

    out_cols: list[Column] = []
    out_names: list[str] = []
    for i, c in enumerate(by):
        col = table.column(c)
        k = gather_table(Table([col]), first_rows).columns[0]
        valid = jnp.logical_and(
            compute.valid_mask(k), in_range
        )
        out_cols.append(Column(k.data, k.dtype, valid, k.lengths))
        out_names.append(
            c if isinstance(c, str) else (table.names[c] if table.names else f"key{i}")
        )

    for agg in aggs:
        col = table.column(agg.column)
        r = _aggregate_segment(col, agg.op, perm, seg, num_segments, row_valid)
        valid = jnp.logical_and(compute.valid_mask(r), in_range)
        out_cols.append(Column(r.data, r.dtype, valid, r.lengths))
        base = (
            agg.column
            if isinstance(agg.column, str)
            else (table.names[agg.column] if table.names else f"c{agg.column}")
        )
        out_names.append(agg.name or f"{agg.op}_{base}")

    return Table(out_cols, out_names), num_groups


def groupby_aggregate(
    table: Table,
    by: Sequence[Union[int, str]],
    aggs: Sequence[GroupbyAgg],
) -> Table:
    """Eager groupby with exact output size (one host sync)."""
    padded, num_groups = groupby_aggregate_capped(
        table, by, aggs, num_segments=max(table.row_count, 1)
    )
    g = int(num_groups)
    cols = [
        Column(
            c.data[:g],
            c.dtype,
            None if c.validity is None else c.validity[:g],
            None if c.lengths is None else c.lengths[:g],
        )
        for c in padded.columns
    ]
    return Table(cols, padded.names)
