"""Group-by aggregation (cudf ``groupby``), sort-based.

TPU has no device-wide atomic hash-table idiom (SURVEY.md §7 hard part 1),
so aggregation is sort-based: normalize keys (ops/keys.py) -> stable
lexsort -> segment boundaries -> XLA segment reductions (which lower to
sorted scatter-adds, efficient on TPU). Null keys form their own group,
like Spark/cudf.

Two forms (see ops/__init__ docstring): ``groupby_aggregate`` host-syncs
the group count; ``groupby_aggregate_capped`` is fully jittable with
``num_segments`` as the static capacity. Large decomposable
aggregations route through the two-level chunked design
(ops/groupby_chunked.py).

Design note — string keys are NOT auto-dictionary-encoded here (unlike
joins, ops/join.py): encoding costs a full-width sort of its own, the
very pass this groupby already performs once, so for a one-shot
aggregation it can only add work. Joins amortize the encode across the
build sort plus 2·log(m) binary-search passes, where one int32 word vs
pad/8+1 words pays for itself.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column, Table
from . import compute
from . import keys as keys_mod
from .gather import gather_table

_AGG_OPS = {
    "sum", "count", "min", "max", "mean", "variance", "std",
    "collect_list", "collect_set", "nunique", "first", "last",
}
_COLLECT_OPS = {"collect_list", "collect_set"}


@dataclasses.dataclass(frozen=True)
class GroupbyAgg:
    """One aggregation: (value column, op, output name).

    ``list_capacity`` is the static per-group element capacity for
    ``collect_list``/``collect_set`` outputs (the LIST pad width) in the
    jittable capped API — groups with more elements are truncated to it
    (the caller owns the capacity, like every ``*_capped`` API); the
    eager API sizes it from the largest group automatically."""

    column: Union[int, str]
    op: str
    name: Optional[str] = None
    list_capacity: Optional[int] = None

    def __post_init__(self):
        if self.op not in _AGG_OPS:
            raise ValueError(f"unknown aggregation {self.op!r}")


def _segment_ids(
    key_cols: Sequence[Column],
    row_valid: Optional[jax.Array] = None,
    payload: Sequence[jax.Array] = (),
    values_via: str = "sort",
):
    """(perm, seg_ids, num_groups_device, sorted_payload): stable sort +
    boundary scan.

    ``row_valid`` excludes rows entirely (shuffle-padding occupancy): the
    leading occupancy word sorts them behind every real row, where their
    garbage keys may split into any number of trailing segments; the group
    count is therefore the highest segment id holding a valid row.

    ``values_via`` routes the ``payload`` arrays to sorted order:
    ``"sort"`` rides them through the variadic sort as non-key operands
    (each payload then pays every one of the network's O(log^2 n)
    passes); ``"gather"`` sorts only the key words + iota and applies
    the permutation with one O(n) gather per payload. Which wins on
    TPU is a measured A/B (bench ``groupby16m``/``_gather`` rungs) —
    the flat-packed CPU A/B had gather 3.5x ahead.
    """
    words: list[jax.Array] = []
    if row_valid is not None:
        # invalid rows last: word 0 for valid, 1 for padding
        words.append(jnp.where(row_valid, jnp.uint64(0), jnp.uint64(1)))
    for c in key_cols:
        if c.validity is not None:
            # null key rows group together: validity is a key word and null
            # payloads must not split the group
            words.append(c.validity.astype(jnp.uint64))
            words.extend(
                jnp.where(c.validity, w, jnp.uint64(0))
                for w in keys_mod.column_order_keys(c)
            )
        else:
            words.extend(keys_mod.column_order_keys(c))
    # one variadic stable sort carries the iota along, yielding the
    # sorted key words AND the permutation together — no post-sort
    # re-gather of each word (jnp.lexsort would return only the perm)
    n_rows = words[0].shape[0]
    iota = jnp.arange(n_rows, dtype=jnp.int32)
    if values_via == "sort":
        sorted_all = jax.lax.sort(
            tuple(words) + (iota,) + tuple(payload),
            num_keys=len(words),
        )
        sorted_words = list(sorted_all[: len(words)])
        perm = sorted_all[len(words)]
        sorted_payload = list(sorted_all[len(words) + 1 :])
    elif values_via == "gather":
        sorted_all = jax.lax.sort(
            tuple(words) + (iota,), num_keys=len(words)
        )
        sorted_words = list(sorted_all[: len(words)])
        perm = sorted_all[len(words)]
        sorted_payload = [jnp.take(p, perm, axis=0) for p in payload]
    else:
        raise ValueError(f"unknown values_via {values_via!r}")
    boundary = jnp.zeros(perm.shape, dtype=jnp.bool_).at[0].set(True)
    for w in sorted_words:
        boundary = boundary | jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), w[1:] != w[:-1]]
        )
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    if row_valid is not None:
        # Padding rows sort behind every real row (leading occupancy word)
        # but can form any number of trailing garbage segments — the real
        # group count is the highest segment id holding a valid row.
        # Sorted validity is the sorted occupancy word itself (word 0 =
        # valid), so it neither rides the sort nor pays a gather.
        rv_sorted = sorted_words[0] == jnp.uint64(0)
        num_groups = jnp.max(jnp.where(rv_sorted, seg + 1, 0))
    else:
        num_groups = seg[-1] + 1
    return perm, seg, num_groups, sorted_payload


def _segment_bounds(seg, num_segments: int):
    """Per-segment [start, end) row ranges via binary search over the
    (sorted, nondecreasing) segment-id vector — the TPU replacement for
    scatter-based segment lookups. XLA lowers ``jax.ops.segment_*`` to
    device scatters, which are serial-ish on TPU (~1.5 s at 16M rows
    measured on v5e); two log(n) searchsorted passes cost ~1 ms."""
    ids = jnp.arange(num_segments, dtype=seg.dtype)
    starts = jnp.searchsorted(seg, ids, side="left").astype(jnp.int32)
    ends = jnp.searchsorted(seg, ids, side="right").astype(jnp.int32)
    return starts, ends


def _sorted_segment_sum(masked_vals, starts, ends):
    """Segment sums of a row-sorted vector as cumsum differences.

    ``total[s] = c[end-1] - c[start-1]`` with ``c = cumsum(vals)``.
    For integer accumulators this is EXACT even if the running cumsum
    wraps: two's-complement overflow cancels in the subtraction. For
    floats XLA computes the cumsum as a log-depth associative scan, so
    rounding error grows O(log n), comparable to a tree reduction."""
    n = masked_vals.shape[0]
    c = jnp.cumsum(masked_vals)
    hi = c[jnp.clip(ends - 1, 0, max(n - 1, 0))]
    lo = jnp.where(
        starts > 0, c[jnp.clip(starts - 1, 0, max(n - 1, 0))], 0
    )
    return jnp.where(ends > starts, hi - lo, 0)


def _sorted_segment_extreme(masked_vals, seg, ends, is_min: bool):
    """Per-segment min/max of a row-sorted vector via one segmented
    associative scan (log-depth, fully vectorized — no scatter): the
    running extreme resets at segment boundaries, and the value at each
    segment's last row is the segment's extreme."""
    n = masked_vals.shape[0]

    def combine(a, b):
        s1, m1 = a
        s2, m2 = b
        same = s1 == s2
        ext = jnp.minimum(m1, m2) if is_min else jnp.maximum(m1, m2)
        return s2, jnp.where(same, ext, m2)

    _, scanned = jax.lax.associative_scan(combine, (seg, masked_vals))
    return scanned[jnp.clip(ends - 1, 0, max(n - 1, 0))]


def _valid_rank_rows(valid_sorted, starts, ranks):
    """Scatter-free within-segment compaction core: the sorted-row index
    of each segment's r-th VALID row, found by binary search over the
    running valid count (rank r lives at the first row where
    cumsum(valid) reaches base + r). ``ranks`` is (num_segments, k);
    out-of-range ranks clip to arbitrary rows — masking is the
    caller's job via per-segment valid counts."""
    n = valid_sorted.shape[0]
    cvalid = jnp.cumsum(valid_sorted.astype(jnp.int32))
    base = jnp.where(
        starts > 0, cvalid[jnp.clip(starts - 1, 0, max(n - 1, 0))], 0
    )
    target = base[:, None] + ranks
    row_idx = jnp.searchsorted(cvalid, target.reshape(-1), side="left")
    return (
        jnp.clip(row_idx, 0, max(n - 1, 0))
        .astype(jnp.int32)
        .reshape(target.shape)
    )


def _nth_valid_gather(vals_sorted, valid_sorted, starts, pad: int):
    """The value of the j-th VALID row of each segment, j = 1..pad."""
    ranks = jnp.arange(1, pad + 1, dtype=jnp.int32)[None, :]
    rows = _valid_rank_rows(valid_sorted, starts, ranks)
    return vals_sorted[rows]


def _first_occurrence(col, seg, vals_sorted, valid_sorted):
    """Value-sort rows within each segment and mark the first occurrence
    of each distinct valid value (the shared core of collect_set and
    nunique). Returns (resorted values, first-occurrence mask)."""
    # vals are arithmetic values (FLOAT64 decoded from bits): re-encode
    # to storage before order-keying, which expects the bit layout
    tmp = Column(
        compute.encode_values(vals_sorted, col.dtype), col.dtype, None
    )
    vword = keys_mod.column_order_keys(tmp)[0]
    # valid rows first within the segment (stable), then by value
    inval = jnp.where(valid_sorted, jnp.uint64(0), jnp.uint64(1))
    seg2, _, vword2, vals2, valid2 = jax.lax.sort(
        (seg, inval, vword, vals_sorted, valid_sorted), num_keys=3
    )
    new_seg = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), seg2[1:] != seg2[:-1]]
    )
    new_val = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), vword2[1:] != vword2[:-1]]
    )
    return vals2, valid2 & (new_seg | new_val)


def _collect_segment(
    col: Column,
    op: str,
    pad: int,
    seg,
    vals_sorted,
    valid_sorted,
    starts,
    ends,
) -> Column:
    """collect_list / collect_set -> LIST column of (num_segments, pad)
    child values + per-group lengths. Nulls are dropped (Spark
    collect_list/collect_set semantics); collect_set returns each
    group's distinct values in ascending order (deterministic; cudf
    leaves set order unspecified)."""
    from ..column import _LIST_CHILD_IDS

    if col.dtype.id not in _LIST_CHILD_IDS:
        raise TypeError(
            f"{op} not supported for {col.dtype} (LIST children are "
            "int8..64, uint8..64, float32, bool)"
        )
    if op == "collect_set":
        vals_sorted, valid_sorted = _first_occurrence(
            col, seg, vals_sorted, valid_sorted
        )
    counts = _sorted_segment_sum(
        valid_sorted.astype(jnp.int32), starts, ends
    )
    lens = jnp.minimum(counts, pad).astype(jnp.int32)
    mat = _nth_valid_gather(vals_sorted, valid_sorted, starts, pad)
    slot_ok = jnp.arange(pad, dtype=jnp.int32)[None, :] < lens[:, None]
    # typed zero: a bare 0 would promote BOOL8 children to int64 and
    # misreport list_child_dtype
    mat = jnp.where(slot_ok, mat, jnp.zeros((), mat.dtype))
    return Column(mat, dt.DType(dt.TypeId.LIST), None, lens)


def _aggregate_segment(
    col: Column,
    op: str,
    perm,
    seg,
    num_segments: int,
    row_valid: Optional[jax.Array] = None,
    bounds=None,
    gathered=None,
    list_capacity: Optional[int] = None,
) -> Column:
    """One aggregation over sorted segments. All paths are scatter-free
    (sorted-segment design): counts/sums are cumsum differences over the
    sorted rows, min/max a segmented associative scan, lookups
    searchsorted — the idiomatic TPU lowering of what cudf does with
    atomics+hash tables (SURVEY.md §7 hard part 1)."""
    is_dec128 = col.dtype.id == dt.TypeId.DECIMAL128
    if gathered is not None:
        vals, valid = gathered
    else:
        if is_dec128:
            g = col.data[perm]
            vals = (g[:, 0], g[:, 1])
        else:
            vals = compute.values(col)[perm]
        valid = compute.valid_mask(col)[perm]
        if row_valid is not None:
            valid = jnp.logical_and(valid, row_valid[perm])
    starts, ends = (
        bounds if bounds is not None else _segment_bounds(seg, num_segments)
    )
    n_valid = _sorted_segment_sum(valid.astype(jnp.int64), starts, ends)
    has = n_valid > 0

    if op == "count":
        return Column(n_valid, dt.INT64, None)

    if op in ("first", "last"):
        # first/last VALID value per group (Spark first()/last() with
        # ignoreNulls): the collect_list rank machinery at a single
        # per-segment rank — 1 for first, n_valid for last
        ranks = (
            jnp.ones_like(n_valid)[:, None]
            if op == "first"
            else n_valid.astype(jnp.int32)[:, None]
        )
        row = _valid_rank_rows(valid, starts, ranks)[:, 0]
        if is_dec128:
            lo, hi_l = vals
            data = jnp.stack([lo[row], hi_l[row]], axis=1)
            return Column(data, col.dtype, has)
        return compute.from_values(vals[row], col.dtype, has)

    if op in _COLLECT_OPS or op == "nunique":
        if is_dec128 or col.dtype.is_string:
            raise TypeError(f"{op} not supported for {col.dtype}")
        if op == "nunique":
            _, first = _first_occurrence(col, seg, vals, valid)
            return Column(
                _sorted_segment_sum(
                    first.astype(jnp.int64), starts, ends
                ),
                dt.INT64,
                None,
            )
        if list_capacity is None:
            raise ValueError(
                f"{op} in the capped API needs GroupbyAgg.list_capacity "
                "(the static LIST pad width)"
            )
        return _collect_segment(
            col, op, list_capacity, seg, vals, valid, starts, ends
        )

    if is_dec128:
        return _aggregate_segment_dec128(
            col, op, vals, valid, seg, starts, ends, n_valid, has
        )

    if op in ("sum", "mean"):
        acc_dtype = jnp.float64 if col.dtype.is_floating else jnp.int64
        total = _sorted_segment_sum(
            jnp.where(valid, vals, 0).astype(acc_dtype), starts, ends
        )
        if op == "mean":
            mean = total.astype(jnp.float64) / jnp.maximum(n_valid, 1)
            if col.dtype.is_decimal:
                mean = mean * (10.0 ** col.dtype.scale)
            return compute.from_values(mean, dt.FLOAT64, has)
        if col.dtype.is_floating:
            return compute.from_values(total, dt.FLOAT64, has)
        if col.dtype.is_decimal:
            return compute.from_values(
                total, dt.DType(dt.TypeId.DECIMAL64, col.dtype.scale), has
            )
        return compute.from_values(total, dt.INT64, has)

    if op in ("variance", "std"):
        # two-pass: segment mean, gather back to rows, segment-sum of
        # squared deviations (the mean-subtracting formula; the naive
        # E[x^2]-E[x]^2 shortcut catastrophically cancels for
        # large-magnitude values). Sample variance, ddof=1; groups with
        # fewer than 2 valid rows are null.
        fvals = vals.astype(jnp.float64)
        if col.dtype.is_decimal:
            fvals = fvals * (10.0 ** col.dtype.scale)
        nf = n_valid.astype(jnp.float64)
        s1 = _sorted_segment_sum(
            jnp.where(valid, fvals, 0.0), starts, ends
        )
        mean = s1 / jnp.maximum(nf, 1)
        dev = fvals - mean[jnp.clip(seg, 0, num_segments - 1)]
        sq = _sorted_segment_sum(
            jnp.where(valid, dev * dev, 0.0), starts, ends
        )
        var = sq / jnp.maximum(nf - 1, 1)
        out = jnp.sqrt(var) if op == "std" else var
        return compute.from_values(out, dt.FLOAT64, n_valid > 1)

    # min / max via masked sentinels + segmented scan
    if col.dtype.is_floating:
        sentinel = np.inf if op == "min" else -np.inf
    elif col.dtype.is_boolean:
        sentinel = op == "min"
    else:
        info = np.iinfo(np.dtype(col.dtype.storage_dtype))
        sentinel = info.max if op == "min" else info.min
    masked = jnp.where(valid, vals, jnp.asarray(sentinel, vals.dtype))
    out = _sorted_segment_extreme(masked, seg, ends, op == "min")
    return compute.from_values(out, col.dtype, has)


def groupby_aggregate_capped(
    table: Table,
    by: Sequence[Union[int, str]],
    aggs: Sequence[GroupbyAgg],
    num_segments: int,
    row_valid: Optional[jax.Array] = None,
    return_collect_overflow: bool = False,
    values_via: str = "sort",
) -> tuple[Table, jax.Array]:
    """Jittable groupby: (padded result of ``num_segments`` rows, count).

    Padding rows have null keys/values (validity False past the count).
    ``row_valid`` excludes rows (e.g. shuffle-padding occupancy).

    ``return_collect_overflow=True`` appends a device scalar: the
    LARGEST pre-clamp valid-element count of any group across the
    collect_list/collect_set aggregations (0 when there are none).
    ``collect_*`` outputs silently truncate groups past
    ``list_capacity`` — unlike every other ``*_capped`` API, whose
    two-phase counts let callers detect overflow — so callers that
    need losslessness check ``overflow <= list_capacity`` and resize
    (r3 advisor finding)."""
    key_cols = [table.column(c) for c in by]

    # value columns ride the variadic sort as payload (one fused sort
    # instead of a 100M-row device gather per agg column)
    distinct: dict = {}
    payload: list = []
    for agg in aggs:
        col = table.column(agg.column)
        if id(col) not in distinct:
            if col.dtype.id == dt.TypeId.DECIMAL128:
                # limb columns ride the sort as two 1-D u64 operands
                v_entries = [col.data[:, 0], col.data[:, 1]]
            else:
                v_entries = [compute.values(col)]
            m = compute.valid_mask(col)
            if row_valid is not None:
                m = jnp.logical_and(m, row_valid)
            distinct[id(col)] = (len(payload), len(v_entries))
            payload.extend(v_entries + [m])
    perm, seg, num_groups, sorted_payload = _segment_ids(
        key_cols, row_valid, payload, values_via=values_via
    )

    # representative (first) sorted row of each segment -> key values
    n = table.row_count
    bounds = _segment_bounds(seg, num_segments)
    starts, _ = bounds
    in_range = jnp.arange(num_segments, dtype=jnp.int32) < num_groups
    first_rows = perm[jnp.clip(starts, 0, max(n - 1, 0))]

    out_cols: list[Column] = []
    out_names: list[str] = []
    for i, c in enumerate(by):
        col = table.column(c)
        k = gather_table(Table([col]), first_rows).columns[0]
        valid = jnp.logical_and(
            compute.valid_mask(k), in_range
        )
        out_cols.append(Column(k.data, k.dtype, valid, k.lengths))
        out_names.append(
            c if isinstance(c, str) else (table.names[c] if table.names else f"key{i}")
        )

    collect_overflow = jnp.zeros((), jnp.int64)
    for agg in aggs:
        col = table.column(agg.column)
        j, nv = distinct[id(col)]
        vals_sorted = (
            tuple(sorted_payload[j : j + nv])
            if nv > 1
            else sorted_payload[j]
        )
        r = _aggregate_segment(
            col, agg.op, perm, seg, num_segments, row_valid, bounds,
            (vals_sorted, sorted_payload[j + nv]),
            list_capacity=agg.list_capacity,
        )
        valid = jnp.logical_and(compute.valid_mask(r), in_range)
        out_cols.append(Column(r.data, r.dtype, valid, r.lengths))
        base = (
            agg.column
            if isinstance(agg.column, str)
            else (table.names[agg.column] if table.names else f"c{agg.column}")
        )
        out_names.append(agg.name or f"{agg.op}_{base}")
        if return_collect_overflow and agg.op in _COLLECT_OPS:
            # pre-clamp element count of a group == its valid-row count
            # (collect drops nulls), which the count machinery already
            # computes from the same sorted payload. For collect_set
            # this is an UPPER bound (valid rows, not distinct values):
            # a conservative overflow signal, never a missed one.
            starts, ends = bounds
            n_valid = _sorted_segment_sum(
                sorted_payload[j + nv].astype(jnp.int64), starts, ends
            )
            collect_overflow = jnp.maximum(
                collect_overflow,
                jnp.max(jnp.where(in_range, n_valid, 0)),
            )

    out = Table(out_cols, out_names)
    if return_collect_overflow:
        return out, num_groups, collect_overflow
    return out, num_groups


# above this, SPARK_RAPIDS_TPU_GROUPBY_FORMULATION=packed/chunked can
# route decomposable aggregations through the two-level designs. The
# default stays on the single variadic sort: the round-5 chip window
# measured it 2.9x/7x AHEAD of the packed/chunked bets at 16M rows
# (BASELINE.md round-5 measured state) — XLA's batched small sorts are
# not VMEM-resident, so the two-level constant only comes back via the
# explicit Pallas engines, which are still an A/B in progress.
CHUNKED_MIN_ROWS = 4_000_000


def groupby_aggregate(
    table: Table,
    by: Sequence[Union[int, str]],
    aggs: Sequence[GroupbyAgg],
) -> Table:
    """Eager groupby with exact output size (one host sync). Collect
    aggregations without an explicit ``list_capacity`` get sized from
    the largest group's valid-row count (a cheap count pre-pass).

    Large inputs route by SPARK_RAPIDS_TPU_GROUPBY_FORMULATION:
    the default "single" keeps the one-variadic-sort path that won the
    round-5 on-chip A/B; "packed"/"chunked" opt into the two-level
    designs (exact-or-fallback) for measurement."""
    formulation = "single"
    if table.row_count > CHUNKED_MIN_ROWS:
        from ..utils.config import get_flag

        formulation = get_flag("GROUPBY_FORMULATION")
    if formulation == "packed":
        from .groupby_packed import (
            groupby_aggregate_packed,
            packed_groupby_supported,
        )

        if packed_groupby_supported(table, by, aggs):
            out = groupby_aggregate_packed(table, by, aggs)
            if out is not None:
                return out
    if formulation in ("packed", "chunked"):
        from .groupby_chunked import (
            chunked_groupby_supported,
            groupby_aggregate_chunked,
        )

        if chunked_groupby_supported(table, aggs):
            out = groupby_aggregate_chunked(table, by, aggs)
            if out is not None:
                return out
    if table.row_count == 0:
        # 0 rows -> 0 groups, but the output SCHEMA must still be exact:
        # run the real pipeline on one all-null dummy row (which forms
        # one null-key group) and slice it away
        dummy_cols = [
            Column(
                jnp.zeros((1,) + c.data.shape[1:], c.data.dtype),
                c.dtype,
                jnp.zeros((1,), jnp.bool_),
                None
                if c.lengths is None
                else jnp.zeros((1,), c.lengths.dtype),
            )
            for c in table.columns
        ]
        aggs = [
            dataclasses.replace(a, list_capacity=a.list_capacity or 1)
            if a.op in _COLLECT_OPS
            else a
            for a in aggs
        ]
        padded, _ = groupby_aggregate_capped(
            Table(dummy_cols, table.names), by, aggs, num_segments=1
        )
        from .copying import slice_rows

        return slice_rows(padded, 0, 0)
    needs = [
        a for a in aggs
        if a.op in _COLLECT_OPS and a.list_capacity is None
    ]
    if needs:
        counts = groupby_aggregate(
            table,
            by,
            [
                GroupbyAgg(a.column, "count", name=f"__collect_n{i}")
                for i, a in enumerate(needs)
            ],
        )
        sized = {}
        for i, a in enumerate(needs):
            c = counts.columns[len(by) + i].to_numpy()
            sized[id(a)] = max(1, int(c.max())) if c.size else 1
        aggs = [
            dataclasses.replace(a, list_capacity=sized[id(a)])
            if id(a) in sized
            else a
            for a in aggs
        ]
    padded, num_groups = groupby_aggregate_capped(
        table, by, aggs, num_segments=max(table.row_count, 1)
    )
    g = int(num_groups)
    cols = [
        Column(
            c.data[:g],
            c.dtype,
            None if c.validity is None else c.validity[:g],
            None if c.lengths is None else c.lengths[:g],
        )
        for c in padded.columns
    ]
    return Table(cols, padded.names)


def _aggregate_segment_dec128(
    col, op, vals, valid, seg, starts, ends, n_valid, has
):
    """DECIMAL128 aggregations over sorted segments (ops/int128.py).

    sum is EXACT mod 2**128: each limb splits into 32-bit halves whose
    per-segment totals fit u64 without wrap (n < 2**32), and the four
    partial sums recombine with 128-bit carries. min/max run one
    segmented lexicographic scan over the order-key words. mean /
    variance use the float64 approximation of the 128-bit value."""
    from . import int128

    lo, hi = vals
    scale = col.dtype.scale

    if op in ("sum", "mean"):
        m32 = jnp.uint64(0xFFFFFFFF)
        zero = jnp.uint64(0)
        parts = []
        for limb in (lo, hi):
            parts.append(jnp.where(valid, limb & m32, zero))
            parts.append(jnp.where(valid, limb >> jnp.uint64(32), zero))
        s_ll, s_lh, s_hl, s_hh = [
            _sorted_segment_sum(p.astype(jnp.int64), starts, ends).astype(
                jnp.uint64
            )
            for p in parts
        ]
        out_lo, out_hi = s_ll, jnp.zeros_like(s_ll)
        out_lo, out_hi = int128.add(
            out_lo, out_hi, s_lh << jnp.uint64(32), s_lh >> jnp.uint64(32)
        )
        out_lo, out_hi = int128.add(
            out_lo, out_hi, jnp.zeros_like(s_hl), s_hl
        )
        out_lo, out_hi = int128.add(
            out_lo, out_hi, jnp.zeros_like(s_hh), s_hh << jnp.uint64(32)
        )
        if op == "mean":
            mean = (
                int128.to_float64(out_lo, out_hi)
                / jnp.maximum(n_valid, 1)
                * (10.0 ** scale)
            )
            return compute.from_values(mean, dt.FLOAT64, has)
        data = jnp.stack([out_lo, out_hi], axis=1)
        return Column(data, dt.DType(dt.TypeId.DECIMAL128, scale), has)

    if op in ("variance", "std"):
        fvals = int128.to_float64(lo, hi) * (10.0 ** scale)
        nf = n_valid.astype(jnp.float64)
        s1 = _sorted_segment_sum(
            jnp.where(valid, fvals, 0.0), starts, ends
        )
        mean = s1 / jnp.maximum(nf, 1)
        num_segments = starts.shape[0]
        dev = fvals - mean[jnp.clip(seg, 0, num_segments - 1)]
        sq = _sorted_segment_sum(
            jnp.where(valid, dev * dev, 0.0), starts, ends
        )
        var = sq / jnp.maximum(nf - 1, 1)
        out = jnp.sqrt(var) if op == "std" else var
        return compute.from_values(out, dt.FLOAT64, n_valid > 1)

    # min / max: lexicographic segmented scan over order-key words
    sign = np.uint64(1) << np.uint64(63)
    key_hi = hi ^ sign
    is_min = op == "min"
    sent = jnp.uint64(0xFFFFFFFFFFFFFFFF) if is_min else jnp.uint64(0)
    k_hi = jnp.where(valid, key_hi, sent)
    k_lo = jnp.where(valid, lo, sent)

    def combine(a, b):
        s1, h1, l1 = a
        s2, h2, l2 = b
        same = s1 == s2
        if is_min:
            a_wins = (h1 < h2) | ((h1 == h2) & (l1 <= l2))
        else:
            a_wins = (h1 > h2) | ((h1 == h2) & (l1 >= l2))
        take_a = same & a_wins
        return s2, jnp.where(take_a, h1, h2), jnp.where(take_a, l1, l2)

    _, sc_hi, sc_lo = jax.lax.associative_scan(
        combine, (seg, k_hi, k_lo)
    )
    n = lo.shape[0]
    idx = jnp.clip(ends - 1, 0, max(n - 1, 0))
    out_hi = sc_hi[idx] ^ sign
    out_lo = sc_lo[idx]
    data = jnp.stack([out_lo, out_hi], axis=1)
    return Column(data, dt.DType(dt.TypeId.DECIMAL128, scale), has)
