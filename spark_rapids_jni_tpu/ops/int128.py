"""Vectorized 128-bit integer arithmetic over two-u64-limb arrays.

DECIMAL128 device representation (round-3 VERDICT item 6): a column of n
128-bit unscaled values is a ``(n, 2)`` uint64 buffer of little-endian
limbs ``[lo, hi]`` (two's-complement; the sign lives in hi's top bit).
TPU has no native int128, but limb arithmetic is pure vector ops — adds
with carry, 32-bit-half multiplies — which XLA fuses well, the same way
the reference gets int128 from CUDA's __int128 emulation in libcudf
(reference surface: decimal128 round-trips in the vendored cudf Java
tests, spark-rapids-cudf/pom.xml:207-217).

All functions take/return (lo, hi) pairs of uint64 arrays.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_U64 = jnp.uint64
_MASK32 = np.uint64(0xFFFFFFFF)


def from_signed_int(v: jax.Array):
    """Sign-extend an int64 (or narrower) array to (lo, hi)."""
    v64 = v.astype(jnp.int64)
    lo = v64.astype(jnp.uint64)
    hi = (v64 >> jnp.int64(63)).astype(jnp.uint64)  # 0 or all-ones
    return lo, hi


def from_py_ints(values, n=None) -> np.ndarray:
    """Host helper: iterable of Python ints -> (n, 2) uint64 limbs."""
    vals = list(values)
    out = np.zeros((len(vals) if n is None else n, 2), dtype=np.uint64)
    mask = (1 << 64) - 1
    for i, v in enumerate(vals):
        if v is None:
            continue
        u = v & ((1 << 128) - 1)  # two's complement
        out[i, 0] = u & mask
        out[i, 1] = (u >> 64) & mask
    return out


def to_py_ints(limbs: np.ndarray) -> list:
    """Host helper: (n, 2) uint64 limbs -> Python ints (signed)."""
    out = []
    for lo, hi in np.asarray(limbs, dtype=np.uint64):
        u = (int(hi) << 64) | int(lo)
        out.append(u - (1 << 128) if u >= (1 << 127) else u)
    return out


def add(a_lo, a_hi, b_lo, b_hi):
    """128-bit add (wrapping)."""
    lo = a_lo + b_lo
    carry = (lo < a_lo).astype(_U64)
    return lo, a_hi + b_hi + carry


def negate(lo, hi):
    """Two's-complement negation: ~x + 1, where the +1 carries into hi
    exactly when lo == 0."""
    carry = (lo == jnp.uint64(0)).astype(_U64)
    return ~lo + jnp.uint64(1), ~hi + carry


def sub(a_lo, a_hi, b_lo, b_hi):
    nb_lo, nb_hi = negate(b_lo, b_hi)
    return add(a_lo, a_hi, nb_lo, nb_hi)


def _mul_u64(a, b):
    """64x64 -> 128 unsigned multiply via 32-bit halves."""
    a_lo = a & _MASK32
    a_hi = a >> jnp.uint64(32)
    b_lo = b & _MASK32
    b_hi = b >> jnp.uint64(32)
    ll = a_lo * b_lo
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    mid = (ll >> jnp.uint64(32)) + (lh & _MASK32) + (hl & _MASK32)
    lo = (ll & _MASK32) | (mid << jnp.uint64(32))
    hi = hh + (lh >> jnp.uint64(32)) + (hl >> jnp.uint64(32)) + (
        mid >> jnp.uint64(32)
    )
    return lo, hi


def mul_u64(lo, hi, m):
    """128-bit x u64 scalar multiply (wrapping) — the rescale primitive
    (x * 10**k when widening a decimal scale)."""
    m = jnp.uint64(m)
    p_lo, p_hi = _mul_u64(lo, m)
    return p_lo, p_hi + hi * m


def lt_signed(a_lo, a_hi, b_lo, b_hi):
    """Signed 128-bit a < b."""
    ah = a_hi.astype(jnp.int64)
    bh = b_hi.astype(jnp.int64)
    return (ah < bh) | ((ah == bh) & (a_lo < b_lo))


def eq(a_lo, a_hi, b_lo, b_hi):
    return (a_lo == b_lo) & (a_hi == b_hi)


def to_float64(lo, hi):
    """Approximate float64 value (for mean/float casts)."""
    neg = (hi >> jnp.uint64(63)) != 0
    nlo, nhi = negate(lo, hi)
    ulo = jnp.where(neg, nlo, lo)
    uhi = jnp.where(neg, nhi, hi)
    mag = uhi.astype(jnp.float64) * np.float64(2.0**64) + ulo.astype(
        jnp.float64
    )
    return jnp.where(neg, -mag, mag)


def order_key_words(limbs: jax.Array):
    """(n, 2) limbs -> [hi ^ signbit, lo] u64 words whose lexicographic
    unsigned order equals signed 128-bit order (keys.py convention)."""
    sign = np.uint64(1) << np.uint64(63)
    return [limbs[:, 1] ^ sign, limbs[:, 0]]


def pow10_limbs(k: int):
    """(lo, hi) host limbs of 10**k, 0 <= k <= 38."""
    if not 0 <= k <= 38:
        raise ValueError(f"10**{k} out of decimal128 range")
    u = 10**k
    return np.uint64(u & ((1 << 64) - 1)), np.uint64(u >> 64)


def divmod_u32_rem(lo, hi, d: int):
    """128-bit unsigned division by a u32 constant via base-2^32 long
    division (d < 2**32). Returns (q_lo, q_hi, remainder u64)."""
    if not 0 < d < 2**32:
        raise ValueError("divisor must fit in u32")
    dd = jnp.uint64(d)
    digits = [
        hi >> jnp.uint64(32),
        hi & _MASK32,
        lo >> jnp.uint64(32),
        lo & _MASK32,
    ]
    r = jnp.zeros_like(lo)
    q = []
    for dig in digits:
        cur = (r << jnp.uint64(32)) | dig
        q.append(cur // dd)
        r = cur % dd
    q_hi = (q[0] << jnp.uint64(32)) | (q[1] & _MASK32)
    q_lo = (q[2] << jnp.uint64(32)) | (q[3] & _MASK32)
    return q_lo, q_hi, r


def divmod_u32(lo, hi, d: int):
    """128-bit unsigned division by a u32 constant; remainder discarded."""
    q_lo, q_hi, _ = divmod_u32_rem(lo, hi, d)
    return q_lo, q_hi


def rescale(lo, hi, from_scale: int, to_scale: int):
    """Change a decimal's scale: multiply (scale down) or divide
    (scale up) by the power of ten, chunked so every step fits the limb
    primitives. Division truncates toward zero (magnitude divide), the
    cudf fixed_point convention."""
    if from_scale == to_scale:
        return lo, hi
    if to_scale < from_scale:
        k = from_scale - to_scale
        while k > 0:
            step = min(k, 19)
            lo, hi = mul_u64(lo, hi, np.uint64(10**step))
            k -= step
        return lo, hi
    # divide by 10^k on magnitudes, then restore the sign
    k = to_scale - from_scale
    neg = (hi >> jnp.uint64(63)) != 0
    nlo, nhi = negate(lo, hi)
    mlo = jnp.where(neg, nlo, lo)
    mhi = jnp.where(neg, nhi, hi)
    while k > 0:
        step = min(k, 9)
        mlo, mhi = divmod_u32(mlo, mhi, 10**step)
        k -= step
    rlo, rhi = negate(mlo, mhi)
    return jnp.where(neg, rlo, mlo), jnp.where(neg, rhi, mhi)
