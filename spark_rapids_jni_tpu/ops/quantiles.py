"""Quantiles (cudf ``quantile``: LINEAR / LOWER / HIGHER / MIDPOINT /
NEAREST interpolation, null-excluding).

Capability-surface row of SURVEY.md §2.3 (cudf Java suite covers
ColumnVector.quantile). One device sort with nulls exiled past the end,
then index arithmetic against the device-resident valid count — fully
jittable, no host sync for the n_valid-dependent positions.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from .. import dtype as dt
from ..column import Column
from . import compute

LINEAR = "linear"
LOWER = "lower"
HIGHER = "higher"
MIDPOINT = "midpoint"
NEAREST = "nearest"


def quantile(
    col: Column, qs: Sequence[float], interpolation: str = LINEAR
) -> Column:
    """FLOAT64 column of one quantile per entry of ``qs`` (null when the
    input has no valid rows)."""
    if interpolation not in (LINEAR, LOWER, HIGHER, MIDPOINT, NEAREST):
        raise ValueError(f"unknown interpolation {interpolation!r}")
    if not (col.dtype.is_numeric or col.dtype.is_timestamp):
        raise TypeError(f"quantile: numeric input required, got {col.dtype}")
    qs = list(qs)
    if any(not (0.0 <= float(q) <= 1.0) for q in qs):
        raise ValueError(f"quantile fractions must be in [0, 1], got {qs}")
    n = len(col)
    vals = compute.values(col).astype(jnp.float64)
    if col.dtype.is_decimal:
        vals = vals * (10.0 ** col.dtype.scale)
    valid = compute.valid_mask(col)
    # NaNs are excluded like nulls (pandas/cudf null-excluding quantile);
    # otherwise they'd sort past the inf null-exile region and shift it
    valid = jnp.logical_and(valid, jnp.logical_not(jnp.isnan(vals)))
    # nulls sort past every real value; n_valid bounds the index range
    sorted_vals = jnp.sort(jnp.where(valid, vals, jnp.inf))
    n_valid = jnp.sum(valid).astype(jnp.float64)

    q = jnp.asarray(qs, jnp.float64)
    pos = q * jnp.maximum(n_valid - 1, 0)
    # clamp to the valid region, not just [0, n-1] — indices past
    # n_valid-1 would read the null-exile infs
    max_i = jnp.maximum(n_valid - 1, 0).astype(jnp.int32)
    lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, max_i)
    hi = jnp.clip(jnp.ceil(pos).astype(jnp.int32), 0, max_i)
    vlo = sorted_vals[lo]
    vhi = sorted_vals[hi]
    if interpolation == LINEAR:
        frac = pos - jnp.floor(pos)
        out = vlo + (vhi - vlo) * frac
    elif interpolation == LOWER:
        out = vlo
    elif interpolation == HIGHER:
        out = vhi
    elif interpolation == MIDPOINT:
        out = (vlo + vhi) * 0.5
    else:  # NEAREST
        out = jnp.where(pos - jnp.floor(pos) <= 0.5, vlo, vhi)
    has = jnp.broadcast_to(n_valid > 0, out.shape)
    return compute.from_values(out, dt.FLOAT64, has)
