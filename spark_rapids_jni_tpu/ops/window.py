"""Window functions (cudf ``rolling_window`` / grouped windows, Spark
WindowExec): rolling aggregates over row-based frames, lead/lag,
row_number — with or without PARTITION BY.

Capability-surface row of SURVEY.md §2.3 (cudf's Java WindowTest
family). TPU formulation: no per-row loops — SUM/COUNT/MEAN windows are
prefix-sum differences, MIN/MAX windows combine two overlapping
power-of-two block minima from a sparse table (O(n log n) build, O(1)
per row), and partition clamping is just index arithmetic on the
sorted-by-(partition, order) layout. Everything jits.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column, Table
from . import compute
from .keys import column_order_keys

_SUMLIKE = {"sum", "count", "mean"}
_MINMAX = {"min", "max"}


def _window_bounds(n, preceding: int, following: int, part_start, part_end):
    """Per-row [start, end) frame, clamped to the partition."""
    i = jnp.arange(n, dtype=jnp.int32)
    start = jnp.maximum(i - preceding, part_start)
    end = jnp.minimum(i + following + 1, part_end)
    return start, jnp.maximum(end, start)


def _count_window(valid, start, end):
    """Per-row count of valid values in [start, end) via prefix sums."""
    cnt = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64), jnp.cumsum(valid.astype(jnp.int64))]
    )
    return cnt[end] - cnt[start]


def _prefix_window(vals, valid, start, end, agg):
    """SUM/COUNT/MEAN via exclusive prefix sums over masked values.

    Returns ``(out, has, wcnt)`` — the per-row valid count comes along
    so callers never recompute the count prefix sums.
    """
    acc = jnp.where(valid, vals, 0).astype(
        jnp.float64 if jnp.issubdtype(vals.dtype, jnp.floating) else jnp.int64
    )
    cs = jnp.concatenate([jnp.zeros((1,), acc.dtype), jnp.cumsum(acc)])
    wsum = cs[end] - cs[start]
    wcnt = _count_window(valid, start, end)
    if agg == "count":
        return wcnt, wcnt >= 0, wcnt
    if agg == "sum":
        return wsum, wcnt > 0, wcnt
    return wsum.astype(jnp.float64) / jnp.maximum(wcnt, 1), wcnt > 0, wcnt


def _minmax_window(col: Column, start, end, op):
    """MIN/MAX over [start, end) via two overlapping blocks of a sparse
    table of winner positions, on order keys (exact for every supported
    dtype incl. f64 bit patterns). Nulls take an exiled key so they only
    win all-null frames; key ties between a null and a legitimate
    extreme value (INT64_MAX has the same key as the min-exile) break
    toward the VALID row, so the winner's validity decides the output."""
    n = len(col)
    keys = column_order_keys(col)
    if len(keys) != 1:
        raise TypeError("window min/max: fixed-width columns only")
    key = keys[0]
    valid = compute.valid_mask(col)
    exile = (
        jnp.uint64(0xFFFFFFFFFFFFFFFF) if op == "min" else jnp.uint64(0)
    )
    key = jnp.where(valid, key, exile)
    length = jnp.maximum(end - start, 1)
    k = jnp.floor(jnp.log2(length.astype(jnp.float64))).astype(jnp.int32)
    # frame [start, end) = block [start, start+2^k) ∪ [end-2^k, end)
    pos_table = _sparse_table_pos(key, valid, op)
    k = jnp.clip(k, 0, pos_table.shape[0] - 1)
    second = jnp.maximum(end - jnp.left_shift(1, k), start)
    pl = pos_table[k, start]
    pr = pos_table[k, second]
    kl, vl = key[pl], valid[pl]
    kr, vr = key[pr], valid[pr]
    if op == "min":
        take_left = (kl < kr) | ((kl == kr) & (vl | ~vr))
    else:
        take_left = (kl > kr) | ((kl == kr) & (vl | ~vr))
    pos = jnp.where(take_left, pl, pr)
    # the winner is null only when the whole frame is null (or empty)
    return pos, valid[pos] & (end > start)


def _sparse_table_pos(keys, valid, op):
    """(K, n) table of the index attaining the op over [i, i+2^k),
    with key ties broken toward valid rows (see _minmax_window)."""
    n = keys.shape[0]
    pad_val = (
        jnp.uint64(0xFFFFFFFFFFFFFFFF) if op == "min" else jnp.uint64(0)
    )

    def better(ak, av, bk, bv):
        if op == "min":
            return (ak < bk) | ((ak == bk) & (av | ~bv))
        return (ak > bk) | ((ak == bk) & (av | ~bv))

    idx = jnp.arange(n, dtype=jnp.int32)
    levels_k = [keys]
    levels_v = [valid]
    levels_p = [idx]
    k = 1
    while k < n:
        pk, pv, pp = levels_k[-1], levels_v[-1], levels_p[-1]
        pad_n = min(k, n)
        sk = jnp.concatenate([pk[k:], jnp.full((pad_n,), pad_val, pk.dtype)])
        sv = jnp.concatenate([pv[k:], jnp.zeros((pad_n,), jnp.bool_)])
        sp = jnp.concatenate([pp[k:], pp[:pad_n]])
        keep = better(pk, pv, sk, sv)
        levels_k.append(jnp.where(keep, pk, sk))
        levels_v.append(jnp.where(keep, pv, sv))
        levels_p.append(jnp.where(keep, pp, sp))
        k *= 2
    return jnp.stack(levels_p)


def rolling_aggregate(
    col: Column,
    preceding: int,
    following: int,
    agg: str,
    min_periods: int = 1,
    partition_starts: Optional[jax.Array] = None,
    partition_ends: Optional[jax.Array] = None,
) -> Column:
    """Row-based rolling window over the column's current order.

    ``preceding``/``following`` are row counts either side of the current
    row (cudf rolling_window semantics). Rows whose frame holds fewer
    than ``min_periods`` valid values are null.
    """
    n = len(col)
    ps = (
        partition_starts
        if partition_starts is not None
        else jnp.zeros((n,), jnp.int32)
    )
    pe = (
        partition_ends
        if partition_ends is not None
        else jnp.full((n,), n, jnp.int32)
    )
    start, end = _window_bounds(n, preceding, following, ps, pe)
    return _frame_aggregate(col, start, end, agg, min_periods)


def _frame_aggregate(
    col: Column, start, end, agg: str, min_periods: int
) -> Column:
    """Aggregate per-row frames [start, end) — the shared back half of
    the ROW and RANGE window paths (the frame *shape* is the only thing
    that differs between them)."""
    valid = compute.valid_mask(col)

    if agg in _SUMLIKE:
        vals = compute.values(col)
        out, has, cnt = _prefix_window(vals, valid, start, end, agg)
        ok = jnp.logical_and(has, cnt >= min_periods)
        if agg == "count":
            return Column(out.astype(jnp.int32), dt.INT32, ok)
        if agg == "mean":
            if col.dtype.is_decimal:
                # unscaled ints -> logical values (the groupby/reduce
                # mean convention, groupby.py mean branch)
                out = out * (10.0 ** col.dtype.scale)
            if col.dtype.is_floating:
                # like sum: f64 accumulation, input floating type out
                # (libcudf MEAN preserves the source floating type)
                return compute.from_values(
                    out.astype(vals.dtype), col.dtype, ok
                )
            return compute.from_values(out, dt.FLOAT64, ok)
        if col.dtype.is_floating:
            # f64 accumulation, but the output keeps the input floating
            # type (cudf rolling_window preserves it)
            return compute.from_values(
                out.astype(vals.dtype), col.dtype, ok
            )
        out_dt = (
            dt.DType(dt.TypeId.DECIMAL64, col.dtype.scale)
            if col.dtype.is_decimal
            else dt.INT64
        )
        return compute.from_values(out, out_dt, ok)

    if agg in _MINMAX:
        pos, has = _minmax_window(col, start, end, agg)
        cnt = _count_window(valid, start, end)
        ok = jnp.logical_and(has, cnt >= min_periods)
        return Column(jnp.take(col.data, pos, axis=0), col.dtype, ok)

    raise ValueError(f"unknown window aggregation {agg!r}")


def _saturating_offset(vals: jax.Array, delta) -> jax.Array:
    """``vals + delta`` with integer wrap-around clamped to the dtype
    extreme (floats saturate to +-inf on their own). The RANGE frame of
    a row near INT64_MAX must be "everything from here up", not wrap to
    the bottom of the partition.

    ``delta`` stays a Python int throughout: casting it to the column
    dtype would raise under numpy>=2 for e.g. a negative delta on a
    uint64 column or an out-of-range delta on INT8."""
    if jnp.issubdtype(vals.dtype, jnp.floating):
        return vals + vals.dtype.type(delta)
    delta = int(delta)
    if delta == 0:
        return vals
    info = jnp.iinfo(vals.dtype)
    if vals.dtype.itemsize < 8:
        # widen: int64 holds any narrow dtype plus a clamped delta
        d = max(min(delta, 1 << 62), -(1 << 62))
        out = jnp.clip(vals.astype(jnp.int64) + d, info.min, info.max)
        return out.astype(vals.dtype)
    # 8-byte lanes have no wider integer to widen into: walk the offset
    # in quarter-steps that each fit BOTH int64 and uint64, detecting
    # wrap after each step (a saturated lane keeps wrapping and is
    # re-pinned every step, so saturation is sticky).
    mag = min(abs(delta), 1 << 64)  # >= full dtype span: total saturation
    sign = 1 if delta > 0 else -1
    out = vals
    while mag:
        q = min(mag, 1 << 62)
        mag -= q
        step = vals.dtype.type(q)
        if sign > 0:
            nxt = out + step
            out = jnp.where(nxt < out, info.max, nxt)
        else:
            nxt = out - step
            out = jnp.where(nxt > out, info.min, nxt)
    return out


def grouped_range_rolling_aggregate(
    table: Table,
    partition_by: Sequence,
    order_by: Union[int, str],
    value: Union[int, str],
    preceding,
    following,
    agg: str,
    min_periods: int = 1,
    ascending: bool = True,
) -> Column:
    """RANGE-framed rolling window (libcudf grouped_range_rolling_window
    / Spark ``RANGE BETWEEN x PRECEDING AND y FOLLOWING``), result in
    the table's ORIGINAL row order.

    Row i's frame holds every partition row j whose ORDER BY value lies
    within ``[v_i - preceding, v_i + following]`` (ascending; descending
    frames span ``[v_i - following, v_i + preceding]``) — peers with
    equal order values always share a frame, the defining difference
    from ROW frames. ``preceding=None`` / ``following=None`` mean
    UNBOUNDED PRECEDING/FOLLOWING. Exactly one ORDER BY column; bounds
    are in the column's storage units (ticks for timestamps, unscaled
    for decimals). NULL order rows form one peer frame per partition
    (the SQL null-peers rule).

    TPU formulation: no per-row scans — on the (partition, order)-sorted
    layout each frame end is a vectorized lexicographic binary search of
    ``(partition_run, null_word, order_key(v_i -/+ bound))`` against the
    rows' own sort words (the join-probe machinery,
    ops/join._lex_searchsorted), so frame discovery is O(n log n) with
    static shapes, and aggregation reuses the shared prefix-sum /
    sparse-table kernels. Everything jits. Contrast: cudf walks each row
    outward with type-dispatched comparators
    (grouped_rolling .cu kernels); a binary search over normalized u64
    words is the shape XLA tiles well."""
    from .gather import gather_column
    from .join import _lex_searchsorted
    from .sort import SortKey

    n = table.row_count
    okey = SortKey(order_by, ascending=ascending)
    sorted_t, starts, ends, inv, idx, new_part = _window_scaffold(
        table, partition_by, [okey]
    )

    oc = sorted_t.column(order_by)
    okeys = column_order_keys(oc)
    if len(okeys) != 1:
        raise TypeError(
            "range frames need a fixed-width ORDER BY column "
            f"(got {oc.dtype})"
        )
    ovalid = compute.valid_mask(oc)
    vals = compute.values(oc)

    # The words the rows are actually ordered by, reduced to three:
    # partition run id (equal pid <=> equal partition keys), the sort's
    # null-placement word, and the (direction-adjusted) order key.
    pid = jnp.cumsum(new_part.astype(jnp.int64)).astype(jnp.uint64)
    if okey.resolved_nulls_first:
        null_word = jnp.where(ovalid, jnp.uint64(1), jnp.uint64(0))
    else:
        null_word = jnp.where(ovalid, jnp.uint64(0), jnp.uint64(1))
    kw = okeys[0] if ascending else ~okeys[0]
    # zero the key word under nulls: the three-word view must be
    # non-decreasing in the sorted layout no matter how the sort
    # tie-broke the null run internally, and a null query then brackets
    # its whole peer run with the same zero word
    kw = jnp.where(ovalid, kw, jnp.uint64(0))
    sorted_words = [pid, null_word, kw]

    def shifted_key(delta):
        if delta is None:
            return None
        shifted = _saturating_offset(vals, delta)
        col = Column(
            compute.encode_values(shifted, oc.dtype), oc.dtype, None
        )
        k = column_order_keys(col)[0]
        return k if ascending else ~k

    # ascending: frame = keys in [ok(v-pre), ok(v+fol)]
    # descending: layout orders by ~ok, frame = values in
    #   [v-fol, v+pre] -> ~ok in [~ok(v+pre), ~ok(v-fol)]
    if ascending:
        lo_kw = shifted_key(-preceding if preceding is not None else None)
        hi_kw = shifted_key(following if following is not None else None)
    else:
        lo_kw = shifted_key(preceding if preceding is not None else None)
        hi_kw = shifted_key(-following if following is not None else None)

    zero = jnp.zeros((n,), jnp.uint64)
    if lo_kw is None:
        start = starts
    else:
        # null rows bracket their own peer run (key word zero, like the
        # sorted view) instead of applying value arithmetic to garbage
        q = [pid, null_word, jnp.where(ovalid, lo_kw, zero)]
        start = _lex_searchsorted(sorted_words, q, "left")
    if hi_kw is None:
        end = ends
    else:
        q = [pid, null_word, jnp.where(ovalid, hi_kw, zero)]
        end = _lex_searchsorted(sorted_words, q, "right")
    start = jnp.clip(start, starts, ends)
    end = jnp.clip(end, start, ends)

    out_sorted = _frame_aggregate(
        sorted_t.column(value), start, end, agg, min_periods
    )
    return gather_column(out_sorted, inv)


def _partition_bounds(table: Table, partition_by: Sequence, new_part=None):
    """(starts, ends) per row for a table sorted by the partition keys.
    Pass ``new_part`` when the boundary vector is already computed."""
    n = table.row_count
    if new_part is None:
        new_part = _change_boundaries(table, partition_by)
    idx = jnp.arange(n, dtype=jnp.int32)
    starts = jax.lax.cummax(jnp.where(new_part, idx, 0))
    # ends: next partition start (reverse cummin of starts-after)
    next_start = jnp.concatenate(
        [jnp.where(new_part, idx, n + 1)[1:], jnp.full((1,), n, jnp.int32)]
    )
    rev = jax.lax.cummin(next_start[::-1])[::-1]
    ends = jnp.minimum(rev, n)
    return starts, ends


def grouped_rolling_aggregate(
    table: Table,
    partition_by: Sequence,
    order_by: Sequence,
    value: Union[int, str],
    preceding: int,
    following: int,
    agg: str,
    min_periods: int = 1,
) -> Column:
    """PARTITION BY + ORDER BY rolling window; result aligned to the
    table's ORIGINAL row order (Spark WindowExec contract)."""
    from .gather import gather_column

    sorted_t, starts, ends, inv, _, _ = _window_scaffold(
        table, partition_by, order_by
    )
    out_sorted = rolling_aggregate(
        sorted_t.column(value),
        preceding,
        following,
        agg,
        min_periods,
        partition_starts=starts,
        partition_ends=ends,
    )
    return gather_column(out_sorted, inv)


def lead(col: Column, n: int = 1, partition_ids=None) -> Column:
    """Value ``n`` rows ahead; null past the end (Spark LEAD)."""
    return _shift(col, -n, partition_ids)


def lag(col: Column, n: int = 1, partition_ids=None) -> Column:
    """Value ``n`` rows behind; null before the start (Spark LAG)."""
    return _shift(col, n, partition_ids)


def _shift(col: Column, n: int, partition_ids) -> Column:
    size = len(col)
    idx = jnp.arange(size, dtype=jnp.int32) - n
    in_range = jnp.logical_and(idx >= 0, idx < size)
    safe = jnp.clip(idx, 0, size - 1)
    if partition_ids is not None:
        same = partition_ids[safe] == partition_ids
        in_range = jnp.logical_and(in_range, same)
    data = jnp.take(col.data, safe, axis=0)
    valid = (
        in_range
        if col.validity is None
        else jnp.logical_and(in_range, jnp.take(col.validity, safe))
    )
    lengths = (
        None if col.lengths is None else jnp.take(col.lengths, safe)
    )
    return Column(data, col.dtype, valid, lengths)


def row_number(
    table: Table, partition_by: Sequence, order_by: Sequence
) -> Column:
    """1-based rank within each partition, in the table's original row
    order (Spark ROW_NUMBER)."""
    from .gather import gather_column

    _, starts, _, inv, idx, _ = _window_scaffold(
        table, partition_by, order_by
    )
    rn_sorted = idx - starts + 1
    return gather_column(Column(rn_sorted, dt.INT32, None), inv)


def _change_boundaries(table: Table, keys: Sequence) -> jnp.ndarray:
    """(n,) bool: row i starts a new run of the key columns. Null rows
    compare EQUAL to each other (payload words are zeroed under the
    validity mask and the mask itself is a word) — the SQL tie rule for
    NULL order keys, and the same normalization _partition_bounds uses."""
    n = table.row_count
    boundary = jnp.zeros((n,), jnp.bool_)
    for c in (table.column(k) for k in keys):
        cwords = column_order_keys(c)
        if c.validity is not None:
            cwords = [
                jnp.where(c.validity, w, jnp.uint64(0)) for w in cwords
            ]
            cwords.append(c.validity.astype(jnp.uint64))
        for w in cwords:
            boundary = jnp.logical_or(
                boundary,
                jnp.concatenate(
                    [jnp.ones((1,), jnp.bool_), w[1:] != w[:-1]]
                ),
            )
    return boundary


def _window_scaffold(table: Table, partition_by, order_by):
    """Shared sort scaffolding for the ranking + range-frame families:
    the table sorted by (partition, order) keys, per-row partition
    [start, end), the inverse permutation back to the original row
    order, and the partition-boundary vector (computed once; both the
    bounds and the range path's partition-run ids derive from it).
    Entries of ``order_by`` may be plain column refs or SortKey."""
    from .gather import gather_table
    from .sort import SortKey, argsort_table

    n = table.row_count
    sort_keys = [
        k if isinstance(k, SortKey) else SortKey(k)
        for k in [*partition_by, *order_by]
    ]
    perm = argsort_table(table, sort_keys)
    sorted_t = gather_table(table, perm)
    part_refs = [
        k.column if isinstance(k, SortKey) else k for k in partition_by
    ]
    new_part = _change_boundaries(sorted_t, part_refs)
    starts, ends = _partition_bounds(sorted_t, part_refs, new_part)
    idx = jnp.arange(n, dtype=jnp.int32)
    inv = jnp.zeros((n,), jnp.int32).at[perm].set(idx)
    return sorted_t, starts, ends, inv, idx, new_part


def _rank_sorted(table: Table, partition_by, order_by, kind: str):
    """Shared rank machinery: returns the rank vector in sorted order
    plus the inverse permutation back to table order."""
    n = table.row_count
    sorted_t, starts, ends, inv, idx, _ = _window_scaffold(
        table, partition_by, order_by
    )
    # tie boundary: any (partition + order) key run changes — the
    # partition-key words are part of the set, so partition starts are
    # boundaries too
    boundary = _change_boundaries(
        sorted_t, [*partition_by, *order_by]
    )

    if kind == "rank":
        # rank = position of the tie group's first row within partition
        group_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
        r = group_start - starts + 1
    elif kind == "dense_rank":
        # count of tie boundaries since the partition start (inclusive)
        cum_b = jnp.cumsum(boundary.astype(jnp.int32))
        cum_at_start = cum_b[jnp.clip(starts, 0, max(n - 1, 0))]
        r = cum_b - cum_at_start + 1
    else:
        raise ValueError(f"unknown rank kind {kind!r}")
    return r.astype(jnp.int32), inv, starts, ends


def rank(table: Table, partition_by: Sequence, order_by: Sequence) -> Column:
    """SQL RANK(): 1-based with gaps after ties (Spark/cudf rank),
    returned in the table's original row order."""
    from .gather import gather_column

    r, inv, _, _ = _rank_sorted(table, partition_by, order_by, "rank")
    return gather_column(Column(r, dt.INT32, None), inv)


def dense_rank(
    table: Table, partition_by: Sequence, order_by: Sequence
) -> Column:
    """SQL DENSE_RANK(): 1-based, no gaps after ties."""
    from .gather import gather_column

    r, inv, _, _ = _rank_sorted(
        table, partition_by, order_by, "dense_rank"
    )
    return gather_column(Column(r, dt.INT32, None), inv)


def percent_rank(
    table: Table, partition_by: Sequence, order_by: Sequence
) -> Column:
    """SQL PERCENT_RANK(): (rank - 1) / (partition rows - 1); 0.0 for
    single-row partitions (Spark semantics)."""
    from .gather import gather_column

    r, inv, starts, ends = _rank_sorted(
        table, partition_by, order_by, "rank"
    )
    size = (ends - starts).astype(jnp.float64)
    pr = jnp.where(
        size > 1, (r - 1).astype(jnp.float64) / jnp.maximum(size - 1, 1), 0.0
    )
    from . import compute

    out_sorted = compute.from_values(pr, dt.FLOAT64, None)
    return gather_column(out_sorted, inv)


def ntile(
    table: Table, partition_by: Sequence, order_by: Sequence, n_tiles: int
) -> Column:
    """SQL NTILE(n): 1-based bucket of each row within its partition,
    larger buckets first when rows don't divide evenly (Spark/cudf)."""
    from .gather import gather_column

    if n_tiles <= 0:
        raise ValueError("ntile: n_tiles must be positive")
    _, starts, ends, inv, idx, _ = _window_scaffold(
        table, partition_by, order_by
    )
    pos = idx - starts  # 0-based position within partition
    size = ends - starts
    base = size // n_tiles
    rem = size % n_tiles
    # first `rem` buckets have base+1 rows
    big_span = rem * (base + 1)
    tile = jnp.where(
        pos < big_span,
        pos // jnp.maximum(base + 1, 1),
        rem + (pos - big_span) // jnp.maximum(base, 1),
    )
    return gather_column(
        Column((tile + 1).astype(jnp.int32), dt.INT32, None), inv
    )
