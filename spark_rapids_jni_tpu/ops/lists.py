"""LIST-column ops over the padded-matrix layout.

The cudf surface the reference artifact ships includes the lists kernel
family (``cudf::explode`` / ``explode_outer`` / ``explode_position``,
``lists::count_elements`` / ``contains`` / ``extract_list_element`` —
SURVEY.md §2.3 relational-ops row; Spark reaches them via ``explode``,
``size``, ``array_contains``, ``element_at``). On the (n, pad) child
matrix + lengths layout these are all gathers and masked comparisons;
explode's data-dependent output size follows the repo's two-phase
discipline: eager APIs host-sync the exact size (the cudf call model),
mirroring filter/join.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column, Table


def _require_list(col: Column):
    if col.dtype.id != dt.TypeId.LIST:
        raise TypeError("expected a LIST column")


def count_elements(col: Column) -> Column:
    """Per-row element count (cudf ``lists::count_elements``; Spark
    ``size``). Null rows are null."""
    _require_list(col)
    return Column(col.lengths.astype(jnp.int32), dt.INT32, col.validity)


def list_contains(col: Column, value) -> Column:
    """True where the row's list contains ``value`` (cudf
    ``lists::contains``; Spark ``array_contains``)."""
    _require_list(col)
    n, pad = col.data.shape
    in_list = jnp.arange(pad)[None, :] < col.lengths[:, None]
    hit = jnp.any((col.data == value) & in_list, axis=1)
    return Column(hit, dt.BOOL8, col.validity)


def extract_list_element(col: Column, index: int) -> Column:
    """Element at ``index`` per row (cudf ``lists::extract_list_element``;
    Spark ``element_at`` is this with 1-based index). Negative indexes
    count from the end; out-of-range rows are null."""
    _require_list(col)
    n, pad = col.data.shape
    idx = jnp.where(index < 0, col.lengths + index, index)
    in_range = (idx >= 0) & (idx < col.lengths)
    vals = jnp.take_along_axis(
        col.data, jnp.clip(idx, 0, pad - 1)[:, None].astype(jnp.int32), axis=1
    )[:, 0]
    validity = (
        in_range if col.validity is None else (col.validity & in_range)
    )
    return Column(vals, col.list_child_dtype, validity)


def _explode_gather(col: Column, outer: bool):
    """Host-synced parent/element index plan for explode (two-phase:
    count, then gather — the filter/join eager discipline)."""
    lens = np.asarray(col.lengths).astype(np.int64)
    valid = (
        np.ones(len(lens), dtype=bool)
        if col.validity is None
        else np.asarray(col.validity)
    )
    lens = np.where(valid, lens, 0)
    if outer:
        # empty/null lists contribute ONE null output row
        slots = np.maximum(lens, 1)
    else:
        slots = lens
    total = int(slots.sum())
    offsets = np.concatenate([[0], np.cumsum(slots)])
    out_idx = np.arange(total)
    parent = np.searchsorted(offsets, out_idx, side="right") - 1
    element = out_idx - offsets[parent]
    # element is in-range except the placeholder row of an empty/null
    # parent under outer semantics
    elem_valid = element < lens[parent]
    return parent.astype(np.int32), element.astype(np.int32), elem_valid


def _explode_table(
    table: Table, column: Union[int, str], outer: bool, position: bool
) -> Table:
    from .join import _resolve_col

    ci = _resolve_col(table, column)
    lcol = table.columns[ci]
    _require_list(lcol)
    parent, element, elem_valid = _explode_gather(lcol, outer)
    parent_j = jnp.asarray(parent)
    element_j = jnp.asarray(element)
    elem_valid_j = jnp.asarray(elem_valid)

    n, pad = lcol.data.shape
    vals = lcol.data[parent_j, jnp.clip(element_j, 0, pad - 1)]
    vals = jnp.where(elem_valid_j, vals, 0)
    child = Column(
        vals,
        lcol.list_child_dtype,
        None if bool(elem_valid.all()) else elem_valid_j,
    )

    out_cols, out_names = [], []
    names = table.names
    for i, c in enumerate(table.columns):
        name = names[i] if names is not None else f"c{i}"
        if i == ci:
            if position:
                pos_validity = (
                    None if bool(elem_valid.all()) else elem_valid_j
                )
                out_cols.append(
                    Column(
                        jnp.where(elem_valid_j, element_j, 0).astype(
                            jnp.int32
                        ),
                        dt.INT32,
                        pos_validity,
                    )
                )
                out_names.append("pos")
            out_cols.append(child)
            out_names.append(name)
        else:
            data = (
                c.data[parent_j]
                if c.data.ndim == 1
                else c.data[parent_j, :]
            )
            validity = (
                c.validity[parent_j] if c.validity is not None else None
            )
            lengths = (
                c.lengths[parent_j] if c.lengths is not None else None
            )
            out_cols.append(Column(data, c.dtype, validity, lengths))
            out_names.append(name)
    return Table(out_cols, out_names if names is not None else None)


def explode(table: Table, column: Union[int, str]) -> Table:
    """Replicate each row once per list element, replacing the LIST
    column with its elements (cudf ``explode``; Spark ``explode`` drops
    empty and null lists)."""
    return _explode_table(table, column, outer=False, position=False)


def explode_outer(table: Table, column: Union[int, str]) -> Table:
    """Like :func:`explode`, but empty/null lists keep one output row
    with a null element (cudf ``explode_outer``)."""
    return _explode_table(table, column, outer=True, position=False)


def explode_position(
    table: Table, column: Union[int, str], outer: bool = False
) -> Table:
    """Explode with a leading ``pos`` INT32 column of element indexes
    (cudf ``explode_position``; Spark ``posexplode``)."""
    return _explode_table(table, column, outer=outer, position=True)
