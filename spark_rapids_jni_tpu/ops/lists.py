"""LIST-column ops over the padded-matrix layout.

The cudf surface the reference artifact ships includes the lists kernel
family (``cudf::explode`` / ``explode_outer`` / ``explode_position``,
``lists::count_elements`` / ``contains`` / ``extract_list_element`` —
SURVEY.md §2.3 relational-ops row; Spark reaches them via ``explode``,
``size``, ``array_contains``, ``element_at``). On the (n, pad) child
matrix + lengths layout these are all gathers and masked comparisons;
explode's data-dependent output size follows the repo's two-phase
discipline: eager APIs host-sync the exact size (the cudf call model),
mirroring filter/join.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from .. import dtype as dt
from ..column import Column, Table


def _require_list(col: Column):
    if col.dtype.id != dt.TypeId.LIST:
        raise TypeError("expected a LIST column")


def count_elements(col: Column) -> Column:
    """Per-row element count (cudf ``lists::count_elements``; Spark
    ``size``). Null rows are null."""
    _require_list(col)
    return Column(col.lengths.astype(jnp.int32), dt.INT32, col.validity)


def list_contains(col: Column, value) -> Column:
    """True where the row's list contains ``value`` (cudf
    ``lists::contains``; Spark ``array_contains``)."""
    _require_list(col)
    n, pad = col.data.shape
    in_list = jnp.arange(pad)[None, :] < col.lengths[:, None]
    hit = jnp.any((col.data == value) & in_list, axis=1)
    return Column(hit, dt.BOOL8, col.validity)


def extract_list_element(col: Column, index: int) -> Column:
    """Element at ``index`` per row (cudf ``lists::extract_list_element``;
    Spark ``element_at`` is this with 1-based index). Negative indexes
    count from the end; out-of-range rows are null."""
    _require_list(col)
    n, pad = col.data.shape
    idx = jnp.where(index < 0, col.lengths + index, index)
    in_range = (idx >= 0) & (idx < col.lengths)
    vals = jnp.take_along_axis(
        col.data, jnp.clip(idx, 0, pad - 1)[:, None].astype(jnp.int32), axis=1
    )[:, 0]
    validity = (
        in_range if col.validity is None else (col.validity & in_range)
    )
    return Column(vals, col.list_child_dtype, validity)


def _replication_plan(slots: np.ndarray):
    """Host-synced (parent, slot-within-parent) plan for exploding
    ``slots[i]`` output rows per input row (two-phase: count, then
    gather — the filter/join eager discipline)."""
    total = int(slots.sum())
    offsets = np.concatenate([[0], np.cumsum(slots)])
    out_idx = np.arange(total)
    parent = np.searchsorted(offsets, out_idx, side="right") - 1
    element = out_idx - offsets[parent]
    return parent.astype(np.int32), element.astype(np.int32)


def _replicate_siblings(table: Table, ci: int, parent_j, new_col: Column,
                        leading: list | None = None):
    """Rebuild a table with row ``parent_j`` replication, the column at
    ``ci`` replaced by ``new_col`` (optionally preceded by ``leading``
    (name, Column) pairs) — shared by the explode family."""
    out_cols, out_names = [], []
    names = table.names
    for i, c in enumerate(table.columns):
        name = names[i] if names is not None else f"c{i}"
        if i == ci:
            for lname, lcol_ in leading or []:
                out_cols.append(lcol_)
                out_names.append(lname)
            out_cols.append(new_col)
            out_names.append(name)
        else:
            data = (
                c.data[parent_j]
                if c.data.ndim == 1
                else c.data[parent_j, :]
            )
            validity = (
                c.validity[parent_j] if c.validity is not None else None
            )
            lengths = (
                c.lengths[parent_j] if c.lengths is not None else None
            )
            out_cols.append(Column(data, c.dtype, validity, lengths))
            out_names.append(name)
    return Table(out_cols, out_names if names is not None else None)


def _explode_gather(col: Column, outer: bool):
    """Explode index plan: (parent, element, element-valid mask)."""
    lens = np.asarray(col.lengths).astype(np.int64)
    valid = (
        np.ones(len(lens), dtype=bool)
        if col.validity is None
        else np.asarray(col.validity)
    )
    lens = np.where(valid, lens, 0)
    # under outer semantics empty/null lists contribute ONE null row
    slots = np.maximum(lens, 1) if outer else lens
    parent, element = _replication_plan(slots)
    elem_valid = element < lens[parent]
    return parent, element, elem_valid


def _explode_table(
    table: Table, column: Union[int, str], outer: bool, position: bool
) -> Table:
    from .join import _resolve_col

    ci = _resolve_col(table, column)
    lcol = table.columns[ci]
    _require_list(lcol)
    parent, element, elem_valid = _explode_gather(lcol, outer)
    parent_j = jnp.asarray(parent)
    element_j = jnp.asarray(element)
    elem_valid_j = jnp.asarray(elem_valid)

    n, pad = lcol.data.shape
    vals = lcol.data[parent_j, jnp.clip(element_j, 0, pad - 1)]
    vals = jnp.where(elem_valid_j, vals, 0)
    child = Column(
        vals,
        lcol.list_child_dtype,
        None if bool(elem_valid.all()) else elem_valid_j,
    )

    leading = []
    if position:
        pos_validity = None if bool(elem_valid.all()) else elem_valid_j
        leading.append((
            "pos",
            Column(
                jnp.where(elem_valid_j, element_j, 0).astype(jnp.int32),
                dt.INT32,
                pos_validity,
            ),
        ))
    return _replicate_siblings(table, ci, parent_j, child, leading)


def explode(table: Table, column: Union[int, str]) -> Table:
    """Replicate each row once per list element, replacing the LIST
    column with its elements (cudf ``explode``; Spark ``explode`` drops
    empty and null lists)."""
    return _explode_table(table, column, outer=False, position=False)


def explode_outer(table: Table, column: Union[int, str]) -> Table:
    """Like :func:`explode`, but empty/null lists keep one output row
    with a null element (cudf ``explode_outer``)."""
    return _explode_table(table, column, outer=True, position=False)


def explode_position(
    table: Table, column: Union[int, str], outer: bool = False
) -> Table:
    """Explode with a leading ``pos`` INT32 column of element indexes
    (cudf ``explode_position``; Spark ``posexplode``)."""
    return _explode_table(table, column, outer=outer, position=True)


def split_explode(
    table: Table, column: Union[int, str], delimiter: str | bytes
) -> Table:
    """Split a string column on a single-byte delimiter and explode the
    tokens to rows in one op — the fused form of Spark's
    ``explode(split(col, d))`` (and of cudf ``strings::split_record`` +
    ``explode``), which sidesteps materializing a LIST<STRING> column
    under the static-shape regime. Null strings produce no rows (Spark
    explode of a null array); empty strings produce one empty token.

    The exploded column keeps its name; sibling columns replicate per
    token. Eager (host-syncs the token total, the cudf call model)."""
    from .join import _resolve_col
    from .strings import _literal_bytes, _require_string

    ci = _resolve_col(table, column)
    scol = table.columns[ci]
    _require_string(scol)
    d = _literal_bytes(delimiter)
    if len(d) != 1:
        raise ValueError("split_explode: single-byte delimiter only")

    n, pad = scol.data.shape
    j = jnp.arange(pad)[None, :]
    in_str = j < scol.lengths[:, None]
    is_delim = (scol.data == d[0]) & in_str
    ntokens = jnp.sum(is_delim.astype(jnp.int32), axis=1) + 1
    valid = (
        np.ones(n, bool)
        if scol.validity is None
        else np.asarray(scol.validity)
    )
    counts = np.where(valid, np.asarray(ntokens), 0).astype(np.int64)
    parent, tok = _replication_plan(counts)
    parent_j = jnp.asarray(parent)
    tok_j = jnp.asarray(tok)

    # token extraction = the shared split_get kernel over the
    # parent-gathered byte matrix with a per-row token index
    from .strings import _extract_token

    tokens = _extract_token(
        scol.data[parent_j], scol.lengths[parent_j], None,
        int(d[0]), tok_j,
    )

    return _replicate_siblings(table, ci, parent_j, tokens)
