"""Plan compiler: whole op chains fused into single cached executables.

``runtime_bridge.table_plan_wire``/``table_plan_resident`` accept a JSON
*list* of ops instead of a single op. This module segments the list into
maximal runs of fusable single-table bucketable ops and compiles each
run into ONE jitted callable cached under a ``(plan signature, schema
signature, bucket)`` key via the same ``utils/buckets.cached_jit`` the
per-op bucketed runners use. Intermediates inside a segment stay traced
values: they never materialize as resident tables, never re-enter
Python, and the whole segment costs one executable launch — the
Weld/Photon-style lazy-fusion step layered on PR 2's shape buckets.

Fusable ops (single-table, bucketable, ``row_valid``-maskable):
``cast``, ``filter``, ``rlike``, ``distinct``, ``sort_by``, ``slice``
(non-negative bounds), and a non-collect ``groupby`` TAIL — a groupby
may close a fused run but not continue it: its output is a fresh
keys+aggregates table and the following ops re-enter the compiler on
the padded result. Everything else (join, concat, explode,
to_rows/from_rows, ...) is a segment boundary dispatched through the
existing per-op ``_dispatch`` path — bucketed runner or exact fallback
— with ``Table.logical_rows`` carried through unchanged so padding
semantics survive the boundary.

Semantics contract: byte-identical to the per-op path (which is itself
byte-identical to the exact path — tests/test_buckets.py). ANY failure
inside a fused segment falls back to per-op replay of that segment, so
op errors surface from the exact path with their real messages —
fusion can change launch counts, never results
(tests/test_plan.py pins both).

Telemetry (``plan.*``, through the metrics registry + flight recorder):
``plan.calls``/``plan.segments``/``plan.fused_segments``/
``plan.fused_ops``/``plan.exact_ops``/``plan.fallbacks``/
``plan.declined`` counters (plus ``plan.mesh_segments``/
``plan.mesh_declined``/``plan.mesh_fallbacks`` when a mesh runner is
offered — see ``parallel/planmesh.py``), a ``plan`` span wrapping each run with one
``plan.segment`` span per segment, ``plan.fallback`` flight instants,
and the ``compile_cache.miss`` instants ``cached_jit`` already emits
(fused executables are named ``srt_fused_plan`` so ``jax.log_compiles``
lines are attributable).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp

from . import dtype as dt
from .column import Column, Table
from .utils import buckets, faults, flight, log, metrics, profiler

# single-table ops a fused segment can carry anywhere in its run
_SIMPLE_FUSABLE = frozenset(
    {"cast", "filter", "rlike", "distinct", "sort_by", "slice"}
)

# mesh exchange boundaries: planmesh splits a plan at these ops into a
# scan-side chain -> counts-sized all-to-all -> merge-side chain, each
# chain still fused under shard_map. On the exact path they run through
# the ordinary per-op dispatch (a stable partition-contiguous reorder).
# Pure literal — the exchange-plane side of the SRT008 parity check:
# every member must also be in runtime_bridge.DISPATCH_OPS,
# _dispatch_impl, and plancheck._RULES.
_EXCHANGE_OPS = frozenset({"partition"})

# fused-segment failures are replayed per-op; warn once per op-chain
# shape (the bucketed._WARNED_OPS discipline), not per call
_WARNED_SIGS = set()

# Donated segments EXPECT partial aliasing: a filter drops its mask
# column and a cast changes a dtype, so some input buffers have no
# same-shaped output to alias and XLA warns per compile. The donation
# of the (dominant) same-schema buffers still lands; the warning is
# noise for this plane and is filtered narrowly. Re-armed per donated
# launch (idempotent: skipped when an equivalent filter is already
# live) because the process filter list is freely reset by embedders
# and per-test by pytest — a one-shot module flag would leak the
# warning everywhere after the first such reset.
_DONATE_WARNING_MSG = "Some donated buffers were not usable"


def _filter_partial_donation_warning() -> None:
    import warnings

    for f in warnings.filters:
        if (
            f[0] == "ignore"
            and f[1] is not None
            and f[1].pattern == _DONATE_WARNING_MSG
        ):
            return
    warnings.filterwarnings("ignore", message=_DONATE_WARNING_MSG)


def op_fusable(op: dict) -> bool:
    """Could this op ride inside a fused segment? (groupby: tail-only,
    see segment_plan). Mirrors ``bucketed.is_bucketable`` plus ``slice``,
    minus multi-table ops."""
    if not isinstance(op, dict):
        return False  # malformed entries fail loudly in run_plan
    name = op.get("op")
    if name in _SIMPLE_FUSABLE:
        if name == "slice":
            # negative bounds raise in the exact path; keep that error
            # surfacing there, not from inside a traced segment
            try:
                start = int(op.get("start", 0))
                stop = op.get("stop")
                return start >= 0 and (stop is None or int(stop) >= 0)
            except (TypeError, ValueError):
                return False
        return True
    if name == "groupby":
        from .ops.groupby import _COLLECT_OPS

        # collect_* needs a data-dependent list-capacity pre-pass the
        # exact path owns (the bucketed-runner decline, applied early)
        return not any(
            a.get("agg") in _COLLECT_OPS for a in op.get("aggs", ())
        )
    return False


def segment_plan(ops: Sequence[dict]) -> List[Tuple[str, list]]:
    """Split a plan into ``[(kind, ops)]`` segments: ``"fused"`` (a run
    of >= 2 fusable ops compiled as one executable) or ``"exact"`` (a
    single op through the per-op dispatch — non-fusable ops, and
    1-op runs, which the per-op bucketed runners already cache under
    their own keys). A groupby is tail-only: it closes the run it ends."""
    segs: List[Tuple[str, list]] = []
    cur: list = []

    def flush():
        nonlocal cur
        if not cur:
            return
        if len(cur) >= 2:
            segs.append(("fused", cur))
        else:
            segs.extend(("exact", [o]) for o in cur)
        cur = []

    for op in ops:
        if op_fusable(op):
            cur.append(op)
            if op.get("op") == "groupby":
                flush()
        else:
            flush()
            segs.append(("exact", [op]))
    flush()
    return segs


# ---------------------------------------------------------------------------
# fused per-op appliers — each runs INSIDE the traced segment, taking
# (op, padded table, device logical count, row_valid occupancy) and
# returning (table at the same physical shape, new device count). The
# occupancy mask is recomputed per step from the flowing count, so a
# filter's clone-padded tail is dead for everything downstream.
# ---------------------------------------------------------------------------


def _fused_cast(op, t, n, rv):
    ci = int(op["column"])
    target = dt.DType(dt.TypeId(op["type_id"]), op.get("scale", 0))
    src = t.columns[ci]
    if src.dtype.is_string or target.is_string:
        from .ops import strings as strings_mod

        out = strings_mod.cast(src, target)
    else:
        from .ops.cast import cast as cast_fn

        out = cast_fn(src, target)
    cols = list(t.columns)
    cols[ci] = out
    return Table(cols, t.names), n


def _fused_filter(op, t, n, rv):
    from .ops.filter import filter_table_capped

    mi = int(op["mask"])
    mask = t.columns[mi]
    # the occupancy gate: padding tails can hold arbitrary garbage
    # (e.g. an upstream capped filter clones kept rows)
    keep = Column(
        jnp.logical_and(mask.data, rv), mask.dtype, mask.validity
    )
    kept = Table(
        [c for i, c in enumerate(t.columns) if i != mi]
    )  # names dropped exactly like the exact-path dispatch
    return filter_table_capped(kept, keep, capacity=t.row_count)


def _fused_rlike(op, t, n, rv):
    from .ops import regex as regex_mod
    from .ops.filter import filter_table_capped

    mask = regex_mod.contains_re(
        t.columns[int(op["column"])], op["pattern"]
    )
    # padding rows are zero-length strings: a pattern matching the
    # empty string would select them without the gate
    keep = Column(
        jnp.logical_and(mask.data, rv), mask.dtype, mask.validity
    )
    return filter_table_capped(t, keep, capacity=t.row_count)


def _fused_distinct(op, t, n, rv):
    from .ops.compaction import distinct_capped

    return distinct_capped(
        t, op.get("keys"), capacity=t.row_count, row_valid=rv
    )


def _fused_sort(op, t, n, rv):
    from .ops.sort import SortKey, sort_table

    ks = [
        SortKey(k["column"], ascending=k.get("ascending", True))
        for k in op["keys"]
    ]
    return sort_table(t, ks, row_valid=rv), n


def _fused_slice(op, t, n, rv):
    from .ops.filter import filter_table_capped

    # exact-path semantics (start/stop clamped to the LOGICAL count)
    # expressed against the device scalar: keep rows [s, e) of the
    # first n, compacted to the front at the same physical shape.
    # Host-side clamp to the physical row count first: n <= row_count,
    # so the clamp is semantics-free and keeps a giant (>= 2^31) but
    # valid bound from overflowing the int32 conversion
    cap = t.row_count
    s = jnp.minimum(jnp.int32(min(int(op.get("start", 0)), cap)), n)
    stop = op.get("stop")
    e = (
        n
        if stop is None
        else jnp.minimum(jnp.int32(min(int(stop), cap)), n)
    )
    e = jnp.maximum(s, e)
    iota = jnp.arange(t.row_count, dtype=jnp.int32)
    keep = jnp.logical_and(iota >= s, iota < e)
    return filter_table_capped(
        t, Column(keep, dt.BOOL8, None), capacity=t.row_count
    )


def _fused_groupby(op, t, n, rv):
    from .ops.groupby import GroupbyAgg, groupby_aggregate_capped

    aggs = [GroupbyAgg(a["column"], a["agg"]) for a in op["aggs"]]
    return groupby_aggregate_capped(
        t, list(op["by"]), aggs, num_segments=t.row_count, row_valid=rv
    )


_FUSED = {
    "cast": _fused_cast,
    "filter": _fused_filter,
    "rlike": _fused_rlike,
    "distinct": _fused_distinct,
    "sort_by": _fused_sort,
    "slice": _fused_slice,
    "groupby": _fused_groupby,
}


def _run_segment_traced(seg_ops: Sequence[dict], t: Table, n):
    """The traced body of one fused segment: thread (table, count)
    through every op at the segment's one physical shape."""
    for op in seg_ops:
        rv = buckets.tail_valid(t.row_count, n)
        t, n = _FUSED[op["op"]](op, t, n, rv)
        if hasattr(n, "astype"):
            n = n.astype(jnp.int32)
    return t, n


def _run_fused(
    seg_ops: Sequence[dict], table: Table, donate: bool = False
) -> Table:
    """One fused segment -> one cached executable -> one launch.

    ``donate=True`` marks the segment's input table as CONSUMED: its
    padded buffers are donated to the executable
    (``buckets.cached_jit(donate_args=(0,))``) so XLA updates HBM in
    place instead of holding input + output simultaneously — the
    resident-chain peak-halving of ISSUE 5. The caller guarantees
    nothing else references the input's buffers (plan-owned
    intermediates, consumed resident ids, freshly decoded wire
    tables). After the call the input arrays are deleted; the
    ``run_plan`` fallback checks for that before attempting a per-op
    replay."""
    from . import bucketed
    from .utils import hbm

    pt = bucketed._padded_input(table)  # _Decline when unbucketable
    key = buckets.cache_key("plan", list(seg_ops), (pt,))

    def build():
        def fn(t, n):
            return _run_segment_traced(seg_ops, t, n)

        return fn

    donate_args = (0,) if donate else ()
    if donate:
        _filter_partial_donation_warning()
    fn = buckets.cached_jit(
        key, build, "srt_fused_plan", donate_args=donate_args
    )
    donated = hbm.table_bytes(pt) if donate else 0
    out, count = fn(bucketed._strip(pt), bucketed._n_dev(pt))
    if donated:
        # counted AFTER the launch: a trace/compile failure falls back
        # to per-op replay with the input intact — nothing was donated
        hbm.note_donation(donated)
    # srt: allow-host-sync(segment boundary: the fused launch is done; the count read is the one sync that sizes the unpadded result)
    return bucketed._finish(out, int(count))


# ops whose output over a row range depends only on the rows in that
# range — the segments the OOM half-batch degradation may legally
# split: run each half, concatenate, and the result is byte-identical.
# sort_by/distinct/groupby/slice are global (cross-row) and must not
# be chunked; they fall back to the exact path instead.
_ROW_LOCAL = frozenset({"cast", "filter", "rlike"})


def _run_chunked(seg_ops: Sequence[dict], table: Table) -> Table:
    """The graceful-degradation path for a ResourceExhausted fused
    segment: split the input at half the rows, run each half through
    the same fused machinery (smaller bucket -> smaller working set),
    and concatenate — parity-safe because every op in the segment is
    row-local (caller-gated on :data:`_ROW_LOCAL`). Returns the exact
    (unpadded) result table; raises faults.ResourceExhausted when the
    input is too small to split."""
    from .ops.copying import concatenate, slice_rows

    t = buckets.unpad_table(table)
    n = int(t.row_count)
    if n < 2:
        raise faults.ResourceExhausted(
            f"segment OOM at {n} row(s): nothing left to split"
        )
    halves = []
    # a _Decline at the half shape propagates: the exact per-op path
    # is the smaller-footprint fallback the caller owns
    for lo, hi in ((0, n // 2), (n // 2, n)):
        part = slice_rows(t, lo, hi)
        halves.append(buckets.unpad_table(_run_fused(seg_ops, part)))
    metrics.counter_add("plan.chunked_segments")
    if flight.enabled():
        flight.record(
            "I", "plan.oom_chunked",
            ",".join(str(o.get("op", "?")) for o in seg_ops),
        )
    return concatenate(halves)


def _run_fused_tolerant(
    seg_ops: Sequence[dict], table: Table, donate: bool
) -> Table:
    """One fused segment with the fault-tolerance contract applied at
    segment granularity:

    * a donated launch that already CONSUMED its input is at-most-once
      (PR 5's doomed-replay rule): its error surfaces as-is, no retry;
    * a ResourceExhausted-classified failure with the input intact
      first asks the spill tier for headroom (utils/spill.py: the
      coldest resident tables demote to host/disk) and retries the
      SAME launch — degrade by moving cold data, not by splitting hot
      work; only when nothing could spill does it retry at half-batch
      chunks (row-local segments only);
    * a transient-classified failure retries the whole segment with
      backoff up to RETRY_MAX (the injection fires BEFORE the launch
      consumes anything, so an injected retry is always safe);
    * anything else propagates to run_plan's per-op replay fallback.
    """
    from . import bucketed

    attempt = 0
    spill_tried = False
    while True:
        faults.check_cancel()
        try:
            faults.inject("dispatch")
            return _run_fused(seg_ops, table, donate=donate)
        except bucketed._Decline:
            raise
        except (faults.Cancelled, faults.DeadlineExceeded):
            raise
        except Exception as e:
            if _input_consumed(table):
                # donated executable failed AFTER consuming its input:
                # retrying (or replaying) would dereference deleted
                # buffers — the worker error is authoritative
                raise
            cls = faults.classify(e)
            if cls is faults.ResourceExhausted and not spill_tried:
                # OOM ladder rung 1: free headroom by spilling cold
                # resident tables, then retry the SAME shape. 2x the
                # input sizes the launch's input + output residency.
                spill_tried = True
                from .utils import hbm, spill

                freed = spill.request_headroom(
                    2 * hbm.table_bytes(table), reason="oom"
                )
                if freed:
                    metrics.counter_add("plan.oom_spill_retries")
                    if flight.enabled():
                        flight.record("I", "plan.oom_spill_retry", freed)
                    continue
            if cls is faults.ResourceExhausted and all(
                o.get("op") in _ROW_LOCAL for o in seg_ops
            ):
                try:
                    return _run_chunked(seg_ops, table)
                # srt: allow-broad-except(chunked-fallback failure defers to the exact path, which owns the original typed error)
                except Exception:
                    raise e  # exact-path fallback owns it from here
            if (
                faults.retryable_class(cls)
                and attempt < faults.retry_max()
            ):
                attempt += 1
                faults.sleep_backoff(
                    attempt, "plan.segment", error=e
                )
                continue
            raise


def _take_rest(op: dict, orig_rest: tuple, queue: list) -> list:
    """Extra input tables for a multi-table fallback op: an explicit
    ``"rest"`` field names indices into the plan call's extra-table
    list; otherwise join/cross_join consume the next unconsumed extra
    table and concat consumes everything left."""
    idxs = op.get("rest")
    if idxs is not None:
        return [orig_rest[int(i)] for i in idxs]
    name = op.get("op")
    if name in ("join", "cross_join"):
        return [queue.pop(0)] if queue else []
    if name == "concat":
        out = list(queue)
        queue.clear()
        return out
    return []


def run_plan(
    ops: Sequence[dict],
    table: Table,
    rest: Sequence[Table] = (),
    donate_input: bool = False,
    mesh_runner=None,
) -> Table:
    """Execute a plan (a list of op dicts) over ``table``; returns the
    final (possibly padded) Table. The chain's flowing table is always
    the FIRST input of every op; ``rest`` supplies extra tables for
    multi-table segment-boundary ops (see ``_take_rest``).

    ``mesh_runner`` (a ``parallel.tolerant.MeshRunner``) offers the
    plan to the mesh data-parallel path first: row-local plans run
    sharded over the runner's mesh with fault-tolerant replay
    (``parallel/planmesh.py``). A plan with no mesh path falls through
    here silently; a mesh whose degradation ladder hits its device
    floor falls back to this single-device exact path (metered as
    ``plan.mesh_fallbacks`` — the serving tier's keep-the-tenant
    guarantee). The mesh path never consumes ``table``, so both
    fallbacks are safe even with ``donate_input=True``.

    ``donate_input=True`` declares ``table`` consumed by this plan —
    nothing else holds its buffers (a wire upload, a resident id the
    caller released) — allowing the FIRST fused segment to donate it.
    Later segments may donate too: the flowing table between segments
    is plan-owned. Because an exact boundary segment's output CAN
    alias its input buffers (a single-table concat returns them
    outright), every donation is additionally gated on the flowing
    table's buffers being disjoint from everything the caller can
    still observe (the undonated input and every ``rest`` table)."""
    from . import bucketed, runtime_bridge

    if not isinstance(ops, (list, tuple)):
        raise TypeError("plan must be a JSON list of op objects")
    if not ops:
        return table
    for op in ops:
        if not isinstance(op, dict) or "op" not in op:
            raise ValueError(f"plan entries must be op objects, got {op!r}")
    if mesh_runner is not None:
        from .parallel import planmesh
        from .utils import hbm

        # the mesh path runs the whole plan as ONE sharded stage, so it
        # gets one whole-plan "mesh" segment for attribution — the
        # plan-stats record of a mesh run carries rows/bytes like the
        # segment loop below does for the exact path
        pseg = profiler.segment_begin(
            0, "mesh", ops, rows_in=int(table.logical_row_count)
        )
        try:
            out = planmesh.run_plan_mesh(ops, table, mesh_runner, rest)
            metrics.counter_add("plan.mesh_segments")
            profiler.segment_end(
                pseg, rows_out=int(out.logical_row_count),
                out_bytes=hbm.table_bytes(out),
            )
            pseg = None
            return out
        except planmesh.MeshUnsupported:
            # not a failure: this plan has no mesh path
            metrics.counter_add("plan.mesh_declined")
            profiler.segment_end(pseg)
            pseg = None
        except faults.Degraded as e:
            # collective failures persisted down to the runner's device
            # floor: the single-device exact path below IS the
            # degradation target — the mesh path never consumed the
            # input, so the replay lineage is intact
            metrics.counter_add("plan.mesh_fallbacks")
            faults.note_error_class(e, "plan.mesh")
            if flight.enabled():
                flight.record("I", "plan.mesh_fallback", str(e)[:160])
            log.log(
                "WARN", "plan", "mesh_degraded_to_exact",
                error=f"{type(e).__name__}: {str(e)[:200]}",
            )
            profiler.segment_end(pseg, fallback=True)
            pseg = None
        finally:
            # an unexpected exception propagates: close the segment so
            # the thread-local binding never leaks past this plan
            if pseg is not None:
                profiler.segment_end(pseg)
    orig_rest = tuple(rest)
    queue = list(orig_rest)
    if buckets.enabled():
        segs = segment_plan(ops)
    else:
        # debugging mode: the whole plan runs per-op on the exact path
        segs = [("exact", [op]) for op in ops]
    metrics.counter_add("plan.calls")
    metrics.counter_add("plan.segments", len(segs))
    owned = bool(donate_input)
    # buffers the CALLER can still observe: a donated segment must
    # never consume these. Ownership flips True after the first
    # segment, but an exact segment's output can ALIAS its input
    # (a single-table concat returns the input buffers outright;
    # unpad_table at the exact row count keeps the same columns), so
    # every donation is additionally gated on buffer disjointness
    # against this set.
    protected: set = set()
    if not donate_input:
        protected.update(_buffer_ids(table))
    for t in orig_rest:
        protected.update(_buffer_ids(t))
    with metrics.span("plan", segments=len(segs), ops=len(ops)):
        for i, (kind, seg_ops) in enumerate(segs):
            faults.check_cancel()  # between-segment checkpoint
            with metrics.span(
                "plan.segment", index=i, kind=kind, ops=len(seg_ops)
            ):
                pseg = profiler.segment_begin(
                    i, kind, seg_ops,
                    rows_in=int(table.logical_row_count),
                )
                fell_back = False
                try:
                    replay = seg_ops
                    if kind == "fused":
                        donate = owned and protected.isdisjoint(
                            _buffer_ids(table)
                        )
                        try:
                            table = _run_fused_tolerant(
                                seg_ops, table, donate=donate
                            )
                            metrics.counter_add("plan.fused_segments")
                            metrics.counter_add(
                                "plan.fused_ops", len(seg_ops)
                            )
                            replay = ()
                        except bucketed._Decline:
                            # not a failure: no bucket for this shape —
                            # the per-op path owns it
                            metrics.counter_add("plan.declined")
                        except (
                            faults.Cancelled, faults.DeadlineExceeded
                        ):
                            # cooperative aborts are not segment
                            # failures: never replayed, never wrapped
                            raise
                        except Exception as e:
                            if _input_consumed(table):
                                # the donated executable failed AFTER
                                # consuming its input: a per-op replay
                                # would dereference deleted buffers —
                                # surface the real error instead
                                raise
                            # fusion must never change semantics: replay
                            # per-op; the exact path raises the real
                            # error if an op itself is at fault
                            fell_back = True
                            metrics.counter_add("plan.fallbacks")
                            names = ",".join(
                                str(o.get("op", "?")) for o in seg_ops
                            )
                            if flight.enabled():
                                flight.record("I", "plan.fallback", names)
                            if names not in _WARNED_SIGS:
                                _WARNED_SIGS.add(names)
                                log.log(
                                    "WARN", "plan",
                                    "fused_segment_failed",
                                    ops=names,
                                    error=(
                                        f"{type(e).__name__}: "
                                        f"{str(e)[:200]}"
                                    ),
                                )
                    for op in replay:
                        table = runtime_bridge._dispatch(
                            op, table, _take_rest(op, orig_rest, queue)
                        )
                        metrics.counter_add("plan.exact_ops")
                finally:
                    if pseg is not None:
                        from .utils import hbm

                        try:
                            ro = int(table.logical_row_count)
                            ob = int(hbm.table_bytes(table))
                        # srt: allow-broad-except(donated-and-failed input has no sizeable buffers; profiling must not mask the real error)
                        except Exception:  # donated-and-failed input
                            ro, ob = 0, 0
                        profiler.segment_end(
                            pseg, rows_out=ro, out_bytes=ob,
                            fallback=fell_back,
                        )
            # every segment output is a fresh plan-owned intermediate:
            # the NEXT fused segment may donate it
            owned = True
    return table


def _buffer_ids(table: Table) -> set:
    """Identities of every device buffer a table holds (aliasing
    check for donation safety)."""
    out = set()
    for c in table.columns:
        out.add(id(c.data))
        if c.validity is not None:
            out.add(id(c.validity))
        if c.lengths is not None:
            out.add(id(c.lengths))
    return out


def _input_consumed(table: Table) -> bool:
    """True when a donated executable already deleted this table's
    buffers (replaying it is impossible)."""
    try:
        return bool(table.columns) and table.columns[0].data.is_deleted()
    # srt: allow-broad-except(backends without is_deleted assume replayable — the conservative donation answer)
    except Exception:
        return False
