#!/usr/bin/env bash
# Restart smoke gate: a durable daemon serving multiple sessions is
# SIGKILLed mid-stream (ISSUE 14). Its successor must replay the
# per-session journals BEFORE accepting traffic: clients reconnect
# with their resume tokens and download BYTE-IDENTICAL tables, replay
# their mutating request ids without re-application, and land their
# plans on a manifest-warmed compile cache — nonzero cache hits, ZERO
# misses across the replayed plans.
#
# Artifacts gate: journal + payload files exist after the kill, the
# restore doc reports every session recovered with zero quarantines
# and zero warm-start failures, clean byes erase the durable state,
# the daemon leaks zero resident tables, and the flight dump merges
# into a Perfetto trace carrying the restore/checkpoint instants.
#
# Runs on the CPU backend so it gates every premerge node — kill -9
# against a laptop process is exactly the crash it rehearses.
set -euxo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export SRT_JAX_PLATFORMS="${SRT_JAX_PLATFORMS:-cpu}"
export SPARK_RAPIDS_TPU_DURABLE=on
export SPARK_RAPIDS_TPU_CHECKPOINT_DIR="$out/ckpt"
export SPARK_RAPIDS_TPU_METRICS=on

# -- life 1: serve multi-session state, then die by SIGKILL -----------
python3 - "$out/state.json" "$out/ready" <<'PY' &
import json
import sys
import threading
import time

import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import serving

state_path, ready_path = sys.argv[1], sys.argv[2]
I64 = int(dt.TypeId.INT64)
F64 = int(dt.TypeId.FLOAT64)
B8 = int(dt.TypeId.BOOL8)

CHAIN = [
    {"op": "filter", "mask": 1},
    {"op": "cast", "column": 0, "type_id": F64},
    {"op": "sort_by", "keys": [{"column": 0}]},
]


def batch(n, seed):
    rng = np.random.default_rng(n + seed)
    k = rng.integers(-500, 500, n, dtype=np.int64)
    m = (k > 0).astype(np.uint8)
    return ([I64, B8], [0, 0], [k.tobytes(), m.tobytes()],
            [None, None], n)


def canon(wire):
    t, s, d, v, n = wire
    return [
        [int(x) for x in t], [int(x) for x in s],
        [None if x is None else bytes(x).hex() for x in d],
        [None if x is None else bytes(x).hex() for x in v], int(n),
    ]


srv = serving.Server(workers=2)
srv.start()
state = {"sessions": []}
clients = []
for i in range(3):
    c = serving.Client(srv.port, name=f"tenant-{i}").connect()
    clients.append(c)
    assert c.resume_token, "durable daemon handed out no resume token"
    doc = {"session": c.session, "token": c.resume_token, "tables": {}}
    up = batch(2048 + 128 * i, seed=i)
    t1 = c.upload(up, req=f"up-{i}")
    doc["tables"][t1] = canon(c.download(t1))
    t2 = c.plan(CHAIN, [t1], req=f"plan-{i}")
    doc["tables"][t2] = canon(c.download(t2))
    doc["replay"] = {"up": [f"up-{i}", t1], "plan": [f"plan-{i}", t2]}
    state["sessions"].append(doc)

# keep a stream in flight so the SIGKILL lands on a HOT daemon — the
# crash the journal exists for, not a quiesced shutdown
streamer = serving.Client(srv.port, name="streamer").connect()
state["streamer"] = {
    "session": streamer.session, "token": streamer.resume_token,
}
with open(state_path, "w") as f:
    json.dump(state, f)


def pound():
    while True:
        streamer.stream(CHAIN, [batch(4096, s) for s in range(4)])


threading.Thread(target=pound, daemon=True).start()
time.sleep(0.2)
open(ready_path, "w").close()
time.sleep(600)  # the shell kill -9s us long before this
PY
life1=$!

for _ in $(seq 300); do
  [ -f "$out/ready" ] && break
  sleep 0.1
done
test -f "$out/ready"
kill -9 "$life1"
wait "$life1" || true

# the crash left durable state behind: journals + table payloads
test -n "$(ls "$out/ckpt"/*.wal)"
test -n "$(ls "$out/ckpt"/*.npz)"

# -- life 2: restore, reconnect, verify ------------------------------
export SPARK_RAPIDS_TPU_TRACE=1
export SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/flight.json"
export SPARK_RAPIDS_TPU_PROFILE=on
python3 - "$out/state.json" <<'PY'
import json
import sys

from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu import serving
from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.utils import metrics

state = json.load(open(sys.argv[1]))
F64 = int(dt.TypeId.FLOAT64)
CHAIN = [
    {"op": "filter", "mask": 1},
    {"op": "cast", "column": 0, "type_id": F64},
    {"op": "sort_by", "keys": [{"column": 0}]},
]


def canon(wire):
    t, s, d, v, n = wire
    return [
        [int(x) for x in t], [int(x) for x in s],
        [None if x is None else bytes(x).hex() for x in d],
        [None if x is None else bytes(x).hex() for x in v], int(n),
    ]


srv = serving.Server(workers=2)
srv.start()
doc = srv.stats()["durability"]
restore = doc["restore"]
# the streamer session held no tables at the kill; it restores too
assert restore["sessions"] >= len(state["sessions"]), restore
assert restore["quarantined"] == {}, restore
assert restore["warm_compiles"] >= 1, restore
assert restore["warm_failures"] == 0, restore

snap = metrics.snapshot()["counters"]
miss0 = snap.get("compile_cache.miss", 0)
hit0 = snap.get("compile_cache.hit", 0)

for sess in state["sessions"]:
    c = serving.Client(
        srv.port, session=sess["session"], resume=sess["token"]
    ).connect()
    # every journaled table survives the crash byte-identical
    for local, want in sess["tables"].items():
        assert canon(c.download(int(local))) == want, (
            f"session {sess['session']} table {local} diverged "
            "across the restart"
        )
    # a replayed mutating request id applies NOTHING new: the daemon
    # answers from the restored idempotency window
    req, t_up = sess["replay"]["up"]
    before = len(sess["tables"])
    assert c.upload(([], [], [], [], 0), req=req) == t_up
    req, t_plan = sess["replay"]["plan"]
    assert c.plan(CHAIN, [t_up], req=req) == t_plan
    stats = next(s for s in srv.stats()["sessions"]
                 if s["session"] == sess["session"])
    assert stats["tables"] == before, (stats, before)
    # a FRESH plan of the same shape lands on the warmed cache
    t_new = c.plan(CHAIN, [t_up], req=req + "-new")
    c.download(t_new)
    c.close()  # clean bye: erases this session's durable state

snap = metrics.snapshot()["counters"]
miss = snap.get("compile_cache.miss", 0) - miss0
hit = snap.get("compile_cache.hit", 0) - hit0
assert miss == 0, f"replayed plans recompiled ({miss} misses)"
assert hit > 0, "replayed plans never touched the warmed cache"
replays = snap.get("serving.idempotent_replays", 0)
assert replays >= 2 * len(state["sessions"]), replays

# the streamer held no tables at the kill; its session restored too —
# a clean bye retires its journal
serving.Client(
    srv.port, session=state["streamer"]["session"],
    resume=state["streamer"]["token"],
).connect().close()

srv.stop()
assert rb.resident_table_count() == 0, "restart leaked resident tables"
assert rb.leak_report() == [], rb.leak_report()
print(
    f"restart driver OK: {restore['sessions']} sessions restored in "
    f"{restore['took_ms']}ms, {restore['warm_compiles']} plans "
    f"warm-compiled, {replays} idempotent replays, {hit} cache hits / "
    "0 misses across replayed plans, byte-identical downloads, "
    "0 leaked tables"
)
PY

# clean byes erased every session's durable state; only the warm-start
# manifest remains for the next restart
leftover="$(ls "$out/ckpt" | grep -v '^manifest\.wal$' || true)"
test -z "$leftover"

# the flight dump merges into a Perfetto trace showing the restore —
# the postmortem view of a crash-recovered daemon
unset SPARK_RAPIDS_TPU_FLIGHT_DUMP SPARK_RAPIDS_TPU_DURABLE \
  SPARK_RAPIDS_TPU_CHECKPOINT_DIR SPARK_RAPIDS_TPU_METRICS \
  SPARK_RAPIDS_TPU_PROFILE
python3 tools/explain.py --merge "$out/flight.json" \
  -o "$out/merged.trace.json" > "$out/merged.txt"
python3 - "$out/merged.trace.json" <<'PY'
import json
import sys

trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
assert events, "empty merged trace"
names = {e["name"].split("/")[-1] for e in events}
assert "restore.done" in names, sorted(names)
assert "restore.session" in names, sorted(names)
print(
    f"restart trace OK: {len(events)} events, restore instants in "
    "the merged Perfetto timeline"
)
PY
