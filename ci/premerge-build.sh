#!/usr/bin/env bash
# Premerge gate — the ci/premerge-build.sh analog: runs on a TPU node,
# gates on accelerator presence (the nvidia-smi gate,
# premerge-build.sh:20), validates the pinned environment, builds the
# native shim with warnings-as-errors, runs the full test suite, the
# multi-chip dry run, and a bench smoke.
#
# Env:
#   REQUIRE_TPU=true|false   fail if no TPU visible (default true on CI)
#   PARALLEL_LEVEL           native build parallelism (default 4)
set -euxo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

# Accelerator gate: the premerge tier needs the real chip the way the
# reference needs a GPU (`nvidia-smi` at premerge-build.sh:20).
if [[ "${REQUIRE_TPU:-true}" == "true" ]]; then
  python3 -c "import jax; ds = jax.devices(); assert ds and ds[0].platform != 'cpu', f'no accelerator: {ds}'; print('devices:', ds)"
fi

build/dependency-check

# Static analysis gate (the compute-sanitizer CI-discipline analog,
# static half): repo-invariant AST passes — env reads outside the
# config plane, broad excepts that bypass the faults taxonomy, hot-path
# env reads, wall clocks in replay-critical modules, retry on donated
# call sites, metric-name conventions, un-tiered bench arms. Exits
# nonzero on any finding not grandfathered in
# tools/srt_check_baseline.json; the one-line summary is the last line.
# SRT008 (dispatch-table/plancheck registry parity) and SRT009 (implicit
# host-sync hazards in hot paths) ride the same gate.
python3 tools/srt_check.py

# Plan-literal gate: every plan literal in the bench arms and smoke
# scripts must tag clean under the plan-time analyzer (the GpuOverrides
# analog) — a driver must never ship a plan the runtime would reject.
python3 tools/plancheck_literals.py bench.py ci/smoke-chaos.sh \
  ci/smoke-chaos-mesh.sh ci/smoke-spill.sh ci/smoke-restart.sh \
  ci/smoke-drift.sh ci/smoke-skew.sh ci/smoke-trace.sh \
  ci/smoke-kernels.sh

# Native build: forced reconfigure on CI (the
# -Dlibcudf.build.configure=true of premerge-build.sh:26).
NATIVE_BUILD_CONFIGURE=true SRT_WERROR=ON \
  CPP_PARALLEL_LEVEL="${PARALLEL_LEVEL:-4}" \
  bash spark-rapids-tpu-runtime/build-native.sh

# Quick tier (CPU-forced inside conftest; op surface + native codec +
# java facade structure). The slow distributed/mesh tier runs nightly;
# premerge covers those paths via the multichip dryrun below, keeping
# the gate's wall-clock bounded as coverage grows (the suite passed
# 600 tests / >1h this round).
python3 -m pytest tests/ -q -m "not slow"

# Multi-chip sharding must compile+run on a virtual 8-device mesh.
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python3 -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"

# Single-chip flagship step compile check.
python3 -c "
from __graft_entry__ import entry
import jax
fn, args = entry()
jax.block_until_ready(jax.jit(fn)(*args))
print('entry OK')
"

# Observability smoke: a tiny bench config with tracing + the flight
# recorder on must leave parseable telemetry artifacts that convert
# into a Perfetto-loadable Chrome trace (the crash-postmortem contract).
bash ci/smoke-observability.sh

# Chaos smoke: a served stream under a seeded fault plan must recover
# byte-identical with nonzero retry counters, the circuit breaker must
# trip and re-close via the background probe, and zero tables may leak.
bash ci/smoke-chaos.sh

# Mesh chaos smoke: a mesh-backed served stream under seeded
# shuffle/collective faults must replay exchanges to byte-identical
# results with nonzero shuffle.retries; persistent collective failure
# must walk the degradation ladder to the floor and fall back to the
# single-device exact path (served, not shed) with zero leaked tables.
bash ci/smoke-chaos-mesh.sh

# Spill smoke: a served stream with a device working set ~2x the
# (shrunk) HBM budget must complete byte-identical by spilling cold
# tables host->disk (zero sheds), re-promote them on re-access, and
# leak zero tables and zero spill files.
bash ci/smoke-spill.sh

# Restart smoke: a durable daemon SIGKILLed mid-stream must restore
# every session from its journals before accepting traffic — clients
# reconnect with resume tokens to byte-identical tables, replayed
# request ids apply nothing new, and replayed plans land on the
# manifest-warmed compile cache with zero misses.
bash ci/smoke-restart.sh

# Drift smoke: every run_plan execution under a stats dir must append
# a CRC-framed per-segment record; a seeded cardinality skew must land
# a typed drift finding; `explain --drift` must render the store as
# predicted-vs-observed percentiles.
bash ci/smoke-drift.sh

# Kernel tier smoke: the static report must tag kernel-eligible ops, a
# KERNELS=on dispatch stream must launch with byte parity vs off, a
# seeded kernel fault must fall back cleanly, and the kernel.<name>
# spans must survive the Perfetto trace merge.
bash ci/smoke-kernels.sh

# Trace smoke: a traced serving request over the 2-device mesh — with
# one client kill -9'd mid-stream — must leave per-process flight
# dumps that tracequery merges into ONE trace (client.rpc + admission
# + queue-wait + compile + per-segment execute + mesh exchange spans,
# one shared trace id across >= 2 processes), and the live `trace`
# command must return the slow-request log + Prometheus exposition.
bash ci/smoke-trace.sh

# Skew smoke: a seeded zipf stream through a plan carrying a
# `partition` op must run on the 8-device mesh byte-identical to the
# exact path; the adaptive splitter must fire (nonzero
# shuffle.skew_splits) and hold the planned max/mean recv ratio under
# SKEW_SPLIT_FACTOR; zero leaked tables; the decision must render as a
# typed DRIFT[skew] finding.
bash ci/smoke-skew.sh

# Bench smoke on whatever device this node has.
python3 bench.py
