#!/usr/bin/env bash
# Kernel tier smoke gate (ISSUE 20): the Pallas kernel tier
# (kernels/registry.py) must hold its whole contract end to end —
#
#   1. the plan-time static report tags kernel-eligible ops (>= 2 ops
#      of a sort/groupby/transpose plan carry a kernel tag, rendered
#      as ~kernel:<name> markers and listed in report["kernel_ops"]);
#   2. a dispatch stream with SPARK_RAPIDS_TPU_KERNELS=on launches
#      kernels (nonzero kernel.launches) and stays byte-identical to
#      the same stream with KERNELS=off;
#   3. a seeded `kernel` chaos fault falls back to the exact path with
#      identical bytes, one metered kernel.fallbacks, and zero leaked
#      resident tables;
#   4. the kernel.<name> spans land on the flight ring and survive the
#      merge into a Perfetto-loadable Chrome trace.
#
# Runs on the CPU backend (interpret=True Pallas) by default so it
# gates every premerge node; set SPARK_RAPIDS_TPU_TEST_PLATFORM /
# JAX_PLATFORMS for an on-chip Mosaic run.
set -euxo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export SRT_JAX_PLATFORMS="${SRT_JAX_PLATFORMS:-cpu}"

# Phase 1: static kernel tagging — the analyzer must tag >= 2 ops of a
# kernel-friendly plan and render the markers
python3 - <<'PY'
from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import plancheck as pc

I64 = dt.TypeId.INT64
PLAN = [
    {"op": "sort_by", "keys": [{"column": 0}]},
    {"op": "groupby", "by": [0], "aggs": [{"column": 1, "agg": "sum"}]},
    {"op": "to_rows"},
]
rep = pc.analyze(
    PLAN, schema=[pc.ColType(I64), pc.ColType(I64)], rows=4096,
)
assert rep["ok"], rep
assert len(rep["kernel_ops"]) >= 2, rep["kernel_ops"]
tags = {e["kernel"] for e in rep["ops"] if e["kernel"]}
assert {"packed_sort", "hash_groupby"} <= tags, tags
txt = pc.render_report(rep)
assert "~kernel:packed_sort" in txt, txt
assert "~kernel:hash_groupby" in txt, txt
print(f"static kernel tagging OK: ops {rep['kernel_ops']} -> {sorted(tags)}")
PY

# Phases 2-4: dispatch parity + counters, seeded-fault fallback, and
# the flight-ring spans (dumped for the trace merge below)
python3 - "$out/flight.json" <<'PY'
import json
import sys

import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu.utils import config, flight, metrics

config.set_flag("METRICS", "1")
config.set_flag("FLIGHT", "1")

I64 = int(dt.TypeId.INT64)
OP_SORT = json.dumps({"op": "sort_by", "keys": [{"column": 0}]})
OP_GROUP = json.dumps(
    {"op": "groupby", "by": [0], "aggs": [{"column": 1, "agg": "sum"},
                                          {"column": 1, "agg": "count"}]}
)
N = 4096

rng = np.random.default_rng(17)
k = rng.integers(-500, 500, N, dtype=np.int64)
v = rng.integers(-100, 100, N, dtype=np.int64)
wire_in = ([I64, I64], [0, 0], [k.tobytes(), v.tobytes()],
           [None, None], N)


def stream():
    t1 = rb.table_op_wire(OP_SORT, *wire_in)
    t2 = rb.table_op_wire(OP_GROUP, *wire_in)
    return t1, t2


# Phase 2: ON vs OFF byte parity with nonzero launches on the ON arm
config.set_flag("KERNELS", "off")
want = stream()
metrics.reset()
config.set_flag("KERNELS", "on")
got = stream()
ctr = metrics.snapshot()["counters"]
assert got == want, "kernel tier changed bytes"
launches = int(ctr.get("kernel.launches", 0))
assert launches >= 2, ctr
assert int(ctr.get("kernel.fallbacks", 0)) == 0, ctr
print(f"kernel parity OK: {launches} launches, 0 fallbacks")

# Phase 3: a seeded kernel fault must fall back byte-identical with
# one metered fallback and zero leaked resident tables
live_before = len(rb._RESIDENT)
config.set_flag("FAULTS", "seed=7,kernel:permanent:1:1")
metrics.reset()
got_faulted = stream()
config.clear_flag("FAULTS")
ctr = metrics.snapshot()["counters"]
assert got_faulted == want, "faulted kernel run changed bytes"
assert int(ctr.get("kernel.fallbacks", 0)) == 1, ctr
assert len(rb._RESIDENT) == live_before, "leaked resident tables"
print("kernel fault fallback OK: byte-identical, 1 fallback, 0 leaks")

path = flight.dump(sys.argv[1])
assert path, "flight dump not written"
PY

# Phase 4: the kernel spans survive the merge into a Chrome trace
test -s "$out/flight.json"
python3 tools/trace2chrome.py "$out/flight.json" -o "$out/trace.json"
python3 - "$out/trace.json" <<'PY'
import json
import sys

trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
spans = [e for e in events if e["ph"] == "X"]
kernel_spans = sorted(
    {e["name"].split("/")[-1] for e in spans
     if e["name"].split("/")[-1].startswith("kernel.")}
)
assert "kernel.packed_sort" in kernel_spans, kernel_spans
assert "kernel.hash_groupby" in kernel_spans, kernel_spans
assert "kernel" in {e["cat"] for e in spans}, "no kernel category"
print(f"kernel trace spans OK: {kernel_spans}")
PY

echo "smoke-kernels: all gates passed"
