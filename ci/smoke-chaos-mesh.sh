#!/usr/bin/env bash
# Mesh chaos smoke gate: a mesh-backed served stream must survive a
# seeded distributed fault plan (ISSUE 15). Phase 1 establishes the
# fault-free baseline: a mesh=8 session streams bucket-edge batches
# byte-identical to the local exact run. Phase 2 arms 10% transient
# faults at the shuffle and collective sites — lineage replay re-runs
# only the failed exchanges and every batch still comes back
# byte-identical, with nonzero shuffle.retries. Phase 3 makes every
# collective launch fail: the MeshRunner ladder walks 8 -> 4 -> 2 -> 1
# (probing each rung), raises typed Degraded at the floor, and the plan
# degrades to the single-device exact path — the tenant is SERVED, not
# shed, and the answer is still byte-identical.
#
# Artifacts gate: the metrics dump carries shuffle.retries /
# mesh.degraded / mesh.exhausted / plan.mesh_fallbacks, the daemon
# leaks ZERO resident tables, and the flight dump merges into a
# Perfetto-loadable trace showing the degradation ladder instants.
#
# Runs on the CPU backend with 8 virtual devices so it gates every
# premerge node — the fault plan is how a laptop rehearses a dying
# TPU slice.
set -euxo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export SRT_JAX_PLATFORMS="${SRT_JAX_PLATFORMS:-cpu}"
export SPARK_RAPIDS_TPU_TRACE=1
export SPARK_RAPIDS_TPU_METRICS_DUMP="$out/metrics.json"
export SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/flight.json"
export SPARK_RAPIDS_TPU_RETRY_BASE_MS=1
# the lock-order detector rides the whole smoke: the ladder's
# degrade-under-lock path is exactly where an inversion would show
export SPARK_RAPIDS_TPU_LOCKCHECK=on

python3 - <<'PY'
import json

import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import parallel
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu import serving
from spark_rapids_jni_tpu.column import Table
from spark_rapids_jni_tpu.utils import config, metrics

I64 = int(dt.TypeId.INT64)
F64 = int(dt.TypeId.FLOAT64)
B8 = int(dt.TypeId.BOOL8)

# row-local chain: eligible for the mesh path at any device count
CHAIN = [
    {"op": "filter", "mask": 1},
    {"op": "cast", "column": 0, "type_id": F64},
]

config.set_flag("BUCKETS", "")


def batch(n, seed):
    rng = np.random.default_rng(n + seed)
    k = rng.integers(-500, 500, n, dtype=np.int64)
    m = (k > 0).astype(np.uint8)
    return ([I64, B8], [0, 0], [k.tobytes(), m.tobytes()],
            [None, None], n)


def norm(wire):
    t, s, d, v, n = wire
    return (
        [int(x) for x in t], [int(x) for x in s],
        [None if x is None else bytes(x) for x in d],
        [None if x is None else bytes(x) for x in v], int(n),
    )


# bucket-edge sizes: padding boundaries are where a wrong gather shows
batches = [batch(n, s) for s, n in enumerate((1023, 1024, 1025))]
want = [
    norm(rb.table_plan_wire(json.dumps(CHAIN), *b)) for b in batches
]

with serving.serve() as srv:
    # -- phase 1: fault-free mesh baseline ----------------------------
    with serving.Client(srv.port, name="mesh-base", mesh=8) as c:
        got = [norm(g) for g in c.stream(CHAIN, batches)]
    assert got == want, "fault-free mesh stream diverged"
    docs = srv.stats()["mesh"]
    assert docs and docs[0]["devices"] == 8, docs

    # -- phase 2: 10% shuffle+collective faults, replay to parity -----
    config.set_flag(
        "FAULTS", "seed=1,shuffle:transient:0.1,collective:transient:0.1"
    )
    with serving.Client(srv.port, name="mesh-chaos", mesh=8) as c:
        for _ in range(4):
            got = [norm(g) for g in c.stream(CHAIN, batches)]
            assert got == want, "mesh stream diverged under faults"
    # the shuffle site lives in the exchange wrappers: drive it direct
    mesh = parallel.make_mesh(8)
    n = 2048
    rng = np.random.default_rng(2)
    t = Table.from_pydict({
        "k": rng.integers(0, 64, n, dtype=np.int64),
        "v": rng.integers(-100, 100, n, dtype=np.int64),
    })
    for _ in range(8):
        out, occ, overflow = parallel.shuffle_table(t, ["k"], mesh)
        assert int(np.asarray(overflow).max()) <= 0
        assert int(np.asarray(occ).sum()) == n, "rows lost under faults"
    c2 = metrics.snapshot()["counters"]
    assert c2.get("faults.injected", 0) > 0, c2
    assert c2.get("shuffle.retries", 0) > 0, c2

    # -- phase 3: persistent collective failure -> ladder -> exact ----
    config.set_flag("FAULTS", "collective:transient:1")
    config.set_flag("RETRY_MAX", "0")
    with serving.Client(srv.port, name="mesh-floor", mesh=8) as c:
        got = [norm(g) for g in c.stream(CHAIN, batches)]
    assert got == want, "degraded-to-exact stream diverged"
    config.set_flag("FAULTS", "")
    config.set_flag("RETRY_MAX", "")

c3 = metrics.snapshot()["counters"]
assert c3.get("mesh.degraded", 0) >= 1, c3
assert c3.get("mesh.exhausted", 0) >= 1, c3
assert c3.get("plan.mesh_fallbacks", 0) >= 1, c3
assert c3.get("plan.mesh_segments", 0) >= 1, c3

assert rb.resident_table_count() == 0, "daemon leaked resident tables"
assert rb.leak_report() == [], rb.leak_report()

from spark_rapids_jni_tpu.utils import lockcheck

lockdoc = lockcheck.assert_clean()
assert lockdoc["acquisitions"] > 0, "lockcheck saw no acquisitions"
print(lockcheck.summary_line())

print(
    f"mesh chaos driver OK: {c3['faults.injected']} faults injected, "
    f"{c3['shuffle.retries']} exchange retries, mesh degraded "
    f"{c3['mesh.degraded']}x to the floor, "
    f"{c3['plan.mesh_fallbacks']} exact-path fallbacks, 0 leaked tables"
)
PY

# the analysis tools below import the package too — drop the dump envs
# so THEIR atexit hooks can't clobber the artifacts under test
unset SPARK_RAPIDS_TPU_FLIGHT_DUMP SPARK_RAPIDS_TPU_METRICS_DUMP \
  SPARK_RAPIDS_TPU_LOCKCHECK

test -s "$out/metrics.json"
test -s "$out/flight.json"
python3 - "$out/metrics.json" <<'PY'
import json
import sys

c = json.load(open(sys.argv[1])).get("counters", {})
assert c.get("shuffle.retries", 0) > 0, c
assert c.get("mesh.degraded", 0) >= 1, c
assert c.get("mesh.exhausted", 0) >= 1, c
assert c.get("plan.mesh_fallbacks", 0) >= 1, c
mesh_counters = {
    k: v for k, v in sorted(c.items())
    if k.split(".")[0] in ("shuffle", "mesh", "plan", "faults")
}
print("mesh chaos metrics dump OK:", mesh_counters)
PY

# the flight dump merges into a Perfetto trace that SHOWS the ladder:
# replay instants per rung, mesh.degraded per halving, mesh.exhausted
# at the floor, and the plan falling back to the exact path
python3 tools/explain.py --merge "$out/flight.json" \
  -o "$out/merged.trace.json" > "$out/merged.txt"
python3 - "$out/merged.trace.json" <<'PY'
import json
import sys

trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
assert events, "empty merged trace"
instants = [e for e in events if e.get("ph") == "i"]
names = {e["name"].split("/")[-1] for e in instants}
assert "mesh.degraded" in names, sorted(names)
assert "mesh.exhausted" in names, sorted(names)
assert "mesh.replay" in names, sorted(names)
assert "plan.mesh_fallback" in names, sorted(names)
print(
    f"mesh chaos trace OK: {len(events)} events, degradation ladder + "
    f"{sum(1 for e in instants if e['name'].endswith('mesh.degraded'))} "
    "degrade instants in the merged Perfetto timeline"
)
PY
