#!/usr/bin/env bash
# Plan-statistics + drift smoke gate (ISSUE 16): every run_plan
# execution under a configured stats dir must append one CRC-framed
# record carrying per-segment observations (rows in/out, bytes, wall
# time, HBM proxy) next to the embedded plan-time prediction; a
# seeded cardinality skew against the accumulated history must raise
# a typed drift finding at append time; and `explain --drift` must
# render the store as per-segment predicted-vs-observed percentiles
# in both human and --json form.
#
# Runs on the CPU backend by default so it gates every premerge node;
# set SPARK_RAPIDS_TPU_TEST_PLATFORM/JAX_PLATFORMS for an on-chip run.
set -euxo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export SRT_JAX_PLATFORMS="${SRT_JAX_PLATFORMS:-cpu}"
export SPARK_RAPIDS_TPU_PLANSTATS_DIR="$out/planstats"

# Phase 1: the same wire plan twice (distinct data seeds). The stats
# hook rides profiler._SessionScope, so the PLANSTATS_DIR flag alone —
# no PROFILE — must be enough to land records.
python3 - <<'PY'
import json

import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import runtime_bridge as rb

I64 = int(dt.TypeId.INT64)
B8 = int(dt.TypeId.BOOL8)
F64 = int(dt.TypeId.FLOAT64)
PLAN = json.dumps([
    {"op": "filter", "mask": 1},
    {"op": "cast", "column": 0, "type_id": F64},
])
N = 600

for seed in (0, 1):
    rng = np.random.default_rng(seed)
    k = rng.integers(-50, 50, N, dtype=np.int64)
    mask = (k > 0).astype(np.uint8)
    rb.table_plan_wire(
        PLAN, [I64, B8], [0, 0], [k.tobytes(), mask.tobytes()],
        [None, None], N,
    )
PY

# one record per execution, each with per-segment observations and the
# embedded static prediction
python3 - "$out/planstats" <<'PY'
import sys

from spark_rapids_jni_tpu.utils import planstats

records = planstats.load(sys.argv[1])
assert len(records) == 2, f"expected 2 records, got {len(records)}"
for r in records:
    assert r["segments"], r
    for s in r["segments"]:
        assert s["calls"] > 0, s
        assert s["rows_in"] > 0, s
        assert s["rows_out"] > 0, s
        assert s["out_bytes"] > 0, s
        assert s["wall_s"] >= 0.0, s
    assert r["pred"]["segments"], r
    assert r["schema"] == "INT64,BOOL8", r
    assert r["bucket"] is not None, r
print(f"planstats store OK: {len(records)} records, "
      f"{len(records[0]['segments'])} segment(s) each")
PY

# Phase 2: seeded cardinality skew. History now holds two runs with
# ~half the rows surviving the filter; an all-pass mask doubles the
# observed rows_out, which must clear the (lowered) drift factor and
# land a typed finding on the record itself.
SPARK_RAPIDS_TPU_DRIFT_ROWS_FACTOR=1.5 python3 - <<'PY'
import json

import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import runtime_bridge as rb

I64 = int(dt.TypeId.INT64)
B8 = int(dt.TypeId.BOOL8)
F64 = int(dt.TypeId.FLOAT64)
PLAN = json.dumps([
    {"op": "filter", "mask": 1},
    {"op": "cast", "column": 0, "type_id": F64},
])
N = 600

rng = np.random.default_rng(7)
k = rng.integers(1, 50, N, dtype=np.int64)  # all positive: mask all-true
mask = (k > 0).astype(np.uint8)
rb.table_plan_wire(
    PLAN, [I64, B8], [0, 0], [k.tobytes(), mask.tobytes()],
    [None, None], N,
)
PY

python3 - "$out/planstats" <<'PY'
import sys

from spark_rapids_jni_tpu.utils import planstats

records = planstats.load(sys.argv[1])
assert len(records) == 3, f"expected 3 records, got {len(records)}"
finds = records[-1].get("drift") or []
kinds = {f["type"] for f in finds}
assert "cardinality" in kinds, (kinds, finds)
card = [f for f in finds if f["type"] == "cardinality"][0]
assert card["segment"] is not None, card
print(f"drift finding OK: {sorted(kinds)} on segment {card['segment']}")
PY

# Phase 3: explain --drift renders the store — per-segment predicted
# bound next to observed p50/p95/max, plus the typed finding — and the
# --json form carries the full report
python3 tools/explain.py --drift "$out/planstats" > "$out/drift.txt"
grep -q "PLAN DRIFT" "$out/drift.txt"
grep -q "rows_out p50/p95/max" "$out/drift.txt"
grep -q "hbm p50/p95/max" "$out/drift.txt"
grep -q "wall p50/p95/max" "$out/drift.txt"
grep -q "pred bound" "$out/drift.txt"
grep -q "DRIFT\[cardinality\]" "$out/drift.txt"

python3 tools/explain.py --drift --json "$out/planstats" > "$out/drift.json"
python3 - "$out/drift.json" <<'PY'
import json
import sys

report = json.load(open(sys.argv[1]))
assert report["records"] == 3, report["records"]
groups = report["groups"]
assert len(groups) == 1, [g["fp"] for g in groups]
g = groups[0]
assert g["runs"] == 3, g["runs"]
assert g["schema"] == "INT64,BOOL8", g
for s in g["segments"]:
    assert s["rows_out"]["n"] == 3, s
    assert s["wall_s"]["n"] == 3, s
    assert s["pred"] is not None, s
kinds = {f["type"] for f in g["findings"]}
assert "cardinality" in kinds, kinds
print(
    f"explain --drift OK: {g['runs']} runs, "
    f"{len(g['segments'])} segment(s), findings={sorted(kinds)}"
)
PY

echo "smoke-drift OK"
