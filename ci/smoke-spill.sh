#!/usr/bin/env bash
# Spill smoke gate: a served query stream whose device working set is
# ~2x the (shrunk) HBM budget must DEGRADE, not die (ISSUE 11). Cold
# resident tables are demoted host->disk under pressure while the
# stream keeps answering BYTE-IDENTICAL batches — zero OverBudget /
# Busy sheds — then every spilled table re-promotes on re-access and
# round-trips exactly.
#
# Artifacts gate: nonzero spill.bytes_out AND spill.bytes_in (the
# stream really evicted and really repaged), disk-tier .npz files
# exist while cold and are GONE afterwards, the daemon leaks zero
# resident tables, and the flight dump merges into a Perfetto trace
# carrying the eviction/repage instants.
#
# Runs on the CPU backend so it gates every premerge node — the shrunk
# budget is how a laptop rehearses HBM pressure.
set -euxo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export SRT_JAX_PLATFORMS="${SRT_JAX_PLATFORMS:-cpu}"
export SPARK_RAPIDS_TPU_TRACE=1
export SPARK_RAPIDS_TPU_METRICS_DUMP="$out/metrics.json"
export SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/flight.json"
export SPARK_RAPIDS_TPU_SPILL=on
export SPARK_RAPIDS_TPU_SPILL_DIR="$out/spill"

python3 - "$out/spill" <<'PY'
import glob
import json
import sys

import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import pipeline
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu import serving
from spark_rapids_jni_tpu.utils import config, hbm, metrics, spill

spill_dir = sys.argv[1]
I64 = int(dt.TypeId.INT64)
F64 = int(dt.TypeId.FLOAT64)
B8 = int(dt.TypeId.BOOL8)

CHAIN = [
    {"op": "filter", "mask": 1},
    {"op": "cast", "column": 0, "type_id": F64},
    {"op": "sort_by", "keys": [{"column": 0}]},
]

config.set_flag("BUCKETS", "")


def batch(n, seed):
    rng = np.random.default_rng(n + seed)
    k = rng.integers(-500, 500, n, dtype=np.int64)
    m = (k > 0).astype(np.uint8)
    return ([I64, B8], [0, 0], [k.tobytes(), m.tobytes()],
            [None, None], n)


def norm(wire):
    t, s, d, v, n = wire
    return (
        [int(x) for x in t], [int(x) for x in s],
        [None if x is None else bytes(x) for x in d],
        [None if x is None else bytes(x) for x in v], int(n),
    )


batches = [batch(4096, s) for s in range(6)]
want = [
    norm(rb.table_plan_wire(json.dumps(CHAIN), *b)) for b in batches
]

# size the COLD set from one probe table, then shrink the budget to
# HALF the working set BEFORE uploading it: each upload past the line
# evicts the coldest predecessor (note_put is the pressure point).
# Host tier takes one table's worth, the rest demotes to disk — all
# three tiers exercised.
probe = rb.table_upload_wire(*batch(1 << 15, 99))
one_table = hbm.table_bytes(rb._RESIDENT[probe])
rb.table_free(probe)
working_set = 12 * one_table
gib = float(1 << 30)
shrunk_gb = (working_set / 2) / (1.0 - hbm.RESERVE_FRACTION) / gib
config.set_flag("HBM_BUDGET_GB", shrunk_gb)
config.set_flag("HOST_SPILL_BUDGET_GB", one_table / gib)

cold_wires = [batch(1 << 15, 100 + s) for s in range(12)]
cold_ids = [rb.table_upload_wire(*w) for w in cold_wires]

# -- phase 1: served stream under pressure — degrade, don't die -------
with serving.serve() as srv:
    with serving.Client(srv.port, name="pressure") as c:
        got = [norm(g) for g in c.stream(CHAIN, batches)]
    assert got == want, "served results diverged under HBM pressure"
    doc = srv.stats()
    assert doc["spill"]["enabled"], doc["spill"]
stats = spill.stats_doc()
assert stats["host_bytes"] + stats["disk_bytes"] > 0, stats
assert stats["disk_bytes"] > 0, stats
pipeline.drain_io()  # demotion writes ride the async IO lane
assert glob.glob(spill_dir + "/*.npz"), "disk tier left no files"

c = metrics.snapshot()
assert c["counters"].get("spill.evictions", 0) > 0, c["counters"]
assert c["counters"].get("spill.demotions", 0) > 0, c["counters"]
assert c["bytes"].get("spill.bytes_out", 0) > 0, c["bytes"]
# graceful degradation means ZERO sheds for a host+disk-fitting load
assert c["counters"].get("serving.over_budget", 0) == 0, c["counters"]
assert c["counters"].get("serving.shed", 0) == 0, c["counters"]

# -- phase 2: re-access re-promotes every cold table byte-identical ---
for w, tid in zip(cold_wires, cold_ids):
    assert norm(rb.table_download_wire(tid)) == norm(w), (
        "spilled table diverged after repage"
    )
c = metrics.snapshot()
assert c["counters"].get("spill.repages", 0) > 0, c["counters"]
assert c["bytes"].get("spill.bytes_in", 0) > 0, c["bytes"]

for tid in cold_ids:
    rb.table_free(tid)
assert rb.resident_table_count() == 0, "daemon leaked resident tables"
assert rb.leak_report() == [], rb.leak_report()
assert spill.spill_file_count() == 0, "spill backing leaked"
assert glob.glob(spill_dir + "/*.npz") == [], "leftover spill files"

c = metrics.snapshot()["counters"]
print(
    f"spill driver OK: working set {working_set} B over a "
    f"{int(shrunk_gb * gib)} B budget, {c['spill.evictions']} "
    f"evictions / {c['spill.demotions']} demotions / "
    f"{c['spill.repages']} repages, byte-identical stream, 0 sheds, "
    "0 leaked tables, 0 leftover files"
)
PY

# the analysis tools below import the package too — drop the dump envs
# so THEIR atexit hooks can't clobber the artifacts under test
unset SPARK_RAPIDS_TPU_FLIGHT_DUMP SPARK_RAPIDS_TPU_METRICS_DUMP \
  SPARK_RAPIDS_TPU_SPILL SPARK_RAPIDS_TPU_SPILL_DIR

# both artifacts exist, parse, and the metrics dump carries the spill
# counters the driver asserted in-process
test -s "$out/metrics.json"
test -s "$out/flight.json"
python3 - "$out/metrics.json" <<'PY'
import json
import sys

m = json.load(open(sys.argv[1]))
c, b = m.get("counters", {}), m.get("bytes", {})
assert c.get("spill.evictions", 0) > 0, c
assert c.get("spill.repages", 0) > 0, c
assert b.get("spill.bytes_out", 0) > 0, b
assert b.get("spill.bytes_in", 0) > 0, b
spill_counters = {
    k: v for k, v in sorted({**c, **b}.items())
    if k.startswith("spill.")
}
print("spill metrics dump OK:", spill_counters)
PY

# the flight dump merges into a Perfetto trace showing the eviction /
# repage instants — the postmortem view of a memory-pressured daemon
python3 tools/explain.py --merge "$out/flight.json" \
  -o "$out/merged.trace.json" > "$out/merged.txt"
python3 - "$out/merged.trace.json" <<'PY'
import json
import sys

trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
assert events, "empty merged trace"
instants = [e for e in events if e.get("ph") == "i"]
names = {e["name"].split("/")[-1] for e in instants}
assert "spill.out" in names, sorted(names)
assert "spill.in" in names, sorted(names)
print(
    f"spill trace OK: {len(events)} events, "
    f"{sum(1 for e in instants if e['name'].endswith('spill.out'))} "
    "eviction instants in the merged Perfetto timeline"
)
PY
