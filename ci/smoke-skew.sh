#!/usr/bin/env bash
# Shuffle-as-a-plan-op + adaptive skew smoke gate (ISSUE 17): a seeded
# zipf stream through a plan carrying a `partition` op must run on the
# 8-device CPU mesh byte-identical to the single-device exact path;
# the adaptive skew splitter must fire on the zipf groupby (nonzero
# `shuffle.skew_splits`) and bring the planned max/mean destination
# recv ratio under SKEW_SPLIT_FACTOR; the run must leak zero resident
# tables; and `explain --drift` over the planstats store must render
# the split decision as a typed DRIFT[skew] finding.
#
# Runs on the CPU backend (forced 8-way host platform) so it gates
# every premerge node.
set -euxo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export SRT_JAX_PLATFORMS="${SRT_JAX_PLATFORMS:-cpu}"
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
export SPARK_RAPIDS_TPU_PLANSTATS_DIR="$out/planstats"
export SPARK_RAPIDS_TPU_METRICS=1

# Phase 1: partition as a plan op — mesh vs exact byte parity at the
# shard boundary sizes, with row-local chains fused on BOTH sides of
# the exchange. Phase 2: the adaptive splitter on the skewed groupby.
# Both phases run in one process so the leak check at the end covers
# the whole plane.
python3 - <<'PY'
import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import plan as plan_mod
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu.column import Table
from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg
from spark_rapids_jni_tpu.parallel import distributed_groupby, make_mesh
from spark_rapids_jni_tpu.parallel.tolerant import MeshRunner
from spark_rapids_jni_tpu.utils import config, metrics, profiler

F64 = int(dt.TypeId.FLOAT64)
PLAN = [
    {"op": "filter", "mask": 2},
    {"op": "partition", "kind": "hash", "keys": [0], "num": 16},
    {"op": "cast", "column": 0, "type_id": F64},
]


def _view(t):
    n = int(t.logical_row_count)
    cols = []
    for c in t.columns:
        data = np.asarray(c.data)
        cols.append((
            str(data.dtype), data[:n].tolist(),
            None if c.validity is None
            else np.asarray(c.validity)[:n].tolist(),
        ))
    return (n, cols)


runner = MeshRunner(8)
for n in (1023, 1024, 1025):
    rng = np.random.default_rng(n)
    k = np.minimum(rng.zipf(1.3, n), 100_000).astype(np.int64)
    v = rng.integers(-100, 100, n, dtype=np.int64)
    m = rng.integers(0, 3, n, dtype=np.int64) > 0
    t = Table.from_pydict({"k": k, "v": v, "m": m})
    schema = "int64,int64,bool8"
    with profiler.profile_session(PLAN, label="smoke-skew", schema=schema):
        got = plan_mod.run_plan(PLAN, t, mesh_runner=runner)
    want = plan_mod.run_plan(PLAN, t)
    assert _view(got) == _view(want), f"mesh/exact divergence at n={n}"
print("partition plan parity OK at 1023/1024/1025")

# Phase 2: zipf(1.3) groupby at 200k rows — hot key concentration must
# trip the splitter, and the planned post-split recv max/mean must be
# under the factor.
config.set_flag("SKEW_SPLIT", "1")
n = 200_000
rng = np.random.default_rng(7)
k = np.minimum(rng.zipf(1.3, n), 100_000).astype(np.int64)
v = rng.integers(-100, 100, n, dtype=np.int64)
t = Table.from_pydict({"k": k, "v": v})
mesh = make_mesh(8)
aggs = [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")]
GROUPBY_PLAN = [{
    "op": "groupby", "by": [0],
    "aggs": [{"column": 1, "agg": "sum"}, {"column": 1, "agg": "count"}],
}]
with profiler.profile_session(
    GROUPBY_PLAN, label="smoke-skew-groupby", schema="int64,int64",
):
    agg, ngroups, overflow = distributed_groupby(t, ["k"], aggs, mesh)
assert int(np.asarray(overflow).max()) <= 0
total_groups = int(np.asarray(ngroups).sum())
assert total_groups == len(np.unique(k)), total_groups

snap = metrics.snapshot()
splits = int(snap["counters"].get("shuffle.skew_splits", 0))
assert splits > 0, f"adaptive splitter never fired: {snap['counters']}"
factor = float(config.get_flag("SKEW_SPLIT_FACTOR"))
ratio_g = snap["gauges"].get("shuffle.skew_post_ratio_x100")
assert ratio_g is not None, snap["gauges"]
post_ratio = float(ratio_g["value"]) / 100.0
assert post_ratio < factor, (
    f"post-split recv ratio {post_ratio:.2f}x >= factor {factor}"
)
print(f"skew split OK: splits={splits}, post max/mean={post_ratio:.2f}x "
      f"(factor {factor})")

# zero leaked resident tables across both phases
leaked = rb.resident_table_count()
assert leaked == 0, f"{leaked} resident table(s) leaked"
print("leak check OK: 0 resident tables")
PY

# Phase 3: the split decision must surface as a typed skew finding in
# the drift report, and the exchange counters must render.
python3 tools/explain.py --drift "$out/planstats" > "$out/drift.txt"
grep -q "DRIFT\[skew\]" "$out/drift.txt"
grep -q "shuffle.skew_splits" "$out/drift.txt"

python3 - "$out/planstats" <<'PY'
import sys

from spark_rapids_jni_tpu.utils import planstats

records = planstats.load(sys.argv[1])
finds = [f for r in records for f in (r.get("drift") or [])]
kinds = {f["type"] for f in finds}
assert "skew" in kinds, (kinds, finds)
skew = [f for f in finds if f["type"] == "skew"]
assert any("split" in (f.get("detail") or "") for f in skew), skew
print(f"drift findings OK: {sorted(kinds)}, {len(skew)} skew finding(s)")
PY

echo "smoke-skew OK"
