#!/usr/bin/env bash
# Deploy artifacts — the ci/deploy.sh analog (ci/deploy.sh:32-76):
# package the per-platform jar + python wheel, optionally GPG-sign, and
# publish to the configured repository. Platform classifiers replace the
# reference's per-CUDA classifiers (cuda11 -> v5e/v5p/v4).
#
# Args:    SIGN_FILE (true|false)
# Env:     CLASSIFIERS (default "v5e"), SERVER_ID, SERVER_URL,
#          GPG_PASSPHRASE (when signing)
#
# No -x: signing runs here, and xtrace would echo secret-bearing
# command lines into the build log (Actions masking is best-effort).
set -euo pipefail

SIGN_FILE="${1:-false}"
CLASSIFIERS="${CLASSIFIERS:-v5e}"

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

version="$(grep -m1 -o '<version>[^<]*</version>' pom.xml | sed 's/<[^>]*>//g')"
out="$repo/dist"
mkdir -p "$out"

# Python wheel of the compute stack.
python3 -m pip wheel --no-deps --wheel-dir "$out" . || \
  python3 setup.py bdist_wheel --dist-dir "$out" || true

# Per-platform jars (requires a JDK + maven node; premerge built them).
IFS=',' read -ra classifiers <<< "$CLASSIFIERS"
for cls in "${classifiers[@]}"; do
  jar="spark-rapids-tpu-jni/target/rapids-4-spark-tpu-${version}-${cls}.jar"
  if [[ -f "$jar" ]]; then
    cp "$jar" "$out/"
    if [[ "$SIGN_FILE" == "true" ]]; then
      # passphrase over fd 3, never argv (argv is visible in /proc)
      gpg --batch --yes --pinentry-mode loopback --passphrase-fd 3 \
        --detach-sign --armor "$out/$(basename "$jar")" \
        3<<<"$GPG_PASSPHRASE"
    fi
  else
    echo "WARNING: $jar not built; skipping classifier $cls"
  fi
done

if [[ -n "${SERVER_URL:-}" ]]; then
  # ci/settings.xml wires a central mirror from MAVEN_MIRROR_URL; only
  # pass it when that variable is set, or the unresolved placeholder
  # would break every dependency download
  mvn ${MAVEN_MIRROR_URL:+-s ci/settings.xml} deploy -DskipTests \
    -DaltDeploymentRepository="${SERVER_ID}::default::${SERVER_URL}"
fi

ls -l "$out"
