#!/usr/bin/env bash
# Chaos smoke gate: a served query stream must survive a seeded fault
# plan (ISSUE 10). Phase 1 streams batches through the serving daemon
# with 10% transient faults injected at the dispatch and serde sites —
# every batch must come back BYTE-IDENTICAL to the local run and the
# retry counters must be nonzero. Phase 2 trips the serving circuit
# breaker (consecutive serve_accept transients), watches the typed
# Degraded shed, then clears the fault plan and waits for the
# BACKGROUND probe to close the breaker with no client traffic.
#
# Artifacts gate: the metrics dump carries retry.attempts /
# faults.injected / breaker.opened / breaker.closed, the daemon leaks
# ZERO resident tables, and the flight dump merges into a
# Perfetto-loadable trace showing the breaker state transitions.
#
# Runs on the CPU backend by default so it gates every premerge node —
# the fault plan is how a laptop rehearses a dying TPU.
set -euxo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export SRT_JAX_PLATFORMS="${SRT_JAX_PLATFORMS:-cpu}"
export SPARK_RAPIDS_TPU_TRACE=1
export SPARK_RAPIDS_TPU_PROFILE=on
export SPARK_RAPIDS_TPU_METRICS_DUMP="$out/metrics.json"
export SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/flight.json"
# the seeded chaos plan under test: 10% transient faults at the device
# dispatch and wire-serde boundaries (deterministic per seed, so this
# gate never flakes), fast backoff, a hair-trigger breaker
export SPARK_RAPIDS_TPU_FAULTS="seed=1,dispatch:transient:0.1,serde:transient:0.1"
export SPARK_RAPIDS_TPU_RETRY_BASE_MS=1
export SPARK_RAPIDS_TPU_BREAKER_THRESHOLD=2
export SPARK_RAPIDS_TPU_BREAKER_PROBE_S=0.2
# dynamic lock-order detector rides the whole smoke (the racecheck
# half of the srt-check CI discipline): every tracked lock records the
# acquisition-order graph; the driver fails on any cycle or inversion
# of the sanctioned registry->session->scheduler->spill order
export SPARK_RAPIDS_TPU_LOCKCHECK=on

python3 - <<'PY'
import json
import time

import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu import serving
from spark_rapids_jni_tpu.utils import config, faults, metrics

I64 = int(dt.TypeId.INT64)
F64 = int(dt.TypeId.FLOAT64)
B8 = int(dt.TypeId.BOOL8)

CHAIN = [
    {"op": "filter", "mask": 1},
    {"op": "cast", "column": 0, "type_id": F64},
    {"op": "sort_by", "keys": [{"column": 0}]},
]

config.set_flag("BUCKETS", "")


def batch(n, seed):
    rng = np.random.default_rng(n + seed)
    k = rng.integers(-500, 500, n, dtype=np.int64)
    m = (k > 0).astype(np.uint8)
    return ([I64, B8], [0, 0], [k.tobytes(), m.tobytes()],
            [None, None], n)


def norm(wire):
    t, s, d, v, n = wire
    return (
        [int(x) for x in t], [int(x) for x in s],
        [None if x is None else bytes(x) for x in d],
        [None if x is None else bytes(x) for x in v], int(n),
    )


batches = [batch(4096, s) for s in range(6)]
# the local runs recover from the same armed fault plan, so parity
# below proves recovery on BOTH sides of the wire
want = [
    norm(rb.table_plan_wire(json.dumps(CHAIN), *b)) for b in batches
]

# -- phase 1: served stream under 10% transient faults ----------------
with serving.serve() as srv:
    with serving.Client(srv.port, name="chaos") as c:
        got = [norm(g) for g in c.stream(CHAIN, batches)]
    assert got == want, "served results diverged under injected faults"

    # -- phase 2: trip the breaker, shed typed, recover via probe -----
    config.set_flag("FAULTS", "serve_accept:transient:1")
    with serving.Client(srv.port, name="tripper") as c:
        for _ in range(2):
            try:
                c.stream(CHAIN, batches[:1])
                raise AssertionError("injected fault did not surface")
            except serving.ServingTransientError:
                pass
        try:
            c.stream(CHAIN, batches[:1])
            raise AssertionError("open breaker did not shed")
        except serving.ServingDegraded:
            pass
        assert srv.stats()["breaker"]["state"] == faults.OPEN
        # device "recovers": only the background probe closes it
        config.set_flag("FAULTS", "")
        deadline = time.perf_counter() + 30
        while srv.breaker.state != faults.CLOSED:
            assert time.perf_counter() < deadline, "breaker stuck open"
            time.sleep(0.05)
        got = [norm(g) for g in c.stream(CHAIN, batches[:1])]
        assert got == want[:1], "post-recovery stream diverged"

assert rb.resident_table_count() == 0, "daemon leaked resident tables"
assert rb.leak_report() == [], rb.leak_report()

# lock-order gate: the retrying, breaker-tripping, multi-threaded run
# above is exactly the interleaving soup where an inversion would show
from spark_rapids_jni_tpu.utils import lockcheck

lockdoc = lockcheck.assert_clean()
assert lockdoc["acquisitions"] > 0, "lockcheck saw no acquisitions"
print(lockcheck.summary_line())

c = metrics.snapshot()["counters"]
assert c.get("retry.attempts", 0) > 0, c
assert c.get("faults.injected", 0) > 0, c
assert c.get("breaker.opened", 0) >= 1, c
assert c.get("breaker.closed", 0) >= 1, c
print(
    f"chaos driver OK: {c['faults.injected']} faults injected, "
    f"{c['retry.attempts']} retries, breaker opened "
    f"{c['breaker.opened']}x / closed {c['breaker.closed']}x, "
    "0 leaked tables"
)
PY

# the analysis tools below import the package too — drop the dump envs
# so THEIR atexit hooks can't clobber the artifacts under test
unset SPARK_RAPIDS_TPU_PROFILE SPARK_RAPIDS_TPU_FLIGHT_DUMP \
  SPARK_RAPIDS_TPU_METRICS_DUMP SPARK_RAPIDS_TPU_FAULTS \
  SPARK_RAPIDS_TPU_LOCKCHECK

# both artifacts exist, parse, and the metrics dump carries the
# fault-plane counters the driver asserted in-process
test -s "$out/metrics.json"
test -s "$out/flight.json"
# the flight dump's lockcheck exit section is the crash postmortem a
# hang-to-SIGKILL run would leave behind — it must carry the graph
python3 - "$out/flight.json" <<'PY'
import json
import sys

sec = json.load(open(sys.argv[1]))["sections"]["lockcheck"]
assert sec["enabled"] is True, sec
assert sec["acquisitions"] > 0, sec
assert sec["cycles"] == [], sec
assert sec["order_violations"] == [], sec
print(
    f"lockcheck flight section OK: {sec['acquisitions']} acquisitions, "
    f"{len(sec['edges'])} edges, 0 cycles, 0 order violations"
)
PY
python3 - "$out/metrics.json" <<'PY'
import json
import sys

c = json.load(open(sys.argv[1])).get("counters", {})
assert c.get("retry.attempts", 0) > 0, c
assert c.get("faults.injected", 0) > 0, c
assert c.get("breaker.opened", 0) >= 1, c
assert c.get("breaker.closed", 0) >= 1, c
fault_counters = {
    k: v for k, v in sorted(c.items())
    if k.split(".")[0] in ("faults", "retry", "breaker")
}
print("chaos metrics dump OK:", fault_counters)
PY

# the flight dump merges into a Perfetto trace that SHOWS the breaker
# walking open -> (half-open) -> closed, plus the injection/retry
# instants — the postmortem view of a degraded daemon
python3 tools/explain.py --merge "$out/flight.json" \
  -o "$out/merged.trace.json" > "$out/merged.txt"
python3 - "$out/merged.trace.json" <<'PY'
import json
import sys

trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
assert events, "empty merged trace"
instants = [e for e in events if e.get("ph") == "i"]
names = {e["name"].split("/")[-1] for e in instants}
assert "breaker.opened" in names, sorted(names)
assert "breaker.closed" in names, sorted(names)
assert "fault.injected" in names, sorted(names)
assert "retry" in names, sorted(names)
print(
    f"chaos trace OK: {len(events)} events, breaker transitions + "
    f"{sum(1 for e in instants if e['name'].endswith('fault.injected'))} "
    "injection instants in the merged Perfetto timeline"
)
PY
