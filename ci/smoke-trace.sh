#!/usr/bin/env bash
# Trace-context smoke gate: one serving request over the 2-device mesh
# with tracing on must leave per-process flight dumps that
# tools/tracequery.py merges into a SINGLE trace — client.rpc from the
# client process; admission, queue-wait, compile, per-segment execute
# and mesh exchange spans from the daemon process — all sharing the
# request's W3C-style trace id (ISSUE 18).
#
# Chaos half: a second client is kill -9'd mid-stream. Its flight dump
# never lands (SIGKILL skips atexit — that dump is the casualty), the
# daemon must keep serving, and tracequery must merge the SURVIVING
# dumps into the complete server -> session -> mesh trace.
#
# Live plane: the `trace` serving command must return the tail-sampled
# slow-request log (entries carrying the trace id + span detail) and a
# non-empty Prometheus text exposition of the metrics snapshot.
#
# Runs on the CPU backend with 2 virtual devices so it gates every
# premerge node.
set -euxo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=2}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export SRT_JAX_PLATFORMS="${SRT_JAX_PLATFORMS:-cpu}"
export SPARK_RAPIDS_TPU_TRACE=1
export SPARK_RAPIDS_TPU_METRICS=1

# -- daemon process: its own flight dump ------------------------------
SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/daemon-flight.json" \
python3 - "$out/port" "$out/stop" <<'PY' &
import os
import sys
import time

from spark_rapids_jni_tpu import serving

port_path, stop_path = sys.argv[1], sys.argv[2]
srv = serving.Server(workers=2)
srv.start()
with open(port_path + ".tmp", "w") as f:
    f.write(str(srv.port))
os.rename(port_path + ".tmp", port_path)  # atomic: readers never race
for _ in range(1200):
    if os.path.exists(stop_path):
        break
    time.sleep(0.1)
srv.stop()
PY
daemon=$!

for _ in $(seq 300); do
  [ -f "$out/port" ] && break
  sleep 0.1
done
test -f "$out/port"
port="$(cat "$out/port")"

# -- victim client: killed -9 mid-stream over the mesh ----------------
SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/victim-flight.json" \
python3 - "$port" "$out/victim-ready" <<'PY' &
import sys
import time

import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import serving

port, ready_path = int(sys.argv[1]), sys.argv[2]
I64 = int(dt.TypeId.INT64)
F64 = int(dt.TypeId.FLOAT64)
CHAIN = [
    {"op": "filter", "mask": 1},
    {"op": "cast", "column": 0, "type_id": F64},
]


def batch(n, seed):
    rng = np.random.default_rng(n + seed)
    k = rng.integers(-500, 500, n, dtype=np.int64)
    m = (k > 0).astype(np.uint8)
    return ([I64, int(dt.TypeId.BOOL8)], [0, 0],
            [k.tobytes(), m.tobytes()], [None, None], n)


c = serving.Client(port, name="victim", mesh=2).connect()
batches = [batch(4096, s) for s in range(4)]
c.stream(CHAIN, batches)
open(ready_path, "w").close()
while True:  # the shell kill -9s us mid-stream
    c.stream(CHAIN, batches)
    time.sleep(0.01)
PY
victim=$!

for _ in $(seq 300); do
  [ -f "$out/victim-ready" ] && break
  sleep 0.1
done
test -f "$out/victim-ready"
kill -9 "$victim"
wait "$victim" || true

# SIGKILL skips atexit: the victim's dump is the one that does NOT
# survive — tracequery must work from the remaining two
test ! -s "$out/victim-flight.json"

# -- clean client: ONE traced request over the mesh + the live plane --
SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/client-flight.json" \
python3 - "$port" "$out/trace_id" <<'PY'
import json
import sys

import numpy as np

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import serving
from spark_rapids_jni_tpu.utils import tracing

port, tid_path = int(sys.argv[1]), sys.argv[2]
I64 = int(dt.TypeId.INT64)
F64 = int(dt.TypeId.FLOAT64)
B8 = int(dt.TypeId.BOOL8)
# two plans under ONE trace: the row-local chain runs sharded over the
# mesh (mesh.stage / plan.mesh exchange spans); the sort chain declines
# the mesh and runs exact, paying a fresh cached_jit compile
# (compile.jit) with per-segment execute spans (plan.segment)
MESH_CHAIN = [
    {"op": "filter", "mask": 1},
    {"op": "cast", "column": 0, "type_id": F64},
]
SORT_CHAIN = MESH_CHAIN + [{"op": "sort_by", "keys": [{"column": 0}]}]


def batch(n, seed):
    rng = np.random.default_rng(n + seed)
    k = rng.integers(-500, 500, n, dtype=np.int64)
    m = (k > 0).astype(np.uint8)
    return ([I64, B8], [0, 0], [k.tobytes(), m.tobytes()],
            [None, None], n)


c = serving.Client(port, name="traced", mesh=2).connect()
# the daemon survived the victim's SIGKILL and still serves
ctx = tracing.new_context()  # the one mint — this id spans the fleet
with tracing.activate(ctx):
    got = c.stream(MESH_CHAIN, [batch(2048, 7), batch(2049, 8)])
    got2 = c.stream(SORT_CHAIN, [batch(1536, 9)])
assert len(got) == 2 and len(got2) == 1, (len(got), len(got2))

# live introspection plane: slow-request log + Prometheus exposition
doc = c.trace()
assert set(doc) >= {"slow_requests", "prometheus", "slo_ms", "topk"}, doc
labels = {r["label"] for r in doc["slow_requests"]}
assert any("stream" in lbl for lbl in labels), labels
traced = [r for r in doc["slow_requests"]
          if r.get("trace_id") == ctx.trace_id]
assert traced, (ctx.trace_id, doc["slow_requests"])
prom = doc["prometheus"]
assert "# TYPE" in prom and "srt_" in prom, prom[:200]
c.close()

with open(tid_path, "w") as f:
    f.write(ctx.trace_id)
print("traced request OK:", ctx.trace_id)
PY

tid="$(cat "$out/trace_id")"

# -- stop the daemon: its atexit flight dump lands --------------------
touch "$out/stop"
wait "$daemon"
test -s "$out/daemon-flight.json"
test -s "$out/client-flight.json"

# the analysis tool below imports the package too — drop the dump envs
# so ITS atexit hooks can't clobber the artifacts under test
unset SPARK_RAPIDS_TPU_FLIGHT_DUMP

# -- merge the surviving dumps: ONE trace, two processes --------------
python3 tools/tracequery.py --list \
  "$out/daemon-flight.json" "$out/client-flight.json"
python3 tools/tracequery.py --trace "$tid" \
  "$out/daemon-flight.json" "$out/client-flight.json"
python3 tools/tracequery.py --trace "$tid" --json \
  "$out/daemon-flight.json" "$out/client-flight.json" \
  > "$out/spans.jsonl"
python3 tools/tracequery.py --trace "$tid" --chrome "$out/req.json" \
  "$out/daemon-flight.json" "$out/client-flight.json"

python3 - "$out/spans.jsonl" "$tid" "$out/req.json" <<'PY'
import json
import sys

recs = [json.loads(line) for line in open(sys.argv[1])]
tid = sys.argv[2]
assert recs, "tracequery merged zero spans for the traced request"
procs = {r["proc"] for r in recs}
assert len(procs) >= 2, f"trace spans only {procs} — expected >= 2 processes"
names = {r["name"].split("/")[-1] for r in recs}
# server -> session -> mesh, across the process boundary:
for want in ("client.rpc", "serving.admission", "serving.queue_wait",
             "serving.stream", "mesh.stage", "plan.mesh"):
    assert want in names, f"{want!r} missing from merged trace: {sorted(names)}"
# compile + per-segment execute spans ride the same trace
assert any(n.startswith("compile.") for n in names), sorted(names)
assert "plan.segment" in names or "plan" in names, sorted(names)

chrome = json.load(open(sys.argv[3]))
spans = [e for e in chrome["traceEvents"] if e.get("ph") == "X"]
pids = {e["pid"] for e in spans}
assert spans and len(pids) >= 2, (len(spans), pids)
print(
    f"trace smoke OK: trace {tid[:12]} merged {len(recs)} spans from "
    f"{len(procs)} processes ({len(spans)} Chrome spans, "
    f"{len(pids)} process tracks)"
)
PY
