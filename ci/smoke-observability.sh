#!/usr/bin/env bash
# Observability smoke gate: a tiny bench config with tracing + the
# flight recorder enabled must leave BOTH telemetry artifacts behind
# (metrics snapshot + flight dump), and the flight dump must convert
# into a Perfetto-loadable Chrome trace with spans from the dispatch,
# wire-serde and bucketed subsystems plus at least one counter track.
#
# This is the crash-postmortem contract of ISSUE 3: if this gate
# passes, a SIGTERM'd production run leaves a timeline you can open at
# https://ui.perfetto.dev instead of a bare "device unreachable".
#
# Runs on the CPU backend by default so it gates every premerge node;
# set SPARK_RAPIDS_TPU_TEST_PLATFORM/JAX_PLATFORMS for an on-chip run.
set -euxo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export SRT_JAX_PLATFORMS="${SRT_JAX_PLATFORMS:-cpu}"
export SPARK_RAPIDS_TPU_TRACE=1
export SPARK_RAPIDS_TPU_METRICS_DUMP="$out/metrics.json"
export SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/flight.json"
# shrink the resident-chain config (filter -> sort -> groupby through
# wire AND resident handles: dispatch + serde + bucketed spans,
# resident.live counter samples) to smoke scale
export SRT_BENCH_RESIDENT_ROWS=200000

python3 bench.py --one resident

# both artifacts exist and parse as JSON
test -s "$out/metrics.json"
test -s "$out/flight.json"
python3 -m json.tool "$out/metrics.json" > /dev/null
python3 -m json.tool "$out/flight.json" > /dev/null

# the flight dump converts into a schema-valid Chrome trace covering
# >= 3 subsystems + >= 1 counter track
python3 tools/trace2chrome.py "$out/flight.json" -o "$out/trace.json"
python3 - "$out/trace.json" <<'PY'
import json
import sys

trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
assert events, "empty trace"
for e in events:
    assert "ph" in e and "pid" in e and "tid" in e, e
spans = [e for e in events if e["ph"] == "X"]
cats = {e["cat"] for e in spans}
assert "dispatch" in cats, cats
assert "wire" in cats, cats
assert "bucketed" in cats, cats
counters = {e["name"] for e in events if e["ph"] == "C"}
assert counters, "no counter tracks"
print(
    f"observability smoke OK: {len(spans)} spans, "
    f"subsystems={sorted(cats)}, counters={sorted(counters)}"
)
PY

# plan-fusion observability (ISSUE 4): a fused plan run under
# METRICS+FLIGHT must land the plan.* counters in the metrics dump and
# its per-segment spans must convert into the Chrome trace
export SPARK_RAPIDS_TPU_METRICS_DUMP="$out/metrics_plan.json"
export SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/flight_plan.json"
export SRT_BENCH_PLAN_ROWS=4000

python3 bench.py --one fused_plan

test -s "$out/metrics_plan.json"
test -s "$out/flight_plan.json"
python3 -m json.tool "$out/metrics_plan.json" > /dev/null
python3 tools/trace2chrome.py "$out/flight_plan.json" -o "$out/trace_plan.json"
python3 - "$out/metrics_plan.json" "$out/trace_plan.json" <<'PY'
import json
import sys

m = json.load(open(sys.argv[1]))
c = m.get("counters", {})
assert c.get("plan.segments", 0) > 0, c
assert c.get("plan.fused_ops", 0) > 0, c
trace = json.load(open(sys.argv[2]))
events = trace["traceEvents"]
assert events, "empty plan trace"
spans = [e for e in events if e["ph"] == "X"]
seg = [e for e in spans if e["name"].split("/")[-1] == "plan.segment"]
assert seg, sorted({e["name"] for e in spans})
assert "plan" in {e["cat"] for e in spans}
print(
    "plan fusion smoke OK:",
    {k: v for k, v in sorted(c.items()) if k.startswith("plan.")},
    f"+ {len(seg)} plan.segment spans in trace",
)
PY

# pipelined dispatch observability (ISSUE 5): a pipelined stream run
# under METRICS+FLIGHT must land the pipeline.* counters in the metrics
# dump, and the converted Chrome trace must show the decode/encode
# STAGE spans on WORKER thread ids distinct from the compute thread —
# the visual proof of host/device overlap the tentpole promises
export SPARK_RAPIDS_TPU_METRICS_DUMP="$out/metrics_pipe.json"
export SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/flight_pipe.json"
export SRT_BENCH_STREAM_ROWS=20000
export SRT_BENCH_PIPELINE_DEPTH=2

python3 bench.py --one pipelined_stream

test -s "$out/metrics_pipe.json"
test -s "$out/flight_pipe.json"
python3 -m json.tool "$out/metrics_pipe.json" > /dev/null
python3 tools/trace2chrome.py "$out/flight_pipe.json" -o "$out/trace_pipe.json"
python3 - "$out/metrics_pipe.json" "$out/trace_pipe.json" <<'PY'
import json
import sys

m = json.load(open(sys.argv[1]))
c = m.get("counters", {})
assert c.get("pipeline.enqueued", 0) > 0, c
assert c.get("pipeline.completed", 0) > 0, c
assert "pipeline.overlap_ms" in m.get("histograms", {}), sorted(
    m.get("histograms", {})
)
assert m.get("bytes", {}).get("hbm.donated_bytes", 0) > 0, m.get("bytes")
trace = json.load(open(sys.argv[2]))
events = trace["traceEvents"]
assert events, "empty pipeline trace"
spans = [e for e in events if e["ph"] == "X"]
stage = [
    e for e in spans
    if e["name"].split("/")[-1] in ("pipeline.decode", "pipeline.encode")
]
assert stage, sorted({e["name"] for e in spans})
stage_tids = {e["tid"] for e in stage}
compute_tids = {
    e["tid"] for e in spans if e["name"].split("/")[-1] == "plan.segment"
}
worker_tids = stage_tids - compute_tids
assert worker_tids, (
    f"stage spans only on compute tids {compute_tids} — no worker-side "
    "stage execution in the trace"
)
print(
    "pipelined dispatch smoke OK:",
    {k: v for k, v in sorted(c.items()) if k.startswith("pipeline.")},
    f"+ {len(stage)} stage spans on {len(worker_tids)} worker tid(s)",
)
PY

# query profiler + EXPLAIN ANALYZE (ISSUE 8): two fused-plan runs under
# PROFILE=on (distinct processes -> distinct pids) must each leave a
# flight dump carrying profile sessions; explain.py must render a
# per-op report naming EVERY plan op with a nonzero fused count and a
# valid --json form, and --merge must combine both dumps into one
# report + one Perfetto trace with two process tracks
export SPARK_RAPIDS_TPU_PROFILE=on
export SRT_BENCH_PLAN_ROWS=4000

export SPARK_RAPIDS_TPU_METRICS_DUMP="$out/metrics_prof0.json"
export SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/flight_prof0.json"
export SPARK_RAPIDS_TPU_PROFILE_DUMP="$out/profile0.json"
python3 bench.py --one fused_plan > "$out/bench_prof0.json"
export SPARK_RAPIDS_TPU_METRICS_DUMP="$out/metrics_prof1.json"
export SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/flight_prof1.json"
export SPARK_RAPIDS_TPU_PROFILE_DUMP="$out/profile1.json"
python3 bench.py --one fused_plan > "$out/bench_prof1.json"
# the analysis tools below import the package too — drop the dump envs
# so THEIR atexit hooks can't clobber the artifacts under test
unset SPARK_RAPIDS_TPU_PROFILE SPARK_RAPIDS_TPU_PROFILE_DUMP \
  SPARK_RAPIDS_TPU_FLIGHT_DUMP SPARK_RAPIDS_TPU_METRICS_DUMP

test -s "$out/profile0.json"
test -s "$out/profile1.json"
python3 -m json.tool "$out/profile0.json" > /dev/null

# the report names every plan op, shows fused segments, and the
# machine form is valid JSON with the split-sums invariant
python3 tools/explain.py "$out/profile0.json" > "$out/explain.txt"
grep -q "EXPLAIN ANALYZE" "$out/explain.txt"
for op in filter cast sort_by groupby; do
  grep -q "$op" "$out/explain.txt"
done
grep -q "fused)" "$out/explain.txt"
python3 tools/explain.py --json "$out/profile0.json" > "$out/explain.json"
python3 - "$out/explain.json" <<'PY'
import json
import sys

sessions = json.load(open(sys.argv[1]))
assert sessions, "no sessions in --json output"
fused = 0
for s in sessions:
    for seg in s["segments"]:
        fused += seg["kind"] == "fused"
        total = (
            seg["compile_s"] + seg["execute_s"] + seg["serde_s"]
            + seg["stall_s"]
        )
        assert abs(total - seg["wall_s"]) < 1e-6, seg
assert fused > 0, "no fused segments profiled"
print(f"explain smoke OK: {len(sessions)} sessions, {fused} fused segments")
PY

# multi-process merge: both flight dumps (which carry the sessions and
# the pid/host/session_id stamps) -> one report + one Perfetto trace
# with two distinct process tracks
python3 tools/explain.py --merge \
  "$out/flight_prof0.json" "$out/flight_prof1.json" \
  -o "$out/merged.trace.json" > "$out/merged.txt"
grep -q "MERGED PROFILE  2 process(es)" "$out/merged.txt"
python3 - "$out/merged.trace.json" <<'PY'
import json
import sys

trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
assert events, "empty merged trace"
pids = {e["pid"] for e in events}
assert len(pids) >= 2, f"merge kept only {pids}"
names = [e for e in events if e["name"] == "process_name"]
assert len({e["pid"] for e in names}) >= 2, names
print(
    f"profile merge smoke OK: {len(events)} events across "
    f"{len(pids)} process tracks"
)
PY

# multi-tenant serving daemon (ISSUE 9): the serving bench starts a
# daemon and streams TPC-DS-shaped plan mixes through concurrent tenant
# sessions. Gates: (i) the session-stamped profile dump merges into an
# EXPLAIN report naming >= 2 served sessions (serve:<name> labels),
# (ii) the served phase warm-hits the cross-session compile cache
# (nonzero compile_cache.hit with ~0 misses), (iii) the daemon shuts
# down clean with ZERO leaked resident tables
export SPARK_RAPIDS_TPU_PROFILE=on
export SPARK_RAPIDS_TPU_METRICS_DUMP="$out/metrics_serve.json"
export SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/flight_serve.json"
export SPARK_RAPIDS_TPU_PROFILE_DUMP="$out/profile_serve.json"
export SRT_BENCH_SERVE_ROWS=8000

python3 bench.py --one serving_multiquery > "$out/bench_serve.json"
unset SPARK_RAPIDS_TPU_PROFILE SPARK_RAPIDS_TPU_PROFILE_DUMP \
  SPARK_RAPIDS_TPU_FLIGHT_DUMP SPARK_RAPIDS_TPU_METRICS_DUMP

test -s "$out/profile_serve.json"
python3 -m json.tool "$out/profile_serve.json" > /dev/null

# gate (ii) + (iii): the structured "serving" block from the bench
# entry — cross-session hits nonzero, misses ~0, zero leaked tables —
# and analyze_bench.py renders the block from the raw entry line
python3 - "$out/bench_serve.json" <<'PY'
import json
import sys

entries = []
for line in open(sys.argv[1]):
    line = line.strip()
    if line.startswith("BENCH_ENTRY "):
        entries.append(json.loads(line[len("BENCH_ENTRY "):]))
blocks = [e["serving"] for e in entries if isinstance(e.get("serving"), dict)]
assert blocks, f"no serving block in {len(entries)} entries"
s = blocks[0]
assert s["sessions"] >= 2, s
assert s["cross_session_hits"] > 0, s
assert s["cross_session_misses"] == 0, s
assert s["leaked_tables"] == 0, s
assert s["requests"] > 0, s
print(
    f"serving bench smoke OK: {s['sessions']} sessions, "
    f"{s['cross_session_hits']} cross-session cache hits, "
    f"shed={s['shed']}, wait p95 {s['queue_wait_ms_p95']} ms, "
    f"0 leaked tables"
)
PY

# gate (i): the profile dump is session-stamped — the EXPLAIN report
# and the flight-dump merge both name >= 2 distinct served sessions
python3 tools/explain.py "$out/profile_serve.json" > "$out/explain_serve.txt"
python3 tools/explain.py --merge "$out/flight_serve.json" \
  -o "$out/merged_serve.trace.json" > "$out/merged_serve.txt"
python3 - "$out/explain_serve.txt" "$out/merged_serve.txt" <<'PY'
import re
import sys

for path in sys.argv[1:3]:
    text = open(path).read()
    served = set(re.findall(r"serve:[\w.-]+", text))
    assert len(served) >= 2, (path, sorted(served))
print(f"serving session stamps OK: {sorted(served)}")
PY
