#!/usr/bin/env bash
# Observability smoke gate: a tiny bench config with tracing + the
# flight recorder enabled must leave BOTH telemetry artifacts behind
# (metrics snapshot + flight dump), and the flight dump must convert
# into a Perfetto-loadable Chrome trace with spans from the dispatch,
# wire-serde and bucketed subsystems plus at least one counter track.
#
# This is the crash-postmortem contract of ISSUE 3: if this gate
# passes, a SIGTERM'd production run leaves a timeline you can open at
# https://ui.perfetto.dev instead of a bare "device unreachable".
#
# Runs on the CPU backend by default so it gates every premerge node;
# set SPARK_RAPIDS_TPU_TEST_PLATFORM/JAX_PLATFORMS for an on-chip run.
set -euxo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export SRT_JAX_PLATFORMS="${SRT_JAX_PLATFORMS:-cpu}"
export SPARK_RAPIDS_TPU_TRACE=1
export SPARK_RAPIDS_TPU_METRICS_DUMP="$out/metrics.json"
export SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/flight.json"
# shrink the resident-chain config (filter -> sort -> groupby through
# wire AND resident handles: dispatch + serde + bucketed spans,
# resident.live counter samples) to smoke scale
export SRT_BENCH_RESIDENT_ROWS=200000

python3 bench.py --one resident

# both artifacts exist and parse as JSON
test -s "$out/metrics.json"
test -s "$out/flight.json"
python3 -m json.tool "$out/metrics.json" > /dev/null
python3 -m json.tool "$out/flight.json" > /dev/null

# the flight dump converts into a schema-valid Chrome trace covering
# >= 3 subsystems + >= 1 counter track
python3 tools/trace2chrome.py "$out/flight.json" -o "$out/trace.json"
python3 - "$out/trace.json" <<'PY'
import json
import sys

trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
assert events, "empty trace"
for e in events:
    assert "ph" in e and "pid" in e and "tid" in e, e
spans = [e for e in events if e["ph"] == "X"]
cats = {e["cat"] for e in spans}
assert "dispatch" in cats, cats
assert "wire" in cats, cats
assert "bucketed" in cats, cats
counters = {e["name"] for e in events if e["ph"] == "C"}
assert counters, "no counter tracks"
print(
    f"observability smoke OK: {len(spans)} spans, "
    f"subsystems={sorted(cats)}, counters={sorted(counters)}"
)
PY

# plan-fusion observability (ISSUE 4): a fused plan run under
# METRICS+FLIGHT must land the plan.* counters in the metrics dump and
# its per-segment spans must convert into the Chrome trace
export SPARK_RAPIDS_TPU_METRICS_DUMP="$out/metrics_plan.json"
export SPARK_RAPIDS_TPU_FLIGHT_DUMP="$out/flight_plan.json"
export SRT_BENCH_PLAN_ROWS=4000

python3 bench.py --one fused_plan

test -s "$out/metrics_plan.json"
test -s "$out/flight_plan.json"
python3 -m json.tool "$out/metrics_plan.json" > /dev/null
python3 tools/trace2chrome.py "$out/flight_plan.json" -o "$out/trace_plan.json"
python3 - "$out/metrics_plan.json" "$out/trace_plan.json" <<'PY'
import json
import sys

m = json.load(open(sys.argv[1]))
c = m.get("counters", {})
assert c.get("plan.segments", 0) > 0, c
assert c.get("plan.fused_ops", 0) > 0, c
trace = json.load(open(sys.argv[2]))
events = trace["traceEvents"]
assert events, "empty plan trace"
spans = [e for e in events if e["ph"] == "X"]
seg = [e for e in spans if e["name"].split("/")[-1] == "plan.segment"]
assert seg, sorted({e["name"] for e in spans})
assert "plan" in {e["cat"] for e in spans}
print(
    "plan fusion smoke OK:",
    {k: v for k, v in sorted(c.items()) if k.startswith("plan.")},
    f"+ {len(seg)} plan.segment spans in trace",
)
PY
