#!/usr/bin/env bash
# Nightly dependency-sync bot — the ci/submodule-sync.sh analog
# (submodule-sync.sh:23-97). Where the reference advances the cudf
# submodule to branch HEAD, gates on a full `mvn verify`, and opens an
# auto-merging PR, this advances the env/requirements-pin.txt pins to
# the currently-installed (or latest-available) versions, gates on the
# full premerge build, and opens a PR through the GitHub REST API with
# the test result as a comment; the PR auto-squash-merges iff green
# (.github/workflows/dependency-sync.yml drives the schedule).
#
# Env: GITHUB_TOKEN, GITHUB_REPO (owner/name), BASE_BRANCH (default main)
#
# No -x: the REST calls below carry the Authorization token; xtrace
# would write it into the build log (Actions masking is best-effort).
set -euo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"
base="${BASE_BRANCH:-main}"
bot_branch="bot-dependency-sync-$(date -u +%Y%m%d)"

# 1. Advance pins to the latest index release (the `git submodule
#    update --remote --merge` analog, submodule-sync.sh:53). The CI
#    image installs FROM the pin file, so the installed environment can
#    never be ahead of it — the candidate version must come from the
#    package index (SYNC_SOURCE=installed exists for air-gapped runs
#    where a newer stack was installed by other means).
python3 - <<'PY'
import importlib.metadata as md
import os
import re
import subprocess

def latest_from_index(name):
    # `pip index versions` prints "name (X.Y.Z)\nAvailable versions: ..."
    out = subprocess.run(
        ["python3", "-m", "pip", "index", "versions", name],
        capture_output=True, text=True, timeout=120,
    )
    m = re.search(r"Available versions: ([^\s,]+)", out.stdout)
    return m.group(1) if m else None

source = os.environ.get("SYNC_SOURCE", "index")
path = "env/requirements-pin.txt"
with open(path) as f:
    lines = f.readlines()
out = []
changed = False
for line in lines:
    m = re.match(r"^(\S+)==(\S+)\s*$", line)
    if not m:
        out.append(line)
        continue
    name, old = m.groups()
    new = None
    if source == "index":
        new = latest_from_index(name)
    if new is None:
        new = md.version(name)
    if new != old:
        changed = True
    out.append(f"{name}=={new}\n")
with open(path, "w") as f:
    f.writelines(out)
print("pins changed" if changed else "pins unchanged")
PY

# Install the candidate stack into a throwaway venv so the shared
# runner's environment is untouched whatever the gate decides (a failed
# gate must not leave other jobs' dependency-check red).
sync_venv="$(mktemp -d)/venv"
python3 -m venv --system-site-packages "$sync_venv"
# shellcheck disable=SC1091
source "$sync_venv/bin/activate"
trap 'deactivate || true' EXIT
python3 -m pip install -r env/requirements-pin.txt

if git diff --quiet env/requirements-pin.txt; then
  echo "dependency-sync: pins already current; nothing to do"
  exit 0
fi

# 2. Gate: the full premerge build must pass with the new pins
#    (submodule-sync.sh:68-72's `mvn verify` gate).
test_pass=true
bash ci/premerge-build.sh || test_pass=false

# 3. Branch, commit, push, PR (REST calls the action-helper python
#    performs in the reference, utils.py:60-146).
git checkout -b "$bot_branch"
git add env/requirements-pin.txt
git commit -m "Advance pinned compute-stack versions (dependency-sync bot)"
git push -u origin "$bot_branch"

api="https://api.github.com/repos/${GITHUB_REPO}"
auth=(-H "Authorization: token ${GITHUB_TOKEN}" -H "Accept: application/vnd.github.v3+json")

pr_number=$(curl -sf "${auth[@]}" -X POST "$api/pulls" -d "$(python3 -c "
import json
print(json.dumps({
  'title': '[bot] dependency-sync: advance env pins',
  'head': '$bot_branch',
  'base': '$base',
  'body': 'Automated pin advance; premerge gate result posted below.',
}))")" | python3 -c "import json,sys; print(json.load(sys.stdin)['number'])")

curl -sf "${auth[@]}" -X POST "$api/issues/$pr_number/comments" \
  -d "{\"body\": \"premerge build: $([[ $test_pass == true ]] && echo PASSED || echo FAILED)\"}"

# 4. Auto-squash-merge iff the gate passed (submodule-sync.sh:83-97).
if [[ "$test_pass" == "true" ]]; then
  curl -sf "${auth[@]}" -X PUT "$api/pulls/$pr_number/merge" \
    -d '{"merge_method": "squash"}'
else
  echo "gate failed; leaving PR open for triage"
  exit 1
fi
