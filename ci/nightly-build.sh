#!/usr/bin/env bash
# Nightly build — the ci/nightly-build.sh analog: clean rebuild of the
# native shim, full verification, packaged artifacts. Unlike premerge,
# starts from a clean build tree (`mvn clean package` analog).
set -euxo pipefail

repo="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo"

# Clean only the CMake outputs: build/ also holds the checked-in
# build-info and dependency-check scripts.
rm -rf build/CMakeCache.txt build/CMakeFiles build/Makefile \
  build/cmake_install.cmake build/libspark_rapids_tpu.so
build/dependency-check || true  # nightly reports drift but proceeds
NATIVE_BUILD_CONFIGURE=true SRT_WERROR=ON \
  CPP_PARALLEL_LEVEL="${PARALLEL_LEVEL:-4}" \
  bash spark-rapids-tpu-runtime/build-native.sh

# FULL suite nightly, slow distributed tier included
python3 -m pytest tests/ -q

XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python3 -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"

# Nightly bench record (BENCH_nightly.json artifact).
# bench.py re-prints its headline line after every config (kill-proof);
# the artifact is the LAST PARSEABLE line, kept as a single JSON doc.
# `|| true`: a bench killed mid-run must still publish the lines it
# flushed (the very scenario the re-emit design exists to survive).
python3 bench.py | tee BENCH_nightly.jsonl || true
python3 - <<'PYEOF'
import json
last = None
with open("BENCH_nightly.jsonl") as f:
    for line in f:
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue  # e.g. a final line truncated by the kill
        last = doc
if last is None:
    raise SystemExit("no parseable bench line")
with open("BENCH_nightly.json", "w") as f:
    json.dump(last, f)
PYEOF
