/*
 * Table/column equality assertions — the AssertUtils helper the
 * reference test suite leans on (RowConversionTest.java:51 calls
 * assertTablesAreEqual). Compares dtype, row count, per-row validity and
 * raw little-endian values.
 */
package ai.rapids.cudf;

public final class AssertUtils {
  private AssertUtils() {}

  public static void assertTablesAreEqual(Table expected, Table actual) {
    if (expected.getNumberOfColumns() != actual.getNumberOfColumns()) {
      throw new AssertionError("column count mismatch: "
          + expected.getNumberOfColumns() + " vs " + actual.getNumberOfColumns());
    }
    for (int c = 0; c < expected.getNumberOfColumns(); c++) {
      assertColumnsAreEqual(expected.getColumn(c), actual.getColumn(c), "col " + c);
    }
  }

  public static void assertColumnsAreEqual(ColumnVector expected,
                                           ColumnVector actual, String what) {
    if (!expected.getType().equals(actual.getType())) {
      throw new AssertionError(what + ": dtype " + expected.getType()
          + " vs " + actual.getType());
    }
    if (expected.getRowCount() != actual.getRowCount()) {
      throw new AssertionError(what + ": rows " + expected.getRowCount()
          + " vs " + actual.getRowCount());
    }
    int width = expected.getType().getSizeInBytes();
    byte[] edata = expected.getData().toByteArray();
    byte[] adata = actual.getData().toByteArray();
    // hoist validity copies out of the row loop: isNull() per row would
    // re-copy the whole native buffer each call
    byte[] evalid = expected.getValid() == null ? null
        : expected.getValid().toByteArray();
    byte[] avalid = actual.getValid() == null ? null
        : actual.getValid().toByteArray();
    for (long r = 0; r < expected.getRowCount(); r++) {
      boolean enull = evalid != null && evalid[(int) r] == 0;
      boolean anull = avalid != null && avalid[(int) r] == 0;
      if (enull != anull) {
        throw new AssertionError(what + " row " + r + ": null " + enull
            + " vs " + anull);
      }
      if (enull) {
        continue; // values under nulls are unspecified
      }
      for (int b = 0; b < width; b++) {
        int idx = (int) (r * width + b);
        if (edata[idx] != adata[idx]) {
          throw new AssertionError(what + " row " + r + " byte " + b
              + ": " + edata[idx] + " vs " + adata[idx]);
        }
      }
    }
  }
}
