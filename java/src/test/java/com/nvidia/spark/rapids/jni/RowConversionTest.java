/*
 * Round-trip test over the real native stack — the analog of the
 * reference's only repo-local test (RowConversionTest.java:28-59):
 * an 8-column fixed-width table with trailing nulls in every column
 * converts to packed rows and back to an equal table, with explicit
 * close/ownership discipline and a zero-leak check at the end
 * (the refcount-debug mode of SURVEY.md §4).
 *
 * Written against JUnit 5 (the reference's framework, pom.xml:186-208);
 * also runnable standalone via main() so environments without a test
 * runner can still execute it (`java ... RowConversionTest`).
 */
package com.nvidia.spark.rapids.jni;

import ai.rapids.cudf.AssertUtils;
import ai.rapids.cudf.ColumnVector;
import ai.rapids.cudf.DType;
import ai.rapids.cudf.Table;

import org.junit.jupiter.api.Test;

public class RowConversionTest {

  @Test
  public void fixedWidthRowsRoundTrip() {
    long before = HostBuffer.liveHandleCount();
    Table in = new Table.TestBuilder()
        .column(3L, 9L, 4L, 2L, 20L, null)
        .column(5.0, 9.5, 0.9, 7.23, 2.8, null)
        .column(5, 1, 0, 2, 7, null)
        .column(true, false, false, true, false, null)
        .column(1.0f, 3.5f, 5.9f, 7.1f, 9.8f, null)
        .column((byte) 2, (byte) 3, (byte) 4, (byte) 5, (byte) 9, null)
        .decimal32Column(-3, 100, 202, 3003, 40004, 500005, null)
        .decimal64Column(-8, 1L, 2L, 3L, 4L, 5L, null)
        .build();
    try {
      DType[] schema = new DType[in.getNumberOfColumns()];
      for (int i = 0; i < schema.length; i++) {
        schema[i] = in.getColumn(i).getType();
      }
      ColumnVector[] rowBatches = RowConversion.convertToRows(in);
      try {
        // 6 rows of ~50 bytes: far below the 2 GB split threshold.
        if (rowBatches.length != 1) {
          throw new AssertionError("expected 1 batch, got " + rowBatches.length);
        }
        if (rowBatches[0].getRowCount() != in.getRowCount()) {
          throw new AssertionError("row count changed in transit");
        }
        Table out = RowConversion.convertFromRows(rowBatches[0], schema);
        try {
          AssertUtils.assertTablesAreEqual(in, out);
        } finally {
          out.close();
        }
      } finally {
        for (ColumnVector cv : rowBatches) {
          cv.close();
        }
      }
    } finally {
      in.close();
    }
    long after = HostBuffer.liveHandleCount();
    if (after != before) {
      throw new AssertionError("leaked " + (after - before) + " native handles");
    }
  }

  @Test
  public void emptySchemaRejected() {
    boolean threw = false;
    try {
      new Table(new ColumnVector[0]);
    } catch (IllegalArgumentException e) {
      threw = true;
    }
    if (!threw) {
      throw new AssertionError("empty table construction should fail");
    }
  }

  public static void main(String[] args) {
    RowConversionTest t = new RowConversionTest();
    t.fixedWidthRowsRoundTrip();
    t.emptySchemaRejected();
    System.out.println("RowConversionTest: OK");
  }
}
