/*
 * Row <-> columnar conversion API.
 *
 * Signature- and semantics-compatible with the reference's
 * com.nvidia.spark.rapids.jni.RowConversion (RowConversion.java:104-128),
 * re-targeted at the TPU runtime: native methods thunk into
 * libspark_rapids_tpu.so (src/jni/RowConversionJni.cpp), whose packed-row
 * codec is golden-tested byte-for-byte against the XLA device
 * implementation (tests/test_native.py).
 *
 * THE ROW FORMAT (normative; mirrors RowConversion.java:43-102):
 *
 * Each row is a C-struct-like packed record of the table's fixed-width
 * columns, in schema order:
 *   - column i's value sits at align_offset(cursor, width_i) — values
 *     are width-aligned so reads never straddle natural boundaries;
 *   - after the last value come the validity bytes: 1 bit per column,
 *     LSB-first, 1 byte per 8 columns (bit c of byte c/8 set = valid);
 *   - the row is padded with zeros to a multiple of 8 bytes so
 *     consecutive rows stay 64-bit aligned.
 *
 * For best packing order columns widest to narrowest (the reference's
 * recommendation, RowConversion.java:77-92): the layout inserts
 * alignment padding between a narrow column and a following wider one.
 *
 * A single packed batch is capped at Integer.MAX_VALUE bytes; larger
 * tables split into batches of (INT_MAX / rowSize) / 32 * 32 rows —
 * multiples of 32 so validity words never straddle batches
 * (row_conversion.cu:476-479). Only fixed-width types are supported
 * (row_conversion.cu:514-516); decimal columns travel as unscaled
 * int32/int64 with their scale carried in the schema wire arrays.
 */
package com.nvidia.spark.rapids.jni;

public class RowConversion {
  static {
    NativeLibraryLoader.loadNativeLibs();
  }

  /**
   * Convert a table into packed UnsafeRow-style batches — the reference's
   * primary entry point (RowConversion.java:104-111): one LIST&lt;INT8&gt;
   * ColumnVector per 2 GB batch, each list element one packed row.
   * Ownership of the returned columns transfers to the caller.
   */
  public static ai.rapids.cudf.ColumnVector[] convertToRows(
      ai.rapids.cudf.Table table) {
    int n = table.getNumberOfColumns();
    int[] typeIds = new int[n];
    for (int i = 0; i < n; i++) {
      typeIds[i] = table.getColumn(i).getType().getTypeId().getNativeId();
    }
    int rowSize = rowSize(typeIds);
    try (HostBuffer packed = table.packForNative()) {
      HostBuffer[] batches = convertToRows(packed, typeIds, table.getRowCount());
      ai.rapids.cudf.ColumnVector[] out =
          new ai.rapids.cudf.ColumnVector[batches.length];
      long maxRows = maxRowsPerBatch(rowSize);
      long remaining = table.getRowCount();
      try {
        for (int b = 0; b < batches.length; b++) {
          long batchRows = Math.min(maxRows, remaining);
          out[b] = ai.rapids.cudf.ColumnVector.fromPackedRows(
              batches[b], batchRows, rowSize);
          remaining -= batchRows;
        }
      } catch (RuntimeException e) {
        // wrapping failed mid-loop: close the vectors already built and
        // the batch buffers not yet owned by one, or their registry
        // handles leak past the caller forever
        for (int b = 0; b < batches.length; b++) {
          if (out[b] != null) {
            out[b].close();
          } else if (batches[b] != null) {
            batches[b].close();
          }
        }
        throw e;
      }
      return out;
    }
  }

  /**
   * Convert one packed row batch back into a table with the asserted
   * schema — the reference's convertFromRows(ColumnView, DType...)
   * (RowConversion.java:113-124). Scales travel as the parallel int
   * array of the JNI wire format.
   */
  public static ai.rapids.cudf.Table convertFromRows(
      ai.rapids.cudf.ColumnView rows, ai.rapids.cudf.DType... schema) {
    int n = schema.length;
    int[] typeIds = new int[n];
    int[] scales = new int[n];
    for (int i = 0; i < n; i++) {
      typeIds[i] = schema[i].getTypeId().getNativeId();
      scales[i] = schema[i].getScale();
    }
    long numRows = rows.getRowCount();
    long[] handles = convertFromRowsNative(rows.getData().getHandle(),
                                           typeIds, scales, numRows);
    ai.rapids.cudf.ColumnVector[] cols = new ai.rapids.cudf.ColumnVector[n];
    HostBuffer[] bufs = new HostBuffer[2 * n];
    try {
      for (int i = 0; i < 2 * n; i++) {
        bufs[i] = new HostBuffer(handles[i]);
      }
      for (int i = 0; i < n; i++) {
        cols[i] = new ai.rapids.cudf.ColumnVector(
            schema[i], numRows, bufs[i], bufs[n + i]);
      }
      return new ai.rapids.cudf.Table(cols);
    } catch (RuntimeException e) {
      // column/table assembly failed: close the vectors already built
      // (each owns its two buffers) and every buffer no vector owns,
      // or their registry handles leak past the caller forever
      for (int i = 0; i < n; i++) {
        if (cols[i] != null) {
          cols[i].close();
          bufs[i] = null;
          bufs[n + i] = null;
        }
      }
      for (HostBuffer b : bufs) {
        if (b != null) {
          b.close();
        }
      }
      throw e;
    }
  }

  /**
   * Convert a host table (column buffers concatenated in the layout the
   * bridge expects: data buffers back to back, then per-column validity
   * byte vectors) into packed row batches.
   *
   * @param table    buffer holding the table's host columns
   * @param typeIds  native dtype ids per column (DType wire format,
   *                 RowConversionJni.cpp:56-61)
   * @param numRows  rows in every column
   * @return one HostBuffer per 2 GB batch of packed rows
   */
  public static HostBuffer[] convertToRows(HostBuffer table, int[] typeIds,
                                           long numRows) {
    int rowSize = rowSize(typeIds);
    long maxRows = maxRowsPerBatch(rowSize);
    int numBatches = (int) ((numRows + maxRows - 1) / Math.max(maxRows, 1));
    if (numRows == 0) {
      numBatches = 1;
    }
    HostBuffer[] out = new HostBuffer[numBatches];
    // Each batch packs its own disjoint [start, start+count) row range —
    // maxRows is a multiple of 32 so validity words never straddle
    // batches (RowConversion.java:36-37,104-111).
    try {
      for (int b = 0; b < numBatches; b++) {
        long start = b * maxRows;
        long count = Math.min(maxRows, numRows - start);
        if (numRows == 0) {
          start = 0;
          count = 0;
        }
        out[b] = new HostBuffer(
            convertToRowsNative(table.getHandle(), typeIds, numRows, start,
                                count));
      }
    } catch (RuntimeException e) {
      // a later batch failed: release the batches already owned here, or
      // their registry buffers leak past the caller forever
      for (HostBuffer b : out) {
        if (b != null) {
          b.close();
        }
      }
      throw e;
    }
    return out;
  }

  /**
   * Convert packed rows back to columns using the asserted schema — the
   * (typeId, scale) parallel int arrays of the reference JNI
   * (RowConversion.java:113-124, RowConversionJni.cpp:56-61).
   *
   * @return handles: numColumns data buffers then numColumns validity
   *         byte vectors, ownership transferred to the caller
   */
  public static HostBuffer[] convertFromRows(HostBuffer rows, int[] typeIds,
                                             int[] scales, long numRows) {
    long[] handles =
        convertFromRowsNative(rows.getHandle(), typeIds, scales, numRows);
    HostBuffer[] out = new HostBuffer[handles.length];
    for (int i = 0; i < handles.length; i++) {
      out[i] = new HostBuffer(handles[i]);
    }
    return out;
  }

  /** Packed row size in bytes for a schema (layout envelope). */
  public static native int rowSize(int[] typeIds);

  /** (INT_MAX / rowSize) / 32 * 32 (row_conversion.cu:476-479). */
  public static native long maxRowsPerBatch(int rowSize);

  private static native long convertToRowsNative(long tableHandle,
                                                 int[] typeIds, long numRows,
                                                 long startRow,
                                                 long batchRows);

  private static native long[] convertFromRowsNative(long rowsHandle,
                                                     int[] typeIds,
                                                     int[] scales,
                                                     long numRows);
}
