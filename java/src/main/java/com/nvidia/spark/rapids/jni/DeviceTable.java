/*
 * Device table ops: the JVM's path to TPU compute.
 *
 * The reference's Java layer reaches device kernels through per-op JNI
 * natives (RowConversion.java:104-128 -> RowConversionJni.cpp:24-66).
 * This class is the TPU equivalent over the generic device dispatch of
 * the native runtime (src/jni/DeviceTableJni.cpp ->
 * src/cpp/jax_runtime.cpp): a Spark executor builds host column
 * buffers, wraps them in registry handles (HostBuffer), and runs
 * groupby / sort / filter / row-transpose ops that execute on the XLA
 * backend. Ownership discipline matches the reference: every returned
 * buffer is caller-owned and must be closed (the refcount-debug leak
 * report catches violations, pom.xml:86,199 analog).
 */
package com.nvidia.spark.rapids.jni;

public class DeviceTable {
  static {
    NativeLibraryLoader.loadNativeLibs();
  }

  /** Result of a device table op: parallel arrays per output column. */
  public static final class Result implements AutoCloseable {
    public final int[] typeIds;
    public final int[] scales;
    public final HostBuffer[] data;
    public final HostBuffer[] valid; // null entry = column has no nulls
    public final long numRows;

    Result(int[] typeIds, int[] scales, HostBuffer[] data,
           HostBuffer[] valid, long numRows) {
      this.typeIds = typeIds;
      this.scales = scales;
      this.data = data;
      this.valid = valid;
      this.numRows = numRows;
    }

    @Override
    public void close() {
      for (HostBuffer b : data) {
        if (b != null) {
          b.close();
        }
      }
      for (HostBuffer b : valid) {
        if (b != null) {
          b.close();
        }
      }
    }
  }

  /** True when the loaded native library embeds the device runtime. */
  public static native boolean isDeviceRuntimeAvailable();

  /** Initialize (or join) the embedded JAX runtime; idempotent. */
  public static native void initDeviceRuntime();

  /** Active device platform name ("tpu", "cpu"). */
  public static native String devicePlatform();

  /**
   * Run one table op on the device runtime.
   *
   * @param opJson   op spec (see runtime_bridge.py op vocabulary:
   *                 groupby / sort_by / filter / to_rows / from_rows)
   * @param typeIds  native dtype ids per input column
   *                 (RowConversionJni.cpp:56-61 wire format)
   * @param scales   decimal scales per input column
   * @param colData  input column buffers (little-endian fixed-width)
   * @param colValid per-column validity byte vectors; null = no nulls
   * @param numRows  rows in every input column
   * @return caller-owned result columns computed on the XLA backend
   */
  public static Result tableOp(String opJson, int[] typeIds, int[] scales,
                               HostBuffer[] colData, HostBuffer[] colValid,
                               long numRows) {
    int n = typeIds.length;
    long[] dataHandles = new long[n];
    long[] validHandles = new long[n];
    for (int i = 0; i < n; i++) {
      dataHandles[i] = colData[i].getHandle();
      validHandles[i] = colValid[i] == null ? 0 : colValid[i].getHandle();
    }
    long[] packed = tableOpNative(opJson, typeIds, scales, dataHandles,
                                  validHandles, numRows);
    return wrapPacked(packed);
  }

  /**
   * Wrap the packed [numCols, numRows, ids..., scales..., data...,
   * valid...] long array into a Result. On a wrap failure mid-loop,
   * closes the wrappers that exist and releases the raw handles never
   * wrapped (the RowConversion cleanup discipline — registry buffers
   * must not leak).
   */
  private static Result wrapPacked(long[] packed) {
    int outCols = (int) packed[0];
    long outRows = packed[1];
    int[] outIds = new int[outCols];
    int[] outScales = new int[outCols];
    HostBuffer[] outData = new HostBuffer[outCols];
    HostBuffer[] outValid = new HostBuffer[outCols];
    int wrapped = 0;
    try {
      for (; wrapped < outCols; wrapped++) {
        int i = wrapped;
        outIds[i] = (int) packed[2 + i];
        outScales[i] = (int) packed[2 + outCols + i];
        outData[i] = new HostBuffer(packed[2 + 2 * outCols + i]);
        long vh = packed[2 + 3 * outCols + i];
        outValid[i] = vh == 0 ? null : new HostBuffer(vh);
      }
    } catch (RuntimeException e) {
      for (int j = 0; j < outCols; j++) {
        if (outData[j] != null) {
          outData[j].close();
        }
        if (outValid[j] != null) {
          outValid[j].close();
        }
      }
      for (int j = wrapped; j < outCols; j++) {
        long dh = packed[2 + 2 * outCols + j];
        long vh = packed[2 + 3 * outCols + j];
        if (outData[j] == null && dh != 0) {
          new HostBuffer(dh).close();
        }
        if (outValid[j] == null && vh != 0) {
          new HostBuffer(vh).close();
        }
      }
      throw e;
    }
    return new Result(outIds, outScales, outData, outValid, outRows);
  }

  private static native long[] tableOpNative(String opJson, int[] typeIds,
                                             int[] scales, long[] colData,
                                             long[] colValid, long numRows);

  /*
   * Device-resident table chaining: the reference passes jlong pointers
   * to DEVICE-resident tables between calls with no host copy between
   * ops (RowConversionJni.cpp:31,54). These methods mirror that model:
   * upload once, chain ops over opaque table ids, download once. A
   * Spark stage chaining filter -> join -> groupby pays the host<->device
   * wire cost twice total instead of twice per op.
   */

  /** Upload host column buffers to a device-resident table; returns its
   * id. Free with {@link #tableFree}. */
  public static long tableUpload(int[] typeIds, int[] scales,
                                 HostBuffer[] colData, HostBuffer[] colValid,
                                 long numRows) {
    int n = typeIds.length;
    long[] dataHandles = new long[n];
    long[] validHandles = new long[n];
    for (int i = 0; i < n; i++) {
      dataHandles[i] = colData[i].getHandle();
      validHandles[i] = colValid[i] == null ? 0 : colValid[i].getHandle();
    }
    return tableUploadNative(typeIds, scales, dataHandles, validHandles,
                             numRows);
  }

  /** Run one op over resident tables; the result STAYS resident (op
   * "join": inputs[0] = left, inputs[1] = right; "concat": all). */
  public static long tableOpResident(String opJson, long[] inputs) {
    return tableOpResidentNative(opJson, inputs);
  }

  /** Download a resident table into caller-owned host buffers (same
   * Result contract as {@link #tableOp}). */
  public static Result tableDownload(long table) {
    return wrapPacked(tableDownloadNative(table));
  }

  /** Rows in a resident table. */
  public static native long tableNumRows(long table);

  /** Drop a resident table (its device buffers become collectable). */
  public static native void tableFree(long table);

  /** Live resident tables — the device-table leak report. */
  public static native long residentTableCount();

  /**
   * Set one SPARK_RAPIDS_TPU_* flag in the embedded runtime's
   * environment (the utils/config.py flag plane) — the path
   * {@code ai.rapids.cudf.Rmm} routes memory/logging configuration
   * through. {@code value == null} unsets. Call BEFORE
   * {@link #initDeviceRuntime}: the embedded interpreter snapshots its
   * environment at startup (the cudf ordering contract —
   * Rmm.initialize before any allocation).
   */
  public static native void setRuntimeFlag(String name, String value);

  private static native long tableUploadNative(int[] typeIds, int[] scales,
                                               long[] colData,
                                               long[] colValid, long numRows);

  private static native long tableOpResidentNative(String opJson,
                                                   long[] inputs);

  private static native long[] tableDownloadNative(long table);
}
