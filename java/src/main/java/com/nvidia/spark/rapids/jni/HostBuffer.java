/*
 * A registry-backed native buffer handle.
 *
 * The ownership model of the reference — opaque jlong handles whose
 * lifetime Java controls, with refcount-debug leak tracking
 * (RowConversionJni.cpp:31-38; -Dai.rapids.refcount.debug,
 * pom.xml:86,199) — over the runtime's handle registry
 * (src/cpp/handle_registry.cpp) instead of raw `new`-ed pointers: a
 * stale handle raises instead of crashing the JVM.
 */
package com.nvidia.spark.rapids.jni;

public class HostBuffer implements AutoCloseable {
  static {
    NativeLibraryLoader.loadNativeLibs();
  }

  private long handle;

  /** Wrap an already-created registry handle (takes ownership). */
  public HostBuffer(long handle) {
    if (handle == 0) {
      throw new IllegalArgumentException("null native handle");
    }
    this.handle = handle;
  }

  /** Copy host bytes into a new native buffer. */
  public static HostBuffer create(byte[] data, String tag) {
    return new HostBuffer(bufferCreate(data, tag));
  }

  public long getHandle() {
    if (handle == 0) {
      throw new IllegalStateException("buffer already closed");
    }
    return handle;
  }

  public long getLength() {
    return bufferSize(getHandle());
  }

  public byte[] toByteArray() {
    return bufferGet(getHandle());
  }

  @Override
  public synchronized void close() {
    if (handle != 0) {
      bufferRelease(handle);
      handle = 0;
    }
  }

  /** Live-handle count for leak tests (SURVEY.md §4 leak detection). */
  public static long liveHandleCount() {
    return nativeLiveHandleCount();
  }

  private static native long bufferCreate(byte[] data, String tag);
  private static native long bufferSize(long handle);
  private static native byte[] bufferGet(long handle);
  private static native void bufferRelease(long handle);
  private static native long nativeLiveHandleCount();
}
