/*
 * Native library loader for the TPU runtime shim.
 *
 * Plays the role of the reference's NativeLibraryLoader
 * (NativeLibraryLoader.java:22-37): an idempotent, synchronized,
 * load-once entry point triggered from static initializers of the API
 * classes. The reference delegates to cudf's NativeDepsLoader, which
 * extracts per-platform .so resources staged under
 * ${os.arch}/${os.name}/ in the jar (spark-rapids-jni/pom.xml:179-188);
 * this loader implements the same resource contract directly (no cudf),
 * falling back to System.loadLibrary for installed copies.
 */
package com.nvidia.spark.rapids.jni;

import java.io.File;
import java.io.IOException;
import java.io.InputStream;
import java.nio.file.Files;
import java.nio.file.Path;
import java.nio.file.StandardCopyOption;

public class NativeLibraryLoader {
  private static final String LIB_NAME = "spark_rapids_tpu";
  private static boolean loaded = false;

  /**
   * Load the native runtime once. Order:
   *   1. -Dspark.rapids.tpu.native.lib=/abs/path (the
   *      SPARK_RAPIDS_TPU_NATIVE_LIB flag of the Python embedder),
   *   2. jar resource /${os.arch}/${os.name}/libspark_rapids_tpu.so
   *      (the NativeDepsLoader staging convention),
   *   3. System.loadLibrary on java.library.path.
   */
  public static synchronized void loadNativeLibs() {
    if (loaded) {
      return;
    }
    String explicit = System.getProperty("spark.rapids.tpu.native.lib");
    if (explicit != null && !explicit.isEmpty()) {
      System.load(explicit);
      loaded = true;
      return;
    }
    String resource =
        "/" + System.getProperty("os.arch") + "/" + System.getProperty("os.name")
            + "/lib" + LIB_NAME + ".so";
    try (InputStream in = NativeLibraryLoader.class.getResourceAsStream(resource)) {
      if (in != null) {
        Path tmp = Files.createTempFile("lib" + LIB_NAME, ".so");
        tmp.toFile().deleteOnExit();
        Files.copy(in, tmp, StandardCopyOption.REPLACE_EXISTING);
        System.load(tmp.toAbsolutePath().toString());
        loaded = true;
        return;
      }
    } catch (IOException e) {
      throw new RuntimeException("failed to extract " + resource, e);
    }
    System.loadLibrary(LIB_NAME);
    loaded = true;
  }
}
