/*
 * Native library loader for the TPU runtime shim.
 *
 * Plays the role of the reference's NativeLibraryLoader
 * (NativeLibraryLoader.java:22-37): an idempotent, synchronized,
 * load-once entry point triggered from static initializers of the API
 * classes, delegating to ai.rapids.cudf.NativeDepsLoader exactly as the
 * reference does (NativeLibraryLoader.java:26-35) — NativeDepsLoader
 * owns the resource-extraction contract
 * (/${os.arch}/${os.name}/lib*.so in the jar) and the once-per-library
 * bookkeeping; this class only names the runtime's libraries.
 */
package com.nvidia.spark.rapids.jni;

import ai.rapids.cudf.NativeDepsLoader;

public class NativeLibraryLoader {
  private NativeLibraryLoader() {}

  /** Load the native runtime once (safe to call repeatedly). */
  public static void loadNativeLibs() {
    NativeDepsLoader.loadNativeDeps(new String[] {"spark_rapids_tpu"});
  }
}
