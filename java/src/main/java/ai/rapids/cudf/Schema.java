/*
 * Column name/type schema — the ai.rapids.cudf.Schema surface file
 * readers take (cudf java Schema.java; the plugin builds one per
 * Parquet/CSV read to bind Spark's StructType to cudf types).
 *
 * Pure metadata here as there: a builder of parallel (name, DType)
 * lists whose wire form is the (typeId, scale) arrays every JNI entry
 * point already speaks (RowConversionJni.cpp wire contract).
 */
package ai.rapids.cudf;

import java.util.ArrayList;
import java.util.List;

public final class Schema {
  public static final Schema INFERRED = new Schema(new ArrayList<String>(),
                                                   new ArrayList<DType>());

  private final List<String> names;
  private final List<DType> types;

  private Schema(List<String> names, List<DType> types) {
    this.names = names;
    this.types = types;
  }

  public static Builder builder() {
    return new Builder();
  }

  public int getNumColumns() {
    return names.size();
  }

  public String[] getColumnNames() {
    return names.toArray(new String[0]);
  }

  public DType[] getTypes() {
    return types.toArray(new DType[0]);
  }

  /** The (typeId, scale) wire arrays of the JNI contract. */
  public int[] getTypeIds() {
    int[] out = new int[types.size()];
    for (int i = 0; i < out.length; i++) {
      out[i] = types.get(i).getTypeId().getNativeId();
    }
    return out;
  }

  public int[] getScales() {
    int[] out = new int[types.size()];
    for (int i = 0; i < out.length; i++) {
      out[i] = types.get(i).getScale();
    }
    return out;
  }

  public static final class Builder {
    private final List<String> names = new ArrayList<>();
    private final List<DType> types = new ArrayList<>();

    public Builder column(DType type, String name) {
      if (names.contains(name)) {
        throw new IllegalArgumentException("duplicate column " + name);
      }
      names.add(name);
      types.add(type);
      return this;
    }

    public Schema build() {
      return new Schema(new ArrayList<>(names), new ArrayList<>(types));
    }
  }
}
