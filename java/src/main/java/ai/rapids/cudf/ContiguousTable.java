/*
 * A table backed by ONE contiguous buffer — the ai.rapids.cudf
 * ContiguousTable surface the RAPIDS shuffle serializes partitions
 * through (cudf java ContiguousTable.java: contiguous_split's output,
 * one table view + one DeviceMemoryBuffer).
 *
 * TPU redesign: the contiguous single-buffer form of a table in this
 * runtime IS the packed Spark UnsafeRow batch the row codec produces
 * (src/cpp/row_format.cpp; format-exact to row_conversion.cu:432-456).
 * Packing to rows and wrapping the one buffer is therefore the same
 * operation as cudf's pack(): the shuffle writes buffer+metadata, the
 * receiver rebuilds columns with {@link #getTable} via the from-rows
 * codec. No bespoke serialization format exists — a ContiguousTable
 * buffer is bit-identical to what RowConversion emits, so either end
 * can be a plain row-conversion call.
 */
package ai.rapids.cudf;

import com.nvidia.spark.rapids.jni.HostBuffer;
import com.nvidia.spark.rapids.jni.RowConversion;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;

public final class ContiguousTable implements AutoCloseable {
  private final int[] typeIds;
  private final int[] scales;
  private final long rows;
  private HostBuffer buffer;

  ContiguousTable(int[] typeIds, int[] scales, long rows,
                  HostBuffer buffer) {
    this.typeIds = typeIds;
    this.scales = scales;
    this.rows = rows;
    this.buffer = buffer;
  }

  /**
   * Pack host columns into one contiguous row-format buffer.
   *
   * @param typeIds  native dtype ids per column (the JNI wire contract)
   * @param scales   decimal scales per column
   * @param table    column buffers concatenated in the bridge layout
   *                 (data buffers back to back, then per-column validity
   *                 byte vectors — RowConversion.convertToRows contract)
   * @param numRows  rows in every column
   * @throws IllegalArgumentException when the packed form would exceed
   *         one 2 GB batch — split first, like cudf contiguous_split
   */
  public static ContiguousTable pack(int[] typeIds, int[] scales,
                                     HostBuffer table, long numRows) {
    int rowSize = RowConversion.rowSize(typeIds);
    long maxRows = RowConversion.maxRowsPerBatch(rowSize);
    if (numRows > maxRows) {
      throw new IllegalArgumentException(
          "table too large for one contiguous buffer: " + numRows
          + " rows > " + maxRows + " max; split first");
    }
    HostBuffer[] batches = RowConversion.convertToRows(table, typeIds,
                                                       numRows);
    // single batch guaranteed by the maxRows check above
    return new ContiguousTable(typeIds.clone(), scales.clone(), numRows,
                               batches[0]);
  }

  /** The one contiguous buffer (packed rows). Owned by this object. */
  public HostBuffer getBuffer() {
    if (buffer == null) {
      throw new IllegalStateException("contiguous table already closed");
    }
    return buffer;
  }

  public long getRowCount() {
    return rows;
  }

  /**
   * Serialization header: [numCols, rows, typeIds..., scales...] as
   * little-endian int64/int32 — what the shuffle writes next to the
   * buffer so the receiving executor can call unpack without a schema
   * side channel (cudf's packed_columns metadata role).
   */
  public ByteBuffer getMetadataDirectBuffer() {
    ByteBuffer bb = ByteBuffer.allocateDirect(8 + 8 + typeIds.length * 8)
        .order(ByteOrder.LITTLE_ENDIAN);
    bb.putLong(typeIds.length);
    bb.putLong(rows);
    for (int i = 0; i < typeIds.length; i++) {
      bb.putInt(typeIds[i]);
      bb.putInt(scales[i]);
    }
    bb.flip();
    return bb;
  }

  /** Rebuild the metadata triple from {@link #getMetadataDirectBuffer}
   * output: {numCols, rows} plus the arrays via out-params length. */
  public static ContiguousTable unpack(ByteBuffer metadata,
                                       HostBuffer buffer) {
    ByteBuffer bb = metadata.duplicate().order(ByteOrder.LITTLE_ENDIAN);
    int numCols = (int) bb.getLong();
    long rows = bb.getLong();
    int[] ids = new int[numCols];
    int[] scales = new int[numCols];
    for (int i = 0; i < numCols; i++) {
      ids[i] = bb.getInt();
      scales[i] = bb.getInt();
    }
    return new ContiguousTable(ids, scales, rows, buffer);
  }

  /**
   * Decode the contiguous buffer back to columns (caller owns every
   * returned vector — the cudf getTable() ownership contract).
   */
  public Table getTable() {
    HostBuffer[] cols = RowConversion.convertFromRows(getBuffer(), typeIds,
                                                      scales, rows);
    int n = typeIds.length;
    ColumnVector[] vecs = new ColumnVector[n];
    try {
      for (int i = 0; i < n; i++) {
        DType t = DType.fromNative(typeIds[i], scales[i]);
        vecs[i] = new ColumnVector(t, rows, cols[i], cols[n + i]);
        cols[i] = null;
        cols[n + i] = null;
      }
    } catch (RuntimeException e) {
      for (ColumnVector v : vecs) {
        if (v != null) {
          v.close();
        }
      }
      for (HostBuffer b : cols) {
        if (b != null) {
          b.close();
        }
      }
      throw e;
    }
    return new Table(vecs);
  }

  @Override
  public synchronized void close() {
    if (buffer != null) {
      buffer.close();
      buffer = null;
    }
  }
}
