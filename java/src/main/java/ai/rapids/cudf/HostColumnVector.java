/*
 * Host-side column storage + builder — the ai.rapids.cudf
 * HostColumnVector surface the spark-rapids plugin stages rows through
 * before device upload (cudf java HostColumnVector.java; every
 * row-to-columnar transition in the plugin builds one per column).
 *
 * TPU redesign: cudf backs this with off-heap HostMemoryBuffer because
 * its JNI layer wants raw addresses. Here the JNI wire protocol ships
 * byte[] into registry-backed native buffers (HostBuffer.create), so
 * heap byte[] IS the staging representation — no off-heap lifetime to
 * manage, no unsafe addressing, and the builder grows amortized like
 * ArrayList. Validity is one byte per row (the wire's validity vector
 * format, RowConversionJni.cpp wire contract), not a packed bitmask:
 * the transpose kernels pack bits on device where the bit-weight
 * dot-product formulation is free (kernels/row_transpose.py).
 */
package ai.rapids.cudf;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;

public final class HostColumnVector implements AutoCloseable {
  private final DType type;
  private final long rows;
  private final long nullCount;
  private final byte[] data;      // fixed-width values, little-endian
  private final byte[] valid;     // 1 byte/row; null = no nulls
  private final int[] offsets;    // STRING: row i = data[offsets[i]..offsets[i+1])

  HostColumnVector(DType type, long rows, long nullCount, byte[] data,
                   byte[] valid, int[] offsets) {
    this.type = type;
    this.rows = rows;
    this.nullCount = nullCount;
    this.data = data;
    this.valid = valid;
    this.offsets = offsets;
  }

  public DType getType() {
    return type;
  }

  public long getRowCount() {
    return rows;
  }

  public long getNullCount() {
    return nullCount;
  }

  public boolean hasNulls() {
    return nullCount > 0;
  }

  public boolean isNull(long row) {
    checkRow(row);
    return valid != null && valid[(int) row] == 0;
  }

  private ByteBuffer dataBuf() {
    return ByteBuffer.wrap(data).order(ByteOrder.LITTLE_ENDIAN);
  }

  public byte getByte(long row) {
    checkValue(row);
    return data[(int) row];
  }

  public boolean getBoolean(long row) {
    checkValue(row);
    return data[(int) row] != 0;
  }

  public short getShort(long row) {
    checkValue(row);
    return dataBuf().getShort((int) row * 2);
  }

  public int getInt(long row) {
    checkValue(row);
    return dataBuf().getInt((int) row * 4);
  }

  public long getLong(long row) {
    checkValue(row);
    return dataBuf().getLong((int) row * 8);
  }

  public float getFloat(long row) {
    checkValue(row);
    return dataBuf().getFloat((int) row * 4);
  }

  public double getDouble(long row) {
    checkValue(row);
    return dataBuf().getDouble((int) row * 8);
  }

  public String getJavaString(long row) {
    checkValue(row);
    int i = (int) row;
    return new String(data, offsets[i], offsets[i + 1] - offsets[i],
                      StandardCharsets.UTF_8);
  }

  /** The wire buffers of this column: data bytes exactly as the JNI
   * ships them. STRING columns carry the Arrow-style wire layout the
   * bridge decodes (runtime_bridge._padded_from_offsets): int32
   * little-endian offsets[rows+1] followed by the concatenated UTF-8
   * payload. */
  public byte[] getDataBytes() {
    if (offsets == null) {
      return data.clone();
    }
    ByteBuffer bb = ByteBuffer
        .allocate(4 * ((int) rows + 1) + dataLength())
        .order(ByteOrder.LITTLE_ENDIAN);
    for (int i = 0; i <= rows; i++) {
      bb.putInt(offsets[i]);
    }
    bb.put(data, 0, dataLength());
    return bb.array();
  }

  private int dataLength() {
    return offsets == null ? data.length : offsets[(int) rows];
  }

  /** Per-row validity byte vector, or null when the column has no nulls. */
  public byte[] getValidityBytes() {
    return valid == null ? null : valid.clone();
  }

  /** Upload to registry-backed native buffers ready for
   * DeviceTable.tableOp: [0]=data, [1]=validity (null when no nulls). */
  public com.nvidia.spark.rapids.jni.HostBuffer[] copyToDevice(String tag) {
    com.nvidia.spark.rapids.jni.HostBuffer d =
        com.nvidia.spark.rapids.jni.HostBuffer.create(getDataBytes(),
                                                      tag + ".data");
    com.nvidia.spark.rapids.jni.HostBuffer v = null;
    if (valid != null) {
      try {
        v = com.nvidia.spark.rapids.jni.HostBuffer.create(valid,
                                                          tag + ".valid");
      } catch (RuntimeException e) {
        d.close();
        throw e;
      }
    }
    return new com.nvidia.spark.rapids.jni.HostBuffer[] {d, v};
  }

  private void checkRow(long row) {
    if (row < 0 || row >= rows) {
      throw new IndexOutOfBoundsException("row " + row + " of " + rows);
    }
  }

  private void checkValue(long row) {
    checkRow(row);
    if (isNull(row)) {
      throw new IllegalStateException("row " + row + " is null");
    }
  }

  /** Heap-backed: close is a no-op kept for cudf drop-in compatibility
   * (plugin code try-with-resources every host vector). */
  @Override
  public void close() {
  }

  // ---- factories (the cudf fromXxx surface) --------------------------

  public static HostColumnVector fromLongs(long... values) {
    Builder b = builder(DType.INT64, values.length);
    for (long v : values) {
      b.append(v);
    }
    return b.build();
  }

  public static HostColumnVector fromInts(int... values) {
    Builder b = builder(DType.INT32, values.length);
    for (int v : values) {
      b.append(v);
    }
    return b.build();
  }

  public static HostColumnVector fromDoubles(double... values) {
    Builder b = builder(DType.FLOAT64, values.length);
    for (double v : values) {
      b.append(v);
    }
    return b.build();
  }

  public static HostColumnVector fromBoxedLongs(Long... values) {
    Builder b = builder(DType.INT64, values.length);
    for (Long v : values) {
      if (v == null) {
        b.appendNull();
      } else {
        b.append(v.longValue());
      }
    }
    return b.build();
  }

  public static HostColumnVector fromStrings(String... values) {
    Builder b = builder(DType.STRING, values.length);
    for (String v : values) {
      if (v == null) {
        b.appendNull();
      } else {
        b.append(v);
      }
    }
    return b.build();
  }

  public static Builder builder(DType type, int initialRows) {
    return new Builder(type, initialRows);
  }

  /** Append-only builder; appendNull writes a zero value slot so the
   * fixed-width stride never varies (the wire format's convention). */
  public static final class Builder implements AutoCloseable {
    private final DType type;
    private final int width;
    private byte[] data;
    private byte[] valid;
    private int[] offsets;
    private int rows;
    private int dataLen;
    private long nulls;

    Builder(DType type, int initialRows) {
      this.type = type;
      boolean isString = type.equals(DType.STRING);
      this.width = isString ? 0 : type.getSizeInBytes();
      int cap = Math.max(initialRows, 8);
      this.data = new byte[isString ? cap * 8 : cap * Math.max(width, 1)];
      this.valid = null;
      this.offsets = isString ? new int[cap + 1] : null;
      if (!isString && width <= 0) {
        throw new IllegalArgumentException(
            "unsupported builder type " + type);
      }
    }

    private void ensure(int moreRows, int moreBytes) {
      if (offsets != null && rows + moreRows + 1 > offsets.length) {
        int[] n = new int[Math.max(offsets.length * 2, rows + moreRows + 1)];
        System.arraycopy(offsets, 0, n, 0, rows + 1);
        offsets = n;
      }
      int need = dataLen + moreBytes;
      if (need > data.length) {
        byte[] n = new byte[Math.max(data.length * 2, need)];
        System.arraycopy(data, 0, n, 0, dataLen);
        data = n;
      }
    }

    private void mark(boolean isValid) {
      if (!isValid && valid == null) {
        // first null: materialize validity as all-valid so far
        valid = new byte[Math.max(rows + 8, 8)];
        java.util.Arrays.fill(valid, 0, rows, (byte) 1);
      }
      if (valid != null) {
        if (rows >= valid.length) {
          byte[] n = new byte[valid.length * 2];
          System.arraycopy(valid, 0, n, 0, rows);
          valid = n;
        }
        valid[rows] = (byte) (isValid ? 1 : 0);
      }
      if (!isValid) {
        nulls++;
      }
    }

    private void putFixed(long bits, boolean isValid) {
      ensure(1, width);
      mark(isValid);
      for (int i = 0; i < width; i++) {
        data[dataLen + i] = (byte) (bits >>> (8 * i));
      }
      dataLen += width;
      if (offsets != null) {
        offsets[rows + 1] = dataLen;
      }
      rows++;
    }

    public Builder append(boolean v) {
      putFixed(v ? 1 : 0, true);
      return this;
    }

    public Builder append(byte v) {
      putFixed(v, true);
      return this;
    }

    public Builder append(short v) {
      putFixed(v, true);
      return this;
    }

    public Builder append(int v) {
      putFixed(v, true);
      return this;
    }

    public Builder append(long v) {
      putFixed(v, true);
      return this;
    }

    public Builder append(float v) {
      putFixed(Float.floatToIntBits(v) & 0xFFFFFFFFL, true);
      return this;
    }

    public Builder append(double v) {
      putFixed(Double.doubleToLongBits(v), true);
      return this;
    }

    public Builder append(String v) {
      byte[] b = v.getBytes(StandardCharsets.UTF_8);
      ensure(1, b.length);
      mark(true);
      System.arraycopy(b, 0, data, dataLen, b.length);
      dataLen += b.length;
      offsets[rows + 1] = dataLen;
      rows++;
      return this;
    }

    public Builder appendNull() {
      if (offsets != null) {
        ensure(1, 0);
        mark(false);
        offsets[rows + 1] = dataLen;
        rows++;
      } else {
        putFixed(0, false);
      }
      return this;
    }

    public HostColumnVector build() {
      byte[] d = java.util.Arrays.copyOf(data, dataLen);
      byte[] v = valid == null ? null
          : java.util.Arrays.copyOf(valid, rows);
      int[] o = offsets == null ? null
          : java.util.Arrays.copyOf(offsets, rows + 1);
      return new HostColumnVector(type, rows, nulls, d, v, o);
    }

    @Override
    public void close() {
    }
  }
}
