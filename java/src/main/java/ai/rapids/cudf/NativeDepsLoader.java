/*
 * Native dependency loader — the ai.rapids.cudf.NativeDepsLoader
 * contract (SURVEY.md §3.3): extract + load per-platform shared
 * libraries staged under /${os.arch}/${os.name}/ in the jar, loadable
 * by name, idempotent. The reference's repo-local loader delegates to
 * this class (NativeLibraryLoader.java:26-35); here both loaders share
 * one implementation since the TPU runtime ships a single shim library
 * instead of the libcudf/libcudfjni pair.
 */
package ai.rapids.cudf;

import java.io.IOException;
import java.io.InputStream;
import java.nio.file.Files;
import java.nio.file.Path;
import java.nio.file.StandardCopyOption;
import java.util.HashSet;
import java.util.Set;

public class NativeDepsLoader {
  private static final Set<String> loaded = new HashSet<>();

  /** Load the runtime's own native deps (the libcudf.so/libcudfjni.so
   * analog: here the single libspark_rapids_tpu.so). */
  public static synchronized void loadNativeDeps() {
    loadNativeDeps(new String[] {"spark_rapids_tpu"});
  }

  /** Load the named libraries, each once, resource-first. */
  public static synchronized void loadNativeDeps(String[] libNames) {
    for (String name : libNames) {
      if (loaded.contains(name)) {
        continue;
      }
      loadDep(name);
      loaded.add(name);
    }
  }

  private static void loadDep(String name) {
    String explicit = System.getProperty("spark.rapids.tpu.native.lib");
    if (explicit != null && !explicit.isEmpty() && name.equals("spark_rapids_tpu")) {
      System.load(explicit);
      return;
    }
    String resource = "/" + System.getProperty("os.arch") + "/"
        + System.getProperty("os.name") + "/lib" + name + ".so";
    try (InputStream in = NativeDepsLoader.class.getResourceAsStream(resource)) {
      if (in != null) {
        Path tmp = Files.createTempFile("lib" + name, ".so");
        tmp.toFile().deleteOnExit();
        Files.copy(in, tmp, StandardCopyOption.REPLACE_EXISTING);
        System.load(tmp.toAbsolutePath().toString());
        return;
      }
    } catch (IOException e) {
      throw new RuntimeException("failed to extract " + resource, e);
    }
    System.loadLibrary(name);
  }
}
