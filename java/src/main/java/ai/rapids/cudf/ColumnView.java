/*
 * Read-only view of a column: (dtype, row count, data buffer, validity).
 *
 * Plays the role of ai.rapids.cudf.ColumnView in the reference layer map
 * (SURVEY.md L4; imported at RowConversion.java:21) — the non-owning
 * handle type the repo-local API accepts (convertFromRows takes a
 * ColumnView, RowConversion.java:113). In the TPU runtime a view is a
 * pair of registry buffer handles (data + optional validity) instead of
 * a cudf column_view pointer; validity is a byte-per-row 0/1 vector, the
 * C ABI convention (c_api.h srt_pack_rows col_valid).
 */
package ai.rapids.cudf;

import com.nvidia.spark.rapids.jni.HostBuffer;

public class ColumnView {
  protected final DType type;
  protected final long rows;
  protected HostBuffer data;
  protected HostBuffer valid; // null when the column has no nulls

  /** For LIST columns (packed row batches): bytes per list element. */
  protected final int listElementSize;

  public ColumnView(DType type, long rows, HostBuffer data, HostBuffer valid) {
    this(type, rows, data, valid, 0);
  }

  ColumnView(DType type, long rows, HostBuffer data, HostBuffer valid,
             int listElementSize) {
    this.type = type;
    this.rows = rows;
    this.data = data;
    this.valid = valid;
    this.listElementSize = listElementSize;
  }

  public DType getType() {
    return type;
  }

  public long getRowCount() {
    return rows;
  }

  /** Closed columns (ColumnVector.close() nulls the buffers) must fail
   * with a diagnostic, not an NPE deep in the registry. */
  protected final void requireOpen() {
    if (data == null) {
      throw new IllegalStateException("column already closed");
    }
  }

  public long getNullCount() {
    requireOpen();
    if (valid == null) {
      return 0;
    }
    long count = 0;
    for (byte b : valid.toByteArray()) {
      if (b == 0) {
        count++;
      }
    }
    return count;
  }

  public boolean hasNulls() {
    return getNullCount() > 0;
  }

  /** Registry handle of the data buffer — the jlong the JNI layer
   * passes (the getNativeView() analog, RowConversion.java:105). */
  public long getNativeView() {
    requireOpen();
    return data.getHandle();
  }

  public HostBuffer getData() {
    requireOpen();
    return data;
  }

  public HostBuffer getValid() {
    requireOpen();
    return valid;
  }

  /** For LIST row-batch columns: the fixed byte width of each element. */
  public int getListElementSize() {
    return listElementSize;
  }

  public boolean isNull(long row) {
    requireOpen();
    if (valid == null) {
      return false;
    }
    return valid.toByteArray()[(int) row] == 0;
  }
}
