/*
 * Column data type: (type id, decimal scale).
 *
 * API-compatible with the ai.rapids.cudf.DType surface the Spark plugin
 * and the reference's repo-local layer consume (RowConversion.java:19-22
 * imports it; RowConversion.java:119-120 calls
 * getTypeId().getNativeId()/getScale() to build the JNI wire arrays;
 * RowConversionJni.cpp:56-61 reconstructs types from those arrays).
 * Native ids match cudf 22.04 type_id values and the TPU runtime's
 * spark_rapids_jni_tpu.dtype.TypeId — one id space across Java, C and
 * Python.
 */
package ai.rapids.cudf;

import java.util.Objects;

public final class DType {

  public enum DTypeEnum {
    EMPTY(0, 0),
    INT8(1, 1),
    INT16(2, 2),
    INT32(3, 4),
    INT64(4, 8),
    UINT8(5, 1),
    UINT16(6, 2),
    UINT32(7, 4),
    UINT64(8, 8),
    FLOAT32(9, 4),
    FLOAT64(10, 8),
    BOOL8(11, 1),
    TIMESTAMP_DAYS(12, 4),
    TIMESTAMP_SECONDS(13, 8),
    TIMESTAMP_MILLISECONDS(14, 8),
    TIMESTAMP_MICROSECONDS(15, 8),
    TIMESTAMP_NANOSECONDS(16, 8),
    DURATION_DAYS(17, 4),
    DURATION_SECONDS(18, 8),
    DURATION_MILLISECONDS(19, 8),
    DURATION_MICROSECONDS(20, 8),
    DURATION_NANOSECONDS(21, 8),
    DICTIONARY32(22, 4),
    STRING(23, 0),
    LIST(24, 0),
    DECIMAL32(25, 4),
    DECIMAL64(26, 8),
    DECIMAL128(27, 16),
    STRUCT(28, 0);

    final int nativeId;
    final int sizeInBytes;

    DTypeEnum(int nativeId, int sizeInBytes) {
      this.nativeId = nativeId;
      this.sizeInBytes = sizeInBytes;
    }

    public int getNativeId() {
      return nativeId;
    }
  }

  public static final DType EMPTY = new DType(DTypeEnum.EMPTY);
  public static final DType INT8 = new DType(DTypeEnum.INT8);
  public static final DType INT16 = new DType(DTypeEnum.INT16);
  public static final DType INT32 = new DType(DTypeEnum.INT32);
  public static final DType INT64 = new DType(DTypeEnum.INT64);
  public static final DType UINT8 = new DType(DTypeEnum.UINT8);
  public static final DType UINT16 = new DType(DTypeEnum.UINT16);
  public static final DType UINT32 = new DType(DTypeEnum.UINT32);
  public static final DType UINT64 = new DType(DTypeEnum.UINT64);
  public static final DType FLOAT32 = new DType(DTypeEnum.FLOAT32);
  public static final DType FLOAT64 = new DType(DTypeEnum.FLOAT64);
  public static final DType BOOL8 = new DType(DTypeEnum.BOOL8);
  public static final DType TIMESTAMP_DAYS = new DType(DTypeEnum.TIMESTAMP_DAYS);
  public static final DType TIMESTAMP_SECONDS = new DType(DTypeEnum.TIMESTAMP_SECONDS);
  public static final DType TIMESTAMP_MILLISECONDS =
      new DType(DTypeEnum.TIMESTAMP_MILLISECONDS);
  public static final DType TIMESTAMP_MICROSECONDS =
      new DType(DTypeEnum.TIMESTAMP_MICROSECONDS);
  public static final DType TIMESTAMP_NANOSECONDS =
      new DType(DTypeEnum.TIMESTAMP_NANOSECONDS);
  public static final DType DURATION_DAYS = new DType(DTypeEnum.DURATION_DAYS);
  public static final DType DURATION_SECONDS = new DType(DTypeEnum.DURATION_SECONDS);
  public static final DType DURATION_MILLISECONDS =
      new DType(DTypeEnum.DURATION_MILLISECONDS);
  public static final DType DURATION_MICROSECONDS =
      new DType(DTypeEnum.DURATION_MICROSECONDS);
  public static final DType DURATION_NANOSECONDS =
      new DType(DTypeEnum.DURATION_NANOSECONDS);
  public static final DType STRING = new DType(DTypeEnum.STRING);
  public static final DType LIST = new DType(DTypeEnum.LIST);
  public static final DType STRUCT = new DType(DTypeEnum.STRUCT);

  private final DTypeEnum typeId;
  /** Decimal scale; value = unscaled * 10^scale (cudf convention, so
   * decimal scales are typically negative). 0 for non-decimals. */
  private final int scale;

  private DType(DTypeEnum id) {
    this(id, 0);
  }

  private DType(DTypeEnum id, int scale) {
    this.typeId = id;
    this.scale = scale;
  }

  public static DType create(DTypeEnum id) {
    if (id == DTypeEnum.DECIMAL32 || id == DTypeEnum.DECIMAL64
        || id == DTypeEnum.DECIMAL128) {
      throw new IllegalArgumentException(
          "decimal types need a scale: use create(id, scale)");
    }
    return new DType(id);
  }

  public static DType create(DTypeEnum id, int scale) {
    return new DType(id, scale);
  }

  /** Rebuild from the (nativeId, scale) wire pair the JNI marshals
   * (RowConversionJni.cpp:56-61). */
  public static DType fromNative(int nativeId, int scale) {
    for (DTypeEnum e : DTypeEnum.values()) {
      if (e.nativeId == nativeId) {
        return new DType(e, scale);
      }
    }
    throw new IllegalArgumentException("unknown native type id " + nativeId);
  }

  public DTypeEnum getTypeId() {
    return typeId;
  }

  public int getScale() {
    return scale;
  }

  public int getSizeInBytes() {
    return typeId.sizeInBytes;
  }

  public boolean isFixedWidth() {
    return typeId.sizeInBytes > 0 && typeId != DTypeEnum.DICTIONARY32;
  }

  public boolean isDecimalType() {
    return typeId == DTypeEnum.DECIMAL32 || typeId == DTypeEnum.DECIMAL64
        || typeId == DTypeEnum.DECIMAL128;
  }

  public boolean isTimestampType() {
    return typeId.nativeId >= DTypeEnum.TIMESTAMP_DAYS.nativeId
        && typeId.nativeId <= DTypeEnum.TIMESTAMP_NANOSECONDS.nativeId;
  }

  @Override
  public boolean equals(Object o) {
    if (this == o) {
      return true;
    }
    if (!(o instanceof DType)) {
      return false;
    }
    DType other = (DType) o;
    return typeId == other.typeId && scale == other.scale;
  }

  @Override
  public int hashCode() {
    return Objects.hash(typeId, scale);
  }

  @Override
  public String toString() {
    return isDecimalType() ? typeId + "(scale=" + scale + ")" : typeId.toString();
  }
}
