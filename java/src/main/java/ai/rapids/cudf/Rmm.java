/*
 * Memory-configuration entry point — the ai.rapids.cudf.Rmm surface the
 * spark-rapids plugin initializes device memory through
 * (GpuDeviceManager calls Rmm.initialize(mode, logConf, poolSize) once
 * per executor; RMM_LOGGING_LEVEL, reference pom.xml:82).
 *
 * TPU redesign: XLA/PJRT owns the allocator, so there is no pool to
 * create — what this runtime can honor is the BUDGET the pool size
 * expresses and the logging the log config asks for. initialize()
 * therefore maps its arguments onto the runtime's flag plane
 * (utils/config.py): poolSize -> spark.rapids.tpu.hbm.budget.gb (the
 * ante-hoc footprint planner's ceiling, utils/hbm.py), logging ->
 * spark.rapids.tpu.alloc.log.level (the hbm/handles observability
 * channels, utils/log.py). Plugin code calling the cudf sequence works
 * unchanged; the semantics move from "create a pool" to "bound and
 * observe the planner", which is the strongest contract an XLA-owned
 * allocator admits.
 */
package ai.rapids.cudf;

public final class Rmm {
  /** Allocation modes (cudf RmmAllocationMode values). Under XLA the
   * distinction is advisory: PJRT preallocates per its own policy. */
  public static final int ALLOCATION_MODE_CUDA_DEFAULT = 0;
  public static final int ALLOCATION_MODE_POOL = 1;
  public static final int ALLOCATION_MODE_ARENA = 2;
  public static final int ALLOCATION_MODE_ASYNC = 3;

  private static boolean initialized = false;
  private static long poolSizeBytes = 0;
  private static int mode = ALLOCATION_MODE_CUDA_DEFAULT;

  private Rmm() {
  }

  /**
   * Configure the device-memory plane. Idempotent-hostile like cudf
   * (double-initialize throws): the plugin relies on that to catch
   * executor misconfiguration.
   *
   * @param allocationMode one of the ALLOCATION_MODE_* constants
   *                       (advisory under XLA)
   * @param enableLogging  route allocation-plane events to stderr
   *                       (the hbm/handles channels at DEBUG)
   * @param poolSize       planner budget in bytes; <=0 keeps the
   *                       backend default (v5e: 16 GiB)
   */
  public static synchronized void initialize(int allocationMode,
                                             boolean enableLogging,
                                             long poolSize) {
    if (initialized) {
      throw new IllegalStateException("RMM already initialized");
    }
    // native flag plane first: a failure here must leave NO partial
    // configuration behind (a retry with corrected args would otherwise
    // run under stale properties from the failed attempt)
    long size = Math.max(poolSize, 0);
    String gb = size > 0
        ? Double.toString(size / (1024.0 * 1024.0 * 1024.0)) : null;
    if (gb != null) {
      com.nvidia.spark.rapids.jni.DeviceTable.setRuntimeFlag(
          "SPARK_RAPIDS_TPU_HBM_BUDGET_GB", gb);
    }
    if (enableLogging) {
      com.nvidia.spark.rapids.jni.DeviceTable.setRuntimeFlag(
          "SPARK_RAPIDS_TPU_ALLOC_LOG_LEVEL", "DEBUG");
    }
    if (gb != null) {
      System.setProperty("spark.rapids.tpu.hbm.budget.gb", gb);
    }
    if (enableLogging) {
      System.setProperty("spark.rapids.tpu.alloc.log.level", "DEBUG");
    }
    mode = allocationMode;
    poolSizeBytes = size;
    initialized = true;
  }

  public static synchronized boolean isInitialized() {
    return initialized;
  }

  /** The configured planner budget in bytes (0 = backend default). */
  public static synchronized long getPoolSize() {
    return poolSizeBytes;
  }

  public static synchronized int getAllocationMode() {
    return mode;
  }

  /** Tear down the Java-side configuration (cudf shutdown contract:
   * re-initializable afterwards). Deliberately does NOT touch the
   * process environment: the embedded runtime snapshotted it at init
   * (so unsetenv would be invisible there anyway), and glibc
   * setenv/unsetenv racing getenv in live runtime threads is undefined
   * behavior. */
  public static synchronized void shutdown() {
    initialized = false;
    poolSizeBytes = 0;
    mode = ALLOCATION_MODE_CUDA_DEFAULT;
    System.clearProperty("spark.rapids.tpu.hbm.budget.gb");
    System.clearProperty("spark.rapids.tpu.alloc.log.level");
  }
}
