/*
 * An owned, refcounted column.
 *
 * Plays the role of ai.rapids.cudf.ColumnVector (SURVEY.md L4;
 * RowConversion.java:106-110 wraps released native pointers in it). The
 * reference's ownership model — Java controls lifetime, refcount-debug
 * mode catches leaks (pom.xml:86,199) — maps onto the runtime's handle
 * registry: close() releases the registry buffers, incRefCount() layers
 * a Java-side count on top, and HostBuffer.liveHandleCount() is the leak
 * oracle the tests assert on (SURVEY.md §4).
 *
 * Buffers are little-endian fixed-width host arrays (BOOL8 = 1 byte),
 * exactly what the C ABI's row codec consumes (c_api.h srt_pack_rows).
 */
package ai.rapids.cudf;

import com.nvidia.spark.rapids.jni.HostBuffer;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;

public final class ColumnVector extends ColumnView implements AutoCloseable {
  private int refCount = 1;

  public ColumnVector(DType type, long rows, HostBuffer data, HostBuffer valid) {
    super(type, rows, data, valid);
  }

  ColumnVector(DType type, long rows, HostBuffer data, HostBuffer valid,
               int listElementSize) {
    super(type, rows, data, valid, listElementSize);
  }

  public synchronized ColumnVector incRefCount() {
    if (refCount <= 0) {
      throw new IllegalStateException("column already closed");
    }
    refCount++;
    return this;
  }

  @Override
  public synchronized void close() {
    if (refCount <= 0) {
      // the double-close class of bug the refcount-debug mode exists to
      // catch: fail loudly instead of driving the count negative
      throw new IllegalStateException("close called too many times");
    }
    refCount--;
    if (refCount == 0) {
      if (data != null) {
        data.close();
        data = null;
      }
      if (valid != null) {
        valid.close();
        valid = null;
      }
    }
  }

  /* ---- factories (the Table.TestBuilder substrate) ------------------- */

  public static ColumnVector fromLongs(long... values) {
    ByteBuffer bb = alloc(values.length * 8);
    for (long v : values) {
      bb.putLong(v);
    }
    return fixedWidth(DType.INT64, values.length, bb, null);
  }

  public static ColumnVector fromInts(int... values) {
    ByteBuffer bb = alloc(values.length * 4);
    for (int v : values) {
      bb.putInt(v);
    }
    return fixedWidth(DType.INT32, values.length, bb, null);
  }

  public static ColumnVector fromDoubles(double... values) {
    ByteBuffer bb = alloc(values.length * 8);
    for (double v : values) {
      bb.putDouble(v);
    }
    return fixedWidth(DType.FLOAT64, values.length, bb, null);
  }

  public static ColumnVector fromFloats(float... values) {
    ByteBuffer bb = alloc(values.length * 4);
    for (float v : values) {
      bb.putFloat(v);
    }
    return fixedWidth(DType.FLOAT32, values.length, bb, null);
  }

  public static ColumnVector fromBooleans(boolean... values) {
    ByteBuffer bb = alloc(values.length);
    for (boolean v : values) {
      bb.put((byte) (v ? 1 : 0));
    }
    return fixedWidth(DType.BOOL8, values.length, bb, null);
  }

  public static ColumnVector fromBytes(byte... values) {
    ByteBuffer bb = alloc(values.length);
    bb.put(values);
    return fixedWidth(DType.INT8, values.length, bb, null);
  }

  public static ColumnVector fromShorts(short... values) {
    ByteBuffer bb = alloc(values.length * 2);
    for (short v : values) {
      bb.putShort(v);
    }
    return fixedWidth(DType.INT16, values.length, bb, null);
  }

  /* Boxed variants: null entries become nulls in the column. */

  public static ColumnVector fromBoxedLongs(Long... values) {
    return fromBoxed(DType.INT64, values);
  }

  public static ColumnVector fromBoxedInts(Integer... values) {
    return fromBoxed(DType.INT32, values);
  }

  public static ColumnVector fromBoxedDoubles(Double... values) {
    return fromBoxed(DType.FLOAT64, values);
  }

  public static ColumnVector fromBoxedFloats(Float... values) {
    return fromBoxed(DType.FLOAT32, values);
  }

  public static ColumnVector fromBoxedBooleans(Boolean... values) {
    return fromBoxed(DType.BOOL8, values);
  }

  public static ColumnVector fromBoxedBytes(Byte... values) {
    return fromBoxed(DType.INT8, values);
  }

  public static ColumnVector fromBoxedShorts(Short... values) {
    return fromBoxed(DType.INT16, values);
  }

  /** DECIMAL32: unscaled int values; value = unscaled * 10^scale. */
  public static ColumnVector decimalFromBoxedInts(int scale, Integer... unscaled) {
    return fromBoxed(DType.create(DType.DTypeEnum.DECIMAL32, scale), unscaled);
  }

  /** DECIMAL64: unscaled long values. */
  public static ColumnVector decimalFromBoxedLongs(int scale, Long... unscaled) {
    return fromBoxed(DType.create(DType.DTypeEnum.DECIMAL64, scale), unscaled);
  }

  public static ColumnVector timestampMillisecondsFromBoxedLongs(Long... values) {
    return fromBoxed(DType.TIMESTAMP_MILLISECONDS, values);
  }

  /** Wrap a packed row batch (rowSize bytes per row) as a LIST<INT8>
   * column — the output shape of convertToRows (row_conversion.cu:405-406:
   * sequence offsets over one INT8 child). Offsets stay implicit because
   * every list element has the same fixed size. */
  public static ColumnVector fromPackedRows(HostBuffer rows, long numRows,
                                            int rowSize) {
    return new ColumnVector(DType.LIST, numRows, rows, null, rowSize);
  }

  private static ColumnVector fromBoxed(DType type, Object[] values) {
    int width = type.getSizeInBytes();
    ByteBuffer bb = alloc(values.length * width);
    byte[] validity = new byte[values.length];
    boolean anyNull = false;
    for (int i = 0; i < values.length; i++) {
      Object v = values[i];
      validity[i] = (byte) (v == null ? 0 : 1);
      anyNull |= v == null;
      putValue(bb, type, v);
    }
    HostBuffer valid = anyNull ? HostBuffer.create(validity, "validity") : null;
    return fixedWidth(type, values.length, bb, valid);
  }

  private static void putValue(ByteBuffer bb, DType type, Object v) {
    switch (type.getTypeId()) {
      case INT64:
      case UINT64:
      case DECIMAL64:
      case TIMESTAMP_SECONDS:
      case TIMESTAMP_MILLISECONDS:
      case TIMESTAMP_MICROSECONDS:
      case TIMESTAMP_NANOSECONDS:
      case DURATION_SECONDS:
      case DURATION_MILLISECONDS:
      case DURATION_MICROSECONDS:
      case DURATION_NANOSECONDS:
        bb.putLong(v == null ? 0L : ((Number) v).longValue());
        break;
      case INT32:
      case UINT32:
      case DECIMAL32:
      case TIMESTAMP_DAYS:
      case DURATION_DAYS:
        bb.putInt(v == null ? 0 : ((Number) v).intValue());
        break;
      case INT16:
      case UINT16:
        bb.putShort(v == null ? 0 : ((Number) v).shortValue());
        break;
      case INT8:
      case UINT8:
        bb.put(v == null ? 0 : ((Number) v).byteValue());
        break;
      case FLOAT64:
        bb.putDouble(v == null ? 0 : ((Number) v).doubleValue());
        break;
      case FLOAT32:
        bb.putFloat(v == null ? 0 : ((Number) v).floatValue());
        break;
      case BOOL8:
        bb.put((byte) (v != null && (Boolean) v ? 1 : 0));
        break;
      default:
        throw new IllegalArgumentException("not fixed-width: " + type);
    }
  }

  private static ByteBuffer alloc(int nbytes) {
    return ByteBuffer.allocate(nbytes).order(ByteOrder.LITTLE_ENDIAN);
  }

  private static ColumnVector fixedWidth(DType type, long rows, ByteBuffer bb,
                                         HostBuffer valid) {
    HostBuffer data = HostBuffer.create(bb.array(), "column");
    return new ColumnVector(type, rows, data, valid);
  }

  /* ---- element access (test/debug path; not the hot path) ------------ */

  public long getLong(long row) {
    return bufferAt(row, 8).getLong();
  }

  public int getInt(long row) {
    return bufferAt(row, 4).getInt();
  }

  public double getDouble(long row) {
    return bufferAt(row, 8).getDouble();
  }

  public float getFloat(long row) {
    return bufferAt(row, 4).getFloat();
  }

  public boolean getBoolean(long row) {
    return bufferAt(row, 1).get() != 0;
  }

  public byte getByte(long row) {
    return bufferAt(row, 1).get();
  }

  public short getShort(long row) {
    return bufferAt(row, 2).getShort();
  }

  private ByteBuffer bufferAt(long row, int width) {
    requireOpen();
    byte[] all = data.toByteArray();
    ByteBuffer bb = ByteBuffer.wrap(all).order(ByteOrder.LITTLE_ENDIAN);
    bb.position((int) (row * width));
    return bb;
  }
}
