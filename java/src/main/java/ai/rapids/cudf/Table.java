/*
 * A set of equal-length columns.
 *
 * Plays the role of ai.rapids.cudf.Table (SURVEY.md L4; the repo-local
 * API's input/output type, RowConversion.java:104,123). Includes the
 * TestBuilder fixture pattern the reference test suite is built on
 * (RowConversionTest.java:30-39 builds its 8-column table with it) — the
 * fixture shape downstream consumers reuse via the -tests jar
 * (SURVEY.md §4 test packaging).
 */
package ai.rapids.cudf;

import com.nvidia.spark.rapids.jni.HostBuffer;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.util.ArrayList;
import java.util.List;

public final class Table implements AutoCloseable {
  private final ColumnVector[] columns;
  private final long rows;

  /** Takes ownership of the columns (they are NOT ref-counted up) —
   * including on construction failure, where they are closed before the
   * throw so the caller can't leak what it no longer owns. */
  public Table(ColumnVector... columns) {
    if (columns.length == 0) {
      throw new IllegalArgumentException("table needs at least one column");
    }
    long rows0 = columns[0].getRowCount();
    for (ColumnVector c : columns) {
      if (c.getRowCount() != rows0) {
        for (ColumnVector toClose : columns) {
          try {
            toClose.close();
          } catch (RuntimeException ignored) {
            // keep closing the rest; the mismatch error wins
          }
        }
        throw new IllegalArgumentException("column row counts differ");
      }
    }
    this.columns = columns;
    this.rows = rows0;
  }

  public long getRowCount() {
    return rows;
  }

  public int getNumberOfColumns() {
    return columns.length;
  }

  public ColumnVector getColumn(int i) {
    return columns[i];
  }

  /** Registry handle standing in for the native table view jlong
   * (RowConversion.java:105): the concatenated host layout the JNI
   * bridge validates and walks (RowConversionJni.cpp — data buffers
   * back to back, then per-column validity byte vectors). Caller owns
   * the returned buffer. */
  public HostBuffer packForNative() {
    int n = columns.length;
    long dataBytes = 0;
    for (ColumnVector c : columns) {
      dataBytes += (long) c.getType().getSizeInBytes() * rows;
    }
    long total = dataBytes + (long) n * rows;
    if (total > Integer.MAX_VALUE) {
      throw new IllegalStateException("host table layout exceeds 2GB");
    }
    ByteBuffer bb = ByteBuffer.allocate((int) total).order(ByteOrder.LITTLE_ENDIAN);
    for (ColumnVector c : columns) {
      bb.put(c.getData().toByteArray());
    }
    for (ColumnVector c : columns) {
      if (c.getValid() != null) {
        bb.put(c.getValid().toByteArray());
      } else {
        for (long r = 0; r < rows; r++) {
          bb.put((byte) 1);
        }
      }
    }
    return HostBuffer.create(bb.array(), "table");
  }

  @Override
  public void close() {
    for (ColumnVector c : columns) {
      c.close();
    }
  }

  /* ---- TestBuilder ---------------------------------------------------- */

  public static final class TestBuilder {
    private final List<ColumnVector> cols = new ArrayList<>();

    public TestBuilder column(Long... values) {
      cols.add(ColumnVector.fromBoxedLongs(values));
      return this;
    }

    public TestBuilder column(Double... values) {
      cols.add(ColumnVector.fromBoxedDoubles(values));
      return this;
    }

    public TestBuilder column(Integer... values) {
      cols.add(ColumnVector.fromBoxedInts(values));
      return this;
    }

    public TestBuilder column(Boolean... values) {
      cols.add(ColumnVector.fromBoxedBooleans(values));
      return this;
    }

    public TestBuilder column(Float... values) {
      cols.add(ColumnVector.fromBoxedFloats(values));
      return this;
    }

    public TestBuilder column(Byte... values) {
      cols.add(ColumnVector.fromBoxedBytes(values));
      return this;
    }

    public TestBuilder column(Short... values) {
      cols.add(ColumnVector.fromBoxedShorts(values));
      return this;
    }

    public TestBuilder decimal32Column(int scale, Integer... unscaled) {
      cols.add(ColumnVector.decimalFromBoxedInts(scale, unscaled));
      return this;
    }

    public TestBuilder decimal64Column(int scale, Long... unscaled) {
      cols.add(ColumnVector.decimalFromBoxedLongs(scale, unscaled));
      return this;
    }

    public TestBuilder timestampMillisecondsColumn(Long... values) {
      cols.add(ColumnVector.timestampMillisecondsFromBoxedLongs(values));
      return this;
    }

    public Table build() {
      return new Table(cols.toArray(new ColumnVector[0]));
    }
  }
}
