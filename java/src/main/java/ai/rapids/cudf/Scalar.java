/*
 * A typed scalar value — the ai.rapids.cudf.Scalar surface the
 * spark-rapids plugin binds literals through (cudf java/src/main/java/
 * ai/rapids/cudf/Scalar.java; every GpuLiteral lowers to one).
 *
 * TPU redesign: cudf keeps scalars DEVICE-resident (a cudf::scalar
 * allocation) because CUDA kernels dereference them at launch. Under
 * XLA a literal is either baked into the compiled graph as a constant
 * or shipped as a one-element operand, so the natural representation is
 * a HOST value: this class is a pure-Java value holder with no native
 * handle and no close-ordering hazard. When a scalar must ride a device
 * op it serializes through the existing wire as a 1-row column
 * (DeviceTable.tableOp), which XLA then fuses as a broadcast operand —
 * the same end state as cudf's device scalar, minus one allocation and
 * one JNI crossing per literal.
 */
package ai.rapids.cudf;

public final class Scalar implements AutoCloseable {
  private final DType type;
  private final boolean valid;
  private final long intBits;     // integer families + timestamps + bool
  private final double floatBits; // float families
  private final byte[] utf8;      // STRING payload

  private Scalar(DType type, boolean valid, long intBits,
                 double floatBits, byte[] utf8) {
    this.type = type;
    this.valid = valid;
    this.intBits = intBits;
    this.floatBits = floatBits;
    this.utf8 = utf8;
  }

  public static Scalar fromBool(boolean v) {
    return new Scalar(DType.BOOL8, true, v ? 1 : 0, 0, null);
  }

  public static Scalar fromByte(byte v) {
    return new Scalar(DType.INT8, true, v, 0, null);
  }

  public static Scalar fromShort(short v) {
    return new Scalar(DType.INT16, true, v, 0, null);
  }

  public static Scalar fromInt(int v) {
    return new Scalar(DType.INT32, true, v, 0, null);
  }

  public static Scalar fromLong(long v) {
    return new Scalar(DType.INT64, true, v, 0, null);
  }

  public static Scalar fromFloat(float v) {
    return new Scalar(DType.FLOAT32, true, 0, v, null);
  }

  public static Scalar fromDouble(double v) {
    return new Scalar(DType.FLOAT64, true, 0, v, null);
  }

  public static Scalar fromString(String v) {
    if (v == null) {
      return nullScalar(DType.STRING);
    }
    return new Scalar(DType.STRING, true, 0, 0,
                      v.getBytes(java.nio.charset.StandardCharsets.UTF_8));
  }

  /** Unscaled decimal value at the given scale (DECIMAL64 wire form). */
  public static Scalar fromDecimal(int scale, long unscaled) {
    return new Scalar(DType.create(DType.DTypeEnum.DECIMAL64, scale),
                      true, unscaled, 0, null);
  }

  public static Scalar timestampDaysFromInt(int days) {
    return new Scalar(DType.TIMESTAMP_DAYS, true, days, 0, null);
  }

  public static Scalar timestampFromLong(DType type, long value) {
    if (!type.isTimestampType()) {
      throw new IllegalArgumentException(type + " is not a timestamp");
    }
    return new Scalar(type, true, value, 0, null);
  }

  /** A null literal of the given type (GpuLiteral(null, t)). */
  public static Scalar nullScalar(DType type) {
    return new Scalar(type, false, 0, 0, null);
  }

  public DType getType() {
    return type;
  }

  public boolean isValid() {
    return valid;
  }

  public boolean getBoolean() {
    requireValid();
    return intBits != 0;
  }

  public byte getByte() {
    requireValid();
    return (byte) intBits;
  }

  public short getShort() {
    requireValid();
    return (short) intBits;
  }

  public int getInt() {
    requireValid();
    return (int) intBits;
  }

  public long getLong() {
    requireValid();
    return intBits;
  }

  public float getFloat() {
    requireValid();
    return (float) floatBits;
  }

  public double getDouble() {
    requireValid();
    return floatBits;
  }

  public String getJavaString() {
    requireValid();
    return new String(utf8, java.nio.charset.StandardCharsets.UTF_8);
  }

  public byte[] getUTF8() {
    requireValid();
    return utf8.clone();
  }

  /**
   * The value as its 8-byte little-endian wire form — what a 1-row
   * column of this type carries through DeviceTable.tableOp. STRING
   * scalars use {@link #getUTF8} instead.
   */
  public long getWireBits() {
    requireValid();
    if (type.equals(DType.FLOAT64)) {
      return Double.doubleToLongBits(floatBits);
    }
    if (type.equals(DType.FLOAT32)) {
      return Float.floatToIntBits((float) floatBits) & 0xFFFFFFFFL;
    }
    return intBits;
  }

  private void requireValid() {
    if (!valid) {
      throw new IllegalStateException("null scalar has no value");
    }
  }

  /** No native resources: close is a no-op kept for cudf API drop-in
   * compatibility (plugin code try-with-resources every Scalar). */
  @Override
  public void close() {
  }
}
