/* JNI bridge: com.nvidia.spark.rapids.jni.DeviceTable native methods.
 *
 * The device-compute entry the reference exposes per-op
 * (RowConversionJni.cpp:24-66 calling device kernels directly). Here one
 * generic thunk carries every table op into the embedded JAX runtime
 * (src/cpp/jax_runtime.cpp): the JVM passes registry buffer handles plus
 * the (type id, scale) wire arrays, and receives freshly-owned handles
 * for the result columns computed on the XLA backend — so a Spark
 * executor thread reaches the TPU through this .so exactly the way a
 * CUDA executor reaches the GPU through libspark_rapids_jni.so.
 *
 * Wire contract (see java/.../DeviceTable.java):
 *   tableOpNative(String opJson, int[] typeIds, int[] scales,
 *                 long[] colData, long[] colValid, long numRows)
 *       -> long[]: [numOutCols, outNumRows,
 *                   outTypeIds..., outScales...,
 *                   outDataHandles..., outValidHandles...]
 * (a single jlongArray return keeps the JNI surface one call; 0 in
 * outValidHandles means the column has no nulls). Compiles only when
 * CMake finds a JDK (SRT_HAVE_JNI). */

#ifdef SRT_HAVE_JNI

#include <jni.h>

#include <cstdint>
#include <string>
#include <vector>

#include "spark_rapids_tpu/c_api.h"

namespace {

void throw_java_dt(JNIEnv* env, const std::string& msg) {
  jclass cls = env->FindClass("java/lang/RuntimeException");
  if (cls != nullptr) env->ThrowNew(cls, msg.c_str());
}

constexpr int32_t kMaxOutColumns = 256;

}  // namespace

extern "C" {

JNIEXPORT jboolean JNICALL
Java_com_nvidia_spark_rapids_jni_DeviceTable_isDeviceRuntimeAvailable(
    JNIEnv*, jclass) {
  return srt_jax_available() == 1 ? JNI_TRUE : JNI_FALSE;
}

JNIEXPORT void JNICALL
Java_com_nvidia_spark_rapids_jni_DeviceTable_initDeviceRuntime(
    JNIEnv* env, jclass) {
  if (srt_jax_init() != SRT_OK) throw_java_dt(env, srt_last_error());
}

JNIEXPORT jstring JNICALL
Java_com_nvidia_spark_rapids_jni_DeviceTable_devicePlatform(
    JNIEnv* env, jclass) {
  char buf[64] = {0};
  if (srt_jax_platform(buf, sizeof buf) != SRT_OK) {
    throw_java_dt(env, srt_last_error());
    return nullptr;
  }
  return env->NewStringUTF(buf);
}

JNIEXPORT jlongArray JNICALL
Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpNative(
    JNIEnv* env, jclass, jstring op_json_j, jintArray type_ids_j,
    jintArray scales_j, jlongArray col_data_j, jlongArray col_valid_j,
    jlong num_rows) {
  if (op_json_j == nullptr || type_ids_j == nullptr ||
      scales_j == nullptr || col_data_j == nullptr ||
      col_valid_j == nullptr) {
    throw_java_dt(env, "null argument to tableOpNative");
    return nullptr;
  }
  jsize num_cols = env->GetArrayLength(type_ids_j);
  if (env->GetArrayLength(scales_j) != num_cols ||
      env->GetArrayLength(col_data_j) != num_cols ||
      env->GetArrayLength(col_valid_j) != num_cols) {
    throw_java_dt(env, "column array length mismatch");
    return nullptr;
  }
  std::vector<int32_t> type_ids(num_cols), scales(num_cols);
  std::vector<int64_t> col_data(num_cols), col_valid(num_cols);
  env->GetIntArrayRegion(type_ids_j, 0, num_cols, type_ids.data());
  env->GetIntArrayRegion(scales_j, 0, num_cols, scales.data());
  env->GetLongArrayRegion(col_data_j, 0, num_cols, col_data.data());
  env->GetLongArrayRegion(col_valid_j, 0, num_cols, col_valid.data());

  const char* op_json = env->GetStringUTFChars(op_json_j, nullptr);
  if (op_json == nullptr) return nullptr; /* OOM already thrown */

  int32_t out_ids[kMaxOutColumns];
  int32_t out_scales[kMaxOutColumns];
  srt_handle out_data[kMaxOutColumns];
  srt_handle out_valid[kMaxOutColumns];
  int32_t out_cols = 0;
  int64_t out_rows = 0;
  srt_status s = srt_jax_table_op(
      op_json, type_ids.data(), scales.data(), num_cols, col_data.data(),
      col_valid.data(), num_rows, kMaxOutColumns, out_ids, out_scales,
      &out_cols, out_data, out_valid, &out_rows);
  env->ReleaseStringUTFChars(op_json_j, op_json);
  if (s != SRT_OK) {
    throw_java_dt(env, srt_last_error());
    return nullptr;
  }

  /* [numOutCols, outNumRows, ids..., scales..., data..., valid...] */
  std::vector<jlong> packed(2 + 4 * static_cast<size_t>(out_cols));
  packed[0] = out_cols;
  packed[1] = out_rows;
  for (int32_t i = 0; i < out_cols; ++i) {
    packed[2 + i] = out_ids[i];
    packed[2 + out_cols + i] = out_scales[i];
    packed[2 + 2 * out_cols + i] = out_data[i];
    packed[2 + 3 * out_cols + i] = out_valid[i];
  }
  jlongArray result = env->NewLongArray(static_cast<jsize>(packed.size()));
  if (result == nullptr) {
    /* allocation failed: the result handles would leak in the registry */
    for (int32_t i = 0; i < out_cols; ++i) {
      srt_buffer_release(out_data[i]);
      if (out_valid[i] != 0) srt_buffer_release(out_valid[i]);
    }
    return nullptr;
  }
  env->SetLongArrayRegion(result, 0, static_cast<jsize>(packed.size()),
                          packed.data());
  return result;
}

/* ---- device-resident table chaining (srt_jax_table_* C ABI) --------- */

JNIEXPORT jlong JNICALL
Java_com_nvidia_spark_rapids_jni_DeviceTable_tableUploadNative(
    JNIEnv* env, jclass, jintArray type_ids_j, jintArray scales_j,
    jlongArray col_data_j, jlongArray col_valid_j, jlong num_rows) {
  if (type_ids_j == nullptr || scales_j == nullptr ||
      col_data_j == nullptr || col_valid_j == nullptr) {
    throw_java_dt(env, "null argument to tableUploadNative");
    return 0;
  }
  jsize num_cols = env->GetArrayLength(type_ids_j);
  if (env->GetArrayLength(scales_j) != num_cols ||
      env->GetArrayLength(col_data_j) != num_cols ||
      env->GetArrayLength(col_valid_j) != num_cols) {
    throw_java_dt(env, "column array length mismatch");
    return 0;
  }
  std::vector<int32_t> type_ids(num_cols), scales(num_cols);
  std::vector<int64_t> col_data(num_cols), col_valid(num_cols);
  env->GetIntArrayRegion(type_ids_j, 0, num_cols, type_ids.data());
  env->GetIntArrayRegion(scales_j, 0, num_cols, scales.data());
  env->GetLongArrayRegion(col_data_j, 0, num_cols, col_data.data());
  env->GetLongArrayRegion(col_valid_j, 0, num_cols, col_valid.data());
  srt_table out = 0;
  if (srt_jax_table_upload(type_ids.data(), scales.data(), num_cols,
                           col_data.data(), col_valid.data(), num_rows,
                           &out) != SRT_OK) {
    throw_java_dt(env, srt_last_error());
    return 0;
  }
  return out;
}

JNIEXPORT jlong JNICALL
Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpResidentNative(
    JNIEnv* env, jclass, jstring op_json_j, jlongArray inputs_j) {
  if (op_json_j == nullptr || inputs_j == nullptr) {
    throw_java_dt(env, "null argument to tableOpResidentNative");
    return 0;
  }
  jsize n = env->GetArrayLength(inputs_j);
  std::vector<int64_t> inputs(static_cast<size_t>(n));
  env->GetLongArrayRegion(inputs_j, 0, n, inputs.data());
  const char* op_json = env->GetStringUTFChars(op_json_j, nullptr);
  if (op_json == nullptr) return 0;
  srt_table out = 0;
  srt_status s =
      srt_jax_table_op_resident(op_json, inputs.data(), n, &out);
  env->ReleaseStringUTFChars(op_json_j, op_json);
  if (s != SRT_OK) {
    throw_java_dt(env, srt_last_error());
    return 0;
  }
  return out;
}

JNIEXPORT jlongArray JNICALL
Java_com_nvidia_spark_rapids_jni_DeviceTable_tableDownloadNative(
    JNIEnv* env, jclass, jlong table) {
  int32_t out_ids[kMaxOutColumns];
  int32_t out_scales[kMaxOutColumns];
  srt_handle out_data[kMaxOutColumns];
  srt_handle out_valid[kMaxOutColumns];
  int32_t out_cols = 0;
  int64_t out_rows = 0;
  if (srt_jax_table_download(table, kMaxOutColumns, out_ids, out_scales,
                             &out_cols, out_data, out_valid,
                             &out_rows) != SRT_OK) {
    throw_java_dt(env, srt_last_error());
    return nullptr;
  }
  std::vector<jlong> packed(2 + 4 * static_cast<size_t>(out_cols));
  packed[0] = out_cols;
  packed[1] = out_rows;
  for (int32_t i = 0; i < out_cols; ++i) {
    packed[2 + i] = out_ids[i];
    packed[2 + out_cols + i] = out_scales[i];
    packed[2 + 2 * out_cols + i] = out_data[i];
    packed[2 + 3 * out_cols + i] = out_valid[i];
  }
  jlongArray result = env->NewLongArray(static_cast<jsize>(packed.size()));
  if (result == nullptr) {
    for (int32_t i = 0; i < out_cols; ++i) {
      srt_buffer_release(out_data[i]);
      if (out_valid[i] != 0) srt_buffer_release(out_valid[i]);
    }
    return nullptr;
  }
  env->SetLongArrayRegion(result, 0, static_cast<jsize>(packed.size()),
                          packed.data());
  return result;
}

JNIEXPORT jlong JNICALL
Java_com_nvidia_spark_rapids_jni_DeviceTable_tableNumRows(JNIEnv* env,
                                                          jclass,
                                                          jlong table) {
  int64_t out = 0;
  if (srt_jax_table_num_rows(table, &out) != SRT_OK) {
    throw_java_dt(env, srt_last_error());
    return 0;
  }
  return out;
}

JNIEXPORT void JNICALL
Java_com_nvidia_spark_rapids_jni_DeviceTable_tableFree(JNIEnv* env, jclass,
                                                       jlong table) {
  if (srt_jax_table_free(table) != SRT_OK) {
    throw_java_dt(env, srt_last_error());
  }
}

JNIEXPORT void JNICALL
Java_com_nvidia_spark_rapids_jni_DeviceTable_setRuntimeFlag(
    JNIEnv* env, jclass, jstring name_j, jstring value_j) {
  if (name_j == nullptr) {
    throw_java_dt(env, "null flag name");
    return;
  }
  const char* name = env->GetStringUTFChars(name_j, nullptr);
  if (name == nullptr) return; /* OOM already thrown */
  const char* value = nullptr;
  if (value_j != nullptr) {
    value = env->GetStringUTFChars(value_j, nullptr);
    if (value == nullptr) {
      /* a failed value fetch must NOT fall through to the unset
       * branch (it would delete the flag instead of setting it), and
       * no further JNI calls are legal with the OOM pending */
      env->ReleaseStringUTFChars(name_j, name);
      return;
    }
  }
  srt_status s = srt_set_runtime_flag(name, value);
  env->ReleaseStringUTFChars(name_j, name);
  if (value != nullptr) env->ReleaseStringUTFChars(value_j, value);
  if (s != SRT_OK) throw_java_dt(env, srt_last_error());
}

JNIEXPORT jlong JNICALL
Java_com_nvidia_spark_rapids_jni_DeviceTable_residentTableCount(JNIEnv* env,
                                                                jclass) {
  int64_t out = 0;
  if (srt_jax_resident_table_count(&out) != SRT_OK) {
    throw_java_dt(env, srt_last_error());
    return 0;
  }
  return out;
}

}  // extern "C"

#endif /* SRT_HAVE_JNI */
