/* JNI bridge: com.nvidia.spark.rapids.jni.HostBuffer native methods over
 * the handle registry (src/cpp/handle_registry.cpp). Compiled only when
 * CMake finds a JDK (SRT_HAVE_JNI). */

#ifdef SRT_HAVE_JNI

#include <jni.h>

#include <cstdint>
#include <string>
#include <vector>

#include "spark_rapids_tpu/c_api.h"

namespace {

void throw_java(JNIEnv* env, const std::string& msg) {
  jclass cls = env->FindClass("java/lang/RuntimeException");
  if (cls != nullptr) env->ThrowNew(cls, msg.c_str());
}

}  // namespace

extern "C" {

JNIEXPORT jlong JNICALL
Java_com_nvidia_spark_rapids_jni_HostBuffer_bufferCreate(JNIEnv* env, jclass,
                                                         jbyteArray data_j,
                                                         jstring tag_j) {
  if (data_j == nullptr) {
    throw_java(env, "data is null");
    return 0;
  }
  jsize n = env->GetArrayLength(data_j);
  std::vector<int8_t> host(static_cast<size_t>(n));
  env->GetByteArrayRegion(data_j, 0, n, host.data());
  const char* tag = tag_j ? env->GetStringUTFChars(tag_j, nullptr) : nullptr;
  srt_handle h = srt_buffer_create(host.data(), n, tag ? tag : "");
  if (tag) env->ReleaseStringUTFChars(tag_j, tag);
  if (h == 0) throw_java(env, srt_last_error());
  return h;
}

JNIEXPORT jlong JNICALL
Java_com_nvidia_spark_rapids_jni_HostBuffer_bufferSize(JNIEnv* env, jclass,
                                                       jlong h) {
  int64_t n = srt_buffer_size(h);
  if (n < 0) throw_java(env, srt_last_error());
  return n;
}

JNIEXPORT jbyteArray JNICALL
Java_com_nvidia_spark_rapids_jni_HostBuffer_bufferGet(JNIEnv* env, jclass,
                                                      jlong h) {
  int64_t n = srt_buffer_size(h);
  void* data = srt_buffer_data(h);
  if (n < 0 || data == nullptr) {
    throw_java(env, srt_last_error());
    return nullptr;
  }
  jbyteArray out = env->NewByteArray(static_cast<jsize>(n));
  if (out == nullptr) return nullptr;
  env->SetByteArrayRegion(out, 0, static_cast<jsize>(n),
                          static_cast<const jbyte*>(data));
  return out;
}

JNIEXPORT void JNICALL
Java_com_nvidia_spark_rapids_jni_HostBuffer_bufferRelease(JNIEnv* env, jclass,
                                                          jlong h) {
  if (srt_buffer_release(h) != SRT_OK) throw_java(env, srt_last_error());
}

JNIEXPORT jlong JNICALL
Java_com_nvidia_spark_rapids_jni_HostBuffer_nativeLiveHandleCount(JNIEnv*,
                                                                  jclass) {
  return srt_live_handle_count();
}

/* RowConversion layout helpers (declared in RowConversion.java). */

JNIEXPORT jint JNICALL
Java_com_nvidia_spark_rapids_jni_RowConversion_rowSize(JNIEnv* env, jclass,
                                                       jintArray type_ids_j) {
  jsize n = env->GetArrayLength(type_ids_j);
  std::vector<int32_t> ids(static_cast<size_t>(n));
  env->GetIntArrayRegion(type_ids_j, 0, n, ids.data());
  std::vector<int32_t> offs(static_cast<size_t>(n)),
      widths(static_cast<size_t>(n));
  srt_row_layout layout{};
  if (srt_compute_row_layout(ids.data(), n, offs.data(), widths.data(),
                             &layout) != SRT_OK) {
    throw_java(env, srt_last_error());
    return 0;
  }
  return layout.row_size;
}

JNIEXPORT jlong JNICALL
Java_com_nvidia_spark_rapids_jni_RowConversion_maxRowsPerBatch(JNIEnv*, jclass,
                                                               jint row_size) {
  return srt_max_rows_per_batch(row_size);
}

}  /* extern "C" */

#endif /* SRT_HAVE_JNI */
