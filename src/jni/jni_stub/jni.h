/* Minimal clean-room JNI ABI surface for SYNTAX-CHECKING the bridge
 * sources in images without a JDK (tests/test_jni_compile.py).
 *
 * This is NOT a JNI implementation and is never linked into anything:
 * it declares just enough of the stable JNI ABI (types + the JNIEnv
 * member functions the bridge files call) for `g++ -fsyntax-only` to
 * typecheck the src/jni sources. Real builds use the JDK's jni.h (CMake's
 * find_package(JNI)); this stub is deliberately last on the include
 * path and guarded so it can never shadow a real JDK header.
 *
 * Written from the public JNI specification's type/function list; no
 * JDK header text was copied. */
#ifndef SRT_JNI_STUB_H
#define SRT_JNI_STUB_H

#ifdef __cplusplus

#include <cstdint>

#define JNIEXPORT __attribute__((visibility("default")))
#define JNICALL
#define JNI_TRUE 1
#define JNI_FALSE 0

typedef int32_t jint;
typedef int64_t jlong;
typedef int8_t jbyte;
typedef uint8_t jboolean;
typedef jint jsize;

class _jobject {
 public:
  /* polymorphic so the mock-JNIEnv test harness (src/jni_mock/) can
   * dynamic_cast its concrete array/string objects; a real JDK header
   * also declares _jobject as a class type, so bridge code can't
   * observe the difference */
  virtual ~_jobject() = default;
};
typedef _jobject* jobject;
typedef jobject jclass;
typedef jobject jstring;
typedef jobject jarray;
typedef jarray jbyteArray;
typedef jarray jintArray;
typedef jarray jlongArray;
typedef jobject jthrowable;

struct JNIEnv {
  jclass FindClass(const char* name);
  jint ThrowNew(jclass cls, const char* msg);
  jsize GetArrayLength(jarray array);
  void GetByteArrayRegion(jbyteArray array, jsize start, jsize len,
                          jbyte* buf);
  void GetIntArrayRegion(jintArray array, jsize start, jsize len,
                         jint* buf);
  void GetLongArrayRegion(jlongArray array, jsize start, jsize len,
                          jlong* buf);
  void SetByteArrayRegion(jbyteArray array, jsize start, jsize len,
                          const jbyte* buf);
  void SetLongArrayRegion(jlongArray array, jsize start, jsize len,
                          const jlong* buf);
  jbyteArray NewByteArray(jsize len);
  jlongArray NewLongArray(jsize len);
  jstring NewStringUTF(const char* utf);
  const char* GetStringUTFChars(jstring str, jboolean* is_copy);
  void ReleaseStringUTFChars(jstring str, const char* chars);
};

#endif /* __cplusplus */

#endif /* SRT_JNI_STUB_H */
