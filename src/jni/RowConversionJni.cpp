/* JNI bridge: com.nvidia.spark.rapids.jni.RowConversion native methods.
 *
 * Mirrors the conventions of the reference bridge
 * (RowConversionJni.cpp:22-68) over the C ABI instead of cudf:
 *   - jlong handles in/out (registry ids, not raw pointers — a stale
 *     handle raises instead of crashing);
 *   - (type id, scale) int arrays as the schema wire format
 *     (RowConversionJni.cpp:56-61);
 *   - null-handle guards and exception translation into
 *     RuntimeException (JNI_NULL_CHECK / CATCH_STD analogs).
 *
 * The device side lives in the embedded Python/JAX runtime; this bridge
 * serves the host fast path (UnsafeRow batches) and buffer hand-off. It
 * compiles only when CMake finds a JDK (SRT_HAVE_JNI).
 *
 * Wire contract (see java/.../RowConversion.java):
 *   convertToRows(long tableHandle, int[] typeIds, long numRows,
 *                 long startRow, long batchRows)
 *       -> long rowsHandle          (packed bytes for rows
 *                                    [startRow, startRow+batchRows),
 *                                    batch_rows * row_size)
 *   convertFromRows(long rowsHandle, int[] typeIds, int[] scales,
 *                   long numRows)
 *       -> long[] columnHandles     (num_columns data buffers first,
 *                                    then num_columns validity buffers,
 *                                    released to Java)
 * where tableHandle's buffer is the concatenation of the per-column
 * fixed-width buffers followed by per-column validity bytes (the layout
 * the Java facade assembles). Buffer sizes are validated against the
 * layout before any pointer walk — an undersized handle raises instead
 * of reading past the registry allocation. */

#ifdef SRT_HAVE_JNI

#include <jni.h>

#include <cstdint>
#include <string>
#include <vector>

#include "spark_rapids_tpu/c_api.h"

namespace {

void throw_java(JNIEnv* env, const std::string& msg) {
  jclass cls = env->FindClass("java/lang/RuntimeException");
  if (cls != nullptr) env->ThrowNew(cls, msg.c_str());
}

bool check_status(JNIEnv* env, srt_status s) {
  if (s == SRT_OK) return true;
  throw_java(env, srt_last_error());
  return false;
}

}  // namespace

extern "C" {

JNIEXPORT jlong JNICALL
Java_com_nvidia_spark_rapids_jni_RowConversion_convertToRowsNative(
    JNIEnv* env, jclass, jlong table_handle, jintArray type_ids_j,
    jlong num_rows, jlong start_row, jlong batch_rows) {
  if (table_handle == 0) {
    throw_java(env, "table handle is null");
    return 0;
  }
  if (start_row < 0 || batch_rows < 0 || start_row + batch_rows > num_rows) {
    throw_java(env, "batch range out of bounds");
    return 0;
  }
  jsize num_cols = env->GetArrayLength(type_ids_j);
  std::vector<int32_t> type_ids(num_cols);
  env->GetIntArrayRegion(type_ids_j, 0, num_cols, type_ids.data());

  std::vector<int32_t> offsets(num_cols), widths(num_cols);
  srt_row_layout layout{};
  if (!check_status(env, srt_compute_row_layout(type_ids.data(), num_cols,
                                                offsets.data(),
                                                widths.data(), &layout)))
    return 0;

  auto* base = static_cast<uint8_t*>(srt_buffer_data(table_handle));
  if (base == nullptr) {
    throw_java(env, srt_last_error());
    return 0;
  }
  // Validate the handle's size against the layout before any pointer
  // walk: data buffers back to back + per-column validity byte vectors.
  int64_t data_bytes = 0;
  for (jsize c = 0; c < num_cols; ++c) {
    data_bytes += static_cast<int64_t>(widths[c]) * num_rows;
  }
  int64_t required = data_bytes + static_cast<int64_t>(num_cols) * num_rows;
  if (srt_buffer_size(table_handle) < required) {
    throw_java(env, "table buffer smaller than layout requires");
    return 0;
  }
  // Column pointers offset to this batch's first row.
  std::vector<const void*> col_data(num_cols);
  std::vector<const uint8_t*> col_valid(num_cols);
  uint8_t* cursor = base;
  for (jsize c = 0; c < num_cols; ++c) {
    col_data[c] = cursor + static_cast<int64_t>(widths[c]) * start_row;
    cursor += static_cast<int64_t>(widths[c]) * num_rows;
  }
  for (jsize c = 0; c < num_cols; ++c) {
    col_valid[c] = cursor + start_row;
    cursor += num_rows;
  }

  srt_handle rows = srt_buffer_alloc(
      static_cast<int64_t>(layout.row_size) * batch_rows, "rows");
  if (rows == 0) {
    throw_java(env, srt_last_error());
    return 0;
  }
  srt_status s = srt_pack_rows(
      type_ids.data(), num_cols, col_data.data(), col_valid.data(),
      batch_rows, static_cast<uint8_t*>(srt_buffer_data(rows)));
  if (s != SRT_OK) {
    srt_buffer_release(rows);
    throw_java(env, srt_last_error());
    return 0;
  }
  return rows;  // ownership to Java (RowConversionJni.cpp:33-38 analog)
}

JNIEXPORT jlongArray JNICALL
Java_com_nvidia_spark_rapids_jni_RowConversion_convertFromRowsNative(
    JNIEnv* env, jclass, jlong rows_handle, jintArray type_ids_j,
    jintArray scales_j, jlong num_rows) {
  (void)scales_j;  // scales don't affect layout; the Java facade keeps them
  if (rows_handle == 0) {
    throw_java(env, "rows handle is null");
    return nullptr;
  }
  jsize num_cols = env->GetArrayLength(type_ids_j);
  std::vector<int32_t> type_ids(num_cols);
  env->GetIntArrayRegion(type_ids_j, 0, num_cols, type_ids.data());

  auto* rows = static_cast<uint8_t*>(srt_buffer_data(rows_handle));
  if (rows == nullptr) {
    throw_java(env, srt_last_error());
    return nullptr;
  }
  // Size gate: the rows buffer must hold num_rows full layout rows.
  std::vector<int32_t> offsets(num_cols), widths(num_cols);
  srt_row_layout layout{};
  if (!check_status(env, srt_compute_row_layout(type_ids.data(), num_cols,
                                                offsets.data(),
                                                widths.data(), &layout)))
    return nullptr;
  if (srt_buffer_size(rows_handle) <
      static_cast<int64_t>(layout.row_size) * num_rows) {
    throw_java(env, "rows buffer smaller than num_rows * row_size");
    return nullptr;
  }

  // Documented return order: num_cols data buffers first, then num_cols
  // validity buffers (RowConversion.java javadoc).
  std::vector<srt_handle> data_handles, valid_handles;
  std::vector<void*> col_data(num_cols);
  std::vector<uint8_t*> col_valid(num_cols);
  auto fail = [&](const char* msg) -> jlongArray {
    for (srt_handle h : data_handles) srt_buffer_release(h);
    for (srt_handle h : valid_handles) srt_buffer_release(h);
    throw_java(env, msg);
    return nullptr;
  };
  for (jsize c = 0; c < num_cols; ++c) {
    int32_t w = srt_type_width(type_ids[c]);
    if (w <= 0) return fail("non-fixed-width type");
    srt_handle hd = srt_buffer_alloc(static_cast<int64_t>(w) * num_rows,
                                     "col_data");
    srt_handle hv = srt_buffer_alloc(num_rows, "col_valid");
    if (hd == 0 || hv == 0) {
      if (hd != 0) srt_buffer_release(hd);
      if (hv != 0) srt_buffer_release(hv);
      return fail(srt_last_error());
    }
    data_handles.push_back(hd);
    valid_handles.push_back(hv);
    col_data[c] = srt_buffer_data(hd);
    col_valid[c] = static_cast<uint8_t*>(srt_buffer_data(hv));
  }
  srt_status s = srt_unpack_rows(type_ids.data(), num_cols, rows, num_rows,
                                 col_data.data(), col_valid.data());
  if (s != SRT_OK) return fail(srt_last_error());

  std::vector<srt_handle> handles;
  handles.insert(handles.end(), data_handles.begin(), data_handles.end());
  handles.insert(handles.end(), valid_handles.begin(), valid_handles.end());
  jlongArray out = env->NewLongArray(static_cast<jsize>(handles.size()));
  if (out == nullptr) return fail("allocation failure");
  env->SetLongArrayRegion(out, 0, static_cast<jsize>(handles.size()),
                          reinterpret_cast<const jlong*>(handles.data()));
  return out;  // convert_table_for_return analog (RowConversionJni.cpp:63)
}

}  /* extern "C" */

#endif  /* SRT_HAVE_JNI */
