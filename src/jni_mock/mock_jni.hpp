/* Mock JNIEnv backing the jni_stub declarations with real storage, so
 * the JNI bridge translation units (src/jni/*.cpp) can be EXECUTED in a
 * JDK-less image — converting the Java boundary's coverage from
 * "typechecks" to "runs, including error and cleanup paths"
 * (round-3 VERDICT item 3). The reference gets this execution for free
 * from a real JVM on GPU CI (ci/premerge-build.sh:22-28); this harness
 * is the no-JVM substitute, the same way the virtual CPU mesh
 * substitutes for a pod in tests/conftest.py.
 *
 * The JNIEnv member functions declared in src/jni/jni_stub/jni.h are
 * DEFINED in mock_jni.cpp over arena-owned vectors/strings. Helpers
 * here are what a harness needs: object construction, result readback,
 * pending-exception inspection (the mock's ThrowNew records instead of
 * raising), and fault injection for allocation-failure paths. */
#ifndef SRT_MOCK_JNI_HPP
#define SRT_MOCK_JNI_HPP

#include <jni.h>

#include <string>
#include <vector>

namespace srt_mock {

/* Concrete object kinds behind the opaque jobject handles. */
struct MockClass : _jobject {
  std::string name;
};
struct MockString : _jobject {
  std::string s;
};
struct MockByteArray : _jobject {
  std::vector<jbyte> v;
};
struct MockIntArray : _jobject {
  std::vector<jint> v;
};
struct MockLongArray : _jobject {
  std::vector<jlong> v;
};

/* Construction (arena-owned; freed by reset()). */
jstring make_string(const std::string& s);
jbyteArray make_byte_array(const std::vector<jbyte>& v);
jintArray make_int_array(const std::vector<jint>& v);
jlongArray make_long_array(const std::vector<jlong>& v);

/* Readback. */
std::vector<jlong> long_array_values(jlongArray a);
std::vector<jbyte> byte_array_values(jbyteArray a);

/* Pending-exception state (ThrowNew records; bridge code returns). */
bool exception_pending();
std::string exception_message();
void clear_exception();

/* Fault injection: the next New{Byte,Long}Array call returns nullptr,
 * exercising the bridge's release-on-allocation-failure paths. */
void fail_next_array_alloc();

/* Drop every arena object and clear exception state. */
void reset();

}  // namespace srt_mock

#endif /* SRT_MOCK_JNI_HPP */
