/* Definitions of the JNIEnv member functions declared in
 * src/jni/jni_stub/jni.h, backed by arena-owned host objects — see
 * mock_jni.hpp for why this exists. Semantics follow the JNI spec for
 * the subset the bridge uses: region copies, UTF strings, pending
 * exceptions (recorded, not raised), nullptr on allocation failure. */

#include "mock_jni.hpp"

#include <cstring>
#include <memory>

namespace srt_mock {

namespace {

std::vector<std::unique_ptr<_jobject>> g_arena;
bool g_pending = false;
std::string g_message;
bool g_fail_next_alloc = false;

template <class T>
T* make() {
  auto p = std::make_unique<T>();
  T* raw = p.get();
  g_arena.push_back(std::move(p));
  return raw;
}

}  // namespace

jstring make_string(const std::string& s) {
  auto* o = make<MockString>();
  o->s = s;
  return o;
}

jbyteArray make_byte_array(const std::vector<jbyte>& v) {
  auto* o = make<MockByteArray>();
  o->v = v;
  return o;
}

jintArray make_int_array(const std::vector<jint>& v) {
  auto* o = make<MockIntArray>();
  o->v = v;
  return o;
}

jlongArray make_long_array(const std::vector<jlong>& v) {
  auto* o = make<MockLongArray>();
  o->v = v;
  return o;
}

std::vector<jlong> long_array_values(jlongArray a) {
  auto* o = dynamic_cast<MockLongArray*>(a);
  return o != nullptr ? o->v : std::vector<jlong>{};
}

std::vector<jbyte> byte_array_values(jbyteArray a) {
  auto* o = dynamic_cast<MockByteArray*>(a);
  return o != nullptr ? o->v : std::vector<jbyte>{};
}

bool exception_pending() { return g_pending; }
std::string exception_message() { return g_message; }
void clear_exception() {
  g_pending = false;
  g_message.clear();
}

void fail_next_array_alloc() { g_fail_next_alloc = true; }

void reset() {
  g_arena.clear();
  clear_exception();
  g_fail_next_alloc = false;
}

}  // namespace srt_mock

/* ---- JNIEnv member definitions -------------------------------------- */

using srt_mock::MockByteArray;
using srt_mock::MockClass;
using srt_mock::MockIntArray;
using srt_mock::MockLongArray;
using srt_mock::MockString;

jclass JNIEnv::FindClass(const char* name) {
  auto* c = srt_mock::make<MockClass>();
  c->name = name != nullptr ? name : "";
  return c;
}

jint JNIEnv::ThrowNew(jclass, const char* msg) {
  srt_mock::g_pending = true;
  srt_mock::g_message = msg != nullptr ? msg : "";
  return 0;
}

jsize JNIEnv::GetArrayLength(jarray array) {
  if (auto* b = dynamic_cast<MockByteArray*>(array))
    return static_cast<jsize>(b->v.size());
  if (auto* i = dynamic_cast<MockIntArray*>(array))
    return static_cast<jsize>(i->v.size());
  if (auto* l = dynamic_cast<MockLongArray*>(array))
    return static_cast<jsize>(l->v.size());
  return 0;
}

void JNIEnv::GetByteArrayRegion(jbyteArray array, jsize start, jsize len,
                                jbyte* buf) {
  auto* o = dynamic_cast<MockByteArray*>(array);
  if (o != nullptr && start >= 0 &&
      start + len <= static_cast<jsize>(o->v.size()))
    std::memcpy(buf, o->v.data() + start, static_cast<size_t>(len));
}

void JNIEnv::GetIntArrayRegion(jintArray array, jsize start, jsize len,
                               jint* buf) {
  auto* o = dynamic_cast<MockIntArray*>(array);
  if (o != nullptr && start >= 0 &&
      start + len <= static_cast<jsize>(o->v.size()))
    std::memcpy(buf, o->v.data() + start, sizeof(jint) * len);
}

void JNIEnv::GetLongArrayRegion(jlongArray array, jsize start, jsize len,
                                jlong* buf) {
  auto* o = dynamic_cast<MockLongArray*>(array);
  if (o != nullptr && start >= 0 &&
      start + len <= static_cast<jsize>(o->v.size()))
    std::memcpy(buf, o->v.data() + start, sizeof(jlong) * len);
}

void JNIEnv::SetByteArrayRegion(jbyteArray array, jsize start, jsize len,
                                const jbyte* buf) {
  auto* o = dynamic_cast<MockByteArray*>(array);
  if (o != nullptr && start >= 0 &&
      start + len <= static_cast<jsize>(o->v.size()))
    std::memcpy(o->v.data() + start, buf, static_cast<size_t>(len));
}

void JNIEnv::SetLongArrayRegion(jlongArray array, jsize start, jsize len,
                                const jlong* buf) {
  auto* o = dynamic_cast<MockLongArray*>(array);
  if (o != nullptr && start >= 0 &&
      start + len <= static_cast<jsize>(o->v.size()))
    std::memcpy(o->v.data() + start, buf, sizeof(jlong) * len);
}

jbyteArray JNIEnv::NewByteArray(jsize len) {
  if (srt_mock::g_fail_next_alloc) {
    srt_mock::g_fail_next_alloc = false;
    return nullptr;
  }
  auto* o = srt_mock::make<MockByteArray>();
  o->v.resize(static_cast<size_t>(len));
  return o;
}

jlongArray JNIEnv::NewLongArray(jsize len) {
  if (srt_mock::g_fail_next_alloc) {
    srt_mock::g_fail_next_alloc = false;
    return nullptr;
  }
  auto* o = srt_mock::make<MockLongArray>();
  o->v.resize(static_cast<size_t>(len));
  return o;
}

jstring JNIEnv::NewStringUTF(const char* utf) {
  return srt_mock::make_string(utf != nullptr ? utf : "");
}

const char* JNIEnv::GetStringUTFChars(jstring str, jboolean* is_copy) {
  if (is_copy != nullptr) *is_copy = JNI_FALSE;
  auto* o = dynamic_cast<MockString*>(str);
  return o != nullptr ? o->s.c_str() : nullptr;
}

void JNIEnv::ReleaseStringUTFChars(jstring, const char*) {}
