/* C ABI of the TPU-native runtime shim (libspark_rapids_tpu.so).
 *
 * This is the foreign-function boundary of the framework: the layer the
 * reference implements as a JNI bridge over cudf handles
 * (RowConversionJni.cpp:22-68 — jlong handle marshaling, dtype wire
 * arrays, error translation). Re-designed as a plain C API so every
 * embedder binds the same way: the JNI bridge (src/jni/) and the Python
 * ctypes binding (spark_rapids_jni_tpu/utils/native.py) are both thin
 * wrappers over these functions.
 *
 * Responsibilities:
 *   1. dtype wire format      — (type id, scale) int pairs, the exact
 *                               arrays the reference marshals
 *                               (RowConversionJni.cpp:56-61).
 *   2. packed row codec       — bit-exact host implementation of the
 *                               row format spec (RowConversion.java:43-102,
 *                               row_conversion.cu:432-456): the JVM-side
 *                               fast path for Spark UnsafeRow interop.
 *   3. handle registry        — Java-long-sized opaque handles over host
 *                               buffers with refcounting and a leak-
 *                               tracking debug mode (the
 *                               ai.rapids.refcount.debug analog,
 *                               pom.xml:86,199).
 *   4. error translation      — status codes + thread-local message
 *                               (the CATCH_STD / JNI_NULL_CHECK analog,
 *                               RowConversionJni.cpp:27,40,49-50,65).
 */
#ifndef SPARK_RAPIDS_TPU_C_API_H
#define SPARK_RAPIDS_TPU_C_API_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#if defined(_WIN32)
#define SRT_EXPORT __declspec(dllexport)
#else
#define SRT_EXPORT __attribute__((visibility("default")))
#endif

/* ---- status / error translation ------------------------------------- */

typedef enum srt_status {
  SRT_OK = 0,
  SRT_ERR_INVALID = 1,   /* bad argument / layout mismatch */
  SRT_ERR_TYPE = 2,      /* non-fixed-width or unknown type id */
  SRT_ERR_OVERFLOW = 3,  /* INT_MAX batch-size cap exceeded */
  SRT_ERR_NULLPTR = 4,   /* required pointer was NULL */
  SRT_ERR_HANDLE = 5,    /* unknown / already-released handle */
  SRT_ERR_UNKNOWN = 6
} srt_status;

/* Thread-local message for the last failing call on this thread. */
SRT_EXPORT const char* srt_last_error(void);

/* Library version string (build provenance; the version-info.properties
 * analog of build/build-info). */
SRT_EXPORT const char* srt_version(void);

/* Set one SPARK_RAPIDS_TPU_* runtime flag (the utils/config.py flag
 * plane) in this process's environment, where the embedded runtime
 * reads it — the path Java memory/logging configuration
 * (ai.rapids.cudf.Rmm) takes into the planner and observability
 * channels. `value` NULL unsets. Call BEFORE srt_jax_init(): the
 * embedded interpreter snapshots its environment at startup, so later
 * changes are invisible to the flag plane (the same ordering cudf
 * demands — Rmm.initialize before any allocation). Names outside the
 * SPARK_RAPIDS_TPU_ prefix return SRT_ERR_INVALID: this is a flag
 * plane, not an arbitrary putenv. */
SRT_EXPORT srt_status srt_set_runtime_flag(const char* name,
                                           const char* value);

/* ---- dtype wire format ----------------------------------------------- */

/* Type ids match spark_rapids_jni_tpu.dtype.TypeId (cudf 22.04 native
 * ids, RowConversion.java:119). */

/* Row-format width in bytes of a fixed-width type id; 0 if not
 * fixed-width. */
SRT_EXPORT int32_t srt_type_width(int32_t type_id);

/* ---- packed row layout (RowConversion.java:43-102) ------------------- */

typedef struct srt_row_layout {
  int32_t num_columns;
  int32_t validity_offset; /* first validity byte */
  int32_t validity_bytes;  /* (num_columns + 7) / 8 */
  int32_t row_size;        /* padded to a multiple of 8 */
} srt_row_layout;

/* Compute per-column offsets/widths and the row envelope.
 * col_offsets/col_widths must hold num_columns int32 each. */
SRT_EXPORT srt_status srt_compute_row_layout(
    const int32_t* type_ids, int32_t num_columns, int32_t* col_offsets,
    int32_t* col_widths, srt_row_layout* layout);

/* 2 GB split granularity: (INT_MAX / row_size) / 32 * 32
 * (row_conversion.cu:476-479). Returns 0 on error. */
SRT_EXPORT int64_t srt_max_rows_per_batch(int32_t row_size);

/* ---- packed row codec -------------------------------------------------
 * Column buffers are little-endian fixed-width arrays (BOOL8 = 1 byte per
 * value). col_valid[i] is NULL (no nulls) or num_rows bytes of 0/1.
 * out_rows must hold num_rows * layout.row_size bytes. */

SRT_EXPORT srt_status srt_pack_rows(
    const int32_t* type_ids, int32_t num_columns,
    const void* const* col_data, const uint8_t* const* col_valid,
    int64_t num_rows, uint8_t* out_rows);

/* Inverse: rows -> caller-allocated column buffers + per-column validity
 * bytes (always written; 1 = valid). */
SRT_EXPORT srt_status srt_unpack_rows(
    const int32_t* type_ids, int32_t num_columns, const uint8_t* rows,
    int64_t num_rows, void* const* col_data_out,
    uint8_t* const* col_valid_out);

/* ---- handle registry --------------------------------------------------
 * Opaque int64 handles (the jlong of RowConversionJni.cpp:31) over host
 * byte buffers. Create copies the input. Handles are refcounted:
 * retain/release; release of the last reference frees the buffer. */

typedef int64_t srt_handle;

SRT_EXPORT srt_handle srt_buffer_create(const void* data, int64_t nbytes,
                                        const char* tag);
/* Allocate an uninitialized buffer (for unpack targets). */
SRT_EXPORT srt_handle srt_buffer_alloc(int64_t nbytes, const char* tag);
SRT_EXPORT srt_status srt_buffer_retain(srt_handle h);
SRT_EXPORT srt_status srt_buffer_release(srt_handle h);
SRT_EXPORT void* srt_buffer_data(srt_handle h); /* NULL on bad handle */
SRT_EXPORT int64_t srt_buffer_size(srt_handle h); /* -1 on bad handle */

/* Leak tracking (the refcount-debug test mode of SURVEY.md §4). */
SRT_EXPORT void srt_set_refcount_debug(int enabled);
SRT_EXPORT int64_t srt_live_handle_count(void);
/* Write a report of live handles ("id tag refcount nbytes" lines) into
 * buf; returns the number of bytes that would be required. */
SRT_EXPORT int64_t srt_leak_report(char* buf, int64_t buflen);

/* ---- embedded JAX device runtime --------------------------------------
 * The device-dispatch layer the reference reaches through
 * `cudf::jni::auto_set_device` + direct kernel calls
 * (RowConversionJni.cpp:24-66). Here the native library hosts (or, when
 * the calling process is already Python, joins) a CPython interpreter
 * running the JAX/XLA compute stack, so ANY embedder — the JNI bridge, a
 * C program, a Spark executor — can run table ops on the TPU through
 * this .so. Available when built with SRT_EMBED_JAX (CMake finds
 * libpython); otherwise these return SRT_ERR_INVALID. */

/* Initialize the runtime (idempotent, thread-safe). Joins an existing
 * in-process interpreter if one is live (ctypes embedders); otherwise
 * starts one, resolving the Python home from SRT_PYTHON_EXECUTABLE or
 * the build-time default. */
SRT_EXPORT srt_status srt_jax_init(void);

/* 1 when built with SRT_EMBED_JAX, else 0. */
SRT_EXPORT int32_t srt_jax_available(void);

/* Write the active JAX backend platform name ("tpu", "cpu", ...). */
SRT_EXPORT srt_status srt_jax_platform(char* buf, int64_t buflen);

/* Generic device table op. `op_json` selects and parameterizes the op
 * (see spark_rapids_jni_tpu/runtime_bridge.py for the op vocabulary:
 * groupby / sort_by / to_rows / from_rows / filter). Input columns
 * arrive as registry handles over little-endian fixed-width host
 * buffers (col_valid[i] = 0 for a non-null column; otherwise a handle
 * to num_rows 0/1 bytes), with the (type id, scale) wire arrays of the
 * reference JNI (RowConversionJni.cpp:56-61). Output columns are
 * freshly created registry handles the CALLER owns; *out_num_columns
 * reports how many were written (capacity: max_out_columns).
 * out_col_valid[i] is 0 when the output column has no nulls. */
SRT_EXPORT srt_status srt_jax_table_op(
    const char* op_json, const int32_t* type_ids, const int32_t* scales,
    int32_t num_columns, const srt_handle* col_data,
    const srt_handle* col_valid, int64_t num_rows,
    int32_t max_out_columns, int32_t* out_type_ids, int32_t* out_scales,
    int32_t* out_num_columns, srt_handle* out_col_data,
    srt_handle* out_col_valid, int64_t* out_num_rows);

/* ---- device-resident table chaining -----------------------------------
 * The reference chains ops by passing jlong pointers to DEVICE-resident
 * cudf tables between calls (RowConversionJni.cpp:31,54 — no host copy
 * between ops). srt_jax_table_op round-trips every input/output through
 * host bytes; these functions keep tables resident on the XLA backend
 * between ops: upload once, chain ops over srt_table ids, download once.
 * A Spark stage chaining filter -> join -> groupby pays the wire cost
 * twice total instead of twice per op. */

typedef int64_t srt_table;

/* Host buffers (wire format of srt_jax_table_op) -> resident table. */
SRT_EXPORT srt_status srt_jax_table_upload(
    const int32_t* type_ids, const int32_t* scales, int32_t num_columns,
    const srt_handle* col_data, const srt_handle* col_valid,
    int64_t num_rows, srt_table* out_table);

/* One op over resident inputs; the result stays resident. Multi-table
 * ops (op "join": inputs[0] = left/probe, inputs[1] = right/build;
 * op "concat": all inputs in order). */
SRT_EXPORT srt_status srt_jax_table_op_resident(
    const char* op_json, const srt_table* inputs, int32_t num_inputs,
    srt_table* out_table);

/* Resident table -> freshly created host buffer handles (same output
 * contract as srt_jax_table_op; caller owns the handles). */
SRT_EXPORT srt_status srt_jax_table_download(
    srt_table table, int32_t max_out_columns, int32_t* out_type_ids,
    int32_t* out_scales, int32_t* out_num_columns,
    srt_handle* out_col_data, srt_handle* out_col_valid,
    int64_t* out_num_rows);

SRT_EXPORT srt_status srt_jax_table_num_rows(srt_table table,
                                             int64_t* out_num_rows);
SRT_EXPORT srt_status srt_jax_table_free(srt_table table);
/* Live resident tables (leak tracking for the device-table registry). */
SRT_EXPORT srt_status srt_jax_resident_table_count(int64_t* out_count);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* SPARK_RAPIDS_TPU_C_API_H */
