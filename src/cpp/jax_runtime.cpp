/* Embedded JAX device runtime: the native->TPU dispatch path.
 *
 * The reference's JNI entry points call straight into device kernels in
 * the same address space (RowConversionJni.cpp:24-66 -> row_conversion.cu).
 * A TPU has no CUDA-style in-process kernel launch for C++ callers, so
 * this file gives native embedders the equivalent capability by hosting
 * the JAX/XLA stack in an embedded CPython interpreter: a JVM (through
 * src/jni/), a C program, or a Spark executor loads
 * libspark_rapids_tpu.so and dispatches table ops that execute on the
 * XLA backend (TPU when present).
 *
 * Two embedding modes, decided at srt_jax_init():
 *   - JOIN: the calling process already runs Python (ctypes binding in
 *     spark_rapids_jni_tpu/utils/native.py) — reuse its interpreter via
 *     the GIL-state API.
 *   - HOST: pure-native caller — initialize an interpreter, resolving
 *     the Python home from $SRT_PYTHON_EXECUTABLE (venv aware), and add
 *     $SRT_PYTHONPATH entries so the dev tree resolves.
 *
 * All compute goes through one Python call:
 * spark_rapids_jni_tpu.runtime_bridge.table_op_wire (see its docstring
 * for the wire format). Compiled only under SRT_EMBED_JAX; without it
 * the entry points report the capability as absent.
 */

#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "error.hpp"
#include "spark_rapids_tpu/c_api.h"

#ifdef SRT_EMBED_JAX
#include <Python.h>
#endif

using spark_rapids_tpu::expects;
using spark_rapids_tpu::srt_error;
using spark_rapids_tpu::translate;

#ifndef SRT_EMBED_JAX

extern "C" {
int32_t srt_jax_available(void) { return 0; }
srt_status srt_jax_init(void) {
  return translate([] {
    throw srt_error(SRT_ERR_INVALID,
                    "built without SRT_EMBED_JAX: no device runtime");
  });
}
srt_status srt_jax_platform(char*, int64_t) { return srt_jax_init(); }
srt_status srt_jax_table_op(const char*, const int32_t*, const int32_t*,
                            int32_t, const srt_handle*, const srt_handle*,
                            int64_t, int32_t, int32_t*, int32_t*, int32_t*,
                            srt_handle*, srt_handle*, int64_t*) {
  return srt_jax_init();
}
srt_status srt_jax_table_upload(const int32_t*, const int32_t*, int32_t,
                                const srt_handle*, const srt_handle*,
                                int64_t, srt_table*) {
  return srt_jax_init();
}
srt_status srt_jax_table_op_resident(const char*, const srt_table*,
                                     int32_t, srt_table*) {
  return srt_jax_init();
}
srt_status srt_jax_table_download(srt_table, int32_t, int32_t*, int32_t*,
                                  int32_t*, srt_handle*, srt_handle*,
                                  int64_t*) {
  return srt_jax_init();
}
srt_status srt_jax_table_num_rows(srt_table, int64_t*) {
  return srt_jax_init();
}
srt_status srt_jax_table_free(srt_table) { return srt_jax_init(); }
srt_status srt_jax_resident_table_count(int64_t*) {
  return srt_jax_init();
}
}

#else  // SRT_EMBED_JAX

namespace {

struct Runtime {
  std::mutex mu;
  bool initialized = false;
  bool owns_interpreter = false;
  PyObject* bridge = nullptr;  // spark_rapids_jni_tpu.runtime_bridge
};

Runtime& runtime() {
  static Runtime r;
  return r;
}

/* RAII GIL acquisition for entry points after init. */
class GilGuard {
 public:
  GilGuard() : state_(PyGILState_Ensure()) {}
  ~GilGuard() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

/* Render the pending Python exception into an srt_error. */
[[noreturn]] void throw_python_error(const char* where) {
  std::string msg = std::string(where) + ": python error";
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = std::string(where) + ": " + c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  throw srt_error(SRT_ERR_UNKNOWN, msg);
}

void start_interpreter() {
  PyConfig config;
  PyConfig_InitPythonConfig(&config);
  const char* exe = std::getenv("SRT_PYTHON_EXECUTABLE");
#ifdef SRT_PYTHON_DEFAULT
  if (exe == nullptr || exe[0] == '\0') exe = SRT_PYTHON_DEFAULT;
#endif
  if (exe != nullptr && exe[0] != '\0') {
    PyConfig_SetBytesString(&config, &config.program_name, exe);
  }
  PyStatus status = Py_InitializeFromConfig(&config);
  PyConfig_Clear(&config);
  if (PyStatus_Exception(status)) {
    throw srt_error(SRT_ERR_UNKNOWN,
                    std::string("python init failed: ") +
                        (status.err_msg ? status.err_msg : "?"));
  }
}

void add_pythonpath_entries() {
  const char* extra = std::getenv("SRT_PYTHONPATH");
  if (extra == nullptr || extra[0] == '\0') return;
  PyObject* sys_path = PySys_GetObject("path");  // borrowed
  if (sys_path == nullptr) throw_python_error("sys.path");
  std::string all(extra);
  size_t start = 0;
  while (start <= all.size()) {
    size_t end = all.find(':', start);
    if (end == std::string::npos) end = all.size();
    std::string entry = all.substr(start, end - start);
    if (!entry.empty()) {
      PyObject* s = PyUnicode_FromString(entry.c_str());
      if (s == nullptr) throw_python_error("path entry");
      PyList_Insert(sys_path, 0, s);
      Py_DECREF(s);
    }
    start = end + 1;
  }
}

void ensure_init() {
  Runtime& rt = runtime();
  std::lock_guard<std::mutex> lock(rt.mu);
  if (rt.initialized) return;
  if (Py_IsInitialized() == 0) {
    start_interpreter();
    rt.owns_interpreter = true;
    /* From here the GIL must be released on EVERY exit — a throw that
     * kept it held would deadlock every later call on other threads
     * (and a same-thread retry takes the JOIN branch below, whose
     * GilGuard only balances its own Ensure). */
    try {
      add_pythonpath_entries();
      PyObject* mod =
          PyImport_ImportModule("spark_rapids_jni_tpu.runtime_bridge");
      if (mod == nullptr) throw_python_error("import runtime_bridge");
      rt.bridge = mod;
      rt.initialized = true;
    } catch (...) {
      PyEval_SaveThread();
      throw;
    }
    PyEval_SaveThread();
  } else {
    GilGuard gil;
    add_pythonpath_entries();
    PyObject* mod =
        PyImport_ImportModule("spark_rapids_jni_tpu.runtime_bridge");
    if (mod == nullptr) throw_python_error("import runtime_bridge");
    rt.bridge = mod;
    rt.initialized = true;
  }
}

PyObject* bridge_attr(const char* name) {
  PyObject* fn = PyObject_GetAttrString(runtime().bridge, name);
  if (fn == nullptr) throw_python_error(name);
  return fn;
}

/* bytes-or-None from a registry handle (0 = None). */
PyObject* buffer_to_py(srt_handle h) {
  if (h == 0) Py_RETURN_NONE;
  void* data = srt_buffer_data(h);
  int64_t size = srt_buffer_size(h);
  expects(data != nullptr && size >= 0, SRT_ERR_HANDLE,
          "unknown buffer handle in table op");
  PyObject* bytes = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), static_cast<Py_ssize_t>(size));
  if (bytes == nullptr) throw_python_error("buffer bytes");
  return bytes;
}

/* Build the four wire argument lists (ids, scales, datas, valids) from
 * registry handles; throws with everything released on failure. */
struct WireArgs {
  PyObject* ids = nullptr;
  PyObject* scales = nullptr;
  PyObject* datas = nullptr;
  PyObject* valids = nullptr;

  ~WireArgs() {
    Py_XDECREF(ids);
    Py_XDECREF(scales);
    Py_XDECREF(datas);
    Py_XDECREF(valids);
  }
};

void build_wire_args(WireArgs& w, const int32_t* type_ids,
                     const int32_t* scales, int32_t num_columns,
                     const srt_handle* col_data,
                     const srt_handle* col_valid) {
  w.ids = PyList_New(num_columns);
  w.scales = PyList_New(num_columns);
  w.datas = PyList_New(num_columns);
  w.valids = PyList_New(num_columns);
  expects(w.ids != nullptr && w.scales != nullptr && w.datas != nullptr &&
              w.valids != nullptr,
          SRT_ERR_UNKNOWN, "argument list allocation failed");
  for (int32_t i = 0; i < num_columns; ++i) {
    PyObject* id_obj = PyLong_FromLong(type_ids[i]);
    PyObject* sc_obj = PyLong_FromLong(scales[i]);
    expects(id_obj != nullptr && sc_obj != nullptr, SRT_ERR_UNKNOWN,
            "int allocation failed");
    PyList_SET_ITEM(w.ids, i, id_obj);
    PyList_SET_ITEM(w.scales, i, sc_obj);
    PyList_SET_ITEM(w.datas, i, buffer_to_py(col_data[i]));
    PyList_SET_ITEM(w.valids, i, buffer_to_py(col_valid[i]));
  }
}

/* Validate + unpack a (type_ids, scales, datas, valids, num_rows) wire
 * result into freshly created registry handles. Borrows `res`; on any
 * failure every handle created so far is released and an srt_error is
 * thrown — the registry can never leak (RowConversion.java cleanup
 * discipline). */
void unpack_wire_result(PyObject* res, int32_t max_out_columns,
                        int32_t* out_type_ids, int32_t* out_scales,
                        int32_t* out_num_columns, srt_handle* out_col_data,
                        srt_handle* out_col_valid, int64_t* out_num_rows) {
  if (!PyTuple_Check(res) || PyTuple_GET_SIZE(res) != 5) {
    throw srt_error(SRT_ERR_UNKNOWN, "wire result: bad shape");
  }
  PyObject* r_ids = PyTuple_GET_ITEM(res, 0);
  PyObject* r_scales = PyTuple_GET_ITEM(res, 1);
  PyObject* r_datas = PyTuple_GET_ITEM(res, 2);
  PyObject* r_valids = PyTuple_GET_ITEM(res, 3);
  PyObject* r_rows = PyTuple_GET_ITEM(res, 4);
  if (!PyList_Check(r_ids) || !PyList_Check(r_scales) ||
      !PyList_Check(r_datas) || !PyList_Check(r_valids) ||
      !PyLong_Check(r_rows)) {
    throw srt_error(SRT_ERR_UNKNOWN, "wire result: bad types");
  }
  Py_ssize_t n_out = PyList_GET_SIZE(r_ids);
  if (PyList_GET_SIZE(r_scales) != n_out ||
      PyList_GET_SIZE(r_datas) != n_out ||
      PyList_GET_SIZE(r_valids) != n_out) {
    throw srt_error(SRT_ERR_UNKNOWN, "wire result: ragged lists");
  }
  if (n_out > max_out_columns) {
    throw srt_error(SRT_ERR_OVERFLOW,
                    "result has more columns than max_out_columns");
  }
  std::vector<srt_handle> created;
  created.reserve(static_cast<size_t>(2 * n_out));
  try {
    for (Py_ssize_t i = 0; i < n_out; ++i) {
      PyObject* d = PyList_GetItem(r_datas, i);
      PyObject* v = PyList_GetItem(r_valids, i);
      PyObject* id_obj = PyList_GetItem(r_ids, i);
      PyObject* sc_obj = PyList_GetItem(r_scales, i);
      expects(id_obj != nullptr && PyLong_Check(id_obj) &&
                  sc_obj != nullptr && PyLong_Check(sc_obj),
              SRT_ERR_UNKNOWN, "wire result: non-int id/scale");
      expects(d != nullptr && PyBytes_Check(d), SRT_ERR_UNKNOWN,
              "wire result: data not bytes");
      srt_handle hd = srt_buffer_create(
          PyBytes_AS_STRING(d), PyBytes_GET_SIZE(d), "jax-op-out");
      expects(hd != 0, SRT_ERR_UNKNOWN, "buffer create failed");
      created.push_back(hd);
      srt_handle hv = 0;
      if (v != nullptr && v != Py_None) {
        expects(PyBytes_Check(v), SRT_ERR_UNKNOWN,
                "wire result: validity not bytes");
        hv = srt_buffer_create(PyBytes_AS_STRING(v), PyBytes_GET_SIZE(v),
                               "jax-op-out-valid");
        expects(hv != 0, SRT_ERR_UNKNOWN, "buffer create failed");
        created.push_back(hv);
      }
      out_type_ids[i] = static_cast<int32_t>(PyLong_AsLong(id_obj));
      out_scales[i] = static_cast<int32_t>(PyLong_AsLong(sc_obj));
      out_col_data[i] = hd;
      out_col_valid[i] = hv;
    }
  } catch (...) {
    for (srt_handle h : created) srt_buffer_release(h);
    throw;
  }
  *out_num_columns = static_cast<int32_t>(n_out);
  *out_num_rows = static_cast<int64_t>(PyLong_AsLongLong(r_rows));
}

/* Call a bridge function returning an int64 (table ids, counts). */
int64_t call_int64(PyObject* res, const char* where) {
  if (res == nullptr) throw_python_error(where);
  if (!PyLong_Check(res)) {
    Py_DECREF(res);
    throw srt_error(SRT_ERR_UNKNOWN,
                    std::string(where) + ": non-int result");
  }
  int64_t out = static_cast<int64_t>(PyLong_AsLongLong(res));
  Py_DECREF(res);
  return out;
}

}  // namespace

extern "C" {

int32_t srt_jax_available(void) { return 1; }

srt_status srt_jax_init(void) {
  return translate([] { ensure_init(); });
}

srt_status srt_jax_platform(char* buf, int64_t buflen) {
  return translate([&] {
    expects(buf != nullptr && buflen > 0, SRT_ERR_NULLPTR, "null buffer");
    ensure_init();
    GilGuard gil;
    PyObject* fn = bridge_attr("platform");
    PyObject* res = PyObject_CallNoArgs(fn);
    Py_DECREF(fn);
    if (res == nullptr) throw_python_error("platform()");
    const char* name = PyUnicode_AsUTF8(res);
    if (name == nullptr) {
      Py_DECREF(res);
      throw_python_error("platform() result");
    }
    std::strncpy(buf, name, static_cast<size_t>(buflen - 1));
    buf[buflen - 1] = '\0';
    Py_DECREF(res);
  });
}

srt_status srt_jax_table_op(
    const char* op_json, const int32_t* type_ids, const int32_t* scales,
    int32_t num_columns, const srt_handle* col_data,
    const srt_handle* col_valid, int64_t num_rows, int32_t max_out_columns,
    int32_t* out_type_ids, int32_t* out_scales, int32_t* out_num_columns,
    srt_handle* out_col_data, srt_handle* out_col_valid,
    int64_t* out_num_rows) {
  return translate([&] {
    expects(op_json != nullptr, SRT_ERR_NULLPTR, "null op_json");
    expects(num_columns >= 0, SRT_ERR_INVALID, "negative column count");
    expects(num_columns == 0 ||
                (type_ids != nullptr && scales != nullptr &&
                 col_data != nullptr && col_valid != nullptr),
            SRT_ERR_NULLPTR, "null column arrays");
    expects(out_type_ids != nullptr && out_scales != nullptr &&
                out_num_columns != nullptr && out_col_data != nullptr &&
                out_col_valid != nullptr && out_num_rows != nullptr,
            SRT_ERR_NULLPTR, "null output arrays");
    ensure_init();
    GilGuard gil;

    PyObject* res = nullptr;
    try {
      WireArgs w;
      build_wire_args(w, type_ids, scales, num_columns, col_data,
                      col_valid);
      PyObject* fn = bridge_attr("table_op_wire");
      res = PyObject_CallFunction(
          fn, "sOOOOL", op_json, w.ids, w.scales, w.datas, w.valids,
          static_cast<long long>(num_rows));
      Py_DECREF(fn);
      if (res == nullptr) throw_python_error("table_op_wire");
    } catch (...) {
      if (PyErr_Occurred()) PyErr_Clear();
      throw;
    }
    try {
      unpack_wire_result(res, max_out_columns, out_type_ids, out_scales,
                         out_num_columns, out_col_data, out_col_valid,
                         out_num_rows);
    } catch (...) {
      Py_DECREF(res);
      throw;
    }
    Py_DECREF(res);
  });
}

srt_status srt_jax_table_upload(
    const int32_t* type_ids, const int32_t* scales, int32_t num_columns,
    const srt_handle* col_data, const srt_handle* col_valid,
    int64_t num_rows, srt_table* out_table) {
  return translate([&] {
    expects(num_columns > 0 && type_ids != nullptr && scales != nullptr &&
                col_data != nullptr && col_valid != nullptr,
            SRT_ERR_NULLPTR, "null column arrays");
    expects(out_table != nullptr, SRT_ERR_NULLPTR, "null out_table");
    ensure_init();
    GilGuard gil;
    PyObject* res = nullptr;
    try {
      WireArgs w;
      build_wire_args(w, type_ids, scales, num_columns, col_data,
                      col_valid);
      PyObject* fn = bridge_attr("table_upload_wire");
      res = PyObject_CallFunction(
          fn, "OOOOL", w.ids, w.scales, w.datas, w.valids,
          static_cast<long long>(num_rows));
      Py_DECREF(fn);
    } catch (...) {
      if (PyErr_Occurred()) PyErr_Clear();
      throw;
    }
    *out_table = call_int64(res, "table_upload_wire");
  });
}

srt_status srt_jax_table_op_resident(
    const char* op_json, const srt_table* inputs, int32_t num_inputs,
    srt_table* out_table) {
  return translate([&] {
    expects(op_json != nullptr, SRT_ERR_NULLPTR, "null op_json");
    expects(inputs != nullptr && num_inputs > 0, SRT_ERR_NULLPTR,
            "null inputs");
    expects(out_table != nullptr, SRT_ERR_NULLPTR, "null out_table");
    ensure_init();
    GilGuard gil;
    PyObject* res = nullptr;
    PyObject* ids = nullptr;
    try {
      ids = PyList_New(num_inputs);
      expects(ids != nullptr, SRT_ERR_UNKNOWN, "list allocation failed");
      for (int32_t i = 0; i < num_inputs; ++i) {
        PyObject* v = PyLong_FromLongLong(inputs[i]);
        expects(v != nullptr, SRT_ERR_UNKNOWN, "int allocation failed");
        PyList_SET_ITEM(ids, i, v);
      }
      PyObject* fn = bridge_attr("table_op_resident");
      res = PyObject_CallFunction(fn, "sO", op_json, ids);
      Py_DECREF(fn);
      Py_DECREF(ids);
    } catch (...) {
      Py_XDECREF(ids);
      if (PyErr_Occurred()) PyErr_Clear();
      throw;
    }
    *out_table = call_int64(res, "table_op_resident");
  });
}

srt_status srt_jax_table_download(
    srt_table table, int32_t max_out_columns, int32_t* out_type_ids,
    int32_t* out_scales, int32_t* out_num_columns,
    srt_handle* out_col_data, srt_handle* out_col_valid,
    int64_t* out_num_rows) {
  return translate([&] {
    expects(out_type_ids != nullptr && out_scales != nullptr &&
                out_num_columns != nullptr && out_col_data != nullptr &&
                out_col_valid != nullptr && out_num_rows != nullptr,
            SRT_ERR_NULLPTR, "null output arrays");
    ensure_init();
    GilGuard gil;
    PyObject* fn = bridge_attr("table_download_wire");
    PyObject* res =
        PyObject_CallFunction(fn, "L", static_cast<long long>(table));
    Py_DECREF(fn);
    if (res == nullptr) throw_python_error("table_download_wire");
    try {
      unpack_wire_result(res, max_out_columns, out_type_ids, out_scales,
                         out_num_columns, out_col_data, out_col_valid,
                         out_num_rows);
    } catch (...) {
      Py_DECREF(res);
      throw;
    }
    Py_DECREF(res);
  });
}

srt_status srt_jax_table_num_rows(srt_table table, int64_t* out_num_rows) {
  return translate([&] {
    expects(out_num_rows != nullptr, SRT_ERR_NULLPTR, "null out");
    ensure_init();
    GilGuard gil;
    PyObject* fn = bridge_attr("table_num_rows");
    PyObject* res =
        PyObject_CallFunction(fn, "L", static_cast<long long>(table));
    Py_DECREF(fn);
    *out_num_rows = call_int64(res, "table_num_rows");
  });
}

srt_status srt_jax_table_free(srt_table table) {
  return translate([&] {
    ensure_init();
    GilGuard gil;
    PyObject* fn = bridge_attr("table_free");
    PyObject* res =
        PyObject_CallFunction(fn, "L", static_cast<long long>(table));
    Py_DECREF(fn);
    if (res == nullptr) throw_python_error("table_free");
    Py_DECREF(res);
  });
}

srt_status srt_jax_resident_table_count(int64_t* out_count) {
  return translate([&] {
    expects(out_count != nullptr, SRT_ERR_NULLPTR, "null out");
    ensure_init();
    GilGuard gil;
    PyObject* fn = bridge_attr("resident_table_count");
    PyObject* res = PyObject_CallNoArgs(fn);
    Py_DECREF(fn);
    *out_count = call_int64(res, "resident_table_count");
  });
}

}  // extern "C"

#endif  // SRT_EMBED_JAX
