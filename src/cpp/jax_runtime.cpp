/* Embedded JAX device runtime: the native->TPU dispatch path.
 *
 * The reference's JNI entry points call straight into device kernels in
 * the same address space (RowConversionJni.cpp:24-66 -> row_conversion.cu).
 * A TPU has no CUDA-style in-process kernel launch for C++ callers, so
 * this file gives native embedders the equivalent capability by hosting
 * the JAX/XLA stack in an embedded CPython interpreter: a JVM (through
 * src/jni/), a C program, or a Spark executor loads
 * libspark_rapids_tpu.so and dispatches table ops that execute on the
 * XLA backend (TPU when present).
 *
 * Two embedding modes, decided at srt_jax_init():
 *   - JOIN: the calling process already runs Python (ctypes binding in
 *     spark_rapids_jni_tpu/utils/native.py) — reuse its interpreter via
 *     the GIL-state API.
 *   - HOST: pure-native caller — initialize an interpreter, resolving
 *     the Python home from $SRT_PYTHON_EXECUTABLE (venv aware), and add
 *     $SRT_PYTHONPATH entries so the dev tree resolves.
 *
 * All compute goes through one Python call:
 * spark_rapids_jni_tpu.runtime_bridge.table_op_wire (see its docstring
 * for the wire format). Compiled only under SRT_EMBED_JAX; without it
 * the entry points report the capability as absent.
 */

#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "error.hpp"
#include "spark_rapids_tpu/c_api.h"

#ifdef SRT_EMBED_JAX
#include <Python.h>
#endif

using spark_rapids_tpu::expects;
using spark_rapids_tpu::srt_error;
using spark_rapids_tpu::translate;

#ifndef SRT_EMBED_JAX

extern "C" {
int32_t srt_jax_available(void) { return 0; }
srt_status srt_jax_init(void) {
  return translate([] {
    throw srt_error(SRT_ERR_INVALID,
                    "built without SRT_EMBED_JAX: no device runtime");
  });
}
srt_status srt_jax_platform(char*, int64_t) { return srt_jax_init(); }
srt_status srt_jax_table_op(const char*, const int32_t*, const int32_t*,
                            int32_t, const srt_handle*, const srt_handle*,
                            int64_t, int32_t, int32_t*, int32_t*, int32_t*,
                            srt_handle*, srt_handle*, int64_t*) {
  return srt_jax_init();
}
}

#else  // SRT_EMBED_JAX

namespace {

struct Runtime {
  std::mutex mu;
  bool initialized = false;
  bool owns_interpreter = false;
  PyObject* bridge = nullptr;  // spark_rapids_jni_tpu.runtime_bridge
};

Runtime& runtime() {
  static Runtime r;
  return r;
}

/* RAII GIL acquisition for entry points after init. */
class GilGuard {
 public:
  GilGuard() : state_(PyGILState_Ensure()) {}
  ~GilGuard() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

/* Render the pending Python exception into an srt_error. */
[[noreturn]] void throw_python_error(const char* where) {
  std::string msg = std::string(where) + ": python error";
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = std::string(where) + ": " + c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  throw srt_error(SRT_ERR_UNKNOWN, msg);
}

void start_interpreter() {
  PyConfig config;
  PyConfig_InitPythonConfig(&config);
  const char* exe = std::getenv("SRT_PYTHON_EXECUTABLE");
#ifdef SRT_PYTHON_DEFAULT
  if (exe == nullptr || exe[0] == '\0') exe = SRT_PYTHON_DEFAULT;
#endif
  if (exe != nullptr && exe[0] != '\0') {
    PyConfig_SetBytesString(&config, &config.program_name, exe);
  }
  PyStatus status = Py_InitializeFromConfig(&config);
  PyConfig_Clear(&config);
  if (PyStatus_Exception(status)) {
    throw srt_error(SRT_ERR_UNKNOWN,
                    std::string("python init failed: ") +
                        (status.err_msg ? status.err_msg : "?"));
  }
}

void add_pythonpath_entries() {
  const char* extra = std::getenv("SRT_PYTHONPATH");
  if (extra == nullptr || extra[0] == '\0') return;
  PyObject* sys_path = PySys_GetObject("path");  // borrowed
  if (sys_path == nullptr) throw_python_error("sys.path");
  std::string all(extra);
  size_t start = 0;
  while (start <= all.size()) {
    size_t end = all.find(':', start);
    if (end == std::string::npos) end = all.size();
    std::string entry = all.substr(start, end - start);
    if (!entry.empty()) {
      PyObject* s = PyUnicode_FromString(entry.c_str());
      if (s == nullptr) throw_python_error("path entry");
      PyList_Insert(sys_path, 0, s);
      Py_DECREF(s);
    }
    start = end + 1;
  }
}

void ensure_init() {
  Runtime& rt = runtime();
  std::lock_guard<std::mutex> lock(rt.mu);
  if (rt.initialized) return;
  if (Py_IsInitialized() == 0) {
    start_interpreter();
    rt.owns_interpreter = true;
    /* From here the GIL must be released on EVERY exit — a throw that
     * kept it held would deadlock every later call on other threads
     * (and a same-thread retry takes the JOIN branch below, whose
     * GilGuard only balances its own Ensure). */
    try {
      add_pythonpath_entries();
      PyObject* mod =
          PyImport_ImportModule("spark_rapids_jni_tpu.runtime_bridge");
      if (mod == nullptr) throw_python_error("import runtime_bridge");
      rt.bridge = mod;
      rt.initialized = true;
    } catch (...) {
      PyEval_SaveThread();
      throw;
    }
    PyEval_SaveThread();
  } else {
    GilGuard gil;
    add_pythonpath_entries();
    PyObject* mod =
        PyImport_ImportModule("spark_rapids_jni_tpu.runtime_bridge");
    if (mod == nullptr) throw_python_error("import runtime_bridge");
    rt.bridge = mod;
    rt.initialized = true;
  }
}

PyObject* bridge_attr(const char* name) {
  PyObject* fn = PyObject_GetAttrString(runtime().bridge, name);
  if (fn == nullptr) throw_python_error(name);
  return fn;
}

/* bytes-or-None from a registry handle (0 = None). */
PyObject* buffer_to_py(srt_handle h) {
  if (h == 0) Py_RETURN_NONE;
  void* data = srt_buffer_data(h);
  int64_t size = srt_buffer_size(h);
  expects(data != nullptr && size >= 0, SRT_ERR_HANDLE,
          "unknown buffer handle in table op");
  PyObject* bytes = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), static_cast<Py_ssize_t>(size));
  if (bytes == nullptr) throw_python_error("buffer bytes");
  return bytes;
}

}  // namespace

extern "C" {

int32_t srt_jax_available(void) { return 1; }

srt_status srt_jax_init(void) {
  return translate([] { ensure_init(); });
}

srt_status srt_jax_platform(char* buf, int64_t buflen) {
  return translate([&] {
    expects(buf != nullptr && buflen > 0, SRT_ERR_NULLPTR, "null buffer");
    ensure_init();
    GilGuard gil;
    PyObject* fn = bridge_attr("platform");
    PyObject* res = PyObject_CallNoArgs(fn);
    Py_DECREF(fn);
    if (res == nullptr) throw_python_error("platform()");
    const char* name = PyUnicode_AsUTF8(res);
    if (name == nullptr) {
      Py_DECREF(res);
      throw_python_error("platform() result");
    }
    std::strncpy(buf, name, static_cast<size_t>(buflen - 1));
    buf[buflen - 1] = '\0';
    Py_DECREF(res);
  });
}

srt_status srt_jax_table_op(
    const char* op_json, const int32_t* type_ids, const int32_t* scales,
    int32_t num_columns, const srt_handle* col_data,
    const srt_handle* col_valid, int64_t num_rows, int32_t max_out_columns,
    int32_t* out_type_ids, int32_t* out_scales, int32_t* out_num_columns,
    srt_handle* out_col_data, srt_handle* out_col_valid,
    int64_t* out_num_rows) {
  return translate([&] {
    expects(op_json != nullptr, SRT_ERR_NULLPTR, "null op_json");
    expects(num_columns >= 0, SRT_ERR_INVALID, "negative column count");
    expects(num_columns == 0 ||
                (type_ids != nullptr && scales != nullptr &&
                 col_data != nullptr && col_valid != nullptr),
            SRT_ERR_NULLPTR, "null column arrays");
    expects(out_type_ids != nullptr && out_scales != nullptr &&
                out_num_columns != nullptr && out_col_data != nullptr &&
                out_col_valid != nullptr && out_num_rows != nullptr,
            SRT_ERR_NULLPTR, "null output arrays");
    ensure_init();
    GilGuard gil;

    PyObject* t_ids = nullptr;
    PyObject* t_scales = nullptr;
    PyObject* datas = nullptr;
    PyObject* valids = nullptr;
    PyObject* res = nullptr;
    try {
      t_ids = PyList_New(num_columns);
      t_scales = PyList_New(num_columns);
      datas = PyList_New(num_columns);
      valids = PyList_New(num_columns);
      expects(t_ids != nullptr && t_scales != nullptr &&
                  datas != nullptr && valids != nullptr,
              SRT_ERR_UNKNOWN, "argument list allocation failed");
      for (int32_t i = 0; i < num_columns; ++i) {
        PyObject* id_obj = PyLong_FromLong(type_ids[i]);
        PyObject* sc_obj = PyLong_FromLong(scales[i]);
        expects(id_obj != nullptr && sc_obj != nullptr, SRT_ERR_UNKNOWN,
                "int allocation failed");
        PyList_SET_ITEM(t_ids, i, id_obj);
        PyList_SET_ITEM(t_scales, i, sc_obj);
        PyList_SET_ITEM(datas, i, buffer_to_py(col_data[i]));
        PyList_SET_ITEM(valids, i, buffer_to_py(col_valid[i]));
      }
      PyObject* fn = bridge_attr("table_op_wire");
      res = PyObject_CallFunction(
          fn, "sOOOOL", op_json, t_ids, t_scales, datas, valids,
          static_cast<long long>(num_rows));
      Py_DECREF(fn);
      if (res == nullptr) throw_python_error("table_op_wire");
    } catch (...) {
      Py_XDECREF(t_ids);
      Py_XDECREF(t_scales);
      Py_XDECREF(datas);
      Py_XDECREF(valids);
      if (PyErr_Occurred()) PyErr_Clear();
      throw;
    }
    Py_DECREF(t_ids);
    Py_DECREF(t_scales);
    Py_DECREF(datas);
    Py_DECREF(valids);

    /* result: (type_ids, scales, datas, valids, num_rows) — validate
     * the whole shape before touching anything, so a malformed bridge
     * result is an error, never SRT_OK with garbage counts */
    if (!PyTuple_Check(res) || PyTuple_GET_SIZE(res) != 5) {
      Py_DECREF(res);
      throw srt_error(SRT_ERR_UNKNOWN, "table_op_wire: bad result shape");
    }
    PyObject* r_ids = PyTuple_GET_ITEM(res, 0);
    PyObject* r_scales = PyTuple_GET_ITEM(res, 1);
    PyObject* r_datas = PyTuple_GET_ITEM(res, 2);
    PyObject* r_valids = PyTuple_GET_ITEM(res, 3);
    PyObject* r_rows = PyTuple_GET_ITEM(res, 4);
    if (!PyList_Check(r_ids) || !PyList_Check(r_scales) ||
        !PyList_Check(r_datas) || !PyList_Check(r_valids) ||
        !PyLong_Check(r_rows)) {
      Py_DECREF(res);
      throw srt_error(SRT_ERR_UNKNOWN, "table_op_wire: bad result types");
    }
    Py_ssize_t n_out = PyList_GET_SIZE(r_ids);
    if (PyList_GET_SIZE(r_scales) != n_out ||
        PyList_GET_SIZE(r_datas) != n_out ||
        PyList_GET_SIZE(r_valids) != n_out) {
      Py_DECREF(res);
      throw srt_error(SRT_ERR_UNKNOWN,
                      "table_op_wire: ragged result lists");
    }
    if (n_out > max_out_columns) {
      Py_DECREF(res);
      throw srt_error(SRT_ERR_OVERFLOW,
                      "result has more columns than max_out_columns");
    }
    /* Create all output buffers, releasing on partial failure so the
     * registry never leaks (the RowConversion.java cleanup discipline). */
    std::vector<srt_handle> created;
    created.reserve(static_cast<size_t>(2 * n_out));
    try {
      for (Py_ssize_t i = 0; i < n_out; ++i) {
        PyObject* d = PyList_GetItem(r_datas, i);
        PyObject* v = PyList_GetItem(r_valids, i);
        PyObject* id_obj = PyList_GetItem(r_ids, i);
        PyObject* sc_obj = PyList_GetItem(r_scales, i);
        expects(id_obj != nullptr && PyLong_Check(id_obj) &&
                    sc_obj != nullptr && PyLong_Check(sc_obj),
                SRT_ERR_UNKNOWN, "table_op_wire: non-int id/scale");
        expects(d != nullptr && PyBytes_Check(d), SRT_ERR_UNKNOWN,
                "table_op_wire: data not bytes");
        srt_handle hd = srt_buffer_create(
            PyBytes_AS_STRING(d), PyBytes_GET_SIZE(d), "jax-op-out");
        expects(hd != 0, SRT_ERR_UNKNOWN, "buffer create failed");
        created.push_back(hd);
        srt_handle hv = 0;
        if (v != nullptr && v != Py_None) {
          expects(PyBytes_Check(v), SRT_ERR_UNKNOWN,
                  "table_op_wire: validity not bytes");
          hv = srt_buffer_create(PyBytes_AS_STRING(v),
                                 PyBytes_GET_SIZE(v), "jax-op-out-valid");
          expects(hv != 0, SRT_ERR_UNKNOWN, "buffer create failed");
          created.push_back(hv);
        }
        out_type_ids[i] = static_cast<int32_t>(PyLong_AsLong(id_obj));
        out_scales[i] = static_cast<int32_t>(PyLong_AsLong(sc_obj));
        out_col_data[i] = hd;
        out_col_valid[i] = hv;
      }
    } catch (...) {
      for (srt_handle h : created) srt_buffer_release(h);
      Py_DECREF(res);
      throw;
    }
    *out_num_columns = static_cast<int32_t>(n_out);
    *out_num_rows = static_cast<int64_t>(PyLong_AsLongLong(r_rows));
    Py_DECREF(res);
  });
}

}  // extern "C"

#endif  // SRT_EMBED_JAX
