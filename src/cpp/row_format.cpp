/* Packed row codec: bit-exact host implementation of the row format.
 *
 * The normative spec is the reference javadoc (RowConversion.java:43-102)
 * and layout computation (row_conversion.cu:432-456):
 *   - each fixed-width column at align_offset(cursor, width);
 *   - validity = 1 bit/column LSB-first, bytes appended after the last
 *     column value (row_conversion.cu:448-453);
 *   - row padded to a 64-bit multiple (:454-455);
 *   - fixed-width types only (:514-516).
 *
 * This host codec is the JVM-boundary fast path (Spark UnsafeRow-style
 * batches handed over JNI without a Python hop); the device path is the
 * XLA/Pallas implementation in spark_rapids_jni_tpu/rows.py, and the two
 * are golden-tested byte-for-byte against each other
 * (tests/test_native.py). Row-major loops over a column-contiguous
 * source: the inner loop strides one column's buffer sequentially, so
 * the hardware prefetcher sees the same streaming pattern the CUDA
 * kernels engineered with coalesced int64 spans (row_conversion.cu:86-106). */

#include <climits>
#include <cstring>
#include <vector>

#include "error.hpp"
#include "spark_rapids_tpu/c_api.h"

namespace {

/* Widths follow spark_rapids_jni_tpu.dtype._WIDTHS (cudf size_of). */
int32_t type_width(int32_t type_id) {
  switch (type_id) {
    case 1:   /* INT8 */
    case 5:   /* UINT8 */
    case 11:  /* BOOL8 */
      return 1;
    case 2:   /* INT16 */
    case 6:   /* UINT16 */
      return 2;
    case 3:   /* INT32 */
    case 7:   /* UINT32 */
    case 9:   /* FLOAT32 */
    case 12:  /* TIMESTAMP_DAYS */
    case 17:  /* DURATION_DAYS */
    case 22:  /* DICTIONARY32 */
    case 25:  /* DECIMAL32 */
      return 4;
    case 4:   /* INT64 */
    case 8:   /* UINT64 */
    case 10:  /* FLOAT64 */
    case 13: case 14: case 15: case 16:  /* TIMESTAMP_* */
    case 18: case 19: case 20: case 21:  /* DURATION_* */
    case 26:  /* DECIMAL64 */
      return 8;
    case 27:  /* DECIMAL128 */
      return 16;
    default:
      return 0;
  }
}

int32_t align_offset(int32_t offset, int32_t alignment) {
  /* row_conversion.cu:417-419 */
  return (offset + alignment - 1) & ~(alignment - 1);
}

struct Layout {
  std::vector<int32_t> offsets;
  std::vector<int32_t> widths;
  int32_t validity_offset = 0;
  int32_t validity_bytes = 0;
  int32_t row_size = 0;
};

Layout compute_layout(const int32_t* type_ids, int32_t num_columns) {
  using spark_rapids_tpu::expects;
  expects(type_ids != nullptr, SRT_ERR_NULLPTR, "type_ids is null");
  expects(num_columns > 0, SRT_ERR_INVALID, "row format requires columns");
  Layout l;
  l.offsets.reserve(num_columns);
  l.widths.reserve(num_columns);
  int32_t cursor = 0;
  for (int32_t i = 0; i < num_columns; ++i) {
    int32_t w = type_width(type_ids[i]);
    expects(w > 0, SRT_ERR_TYPE, "non-fixed-width type in row format");
    cursor = align_offset(cursor, w);
    l.offsets.push_back(cursor);
    l.widths.push_back(w);
    cursor += w;
  }
  l.validity_offset = cursor;
  l.validity_bytes = (num_columns + 7) / 8;
  cursor += l.validity_bytes;
  l.row_size = align_offset(cursor, 8);
  return l;
}

}  // namespace

extern "C" {

int32_t srt_type_width(int32_t type_id) { return type_width(type_id); }

srt_status srt_compute_row_layout(const int32_t* type_ids,
                                  int32_t num_columns, int32_t* col_offsets,
                                  int32_t* col_widths,
                                  srt_row_layout* layout) {
  return spark_rapids_tpu::translate([&] {
    using spark_rapids_tpu::expects;
    expects(col_offsets && col_widths && layout, SRT_ERR_NULLPTR,
            "null output pointer");
    Layout l = compute_layout(type_ids, num_columns);
    std::memcpy(col_offsets, l.offsets.data(),
                sizeof(int32_t) * static_cast<size_t>(num_columns));
    std::memcpy(col_widths, l.widths.data(),
                sizeof(int32_t) * static_cast<size_t>(num_columns));
    layout->num_columns = num_columns;
    layout->validity_offset = l.validity_offset;
    layout->validity_bytes = l.validity_bytes;
    layout->row_size = l.row_size;
  });
}

int64_t srt_max_rows_per_batch(int32_t row_size) {
  /* row_conversion.cu:476-479 (with the 32-row-multiple discipline). */
  if (row_size <= 0) return 0;
  if (static_cast<int64_t>(row_size) * 32 > INT_MAX) return 0;
  return (INT_MAX / row_size) / 32 * 32;
}

srt_status srt_pack_rows(const int32_t* type_ids, int32_t num_columns,
                         const void* const* col_data,
                         const uint8_t* const* col_valid, int64_t num_rows,
                         uint8_t* out_rows) {
  return spark_rapids_tpu::translate([&] {
    using spark_rapids_tpu::expects;
    expects(col_data && out_rows, SRT_ERR_NULLPTR, "null buffer pointer");
    expects(num_rows >= 0, SRT_ERR_INVALID, "negative row count");
    Layout l = compute_layout(type_ids, num_columns);
    const size_t row_size = static_cast<size_t>(l.row_size);
    std::memset(out_rows, 0, row_size * static_cast<size_t>(num_rows));

    for (int32_t c = 0; c < num_columns; ++c) {
      const auto* src = static_cast<const uint8_t*>(col_data[c]);
      expects(src != nullptr, SRT_ERR_NULLPTR, "null column data");
      const size_t w = static_cast<size_t>(l.widths[c]);
      const size_t off = static_cast<size_t>(l.offsets[c]);
      uint8_t* dst = out_rows + off;
      for (int64_t r = 0; r < num_rows; ++r) {
        std::memcpy(dst, src, w);
        src += w;
        dst += row_size;
      }
    }
    /* Validity bytes: LSB-first bit per column, appended after the last
     * value (row_conversion.cu:448-453). Absent mask = all valid. */
    for (int64_t r = 0; r < num_rows; ++r) {
      uint8_t* vb = out_rows + r * row_size + l.validity_offset;
      for (int32_t c = 0; c < num_columns; ++c) {
        bool valid =
            (col_valid == nullptr || col_valid[c] == nullptr)
                ? true
                : (col_valid[c][r] != 0);
        if (valid) vb[c / 8] |= static_cast<uint8_t>(1u << (c % 8));
      }
    }
  });
}

srt_status srt_unpack_rows(const int32_t* type_ids, int32_t num_columns,
                           const uint8_t* rows, int64_t num_rows,
                           void* const* col_data_out,
                           uint8_t* const* col_valid_out) {
  return spark_rapids_tpu::translate([&] {
    using spark_rapids_tpu::expects;
    expects(rows && col_data_out && col_valid_out, SRT_ERR_NULLPTR,
            "null buffer pointer");
    expects(num_rows >= 0, SRT_ERR_INVALID, "negative row count");
    Layout l = compute_layout(type_ids, num_columns);
    const size_t row_size = static_cast<size_t>(l.row_size);

    for (int32_t c = 0; c < num_columns; ++c) {
      auto* dst = static_cast<uint8_t*>(col_data_out[c]);
      uint8_t* vdst = col_valid_out[c];
      expects(dst != nullptr && vdst != nullptr, SRT_ERR_NULLPTR,
              "null output column");
      const size_t w = static_cast<size_t>(l.widths[c]);
      const uint8_t* src = rows + static_cast<size_t>(l.offsets[c]);
      const uint8_t* vsrc = rows + static_cast<size_t>(l.validity_offset);
      const uint8_t bit = static_cast<uint8_t>(1u << (c % 8));
      const size_t vbyte = static_cast<size_t>(c / 8);
      for (int64_t r = 0; r < num_rows; ++r) {
        std::memcpy(dst, src, w);
        dst += w;
        vdst[r] = (vsrc[vbyte] & bit) ? 1 : 0;
        src += row_size;
        vsrc += row_size;
      }
    }
  });
}

}  /* extern "C" */
