/* Error translation internals: the CATCH_STD analog.
 *
 * The reference converts C++ exceptions to Java exceptions with CATCH_STD
 * and guards null handles with JNI_NULL_CHECK (RowConversionJni.cpp:27,
 * 40,49-50,65). Here every C-ABI entry point wraps its body in
 * SRT_TRANSLATE, which converts exceptions into status codes and stores a
 * thread-local message retrievable via srt_last_error(). */
#pragma once

#include <exception>
#include <stdexcept>
#include <string>

#include "spark_rapids_tpu/c_api.h"

namespace spark_rapids_tpu {

/* Typed exception carrying an srt_status. */
class srt_error : public std::runtime_error {
 public:
  srt_error(srt_status code, const std::string& what)
      : std::runtime_error(what), code_(code) {}
  srt_status code() const { return code_; }

 private:
  srt_status code_;
};

void set_last_error(const std::string& msg);

/* Run fn(); translate exceptions to status codes. */
template <typename Fn>
srt_status translate(Fn&& fn) {
  try {
    fn();
    return SRT_OK;
  } catch (const srt_error& e) {
    set_last_error(e.what());
    return e.code();
  } catch (const std::exception& e) {
    set_last_error(e.what());
    return SRT_ERR_UNKNOWN;
  } catch (...) {
    set_last_error("unknown error");
    return SRT_ERR_UNKNOWN;
  }
}

inline void expects(bool cond, srt_status code, const char* msg) {
  if (!cond) throw srt_error(code, msg);
}

}  // namespace spark_rapids_tpu
