#include "error.hpp"

#include <cstdlib>

namespace spark_rapids_tpu {
namespace {
thread_local std::string g_last_error;
}

void set_last_error(const std::string& msg) { g_last_error = msg; }

}  // namespace spark_rapids_tpu

extern "C" {

const char* srt_last_error(void) {
  return spark_rapids_tpu::g_last_error.c_str();
}

const char* srt_version(void) { return "spark-rapids-tpu 0.1.0"; }

srt_status srt_set_runtime_flag(const char* name, const char* value) {
  if (name == nullptr) {
    spark_rapids_tpu::set_last_error("flag name is NULL");
    return SRT_ERR_NULLPTR;
  }
  const std::string prefix = "SPARK_RAPIDS_TPU_";
  if (std::string(name).rfind(prefix, 0) != 0) {
    spark_rapids_tpu::set_last_error(
        std::string("runtime flag must start with ") + prefix);
    return SRT_ERR_INVALID;
  }
  int rc = value == nullptr ? ::unsetenv(name) : ::setenv(name, value, 1);
  if (rc != 0) {
    spark_rapids_tpu::set_last_error("setenv failed");
    return SRT_ERR_UNKNOWN;
  }
  return SRT_OK;
}

}  /* extern "C" */
