#include "error.hpp"

namespace spark_rapids_tpu {
namespace {
thread_local std::string g_last_error;
}

void set_last_error(const std::string& msg) { g_last_error = msg; }

}  // namespace spark_rapids_tpu

extern "C" {

const char* srt_last_error(void) {
  return spark_rapids_tpu::g_last_error.c_str();
}

const char* srt_version(void) { return "spark-rapids-tpu 0.1.0"; }

}  /* extern "C" */
