/* Handle registry: the native ownership model of the framework.
 *
 * The reference passes raw `new`-ed cudf object pointers across JNI as
 * jlongs and transfers ownership by `release()`-ing unique_ptrs into a
 * long array (RowConversionJni.cpp:31-38,54-63); leak hunting is a Java-
 * side refcount-debug system property (pom.xml:86,199). This registry
 * makes both first-class in native code: handles are registry ids (never
 * raw pointers — a stale handle is an error, not a crash), refcounts are
 * explicit, and a debug mode records provenance tags + a live-handle
 * report for leak tests (SURVEY.md §4 "leak detection as a test mode"). */

#include <atomic>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "error.hpp"
#include "spark_rapids_tpu/c_api.h"

namespace spark_rapids_tpu {
namespace {

struct Buffer {
  std::vector<uint8_t> bytes;
  int64_t refcount = 1;
  std::string tag;
  uint64_t seq = 0;  // creation order (provenance in debug mode)
};

struct Registry {
  std::mutex mu;
  std::map<int64_t, Buffer> buffers;
  int64_t next_id = 1;
  uint64_t next_seq = 1;
  std::atomic<bool> refcount_debug{false};
};

Registry& registry() {
  static Registry r;
  return r;
}

}  // namespace
}  // namespace spark_rapids_tpu

using spark_rapids_tpu::expects;
using spark_rapids_tpu::registry;
using spark_rapids_tpu::translate;

extern "C" {

srt_handle srt_buffer_create(const void* data, int64_t nbytes,
                             const char* tag) {
  srt_handle out = 0;
  srt_status s = translate([&] {
    expects(nbytes >= 0, SRT_ERR_INVALID, "negative buffer size");
    expects(data != nullptr || nbytes == 0, SRT_ERR_NULLPTR,
            "null data with nonzero size");
    auto& reg = registry();
    std::lock_guard<std::mutex> lock(reg.mu);
    int64_t id = reg.next_id++;
    auto& buf = reg.buffers[id];
    buf.bytes.resize(static_cast<size_t>(nbytes));
    if (nbytes > 0) std::memcpy(buf.bytes.data(), data, nbytes);
    buf.tag = tag ? tag : "";
    buf.seq = reg.next_seq++;
    out = id;
  });
  return s == SRT_OK ? out : 0;
}

srt_handle srt_buffer_alloc(int64_t nbytes, const char* tag) {
  srt_handle out = 0;
  srt_status s = translate([&] {
    expects(nbytes >= 0, SRT_ERR_INVALID, "negative buffer size");
    auto& reg = registry();
    std::lock_guard<std::mutex> lock(reg.mu);
    int64_t id = reg.next_id++;
    auto& buf = reg.buffers[id];
    buf.bytes.resize(static_cast<size_t>(nbytes));
    buf.tag = tag ? tag : "";
    buf.seq = reg.next_seq++;
    out = id;
  });
  return s == SRT_OK ? out : 0;
}

srt_status srt_buffer_retain(srt_handle h) {
  return translate([&] {
    auto& reg = registry();
    std::lock_guard<std::mutex> lock(reg.mu);
    auto it = reg.buffers.find(h);
    expects(it != reg.buffers.end(), SRT_ERR_HANDLE, "unknown handle");
    it->second.refcount++;
  });
}

srt_status srt_buffer_release(srt_handle h) {
  return translate([&] {
    auto& reg = registry();
    std::lock_guard<std::mutex> lock(reg.mu);
    auto it = reg.buffers.find(h);
    expects(it != reg.buffers.end(), SRT_ERR_HANDLE, "unknown handle");
    if (--it->second.refcount == 0) reg.buffers.erase(it);
  });
}

void* srt_buffer_data(srt_handle h) {
  // Non-null sentinel for valid zero-length buffers: callers use nullptr
  // to mean "bad handle", and vector<uint8_t>::data() may return nullptr
  // when empty. Zero-byte reads/writes through this pointer are no-ops.
  static uint8_t empty_sentinel = 0;
  auto& reg = registry();
  std::lock_guard<std::mutex> lock(reg.mu);
  auto it = reg.buffers.find(h);
  if (it == reg.buffers.end()) {
    spark_rapids_tpu::set_last_error("unknown handle");
    return nullptr;
  }
  if (it->second.bytes.empty()) return &empty_sentinel;
  return it->second.bytes.data();
}

int64_t srt_buffer_size(srt_handle h) {
  auto& reg = registry();
  std::lock_guard<std::mutex> lock(reg.mu);
  auto it = reg.buffers.find(h);
  if (it == reg.buffers.end()) {
    spark_rapids_tpu::set_last_error("unknown handle");
    return -1;
  }
  return static_cast<int64_t>(it->second.bytes.size());
}

void srt_set_refcount_debug(int enabled) {
  registry().refcount_debug.store(enabled != 0);
}

int64_t srt_live_handle_count(void) {
  auto& reg = registry();
  std::lock_guard<std::mutex> lock(reg.mu);
  return static_cast<int64_t>(reg.buffers.size());
}

int64_t srt_leak_report(char* buf, int64_t buflen) {
  auto& reg = registry();
  std::lock_guard<std::mutex> lock(reg.mu);
  std::string report;
  for (const auto& [id, b] : reg.buffers) {
    report += std::to_string(id) + " tag=" + (b.tag.empty() ? "?" : b.tag) +
              " refcount=" + std::to_string(b.refcount) +
              " nbytes=" + std::to_string(b.bytes.size()) +
              " seq=" + std::to_string(b.seq) + "\n";
  }
  int64_t needed = static_cast<int64_t>(report.size()) + 1;
  if (buf != nullptr && buflen > 0) {
    int64_t n = std::min<int64_t>(buflen - 1, report.size());
    std::memcpy(buf, report.data(), static_cast<size_t>(n));
    buf[n] = '\0';
  }
  return needed;
}

}  /* extern "C" */
