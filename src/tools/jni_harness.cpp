/* Executes the REAL JNI bridge entry points end-to-end against the mock
 * JNIEnv (src/jni_mock/) and the embedded JAX runtime — the no-JVM
 * equivalent of the reference running RowConversionTest through a live
 * JVM on GPU CI (RowConversionJni.cpp:24-66, ci/premerge-build.sh:22-28).
 *
 * Covers, through the actual Java_com_nvidia_spark_rapids_jni_* symbols:
 *   1. DeviceTable: runtime availability/init/platform
 *   2. DeviceTable.tableOpNative groupby on the XLA backend vs an oracle
 *   3. RowConversion.convertToRowsNative vs the host codec, then
 *      convertFromRowsNative round-trip (HostBuffer handles throughout)
 *   4. Error paths: null args, length mismatches, bad batch ranges,
 *      stale handles — each must record a pending Java exception
 *   5. Cleanup paths: allocation-failure fault injection must release
 *      every registry handle (the RowConversion.java:56 discipline)
 *   6. Zero leaked handles at exit (refcount-debug analog)
 *
 * Exit 0 on success; prints the failing check otherwise. */

#include <jni.h>

#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#include "../jni_mock/mock_jni.hpp"
#include "spark_rapids_tpu/c_api.h"

/* The bridge's exported JNI symbols (declared here rather than via a
 * generated header; signatures must match src/jni/ *.cpp). */
extern "C" {
jboolean Java_com_nvidia_spark_rapids_jni_DeviceTable_isDeviceRuntimeAvailable(
    JNIEnv*, jclass);
void Java_com_nvidia_spark_rapids_jni_DeviceTable_initDeviceRuntime(
    JNIEnv*, jclass);
jstring Java_com_nvidia_spark_rapids_jni_DeviceTable_devicePlatform(
    JNIEnv*, jclass);
jlongArray Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpNative(
    JNIEnv*, jclass, jstring, jintArray, jintArray, jlongArray, jlongArray,
    jlong);
jlong Java_com_nvidia_spark_rapids_jni_DeviceTable_tableUploadNative(
    JNIEnv*, jclass, jintArray, jintArray, jlongArray, jlongArray, jlong);
jlong Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpResidentNative(
    JNIEnv*, jclass, jstring, jlongArray);
jlongArray Java_com_nvidia_spark_rapids_jni_DeviceTable_tableDownloadNative(
    JNIEnv*, jclass, jlong);
jlong Java_com_nvidia_spark_rapids_jni_DeviceTable_tableNumRows(
    JNIEnv*, jclass, jlong);
void Java_com_nvidia_spark_rapids_jni_DeviceTable_tableFree(
    JNIEnv*, jclass, jlong);
jlong Java_com_nvidia_spark_rapids_jni_DeviceTable_residentTableCount(
    JNIEnv*, jclass);
void Java_com_nvidia_spark_rapids_jni_DeviceTable_setRuntimeFlag(
    JNIEnv*, jclass, jstring, jstring);
jlong Java_com_nvidia_spark_rapids_jni_RowConversion_convertToRowsNative(
    JNIEnv*, jclass, jlong, jintArray, jlong, jlong, jlong);
jlongArray Java_com_nvidia_spark_rapids_jni_RowConversion_convertFromRowsNative(
    JNIEnv*, jclass, jlong, jintArray, jintArray, jlong);
jlong Java_com_nvidia_spark_rapids_jni_HostBuffer_bufferCreate(
    JNIEnv*, jclass, jbyteArray, jstring);
jbyteArray Java_com_nvidia_spark_rapids_jni_HostBuffer_bufferGet(
    JNIEnv*, jclass, jlong);
void Java_com_nvidia_spark_rapids_jni_HostBuffer_bufferRelease(
    JNIEnv*, jclass, jlong);
jint Java_com_nvidia_spark_rapids_jni_RowConversion_rowSize(
    JNIEnv*, jclass, jintArray);
}

namespace {

constexpr int32_t kInt64 = 4;    /* TypeId.INT64 */
constexpr int32_t kFloat64 = 10; /* TypeId.FLOAT64 */

#define CHECK(cond, msg)                                          \
  do {                                                            \
    if (!(cond)) {                                                \
      std::fprintf(stderr, "FAIL: %s (last error: %s; jexc: %s)\n", \
                   msg, srt_last_error(),                         \
                   srt_mock::exception_message().c_str());        \
      return 1;                                                   \
    }                                                             \
  } while (0)

#define CHECK_THROWS(expr, msg)                          \
  do {                                                   \
    srt_mock::clear_exception();                         \
    (void)(expr);                                        \
    CHECK(srt_mock::exception_pending(), msg);           \
    srt_mock::clear_exception();                         \
  } while (0)

}  // namespace

int main() {
  JNIEnv env_obj;
  JNIEnv* env = &env_obj;
  jclass cls = env->FindClass("mock/Cls");

  /* -- 0. runtime flag plane (the ai.rapids.cudf.Rmm path): set before
   * init like a real executor would, verify the env the embedded
   * runtime reads, unset, and reject non-flag-plane names ------------ */
  {
    jstring fname = env->NewStringUTF("SPARK_RAPIDS_TPU_ALLOC_LOG_LEVEL");
    jstring fval = env->NewStringUTF("DEBUG");
    Java_com_nvidia_spark_rapids_jni_DeviceTable_setRuntimeFlag(
        env, cls, fname, fval);
    CHECK(!srt_mock::exception_pending(), "setRuntimeFlag threw");
    const char* got = std::getenv("SPARK_RAPIDS_TPU_ALLOC_LOG_LEVEL");
    CHECK(got != nullptr && std::string(got) == "DEBUG",
          "flag did not reach the process environment");
    Java_com_nvidia_spark_rapids_jni_DeviceTable_setRuntimeFlag(
        env, cls, fname, nullptr);
    CHECK(!srt_mock::exception_pending(), "setRuntimeFlag(unset) threw");
    CHECK(std::getenv("SPARK_RAPIDS_TPU_ALLOC_LOG_LEVEL") == nullptr,
          "flag unset did not clear the environment");
    jstring bad = env->NewStringUTF("PATH");
    CHECK_THROWS(Java_com_nvidia_spark_rapids_jni_DeviceTable_setRuntimeFlag(
                     env, cls, bad, fval),
                 "non-flag-plane name must be rejected");
  }

  /* -- 1. runtime lifecycle through the DeviceTable entry points ----- */
  CHECK(Java_com_nvidia_spark_rapids_jni_DeviceTable_isDeviceRuntimeAvailable(
            env, cls) == JNI_TRUE,
        "device runtime not built in");
  Java_com_nvidia_spark_rapids_jni_DeviceTable_initDeviceRuntime(env, cls);
  CHECK(!srt_mock::exception_pending(), "initDeviceRuntime threw");
  jstring plat =
      Java_com_nvidia_spark_rapids_jni_DeviceTable_devicePlatform(env, cls);
  CHECK(plat != nullptr, "devicePlatform returned null");
  const char* plat_c = env->GetStringUTFChars(plat, nullptr);
  std::printf("jni_harness: platform = %s\n", plat_c);

  /* -- table data: k int64 (one null), v float64 --------------------- */
  const int64_t n = 64;
  std::vector<int64_t> k(n);
  std::vector<double> v(n);
  std::vector<uint8_t> k_valid(n, 1);
  for (int64_t i = 0; i < n; ++i) {
    k[i] = i % 5;
    v[i] = static_cast<double>(i) * 0.5;
  }
  k_valid[9] = 0;

  srt_handle hk = srt_buffer_create(k.data(), n * 8, "h-k");
  srt_handle hv = srt_buffer_create(v.data(), n * 8, "h-v");
  srt_handle hkv = srt_buffer_create(k_valid.data(), n, "h-kv");
  CHECK(hk != 0 && hv != 0 && hkv != 0, "buffer create");

  /* -- 2. groupby through tableOpNative ------------------------------ */
  jstring op = srt_mock::make_string(
      "{\"op\": \"groupby\", \"by\": [0], "
      "\"aggs\": [{\"column\": 1, \"agg\": \"sum\"}]}");
  jintArray ids = srt_mock::make_int_array({kInt64, kFloat64});
  jintArray scales = srt_mock::make_int_array({0, 0});
  jlongArray data = srt_mock::make_long_array({hk, hv});
  jlongArray valid = srt_mock::make_long_array({hkv, 0});
  jlongArray packed = Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpNative(
      env, cls, op, ids, scales, data, valid, n);
  CHECK(!srt_mock::exception_pending(), "tableOpNative threw");
  CHECK(packed != nullptr, "tableOpNative returned null");
  std::vector<jlong> pk = srt_mock::long_array_values(packed);
  CHECK(pk.size() >= 2, "packed result too short");
  const int64_t out_cols = pk[0];
  const int64_t out_rows = pk[1];
  CHECK(out_cols == 2, "groupby output arity");
  CHECK(pk.size() == 2 + 4 * static_cast<size_t>(out_cols),
        "packed result length");

  std::map<int64_t, double> want;
  double null_sum = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    if (k_valid[i]) {
      want[k[i]] += v[i];
    } else {
      null_sum += v[i];
    }
  }
  CHECK(out_rows == static_cast<int64_t>(want.size()) + 1,
        "groupby group count (null key group included)");
  srt_handle gk = pk[2 + 2 * out_cols + 0];
  srt_handle gs = pk[2 + 2 * out_cols + 1];
  srt_handle gkv = pk[2 + 3 * out_cols + 0];
  const int64_t* got_k = static_cast<const int64_t*>(srt_buffer_data(gk));
  const double* got_s = static_cast<const double*>(srt_buffer_data(gs));
  const uint8_t* got_kv =
      gkv == 0 ? nullptr : static_cast<const uint8_t*>(srt_buffer_data(gkv));
  CHECK(got_k != nullptr && got_s != nullptr, "groupby output buffers");
  for (int64_t i = 0; i < out_rows; ++i) {
    if (got_kv != nullptr && got_kv[i] == 0) {
      CHECK(got_s[i] == null_sum, "null-group sum");
      continue;
    }
    auto it = want.find(got_k[i]);
    CHECK(it != want.end() && it->second == got_s[i], "group sum");
  }
  std::printf("jni_harness: tableOpNative groupby %" PRId64
              " rows -> %" PRId64 " groups ok\n", n, out_rows);

  /* -- 3. RowConversion round trip over HostBuffer handles ----------- */
  /* table buffer = col buffers back-to-back, then validity vectors */
  std::vector<jbyte> tbl_bytes;
  auto append = [&tbl_bytes](const void* p, size_t nbytes) {
    const auto* b = static_cast<const jbyte*>(p);
    tbl_bytes.insert(tbl_bytes.end(), b, b + nbytes);
  };
  append(k.data(), n * 8);
  append(v.data(), n * 8);
  append(k_valid.data(), n);
  std::vector<uint8_t> all_valid(n, 1);
  append(all_valid.data(), n);

  jlong th = Java_com_nvidia_spark_rapids_jni_HostBuffer_bufferCreate(
      env, cls, srt_mock::make_byte_array(tbl_bytes),
      srt_mock::make_string("tbl"));
  CHECK(!srt_mock::exception_pending() && th != 0, "bufferCreate");

  jint row_size = Java_com_nvidia_spark_rapids_jni_RowConversion_rowSize(
      env, cls, ids);
  CHECK(row_size > 0, "rowSize");
  jlong rows_h =
      Java_com_nvidia_spark_rapids_jni_RowConversion_convertToRowsNative(
          env, cls, th, ids, n, 0, n);
  CHECK(!srt_mock::exception_pending() && rows_h != 0, "convertToRows");

  /* byte-exact vs the host codec (the golden row-format check) */
  std::vector<uint8_t> want_rows(static_cast<size_t>(n) * row_size);
  const int32_t tids[2] = {kInt64, kFloat64};
  const void* cols[2] = {k.data(), v.data()};
  const uint8_t* valids[2] = {k_valid.data(), nullptr};
  CHECK(srt_pack_rows(tids, 2, cols, valids, n, want_rows.data()) == SRT_OK,
        "host pack");
  CHECK(srt_buffer_size(rows_h) == static_cast<int64_t>(want_rows.size()),
        "rows size");
  CHECK(std::memcmp(srt_buffer_data(rows_h), want_rows.data(),
                    want_rows.size()) == 0,
        "bridge rows != host codec rows");

  jlongArray back =
      Java_com_nvidia_spark_rapids_jni_RowConversion_convertFromRowsNative(
          env, cls, rows_h, ids, scales, n);
  CHECK(!srt_mock::exception_pending() && back != nullptr,
        "convertFromRows");
  std::vector<jlong> bh = srt_mock::long_array_values(back);
  CHECK(bh.size() == 4, "convertFromRows handle count");
  CHECK(std::memcmp(srt_buffer_data(bh[0]), k.data(), n * 8) == 0,
        "k column round trip");
  CHECK(std::memcmp(srt_buffer_data(bh[1]), v.data(), n * 8) == 0,
        "v column round trip");
  CHECK(std::memcmp(srt_buffer_data(bh[2]), k_valid.data(), n) == 0,
        "k validity round trip");
  std::printf("jni_harness: RowConversion round trip ok (%d B/row)\n",
              row_size);

  /* -- 3b. device-resident chaining through the JNI entry points ----- */
  {
    jlong sales_t = Java_com_nvidia_spark_rapids_jni_DeviceTable_tableUploadNative(
        env, cls, ids, scales, data, valid, n);
    CHECK(!srt_mock::exception_pending() && sales_t != 0, "tableUpload");
    CHECK(Java_com_nvidia_spark_rapids_jni_DeviceTable_tableNumRows(
              env, cls, sales_t) == n,
          "tableNumRows");
    jlong sorted_t =
        Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpResidentNative(
            env, cls,
            srt_mock::make_string(
                "{\"op\": \"sort_by\", \"keys\": [{\"column\": 0}]}"),
            srt_mock::make_long_array({sales_t}));
    CHECK(!srt_mock::exception_pending() && sorted_t != 0,
          "tableOpResident");
    jlong agg_t =
        Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpResidentNative(
            env, cls,
            srt_mock::make_string(
                "{\"op\": \"groupby\", \"by\": [0], "
                "\"aggs\": [{\"column\": 1, \"agg\": \"sum\"}]}"),
            srt_mock::make_long_array({sorted_t}));
    CHECK(!srt_mock::exception_pending() && agg_t != 0,
          "chained tableOpResident");
    jlongArray dl =
        Java_com_nvidia_spark_rapids_jni_DeviceTable_tableDownloadNative(
            env, cls, agg_t);
    CHECK(!srt_mock::exception_pending() && dl != nullptr,
          "tableDownload");
    std::vector<jlong> dlv = srt_mock::long_array_values(dl);
    CHECK(dlv.size() >= 2 && dlv[0] == 2 && dlv[1] == out_rows,
          "resident chain result shape");
    /* chained groupby over sorted input must equal the wire groupby */
    const int64_t dcols = dlv[0];
    const double* ds =
        static_cast<const double*>(srt_buffer_data(dlv[2 + 2 * dcols + 1]));
    CHECK(ds != nullptr, "download buffers");
    double total_direct = 0.0;
    double total_res = 0.0;
    for (int64_t i = 0; i < out_rows; ++i) {
      total_direct += got_s[i];
      total_res += ds[i];
    }
    CHECK(total_direct == total_res, "resident chain sum mismatch");
    for (int64_t i = 0; i < dcols; ++i) {
      srt_buffer_release(dlv[2 + 2 * dcols + i]);
      if (dlv[2 + 3 * dcols + i] != 0)
        srt_buffer_release(dlv[2 + 3 * dcols + i]);
    }
    Java_com_nvidia_spark_rapids_jni_DeviceTable_tableFree(env, cls,
                                                           sales_t);
    Java_com_nvidia_spark_rapids_jni_DeviceTable_tableFree(env, cls,
                                                           sorted_t);
    Java_com_nvidia_spark_rapids_jni_DeviceTable_tableFree(env, cls, agg_t);
    CHECK(Java_com_nvidia_spark_rapids_jni_DeviceTable_residentTableCount(
              env, cls) == 0,
          "resident table leak");
    /* freeing twice / unknown id must raise */
    CHECK_THROWS(
        Java_com_nvidia_spark_rapids_jni_DeviceTable_tableFree(env, cls,
                                                               agg_t),
        "double free must throw");
    std::printf("jni_harness: resident-table chaining ok\n");
  }

  /* -- 3c. DECIMAL128 across the JNI wire (16-byte limb values) ------- */
  {
    /* unscaled values spanning past 64 bits: -(2^70), -1, 0, 1, 2^70 */
    const int64_t dn = 5;
    uint64_t limbs[dn][2] = {
        {0, 0xFFFFFFFFFFFFFFC0ULL},  /* -(2^70) : lo=0, hi=-(1<<6) */
        {0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL}, /* -1 */
        {0, 0},
        {1, 0},
        {0, 0x40ULL},                /* 2^70 : hi = 1<<6 */
    };
    /* shuffle them out of order */
    uint64_t shuffled[dn][2];
    const int order[dn] = {4, 1, 3, 0, 2};
    for (int i = 0; i < dn; ++i) {
      shuffled[i][0] = limbs[order[i]][0];
      shuffled[i][1] = limbs[order[i]][1];
    }
    srt_handle hd128 = srt_buffer_create(shuffled, sizeof shuffled, "d128");
    CHECK(hd128 != 0, "decimal128 buffer");
    jintArray did = srt_mock::make_int_array({27});   /* DECIMAL128 */
    jintArray dsc = srt_mock::make_int_array({-7});
    jlongArray ddat = srt_mock::make_long_array({hd128});
    jlongArray dval = srt_mock::make_long_array({0});
    jlongArray dres = Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpNative(
        env, cls,
        srt_mock::make_string(
            "{\"op\": \"sort_by\", \"keys\": [{\"column\": 0}]}"),
        did, dsc, ddat, dval, dn);
    CHECK(!srt_mock::exception_pending() && dres != nullptr,
          "decimal128 sort dispatch");
    std::vector<jlong> dv = srt_mock::long_array_values(dres);
    CHECK(dv[0] == 1 && dv[1] == dn, "decimal128 result shape");
    CHECK(dv[2] == 27 && dv[2 + 1] == -7, "decimal128 type/scale echo");
    const auto* sorted128 =
        static_cast<const uint64_t*>(srt_buffer_data(dv[4]));
    for (int64_t i = 0; i < dn; ++i) {
      CHECK(sorted128[2 * i] == limbs[i][0] &&
                sorted128[2 * i + 1] == limbs[i][1],
            "decimal128 sorted order");
    }
    srt_buffer_release(dv[4]);
    srt_buffer_release(hd128);
    std::printf("jni_harness: DECIMAL128 wire sort ok\n");
  }

  /* -- 3d. STRING columns over the JNI wire (Arrow offsets+bytes) +
   *        the regex row filter (rlike) --------------------------------*/
  {
    const int64_t sn = 4;
    const char* words[4] = {"id=42", "nope", "id=7", "xid="};
    std::vector<int32_t> offs(sn + 1, 0);
    std::string payload;
    for (int i = 0; i < sn; ++i) {
      payload += words[i];
      offs[i + 1] = static_cast<int32_t>(payload.size());
    }
    std::vector<uint8_t> swire(4 * (sn + 1) + payload.size());
    std::memcpy(swire.data(), offs.data(), 4 * (sn + 1));
    std::memcpy(swire.data() + 4 * (sn + 1), payload.data(),
                payload.size());
    std::vector<int64_t> skeys = {0, 1, 2, 3};
    srt_handle hsk = srt_buffer_create(skeys.data(), sn * 8, "s-k");
    srt_handle hss = srt_buffer_create(swire.data(),
                                       static_cast<int64_t>(swire.size()),
                                       "s-s");
    CHECK(hsk != 0 && hss != 0, "string wire buffers");
    jintArray sid = srt_mock::make_int_array({kInt64, 23 /* STRING */});
    jintArray ssc = srt_mock::make_int_array({0, 0});
    jlongArray sdat = srt_mock::make_long_array({hsk, hss});
    jlongArray sval = srt_mock::make_long_array({0, 0});
    jlongArray sres = Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpNative(
        env, cls,
        srt_mock::make_string(
            "{\"op\": \"rlike\", \"column\": 1, "
            "\"pattern\": \"^id=\\\\d+$\"}"),
        sid, ssc, sdat, sval, sn);
    CHECK(!srt_mock::exception_pending() && sres != nullptr,
          "string rlike dispatch");
    std::vector<jlong> sv = srt_mock::long_array_values(sres);
    CHECK(sv[0] == 2 && sv[1] == 2, "rlike result shape (2 rows kept)");
    CHECK(sv[2] == kInt64 && sv[3] == 23, "rlike type echo");
    const int64_t scols = sv[0];
    const auto* fk =
        static_cast<const int64_t*>(srt_buffer_data(sv[2 + 2 * scols]));
    CHECK(fk[0] == 0 && fk[1] == 2, "rlike kept the matching rows");
    const auto* fs = static_cast<const uint8_t*>(
        srt_buffer_data(sv[2 + 2 * scols + 1]));
    const auto* foffs = reinterpret_cast<const int32_t*>(fs);
    CHECK(foffs[0] == 0 && foffs[1] == 5 && foffs[2] == 9,
          "filtered string offsets");
    CHECK(std::memcmp(fs + 4 * 3, "id=42id=7", 9) == 0,
          "filtered string payload");
    for (int64_t i = 0; i < scols; ++i) {
      srt_buffer_release(sv[2 + 2 * scols + i]);
      if (sv[2 + 3 * scols + i] != 0)
        srt_buffer_release(sv[2 + 3 * scols + i]);
    }
    srt_buffer_release(hsk);
    srt_buffer_release(hss);
    std::printf("jni_harness: STRING wire rlike ok\n");
  }

  /* -- 4. error paths must record pending Java exceptions ------------ */
  CHECK_THROWS(
      Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpNative(
          env, cls, nullptr, ids, scales, data, valid, n),
      "null op_json must throw");
  CHECK_THROWS(
      Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpNative(
          env, cls, op, ids, srt_mock::make_int_array({0}), data, valid, n),
      "length mismatch must throw");
  CHECK_THROWS(
      Java_com_nvidia_spark_rapids_jni_RowConversion_convertToRowsNative(
          env, cls, th, ids, n, n - 4, 8),
      "out-of-bounds batch must throw");
  CHECK_THROWS(
      Java_com_nvidia_spark_rapids_jni_RowConversion_convertToRowsNative(
          env, cls, 0, ids, n, 0, n),
      "null table handle must throw");
  CHECK_THROWS(
      Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpNative(
          env, cls, srt_mock::make_string("{\"op\": \"nope\"}"), ids,
          scales, data, valid, n),
      "unknown op must surface the runtime error");

  /* -- 5. allocation-failure cleanup paths --------------------------- */
  int64_t live_before = srt_live_handle_count();
  srt_mock::fail_next_array_alloc();
  jlongArray r1 = Java_com_nvidia_spark_rapids_jni_DeviceTable_tableOpNative(
      env, cls, op, ids, scales, data, valid, n);
  CHECK(r1 == nullptr, "tableOpNative must fail on alloc failure");
  CHECK(srt_live_handle_count() == live_before,
        "tableOpNative leaked handles on alloc failure");
  srt_mock::fail_next_array_alloc();
  jlongArray r2 =
      Java_com_nvidia_spark_rapids_jni_RowConversion_convertFromRowsNative(
          env, cls, rows_h, ids, scales, n);
  CHECK(r2 == nullptr, "convertFromRows must fail on alloc failure");
  CHECK(srt_live_handle_count() == live_before,
        "convertFromRows leaked handles on alloc failure");
  srt_mock::clear_exception();
  std::printf("jni_harness: error + cleanup paths ok\n");

  /* -- 6. release everything; registry must be empty ------------------ */
  for (int64_t i = 0; i < out_cols; ++i) {
    srt_buffer_release(pk[2 + 2 * out_cols + i]);
    if (pk[2 + 3 * out_cols + i] != 0)
      srt_buffer_release(pk[2 + 3 * out_cols + i]);
  }
  for (jlong h : bh) srt_buffer_release(h);
  Java_com_nvidia_spark_rapids_jni_HostBuffer_bufferRelease(env, cls,
                                                            rows_h);
  Java_com_nvidia_spark_rapids_jni_HostBuffer_bufferRelease(env, cls, th);
  srt_buffer_release(hk);
  srt_buffer_release(hv);
  srt_buffer_release(hkv);
  CHECK(srt_live_handle_count() == 0, "handle leak at exit");
  srt_mock::reset();
  std::printf("jni_harness: ok\n");
  return 0;
}
