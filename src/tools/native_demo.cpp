/* Pure-native caller driving device compute through the C ABI — the
 * RowConversionTest of the native->TPU path (the role
 * RowConversionTest.java:28-59 plays in the reference: build a table,
 * round-trip rows, aggregate, verify — but from C++ with no Python in
 * the process until the library hosts it).
 *
 * Exercises:
 *   1. srt_jax_init / srt_jax_platform (interpreter hosting)
 *   2. groupby-sum on the XLA backend vs a local oracle
 *   3. device row transpose round-trip vs the HOST codec (srt_pack_rows)
 *      — the cross-backend golden check of tests/test_native.py, now
 *      initiated from native code
 * Exit 0 on success; prints the failing check otherwise.
 */

#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <vector>

#include "spark_rapids_tpu/c_api.h"

namespace {

constexpr int32_t kInt64 = 4;   /* TypeId.INT64 */
constexpr int32_t kFloat64 = 10; /* TypeId.FLOAT64 */

#define CHECK(cond, msg)                                        \
  do {                                                          \
    if (!(cond)) {                                              \
      std::fprintf(stderr, "FAIL: %s (%s)\n", msg,              \
                   srt_last_error());                           \
      return 1;                                                 \
    }                                                           \
  } while (0)

}  // namespace

int main() {
  CHECK(srt_jax_available() == 1, "built without SRT_EMBED_JAX");
  CHECK(srt_jax_init() == SRT_OK, "srt_jax_init");
  char platform[32] = {0};
  CHECK(srt_jax_platform(platform, sizeof platform) == SRT_OK,
        "srt_jax_platform");
  std::printf("native_demo: jax platform = %s\n", platform);

  /* table: k int64 (with one null), v float64 */
  const int64_t n = 64;
  std::vector<int64_t> k(n);
  std::vector<double> v(n);
  std::vector<uint8_t> k_valid(n, 1);
  for (int64_t i = 0; i < n; ++i) {
    k[i] = i % 5;
    v[i] = static_cast<double>(i);
  }
  k_valid[7] = 0; /* one null key: groupby must drop it from groups */

  srt_handle hk = srt_buffer_create(k.data(), n * 8, "demo-k");
  srt_handle hv = srt_buffer_create(v.data(), n * 8, "demo-v");
  srt_handle hkv = srt_buffer_create(k_valid.data(), n, "demo-k-valid");
  CHECK(hk != 0 && hv != 0 && hkv != 0, "buffer create");

  /* -- groupby on device ------------------------------------------- */
  const int32_t type_ids[2] = {kInt64, kFloat64};
  const int32_t scales[2] = {0, 0};
  const srt_handle data[2] = {hk, hv};
  const srt_handle valid[2] = {hkv, 0};
  int32_t out_ids[8];
  int32_t out_scales[8];
  srt_handle out_data[8];
  srt_handle out_valid[8];
  int32_t out_cols = 0;
  int64_t out_rows = 0;
  const char* op =
      "{\"op\": \"groupby\", \"by\": [0], "
      "\"aggs\": [{\"column\": 1, \"agg\": \"sum\"}]}";
  CHECK(srt_jax_table_op(op, type_ids, scales, 2, data, valid, n, 8,
                         out_ids, out_scales, &out_cols, out_data,
                         out_valid, &out_rows) == SRT_OK,
        "groupby dispatch");
  CHECK(out_cols == 2, "groupby output arity");

  /* local oracle: NULL keys form their own group (Spark GROUP BY) */
  std::map<int64_t, double> want;
  double null_sum = 0.0;
  bool has_null_group = false;
  for (int64_t i = 0; i < n; ++i) {
    if (k_valid[i]) {
      want[k[i]] += v[i];
    } else {
      null_sum += v[i];
      has_null_group = true;
    }
  }
  CHECK(static_cast<int64_t>(want.size()) + (has_null_group ? 1 : 0) ==
            out_rows,
        "groupby group count");
  const int64_t* got_k =
      static_cast<const int64_t*>(srt_buffer_data(out_data[0]));
  const double* got_s =
      static_cast<const double*>(srt_buffer_data(out_data[1]));
  const uint8_t* got_kv =
      out_valid[0] == 0
          ? nullptr
          : static_cast<const uint8_t*>(srt_buffer_data(out_valid[0]));
  CHECK(got_k != nullptr && got_s != nullptr, "output buffers");
  int64_t null_groups_seen = 0;
  for (int64_t i = 0; i < out_rows; ++i) {
    if (got_kv != nullptr && got_kv[i] == 0) {
      CHECK(null_sum == got_s[i], "null-group sum mismatch");
      ++null_groups_seen;
      continue;
    }
    auto it = want.find(got_k[i]);
    CHECK(it != want.end(), "unexpected group key");
    CHECK(it->second == got_s[i], "group sum mismatch");
  }
  CHECK(null_groups_seen == (has_null_group ? 1 : 0), "null group arity");
  std::printf("native_demo: groupby-sum over %" PRId64
              " rows -> %" PRId64 " groups ok\n",
              n, out_rows);

  /* -- device row transpose vs host codec --------------------------- */
  const char* to_rows_op = "{\"op\": \"to_rows\"}";
  int32_t r_ids[4];
  int32_t r_scales[4];
  srt_handle r_data[4];
  srt_handle r_valid[4];
  int32_t r_cols = 0;
  int64_t r_rows = 0;
  CHECK(srt_jax_table_op(to_rows_op, type_ids, scales, 2, data, valid, n,
                         4, r_ids, r_scales, &r_cols, r_data, r_valid,
                         &r_rows) == SRT_OK,
        "to_rows dispatch");
  CHECK(r_cols == 1, "to_rows output arity");
  /* packed rows arrive as a true LIST<UINT8> wire column: type id 24
   * (LIST), the scale slot carrying the child type id, and the data
   * buffer holding int32 offsets[n+1] then the child bytes — the
   * reference's own output type (row_conversion.cu:389-406). */
  CHECK(r_ids[0] == 24, "to_rows type is LIST");
  CHECK(r_scales[0] == 5 /* UINT8 */, "LIST child type id");
  CHECK(r_rows == n, "to_rows row count");

  srt_row_layout layout;
  int32_t offs[2];
  int32_t widths[2];
  CHECK(srt_compute_row_layout(type_ids, 2, offs, widths, &layout) ==
            SRT_OK,
        "row layout");
  std::vector<uint8_t> host_rows(
      static_cast<size_t>(n) * layout.row_size);
  const void* cols[2] = {k.data(), v.data()};
  const uint8_t* valids[2] = {k_valid.data(), nullptr};
  CHECK(srt_pack_rows(type_ids, 2, cols, valids, n, host_rows.data()) ==
            SRT_OK,
        "host pack");
  const size_t header = sizeof(int32_t) * static_cast<size_t>(n + 1);
  CHECK(srt_buffer_size(r_data[0]) ==
            static_cast<int64_t>(header + host_rows.size()),
        "packed size mismatch");
  const auto* list_bytes =
      static_cast<const uint8_t*>(srt_buffer_data(r_data[0]));
  const auto* list_offs = reinterpret_cast<const int32_t*>(list_bytes);
  for (int64_t i = 0; i <= n; ++i) {
    CHECK(list_offs[i] == i * layout.row_size,
          "LIST offsets not the row_size sequence");
  }
  CHECK(std::memcmp(list_bytes + header, host_rows.data(),
                    host_rows.size()) == 0,
        "device rows != host codec rows");
  std::printf("native_demo: device to_rows (LIST<UINT8>) matches host "
              "codec (%zu bytes)\n",
              host_rows.size());

  /* cleanup: every handle back to the registry */
  for (int32_t i = 0; i < out_cols; ++i) {
    srt_buffer_release(out_data[i]);
    if (out_valid[i] != 0) srt_buffer_release(out_valid[i]);
  }
  for (int32_t i = 0; i < r_cols; ++i) {
    srt_buffer_release(r_data[i]);
    if (r_valid[i] != 0) srt_buffer_release(r_valid[i]);
  }
  srt_buffer_release(hk);
  srt_buffer_release(hv);
  srt_buffer_release(hkv);
  CHECK(srt_live_handle_count() == 0, "handle leak");
  std::printf("native_demo: ok\n");
  return 0;
}
